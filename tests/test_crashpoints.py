"""Crash-point enumeration sweep (ISSUE 20 tentpole).

Every durable mutation routes through the ``utils/fsio`` verb seam,
so the set of crash boundaries IS the sequence of mutating verb calls
a lifecycle makes.  A subprocess stub runner (no jax — the pipeline is
stubbed out) drives the serve lifecycle (submit -> claim -> batch ->
flush -> complete) and the streaming lifecycle (feed append/finalize
-> tick rows -> durable cursor -> resume):

1. a COUNT run (``SCINT_FSIO_COUNT_FILE``) learns K, the number of
   crash points, and an untouched ORACLE run exports the expected CSV;
2. a single DRIVER subprocess then ``os.fork``s one child per (k,
   kind) for EVERY k in 1..K and both covering crash shapes (``torn``
   = partial bytes then die, ``after`` = op completes then die — see
   fsio's module doc for why these two cover every boundary): the
   child arms the sweep via :func:`fsio.arm`, is hard-killed at point
   k (asserted via the distinct exit code), and a second disarmed fork
   RE-DRIVES the same dir with the identical idempotent lifecycle —
   fork instead of spawn so the interpreter+import cost is paid ONCE,
   keeping the full sweep sub-minute on one core;
3. after recovery: ``fsck --repair`` converges (a second dry-run
   audit reports clean), the queue holds no lost/duplicated work, the
   stream cursor never leads the committed feed, and the exported CSV
   is byte-identical to the oracle's.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from scintools_tpu.serve import fsck
from scintools_tpu.utils import fsio

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the stub runner: drives one lifecycle against a queue dir (crashing
# wherever the fsio sweep says, re-driving idempotently when disarmed)
# and, in ``sweep`` mode, forks the whole (k, kind) grid in-process.
RUNNER = r'''
import os, sys, time

from scintools_tpu.serve import queue as queue_mod
from scintools_tpu.serve.queue import JobQueue
from scintools_tpu.utils import fsio
from scintools_tpu.utils.store import ResultsStore

# stub: cfg validation builds the jax pipeline config; irrelevant here
queue_mod.validate_job_cfg = lambda cfg: None


def run_serve(qdir):
    os.makedirs(os.path.join(qdir, "in"), exist_ok=True)
    files = []
    for i in range(2):
        p = os.path.join(qdir, "in", f"epoch{i}.dat")
        if not os.path.exists(p):
            with open(p, "w") as fh:
                fh.write(f"epoch-{i}\n" * 4)   # deterministic job ids
        files.append(p)
    q = JobQueue(qdir, max_retries=99, backoff_s=0.0)
    for i, p in enumerate(files):
        q.submit(p, {"i": i}, lane="bulk")
    for _round in range(50):
        # logical clock: far ahead of real time so a crashed run's
        # stamps are stale, and ADVANCING per round so any lease the
        # crashed run wrote (with its own skewed clock) expires
        now = time.time() + 3600.0 * (1 + _round)
        q.reap_expired(now)
        jobs = q.claim("stub", 4, lease_s=1.0, now=now)
        if not jobs:
            if not q._ids("queued") and not q._ids("leased"):
                break
            continue
        for job in jobs:
            if job.id not in q.results:
                q.results.put_new_buffered(job.id, {
                    "src": os.path.basename(job.file),
                    "value": float(job.cfg["i"]) * 0.5})
        q.results.flush()
        for job in jobs:
            q.complete(job)
    assert len(q._ids("done")) == 2, q.counts()
    assert not q._ids("queued") and not q._ids("leased"), q.counts()


def run_stream(qdir):
    import numpy as np

    from scintools_tpu.stream.ingest import FeedWriter, _read_manifest

    JobQueue(qdir)                      # the audited queue dir exists
    feed = os.path.join(qdir, "feed")
    results = ResultsStore(os.path.join(qdir, "results"))
    NF, NT, NCHUNK = 4, 2, 3
    writer = FeedWriter(feed, freqs=[1e3 + i for i in range(NF)],
                        dt=1.0)         # reopen recovers orphan chunks
    have = {int(c["seq"]) for c in writer.manifest["chunks"]}
    for seq in range(NCHUNK):
        if seq in have:
            continue
        chunk = (np.arange(NF * NT, dtype="float32")
                 .reshape(NF, NT) + seq)
        writer.append(chunk)
    writer.finalize()
    jid = "stubstream01"
    meta = results.get_meta(f"stream.{jid}") or {}
    consumed, tick = int(meta.get("consumed", 0)), \
        int(meta.get("tick_seq", 0))
    for end in (4, 6):                  # window=4 hop=2 over 6 samples
        if end <= consumed:
            continue
        row = {"feed": "feed", "window_end": end, "eta": end * 0.25}
        results.put_versioned(f"{jid}.w{end:09d}", row, series=jid)
        results.put_versioned(f"{jid}.live", row, series=jid)
        results.flush()                 # rows durable BEFORE cursor
        tick += 1
        results.put_meta(f"stream.{jid}",
                         {"consumed": end, "tick_seq": tick})
        consumed = end
    man = _read_manifest(feed)
    assert man["finalized"]
    assert sum(int(c["nt"]) for c in man["chunks"]) == NCHUNK * NT
    cur = results.get_meta(f"stream.{jid}")
    assert cur and int(cur["consumed"]) == NCHUNK * NT, cur


def _fork_lifecycle(run, qdir, k=0, kind="torn"):
    """Run one lifecycle in a forked child (armed iff k > 0) and
    return its exit status — fork shares the already-imported
    interpreter, so each crash point costs milliseconds, not a
    fresh python startup."""
    sys.stdout.flush()
    sys.stderr.flush()
    pid = os.fork()
    if pid == 0:
        fsio.arm(k, kind)               # arm(0) disarms (the re-drive)
        try:
            run(qdir)
        except BaseException:
            import traceback
            traceback.print_exc()
            os._exit(1)
        os._exit(0)
    return os.waitstatus_to_exitcode(os.waitpid(pid, 0)[1])


def sweep(scenario, base, k_total):
    run = {"serve": run_serve, "stream": run_stream}[scenario]
    for k in range(1, k_total + 1):
        for kind in ("torn", "after"):
            qdir = os.path.join(base, f"{kind}-{k:03d}")
            rc = _fork_lifecycle(run, qdir, k, kind)
            if rc != fsio.CRASH_EXIT_CODE:
                print(f"FAIL k={k} {kind}: expected the injected "
                      f"hard kill, got rc={rc}", flush=True)
                sys.exit(3)
            rc = _fork_lifecycle(run, qdir)
            if rc != 0:
                print(f"FAIL k={k} {kind}: re-drive failed rc={rc}",
                      flush=True)
                sys.exit(4)


cmd = sys.argv[1]
if cmd == "sweep":
    sweep(sys.argv[2], sys.argv[3], int(sys.argv[4]))
else:
    {"serve": run_serve, "stream": run_stream}[cmd](sys.argv[2])
'''


@pytest.fixture(scope="module")
def runner_path(tmp_path_factory):
    p = tmp_path_factory.mktemp("crashpoints") / "runner.py"
    p.write_text(RUNNER)
    return str(p)


def _env(**extra) -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SCINT_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


def _run(runner, *args, **envkw):
    return subprocess.run(
        [sys.executable, runner, *args], env=_env(**envkw),
        capture_output=True, text=True, timeout=300)


def _export(qdir: str, out: str) -> bytes:
    from scintools_tpu.utils.store import ResultsStore

    ResultsStore(os.path.join(qdir, "results")).export_csv(
        out, full=True)
    with open(out, "rb") as fh:
        return fh.read()


def _age_crash_litter(qdir: str) -> None:
    """Backdate the crashed run's ``.tmp``/``.open`` litter past the
    fsck/salvage remote-writer grace windows, so the audit must both
    FLAG and REPAIR it (fresh litter is deliberately left alone)."""
    for dirpath, _dirs, files in os.walk(qdir):
        for f in files:
            if ".tmp" in f or f.endswith(".open"):
                p = os.path.join(dirpath, f)
                old = time.time() - 600.0
                try:
                    os.utime(p, (old, old))
                except OSError:
                    pass


def _learn_k_and_oracle(runner, scenario, tmp_path):
    """K (the crash-point count) from a counted clean run + the oracle
    CSV bytes from its own results."""
    qdir = str(tmp_path / f"oracle-{scenario}")
    count_file = str(tmp_path / f"count-{scenario}")
    r = _run(runner, scenario, qdir, SCINT_FSIO_COUNT_FILE=count_file)
    assert r.returncode == 0, r.stderr
    with open(count_file) as fh:
        k_total = int(fh.read())
    assert k_total > 10, f"{scenario}: suspiciously few crash points"
    oracle = _export(qdir, str(tmp_path / f"oracle-{scenario}.csv"))
    assert oracle, "oracle CSV is empty"
    return k_total, oracle


def _audit_one(scenario, qdir, k, kind, oracle):
    """Post-recovery invariants for one swept crash point: fsck
    --repair converges and the recovered CSV matches the oracle."""
    _age_crash_litter(qdir)
    rep = fsck.run_fsck(qdir, repair=True)
    assert rep["clean"], (k, kind, rep["findings"])
    rep2 = fsck.run_fsck(qdir)
    assert rep2["clean"] and not rep2["findings"], (
        f"{scenario} k={k} {kind}: repair did not converge: "
        f"{rep2['findings']}")
    csv = _export(qdir, os.path.join(qdir, "out.csv"))
    assert csv == oracle, (
        f"{scenario} k={k} {kind}: recovered CSV diverged from the "
        f"clean run's")


def _sweep(runner, scenario, tmp_path, extra_check=None):
    k_total, oracle = _learn_k_and_oracle(runner, scenario, tmp_path)
    base = str(tmp_path / f"sweep-{scenario}")
    r = _run(runner, "sweep", scenario, base, str(k_total))
    assert r.returncode == 0, (
        f"{scenario} sweep driver failed\n{r.stdout}\n{r.stderr}")
    for k in range(1, k_total + 1):
        for kind in ("torn", "after"):
            qdir = os.path.join(base, f"{kind}-{k:03d}")
            _audit_one(scenario, qdir, k, kind, oracle)
            if extra_check is not None:
                extra_check(qdir, k, kind)
    return k_total


def test_serve_lifecycle_survives_every_crash_point(runner_path,
                                                    tmp_path):
    """Hard-killing submit->claim->flush->complete at EVERY mutating
    fsio call (both covering shapes) recovers to the oracle CSV with
    no lost or duplicated jobs, and fsck --repair converges."""
    def check(qdir, k, kind):
        from scintools_tpu.serve.queue import JobQueue

        q = JobQueue(qdir)
        c = q.counts()
        assert c["done"] == 2 and c["queued"] == 0 \
            and c["leased"] == 0 and c["failed"] == 0, (k, kind, c)

    k_total = _sweep(runner_path, "serve", tmp_path, check)
    assert k_total >= 20   # the lifecycle really spans the planes


def test_stream_lifecycle_survives_every_crash_point(runner_path,
                                                     tmp_path):
    """Hard-killing feed append/finalize -> tick rows -> cursor at
    EVERY mutating fsio call recovers to the oracle CSV with the
    cursor never leading the committed feed."""
    def check(qdir, k, kind):
        from scintools_tpu.stream.ingest import _read_manifest
        from scintools_tpu.utils.store import ResultsStore

        man = _read_manifest(os.path.join(qdir, "feed"))
        total = sum(int(c["nt"]) for c in man["chunks"])
        assert man["finalized"] and total == 6, (k, kind, man)
        cur = ResultsStore(os.path.join(qdir, "results")).get_meta(
            "stream.stubstream01")
        assert cur and int(cur["consumed"]) <= total, (k, kind, cur)
        assert int(cur["consumed"]) == total, (k, kind, cur)

    _sweep(runner_path, "stream", tmp_path, check)


def test_crash_sweep_runner_is_deterministic(runner_path, tmp_path):
    """Two counted clean runs agree on K — the sweep's guarantee that
    crash point k in a killed run is the same boundary the count run
    enumerated."""
    ks = []
    for tag in ("a", "b"):
        count = str(tmp_path / f"count-{tag}")
        r = _run(runner_path, "serve", str(tmp_path / f"det-{tag}"),
                 SCINT_FSIO_COUNT_FILE=count)
        assert r.returncode == 0, r.stderr
        with open(count) as fh:
            ks.append(int(fh.read()))
    assert ks[0] == ks[1], ks
