"""Deterministic fault injection + self-healing execution (ISSUE 5).

The acceptance contract, proven end-to-end with the registry instead of
ad-hoc subprocess SIGKILLs:

* a survey with an injected OOM on chunk k COMPLETES, its results CSV
  byte-identical to the un-faulted run, with ``oom_backoff >= 1`` (and
  the degraded ``effective_chunk``) in the trace;
* an injected transient fault in a serve worker leaves ``job.attempts``
  unchanged and the job eventually ``done``;
* a deterministic bad job still poisons after the same bounded retries
  as today;
* the default (no-faults) path is bit-identical, with injection
  overhead = one dict lookup.

All pipeline-executing tests share the tiny 32x32 signature test_serve
uses, so the in-process jit trace is shared across the suite."""

import json
import os
import time

import numpy as np
import pytest

from synth import synth_arc_epoch

from scintools_tpu import faults, obs
from scintools_tpu.faults import (FaultSpec, InjectedFault, InjectedPoison,
                                  PoisonError, TransientError,
                                  classify_error, is_oom_error, parse_env)
from scintools_tpu.io.psrflux import write_psrflux
from scintools_tpu.parallel import PipelineConfig, run_pipeline
from scintools_tpu.serve import JobQueue, ServeWorker, SurveyClient
from scintools_tpu.serve.worker import load_epoch

OPTS = {"lamsteps": True, "arc_numsteps": 96, "lm_steps": 3}
GOOD_SEEDS = (1, 2, 4, 5, 7, 8)
PCFG = PipelineConfig(arc_numsteps=96, lm_steps=3)


def _write_epochs(tmp_path, seeds):
    files = []
    for s in seeds:
        fn = str(tmp_path / f"epoch_{s:02d}.dynspec")
        write_psrflux(synth_arc_epoch(nf=32, nt=32, seed=s), fn)
        files.append(fn)
    return files


def _stub_runner(fail_names=()):
    def run(batch, batch_size, mesh, async_exec):
        rows = []
        for job, ep in zip(batch.jobs, batch.epochs):
            name = os.path.basename(job.file)
            if name in fail_names:
                rows.append({"name": name, "tau": float("nan")})
            else:
                rows.append({"name": name, "mjd": ep.mjd, "freq": ep.freq,
                             "bw": ep.bw, "tobs": ep.tobs, "dt": ep.dt,
                             "df": ep.df, "tau": 1.5, "tauerr": 0.1})
        return rows

    return run


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# the registry itself
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_registry_at_call_window_times_and_clear():
    spec = FaultSpec(kind="transient", at_call=2, times=2)
    with faults.injected("some.site", spec):
        faults.check("some.site")                       # call 1: clean
        for _ in range(2):                              # calls 2, 3: fire
            with pytest.raises(InjectedFault):
                faults.check("some.site")
        # the last window call disarmed the site for real: active()
        # stops reporting it and later calls are dict-miss cheap
        assert "some.site" not in faults.active()
        faults.check("some.site")                       # call 4: disarmed
        assert spec.calls == 3                          # counter frozen
        faults.check("other.site")                      # unarmed site
    assert faults.active() == {}                        # scoped clear
    faults.check("some.site")                           # fully disarmed


@pytest.mark.chaos
def test_registry_kinds_map_to_taxonomy():
    for kind, exc_type in (("oom", InjectedFault),
                           ("transient", InjectedFault),
                           ("poison", InjectedPoison),
                           ("oserror", OSError),
                           ("error", RuntimeError)):
        with faults.injected("k.site", FaultSpec(kind=kind)):
            with pytest.raises(exc_type) as ei:
                faults.check("k.site")
        if kind == "oom":
            assert is_oom_error(ei.value)


@pytest.mark.chaos
def test_env_spec_parsing_and_install():
    specs = parse_env("driver.chunk_execute:oom@3, worker.load:"
                      "transient@1x2,queue.claim_rename:oserror")
    assert specs["driver.chunk_execute"].kind == "oom"
    assert specs["driver.chunk_execute"].at_call == 3
    assert specs["worker.load"].times == 2
    assert specs["queue.claim_rename"].at_call == 1
    for bad in ("nonsense", "worker.load:", ":oom", "worker.load:oom@x",
                # unknown kinds fail LOUDLY (a typo'd spec must never
                # silently inject a differently-classified fault)
                "worker.load:oomx2", "worker.load:posion@1",
                "worker.load:oom@0",
                # ... and so do unknown SITES (a typo'd site would arm
                # nothing and the chaos run would pass vacuously)
                "worker.loda:oom@1", "driver.chunk_exec:oom@1"):
        with pytest.raises(ValueError):
            parse_env(bad)
    with pytest.raises(ValueError, match="unknown site"):
        parse_env("driver.chunk_exec:oom@1")
    with pytest.raises(ValueError, match="unknown kind"):
        FaultSpec(kind="posion")
    # non-integer at_call/times carry the SCINT_FAULTS entry context,
    # not a bare int() traceback
    with pytest.raises(ValueError, match="non-integer"):
        parse_env("worker.load:oom@x3")


@pytest.mark.chaos
def test_install_env_retry_after_parse_failure(monkeypatch):
    # a failed parse must NOT latch env arming off: fix the env var,
    # call again, and the faults arm
    faults.clear()
    monkeypatch.setattr(faults, "_ENV_INSTALLED", False)
    monkeypatch.setenv(faults.ENV_VAR, "worker.load:oomx2")
    with pytest.raises(ValueError):
        faults.install_env()
    monkeypatch.setenv(faults.ENV_VAR, "worker.load:oom@1")
    try:
        assert faults.install_env() == 1
        with pytest.raises(Exception) as ei:
            faults.check("worker.load")
        assert is_oom_error(ei.value)
    finally:
        faults.clear()
        monkeypatch.setattr(faults, "_ENV_INSTALLED", False)


def test_classification_taxonomy():
    assert classify_error(TransientError("x")) == "transient"
    assert classify_error(InjectedFault("RESOURCE_EXHAUSTED: y")) \
        == "transient"
    assert classify_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating ...")) == "transient"
    assert classify_error(RuntimeError("worker lease expired")) \
        == "transient"
    assert classify_error(PoisonError("bad")) == "poison"
    assert classify_error(ValueError("bad config")) == "poison"
    assert classify_error(RuntimeError("segfault-ish mystery")) \
        == "unknown"
    # deterministic TYPES outrank message substrings: a validation
    # error quoting an infra-looking value must still poison, and an
    # incidental token in a path ('ZOOM', a bare 'OOM') is not device
    # memory exhaustion
    assert classify_error(ValueError(
        "bad constraint 'UNAVAILABLE'")) == "poison"
    assert classify_error(
        FileNotFoundError("/data/ZOOM_55.dynspec: no such file")) \
        == "unknown"
    assert not is_oom_error(FileNotFoundError("/data/ZOOM_55.dynspec"))


def test_transient_requeues_escalate_after_bound(tmp_path):
    """A job stuck in classified-transient failures cannot livelock
    the queue: after max_transients budget-free requeues, further
    transient failures burn attempts like any other failure and the
    job terminates in failed/."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:1])
    q = JobQueue(str(tmp_path / "q"), max_retries=1, backoff_s=0.0,
                 max_transients=2)
    jid, _ = q.submit(files[0], OPTS)
    now = 1000.0
    states = []
    for k in range(5):
        jobs = q.claim("w", n=1, lease_s=5.0, now=now)
        if not jobs:
            break
        states.append(q.fail(jobs[0], f"infra? {k}", transient=True,
                             now=now))
        j = q.get(jid)
        now = max(now, j.not_before if j.not_before else now) + 0.1
    # 2 budget-free requeues, then 2 escalated attempts-burning ones
    # (max_retries=1 -> queued once, then failed)
    assert states == ["queued", "queued", "queued", "failed"]
    j = q.get(jid)
    assert j.transients == 2 and j.attempts == 2
    assert q.state_of(jid) == "failed"


def test_disarmed_overhead_is_one_dict_lookup():
    """The production path: empty registry, counters untouched, and a
    million checks cost what a million dict lookups cost (generous
    wall bound — the point is no env read / lock / allocation per
    call)."""
    assert faults.active() == {}
    with obs.tracing():
        t0 = time.perf_counter()
        for _ in range(100_000):
            faults.check("driver.chunk_execute")
        dt = time.perf_counter() - t0
        assert obs.counters() == {}
    assert dt < 1.0, f"disarmed check too slow: {dt:.3f}s / 100k calls"
    obs.reset()


# ---------------------------------------------------------------------------
# preflight quarantine (scintools_tpu.health)
# ---------------------------------------------------------------------------


def _epoch_with(dyn=None, freqs=None, times=None):
    import dataclasses

    ep = synth_arc_epoch(nf=32, nt=32, seed=1)
    kw = {}
    if dyn is not None:
        kw["dyn"] = dyn
    if freqs is not None:
        kw["freqs"] = freqs
    if times is not None:
        kw["times"] = times
    return dataclasses.replace(ep, **kw)


def test_preflight_reason_codes():
    from scintools_tpu.health import preflight_epoch

    ep = synth_arc_epoch(nf=32, nt=32, seed=1)
    base = np.asarray(ep.dyn)
    assert preflight_epoch(ep) == []
    assert preflight_epoch(_epoch_with(dyn=np.zeros_like(base))) \
        == ["all_zero"]
    mostly_nan = base.copy()
    mostly_nan[:, ::2] = np.nan            # 50% NaN is tolerated...
    assert preflight_epoch(_epoch_with(dyn=mostly_nan)) == []
    mostly_nan[:] = np.nan                 # ...fully NaN is not
    assert preflight_epoch(_epoch_with(dyn=mostly_nan)) \
        == ["nonfinite", "all_zero"]
    dead_band = base.copy()
    dead_band[4:28, :] = 0.0               # 24/32 interior channels dead
    assert preflight_epoch(_epoch_with(dyn=dead_band)) == ["zero_band"]
    f = np.asarray(ep.freqs).copy()
    f[5] = f[4]                            # non-monotonic axis
    assert preflight_epoch(_epoch_with(freqs=f)) == ["axis_nonmonotonic"]
    assert preflight_epoch(_epoch_with(times=np.asarray(ep.times)[:-1])) \
        == ["axis_shape"]


def test_load_epoch_quarantines_zero_band_with_counters(tmp_path):
    """The shared load chain rejects a dead-band epoch BEFORE refill
    can repair it by interpolation: PreflightError with machine-
    readable codes + the epochs_quarantined counters."""
    import dataclasses

    from scintools_tpu.health import PreflightError

    ep = synth_arc_epoch(nf=32, nt=32, seed=1)
    dyn = np.asarray(ep.dyn).copy()
    dyn[4:28, :] = 0.0
    fn = str(tmp_path / "zeroband.dynspec")
    write_psrflux(dataclasses.replace(ep, dyn=dyn), fn)
    obs.disable(flush=False)
    obs.reset()
    with obs.tracing():
        with pytest.raises(PreflightError, match="zero_band") as ei:
            load_epoch(fn)
        c = obs.counters()
    assert ei.value.reasons == ["zero_band"]
    assert c.get("epochs_quarantined") == 1
    assert c.get("epochs_quarantined[zero_band]") == 1
    # preflight=False restores the raw chain (refill repairs the band)
    d = load_epoch(fn, preflight=False)
    assert np.isfinite(np.asarray(d.dyn)).all()
    # deterministic data pathology -> the POISON side of the taxonomy
    assert classify_error(ei.value) == "poison"
    obs.reset()


def test_cli_batched_process_quarantines_and_still_serves_good(tmp_path,
                                                               capsys):
    """`process --batched` with one structurally-bad epoch: the healthy
    epochs complete, the bad one is quarantined (rc=1), and the CSV
    carries exactly the healthy rows."""
    import dataclasses

    from scintools_tpu.cli import main as cli_main

    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    ep = synth_arc_epoch(nf=32, nt=32, seed=9)
    dyn = np.asarray(ep.dyn).copy()
    dyn[4:28, :] = 0.0
    bad = str(tmp_path / "zz_bad.dynspec")
    write_psrflux(dataclasses.replace(ep, dyn=dyn), bad)
    out = str(tmp_path / "res.csv")
    rc = cli_main(["process", "--batched", "--lamsteps",
                   "--results", out, *files, bad])
    capsys.readouterr()
    assert rc == 1
    with open(out) as fh:
        text = fh.read()
    assert text.count("\n") == 3   # header + the 2 healthy epochs
    assert "zz_bad" not in text


# ---------------------------------------------------------------------------
# OOM-adaptive chunk backoff (the acceptance demo)
# ---------------------------------------------------------------------------


def _survey_csv(files, tmp_path, tag, chunk=4):
    """run_pipeline -> content-keyed store -> CSV, the serve/CLI row
    path in miniature (same builders), chunked."""
    from scintools_tpu.io.results import (batch_lane_row, results_row,
                                          row_fit_values)
    from scintools_tpu.serve import job_key
    from scintools_tpu.utils.store import ResultsStore

    epochs = [load_epoch(f) for f in files]
    store = ResultsStore(str(tmp_path / f"store_{tag}"))
    buckets = run_pipeline(epochs, PCFG, chunk=chunk)
    for idx, res in buckets:
        for lane, i in enumerate(idx):
            row = results_row(epochs[i])
            row.update(batch_lane_row(res, lane, PCFG.lamsteps))
            fitvals = row_fit_values(row)
            if fitvals and not np.all(np.isfinite(fitvals)):
                continue
            row["name"] = os.path.basename(files[i])
            store.put(job_key(files[i], OPTS), row)
    out = str(tmp_path / f"{tag}.csv")
    store.export_csv(out)
    with open(out) as fh:
        return fh.read()


@pytest.mark.chaos
def test_injected_oom_backoff_completes_byte_identical(tmp_path):
    """THE tentpole acceptance: OOM on chunk 2 of a chunk=4 survey ->
    the driver halves to 2, replays only the unfinished epochs, the
    survey completes, and the exported CSV is BYTE-identical to the
    un-faulted run — with oom_backoff >= 1 and the degraded
    effective_chunk in the trace, and the reliability section visible
    in `trace report`."""
    files = _write_epochs(tmp_path, GOOD_SEEDS)   # 6 epochs, chunks 4+2
    clean = _survey_csv(files, tmp_path, "clean")
    obs.disable(flush=False)
    obs.reset()
    trace = str(tmp_path / "chaos.jsonl")
    with obs.tracing(jsonl=trace):
        with faults.injected("driver.chunk_execute",
                             FaultSpec(kind="oom", at_call=2)):
            faulted = _survey_csv(files, tmp_path, "faulted")
        c = obs.counters()
        g = obs.get_registry().gauges()
    assert faulted == clean
    assert faulted.count("\n") == len(files) + 1
    assert c.get("oom_backoff", 0) >= 1, c
    assert c.get("faults_injected[driver.chunk_execute]") == 1
    assert g.get("effective_chunk") == 2
    text = obs.report(trace)
    assert "reliability (self-healing events)" in text
    assert "oom_backoff = 1 (effective_chunk = 2)" in text
    obs.reset()


@pytest.mark.chaos
def test_oom_at_floor_chunk_propagates(tmp_path):
    """A chunk already at the floor (1, or the mesh multiple) cannot
    shrink: the OOM propagates instead of looping forever."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    epochs = [load_epoch(f) for f in files]
    with faults.injected("driver.chunk_execute",
                         FaultSpec(kind="oom", at_call=1, times=99)):
        with pytest.raises(Exception) as ei:
            run_pipeline(epochs, PCFG, chunk=1)
    assert is_oom_error(ei.value)


@pytest.mark.chaos
def test_prefetch_fault_propagates_to_caller(tmp_path):
    """An injected prefetch-thread death (schedule.prefetch) surfaces
    as the caller's exception — never a hang, never a silent partial
    result."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:4])
    epochs = [load_epoch(f) for f in files]
    with faults.injected("schedule.prefetch",
                         FaultSpec(kind="error", at_call=2)):
        with pytest.raises(RuntimeError, match="schedule.prefetch"):
            run_pipeline(epochs, PCFG, chunk=2, async_exec=True)


@pytest.mark.chaos
def test_compile_cache_load_fault_degrades_to_jit(tmp_path, monkeypatch):
    """An injected artifact-load failure degrades to the jit path
    (counted as a miss) — the survey completes with identical
    results."""
    from scintools_tpu import compile_cache
    from scintools_tpu.parallel.driver import make_pipeline

    monkeypatch.setenv("SCINT_COMPILE_CACHE", str(tmp_path / "scc"))
    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    epochs = [load_epoch(f) for f in files]
    f, t = np.asarray(epochs[0].freqs), np.asarray(epochs[0].times)
    step = make_pipeline(f, t, PCFG)
    key = compile_cache.step_key(f, t, PCFG, None, False,
                                 (2,) + np.asarray(epochs[0].dyn).shape,
                                 np.float64)
    assert compile_cache.export_step(
        step, (2,) + np.asarray(epochs[0].dyn).shape, np.float64,
        key) is not None
    [(i0, r0)] = run_pipeline(epochs, PCFG)
    # drop the in-process memo of the deserialized step, so the faulted
    # run actually re-reads the artifact (the failure being simulated)
    compile_cache._LOADED.clear()
    obs.disable(flush=False)
    obs.reset()
    with obs.tracing():
        with faults.injected("compile_cache.load",
                             FaultSpec(kind="error", times=99)):
            [(i1, r1)] = run_pipeline(epochs, PCFG)
        c = obs.counters()
    assert c.get("compile_cache_miss", 0) >= 1
    np.testing.assert_array_equal(np.asarray(r0.scint.tau),
                                  np.asarray(r1.scint.tau))
    obs.reset()


def test_no_faults_path_bit_identical(tmp_path):
    """Arming then clearing the registry leaves the default path
    untouched: identical results, no counters, empty registry."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:4])
    epochs = [load_epoch(f) for f in files]
    [(i0, r0)] = run_pipeline(epochs, PCFG, chunk=2)
    faults.inject("driver.chunk_execute", FaultSpec(kind="oom"))
    faults.clear()
    with obs.tracing():
        [(i1, r1)] = run_pipeline(epochs, PCFG, chunk=2)
        c = obs.counters()
    assert "oom_backoff" not in c and "faults_injected" not in c
    np.testing.assert_array_equal(np.asarray(r0.scint.tau),
                                  np.asarray(r1.scint.tau))
    np.testing.assert_array_equal(np.asarray(r0.arc.eta),
                                  np.asarray(r1.arc.eta))
    obs.reset()


# ---------------------------------------------------------------------------
# serve: transient vs poison (stub runner — sub-second)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_serve_transient_fault_keeps_attempts_and_completes(tmp_path):
    """Acceptance: an injected transient infra fault in the worker
    leaves job.attempts unchanged (the bounded budget is untouched)
    and every job eventually completes."""
    t0 = time.perf_counter()
    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    q = JobQueue(str(tmp_path / "q"), max_retries=1, backoff_s=0.0)
    ids = [q.submit(f, OPTS)[0] for f in files]
    q.request_drain()
    worker = ServeWorker(q, batch_size=2, max_wait_s=0.0, lease_s=30.0,
                         poll_s=0.01, runner=_stub_runner())
    with faults.injected("worker.batch_execute",
                         FaultSpec(kind="transient", at_call=1)):
        stats = worker.run()
    assert stats["jobs_done"] == 2 and stats["jobs_failed"] == 0
    assert stats["job_transient_retries"] == 2
    assert stats["job_retries"] == 0
    for jid in ids:
        job = q.get(jid)
        assert q.state_of(jid) == "done"
        assert job.attempts == 0 and job.transients == 1
    assert time.perf_counter() - t0 < 1.0, "chaos test must stay fast"


@pytest.mark.chaos
def test_serve_deterministic_poison_keeps_bounded_budget(tmp_path):
    """Acceptance: a deterministic bad job still poisons after exactly
    the same bounded retries as today (max_retries+1 attempts), while
    a transient fault injected ALONGSIDE it burns nothing."""
    t0 = time.perf_counter()
    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    bad = os.path.basename(files[1])
    q = JobQueue(str(tmp_path / "q"), max_retries=1, backoff_s=0.0)
    ids = [q.submit(f, OPTS)[0] for f in files]
    q.request_drain()
    worker = ServeWorker(q, batch_size=2, max_wait_s=0.0, lease_s=30.0,
                         poll_s=0.01, runner=_stub_runner(
                             fail_names={bad}))
    with faults.injected("worker.load",
                         FaultSpec(kind="transient", at_call=1)):
        stats = worker.run()
    assert stats["jobs_done"] == 1 and stats["jobs_failed"] == 1
    assert stats["job_transient_retries"] == 1
    good_job, bad_job = q.get(ids[0]), q.get(ids[1])
    assert q.state_of(ids[0]) == "done" and good_job.attempts == 0
    # the NaN-lane job burned the full bounded budget, as before
    assert q.state_of(ids[1]) == "failed"
    assert bad_job.attempts == q.max_retries + 1
    assert "non-finite" in bad_job.error
    assert time.perf_counter() - t0 < 1.0, "chaos test must stay fast"


@pytest.mark.chaos
def test_worker_counts_escalated_transients_as_retries(tmp_path):
    """Once a job exhausts max_transients, a transient-classified
    failure is counted/logged as a normal budget-burning retry
    (job_retries), not a budget-free one — the escalation must be
    visible in the stats an operator watches."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:1])
    q = JobQueue(str(tmp_path / "q"), max_retries=2, backoff_s=0.0,
                 max_transients=0)   # escalate immediately
    q.submit(files[0], OPTS)
    q.request_drain()
    worker = ServeWorker(q, batch_size=1, max_wait_s=0.0, lease_s=30.0,
                         poll_s=0.01, runner=_stub_runner())
    with faults.injected("worker.batch_execute",
                         FaultSpec(kind="transient", at_call=1)):
        stats = worker.run()
    assert stats["jobs_done"] == 1
    assert stats["job_transient_retries"] == 0
    assert stats["job_retries"] == 1    # escalated: budget burned
    (jid,) = q.results.keys()
    assert q.get(jid).attempts == 1 and q.get(jid).transients == 0


@pytest.mark.chaos
def test_escalated_batch_transient_requeues_solo(tmp_path):
    """Past max_transients a transient whole-batch failure escalates to
    the attempts-burning path AND solo-marks the members, like the
    deterministic branch — otherwise the same batch re-coalesces every
    round and burns one attempt per member until ALL poison together."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    q = JobQueue(str(tmp_path / "q"), max_retries=2, backoff_s=0.0,
                 max_transients=0)   # escalate immediately
    for f in files:
        q.submit(f, OPTS)
    worker = ServeWorker(q, batch_size=2, max_wait_s=0.0, lease_s=30.0,
                         poll_s=0.01, runner=_stub_runner())
    with faults.injected("worker.batch_execute",
                         FaultSpec(kind="transient", at_call=1)):
        worker.poll_once()
    jobs = q.jobs("queued")
    assert len(jobs) == 2
    assert all(j.solo for j in jobs), "escalated members must go solo"
    assert all(j.attempts == 1 and j.transients == 0 for j in jobs)
    # ...and within the transient budget the batch stays UN-shattered
    q2 = JobQueue(str(tmp_path / "q2"), max_retries=2, backoff_s=0.0)
    for f in files:
        q2.submit(f, OPTS)
    worker2 = ServeWorker(q2, batch_size=2, max_wait_s=0.0,
                          lease_s=30.0, poll_s=0.01,
                          runner=_stub_runner())
    with faults.injected("worker.batch_execute",
                         FaultSpec(kind="transient", at_call=1)):
        worker2.poll_once()
    jobs2 = q2.jobs("queued")
    assert len(jobs2) == 2 and not any(j.solo for j in jobs2)
    assert all(j.attempts == 0 and j.transients == 1 for j in jobs2)


@pytest.mark.chaos
def test_claim_rename_fault_skips_then_recovers(tmp_path):
    """An injected lost claim race (queue.claim_rename, kind=oserror)
    makes claim() move on — the job is simply claimed by the next
    poll, attempts untouched."""
    files = _write_epochs(tmp_path, GOOD_SEEDS[:1])
    q = JobQueue(str(tmp_path / "q"))
    jid, _ = q.submit(files[0], OPTS)
    with faults.injected("queue.claim_rename",
                         FaultSpec(kind="oserror", at_call=1)):
        assert q.claim("w", n=1, lease_s=5.0) == []
        (job,) = q.claim("w", n=1, lease_s=5.0)
    assert job.id == jid and job.attempts == 0


@pytest.mark.chaos
def test_env_driven_chaos_through_cli_serve(tmp_path, capsys,
                                            monkeypatch):
    """SCINT_FAULTS drives a subprocess-style chaos run through the CLI
    entrypoint: the armed transient fault fires in the worker, the
    queue drains clean, and the stats line shows the budget-preserving
    retry."""
    from scintools_tpu.cli import main as cli_main

    files = _write_epochs(tmp_path, GOOD_SEEDS[:2])
    qdir = str(tmp_path / "q")
    client = SurveyClient(qdir)
    client.submit(files, {"lamsteps": True})
    client.drain()
    monkeypatch.setenv(faults.ENV_VAR,
                       "worker.load:transient@1")
    faults.install_env(force=True)
    try:
        # the real pipeline runner would dominate the budget: drive the
        # worker loop directly with the stub (the CLI wiring under test
        # is install_env -> registry -> worker sites)
        q = JobQueue(qdir, backoff_s=0.0)
        worker = ServeWorker(q, batch_size=2, max_wait_s=0.0,
                             lease_s=30.0, poll_s=0.01,
                             runner=_stub_runner())
        stats = worker.run()
    finally:
        faults.clear()
    assert stats["jobs_done"] == 2
    assert stats["job_transient_retries"] == 1
    # and the CLI status verb still reads a clean queue
    assert cli_main(["status", qdir]) == 0
    st = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert st["done"] == 2 and st["depth"] == 0


# ---------------------------------------------------------------------------
# the storage driver seam (utils/fsio -- ISSUE 20 satellites)
# ---------------------------------------------------------------------------


def test_enospc_and_edquot_classify_transient():
    """A full disk / blown quota recovers after compaction or space
    recovery -- it must requeue on the budget-free transient path, not
    burn the bounded retry budget and poison a good job."""
    import errno

    assert classify_error(OSError(errno.ENOSPC, "disk full")) \
        == "transient"
    assert classify_error(OSError(errno.EDQUOT, "quota")) == "transient"
    # an unrelated errno keeps the unknown bucket's bounded retries
    assert classify_error(OSError(errno.EPERM, "denied")) == "unknown"


def test_fsio_errno_fault_kinds_reach_callers(tmp_path):
    """The enospc/eio kinds armed at an fsio verb surface as the real
    OSError the caller's narrow handlers and classify_error see."""
    import errno

    from scintools_tpu.utils import fsio

    p = str(tmp_path / "f.json")
    with faults.injected("fsio.put", FaultSpec(kind="enospc")):
        with pytest.raises(OSError) as ei:
            fsio.put_atomic(p, b"{}")
    assert ei.value.errno == errno.ENOSPC
    assert classify_error(ei.value) == "transient"
    assert not os.path.exists(p)    # fired before any byte landed
    fsio.put_atomic(p, b"{}")       # disarmed: the verb works again
    with faults.injected("fsio.read", FaultSpec(kind="eio")):
        with pytest.raises(OSError) as ei:
            fsio.read(p)
    assert ei.value.errno == errno.EIO
    assert fsio.read(p) == b"{}"


def test_fsio_crash_kinds_carry_driver_choreography():
    """The crash kinds raise the InjectedCrash directive whose .crash
    names the driver's partial-work shape (the fsio verbs translate it
    into bytes + os._exit -- proven end-to-end by the subprocess sweep
    in test_crashpoints.py; here: the registry->directive mapping)."""
    for kind, crash in (("torn_write", "torn"),
                        ("crash_before_rename", "before"),
                        ("crash_after_rename", "after")):
        with faults.injected("fsio.delete", FaultSpec(kind=kind)):
            with pytest.raises(faults.InjectedCrash) as ei:
                faults.check("fsio.delete")
        assert ei.value.crash == crash


def test_fsio_disarmed_overhead_is_one_gate():
    """The production fsio gate: sweep off, registry empty -- 100k
    gate passes cost what 100k dict lookups cost, and no counter or
    crash-point state is touched."""
    from scintools_tpu.utils import fsio

    assert fsio._SWEEP is None          # env instrumentation off
    assert fsio.crash_points() == 0
    assert faults.active() == {}
    with obs.tracing():
        t0 = time.perf_counter()
        for _ in range(100_000):
            fsio._gate("put")
        dt = time.perf_counter() - t0
        assert obs.counters() == {}
    assert dt < 1.0, f"disarmed fsio gate too slow: {dt:.3f}s / 100k"
    obs.reset()


def test_heartbeat_write_failure_counts_fsio_write_errors(tmp_path):
    """Satellite: a worker whose heartbeat put fails (full disk, dead
    NFS) degrades to fsio_write_errors[heartbeat] + a log line -- the
    worker must never crash over liveness reporting."""
    q = JobQueue(str(tmp_path / "q"))
    worker = ServeWorker(q, runner=_stub_runner())
    with obs.tracing():
        with faults.injected("fsio.put", FaultSpec(kind="enospc")):
            worker._beat(force=True)
        c = obs.counters()
    assert c.get("fsio_write_errors") == 1
    assert c.get("fsio_write_errors[heartbeat]") == 1
    obs.reset()


def test_claim_survives_vanished_queue_dirs(tmp_path):
    """Satellite: a vanished lane/shard dir (concurrent GC, a remote
    backend re-sync) or a listing error mid-claim degrades to an empty
    claim, never an exception -- and the next claim heals."""
    qdir = str(tmp_path / "q")
    q = JobQueue(qdir)
    src = str(tmp_path / "v.dat")
    with open(src, "w") as fh:
        fh.write("epoch\n" * 4)
    q.submit(src, {"lamsteps": True}, lane="bulk")
    import shutil

    shutil.rmtree(os.path.join(qdir, "queued"))
    assert q.claim("w", 4, lease_s=5.0) == []
    q = JobQueue(qdir)                  # re-init recreates the layout
    src2 = str(tmp_path / "v2.dat")
    with open(src2, "w") as fh:
        fh.write("epoch2\n" * 4)
    jid2, _ = q.submit(src2, {"lamsteps": True}, lane="bulk")
    with faults.injected("fsio.list",
                         FaultSpec(kind="oserror", times=99)):
        assert q.claim("w", 4, lease_s=5.0) == []
    jobs = q.claim("w", 4, lease_s=5.0)
    assert [j.id for j in jobs] == [jid2]
