"""Plotting smoke tests (Agg backend): every view renders and saves."""

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from scintools_tpu import Dynspec  # noqa: E402
from scintools_tpu import plotting  # noqa: E402
from scintools_tpu.io import from_simulation  # noqa: E402
from scintools_tpu.sim import Simulation  # noqa: E402


@pytest.fixture(scope="module")
def ds():
    sim = Simulation(mb2=2, ns=128, nf=128, dlam=0.25, seed=1234)
    d = from_simulation(sim, freq=1400.0, dt=8.0)
    out = Dynspec(data=d, process=True, lamsteps=True)
    out._sim = sim
    return out


def test_plot_dyn(ds, tmp_path):
    fig = ds.plot_dyn(filename=str(tmp_path / "dyn.png"))
    assert (tmp_path / "dyn.png").stat().st_size > 0
    plt.close(fig)


def test_plot_acf(ds, tmp_path):
    ds.get_scint_params()
    fig = ds.plot_acf(filename=str(tmp_path / "acf.png"), crop_frac=0.5)
    assert (tmp_path / "acf.png").stat().st_size > 0
    plt.close(fig)


def test_plot_acf_reference_parity_features(ds, tmp_path):
    """plot_acf carries the reference's UX: contour mode
    (dynspec.py:276-277), the exact lag0-lag1 white-noise-spike
    subtraction (dynspec.py:267-270), and the scint-scaled twin axes
    (dynspec.py:283-292) when a fit is supplied."""
    from scintools_tpu.plotting import plot_acf

    ds.get_scint_params()
    a = np.asarray(ds.acf)
    fig = plot_acf(a, d=ds.data, scint_params=ds.scint_params,
                   contour=True, filename=str(tmp_path / "c.png"))
    # twin axes present: base + twinx + twiny (+ colorbar axes)
    assert len(fig.axes) >= 4
    labels = {ax.get_ylabel() for ax in fig.axes} \
        | {ax.get_xlabel() for ax in fig.axes}
    assert any("dnu_d" in s for s in labels)
    assert any("tau_d" in s for s in labels)
    plt.close(fig)

    # wn_method="reference": the PLOTTED centre pixel equals the +1
    # time-lag neighbour (read back from the QuadMesh), and the caller's
    # array keeps its spike
    nf, nt = a.shape
    cf, ct = nf // 2, nt // 2
    spike_before = a[cf, ct]
    fig2 = plot_acf(a, d=ds.data, wn_method="reference")
    plotted = np.asarray(
        fig2.axes[0].collections[0].get_array()).reshape(nf, nt)
    assert plotted[cf, ct] == a[cf, ct + 1]
    assert a[cf, ct] == spike_before  # input untouched
    plt.close(fig2)
    fig3 = plot_acf(a, d=ds.data, wn_method="neighbours")
    plotted3 = np.asarray(
        fig3.axes[0].collections[0].get_array()).reshape(nf, nt)
    assert plotted3[cf, ct] == (a[cf, ct - 1] + a[cf, ct + 1]
                                + a[cf - 1, ct] + a[cf + 1, ct]) / 4
    plt.close(fig3)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="wn_method"):
        plot_acf(a, d=ds.data, wn_method="refernce")


def test_plot_sspec_with_arc(ds, tmp_path):
    ds.fit_arc(lamsteps=True, numsteps=2000)
    fig = ds.plot_sspec(plotarc=True, filename=str(tmp_path / "ss.png"))
    assert (tmp_path / "ss.png").stat().st_size > 0
    plt.close(fig)


def test_plot_norm_sspec_and_arc_profile(ds, tmp_path):
    ns = ds.norm_sspec(numsteps=256)
    fig = plotting.plot_norm_sspec(ns, filename=str(tmp_path / "ns.png"))
    plt.close(fig)
    fig = plotting.plot_arc_profile(ds.arc_fit,
                                    filename=str(tmp_path / "ap.png"))
    assert (tmp_path / "ap.png").stat().st_size > 0
    plt.close(fig)


def test_plot_all(ds, tmp_path):
    fig = ds.plot_all(filename=str(tmp_path / "all.png"))
    assert (tmp_path / "all.png").stat().st_size > 0
    plt.close(fig)


def test_sim_views(ds, tmp_path):
    sim = ds._sim
    for fn, name in ((plotting.plot_screen, "screen"),
                     (plotting.plot_intensity, "intensity"),
                     (plotting.plot_efield, "efield")):
        fig = fn(sim, filename=str(tmp_path / f"{name}.png"))
        assert (tmp_path / f"{name}.png").stat().st_size > 0
        plt.close(fig)


def test_plot_thetatheta(ds, tmp_path):
    from scintools_tpu.fit import fit_arc_thetatheta

    if ds.betaeta is None:  # self-contained: don't rely on test order
        ds.fit_arc(lamsteps=True, numsteps=2000)
    sec = ds._secspec(True)
    eta, err, etas, conc = fit_arc_thetatheta(
        sec, ds.betaeta / 3, ds.betaeta * 3, n_eta=32, backend="numpy")
    fig = plotting.plot_thetatheta(sec, eta, conc_curve=(etas, conc),
                                   filename=str(tmp_path / "tt.png"))
    assert (tmp_path / "tt.png").stat().st_size > 0
    plt.close(fig)


def test_plot_wavefield(ds, tmp_path):
    wf = ds.retrieve_wavefield(eta=0.4, chunk_nf=32, chunk_nt=32,
                               backend="numpy")
    fig = plotting.plot_wavefield(wf, filename=str(tmp_path / "wf.png"))
    assert (tmp_path / "wf.png").stat().st_size > 0
    plt.close(fig)
    # single-Axes convention (amplitude panel only)
    fig, ax = plt.subplots()
    out = plotting.plot_wavefield(wf, ax=ax,
                                  filename=str(tmp_path / "wf1.png"))
    assert (tmp_path / "wf1.png").stat().st_size > 0
    plt.close(out)


def test_plot_dyn_lamsteps_and_trap(sim_dynspec, tmp_path):
    """plot_dyn(lamsteps=True)/(trap=True) plot the rescaled arrays
    (dynspec.py:206-229), resampling lazily."""
    from scintools_tpu import Dynspec

    ds = Dynspec(data=sim_dynspec, process=False, backend="numpy")
    out = tmp_path / "lam.png"
    ds.plot_dyn(lamsteps=True, filename=str(out))
    assert out.exists() and ds.lamdyn is not None
    out2 = tmp_path / "trap.png"
    ds.plot_dyn(trap=True, filename=str(out2))
    assert out2.exists() and ds.trapdyn is not None


def test_plot_norm_sspec_all_panels(sim_dynspec, tmp_path):
    """The three reference norm_sspec views (scrunched, unscrunched 2-D,
    power spectrum) render (dynspec.py:869-925)."""
    from scintools_tpu import Dynspec
    from scintools_tpu.plotting import plot_norm_sspec

    ds = Dynspec(data=sim_dynspec, process=True, lamsteps=True,
                 backend="numpy")
    ns = ds.norm_sspec(eta=0.5, numsteps=128)
    out = tmp_path / "norm3.png"
    plot_norm_sspec(ns, filename=str(out), unscrunched=True,
                    powerspec=True)
    assert out.exists() and out.stat().st_size > 0
