"""MFU / roofline accounting (utils/roofline.py): the analytic flop model
and peak resolution feeding bench.py's headline record and
benchmarks/profile_stages.py's per-row %-of-peak columns."""

from types import SimpleNamespace

import numpy as np
import pytest

from scintools_tpu.utils.roofline import (PEAKS_BY_KIND, device_peaks,
                                          pipeline_epoch_model,
                                          roofline_record)


def test_epoch_model_stages_and_totals():
    m = pipeline_epoch_model(256, 512)
    assert set(m) == {"lam", "sspec", "scint", "arc", "total"}
    for v in m.values():
        assert v["flops"] > 0 and v["bytes"] > 0
    assert m["total"]["flops"] == pytest.approx(
        sum(v["flops"] for k, v in m.items() if k != "total"))
    # the padded fft2 dominates an individual epoch at bench shapes
    assert m["sspec"]["flops"] > m["arc"]["flops"]


def test_epoch_model_flags_drop_stages():
    m = pipeline_epoch_model(128, 128, lamsteps=False, fit_arc=False,
                             fit_scint=False)
    assert set(m) == {"sspec", "total"}


def test_epoch_model_monotone_in_shape_and_steps():
    small = pipeline_epoch_model(64, 64)["total"]["flops"]
    big = pipeline_epoch_model(256, 512)["total"]["flops"]
    assert big > small
    a = pipeline_epoch_model(64, 64, numsteps=500)["arc"]["flops"]
    b = pipeline_epoch_model(64, 64, numsteps=2000)["arc"]["flops"]
    assert b == pytest.approx(4 * a)


def test_epoch_model_cut_routes_differ():
    mm = pipeline_epoch_model(256, 512, scint_cuts="matmul")
    ff = pipeline_epoch_model(256, 512, scint_cuts="fft")
    # the Gram route does more raw flops (that's the point: MXU work)
    assert mm["scint"]["flops"] > ff["scint"]["flops"]


def test_device_peaks_table_and_override(monkeypatch):
    p = device_peaks(SimpleNamespace(device_kind="TPU v4"))
    assert p["peak_tflops"] == PEAKS_BY_KIND["TPU v4"][0]
    assert p["peak_gbs"] == PEAKS_BY_KIND["TPU v4"][1]
    assert "TPU v4" in p["source"]

    unknown = device_peaks(SimpleNamespace(device_kind="FPGA x1"))
    assert unknown["peak_tflops"] is None and unknown["peak_gbs"] is None

    monkeypatch.setenv("SCINT_PEAK_TFLOPS", "123.5")
    monkeypatch.setenv("SCINT_PEAK_GBS", "456.0")
    ov = device_peaks(SimpleNamespace(device_kind="FPGA x1"))
    assert ov["peak_tflops"] == 123.5 and ov["peak_gbs"] == 456.0
    assert "override" in ov["source"]


def test_roofline_record_arithmetic():
    rate = 100.0  # epochs/s
    peaks = {"device_kind": "TPU v4", "peak_tflops": 275.0,
             "peak_gbs": 1228.0, "source": "test"}
    rec = roofline_record(rate, 256, 512, peaks=peaks)
    m = pipeline_epoch_model(256, 512)["total"]
    assert rec["achieved_gflops"] == pytest.approx(rate * m["flops"] / 1e9,
                                                   rel=1e-2)
    assert rec["mfu_pct"] == pytest.approx(
        100.0 * rate * m["flops"] / 275e12, rel=2e-2)
    assert rec["hbm_pct"] == pytest.approx(
        100.0 * rate * m["bytes"] / 1228e9, rel=2e-2)
    assert rec["arithmetic_intensity_flop_per_byte"] > 0
    assert set(rec["per_stage_gflop"]) == {"lam", "sspec", "scint", "arc"}


def test_roofline_record_no_peaks_omits_mfu():
    rec = roofline_record(10.0, 64, 64, peaks={})
    assert "mfu_pct" not in rec and "hbm_pct" not in rec
    assert rec["achieved_gflops"] > 0


def test_epoch_model_sanity_magnitude():
    """Order-of-magnitude anchor: one 256x512 epoch is a few hundred
    MFLOP (fft2 on 512x1024 padded grid ~ 50 MFLOP, the cubic solve and
    Gram cuts dominate) — if the model drifts by orders of magnitude the
    MFU headline is garbage."""
    f = pipeline_epoch_model(256, 512)["total"]["flops"]
    assert 1e8 < f < 1e10


def test_roofline_pct_against_ai_implied_ceiling():
    """roofline_pct judges the rate against min(peak_flops, AI*peak_bw):
    for this pipeline's AI (~a few flop/byte) on a v4-like chip the bound
    is bandwidth, and the fraction equals achieved_bytes/peak_bytes."""
    peaks = {"device_kind": "TPU v4", "peak_tflops": 275.0,
             "peak_gbs": 1228.0, "source": "test"}
    rec = roofline_record(100.0, 256, 512, peaks=peaks)
    m = pipeline_epoch_model(256, 512)["total"]
    ai = m["flops"] / m["bytes"]
    assert ai * 1228e9 < 275e12  # bandwidth-bound at this AI
    assert rec["roofline_bound"] == "bandwidth"
    assert rec["roofline_pct"] == pytest.approx(
        100.0 * 100.0 * m["bytes"] / 1228e9, rel=2e-2)
    # bandwidth-bound => roofline_pct coincides with hbm_pct
    assert rec["roofline_pct"] == pytest.approx(rec["hbm_pct"], rel=2e-2)


def test_measure_host_peaks_shape():
    from scintools_tpu.utils.roofline import measure_host_peaks

    p = measure_host_peaks(matmul_n=256, copy_mb=32, iters=1)
    assert p["device_kind"] == "host-cpu"
    assert p["peak_tflops"] > 0 and p["peak_gbs"] > 0
    assert p["source"].startswith("measured on this host")
