"""Ingest/IO tests: psrflux round-trip + reference-loader parity, par
parser, results CSV, adapters (SURVEY.md §4 item 1)."""

import numpy as np
import pytest

from scintools_tpu.data import stack_batch
from scintools_tpu.io import (concatenate_time, from_arrays, from_simulation,
                              float_array_from_dict, pars_to_params, read_par,
                              read_psrflux, read_results, results_row,
                              write_psrflux, write_results)

from reference_oracle import reference_modules


def _small_dyn(rng=None):
    rng = rng or np.random.default_rng(0)
    nchan, nsub = 16, 24
    return from_arrays(
        dyn=rng.standard_normal((nchan, nsub)) + 10,
        times=30.0 * (np.arange(nsub) + 0.5),
        freqs=1400.0 + 1.0 * np.arange(nchan),
        df=1.0, dt=30.0, mjd=55000.0, name="test.dynspec")


def test_psrflux_roundtrip(tmp_path):
    d = _small_dyn()
    path = str(tmp_path / "t.dynspec")
    write_psrflux(d, path)
    d2 = read_psrflux(path)
    np.testing.assert_allclose(np.asarray(d2.dyn), np.asarray(d.dyn),
                               rtol=1e-7)
    np.testing.assert_allclose(d2.freqs, d.freqs, rtol=1e-9)
    assert d2.mjd == d.mjd
    assert d2.nchan == d.nchan and d2.nsub == d.nsub


def test_psrflux_matches_reference_loader(tmp_path):
    mods = reference_modules()
    if mods is None:
        pytest.skip("reference not available")
    ref_dynspec = mods[0]
    d = _small_dyn()
    path = str(tmp_path / "t.dynspec")
    write_psrflux(d, path)
    rd = ref_dynspec.Dynspec(filename=path, verbose=False, process=False)
    ours = read_psrflux(path)
    np.testing.assert_allclose(np.asarray(ours.dyn), rd.dyn, rtol=1e-12)
    np.testing.assert_allclose(ours.freqs, rd.freqs)
    assert ours.nchan == rd.nchan and ours.nsub == rd.nsub
    assert ours.df == rd.df and ours.bw == rd.bw
    assert ours.dt == rd.dt and ours.tobs == rd.tobs
    assert ours.mjd == rd.mjd


def test_read_par(tmp_path):
    p = tmp_path / "psr.par"
    p.write_text(
        "PSRJ     J0437-4715\n"
        "RAJ      04:37:15.8  1  0.1\n"
        "DECJ     -47:15:09.1  1  0.2\n"
        "F0       173.6879458121843  1  1e-12\n"
        "PB       5.741  0  1D-5\n"
        "E        1.9180D-5\n"
        "DMMODEL  ignore-me\n"
        "# comment\n")
    par = read_par(str(p))
    assert par["PSRJ"] == "J0437-4715"
    assert par["ECC"] == pytest.approx(1.918e-5)
    assert par["PB_ERR"] == pytest.approx(1e-5)
    assert par["F0_TYPE"] == "f"
    assert "DMMODEL" not in par
    params = pars_to_params(par)
    # RAJ: 4h37m15.8s -> radians
    assert params["RAJ"] == pytest.approx(
        (4 + 37 / 60 + 15.8 / 3600) * np.pi / 12)
    assert params["DECJ"] == pytest.approx(
        -(47 + 15 / 60 + 9.1 / 3600) * np.pi / 180)


def test_pars_to_lmfit_params_interop():
    """Reference-type interop (scint_utils.py:252-278): returns lmfit
    Parameters with vary=False when lmfit is installed; without it (this
    CI image) raises an ImportError that names the dict alternative."""
    from scintools_tpu.io import pars_to_lmfit_params

    try:
        import lmfit  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="pars_to_params"):
            pars_to_lmfit_params({"F0": 100.0})
        return
    out = pars_to_lmfit_params({"F0": 100.0, "PB": 5.74})
    assert out["F0"].value == 100.0 and not out["F0"].vary
    assert out["PB"].value == 5.74


def test_read_par_matches_reference(tmp_path):
    mods = reference_modules()
    if mods is None:
        pytest.skip("reference not available")
    ref_utils = mods[3]
    p = tmp_path / "psr.par"
    p.write_text("F0  173.68  1  1e-12\nPB  5.741\nE  1.918D-5\nNITS 1\n")
    assert read_par(str(p)) == ref_utils.read_par(str(p))


def test_results_roundtrip(tmp_path):
    path = str(tmp_path / "results.csv")
    d = _small_dyn()

    class S:  # minimal fit-result stand-ins
        tau, tauerr, dnu, dnuerr = 100.0, 5.0, 1.5, 0.1

    class A:
        eta, etaerr, lamsteps = 0.5, 0.05, True

    write_results(path, results_row(d, scint=S, arc=A))
    write_results(path, results_row(d))  # row without fits appends fine
    out = read_results(path)
    assert out["name"][0] == "test.dynspec"
    np.testing.assert_allclose(float_array_from_dict(out, "tau"), [100.0])
    assert "betaeta" in out


def test_concatenate_time_gap():
    a = _small_dyn()
    b = a.replace(mjd=a.mjd + (a.tobs + 300) / 86400, name="b.dynspec")
    c = concatenate_time(a, b)
    assert c.nsub > a.nsub + b.nsub  # gap inserted
    assert c.tobs == pytest.approx(a.tobs + 300 + b.tobs, rel=1e-6)
    # gap region is zero-filled
    gap = np.asarray(c.dyn)[:, a.nsub:c.nsub - b.nsub]
    assert np.all(gap == 0)


def test_stack_batch():
    a, b = _small_dyn(), _small_dyn(np.random.default_rng(1))
    batch = stack_batch([a, b])
    assert batch.dyn.shape == (2, a.nchan, a.nsub)
    assert batch.mjd.shape == (2,)


def test_from_simulation_matches_reference_simdyn():
    mods = reference_modules()
    if mods is None:
        pytest.skip("reference not available")
    ref_dynspec, ref_sim = mods[0], mods[1]
    rs = ref_sim.Simulation(ns=32, nf=8, dlam=0.25, seed=9, verbose=False)
    sd = ref_dynspec.SimDyn(rs, freq=1400.0, dt=0.5)

    from scintools_tpu.sim import Simulation

    ours_sim = Simulation(ns=32, nf=8, dlam=0.25, seed=9)
    ours = from_simulation(ours_sim, freq=1400.0, dt=0.5)
    np.testing.assert_allclose(np.asarray(ours.dyn), sd.dyn, rtol=1e-12)
    np.testing.assert_allclose(ours.freqs, sd.freqs, rtol=1e-12)
    assert ours.name == sd.name


def test_clean_archive_gated():
    """Without the observatory stack, clean_archive raises an actionable
    ImportError rather than crashing obscurely (scint_utils.py:19-56)."""
    from scintools_tpu.io import clean_archive

    try:
        import coast_guard  # noqa: F401

        pytest.skip("coast_guard installed; gate not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="psrchive"):
        clean_archive(None)
