"""Fit-engine tests: parabola/LM golden values, scint-parameter recovery,
arc-curvature recovery on synthetic arcs, reference parity (SURVEY.md §4)."""

import numpy as np
import pytest

from scintools_tpu.fit import (fit_arc, fit_scint_params,
                               fit_scint_params_batch, lm_fit_jax,
                               least_squares_numpy, norm_sspec, savgol1)
from scintools_tpu.fit.arc_fit import make_arc_fitter
from scintools_tpu.data import SecSpec
from scintools_tpu.models import (fit_log_parabola, fit_parabola,
                                  polyfit2_cov, tau_acf_model)

from reference_oracle import reference_modules


# ----------------------------------------------------------------- parabola

def test_polyfit2_matches_numpy_polyfit(rng):
    x = np.linspace(1, 5, 40)
    y = 2 * x ** 2 - 3 * x + 1 + 0.01 * rng.standard_normal(40)
    c_np, cov_np = np.polyfit(x, y, 2, cov=True)
    c, cov = polyfit2_cov(x, y)
    np.testing.assert_allclose(c, c_np, rtol=1e-8)
    np.testing.assert_allclose(cov, cov_np, rtol=1e-6)


def test_fit_parabola_matches_reference(rng):
    mods = reference_modules()
    if mods is None:
        pytest.skip("reference not available")
    ref_models = mods[2]
    x = np.linspace(0.5, 2.0, 30)
    y = -(x - 1.2) ** 2 + 0.02 * rng.standard_normal(30)
    yfit_r, peak_r, err_r = ref_models.fit_parabola(x, y)
    yfit, peak, err = fit_parabola(x, y)
    np.testing.assert_allclose(peak, peak_r, rtol=1e-9)
    np.testing.assert_allclose(err, err_r, rtol=1e-6)
    np.testing.assert_allclose(yfit, yfit_r, rtol=1e-9)

    yfit_r, peak_r, err_r = ref_models.fit_log_parabola(x, y)
    yfit, peak, err = fit_log_parabola(x, y)
    np.testing.assert_allclose(peak, peak_r, rtol=1e-9)
    np.testing.assert_allclose(err, err_r, rtol=1e-6)


def test_masked_parabola_equals_sliced(rng):
    import jax.numpy as jnp

    x = np.linspace(1, 3, 50)
    y = -(x - 2.1) ** 2 + 0.01 * rng.standard_normal(50)
    w = np.zeros(50)
    w[10:40] = 1
    _, peak_s, err_s = fit_parabola(x[10:40], y[10:40])
    _, peak_m, err_m = fit_parabola(jnp.asarray(x), jnp.asarray(y),
                                    w=jnp.asarray(w), xp=jnp)
    np.testing.assert_allclose(float(peak_m), peak_s, rtol=1e-9)
    np.testing.assert_allclose(float(err_m), err_s, rtol=1e-7)


# ------------------------------------------------------------------- savgol

def test_savgol1_matches_scipy(rng):
    from scipy.signal import savgol_filter

    y = rng.standard_normal(61).cumsum()
    ours = savgol1(y, 5)
    ref = savgol_filter(y, 5, 1)
    np.testing.assert_allclose(ours, ref, rtol=1e-10, atol=1e-10)


def test_savgol1_jax_matches_scipy(rng):
    import jax.numpy as jnp
    from scipy.signal import savgol_filter

    y = rng.standard_normal(41).cumsum()
    ours = np.asarray(savgol1(jnp.asarray(y), 7, xp=jnp))
    ref = savgol_filter(y, 7, 1)
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


# ----------------------------------------------------------------------- LM

def test_lm_recovers_exponential():
    import jax.numpy as jnp

    x = np.linspace(0, 10, 100)
    true = np.array([2.5, 1.3])
    y = true[1] * np.exp(-x / true[0])

    def resid(p, x_, y_):
        return y_ - p[1] * jnp.exp(-x_ / p[0])

    res = lm_fit_jax(resid, jnp.array([1.0, 1.0]),
                     bounds=(jnp.array([1e-6, 1e-6]),
                             jnp.array([np.inf, np.inf])),
                     args=(jnp.asarray(x), jnp.asarray(y)), steps=30)
    np.testing.assert_allclose(np.asarray(res.params), true, rtol=1e-6)


def test_lm_matches_scipy_with_noise(rng):
    import jax.numpy as jnp

    x = np.linspace(0, 10, 200)
    y = 1.5 * np.exp(-x / 3.0) + 0.01 * rng.standard_normal(200)

    def resid_np(p):
        return y - p[1] * np.exp(-x / p[0])

    def resid_jax(p, x_, y_):
        return y_ - p[1] * jnp.exp(-x_ / p[0])

    r_np = least_squares_numpy(resid_np, np.array([1.0, 1.0]),
                               bounds=([1e-6, 1e-6], [np.inf, np.inf]))
    r_jax = lm_fit_jax(resid_jax, jnp.array([1.0, 1.0]),
                       bounds=(jnp.array([1e-6, 1e-6]),
                               jnp.array([np.inf, np.inf])),
                       args=(jnp.asarray(x), jnp.asarray(y)), steps=40)
    np.testing.assert_allclose(np.asarray(r_jax.params), r_np.params,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(r_jax.stderr), r_np.stderr,
                               rtol=1e-2)


# ------------------------------------------------------------- scint params

def _synthetic_acf(nchan=64, nsub=128, tau=120.0, dnu=4.0, dt=10.0, df=0.5,
                   amp=1.0, wn=0.3):
    """Build a [2nf, 2nt] ACF whose central cuts follow the model exactly."""
    acf = np.zeros((2 * nchan, 2 * nsub))
    tlags = dt * np.linspace(0, nsub, nsub)
    flags = df * np.linspace(0, nchan, nchan)
    cut_t = tau_acf_model(tlags, tau, amp, 0.0)
    cut_f = amp * np.exp(-flags / (dnu / np.log(2))) * (1 - flags / flags.max())
    acf[nchan, nsub:] = cut_t
    acf[nchan:, nsub] = cut_f
    acf[nchan, nsub] += wn  # zero-lag spike appears in both cuts
    return acf


def test_fit_scint_params_numpy_recovers():
    acf = _synthetic_acf()
    sp = fit_scint_params(acf, dt=10.0, df=0.5, nchan=64, nsub=128,
                          backend="numpy")
    np.testing.assert_allclose(sp.tau, 120.0, rtol=2e-2)
    np.testing.assert_allclose(sp.dnu, 4.0, rtol=5e-2)


def test_fit_scint_params_jax_matches_numpy():
    acf = _synthetic_acf()
    sp_np = fit_scint_params(acf, dt=10.0, df=0.5, nchan=64, nsub=128,
                             backend="numpy")
    sp_j = fit_scint_params(acf, dt=10.0, df=0.5, nchan=64, nsub=128,
                            backend="jax")
    np.testing.assert_allclose(float(sp_j.tau), sp_np.tau, rtol=1e-3)
    np.testing.assert_allclose(float(sp_j.dnu), sp_np.dnu, rtol=1e-3)


def test_fit_scint_params_batch():
    acfs = np.stack([_synthetic_acf(tau=100.0), _synthetic_acf(tau=200.0)])
    sp = fit_scint_params_batch(acfs, dt=10.0, df=0.5, nchan=64, nsub=128)
    np.testing.assert_allclose(np.asarray(sp.tau), [100.0, 200.0], rtol=5e-2)


def test_fit_scint_params_on_simulated(sim_dynspec):
    """End-to-end: simulated dynspec -> ACF -> fit; recovered scales are
    positive and within the observation span."""
    from scintools_tpu.ops import acf

    d = sim_dynspec
    a = acf(np.asarray(d.dyn, dtype=np.float64), backend="numpy")
    sp = fit_scint_params(a, dt=d.dt, df=d.df, nchan=d.nchan, nsub=d.nsub,
                          backend="numpy")
    assert 0 < sp.tau < d.tobs
    assert 0 < sp.dnu < d.bw


# ---------------------------------------------------------------- arc fits

def _arc_secspec(eta=0.5, nr=128, nc=256, noise=0.05, rng=None):
    """Synthetic secondary spectrum with power concentrated on the parabola
    tdel = eta * fdop^2 (plus noise floor), in dB."""
    rng = rng or np.random.default_rng(7)
    fdop = np.linspace(-10, 10, nc)
    tdel = np.linspace(0, 40, nr)
    power = np.full((nr, nc), 1e-3)
    arc_t = eta * fdop ** 2
    for j, t in enumerate(arc_t):
        i = np.argmin(np.abs(tdel - t))
        if t <= tdel[-1]:
            power[max(i - 1, 0): i + 2, j] += 1.0
    power *= rng.uniform(0.8, 1.2, size=power.shape)
    sec_db = 10 * np.log10(power + noise * 1e-3)
    return SecSpec(sspec=sec_db, fdop=fdop, tdel=tdel, beta=tdel,
                   lamsteps=True)


def test_fit_arc_norm_sspec_recovers_eta():
    sec = _arc_secspec(eta=0.5)
    fit = fit_arc(sec, freq=1400.0, numsteps=2000, backend="numpy")
    assert fit.eta == pytest.approx(0.5, rel=0.15)
    assert fit.etaerr > 0


def test_fit_arc_gridmax_recovers_eta():
    sec = _arc_secspec(eta=0.5)
    fit = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=500,
                  backend="numpy")
    assert fit.eta == pytest.approx(0.5, rel=0.2)


def test_fit_arc_jax_matches_numpy():
    sec = _arc_secspec(eta=0.8)
    f_np = fit_arc(sec, freq=1400.0, numsteps=1024, backend="numpy")
    f_j = fit_arc(sec, freq=1400.0, numsteps=1024, backend="jax")
    np.testing.assert_allclose(float(f_j.eta), f_np.eta, rtol=0.05)
    assert f_j.profile_power.shape == f_j.profile_power_filt.shape


def test_fit_arc_jax_matches_numpy_offref_freq():
    """Regression: the delmax double-adjustment and eta double-conversion
    quirks must match between backends when freq != ref_freq."""
    sec = _arc_secspec(eta=0.5)
    kw = dict(freq=1000.0, delmax=10.0, numsteps=1024)
    f_np = fit_arc(sec, backend="numpy", **kw)
    f_j = fit_arc(sec, backend="jax", **kw)
    np.testing.assert_allclose(float(f_j.eta), f_np.eta, rtol=0.05)


def test_fit_arc_gridmax_jax_falls_back_to_numpy():
    sec = _arc_secspec(eta=0.5)
    fit = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=500,
                  backend="jax")
    assert fit.eta == pytest.approx(0.5, rel=0.2)


def test_arc_fitter_batched():
    secs = [_arc_secspec(eta=e, rng=np.random.default_rng(i))
            for i, e in enumerate([0.4, 0.8])]
    fitter = make_arc_fitter(fdop=secs[0].fdop, yaxis=secs[0].beta,
                             tdel=secs[0].tdel, freq=1400.0, numsteps=1024)
    import jax.numpy as jnp

    batch = jnp.stack([jnp.asarray(s.sspec) for s in secs])
    fit = fitter(batch)
    etas = np.asarray(fit.eta)
    np.testing.assert_allclose(etas, [0.4, 0.8], rtol=0.15)


def test_arc_fitter_stacked_campaign():
    """fitter.stacked: nanmean the per-epoch normalised profiles across
    a campaign of same-eta epochs, then one measurement.  B=1 stacking
    must equal the per-epoch fit exactly (same chain, trivial mean);
    stacking many noisy epochs must recover eta at least as well as the
    median single-epoch fit and report a smaller vertex error."""
    import jax.numpy as jnp

    eta_true = 0.6
    secs = [_arc_secspec(eta=eta_true, rng=np.random.default_rng(100 + i))
            for i in range(6)]
    fitter = make_arc_fitter(fdop=secs[0].fdop, yaxis=secs[0].beta,
                             tdel=secs[0].tdel, freq=1400.0, numsteps=1024)
    batch = jnp.stack([jnp.asarray(s.sspec) for s in secs])

    one = fitter(batch[:1])
    one_stacked = fitter.stacked(batch[:1])
    np.testing.assert_allclose(float(one_stacked.eta),
                               float(np.asarray(one.eta)[0]), rtol=1e-12)

    per_epoch = fitter(batch)
    stacked = fitter.stacked(batch)
    eta_s = float(stacked.eta)
    assert np.isfinite(eta_s)
    assert eta_s == pytest.approx(eta_true, rel=0.15)
    med_err = np.nanmedian(np.abs(np.asarray(per_epoch.eta) - eta_true))
    assert abs(eta_s - eta_true) <= med_err + 0.05 * eta_true
    # the stacked profile is smoother: the parabola-vertex error must
    # not exceed the median per-epoch one
    assert float(stacked.etaerr2) <= float(
        np.nanmedian(np.asarray(per_epoch.etaerr2))) * 1.5

    # one fully corrupted epoch (all-NaN sspec -> NaN profile AND NaN
    # noise estimate) must not poison the campaign: both the profile
    # stack and the noise reduction are nan-robust
    corrupted = np.asarray(batch).copy()
    corrupted[2] = np.nan
    stacked_c = fitter.stacked(jnp.asarray(corrupted))
    assert np.isfinite(float(stacked_c.eta))
    assert float(stacked_c.eta) == pytest.approx(eta_true, rel=0.15)


def test_arc_fitter_scrunch_rows_matches_gather():
    """scrunch_rows>0 (lax.scan row-block delay-scrunch, bounded HBM
    working set) reproduces the full-gather path's measurements to
    floating-point association."""
    secs = [_arc_secspec(eta=e, rng=np.random.default_rng(i))
            for i, e in enumerate([0.4, 0.8])]
    kw = dict(fdop=secs[0].fdop, yaxis=secs[0].beta, tdel=secs[0].tdel,
              freq=1400.0, numsteps=1024)
    import jax.numpy as jnp

    batch = np.stack([np.asarray(s.sspec) for s in secs])
    batch[0, 40, 10] = -np.inf  # zero-power dB pixel: must poison the
    batch[1, 25, 30] = np.nan   # mean exactly like nanmean; NaN skipped
    batch = jnp.asarray(batch)
    base = make_arc_fitter(**kw)(batch)
    for rc in (7, 32):  # non-divisor and divisor block sizes
        fit = make_arc_fitter(scrunch_rows=rc, **kw)(batch)
        np.testing.assert_allclose(np.asarray(fit.eta),
                                   np.asarray(base.eta), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(fit.etaerr),
                                   np.asarray(base.etaerr), rtol=1e-8)
    with pytest.raises(ValueError, match="scrunch_rows"):
        make_arc_fitter(scrunch_rows=-7, **kw)


def test_norm_sspec_profile_peaks_at_unity():
    """With eta set to the true curvature, the folded normalised profile
    peaks at normalised fdop = +-1."""
    sec = _arc_secspec(eta=0.6)
    ns = norm_sspec(sec, freq=1400.0, eta=0.6, maxnormfac=2, numsteps=512)
    prof = ns.normsspecavg
    fx = ns.fdopnew
    good = np.isfinite(prof) & (np.abs(fx) > 0.2)
    peak_x = np.abs(fx[good][np.argmax(prof[good])])
    assert peak_x == pytest.approx(1.0, abs=0.15)


def test_fit_arc_forward_parabola_raises():
    """A spectrum with power at the centre only (no arc) should trip the
    forward-parabola guard (dynspec.py:723-724) or produce a tiny eta."""
    rng = np.random.default_rng(3)
    sec_db = 10 * np.log10(rng.uniform(0.9, 1.1, size=(64, 128)) * 1e-3)
    sec = SecSpec(sspec=sec_db, fdop=np.linspace(-5, 5, 128),
                  tdel=np.linspace(0, 20, 64), beta=np.linspace(0, 20, 64),
                  lamsteps=True)
    try:
        fit = fit_arc(sec, freq=1400.0, numsteps=500, backend="numpy")
        assert np.isfinite(fit.eta)
    except ValueError as e:
        assert "forward parabola" in str(e)


def test_multi_arc_fit():
    """Two arcs injected at different curvatures are both recovered via
    the multi-arc brackets (the reference's etamin/etamax-array mode)."""
    from scintools_tpu.fit.arc_fit import fit_arcs_multi

    fdop = np.linspace(-10, 10, 256)
    tdel = np.linspace(0, 40, 128)
    power = np.full((128, 256), 1e-4)
    rng = np.random.default_rng(0)
    for eta_true in (0.3, 2.0):
        for i, td in enumerate(tdel):
            if td <= 0:
                continue
            x_arc = np.sqrt(td / eta_true)
            for s in (-1, 1):
                j = np.argmin(np.abs(fdop - s * x_arc))
                power[i, j] += 1.0 + 0.05 * rng.standard_normal()
    sec_db = 10 * np.log10(power)
    sec = SecSpec(sspec=sec_db, fdop=fdop, tdel=tdel, beta=tdel,
                  lamsteps=True)
    fits = fit_arcs_multi(sec, freq=1400.0, brackets=[(0.1, 1.0),
                                                      (1.0, 5.0)],
                          numsteps=2000)
    etas = [float(f.eta) for f in fits]
    assert etas[0] == pytest.approx(0.3, rel=0.25)
    assert etas[1] == pytest.approx(2.0, rel=0.25)


def test_scint_params_sspec_method():
    """Fourier-domain fit (reference's unfinished 'sspec' method) recovers
    tau/dnu consistently with the ACF-domain fit."""
    from scintools_tpu.fit.scint_fit import (fit_scint_params,
                                             fit_scint_params_sspec)
    from scintools_tpu.models.acf_models import scint_acf_model_2d

    nchan, nsub, dt, df = 64, 96, 8.0, 0.25
    x_t = dt * np.arange(-nsub, nsub)
    x_f = df * np.arange(-nchan, nchan)
    acf2d = scint_acf_model_2d(x_t, x_f, 120.0, 4.0, 1.0, 0.1, xp=np)
    acf2d = acf2d + 0.005 * np.random.default_rng(1).standard_normal(
        acf2d.shape)
    sp_acf = fit_scint_params(acf2d, dt, df, nchan, nsub)
    sp_ss = fit_scint_params_sspec(acf2d, dt, df, nchan, nsub)
    assert float(sp_ss.tau) == pytest.approx(float(sp_acf.tau), rel=0.15)
    assert float(sp_ss.dnu) == pytest.approx(float(sp_acf.dnu), rel=0.25)
    # jax engine agrees with numpy engine
    sp_j = fit_scint_params_sspec(acf2d, dt, df, nchan, nsub,
                                  backend="jax")
    assert float(sp_j.tau) == pytest.approx(float(sp_ss.tau), rel=0.05)


def test_dynspec_multi_arc_attribute_handling():
    """Multi-arc via the wrapper: scalar etamax broadcasts, mismatched
    lengths raise, and downstream norm_sspec/plot use the primary arc."""
    from scintools_tpu import Dynspec
    from scintools_tpu.io import from_simulation
    from scintools_tpu.sim import Simulation

    d = from_simulation(Simulation(mb2=2, ns=128, nf=128, dlam=0.25,
                                   seed=1234), freq=1400.0, dt=8.0)
    ds = Dynspec(data=d, process=True, lamsteps=True)
    fits = ds.fit_arc(lamsteps=True, numsteps=2000,
                      etamin=[1.0, 20.0], etamax=[20.0, 200.0])
    assert len(fits) == 2
    assert ds.betaeta.shape == (2,)
    assert (ds.betaeta > 0).all()
    # downstream consumers normalise to the primary arc
    ns = ds.norm_sspec(numsteps=128)
    assert np.isfinite(ns.normsspecavg).any()
    with pytest.raises(ValueError, match="lengths differ"):
        ds.fit_arc(lamsteps=True, etamin=[1.0, 5.0, 10.0],
                   etamax=[5.0, 10.0])


def test_multi_arc_non_lamsteps_unit_consistency():
    """For tdel-space spectra, bracket windows for arcs 2..N must go
    through the SAME unit conversion fit_arc applies to arc 1's
    constraint: the same bracket given twice must yield identical fits
    (arc 1 via fit_arc's internal conversion, arc 2 via the multi-arc
    driver's)."""
    from scintools_tpu.fit.arc_fit import (_beta_to_eta_factor,
                                           fit_arcs_multi)

    # a WELL-CONDITIONED nonlam spectrum (explicit etamin keeps the
    # double-converted resample scales in-grid; sim-default nonlam fits
    # are flat-window degenerate and quarantined — see
    # test_fit_arc_nonlam_degenerate_quarantine_parity)
    sec, etamin, _ = _nonlam_arc_secspec()
    b2e = _beta_to_eta_factor(1400.0, 1400.0)
    fit1 = fit_arc(sec, freq=1400.0, numsteps=2000, backend="numpy",
                   etamin=etamin, etamax=100 * etamin)
    eta_user = float(fit1.eta) / b2e  # bracket in user (tdel) units
    fits = fit_arcs_multi(sec, 1400.0,
                          brackets=[(0.5 * eta_user, 2 * eta_user)] * 2,
                          numsteps=2000, etamin=etamin,
                          etamax=100 * etamin)
    assert float(fits[0].eta) == pytest.approx(float(fits[1].eta),
                                               rel=1e-9)
    assert np.isfinite(fits[0].noise) and fits[0].noise > 0


def test_gridmax_jax_matches_numpy():
    """The new jax gridmax fitter agrees with the numpy reference-parity
    path on a synthetic arc (documented mask-fill smoothing differences ->
    relative tolerance)."""
    sec = _arc_secspec(eta=0.6)
    fit_np = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=2000,
                     backend="numpy")
    fit_j = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=2000,
                    backend="jax")
    assert float(fit_j.eta) == pytest.approx(float(fit_np.eta), rel=0.15)
    assert float(fit_j.etaerr) > 0


def test_jax_arc_fitter_impossible_constraint_raises():
    """A constraint excluding the whole eta grid fails loudly at build
    time on the jax path (the numpy path raises at fit time)."""
    sec = _arc_secspec(eta=0.5)
    for method in ("norm_sspec", "gridmax"):
        with pytest.raises(ValueError, match="no eta grid points"):
            fit_arc(sec, freq=1400.0, method=method, numsteps=500,
                    constraint=(1e7, 2e7), backend="jax")


def test_constraint_past_emax_raises_norm_sspec():
    """A constraint inside the eta grid but wholly past the emax validity
    window must also fail at build time (guard intersects keep_static):
    for this geometry the grid tops out ~3x past emax."""
    sec = _arc_secspec(eta=0.5)
    fdop = np.asarray(sec.fdop)
    tdel = np.asarray(sec.tdel)
    emax = tdel.max() / ((fdop[1] - fdop[0]) * 3) ** 2  # default cutmid=3
    with pytest.raises(ValueError, match="no eta grid points"):
        fit_arc(sec, freq=1400.0, numsteps=500, backend="jax",
                constraint=(emax * 2, emax * 5))


def test_fit_arc_bit_matches_reference_end_to_end():
    """FLAGSHIP PARITY: the full measurement chain (trim -> refill ->
    lambda rescale -> secondary spectrum -> norm_sspec arc fit) matches
    the actual reference implementation to machine precision, including
    the noise-walk error bar."""
    mods = reference_modules()
    if mods is None:
        pytest.skip("reference not available")
    from reference_oracle import make_ref_dynspec

    from scintools_tpu import Dynspec
    from scintools_tpu.io import from_simulation
    from scintools_tpu.sim import Simulation

    d = from_simulation(Simulation(mb2=2, ns=128, nf=128, dlam=0.25,
                                   seed=1234), freq=1400.0, dt=8.0)
    rd = make_ref_dynspec(d)
    rd.trim_edges()
    rd.refill(linear=True)
    rd.calc_sspec(lamsteps=True, plot=False)
    rd.fit_arc(lamsteps=True, numsteps=2000, plot=False, display=False)

    ds = Dynspec(data=d, process=False)
    ds.trim_edges().refill()
    ds.fit_arc(lamsteps=True, numsteps=2000)

    np.testing.assert_allclose(ds.betaeta, rd.betaeta, rtol=1e-10)
    np.testing.assert_allclose(ds.betaetaerr, rd.betaetaerr, rtol=1e-10)


def test_fit_arc_nonlam_degenerate_quarantine_parity():
    """Non-lamsteps norm_sspec fits are degenerate BY CONSTRUCTION in
    the reference: the double eta conversion (dynspec.py:498-499 then
    820-825) shrinks eta by beta_to_eta^2 ~ 2e-8, so every resample
    scale lands ~4 orders past the fdop grid, every bin clamps to the
    row-edge mean, and the parabola vertex is rounding noise.  Both
    backends must detect this flat window identically (bit-identical
    profile values drive the decision): numpy raises, jax quarantines
    to NaN — never a spurious finite curvature on either side.  The
    underlying profile/filter must still match bit-for-bit."""
    from scintools_tpu.io import from_simulation
    from scintools_tpu.ops import sspec as sspec_op, sspec_axes
    from scintools_tpu.sim import Simulation

    for seed in (3, 7, 15):   # ex-mismatch seeds: raise/finite, 2-5% off
        d = from_simulation(Simulation(mb2=2, ns=128, nf=128, dlam=0.25,
                                       seed=seed), freq=1400.0, dt=8.0)
        arr = sspec_op(np.asarray(d.dyn, np.float64), backend="numpy")
        fdop, tdel, beta = sspec_axes(d.dyn.shape[0], d.dyn.shape[1],
                                      float(d.dt), float(d.df))
        sec = SecSpec(sspec=arr, fdop=fdop, tdel=tdel, beta=beta,
                      lamsteps=False)
        with pytest.raises(ValueError, match="flat across the fit"):
            fit_arc(sec, freq=float(d.freq), numsteps=500,
                    backend="numpy")
        fj = fit_arc(sec, freq=float(d.freq), numsteps=500,
                     backend="jax")
        assert np.isnan(float(fj.eta)) and np.isnan(float(fj.etaerr))
        # the profile itself (not the degenerate vertex) stays
        # bit-compatible: compare the jax full-grid profile's finite
        # bins against a serial norm_sspec-chain recomputation
        pp = np.asarray(fj.profile_power)
        assert np.isfinite(pp).sum() > 0


def _nonlam_arc_secspec(seed=11):
    """Non-lamsteps secondary spectrum with a recoverable arc: etamin
    chosen so the reference's double-converted resample scales stay
    inside the fdop grid (top-row scale ~ max|fdop|), arc planted at
    normalised fdop = 0.5 => eta_peak = 4*etamin in converted units.
    Returns (sec, etamin, eta_t)."""
    from scintools_tpu.fit.arc_fit import _beta_to_eta_factor

    rng = np.random.default_rng(seed)
    nr, nc = 128, 256
    fdop = np.linspace(-10, 10, nc)
    tdel = np.linspace(0, 40, nr)
    b2e = _beta_to_eta_factor(1400.0, 1400.0)
    etamin = 40.0 / (10.0 ** 2 * b2e ** 2)
    eta_t = 4.0 * etamin * b2e ** 2
    power = np.full((nr, nc), 1e-3)
    arc_t = eta_t * fdop ** 2
    for j, t in enumerate(arc_t):
        i = np.argmin(np.abs(tdel - t))
        if t <= tdel[-1]:
            power[max(i - 1, 0): i + 2, j] += 1.0
    power *= rng.uniform(0.8, 1.2, size=power.shape)
    sec = SecSpec(sspec=10 * np.log10(power + 0.05e-3), fdop=fdop,
                  tdel=tdel, beta=tdel, lamsteps=False)
    return sec, etamin, eta_t


def test_fit_arc_nonlam_wellconditioned_bit_parity():
    """With an explicit etamin large enough that the double-converted
    resample scales stay inside the fdop grid, the non-lamsteps profile
    has real structure and an interior peak — and the batched fitter
    must then match the serial chain tightly (the grid-edge/flat corner
    from round 1 is quarantined, not silently different)."""
    from scintools_tpu.fit.arc_fit import _beta_to_eta_factor

    b2e = _beta_to_eta_factor(1400.0, 1400.0)
    sec, etamin, eta_t = _nonlam_arc_secspec()
    fn = fit_arc(sec, freq=1400.0, numsteps=500, backend="numpy",
                 etamin=etamin, etamax=100 * etamin)
    fj = fit_arc(sec, freq=1400.0, numsteps=500, backend="jax",
                 etamin=etamin, etamax=100 * etamin)
    np.testing.assert_allclose(float(fj.eta), float(fn.eta), rtol=1e-12)
    np.testing.assert_allclose(float(fj.etaerr), float(fn.etaerr),
                               rtol=1e-12)
    # the peak is interior (not the round-1 grid-edge corner) and lands
    # on the planted arc: eta_peak = 4*etamin in converted units
    filt = np.asarray(fn.profile_power_filt)
    peak = int(np.argmin(np.abs(filt - np.max(filt))))
    assert 10 < peak < filt.size - 10
    etamin_c = etamin * b2e   # fit-space units ((f/ref)^2 = 1 here)
    assert float(fn.eta) == pytest.approx(4 * etamin_c, rel=0.05)


def test_lm_steps_default_is_converged():
    """The PipelineConfig default lm_steps must leave the batched
    scint fit CONVERGED: quadrupling the step budget may move tau/dnu
    by at most a small fraction of their own 1-sigma errors on
    realistic simulated epochs (guards both the default and future
    LM-schedule changes)."""
    from scintools_tpu.io import from_simulation
    from scintools_tpu.ops import refill, trim_edges
    from scintools_tpu.parallel import PipelineConfig, make_pipeline
    from scintools_tpu.sim import Simulation

    eps = [refill(trim_edges(from_simulation(
        Simulation(mb2=m, ns=128, nf=128, dlam=0.25, seed=s),
        freq=1400.0, dt=8.0))) for s, m in ((0, 2), (1, 8), (2, 20))]
    dyn = np.stack([np.asarray(e.dyn, np.float32) for e in eps])
    freqs, times = np.asarray(eps[0].freqs), np.asarray(eps[0].times)
    default = PipelineConfig().lm_steps

    def fit(steps):
        r = make_pipeline(freqs, times,
                          PipelineConfig(fit_arc=False,
                                         lm_steps=steps))(dyn)
        return (np.asarray(r.scint.tau), np.asarray(r.scint.dnu),
                np.asarray(r.scint.tauerr), np.asarray(r.scint.dnuerr))

    base = fit(default)
    ref = fit(4 * default)
    dtau = np.abs(base[0] - ref[0]) / np.maximum(ref[2], 1e-12)
    ddnu = np.abs(base[1] - ref[1]) / np.maximum(ref[3], 1e-12)
    assert dtau.max() < 0.1, dtau
    assert ddnu.max() < 0.1, ddnu


def test_arc_power_curve_template_and_fit():
    """models.arc_power_curve: the reference's empty stub
    (scint_models.py:191-201) implemented as a power-law + floor dB
    template; the LM fit recovers planted parameters on both backends
    and the residual convention matches (ydata - model) * weights."""
    from scintools_tpu.models import (arc_power_curve,
                                      arc_power_curve_model,
                                      fit_arc_power_curve)

    rng = np.random.default_rng(5)
    x = np.linspace(0.2, 8.0, 120)
    amp, index, floor = 3.0, 2.2, 0.05
    y = arc_power_curve_model(x, amp, index, floor)
    y_noisy = y + rng.normal(0, 0.05, x.size)
    # residual convention
    res = arc_power_curve({"amp": amp, "index": index, "floor": floor},
                          x, ydata=y, weights=np.full(x.size, 2.0))
    np.testing.assert_allclose(res, 0.0, atol=1e-12)
    tmpl = arc_power_curve({"amp": amp, "index": index, "floor": floor},
                           x)
    np.testing.assert_allclose(tmpl, y, rtol=1e-12)
    for backend in ("numpy", "jax"):
        p, err = fit_arc_power_curve(x, y_noisy, backend=backend)
        assert p[0] == pytest.approx(amp, rel=0.2), backend
        assert p[1] == pytest.approx(index, rel=0.1), backend
        assert p[2] == pytest.approx(floor, rel=0.5), backend
        assert np.all(np.isfinite(err))
    # NaN bins are dropped; too-masked profiles fail loudly
    y_nan = y_noisy.copy()
    y_nan[::2] = np.nan
    p, _ = fit_arc_power_curve(x, y_nan)
    assert p[1] == pytest.approx(index, rel=0.15)
    with pytest.raises(ValueError, match=">= 4 finite"):
        fit_arc_power_curve(x[:3], y[:3])


def test_make_dynspec_gates_without_psrchive(monkeypatch, tmp_path):
    """io.make_dynspec (reference's empty stub, scint_utils.py:431-437)
    raises actionable guidance when psrflux is absent, and builds the
    documented command line when a stand-in executable exists."""
    import scintools_tpu.io.archive as arch

    monkeypatch.setattr("shutil.which", lambda _: None)
    with pytest.raises(RuntimeError, match="psrflux"):
        arch.make_dynspec("fake.ar")

    calls = {}
    monkeypatch.setattr("shutil.which", lambda _: "/usr/bin/psrflux")

    def fake_run(cmd, check, capture_output):
        calls["cmd"] = cmd
        open(str(tmp_path / "a.ar.dynspec"), "w").write("")
        return None

    monkeypatch.setattr("subprocess.run", fake_run)
    out = arch.make_dynspec(str(tmp_path / "a.ar"), template="t.std")
    assert out == str(tmp_path / "a.ar.dynspec")
    assert calls["cmd"] == ["psrflux", "-s", "t.std", "-e", "dynspec",
                            str(tmp_path / "a.ar")]
    # outdir relocates host-side (psrflux always writes beside the
    # archive; no version-dependent flags involved)
    out2 = arch.make_dynspec(str(tmp_path / "a.ar"),
                             outdir=str(tmp_path / "moved"))
    assert out2 == str(tmp_path / "moved" / "a.ar.dynspec")
    import os

    assert os.path.exists(out2)
    with pytest.raises(NotImplementedError, match="phasebin"):
        arch.make_dynspec(str(tmp_path / "a.ar"), phasebin=4)


def test_thetatheta_recovers_curvature_both_backends():
    """Eigenvalue-concentration curvature (beyond-reference method):
    recovers the true eta on a synthetic arc, backends agree, and the
    concentration peaks at the arc."""
    from scintools_tpu.fit import fit_arc_thetatheta

    sec = _arc_secspec(eta=0.6)
    eta_np, err_np, etas, conc = fit_arc_thetatheta(sec, 0.1, 5.0,
                                                    n_eta=64,
                                                    backend="numpy")
    eta_j, err_j, _, conc_j = fit_arc_thetatheta(sec, 0.1, 5.0, n_eta=64,
                                                 backend="jax")
    assert eta_np == pytest.approx(0.6, rel=0.1)
    assert eta_j == pytest.approx(eta_np, rel=0.05)
    np.testing.assert_allclose(conc_j, conc, rtol=2e-3, atol=2e-3)
    assert err_np > 0
    # the concentration curve peaks near the true arc, not at the edges
    assert 0.3 < etas[np.argmax(conc)] < 1.2


def test_thetatheta_via_fit_arc_dispatch():
    from scintools_tpu.fit import fit_arc

    sec = _arc_secspec(eta=0.6)
    fit = fit_arc(sec, freq=1400.0, method="thetatheta", etamin=0.1,
                  etamax=5.0, numsteps=64)
    assert float(fit.eta) == pytest.approx(0.6, rel=0.1)
    with pytest.raises(ValueError, match="etamin/etamax"):
        fit_arc(sec, freq=1400.0, method="thetatheta")


def test_batched_fit_arc_quarantines_where_numpy_raises():
    """Quarantine parity: on epochs where the serial reference chain
    RAISES (forward parabola / too-short window — genuinely common on
    small noisy spectra), the batched fitter returns NaN, never a
    spurious finite curvature; where the chain succeeds, the batched
    value is bit-identical.  This also pins down what used to be
    plain-vs-sharded nondeterminism: 2-point parabola vertices are
    floating-point noise."""
    from scintools_tpu.io import from_simulation
    from scintools_tpu.ops import scale_lambda, sspec as sspec_op, \
        sspec_axes
    from scintools_tpu.sim import Simulation

    matched = raised = 0
    for seed in (1, 2, 40, 41, 203):
        nf, nt = (32, 32) if seed in (1, 2) else (96, 128)
        d = from_simulation(Simulation(mb2=2, ns=nt, nf=nf, dlam=0.25,
                                       seed=seed), freq=1400.0, dt=8.0)
        lamdyn, lam, dlam = scale_lambda(d, backend="numpy")
        arr = sspec_op(np.asarray(lamdyn, np.float64), backend="numpy")
        fdop, tdel, beta = sspec_axes(lamdyn.shape[0], lamdyn.shape[1],
                                      float(d.dt), float(d.df), dlam=dlam)
        sec = SecSpec(sspec=arr, fdop=fdop, tdel=tdel, beta=beta,
                      lamsteps=True)
        try:
            eta_n = float(fit_arc(sec, freq=float(d.freq), numsteps=500,
                                  backend="numpy").eta)
        except ValueError:
            eta_n = float("nan")
            raised += 1
        eta_j = float(fit_arc(sec, freq=float(d.freq), numsteps=500,
                              backend="jax").eta)
        if np.isnan(eta_n):
            assert np.isnan(eta_j), (seed, eta_j)
        else:
            np.testing.assert_allclose(eta_j, eta_n, rtol=1e-12)
            matched += 1
    assert matched >= 1 and raised >= 1   # both behaviors exercised


def test_make_tt_fitter_batched_matches_single():
    """The batched fixed-shape theta-theta fitter reproduces
    fit_arc_thetatheta's eta/etaerr/concentration on every lane."""
    from scintools_tpu.fit import fit_arc_thetatheta
    from scintools_tpu.fit.thetatheta import make_tt_fitter

    sec = _arc_secspec(eta=0.6)
    eta_j, err_j, etas, conc_j = fit_arc_thetatheta(
        sec, 0.1, 5.0, n_eta=64, backend="jax")
    fitter = make_tt_fitter(sec.fdop, sec.beta, 0.1, 5.0, n_eta=64,
                            lamsteps=True)
    batch = np.stack([np.asarray(sec.sspec)] * 3)
    fit = fitter(batch)
    assert np.asarray(fit.eta).shape == (3,)
    np.testing.assert_allclose(np.asarray(fit.profile_eta), etas,
                               rtol=1e-12)
    for b in range(3):
        np.testing.assert_allclose(np.asarray(fit.profile_power[b]),
                                   conc_j, rtol=1e-5, atol=1e-7)
        assert float(fit.eta[b]) == pytest.approx(eta_j, rel=1e-5)
        assert float(fit.etaerr[b]) == pytest.approx(err_j, rel=1e-5)


def test_make_tt_fitter_validation():
    from scintools_tpu.fit.thetatheta import make_tt_fitter

    with pytest.raises(ValueError, match="bracket"):
        make_tt_fitter(np.linspace(-10, 10, 32), np.linspace(0, 40, 16),
                       0.0, np.inf)


def test_thetatheta_on_simulated_spectrum():
    """On a realistic simulated epoch the theta-theta eta lands in the
    same range as the norm_sspec measurement."""
    from scintools_tpu import Dynspec
    from scintools_tpu.fit import fit_arc_thetatheta
    from scintools_tpu.io import from_simulation
    from scintools_tpu.sim import Simulation

    d = from_simulation(Simulation(mb2=2, ns=128, nf=128, dlam=0.25,
                                   seed=1234), freq=1400.0, dt=8.0)
    ds = Dynspec(data=d, process=True, lamsteps=True)
    ds.fit_arc(lamsteps=True, numsteps=2000)   # norm_sspec: ~12.3
    sec = ds._secspec(True)
    eta_tt, err_tt, _, _ = fit_arc_thetatheta(
        sec, ds.betaeta / 4, ds.betaeta * 4, n_eta=96)
    assert eta_tt == pytest.approx(ds.betaeta, rel=0.6)


def test_thetatheta_multi_arc_and_kwargs():
    """Multi-arc thetatheta runs one bounded sweep per bracket; cutmid/
    startbin forward; constraint narrows the bracket."""
    from scintools_tpu.fit.arc_fit import fit_arcs_multi

    fdop = np.linspace(-10, 10, 256)
    tdel = np.linspace(0, 40, 128)
    power = np.full((128, 256), 1e-3)
    for eta_true in (0.3, 2.0):
        for j, f in enumerate(fdop):
            t = eta_true * f ** 2
            i = np.argmin(np.abs(tdel - t))
            if t <= tdel[-1]:
                power[max(i - 1, 0): i + 2, j] += 1.0
    sec_db = 10 * np.log10(power)
    sec = SecSpec(sspec=sec_db, fdop=fdop, tdel=tdel, beta=tdel,
                  lamsteps=True)
    fits = fit_arcs_multi(sec, 1400.0, brackets=[(0.1, 0.9), (0.9, 6.0)],
                          method="thetatheta", numsteps=64)
    assert float(fits[0].eta) == pytest.approx(0.3, rel=0.25)
    assert float(fits[1].eta) == pytest.approx(2.0, rel=0.25)
    # constraint intersects the bracket
    f2 = fit_arc(sec, 1400.0, method="thetatheta", etamin=0.1, etamax=6.0,
                 numsteps=64, constraint=(0.9, 6.0))
    assert float(f2.eta) == pytest.approx(2.0, rel=0.25)
    with pytest.raises(ValueError, match="empty eta bracket"):
        fit_arc(sec, 1400.0, method="thetatheta", etamin=0.1, etamax=0.5,
                constraint=(1.0, 2.0))


# ------------------------------------------------------------ asymm arms

def _asymm_secspec(eta_l=0.6, eta_r=0.4, nr=128, nc=256, rng=None):
    """Arc with different curvature on the two fdop arms (refractive
    asymmetry): left arm follows eta_l, right arm eta_r."""
    rng = rng or np.random.default_rng(11)
    fdop = np.linspace(-10, 10, nc)
    tdel = np.linspace(0, 40, nr)
    power = np.full((nr, nc), 1e-3)
    for j, f in enumerate(fdop):
        eta = eta_l if f < 0 else eta_r
        t = eta * f ** 2
        i = np.argmin(np.abs(tdel - t))
        if t <= tdel[-1]:
            power[max(i - 1, 0): i + 2, j] += 1.0
    power *= rng.uniform(0.9, 1.1, size=power.shape)
    sec_db = 10 * np.log10(power)
    return SecSpec(sspec=sec_db, fdop=fdop, tdel=tdel, beta=tdel,
                   lamsteps=True)


def test_fit_arc_asymm_recovers_per_arm_curvatures():
    """asymm=True measures each fdop arm independently (the reference
    plumbs `asymm` but its per-arm fits are broken by a copy-paste bug,
    dynspec.py:567-568, and never returned)."""
    sec = _asymm_secspec(eta_l=0.7, eta_r=0.35)
    fit = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=501,
                  asymm=True, backend="numpy")
    assert fit.eta_left == pytest.approx(0.7, rel=0.2)
    assert fit.eta_right == pytest.approx(0.35, rel=0.2)
    assert fit.eta_left > fit.eta_right
    assert fit.etaerr_left > 0 and fit.etaerr_right > 0
    # combined eta sits between the arms
    assert fit.eta_right * 0.8 < fit.eta < fit.eta_left * 1.2


def test_fit_arc_asymm_norm_sspec_symmetric_arms_agree():
    """On a symmetric arc both arms and the combined fit agree."""
    sec = _arc_secspec(eta=0.5)
    fit = fit_arc(sec, freq=1400.0, numsteps=2000, asymm=True,
                  backend="numpy")
    assert fit.eta_left == pytest.approx(fit.eta_right, rel=0.15)
    assert fit.eta == pytest.approx(0.5, rel=0.15)


def test_fit_arc_asymm_default_off_leaves_arm_fields_none():
    sec = _arc_secspec(eta=0.5)
    fit = fit_arc(sec, freq=1400.0, numsteps=1000, backend="numpy")
    assert fit.eta_left is None and fit.eta_right is None


def test_fit_arc_asymm_rejects_unsupported_modes():
    sec = _arc_secspec(eta=0.5)
    with pytest.raises(ValueError, match="thetatheta"):
        fit_arc(sec, freq=1400.0, method="thetatheta", etamin=0.1,
                etamax=1.0, asymm=True, backend="numpy")
    from scintools_tpu import Dynspec
    from scintools_tpu.io import from_simulation
    from scintools_tpu.sim import Simulation

    d = from_simulation(Simulation(mb2=2, ns=64, nf=64, dlam=0.25, seed=3),
                        freq=1400.0, dt=8.0)
    ds = Dynspec(data=d, process=False, backend="numpy")
    with pytest.raises(ValueError, match="multi-arc"):
        ds.fit_arc(etamin=[0.1, 0.5], etamax=[0.4, 1.0], asymm=True)


def test_fit_arc_asymm_jax_matches_numpy():
    """The batched jax fitter's per-arm measurement agrees with the numpy
    per-arm path on an asymmetric synthetic arc (both methods)."""
    sec = _asymm_secspec(eta_l=0.7, eta_r=0.35)
    for method, steps in (("gridmax", 501), ("norm_sspec", 1500)):
        f_np = fit_arc(sec, freq=1400.0, method=method, numsteps=steps,
                       asymm=True, backend="numpy")
        f_j = fit_arc(sec, freq=1400.0, method=method, numsteps=steps,
                      asymm=True, backend="jax")
        assert float(f_j.eta_left) == pytest.approx(f_np.eta_left,
                                                    rel=0.15), method
        assert float(f_j.eta_right) == pytest.approx(f_np.eta_right,
                                                     rel=0.15), method
        assert float(f_j.eta_left) > float(f_j.eta_right)


def test_pipeline_arc_asymm_batched():
    """PipelineConfig(arc_asymm=True): per-arm curvatures come out of the
    one-jit batched step with [B] leaves."""
    import jax.numpy as jnp

    from scintools_tpu.parallel import PipelineConfig, make_pipeline

    from synth import synth_arc_epoch

    B = 3
    eps = [synth_arc_epoch(seed=s) for s in range(B)]
    dyn = np.stack([np.asarray(d.dyn, dtype=np.float32) for d in eps])
    cfg = PipelineConfig(arc_numsteps=500, lm_steps=10, arc_asymm=True)
    res = make_pipeline(np.asarray(eps[0].freqs),
                        np.asarray(eps[0].times), cfg)(jnp.asarray(dyn))
    for field in ("eta_left", "etaerr_left", "eta_right", "etaerr_right"):
        v = getattr(res.arc, field)
        assert v is not None and v.shape == (B,)
    assert np.all(np.isfinite(np.asarray(res.arc.eta)))


def test_fit_arc_asymm_degenerate_arm_is_nan_on_jax():
    """An arc with power on only one fdop arm: the empty arm's fit is a
    forward parabola; numpy NaNs it via the caught raise, and the jax
    path must NaN-poison it too (not return a spurious finite eta)."""
    rng = np.random.default_rng(13)
    fdop = np.linspace(-10, 10, 256)
    tdel = np.linspace(0, 40, 128)
    power = np.full((128, 256), 1e-3)
    for j, f in enumerate(fdop):
        if f >= 0:  # right arm only
            t = 0.5 * f ** 2
            i = np.argmin(np.abs(tdel - t))
            if t <= tdel[-1]:
                power[max(i - 1, 0): i + 2, j] += 1.0
    power *= rng.uniform(0.95, 1.05, size=power.shape)
    sec = SecSpec(sspec=10 * np.log10(power), fdop=fdop, tdel=tdel,
                  beta=tdel, lamsteps=True)
    f_j = fit_arc(sec, freq=1400.0, method="gridmax", numsteps=501,
                  asymm=True, backend="jax")
    assert float(f_j.eta_right) == pytest.approx(0.5, rel=0.25)
    # left arm has no arc: either NaN-poisoned (forward parabola) or at
    # least wildly unconstrained relative to the right arm
    el = float(f_j.eta_left)
    assert np.isnan(el) or abs(el - 0.5) > 0.25 * 0.5


def test_batched_multi_arc_windows():
    """make_arc_fitter(constraints=[...]) measures K arcs per epoch from
    ONE shared profile: [B, K] eta leaves, each window's eta inside it."""
    import jax.numpy as jnp

    sec = _arc_secspec(eta=0.5)
    # add a second arc at eta=1.5
    fdop = np.asarray(sec.fdop)
    tdel = np.asarray(sec.tdel)
    power = 10 ** (np.asarray(sec.sspec) / 10)
    for j, f in enumerate(fdop):
        t = 1.5 * f ** 2
        i = np.argmin(np.abs(tdel - t))
        if t <= tdel[-1]:
            power[max(i - 1, 0): i + 2, j] += 0.6
    sec2 = SecSpec(sspec=10 * np.log10(power), fdop=fdop, tdel=tdel,
                   beta=tdel, lamsteps=True)

    windows = ((0.25, 0.9), (1.0, 2.5))
    fitter = make_arc_fitter(fdop=fdop, yaxis=tdel, tdel=tdel, freq=1400.0,
                             lamsteps=True, numsteps=2000,
                             constraints=windows)
    batch = fitter(jnp.asarray(sec2.sspec)[None])
    eta = np.asarray(batch.eta)
    assert eta.shape == (1, 2)
    assert windows[0][0] < eta[0, 0] < windows[0][1]
    assert windows[1][0] < eta[0, 1] < windows[1][1]
    assert eta[0, 0] == pytest.approx(0.5, rel=0.2)
    assert eta[0, 1] == pytest.approx(1.5, rel=0.2)


def test_pipeline_arc_brackets_batched():
    """PipelineConfig(arc_brackets=...) yields [B, K] curvature leaves
    from the one-jit step."""
    import jax.numpy as jnp

    from scintools_tpu.parallel import PipelineConfig, make_pipeline

    rng = np.random.default_rng(9)
    B, nf, nt = 2, 48, 48
    dyn = (1 + 0.3 * rng.standard_normal((B, nf, nt))).astype(np.float32)**2
    freqs = np.linspace(1380.0, 1420.0, nf)
    times = np.arange(nt) * 4.0
    cfg = PipelineConfig(arc_numsteps=300, lm_steps=10, fit_scint=False,
                         arc_brackets=((0.0, 5.0), (5.0, np.inf)))
    res = make_pipeline(freqs, times, cfg)(jnp.asarray(dyn))
    assert np.asarray(res.arc.eta).shape == (B, 2)
    assert np.asarray(res.arc.etaerr).shape == (B, 2)


def test_batched_multi_arc_rejects_asymm_combo():
    sec = _arc_secspec(eta=0.5)
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_arc_fitter(fdop=np.asarray(sec.fdop),
                        yaxis=np.asarray(sec.tdel),
                        tdel=np.asarray(sec.tdel), freq=1400.0,
                        lamsteps=True, numsteps=500, asymm=True,
                        constraints=((0.1, 1.0),))


def test_scint_params_sspec_free_alpha(sim_dynspec):
    """alpha=None on the Fourier-domain fit: every get_scint_params
    method now supports a free power-law index."""
    from scintools_tpu import Dynspec

    ds = Dynspec(data=sim_dynspec, process=False, backend="numpy")
    ds.calc_acf()
    sp = ds.get_scint_params(method="sspec", alpha=None)
    assert 0 < float(sp.talpha) < 8
    assert np.isfinite(ds.tau) and np.isfinite(ds.dnu)


def test_batched_multi_arc_non_lamsteps_window_units():
    """constraints windows on a tdel-space (lamsteps=False) fitter get the
    same beta-eta unit conversion as the single constraint: a window
    bracketing the fitted eta in USER units must contain the measurement."""
    import jax.numpy as jnp

    from scintools_tpu.fit.arc_fit import _beta_to_eta_factor

    # the well-conditioned nonlam spectrum (in-grid resample scales +
    # interior peak); sim-style nonlam epochs are flat-window degenerate
    # and quarantined, so they cannot carry a units test
    sec, etamin, _ = _nonlam_arc_secspec()
    freq = 1400.0
    kw = dict(etamin=etamin, etamax=100 * etamin)
    single = fit_arc(sec, freq=freq, numsteps=500, backend="jax", **kw)
    assert np.isfinite(float(single.eta))
    b2e = _beta_to_eta_factor(freq, 1400.0) / (freq / 1400.0) ** 2
    eta_user = float(single.eta) / b2e
    fitter = make_arc_fitter(fdop=np.asarray(sec.fdop),
                             yaxis=np.asarray(sec.tdel),
                             tdel=np.asarray(sec.tdel), freq=freq,
                             lamsteps=False, numsteps=500,
                             constraints=((0.5 * eta_user, 2 * eta_user),),
                             **kw)
    batch = fitter(jnp.asarray(sec.sspec)[None])
    np.testing.assert_allclose(float(batch.eta[0, 0]), float(single.eta),
                               rtol=1e-9)


def test_get_scint_params_unknown_method_raises(sim_dynspec):
    # mcmc=True now works for every method (tests/test_mcmc_2d.py);
    # only unknown method names fail
    from scintools_tpu import Dynspec

    ds = Dynspec(data=sim_dynspec, process=False, backend="numpy")
    ds.calc_acf()
    with pytest.raises(ValueError, match="unknown method"):
        ds.get_scint_params(method="nope")


# ---------------------------------------------------------------------------
# arc_tail="fast": masked-reduction measurement tail (opt-in speed knob)
# ---------------------------------------------------------------------------


def test_arc_tail_fast_matches_exact_within_etaerr():
    """The fast tail runs the same smooth/peak/walk/parabola stages as
    the exact (reference-semantics) tail, on the masked full grid —
    the contract is eta agreement within the fit's OWN etaerr on
    healthy arcs, not bit equality."""
    import jax.numpy as jnp

    secs = [_arc_secspec(eta=e, rng=np.random.default_rng(10 + i))
            for i, e in enumerate([0.3, 0.5, 0.8, 1.2])]
    kw = dict(fdop=secs[0].fdop, yaxis=secs[0].beta, tdel=secs[0].tdel,
              freq=1400.0, numsteps=1024)
    batch = jnp.stack([jnp.asarray(s.sspec) for s in secs])
    exact = make_arc_fitter(arc_tail="exact", **kw)(batch)
    fast = make_arc_fitter(arc_tail="fast", **kw)(batch)
    e_ex = np.asarray(exact.eta)
    e_fa = np.asarray(fast.eta)
    err = np.maximum(np.asarray(exact.etaerr), np.asarray(fast.etaerr))
    assert np.all(np.isfinite(e_fa)), e_fa
    assert np.all(np.abs(e_fa - e_ex) <= err), (e_fa, e_ex, err)
    # both recover the planted curvatures
    np.testing.assert_allclose(e_fa, [0.3, 0.5, 0.8, 1.2], rtol=0.15)
    assert np.all(np.asarray(fast.etaerr) > 0)


def test_arc_tail_fast_gridmax():
    import jax.numpy as jnp

    sec = _arc_secspec(eta=0.5)
    kw = dict(fdop=np.asarray(sec.fdop), yaxis=np.asarray(sec.beta),
              tdel=np.asarray(sec.tdel), freq=1400.0, numsteps=500,
              method="gridmax")
    batch = jnp.asarray(sec.sspec)[None]
    exact = make_arc_fitter(arc_tail="exact", **kw)(batch)
    fast = make_arc_fitter(arc_tail="fast", **kw)(batch)
    e_ex = float(np.asarray(exact.eta)[0])
    e_fa = float(np.asarray(fast.eta)[0])
    err = max(float(np.asarray(exact.etaerr)[0]),
              float(np.asarray(fast.etaerr)[0]))
    assert np.isfinite(e_fa)
    assert abs(e_fa - e_ex) <= err, (e_fa, e_ex, err)
    assert e_fa == pytest.approx(0.5, rel=0.2)


def test_arc_tail_fast_degenerate_lanes_nan():
    """Degenerate epochs NaN out under the fast tail exactly like the
    exact tail (the batch driver's quarantine contract): a flat
    (constant-power) spectrum and an all-NaN spectrum."""
    import jax.numpy as jnp

    sec = _arc_secspec(eta=0.5)
    kw = dict(fdop=sec.fdop, yaxis=sec.beta, tdel=sec.tdel,
              freq=1400.0, numsteps=1024)
    flat = np.zeros_like(np.asarray(sec.sspec))
    allnan = np.full_like(flat, np.nan)
    batch = jnp.stack([jnp.asarray(sec.sspec), jnp.asarray(flat),
                       jnp.asarray(allnan)])
    for tail in ("exact", "fast"):
        fit = make_arc_fitter(arc_tail=tail, **kw)(batch)
        eta = np.asarray(fit.eta)
        assert np.isfinite(eta[0]), (tail, eta)
        assert np.isnan(eta[1]) and np.isnan(eta[2]), (tail, eta)
        assert np.isnan(np.asarray(fit.etaerr)[1:]).all(), tail


def test_arc_tail_fast_stacked_and_constraints():
    """The fast tail rides the same late-bound closure as the exact
    one: the campaign stack and multi-window (constraints) modes route
    through it unchanged."""
    import jax.numpy as jnp

    eta_true = 0.6
    secs = [_arc_secspec(eta=eta_true, rng=np.random.default_rng(200 + i))
            for i in range(4)]
    kw = dict(fdop=secs[0].fdop, yaxis=secs[0].beta, tdel=secs[0].tdel,
              freq=1400.0, numsteps=1024)
    batch = jnp.stack([jnp.asarray(s.sspec) for s in secs])
    fitter = make_arc_fitter(arc_tail="fast", **kw)
    stacked = fitter.stacked(batch)
    assert float(stacked.eta) == pytest.approx(eta_true, rel=0.15)
    multi = make_arc_fitter(arc_tail="fast",
                            constraints=((0.3, 1.2), (0.05, 0.3)),
                            **kw)(batch)
    assert np.asarray(multi.eta).shape == (4, 2)
    np.testing.assert_allclose(np.asarray(multi.eta)[:, 0], eta_true,
                               rtol=0.15)


def test_arc_tail_validation():
    from scintools_tpu.parallel import PipelineConfig, make_pipeline

    sec = _arc_secspec()
    with pytest.raises(ValueError, match="arc_tail"):
        make_arc_fitter(fdop=sec.fdop, yaxis=sec.beta, tdel=sec.tdel,
                        freq=1400.0, arc_tail="bogus")
    freqs = np.linspace(1400.0, 1440.0, 32)
    times = np.arange(32) * 8.0
    with pytest.raises(ValueError, match="arc_tail"):
        make_pipeline(freqs, times, PipelineConfig(arc_tail="bogus"))
    with pytest.raises(ValueError, match="arc_tail"):
        make_pipeline(freqs, times,
                      PipelineConfig(arc_method="thetatheta",
                                     arc_tail="fast",
                                     arc_constraint=(0.1, 2.0)))


def test_arc_tail_fast_asymm_arms():
    """The fast tail serves the per-arm (asymm) measurements through
    the same late-bound closure: arm etas bracket the combined eta and
    agree with the exact tail within the arm errors."""
    import jax.numpy as jnp

    sec = _arc_secspec(eta=0.6, rng=np.random.default_rng(77))
    kw = dict(fdop=sec.fdop, yaxis=sec.beta, tdel=sec.tdel,
              freq=1400.0, numsteps=1024, asymm=True)
    batch = jnp.asarray(sec.sspec)[None]
    exact = make_arc_fitter(arc_tail="exact", **kw)(batch)
    fast = make_arc_fitter(arc_tail="fast", **kw)(batch)
    for arm in ("eta_left", "eta_right"):
        e = float(np.asarray(getattr(exact, arm))[0])
        f = float(np.asarray(getattr(fast, arm))[0])
        err = max(float(np.asarray(
            getattr(exact, arm.replace("eta_", "etaerr_")))[0]), 1e-3)
        assert np.isfinite(f)
        assert f == pytest.approx(0.6, rel=0.25)
        assert abs(f - e) <= max(3 * err, 0.15 * e), (arm, f, e, err)
