"""scintools_tpu.obs: spans, counters, JSONL round-trip, disabled-mode
no-op, and the traced batched pipeline (ISSUE 1 tentpole acceptance:
compile-vs-execute rows in `trace report`, bit-identical results with
tracing on vs off, stage spans exactly once per epoch batch)."""

import json
import threading
import time

import numpy as np
import pytest

from synth import synth_arc_epoch

from scintools_tpu import obs


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Every test starts and ends with tracing off and an empty registry
    (obs state is process-global by design)."""
    obs.disable(flush=False)
    obs.reset()
    yield
    obs.disable(flush=False)
    obs.reset()


# ---------------------------------------------------------------------------
# core: disabled no-op, nesting, counters
# ---------------------------------------------------------------------------


def test_disabled_span_is_shared_noop():
    # disabled span() returns ONE shared singleton: no allocation beyond
    # the flag check, nothing recorded
    assert not obs.enabled()
    s1, s2 = obs.span("a", attr=1), obs.span("b")
    assert s1 is s2
    with s1 as inside:
        inside.set(more=2)     # set() is a no-op, not an error
    obs.inc("epochs_processed", 5)
    obs.gauge("g", 1.0)
    assert obs.summary() == {}
    assert obs.counters() == {}
    assert obs.get_registry().events() == []


def test_disabled_wrapper_paths_record_nothing():
    # the pipeline's always-installed hooks must stay silent when off
    @obs.traced("f.stage")
    def f(x):
        return x + 1

    assert f(1) == 2
    assert obs.fence(np.ones(3)).sum() == 3.0
    assert obs.summary() == {}


def test_nested_span_timing_attrs_and_paths():
    with obs.tracing() as reg:
        with obs.span("outer", kind="root") as sp_out:
            time.sleep(0.002)
            with obs.span("inner") as sp_in:
                time.sleep(0.001)
                sp_in.set(found=3)
    events = {e["name"]: e for e in reg.events()}
    assert set(events) == {"outer", "inner"}
    assert events["inner"]["path"] == "outer/inner"
    assert events["outer"]["path"] == "outer"
    assert events["outer"]["attrs"] == {"kind": "root"}
    assert events["inner"]["attrs"] == {"found": 3}
    # monotonic-clock duration: child fits inside parent, both >= sleeps
    assert sp_in.dur_ms >= 1.0
    assert sp_out.dur_ms >= sp_in.dur_ms + 2.0 - 0.5
    s = obs.summary()
    assert s["outer"]["count"] == 1
    for k in ("total_ms", "mean_ms", "p50_ms", "p95_ms"):
        assert s["outer"][k] >= s["inner"][k] > 0


def test_counter_aggregation_across_threads():
    with obs.tracing():
        def work():
            for _ in range(1000):
                obs.inc("epochs_processed")
                obs.inc("bytes_h2d", 2)

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # spans from concurrent threads must not corrupt each other's
        # nesting (thread-local stacks)
        with obs.span("main.only"):
            pass
    c = obs.counters()
    assert c["epochs_processed"] == 8000
    assert c["bytes_h2d"] == 16000
    assert obs.summary()["main.only"]["count"] == 1


def test_summary_percentiles():
    with obs.tracing() as reg:
        pass
    # inject known durations straight into the registry
    for d in [1.0, 2.0, 3.0, 4.0, 100.0]:
        reg._durs.setdefault("x", []).append(d)
    s = reg.summary()["x"]
    assert s["count"] == 5
    assert s["total_ms"] == 110.0
    assert s["p50_ms"] == 3.0
    assert s["p95_ms"] == 100.0


def test_hist_quantile_clamps_to_observed_max():
    """Regression (ISSUE 16 satellite): a quantile landing in the last
    populated bucket must report at most the observed max, not the
    bucket's upper ladder edge — a single 1.0 s sample sits in the
    (~0.71, 1.0] bucket and an unclamped p99 would read the edge of a
    LATER interpolation point, overshooting the true extreme."""
    from scintools_tpu.obs.hist import Hist
    h = Hist()
    for v in (0.2, 0.3, 1.0):
        h.observe(v)
    for q in (0.5, 0.9, 0.99, 1.0):
        assert h.quantile(q) <= 1.0, (q, h.quantile(q))
    assert h.quantile(1.0) == 1.0
    # and the low side symmetrically never reads below the observed min
    assert h.quantile(0.0) >= h.vmin


# ---------------------------------------------------------------------------
# JSONL sink -> trace report round trip
# ---------------------------------------------------------------------------


def test_jsonl_roundtrip_through_trace_report(tmp_path, capsys):
    from scintools_tpu.cli import main as cli_main

    path = str(tmp_path / "t.jsonl")
    with obs.tracing(jsonl=path):
        with obs.span("ops.sspec", backend="numpy"):
            time.sleep(0.001)
        with obs.span("ops.sspec", backend="numpy"):
            pass
        obs.inc("epochs_processed", 3)
    # file has one JSON object per line; spans + flushed counters
    events = [json.loads(x) for x in open(path) if x.strip()]
    kinds = {e["kind"] for e in events}
    assert kinds == {"span", "counter"}
    assert sum(e["kind"] == "span" for e in events) == 2

    rc = cli_main(["trace", "report", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ops.sspec" in out
    assert "epochs_processed = 3" in out
    # aggregation columns present
    for col in ("count", "total_ms", "p50_ms", "p95_ms"):
        assert col in out


def test_multiple_flushes_do_not_double_count(tmp_path, capsys):
    # bench flushes at its exit points AND inside device_throughput;
    # counter events are deltas, so trace report's sum stays the truth
    from scintools_tpu.cli import main as cli_main

    path = str(tmp_path / "f.jsonl")
    obs.enable(jsonl=path)
    try:
        obs.inc("bytes_h2d", 100)
        obs.flush()
        obs.flush()                      # no new increments: no event
        obs.inc("bytes_h2d", 50)
    finally:
        obs.disable()                    # flushes the remaining delta
    events = [json.loads(x) for x in open(path) if x.strip()]
    vals = [e["value"] for e in events if e["kind"] == "counter"]
    assert vals == [100, 50]
    rc = cli_main(["trace", "report", path])
    assert rc == 0
    assert "bytes_h2d = 150" in capsys.readouterr().out


def test_trace_report_missing_or_binary_file(tmp_path, capsys):
    from scintools_tpu.cli import main as cli_main

    rc = cli_main(["trace", "report", str(tmp_path / "nope.jsonl")])
    assert rc == 1
    binary = tmp_path / "not_a_trace.bin"
    binary.write_bytes(b"\xff\xfe\x00binary\x9c")
    rc = cli_main(["trace", "report", str(binary)])   # no traceback
    assert rc == 1


def test_cli_unwritable_trace_path_is_clean_error(tmp_path, capsys):
    from scintools_tpu.cli import main as cli_main

    rc = cli_main(["--trace", str(tmp_path / "no/such/dir/t.jsonl"),
                   "trace", "report", str(tmp_path / "x.jsonl")])
    assert rc == 1
    assert "cannot open" in capsys.readouterr().err
    assert not obs.enabled()


# ---------------------------------------------------------------------------
# traced batched pipeline (the acceptance criteria)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_pipeline(tmp_path_factory):
    """One pipeline over 2 simulated epochs, run tracing-off then
    tracing-on (JSONL attached), results + events captured."""
    from scintools_tpu.parallel import PipelineConfig, run_pipeline

    # smallest program that still exercises the full step (sspec -> arc
    # fit -> scint fit): the fixture pays TWO compiles (jit for the off
    # run, AOT for the traced run), so keep the trace cheap
    epochs = [synth_arc_epoch(seed=s) for s in range(2)]
    cfg = PipelineConfig(arc_numsteps=64, lm_steps=3)
    obs.disable(flush=False)
    obs.reset()
    res_off = run_pipeline(epochs, cfg)
    spans_off = obs.summary()
    path = str(tmp_path_factory.mktemp("trace") / "pipe.jsonl")
    with obs.tracing(jsonl=path) as reg:
        res_on = run_pipeline(epochs, cfg)
        events = reg.events()
        counters = obs.counters()
    res_off2 = run_pipeline(epochs, cfg)   # off again, post-trace
    return dict(res_off=res_off, res_on=res_on, res_off2=res_off2,
                events=events, counters=counters, path=path,
                spans_off=spans_off)


def test_disabled_pipeline_records_no_spans(traced_pipeline):
    assert traced_pipeline["spans_off"] == {}


def test_stage_spans_once_per_epoch_batch(traced_pipeline):
    # 2 equal-grid epochs -> ONE bucket batch -> each stage span exactly
    # once; compile and execute split into separate spans by the
    # AOT-instrumented step
    names = [e["name"] for e in traced_pipeline["events"]]
    for stage in ("pipeline.run", "pipeline.stage",
                  "pipeline.step.compile", "pipeline.step.execute",
                  "pipeline.gather"):
        assert names.count(stage) == 1, (stage, names)
    # nesting: stage/gather under the run root
    paths = {e["name"]: e["path"] for e in traced_pipeline["events"]}
    assert paths["pipeline.stage"] == "pipeline.run/pipeline.stage"
    assert paths["pipeline.gather"] == "pipeline.run/pipeline.gather"


def test_pipeline_counters(traced_pipeline):
    c = traced_pipeline["counters"]
    assert c["epochs_processed"] == 2
    assert c["jit_cache_miss"] >= 1
    # 2 epochs of 64x64 float64
    assert c["bytes_h2d"] == 2 * 64 * 64 * 8


def test_tracing_does_not_change_results(traced_pipeline):
    """Acceptance: bit-identical results with tracing on vs off (and
    off-after-on, so enabling tracing once cannot poison later runs)."""
    def leaves(buckets):
        out = []
        for _idx, res in buckets:
            for leaf in (res.scint.tau, res.scint.dnu, res.arc.eta,
                         res.arc.etaerr):
                out.append(np.asarray(leaf))
        return out

    for a, b in zip(leaves(traced_pipeline["res_off"]),
                    leaves(traced_pipeline["res_on"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(leaves(traced_pipeline["res_off"]),
                    leaves(traced_pipeline["res_off2"])):
        np.testing.assert_array_equal(a, b)


def test_trace_report_has_compile_and_execute_rows(traced_pipeline,
                                                   capsys):
    """Acceptance: `trace report` on a JSONL from a traced run_pipeline
    over >= 2 simulated epochs shows distinct compile-time and
    execute-time rows."""
    from scintools_tpu.cli import main as cli_main

    rc = cli_main(["trace", "report", traced_pipeline["path"]])
    out = capsys.readouterr().out
    assert rc == 0
    assert "pipeline.step.compile" in out
    assert "pipeline.step.execute" in out
    lines = {ln.split()[0]: ln for ln in out.splitlines() if ln.strip()}
    # compile and execute are separate aggregation rows with real times
    assert lines["pipeline.step.compile"] != lines["pipeline.step.execute"]
    assert "epochs_processed = 2" in out


def test_instrument_jit_reuses_compiled_signature():
    import jax
    import jax.numpy as jnp

    calls = []

    @jax.jit
    def f(x):
        calls.append(1)
        return jnp.sin(x).sum()

    g = obs.instrument_jit(f, "t.step")
    assert obs.instrument_jit(f, "t.step") is g    # memoised wrapper
    x = np.ones((4, 4), np.float32)
    with obs.tracing() as reg:
        out1 = g(x)
        out2 = g(x)                                 # same signature
        g(np.ones((2, 2), np.float32))              # new signature
    names = [e["name"] for e in reg.events()]
    assert names.count("t.step.compile") == 2
    assert names.count("t.step.execute") == 3
    assert obs.counters()["jit_cache_miss"] == 2
    assert float(np.asarray(out1)) == float(np.asarray(out2))
    # disabled: falls straight through to the jit callable
    y = g(x)
    assert float(np.asarray(y)) == float(np.asarray(out1))
