"""svd_model (scint_utils.py:401-426 parity): rank-N flattening."""

import numpy as np
import pytest

from scintools_tpu.ops import svd_model


@pytest.fixture(scope="module")
def banded(rng):
    """Rank-1 bandpass times a noisy scintillation field."""
    nf, nt = 40, 60
    band = 1.0 + 0.5 * np.sin(np.linspace(0, np.pi, nf))
    gain = 1.0 + 0.2 * np.cos(np.linspace(0, 4, nt))
    field = 1.0 + 0.05 * rng.standard_normal((nf, nt))
    return band[:, None] * gain[None, :] * field


def test_rank1_model_recovers_bandpass(banded):
    flat, model = svd_model(banded, nmodes=1)
    # the flattened spectrum loses the rank-1 band structure
    row_means = flat.mean(axis=1)
    assert np.ptp(row_means) < 0.02
    # model itself is close to the data (rank-1 dominates)
    assert np.linalg.norm(banded - model) / np.linalg.norm(banded) < 0.1


def test_jax_matches_numpy(banded):
    flat_np, model_np = svd_model(banded, nmodes=2, backend="numpy")
    flat_j, model_j = svd_model(banded, nmodes=2, backend="jax")
    # SVD sign conventions may differ per mode, but the rank-2 reconstruction
    # and the flattened magnitude are basis-invariant
    np.testing.assert_allclose(np.abs(model_j), np.abs(model_np),
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.abs(flat_j), np.abs(flat_np),
                               rtol=1e-8, atol=1e-10)


def test_zero_guard():
    arr = np.zeros((4, 4))
    flat, model = svd_model(arr)
    assert np.all(np.isfinite(flat))
