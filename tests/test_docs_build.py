"""The documentation site builds from the shipped markdown sources
(SURVEY.md: the reference ships built Sphinx HTML; the pinned
environment has no sphinx, so scripts/build_docs.py is the
zero-dependency builder and this test is its gate)."""

import os
import re
import runpy
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    import build_docs

    out = str(tmp_path_factory.mktemp("docs_html"))
    return build_docs, build_docs.build(out)


def test_all_pages_build_nonempty(built):
    build_docs, pages = built
    assert len(pages) == len(build_docs.PAGES)
    for p in pages:
        assert os.path.getsize(p) > 1000, p


def test_index_carries_nav_and_quickstart(built):
    _, pages = built
    index = [p for p in pages if p.endswith("index.html")][0]
    h = open(index, encoding="utf-8").read()
    assert "<nav>" in h and 'href="performance.html"' in h
    assert "Quickstart" in h
    # code fences render as escaped blocks, not markup soup
    assert "<pre><code>" in h


def test_tables_and_escaping(built):
    build_docs, pages = built
    perf = [p for p in pages if p.endswith("performance.html")][0]
    h = open(perf, encoding="utf-8").read()
    assert "<table>" in h and "<th>" in h
    # no markdown table separators may leak into rendered paragraphs
    text = re.sub(r"<[^>]+>", "", h)
    assert "|---" not in text
    # raw angle brackets in prose/code must be escaped, not swallowed
    # or emitted as live markup
    frag = build_docs.md_to_html(
        "threshold `a < b` and loose x < y prose\n\n```\nif a < b:\n```\n")
    assert "a &lt; b" in frag and "x &lt; y" in frag, frag
    assert "if a &lt; b:" in frag, frag


def test_internal_md_links_rewritten(built):
    _, pages = built
    for p in pages:
        h = open(p, encoding="utf-8").read()
        # no intra-site link may still point at a .md file
        for m in re.finditer(r'href="([^"]+)"', h):
            url = m.group(1)
            if url.startswith(("http", "#", "mailto:")):
                continue
            assert not url.endswith(".md"), (p, url)


def test_cli_entrypoint(tmp_path, capsys, monkeypatch):
    monkeypatch.setattr(sys, "argv",
                        ["build_docs.py", str(tmp_path / "out")])
    runpy.run_path(os.path.join(REPO, "scripts", "build_docs.py"),
                   run_name="__main__")
    assert "built" in capsys.readouterr().out
    assert (tmp_path / "out" / "index.html").exists()
