"""Shippable warm-cache artifact (compile_cache pack/unpack/verify +
scripts/build_warm_cache.py) and compile-cache hygiene (size cap + LRU
eviction).  The two-process smoke is the fresh-pod acceptance: a pod
given ONLY the packed artifact serves a catalog-shaped survey with
``jit_cache_miss == 0`` and no compile span over 1 s."""

import json
import os
import subprocess
import sys
import tarfile
import time

import pytest

from synth import synth_arc_epoch

from scintools_tpu import compile_cache, obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "build_warm_cache.py")


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "scc")
    monkeypatch.setenv("SCINT_COMPILE_CACHE", d)
    obs.disable(flush=False)
    obs.reset()
    yield d
    obs.disable(flush=False)
    obs.reset()


def _seed_cache(d, names=("aa", "bb"), size=1024, age_step=10.0):
    """Plant fake cache entries with strictly increasing mtimes."""
    os.makedirs(d, exist_ok=True)
    t0 = time.time() - 1000.0
    for i, name in enumerate(names):
        p = os.path.join(d, name + ".bin")
        with open(p, "wb") as fh:
            fh.write(b"x" * size)
        os.utime(p, (t0 + i * age_step, t0 + i * age_step))
    return [os.path.join(d, n + ".bin") for n in names]


# ---------------------------------------------------------------------------
# hygiene: size cap + LRU eviction
# ---------------------------------------------------------------------------


def test_cache_cap_env_parsing(monkeypatch):
    assert compile_cache.cache_cap_bytes() \
        == compile_cache.DEFAULT_CAP_MB << 20
    monkeypatch.setenv(compile_cache.CAP_ENV, "7")
    assert compile_cache.cache_cap_bytes() == 7 << 20
    for off in ("0", "off", "none", ""):
        monkeypatch.setenv(compile_cache.CAP_ENV, off)
        assert compile_cache.cache_cap_bytes() is None
    monkeypatch.setenv(compile_cache.CAP_ENV, "lots")
    with pytest.raises(ValueError):
        compile_cache.cache_cap_bytes()


def test_enforce_cache_cap_evicts_lru(cache_dir):
    paths = _seed_cache(cache_dir, names=("old", "mid", "new"),
                        size=1000)
    # manifest is provenance, never eviction bait
    with open(os.path.join(cache_dir, compile_cache.MANIFEST_NAME),
              "w") as fh:
        json.dump({"digest": "d"}, fh)
    with obs.tracing():
        n = compile_cache.enforce_cache_cap(cache_dir, cap_bytes=2000)
        c = obs.counters()
    assert n == 1
    assert not os.path.exists(paths[0])          # oldest evicted
    assert os.path.exists(paths[1]) and os.path.exists(paths[2])
    assert os.path.exists(os.path.join(cache_dir,
                                       compile_cache.MANIFEST_NAME))
    assert c.get("compile_cache_evictions") == 1
    # under the cap: no-op
    assert compile_cache.enforce_cache_cap(cache_dir,
                                           cap_bytes=10000) == 0


def test_enforce_cache_cap_disabled_and_missing(cache_dir):
    _seed_cache(cache_dir, size=1000)
    assert compile_cache.enforce_cache_cap(cache_dir,
                                           cap_bytes=None) == 0
    assert compile_cache.enforce_cache_cap("/nonexistent/nowhere",
                                           cap_bytes=1) == 0


# ---------------------------------------------------------------------------
# artifact pack / verify / unpack
# ---------------------------------------------------------------------------


def test_pack_verify_unpack_roundtrip(cache_dir, tmp_path):
    _seed_cache(cache_dir, names=("entry1", "entry2"))
    os.makedirs(os.path.join(cache_dir, "aot"), exist_ok=True)
    with open(os.path.join(cache_dir, "aot", "k.jaxexport"), "wb") as fh:
        fh.write(b"stablehlo-bytes")
    art = str(tmp_path / "warm.tgz")
    with obs.tracing():
        man = compile_cache.pack_warm_cache(art, cache=cache_dir,
                                            catalog_digest="cat123")
        c = obs.counters()
    assert os.path.exists(art)
    assert man["digest"] == "cat123" and man["files"] == 3
    assert compile_cache.verify_artifact(man) == []
    assert c.get("cache_artifact_packed") == 1
    # the manifest landed in the cache dir too
    assert compile_cache.artifact_manifest(cache_dir)["digest"] == "cat123"
    # fresh destination: verify + extract + manifest present
    dest = str(tmp_path / "fresh")
    with obs.tracing():
        man2 = compile_cache.unpack_warm_cache(art, cache=dest)
        c = obs.counters()
    assert man2["digest"] == "cat123"
    assert os.path.exists(os.path.join(dest, "entry1.bin"))
    assert os.path.exists(os.path.join(dest, "aot", "k.jaxexport"))
    assert compile_cache.artifact_manifest(dest)["digest"] == "cat123"
    assert c.get("cache_artifact_unpacked") == 1


def test_unpack_rejects_version_skew(cache_dir, tmp_path, monkeypatch):
    import jax

    _seed_cache(cache_dir, names=("entry",))
    art = str(tmp_path / "warm.tgz")
    compile_cache.pack_warm_cache(art, cache=cache_dir)
    monkeypatch.setattr(jax, "__version__", "999.0.0")
    dest = str(tmp_path / "fresh")
    with obs.tracing():
        with pytest.raises(ValueError, match="does not match this "
                                             "runtime"):
            compile_cache.unpack_warm_cache(art, cache=dest)
        c = obs.counters()
    assert c.get("cache_artifact_rejected") == 1
    assert not os.path.exists(os.path.join(dest, "entry.bin"))
    # force: stale keys miss and recompile — slow, never wrong
    man = compile_cache.unpack_warm_cache(art, cache=dest, force=True)
    assert os.path.exists(os.path.join(dest, "entry.bin"))
    assert compile_cache.verify_artifact(man) != []


def test_unpack_rejects_non_artifact_and_unsafe_members(cache_dir,
                                                        tmp_path):
    # a tarball without a manifest is not a warm-cache artifact
    bogus = str(tmp_path / "bogus.tgz")
    plain = str(tmp_path / "plain.txt")
    with open(plain, "w") as fh:
        fh.write("hi")
    with tarfile.open(bogus, "w:gz") as tar:
        tar.add(plain, arcname="plain.txt")
    with pytest.raises(ValueError, match="not a warm-cache artifact"):
        compile_cache.unpack_warm_cache(bogus, cache=str(tmp_path / "d"))
    # a manifest-bearing tarball with a traversal member is rejected
    evil = str(tmp_path / "evil.tgz")
    manp = str(tmp_path / compile_cache.MANIFEST_NAME)
    with open(manp, "w") as fh:
        json.dump(compile_cache._env_fingerprint()
                  | {"format": compile_cache._FORMAT}, fh)
    with tarfile.open(evil, "w:gz") as tar:
        tar.add(manp, arcname=compile_cache.MANIFEST_NAME)
        tar.add(plain, arcname="../escape.txt")
    with pytest.raises(ValueError, match="unsafe member"):
        compile_cache.unpack_warm_cache(evil, cache=str(tmp_path / "d"))


def test_build_script_verify_subcommand(cache_dir, tmp_path):
    _seed_cache(cache_dir, names=("entry",))
    art = str(tmp_path / "warm.tgz")
    compile_cache.pack_warm_cache(art, cache=cache_dir,
                                  catalog_digest="cat9")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, SCRIPT, "verify", art],
                         text=True, capture_output=True, timeout=300,
                         env=env, cwd=REPO)
    assert out.returncode == 0, (out.stdout, out.stderr)
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["usable"] is True
    assert rec["manifest"]["digest"] == "cat9"
    assert rec["mismatches"] == []


def test_warm_cache_artifact_two_process(tmp_path):
    """THE fresh-pod acceptance (tier-1-safe, CPU): process A builds a
    tiny warm-cache artifact over the closed catalog
    (scripts/build_warm_cache.py build -> warmup --catalog subprocess
    -> pack); process B gets ONLY the artifact, unpacks it into a
    brand-new SCINT_COMPILE_CACHE via the script, and a third cold
    process serves a catalog-shaped survey with jit_cache_miss == 0,
    compile_cache_hit >= 1, every compile span under 1 s, and the
    artifact digest visible in its trace gauges."""
    from scintools_tpu.io.psrflux import write_psrflux

    files = []
    for s in range(2):
        fn = str(tmp_path / f"tmpl_{s}.dynspec")
        write_psrflux(synth_arc_epoch(seed=s), fn)
        files.append(fn)
    cache_a = str(tmp_path / "cacheA")
    art = str(tmp_path / "warm_cache.tgz")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SCINT_COMPILE_CACHE=cache_a, SCINT_BUCKET_TOP="2")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, SCRIPT, "build", "--out", art] + files
        + ["--", "--no-arc", "--batch", "2"],
        text=True, capture_output=True, timeout=600, env=env, cwd=REPO)
    assert out.returncode == 0, (out.stdout, out.stderr)
    rec = json.loads([ln for ln in out.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["warmup"]["signatures"] >= 2, rec
    assert rec["manifest"].get("digest"), rec

    # process B: a FRESH pod — empty cache dir, only the artifact
    cache_b = str(tmp_path / "cacheB")
    env_b = dict(env, SCINT_COMPILE_CACHE=cache_b)
    out = subprocess.run(
        [sys.executable, SCRIPT, "unpack", art],
        text=True, capture_output=True, timeout=300, env=env_b, cwd=REPO)
    assert out.returncode == 0, (out.stdout, out.stderr)
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["manifest"]["files"] >= 1

    # cold consumer: catalog-shaped survey (1 epoch -> rung 1) must
    # pay ZERO trace/compile — counter- AND span-asserted
    consumer = (
        "from scintools_tpu.backend import force_host_cpu_devices\n"
        "force_host_cpu_devices(1)\n"
        "import json\n"
        "import numpy as np\n"
        "from scintools_tpu import obs\n"
        "from scintools_tpu.io.psrflux import read_psrflux\n"
        "from scintools_tpu.ops.clean import refill, trim_edges\n"
        "from scintools_tpu.parallel import (PipelineConfig, make_mesh,\n"
        "                                    run_pipeline)\n"
        "epochs = [refill(trim_edges(read_psrflux(%r)))]\n"
        "cfg = PipelineConfig(lamsteps=False, fit_arc=False)\n"
        "mesh = make_mesh()\n"
        "with obs.tracing() as reg:\n"
        "    buckets = run_pipeline(epochs, cfg, mesh=mesh,\n"
        "                           bucket=True)\n"
        "    c = obs.counters()\n"
        "    g = reg.gauges()\n"
        "    spans = [(e['name'], e['dur_ms']) for e in reg.events()\n"
        "             if e.get('kind') == 'span'\n"
        "             and '.compile' in e['name']]\n"
        "(_i, res), = buckets\n"
        "from scintools_tpu import buckets as bmod\n"
        "from scintools_tpu import compile_cache\n"
        "from scintools_tpu.parallel.driver import (_resolve_chan_sharded,\n"
        "                                           stage_dtype)\n"
        "f, t = np.asarray(epochs[0].freqs), np.asarray(epochs[0].times)\n"
        "rung = bmod.rung_for(1, mesh.shape['data'])\n"
        "key = compile_cache.step_key(\n"
        "    f, t, cfg, mesh, _resolve_chan_sharded(mesh, None),\n"
        "    (rung, len(f), len(t)), stage_dtype(cfg.precision))\n"
        "fn = compile_cache.load_step(key, count=False)\n"
        "print(json.dumps({'counters': c,\n"
        "                  'artifact': g.get('compile_cache_artifact'),\n"
        "                  'compile_spans': spans,\n"
        "                  'exec_layer': bool(fn is not None\n"
        "                                     and not hasattr(fn,\n"
        "                                                     'lower')),\n"
        "                  'tau_finite': bool(np.all(np.isfinite(\n"
        "                      np.asarray(res.scint.tau))))}))\n"
        % files[0])
    out = subprocess.run([sys.executable, "-c", consumer], text=True,
                         capture_output=True, timeout=600, env=env_b,
                         cwd=REPO)
    assert out.returncode == 0, (out.stdout, out.stderr)
    rec = json.loads([ln for ln in out.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["counters"].get("jit_cache_miss", 0) == 0, rec
    assert rec["counters"].get("compile_cache_hit", 0) >= 1, rec
    assert rec["tau_finite"], rec
    # the fast layer really served: a ready Compiled (no .lower), not
    # the StableHLO-jit fallback that would pay XLA compile
    assert rec["exec_layer"], rec
    # artifact provenance is visible to trace report
    assert rec["artifact"], rec
    # no compile span over 1 s: the whole remaining "compile" is
    # deserialization served by the unpacked persistent cache
    assert rec["compile_spans"], rec
    worst = max(d for _n, d in rec["compile_spans"])
    assert worst < 1000.0, rec["compile_spans"]


# ---------------------------------------------------------------------------
# bench: time_to_first_result probe
# ---------------------------------------------------------------------------


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_time_to_first_result_probe(monkeypatch):
    """The cold-process submit->first-CSV-row probe returns a real
    latency plus the counters that say whether it measured a cold or a
    warm start (the flight-record trajectory metric of ISSUE 7)."""
    bench = _load_bench()
    monkeypatch.setenv("SCINT_BENCH_TTFR", "0")
    assert bench.time_to_first_result(64, 64) == {"skipped": True}
    monkeypatch.setenv("SCINT_BENCH_TTFR", "1")
    rec = bench.time_to_first_result(64, 64, timeout_s=540,
                                     arc_numsteps=96, lm_steps=3,
                                     force_cpu=True)
    assert "error" not in rec, rec
    assert rec["s"] > 0
    assert rec["shape"] == [1, 64, 64]
    assert rec["backend"] == "cpu-forced"
    for k in ("jit_cache_miss", "compile_cache_hit",
              "compile_cache_miss"):
        assert k in rec


# ---------------------------------------------------------------------------
# serialized-executable layer (the load_step fast path)
# ---------------------------------------------------------------------------


def test_export_executable_roundtrip_preferred(cache_dir):
    """export_executable persists the COMPILED step; load_step prefers
    it over the StableHLO export (no retrace, no compile) and the
    result is bit-identical to the live step's."""
    import numpy as np

    from scintools_tpu.parallel import PipelineConfig
    from scintools_tpu.parallel.driver import make_pipeline

    cfg = PipelineConfig(fit_arc=False, lm_steps=3)
    eps = [synth_arc_epoch(seed=s) for s in range(2)]
    f, t = np.asarray(eps[0].freqs), np.asarray(eps[0].times)
    dyn = np.stack([np.asarray(e.dyn, dtype=np.float64) for e in eps])
    step = make_pipeline(f, t, cfg)
    key = compile_cache.step_key(f, t, cfg, None, False, dyn.shape,
                                 dyn.dtype)
    epath = compile_cache.export_executable(step, dyn.shape, dyn.dtype,
                                            key)
    assert epath is not None and epath.endswith(".jaxexec")
    assert os.path.exists(epath)
    # also write the StableHLO layer; the exec layer must still win
    assert compile_cache.export_step(step, dyn.shape, dyn.dtype,
                                     key) is not None
    with obs.tracing():
        fn = compile_cache.load_step(key)
        c = obs.counters()
    assert fn is not None and c.get("compile_cache_hit") == 1
    # a ready Compiled: no .lower, directly callable
    assert not hasattr(fn, "lower")
    import jax

    live = step(dyn)
    out = fn(jax.device_put(dyn))
    for a, b in zip(jax.tree_util.tree_leaves(live),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # corrupt executable artifact degrades to the StableHLO layer
    compile_cache._LOADED.clear()
    with open(epath, "wb") as fh:
        fh.write(b"not-a-pickle")
    with obs.tracing():
        fn2 = compile_cache.load_step(key)
        c = obs.counters()
    assert fn2 is not None and c.get("compile_cache_hit") == 1
    assert hasattr(fn2, "lower")          # the jit'd deserialized module
