"""Screen-parameter inference from a dynamic spectrum (ABC over a
simulated parameter grid).

A beyond-reference workflow built on ``simulate_sweep`` (traced physics
parameters: the whole grid compiles ONCE): given an observed dynamic
spectrum, recover the scattering strength ``mb2`` and anisotropy ``ar``
of the underlying phase screen by approximate Bayesian computation —

    1. simulate a (mb2, ar) grid of screens, several noise realisations
       per point, all in one compiled program,
    2. reduce every realisation to summary statistics that the
       measurement chain itself uses: the modulation index and the
       e-folding widths of the two central ACF cuts
       (``ops.acf.acf_cuts_direct`` — the batched scint-fit fast path),
    3. score each grid point with a Gaussian synthetic likelihood (the
       point's own repeat mean/std per summary — Price et al. 2018
       "Bayesian synthetic likelihood"), and report the posterior
       mean / MAP over the grid.

Run:  python examples/screen_inference.py [outdir]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scintools_tpu.backend import honor_platform_env  # noqa: E402

honor_platform_env()

import numpy as np  # noqa: E402


def summaries(spi_batch) -> np.ndarray:
    """[B, nx, nf] intensities -> [B, 3] (m2, t_width, f_width).

    m2 is the scintillation index var/mean^2; the widths are the
    e-folding lags (in pixels) of the central time/frequency ACF cuts,
    computed with the same direct-cuts kernel the batched scint fit
    uses.  Widths are interpolated between lags for sub-pixel
    resolution; saturated cuts fall back to the last lag.
    """
    from scintools_tpu.ops.acf import acf_cuts_direct

    spi = np.asarray(spi_batch, dtype=np.float64)
    # the sim's [nx(time), nf] layout -> the kernels' [freq, time]
    dyn = np.swapaxes(spi, -1, -2)
    m2 = spi.var(axis=(1, 2)) / spi.mean(axis=(1, 2)) ** 2
    cut_t, cut_f = (np.asarray(c) for c in acf_cuts_direct(dyn))

    def efold(cuts):
        c0 = cuts[:, :1]
        norm = np.where(c0 != 0, cuts / np.where(c0 == 0, 1.0, c0), 0.0)
        target = 1.0 / np.e
        out = np.empty(len(cuts))
        for b, row in enumerate(norm):
            below = np.nonzero(row < target)[0]
            if len(below) == 0:
                out[b] = len(row) - 1.0
                continue
            i = int(below[0])
            if i == 0:
                out[b] = 0.0
                continue
            y0, y1 = row[i - 1], row[i]
            out[b] = i - 1 + (y0 - target) / max(y0 - y1, 1e-30)
        return out

    return np.stack([m2, efold(cut_t), efold(cut_f)], axis=-1)


def main(outdir: str = "/tmp/screen_inference",
         nx: int = 128, nf: int = 32, n_mb2: int = 7, n_ar: int = 4,
         repeats: int = 6, seed: int = 11,
         truth_mb2: float = 4.0, truth_ar: float = 2.0) -> dict:
    import dataclasses

    import jax

    from scintools_tpu.sim import SimParams, simulate_intensity, \
        simulate_sweep
    from scintools_tpu.utils import log_event, get_logger

    os.makedirs(outdir, exist_ok=True)
    log = get_logger()
    base = SimParams(nx=nx, ny=nx, nf=nf, dlam=0.25)

    # --- the "observed" epoch (hidden truth; key disjoint from the grid)
    obs = np.asarray(simulate_intensity(
        jax.random.PRNGKey(seed + 999),
        dataclasses.replace(base, mb2=truth_mb2, ar=truth_ar)))
    s_obs = summaries(obs[None])[0]

    # --- simulate the grid: K points x repeats, ONE compiled program
    mb2_grid = np.geomspace(0.5, 32.0, n_mb2)
    ar_grid = np.linspace(1.0, 4.0, n_ar)
    MB2, AR = np.meshgrid(mb2_grid, ar_grid, indexing="ij")
    points = np.stack([MB2.ravel(), AR.ravel()], axis=-1)   # [K, 2]
    K = len(points)
    keys = jax.random.split(jax.random.PRNGKey(seed), K * repeats)
    sweep = {"mb2": np.repeat(points[:, 0], repeats),
             "ar": np.repeat(points[:, 1], repeats)}
    spi = simulate_sweep(keys, base, sweep, point_chunk=4)
    log_event(log, "sweep_done", points=K, repeats=repeats)

    # --- summaries + Gaussian synthetic likelihood per grid point:
    # each point's repeats estimate its own summary mean/std, so a point
    # whose summaries are merely globally-typical but many of ITS OWN
    # sigmas away from the observation is properly penalised
    s_sim = summaries(spi).reshape(K, repeats, 3)
    mu = s_sim.mean(axis=1)                                   # [K, 3]
    sd = np.maximum(s_sim.std(axis=1, ddof=1), 1e-6)
    loglik = (-0.5 * (((s_obs - mu) / sd) ** 2)
              - np.log(sd)).sum(-1)                           # [K]
    w = np.exp(loglik - loglik.max())
    w = w / w.sum()

    post_mean = w @ points
    post_std = np.sqrt(w @ (points - post_mean) ** 2)
    imap = int(np.argmax(w))
    result = {
        "truth": {"mb2": truth_mb2, "ar": truth_ar},
        "map": {"mb2": float(points[imap, 0]),
                "ar": float(points[imap, 1])},
        "posterior_mean": {"mb2": float(post_mean[0]),
                           "ar": float(post_mean[1])},
        "posterior_std": {"mb2": float(post_std[0]),
                          "ar": float(post_std[1])},
    }
    log_event(log, "inference_done", **result["map"])

    # --- posterior heat map
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6, 4))
    pm = ax.pcolormesh(ar_grid, mb2_grid, w.reshape(n_mb2, n_ar),
                       shading="nearest")
    ax.set_title("synthetic-likelihood posterior")
    ax.plot(truth_ar, truth_mb2, "w*", ms=14, label="truth")
    ax.plot(result["map"]["ar"], result["map"]["mb2"], "r+", ms=12,
            mew=2, label="MAP")
    ax.set_yscale("log")
    ax.set_xlabel("axial ratio ar")
    ax.set_ylabel("scattering strength mb2")
    fig.colorbar(pm, label="ABC weight")
    ax.legend()
    fig.tight_layout()
    fig.savefig(os.path.join(outdir, "posterior.png"), dpi=120)
    plt.close(fig)
    return result


if __name__ == "__main__":
    out = main(*sys.argv[1:2])
    print(out)
