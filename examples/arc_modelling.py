"""Arc-modelling walkthrough on simulated data.

This is the reference's de-facto integration test — the
``examples/arc_modelling.ipynb`` J0437-4715 workflow (26 cells; its data
directory is not shipped, so the notebook cannot actually run) — rebuilt
as a runnable script on committed *simulated* data:

    1. simulate a scintillating epoch from an anisotropic Kolmogorov
       phase screen (seeded: deterministic),
    2. load it as a Dynspec and run the default processing chain,
    3. flatten the bandpass, resample to uniform wavelength steps,
    4. measure the scintillation arc curvature (norm_sspec method),
    5. sum two epochs with `+` and re-measure,
    6. curvature-normalise the secondary spectrum,
    7. fit scintillation timescale/bandwidth, and predict the annual
       curvature curve from the analytic ephemeris + a pulsar orbit.

Run:  python examples/arc_modelling.py [outdir]
"""

import os
import sys

# run-from-checkout bootstrap: put the repo root on sys.path so the script
# works without pip-installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scintools_tpu.backend import honor_platform_env  # noqa: E402

honor_platform_env()  # make JAX_PLATFORMS=cpu reliable under axon

import numpy as np

import matplotlib

matplotlib.use("Agg")

from scintools_tpu import Dynspec  # noqa: E402
from scintools_tpu.astro import get_earth_velocity, get_true_anomaly  # noqa: E402
from scintools_tpu.io import from_simulation  # noqa: E402
from scintools_tpu.models.velocity import arc_curvature_model  # noqa: E402
from scintools_tpu.plotting import plot_norm_sspec  # noqa: E402
from scintools_tpu.sim import Simulation  # noqa: E402


def main(outdir: str = "/tmp/arc_modelling") -> dict:
    os.makedirs(outdir, exist_ok=True)
    results = {}

    # -- 1. simulate one observing epoch --------------------------------
    sim = Simulation(mb2=2, ns=256, nf=256, ar=2, psi=30, dlam=0.25,
                     seed=64)
    data = from_simulation(sim, freq=1400.0, dt=8.0)

    # -- 2-3. process: trim -> refill -> acf -> lambda-resample -> sspec -
    ds = Dynspec(data=data, process=True, lamsteps=True)
    ds.correct_band()
    ds.calc_sspec(lamsteps=True)
    ds.plot_dyn(filename=f"{outdir}/dynspec.png")

    # -- 4. arc curvature ------------------------------------------------
    ds.fit_arc(lamsteps=True, numsteps=4000)
    results["betaeta_single"] = ds.betaeta
    print(f"single epoch:  betaeta = {ds.betaeta:.3f} "
          f"+/- {ds.betaetaerr:.3f}")
    ds.plot_sspec(plotarc=True, filename=f"{outdir}/sspec_arc.png")

    # cross-check with the theta-theta eigen-concentration estimator
    # (beyond-reference).  On sharp, strongly-anisotropic arcs the two
    # methods agree tightly; this epoch's mb2=2, ar=2 screen makes a
    # DIFFUSE arc, where the power profile tracks the power-weighted
    # mean curvature while the concentration sweep locks onto the
    # sharpest substructure — expect same-order, not identical, values
    saved = (ds.betaeta, ds.betaetaerr)
    tt = ds.fit_arc(method="thetatheta", lamsteps=True,
                    etamin=ds.betaeta / 5, etamax=ds.betaeta * 5,
                    numsteps=128)
    # restore the power-profile measurement: fit_arc sets ds.betaeta,
    # and the norm_sspec section below normalises by it
    ds.betaeta, ds.betaetaerr = saved
    results["betaeta_thetatheta"] = float(tt.eta)
    print(f"theta-theta:   betaeta = {float(tt.eta):.3f} "
          f"+/- {float(tt.etaerr):.3f}  (diffuse-arc epoch: same order, "
          "not identical — see comment)")

    # ...and pin BOTH estimators to a closed-form ground truth: a
    # synthetic thin-arc epoch plants a KNOWN curvature
    # (sim.synth.thin_arc_betaeta), so unlike the diffuse screen above
    # this is a real accuracy gate, not an order-of-magnitude check.
    # Measured across seeds: theta-theta lands within ~5% of truth
    # (the concentration sweep locks onto the planted arc), while the
    # power profile carries a 10-45% power-weighted envelope bias on
    # this epoch type — both asserted in tests/test_example.py.
    from scintools_tpu.sim import thin_arc_epoch
    from scintools_tpu.sim.synth import thin_arc_betaeta

    sharp = Dynspec(data=thin_arc_epoch(nf=96, nt=96, seed=23),
                    process=False, lamsteps=True)
    truth = thin_arc_betaeta(sharp.freqs)
    sharp.fit_arc(lamsteps=True, numsteps=2000)
    results["betaeta_planted_ns"] = float(sharp.betaeta)
    tt_sharp = sharp.fit_arc(method="thetatheta", lamsteps=True,
                             etamin=truth / 3, etamax=truth * 3,
                             numsteps=128)
    results["betaeta_planted_truth"] = float(truth)
    results["betaeta_planted_tt"] = float(tt_sharp.eta)
    print(f"planted arc:   truth = {truth:.3f}  theta-theta = "
          f"{float(tt_sharp.eta):.3f}  norm_sspec = "
          f"{float(results['betaeta_planted_ns']):.3f}")

    # -- 5. epoch summing ------------------------------------------------
    sim2 = Simulation(mb2=2, ns=256, nf=256, ar=2, psi=30, dlam=0.25,
                      seed=65)
    data2 = from_simulation(
        sim2, freq=1400.0, dt=8.0,
        mjd=data.mjd + (data.tobs + 30.0) / 86400.0)
    summed = Dynspec(data=data, process=False) + \
        Dynspec(data=data2, process=False)
    summed.refill()
    summed.lamsteps = True
    summed.fit_arc(lamsteps=True, numsteps=4000)
    results["betaeta_summed"] = summed.betaeta
    print(f"summed epochs: betaeta = {summed.betaeta:.3f} "
          f"+/- {summed.betaetaerr:.3f}")

    # -- 6. curvature-normalised secondary spectrum ----------------------
    ns = ds.norm_sspec(maxnormfac=2, numsteps=1024)
    plot_norm_sspec(ns, filename=f"{outdir}/norm_sspec.png")

    # -- 7. scintillation parameters + annual curvature model ------------
    sp = ds.get_scint_params()
    results["tau"] = ds.tau
    results["dnu"] = ds.dnu
    print(f"tau_d = {ds.tau:.1f} s   dnu_d = {ds.dnu:.3f} MHz   "
          f"(redchi {float(np.asarray(sp.redchi)):.3g})")

    # annual eta(t) prediction for a J0437-like system from the built-in
    # analytic ephemeris (reference needs astropy + tempo2 par files)
    pars = {"T0": 50000.0, "PB": 5.741, "ECC": 0.0879, "A1": 3.3667,
            "OM": 1.0, "KIN": 137.6, "KOM": 207.0, "PMRA": 121.4,
            "PMDEC": -71.5, "d": 0.157, "s": 0.7}
    mjds = 53000.0 + np.linspace(0, 365.25, 120)
    nu = get_true_anomaly(mjds, pars)
    v_ra, v_dec = get_earth_velocity(mjds, 1.2098, -0.8243)
    eta_annual = arc_curvature_model(pars, nu, v_ra, v_dec)
    results["eta_annual_minmax"] = (float(eta_annual.min()),
                                    float(eta_annual.max()))
    print(f"annual curvature range: {eta_annual.min():.3f} - "
          f"{eta_annual.max():.3f} (1/(m mHz^2))")

    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 4))
    ax.plot(mjds - 53000.0, eta_annual, "k-")
    ax.set_xlabel("Days")
    ax.set_ylabel(r"$\eta$ (1/(m mHz$^2$))")
    fig.savefig(f"{outdir}/eta_annual.png", dpi=150, bbox_inches="tight")
    plt.close("all")

    # -- 8. wavefield retrieval (holography; no reference analogue) ------
    # a strongly anisotropic screen gives the thin arc the rank-1
    # theta-theta model needs; curvature from the eigenvalue sweep, then
    # the chunked retrieval reconstructs the complex E-field
    from scintools_tpu.plotting import plot_sspec, plot_wavefield

    sim_h = Simulation(mb2=20, ns=192, nf=192, ar=10, psi=90, dlam=0.25,
                       seed=77)
    ds_h = Dynspec(data=from_simulation(sim_h, freq=1400.0, dt=8.0),
                   process=True)
    ds_h.fit_arc(method="thetatheta", lamsteps=False, etamin=1e-3,
                 etamax=10.0, numsteps=96)
    eta_h = ds_h.eta
    wf = ds_h.retrieve_wavefield(chunk_nf=32, chunk_nt=32)
    dyn_h = np.asarray(ds_h.data.dyn, float)
    results["wavefield_corr"] = float(np.corrcoef(
        dyn_h.ravel(), wf.model_dynspec.ravel())[0, 1])
    print(f"wavefield: eta = {eta_h:.3f}, |E|^2 reconstruction corr = "
          f"{results['wavefield_corr']:.2f}")
    plot_wavefield(wf, filename=f"{outdir}/wavefield.png")
    plot_sspec(wf.secspec(), eta=eta_h,
               filename=f"{outdir}/wavefield_sspec.png")
    plt.close("all")

    # -- 9. posterior scintillation parameters (mcmc=True) ---------------
    # the reference's lmfit-emcee + corner option, rebuilt as a jitted
    # ensemble sampler: every get_scint_params method accepts mcmc=True;
    # the post-burn chain lands on ds.mcmc_chain for corner export
    from scintools_tpu.plotting import plot_posterior

    sp_post = ds.get_scint_params(method="acf1d", mcmc=True)
    results["tau_posterior"] = float(sp_post.tau)
    results["tau_posterior_err"] = float(sp_post.tauerr)
    print(f"posterior: tau = {sp_post.tau:.1f} +- {sp_post.tauerr:.1f} s "
          f"(LM point fit above; errors now from the sampled posterior)")
    plot_posterior(ds.mcmc_chain, labels=["tau", "dnu", "amp", "wn"],
                   filename=f"{outdir}/posterior_corner.png")
    plt.close("all")

    # -- 10. real-format dirty data: the survey cleaning recipe ----------
    # the committed psrflux fixture carries real-survey defects (dead band
    # edges, a dropout gap, narrowband + impulsive RFI, a drifting-gain
    # channel, gain drift, bandpass ripple — scripts/make_fixture.py);
    # the chain below recovers the arc to ~2% of the clean-sim truth.
    # NOTE the channel triage (zap(method="channels")): the drifting-gain
    # channel is invisible to pixel thresholds but buries the arc —
    # docs/performance.md and tests/test_dirty_fixture.py tell the story.
    fixture = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "data",
        "J0000+0000_degraded.dynspec")
    if os.path.isfile(fixture):
        dirty = Dynspec(filename=fixture, process=False)
        dirty.trim_edges().zap(method="channels", sigma=4).zap(sigma=5) \
             .refill().correct_band(frequency=True, time=True)
        dirty.fit_arc(lamsteps=True, numsteps=2000)
        dirty.get_scint_params()
        results["dirty_betaeta"] = dirty.betaeta
        results["dirty_tau"] = dirty.tau
        print(f"dirty fixture: betaeta = {dirty.betaeta:.1f} "
              f"(clean-sim truth 266.0), tau = {dirty.tau:.0f} s")
        dirty.plot_dyn(lamsteps=False,
                       filename=f"{outdir}/dirty_cleaned_dyn.png")
        plt.close("all")

    print(f"plots in {outdir}/")
    return results


if __name__ == "__main__":
    main(*sys.argv[1:2])
