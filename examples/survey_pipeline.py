"""Survey-scale batched processing on an accelerator mesh.

What the reference cannot do at all (SURVEY.md §2.7: its batch driver is
a serial per-file Python loop, dynspec.py:1615-1657): process a whole
survey of observing epochs as jit-compiled SPMD steps, reduce survey
statistics with device collectives, and checkpoint results so a killed
run resumes where it stopped.

    1. simulate a mixed-shape "survey" of epochs (three seeded screens
       expanded with noise realisations),
    2. run the batched pipeline: shape-bucketing, padding, one compiled
       step per bucket (ACF-cuts -> tau/dnu LM fits; lambda-resample ->
       secondary spectrum -> arc fits),
    3. survey statistics (masked mean/std of tau, dnu, eta) via psum
       collectives over the device mesh,
    4. persist per-epoch rows to a content-hash store + reference-
       compatible CSV; rerunning skips finished epochs.

Run:  python examples/survey_pipeline.py [outdir]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scintools_tpu.backend import honor_platform_env  # noqa: E402

honor_platform_env()  # make JAX_PLATFORMS=cpu reliable under axon

import numpy as np  # noqa: E402


def make_survey(n_epochs: int = 64, seed: int = 7):
    """Simulated epochs in two shape buckets (as real surveys have)."""
    from scintools_tpu.io import from_simulation
    from scintools_tpu.sim import Simulation

    rng = np.random.default_rng(seed)
    epochs = []
    for shape_seed, (ns, nf) in ((seed, (128, 128)),
                                 (seed + 1, (128, 64))):
        base = from_simulation(
            Simulation(mb2=2, ns=ns, nf=nf, dlam=0.25, seed=shape_seed),
            freq=1400.0, dt=8.0)
        for k in range(n_epochs // 2):
            noisy = np.asarray(base.dyn) * (
                1.0 + 0.02 * rng.standard_normal())
            epochs.append(base.replace(
                dyn=noisy, name=f"epoch_{ns}x{nf}_{k:03d}",
                mjd=base.mjd + k))
    return epochs


def main(outdir: str = "/tmp/survey_pipeline") -> dict:
    import jax.numpy as jnp

    from scintools_tpu.io.results import results_row
    from scintools_tpu.parallel import (PipelineConfig, make_mesh,
                                        run_pipeline, survey_stats)
    from scintools_tpu.utils import (ResultsStore, StageTimers,
                                     content_key, get_logger, log_event)

    os.makedirs(outdir, exist_ok=True)
    log = get_logger()
    timers = StageTimers()
    store = ResultsStore(os.path.join(outdir, "store"))

    epochs = make_survey()
    todo = store.pending(epochs, lambda d: content_key(np.asarray(d.dyn)))
    log_event(log, "survey_start", total=len(epochs), todo=len(todo))

    mesh = make_mesh()  # all devices on the data axis
    # arc_stack: besides the per-epoch fits, nanmean-stack every epoch's
    # normalised profile and measure ONE campaign curvature per bucket
    # (weak-arc S/N grows as sqrt(epochs) — beyond the reference's
    # one-file-at-a-time fitter)
    cfg = PipelineConfig(lamsteps=True, arc_numsteps=1000, lm_steps=30,
                         arc_stack=True)

    stats = {}
    if todo:
        with timers.stage("batched_pipeline"):
            buckets = run_pipeline(todo, cfg, mesh=mesh)

        # gather per-epoch rows + survey reductions per shape bucket
        all_tau, all_eta = [], []
        for bucket_no, (indices, res) in enumerate(buckets):
            camp_eta = float(np.asarray(res.arc_stacked.eta))
            log_event(log, "campaign_arc", bucket=bucket_no,
                      n_epochs=len(indices), betaeta=camp_eta,
                      betaetaerr=float(np.asarray(res.arc_stacked.etaerr)))
            stats.setdefault("campaign_eta", []).append(camp_eta)
            tau = np.asarray(res.scint.tau)
            eta = np.asarray(res.arc.eta)
            all_tau.append(tau)
            all_eta.append(eta)
            for lane, idx in enumerate(indices):
                d = todo[idx]
                row = results_row(d)
                row.update(tau=float(tau[lane]),
                           tauerr=float(np.asarray(
                               res.scint.tauerr)[lane]),
                           betaeta=float(eta[lane]),
                           betaetaerr=float(np.asarray(
                               res.arc.etaerr)[lane]))
                store.put(content_key(np.asarray(d.dyn)), row)

        with timers.stage("survey_stats"):
            for name, vals in (("tau", np.concatenate(all_tau)),
                               ("eta", np.concatenate(all_eta))):
                pad = (-len(vals)) % mesh.shape["data"]
                v = np.pad(vals, (0, pad), constant_values=np.nan)
                from scintools_tpu.parallel.mesh import shard_leading

                stats[name] = survey_stats(
                    shard_leading(jnp.asarray(v), mesh), mesh)
                log_event(log, "survey_stat", measurement=name,
                          **stats[name])

        # posterior error bars at survey scale (beyond the reference,
        # whose mcmc option runs one file at a time): ONE vmapped
        # stretch-move sampler over a sub-batch of epochs, the epoch
        # axis sharded over the mesh's data axis
        indices0, _ = buckets[0]
        # the sharded epoch axis must divide the mesh's data axis, and
        # a PARTIAL resume can leave bucket 0 with any count — round
        # what is actually available down to a mesh multiple and skip
        # the section when the bucket is smaller than the mesh
        data_ax = mesh.shape["data"]
        n_sub = (min(8, len(indices0)) // data_ax) * data_ax
        if n_sub:
            with timers.stage("mcmc_batch"):
                from scintools_tpu.fit import fit_scint_params_mcmc_batch
                from scintools_tpu.ops import acf as acf_op

                sub = [todo[i] for i in indices0[:n_sub]]
                acf_b = np.asarray(acf_op(np.stack(
                    [np.asarray(d.dyn, np.float64) for d in sub]),
                    backend="jax"))
                d0 = sub[0]
                post = fit_scint_params_mcmc_batch(
                    acf_b, dt=float(d0.times[1] - d0.times[0]),
                    df=float(d0.freqs[1] - d0.freqs[0]),
                    nchan=acf_b.shape[1] // 2, nsub=acf_b.shape[2] // 2,
                    nwalkers=16, steps=120, burn=60, seed=11, mesh=mesh)
                stats["tau_posterior"] = [round(float(t), 3)
                                          for t in np.asarray(post.tau)]
                log_event(log, "mcmc_batch", n=len(sub),
                          tau_med=stats["tau_posterior"])
        else:
            log_event(log, "mcmc_batch_skipped",
                      n_bucket=len(indices0), mesh_data=data_ax)

    csv_path = os.path.join(outdir, "results.csv")
    n_rows = store.export_csv(csv_path)
    log_event(log, "survey_done", rows=n_rows)
    print(timers.report() or "(nothing to do: fully resumed)",
          file=sys.stderr)
    return {"rows": n_rows, "stats": stats,
            "resumed": len(epochs) - len(todo)}


if __name__ == "__main__":
    main(*sys.argv[1:2])
