"""Ground-truth wavefield-fidelity regime map (round-3 VERDICT item 8).

For a grid of simulated Kolmogorov screens (mb2 x axial ratio), retrieve
the wavefield from the intensity alone and score it against the
simulator's TRUE complex field (sim.spe) — the phase-sensitive metric no
|E|^2 comparison can fake — plus the intensity correlation, for:

  (a) the chunked eigen retrieval + per-chunk projections (refine=10,
      the default), and
  (b) (a) + global arc-support Gerchberg-Saxton (refine_global=30), and
  (c) the round-4 AUTO rule (refine_global="auto", the default): refine
      iff the measured intensity corr of (a) is < 0.80 — the table shows
      which branch auto takes and that it is the better one per cell.

Output: a markdown table (stdout) pasted into docs/wavefield.md, which
documents the applicability envelope: where the thin-arc rank-1 model
holds, where the global refinement rescues it, and where it hurts.

Runtime ~10 min on CPU.  Deterministic (seed 1234).
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scintools_tpu.backend import force_host_cpu_devices  # noqa: E402

force_host_cpu_devices(1)

from scintools_tpu import Dynspec  # noqa: E402
from scintools_tpu.fit import fit_arc_thetatheta  # noqa: E402
from scintools_tpu.fit.wavefield import (auto_refine_decision,  # noqa: E402
                                         field_overlap, intensity_corr,
                                         refine_wavefield_global,
                                         retrieve_wavefield)
from scintools_tpu.io import from_simulation  # noqa: E402
from scintools_tpu.sim import Simulation  # noqa: E402


def chunk_overlap(A, B, cs=32):
    """Mean of the package's canonical gauge-invariant fidelity metric
    (fit.wavefield.field_overlap — the same definition CI uses)."""
    return float(np.mean(field_overlap(A, B, cs)))


def one(mb2, ar, seed=1234):
    psi = 90 if ar > 1 else 0
    sim = Simulation(mb2=mb2, ar=ar, psi=psi, ns=256, nf=256, dlam=0.25,
                     seed=seed)
    d = from_simulation(sim, freq=1400.0, dt=8.0)
    E_true = np.asarray(sim.spe).T
    ds = Dynspec(data=d, process=True)
    eta, _, _, _ = fit_arc_thetatheta(ds.secspec(False), 1e-3, 10.0,
                                      n_eta=96, backend="numpy")
    dyn = np.asarray(d.dyn, float)
    wf = retrieve_wavefield(d, eta, chunk_nf=32, chunk_nt=32, refine=10,
                            backend="jax")
    E0 = np.asarray(wf.field)
    Eg = refine_wavefield_global(E0, dyn, float(d.df), float(d.dt), eta,
                                 iters=30)

    # the LIBRARY's own corr metric feeds the auto decision — the
    # published table must show exactly what the shipped rule computes
    r = {"mb2": mb2, "ar": ar, "eta": eta,
         "corr0": intensity_corr(E0, dyn), "ov0": chunk_overlap(E0, E_true),
         "corrG": intensity_corr(Eg, dyn), "ovG": chunk_overlap(Eg, E_true)}
    r["auto_on"] = auto_refine_decision(r["corr0"])
    r["ovA"] = r["ovG"] if r["auto_on"] else r["ov0"]
    return r


def main():
    rows = []
    for mb2 in (1, 2, 5, 20):
        for ar in (1, 3, 10):
            r = one(mb2, ar)
            rows.append(r)
            print(f"# mb2={mb2} ar={ar}: ov {r['ov0']:.3f}->{r['ovG']:.3f}"
                  f"  corr {r['corr0']:.3f}->{r['corrG']:.3f}",
                  flush=True)
    print()
    print("| mb2 | ar | corr (refine=10) | overlap (refine=10) | "
          "+ refine_global | corr after refine_global | auto picks | "
          "auto overlap |")
    print("|---|---|---|---|---|---|---|---|")
    n_best = 0
    for r in rows:
        # bold marks a genuine true-field lift (the committed docs table's
        # semantics); regressions/flat cells stay unbolded
        gcell = (f"**{r['ovG']:.3f}**" if r["ovG"] > r["ov0"] + 0.005
                 else f"{r['ovG']:.3f}")
        n_best += r["ovA"] >= max(r["ov0"], r["ovG"]) - 1e-9
        print(f"| {r['mb2']} | {r['ar']} | {r['corr0']:.3f} | "
              f"{r['ov0']:.3f} | {gcell} | {r['corrG']:.3f} | "
              f"{'on' if r['auto_on'] else 'off'} | {r['ovA']:.3f} |")
    print(f"\nauto picks the better-or-equal branch in {n_best}/"
          f"{len(rows)} cells")


if __name__ == "__main__":
    main()
