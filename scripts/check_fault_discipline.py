#!/usr/bin/env python
"""Repo lint: no silent ``except Exception`` swallows in the fault-
critical subtrees (``scintools_tpu/parallel/``, ``scintools_tpu/
serve/``, ``scintools_tpu/ops/``).

The reliability layer (ISSUE 5) turns infrastructure failures into
*classified, observable, recoverable* events — a broad handler that
catches ``Exception``/``BaseException`` (or everything, bare) and then
neither re-raises nor reports is the one pattern that defeats it: the
fault vanishes, no counter moves, no trace event lands, and the
self-healing paths (OOM backoff, transient requeue, quarantine) never
see it.  This lint rejects exactly that pattern.

A broad handler passes when its body (recursively) contains any of:

* a ``raise`` statement (re-raise or translate);
* a call to the observability surface — ``log_event``, ``obs.inc`` /
  ``obs.gauge``, ``warnings.warn``, logger methods (`` .warning`` /
  ``.error`` / ``.exception`` / ``.log``), or ``faults.check``;
* a ``# fault-ok: <why>`` annotation on the ``except`` line — the
  triaged allowlist for handlers whose swallowing is the contract
  (e.g. best-effort capability probes), documenting WHY in place.

Narrow handlers (``except OSError``, ``except ValueError``, ...) are
out of scope: catching a *specific* exception is a statement about the
expected failure; catching everything is only safe when the handler
reports.  AST-based, so strings/comments mentioning ``except`` don't
count.  Enforced in tier-1 via tests/test_fault_discipline.py.
"""

from __future__ import annotations

import ast
import os
import sys

MARKER = "fault-ok"
# stream/ joined the walk with the ISSUE 15 streaming ingest plane:
# its feed log + resume cursor are the durability layer under live
# monitoring — a silent swallow there can lose appended samples or a
# tick with no counter moving.
# infer/ joined with the ISSUE 18 differentiable inference plane: a
# swallowed optimiser failure would publish half-fitted physics as if
# converged — divergence must route to the quarantine/poison taxonomy
#
# search/ joined with the ISSUE 19 acceleration-search plane: a
# swallowed bank-build or scoring failure would publish empty or
# half-scored candidate rows as if searched — failures must route to
# the quarantine/poison taxonomy
SUBTREES = ("infer", "ops", "parallel", "search", "serve", "stream")
# single modules outside the subtree walk that are fault-critical too:
# the ISSUE 11 results plane (utils/segments.py + utils/store.py) is
# the durability layer under the serve queue — a silent swallow there
# can lose rows without a counter moving; extend alongside any new
# storage module, pinned by tests/test_fault_discipline.py::*_is_covered
EXTRA_FILES = (os.path.join("utils", "segments.py"),
               os.path.join("utils", "store.py"),
               # the ISSUE 13 pool controller spawns/kills worker
               # processes — a silent swallow there can strand a fleet
               # with no counter moving (serve/ is already walked;
               # pinned here so a future move out of serve/ cannot
               # silently drop it from the discipline)
               os.path.join("serve", "pool.py"),
               # the ISSUE 20 storage-driver seam: every durable write
               # in the system funnels through it, so a swallowed
               # OSError here loses state across ALL planes at once
               os.path.join("utils", "fsio.py"),
               # ...and the auditor that repairs what crashes leave
               # behind — a swallowed repair failure would report
               # "clean" over a still-broken dir (serve/ is walked;
               # pinned like pool.py against a future move)
               os.path.join("serve", "fsck.py"))
# exception names whose handlers are in scope (everything-catchers)
BROAD = {"Exception", "BaseException"}
# call names (attribute tails) that count as reporting the failure
_REPORT_CALLS = {"log_event", "inc", "gauge", "warn", "warning", "error",
                 "exception", "log", "check", "fail", "_job_failed"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _reports(handler: ast.ExceptHandler) -> bool:
    """Whether the handler body re-raises or reports (see module doc)."""
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in _REPORT_CALLS:
                return True
    return False


def find_silent_handlers(path: str) -> list:
    """(line, text) of every unannotated silent broad handler."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError:  # pragma: no cover - unparseable file
        return [(0, "SyntaxError: could not parse")]
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or _reports(node):
            continue
        text = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if MARKER in text:
            continue
        hits.append((node.lineno, text.strip()))
    return sorted(hits)


def check_tree(pkg_dir: str) -> list:
    """All offending (path, line, text) under the fault-critical
    subtrees plus the pinned EXTRA_FILES."""
    offenders = []
    for sub in SUBTREES:
        root_dir = os.path.join(pkg_dir, sub)
        for root, _dirs, files in os.walk(root_dir):
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(root, name)
                for line, text in find_silent_handlers(path):
                    offenders.append((os.path.relpath(path, pkg_dir),
                                      line, text))
    for rel in EXTRA_FILES:
        path = os.path.join(pkg_dir, rel)
        if not os.path.exists(path):
            continue
        for line, text in find_silent_handlers(path):
            offenders.append((rel, line, text))
    return offenders


def main() -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(here, "scintools_tpu")
    offenders = check_tree(pkg)
    for path, line, text in offenders:
        sys.stderr.write(
            f"{path}:{line}: broad except swallows silently — re-raise, "
            f"report via obs/log_event, or annotate '# {MARKER}: <why>': "
            f"{text}\n")
    if offenders:
        sys.stderr.write(f"{len(offenders)} silent broad handler(s) in "
                         f"{'/'.join(SUBTREES)}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
