#!/usr/bin/env bash
# Sanitizer run for the native NUDFT kernel (SURVEY.md §5 "race detection"
# row): build with AddressSanitizer + UndefinedBehaviorSanitizer and drive
# every branch (uniform rotation recurrence, non-uniform fallback, edge
# shapes) against the numpy oracle.
#
# ThreadSanitizer is intentionally not run: it requires a TSan-instrumented
# libgomp to avoid false positives with OpenMP, and the kernel has no shared
# mutable state by construction (each (r, f) output bin is written by
# exactly one loop iteration; see nudft.cc).
set -euo pipefail
cd "$(dirname "$0")/.."
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

g++ -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer -fopenmp \
    -shared -fPIC -std=c++17 scintools_tpu/native/nudft.cc \
    -o "$WORK/libnudft_san.so"

ASAN_LIB=$(g++ -print-file-name=libasan.so)
ASAN_OPTIONS=detect_leaks=0 LD_PRELOAD="$ASAN_LIB" \
PYTHONPATH="$PWD" LIB="$WORK/libnudft_san.so" python3 - <<'EOF'
import os
import numpy as np

from scintools_tpu.native import bind_nudft  # the one true ABI signature
from scintools_tpu.ops.nudft import _nudft_numpy, _r_grid

lib = bind_nudft(os.environ["LIB"])

rng = np.random.default_rng(0)
for nt, nf, uniform in ((128, 64, 1), (257, 33, 1), (64, 1, 1), (2, 2, 1),
                        (128, 16, 0)):
    power = np.ascontiguousarray(rng.standard_normal((nt, nf)))
    fscale = np.ascontiguousarray(np.linspace(0.93, 1.07, nf))
    tsrc = (np.arange(nt, dtype=np.float64) if uniform
            else np.ascontiguousarray(np.sort(rng.uniform(0, nt, nt))))
    r0, dr, nr = _r_grid(nt)
    out = np.zeros((nr, nf), dtype=np.complex128)
    lib.scint_nudft(nt, nf, nr, r0, dr, fscale, tsrc, uniform, power, out)
    ref = _nudft_numpy(power, fscale, tsrc, r0, dr, nr)
    err = np.max(np.abs(out - ref))
    assert err < 1e-9, (nt, nf, uniform, err)
    print(f"{nt}x{nf} uniform={uniform}: clean, max err {err:.2e}")
print("ASan/UBSan: all branches clean")
EOF
