#!/usr/bin/env bash
# Tunnel watcher: probe the axon tunnel every TPU_WATCH_PAUSE seconds; on
# the first healthy probe, run the complete single-flight capture set
# (scripts/tpu_recheck.sh — headline bench FIRST) into a timestamped
# flight log, then exit.  The tunnel's health comes and goes in
# minute-scale windows, so the capture must start the moment a probe
# answers — not at the next human check-in.
#
# Single-flight discipline: this script is the ONLY process allowed to
# touch the device while it runs (concurrent device processes can wedge
# the tunnel for good).  CPU-side work (tests, dryruns) must pin
# JAX_PLATFORMS=cpu.
set -u
cd "$(dirname "$0")/.."

PAUSE="${TPU_WATCH_PAUSE:-600}"
MAX_TRIES="${TPU_WATCH_TRIES:-60}"
LOG_DIR=benchmarks/flights
mkdir -p "$LOG_DIR"

for ((i = 1; i <= MAX_TRIES; i++)); do
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  # a wedged claim ignores SIGTERM: escalate to SIGKILL after 5 s
  # match the success marker anywhere in the output (NOT tail -1: an
  # unfiltered trailing teardown line must not mask a healthy probe).
  # The marker embeds the backend platform: a silent CPU fallback must
  # NOT trigger the one-shot capture on the wrong device.
  out=$(timeout -k 5 180 python -u -c "
import numpy as np, jax, jax.numpy as jnp
s = float(np.asarray(jnp.sum(jnp.ones((64,64)))))
print('probe platform=%s sum=%s' % (jax.devices()[0].platform, s))
if jax.devices()[0].platform in ('tpu', 'axon') and s == 4096.0:
    print('tpu alive')
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -3)
  echo "[$ts] probe $i/$MAX_TRIES: ${out##*$'\n'}"
  if [[ "$out" == *"tpu alive"* ]]; then
    log="$LOG_DIR/r5_flight_${ts}.log"
    echo "[$ts] tunnel ALIVE — starting full capture -> $log"
    bash scripts/tpu_recheck.sh 2>&1 | tee "$log"
    rc=${PIPESTATUS[0]}
    echo "recheck rc=$rc (log: $log)"
    exit "$rc"
  fi
  sleep "$PAUSE"
done
echo "tunnel never answered in $MAX_TRIES probes"
exit 1
