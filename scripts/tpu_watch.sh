#!/usr/bin/env bash
# Tunnel watcher: probe the axon tunnel every TPU_WATCH_PAUSE seconds; on
# the first healthy probe, run the complete single-flight capture set
# (scripts/tpu_recheck.sh — headline bench FIRST) into a timestamped
# flight log, then exit.  The tunnel's health comes and goes in
# minute-scale windows, so the capture must start the moment a probe
# answers — not at the next human check-in.
#
# Single-flight discipline: this script is the ONLY process allowed to
# touch the device while it runs (concurrent device processes can wedge
# the tunnel for good).  CPU-side work (tests, dryruns) must pin
# JAX_PLATFORMS=cpu.
set -u
cd "$(dirname "$0")/.."

PAUSE="${TPU_WATCH_PAUSE:-600}"
MAX_TRIES="${TPU_WATCH_TRIES:-60}"
# Soft stop (epoch seconds): stop launching NEW probes past this time.
# The REAL single-flight guarantee against the round driver's own
# end-of-round bench is the .device.lock flock that tpu_recheck.sh and
# bench.py both take — a capture already in flight simply holds the
# lock and a concurrent bench WAITS instead of double-claiming.  The
# deadline just stops pointless probing late in the round.
DEADLINE="${TPU_WATCH_DEADLINE:-0}"
if ! [[ "$DEADLINE" =~ ^[0-9]+$ ]]; then
  echo "TPU_WATCH_DEADLINE must be numeric epoch seconds, got: $DEADLINE"
  exit 2
fi
LOG_DIR=benchmarks/flights
mkdir -p "$LOG_DIR"

# sleep PAUSE, but never past the deadline (a failed/skipped probe at
# deadline-30s must not add a full PAUSE before standing down)
nap_capped() {
  local nap="$PAUSE"
  if [[ "$DEADLINE" -gt 0 ]]; then
    local left=$((DEADLINE - $(date +%s)))
    ((left < nap)) && nap=$((left > 0 ? left : 0))
  fi
  sleep "$nap"
}

for ((i = 1; i <= MAX_TRIES; i++)); do
  now=$(date +%s)
  if [[ "$DEADLINE" -gt 0 && "$now" -ge "$DEADLINE" ]]; then
    echo "[$(date -u +%Y%m%dT%H%M%SZ)] deadline reached; standing down"
    exit 3
  fi
  ts=$(date -u +%Y%m%dT%H%M%SZ)
  # a wedged claim ignores SIGTERM: escalate to SIGKILL after 5 s
  # the probe itself claims the device, so it must respect the
  # single-flight lock: if a capture (or the round driver's bench)
  # holds it, SKIP this cycle instead of double-claiming the tunnel
  exec 9>".device.lock"
  if ! flock -n 9; then
    echo "[$ts] probe $i/$MAX_TRIES: skipped (.device.lock held)"
    exec 9>&-
    nap_capped
    continue
  fi
  # match the success marker anywhere in the output (NOT tail -1: an
  # unfiltered trailing teardown line must not mask a healthy probe);
  # scripts/device_probe.py embeds the platform check
  out=$(timeout -k 5 180 python -u scripts/device_probe.py \
    2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -3)
  # probe subprocess has exited: release BEFORE launching the capture
  # (tpu_recheck.sh takes the same lock with its own descriptor; holding
  # ours across the child would deadlock it against its own parent)
  exec 9>&-
  echo "[$ts] probe $i/$MAX_TRIES: ${out##*$'\n'}"
  if [[ "$out" == *"tpu alive"* ]]; then
    log="$LOG_DIR/r5_flight_${ts}.log"
    echo "[$ts] tunnel ALIVE — starting full capture -> $log"
    bash scripts/tpu_recheck.sh 2>&1 | tee "$log"
    rc=${PIPESTATUS[0]}
    echo "recheck rc=$rc (log: $log)"
    exit "$rc"
  fi
  nap_capped
done
echo "tunnel never answered in $MAX_TRIES probes"
exit 1
