"""Regenerate tests/data/earth_ephemeris_golden.json from the
independent VSOP87-based truth source (tests/vsop87_truth.py).

The committed table is the external anchor for the production analytic
ephemeris's documented accuracy bounds (astro/ephemeris.py: <=1e-4 AU,
<=0.02 km/s); tests/test_astro.py asserts both the production module
against the table AND the generator against the table (so silent edits
to either side fail).

Usage: python scripts/make_ephemeris_golden.py
"""

import json
import os
import sys

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_HERE, "tests"))

import vsop87_truth  # noqa: E402


def main():
    table = vsop87_truth.make_golden_table()
    out = os.path.join(_HERE, "tests", "data",
                       "earth_ephemeris_golden.json")
    with open(out, "w") as f:
        json.dump(table, f, indent=1)
        f.write("\n")
    print(f"wrote {out} ({len(table['epochs'])} epochs)")


if __name__ == "__main__":
    main()
