"""Build the static HTML documentation site from the markdown docs.

The reference ships a Sphinx skeleton plus built HTML
(/root/reference/docs/source/index.rst, docs/build/).  This repo's docs
are markdown (docs/*.md + README.md + PARITY.md); two build routes:

  - ``docs/conf.py`` + ``docs/index.rst``: a standard Sphinx+MyST
    skeleton for environments that have sphinx installed.
  - this script: a ZERO-DEPENDENCY builder (stdlib only — the pinned
    environment ships no sphinx/mkdocs and installs are not allowed)
    covering the subset of markdown the docs actually use: ATX
    headings, fenced code, tables, nested lists, blockquotes, links,
    emphasis, inline code.

Usage: python scripts/build_docs.py [outdir]   (default docs/build/html)
Exit status is nonzero if any page fails to convert.
"""

from __future__ import annotations

import html
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (source path relative to repo, output stem, nav title)
PAGES = [
    ("README.md", "index", "Overview & quickstart"),
    ("docs/overview.md", "overview", "Architecture overview"),
    ("docs/api.md", "api", "API reference"),
    ("docs/performance.md", "performance", "Performance & roofline"),
    ("docs/serving.md", "serving", "Resident survey service"),
    ("docs/streaming.md", "streaming", "Streaming ingest (live feeds)"),
    ("docs/inference.md", "inference", "Differentiable inference"),
    ("docs/search.md", "search", "Acceleration search"),
    ("docs/fleet.md", "fleet", "Fleet pool controller"),
    ("docs/reliability.md", "reliability", "Reliability & fault injection"),
    ("docs/observability.md", "observability", "Tracing & metrics"),
    ("docs/slo.md", "slo", "SLOs, error budgets & alerting"),
    ("docs/migrating.md", "migrating", "Migrating from scintools"),
    ("docs/wavefield.md", "wavefield", "Wavefield holography"),
    ("docs/roadmap.md", "roadmap", "Roadmap / build log"),
    ("PARITY.md", "parity", "Reference parity contract"),
    ("BASELINE.md", "baseline", "Benchmark baselines"),
]

_STYLE = """
body { margin: 0; font: 15px/1.55 system-ui, sans-serif; color: #1a202c; }
.wrap { display: flex; min-height: 100vh; }
nav { width: 230px; flex-shrink: 0; background: #f7f8fa;
      border-right: 1px solid #e2e8f0; padding: 1.2em 1em; }
nav h1 { font-size: 1.0em; margin: 0 0 .8em; }
nav a { display: block; color: #2b6cb0; text-decoration: none;
        padding: .18em 0; font-size: .95em; }
nav a.current { font-weight: 600; color: #1a202c; }
main { flex: 1; max-width: 54em; padding: 1.5em 2.5em 4em; }
pre { background: #f6f8fa; border: 1px solid #e2e8f0; border-radius: 6px;
      padding: .8em 1em; overflow-x: auto; font-size: .88em; }
code { background: #f0f2f5; border-radius: 3px; padding: .08em .3em;
       font-size: .92em; }
pre code { background: none; padding: 0; }
table { border-collapse: collapse; margin: 1em 0; font-size: .93em; }
th, td { border: 1px solid #cbd5e0; padding: .35em .7em; text-align: left; }
th { background: #f7f8fa; }
blockquote { border-left: 3px solid #cbd5e0; margin: 1em 0;
             padding: .1em 1em; color: #4a5568; }
h1, h2, h3 { line-height: 1.25; }
h2 { border-bottom: 1px solid #e2e8f0; padding-bottom: .25em; }
"""


def _inline(s: str) -> str:
    """Inline markdown -> HTML on an ALREADY-ESCAPED string."""
    # protect inline code spans first so emphasis rules can't touch them
    spans: list[str] = []
    s = re.sub(r"``(.+?)``|`([^`]+)`",
               lambda m: _stash_wrap(m, spans), s)
    s = re.sub(r"\[([^\]]+)\]\(([^)\s]+)\)", _link, s)
    s = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", s)
    s = re.sub(r"(?<![\w*])\*([^*\s][^*]*?)\*(?![\w*])", r"<em>\1</em>", s)
    s = re.sub(r"\x00(\d+)\x00", lambda m: spans[int(m.group(1))], s)
    return s


def _stash_wrap(m, spans):
    code = m.group(1) if m.group(1) is not None else m.group(2)
    spans.append(f"<code>{code}</code>")
    return f"\x00{len(spans) - 1}\x00"


def _link(m):
    text, url = m.group(1), m.group(2)
    # internal .md links become .html siblings (sections dropped)
    base = url.split("#")[0]
    for src, stem, _ in PAGES:
        if base and os.path.basename(src) == os.path.basename(base):
            url = stem + ".html"
            break
    return f'<a href="{url}">{text}</a>'


def md_to_html(text: str) -> str:
    out: list[str] = []
    lines = text.splitlines()
    i = 0
    in_code = False
    para: list[str] = []
    lists: list[str] = []          # stack of open list tags
    table: list[str] = []

    def flush_para():
        if para:
            out.append("<p>" + _inline(" ".join(para)) + "</p>")
            para.clear()

    def close_lists(depth=0):
        while len(lists) > depth:
            out.append(f"</{lists.pop()}>")

    def flush_table():
        if not table:
            return
        rows = [r for r in table if not re.fullmatch(
            r"\|?[\s:|-]+\|?", r)]
        out.append("<table>")
        for k, row in enumerate(rows):
            cells = [c.strip() for c in row.strip().strip("|").split("|")]
            tag = "th" if k == 0 else "td"
            out.append("<tr>" + "".join(
                f"<{tag}>{_inline(c)}</{tag}>" for c in cells) + "</tr>")
        out.append("</table>")
        table.clear()

    while i < len(lines):
        raw = lines[i]
        line = html.escape(raw, quote=False)
        if raw.lstrip().startswith("```"):
            flush_para(); flush_table()
            if not in_code:
                close_lists()
                out.append("<pre><code>")
            else:
                out.append("</code></pre>")
            in_code = not in_code
            i += 1
            continue
        if in_code:
            out.append(line)
            i += 1
            continue
        if re.fullmatch(r"\s*", raw):
            flush_para(); flush_table(); close_lists()
            i += 1
            continue
        m = re.match(r"(#{1,5})\s+(.*)", raw)
        if m:
            flush_para(); flush_table(); close_lists()
            n = len(m.group(1))
            out.append(f"<h{n}>{_inline(html.escape(m.group(2)))}</h{n}>")
            i += 1
            continue
        if re.fullmatch(r"\s*(-{3,}|\*{3,})\s*", raw):
            flush_para(); flush_table(); close_lists()
            out.append("<hr/>")
            i += 1
            continue
        if raw.lstrip().startswith("|"):
            flush_para(); close_lists()
            table.append(line)
            i += 1
            continue
        m = re.match(r"(\s*)([-*]|\d+\.)\s+(.*)", raw)
        if m:
            flush_para(); flush_table()
            depth = len(m.group(1)) // 2 + 1
            tag = "ol" if m.group(2)[0].isdigit() else "ul"
            while len(lists) > depth:
                out.append(f"</{lists.pop()}>")
            while len(lists) < depth:
                lists.append(tag)
                out.append(f"<{tag}>")
            out.append("<li>" + _inline(html.escape(m.group(3),
                                                    quote=False)) + "</li>")
            i += 1
            continue
        if raw.lstrip().startswith(">"):
            flush_para(); flush_table(); close_lists()
            quote = []
            while i < len(lines) and lines[i].lstrip().startswith(">"):
                quote.append(html.escape(
                    lines[i].lstrip()[1:].strip(), quote=False))
                i += 1
            out.append("<blockquote><p>" + _inline(" ".join(quote))
                       + "</p></blockquote>")
            continue
        if lists:
            # lazy continuation of the previous list item
            out[-1] = out[-1][:-5] + " " + _inline(line.strip()) + "</li>"
            i += 1
            continue
        para.append(line.strip())
        i += 1
    flush_para(); flush_table(); close_lists()
    if in_code:
        raise ValueError("unterminated code fence")
    return "\n".join(out)


def build(outdir: str) -> list[str]:
    os.makedirs(outdir, exist_ok=True)
    written = []
    for src, stem, title in PAGES:
        path = os.path.join(REPO, src)
        if not os.path.exists(path):
            raise FileNotFoundError(f"doc source missing: {src}")
        with open(path, encoding="utf-8") as fh:
            body = md_to_html(fh.read())
        nav = "\n".join(
            f'<a href="{s}.html"{" class=current" if s == stem else ""}>'
            f"{t}</a>" for _, s, t in PAGES)
        page = (
            "<!DOCTYPE html><html><head><meta charset='utf-8'/>"
            f"<title>scintools-tpu — {title}</title>"
            f"<style>{_STYLE}</style></head><body><div class='wrap'>"
            f"<nav><h1>scintools-tpu</h1>{nav}</nav>"
            f"<main>{body}</main></div></body></html>")
        dest = os.path.join(outdir, stem + ".html")
        with open(dest, "w", encoding="utf-8") as fh:
            fh.write(page)
        written.append(dest)
    return written


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "docs", "build", "html")
    pages = build(out)
    print(f"built {len(pages)} pages -> {out}")
