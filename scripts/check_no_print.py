#!/usr/bin/env python
"""Repo lint: no ``print(`` in ``scintools_tpu/`` outside the two
display modules (plotting.py, cli.py).

The observability layer (scintools_tpu.obs spans/counters + the
utils.log key=value channel) is the ONLY reporting channel for compute
code; a stray print in an op or fitter bypasses sinks, corrupts
machine-readable CLI stdout (the bench/sim/sort AND serve/submit/
status/drain commands print JSON records), and is invisible to `trace
report`.  The walk covers every package subtree — including
``scintools_tpu/serve/`` (whose worker/queue/client must report via
obs counters and log_event, never stdout: the serve CLI's JSON line is
parsed by scripts).  Enforced in tier-1 via tests/test_no_print.py.

Token-based, not regex: string literals and comments mentioning print()
(docstrings quoting the reference's behaviour) are fine; only a real
NAME token ``print`` in code counts.
"""

from __future__ import annotations

import io
import os
import sys
import tokenize

# display modules: stdout IS their output channel
ALLOWED = {"plotting.py", "cli.py"}


def find_prints(path: str) -> list:
    """(line, text) of every real ``print`` name token in a source file."""
    with open(path, "rb") as fh:
        src = fh.read()
    hits = []
    try:
        tokens = tokenize.tokenize(io.BytesIO(src).readline)
        for tok in tokens:
            if tok.type == tokenize.NAME and tok.string == "print":
                hits.append((tok.start[0], tok.line.strip()))
    except tokenize.TokenError:  # pragma: no cover - unparseable file
        hits.append((0, "TokenError: could not tokenize"))
    return hits


def check_tree(pkg_dir: str) -> list:
    """All offending (path, line, text) under ``pkg_dir``."""
    offenders = []
    for root, _dirs, files in os.walk(pkg_dir):
        for name in sorted(files):
            if not name.endswith(".py") or name in ALLOWED:
                continue
            path = os.path.join(root, name)
            for line, text in find_prints(path):
                offenders.append((os.path.relpath(path, pkg_dir), line,
                                  text))
    return offenders


def main() -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(here, "scintools_tpu")
    offenders = check_tree(pkg)
    for path, line, text in offenders:
        sys.stderr.write(f"{path}:{line}: print() in compute path "
                         f"(use scintools_tpu.obs / utils.log): "
                         f"{text}\n")
    if offenders:
        sys.stderr.write(f"{len(offenders)} print() call(s) outside "
                         f"{sorted(ALLOWED)}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
