"""Generate the committed real-format observational fixture.

The reference's de-facto integration target is real J0437-4715 psrflux
data with band-edge roll-off, dropout gaps and RFI (its notebook,
reference examples/arc_modelling.ipynb; the data directory is not
shipped).  This script writes a faithfully degraded simulated epoch
through the framework's own psrflux writer so CI can exercise the
dirty-data path (trim -> refill -> zap -> correct_band -> sspec -> fits)
on a REAL-format file with genuine defects:

* dead band edges (all-zero channels, as backends emit them),
* a dropout time gap (zeroed subints mid-observation),
* narrowband RFI (two hot channels, one multiplicative ramp),
* impulsive broadband RFI (two hot subints),
* a slow receiver gain drift in time,
* a bandpass ripple in frequency.

Deterministic (fixed seeds); re-running reproduces the committed file
byte-for-byte.  Output: tests/data/J0000+0000_degraded.dynspec
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scintools_tpu.io import from_simulation, write_psrflux  # noqa: E402
from scintools_tpu.sim import Simulation  # noqa: E402


def build(nf: int = 96, nt: int = 144, seed: int = 20260731):
    sim = Simulation(mb2=2, ns=nt, nf=nf, dlam=0.25, seed=seed)
    d = from_simulation(sim, freq=1400.0, dt=8.0)
    dyn = np.asarray(d.dyn, dtype=np.float64).copy()
    rng = np.random.default_rng(seed)

    # receiver systematics BEFORE the defects (they multiply real flux)
    gain_t = 1.0 + 0.25 * np.sin(2 * np.pi * np.arange(nt) / nt * 1.5)
    bandpass_f = 1.0 + 0.30 * np.cos(2 * np.pi * np.arange(nf) / nf * 2.2)
    dyn *= bandpass_f[:, None] * gain_t[None, :]

    # narrowband RFI: two hot channels + one multiplicative ramp channel
    dyn[17, :] += np.abs(rng.normal(25.0, 5.0, nt))
    dyn[58, :] += np.abs(rng.normal(40.0, 8.0, nt))
    dyn[33, :] *= np.linspace(1.0, 9.0, nt)
    # impulsive broadband RFI: two hot subints
    dyn[:, 41] += np.abs(rng.normal(30.0, 6.0, nf))
    dyn[:, 97] += np.abs(rng.normal(22.0, 4.0, nf))

    # dropout gap: backend wrote zeros for 9 dead subints
    dyn[:, 70:79] = 0.0
    # dead band edges: 4 + 3 all-zero channels (receiver roll-off)
    dyn[:4, :] = 0.0
    dyn[-3:, :] = 0.0
    # scattered dead pixels (packet loss)
    ii = rng.integers(4, nf - 3, 60)
    jj = rng.integers(0, nt, 60)
    dyn[ii, jj] = 0.0

    return type(d)(dyn=dyn, freqs=np.asarray(d.freqs),
                   times=np.asarray(d.times), mjd=58000.0,
                   name="J0000+0000_degraded")


def main():
    out_dir = os.environ.get("SCINT_FIXTURE_OUT",
                             os.path.join(REPO, "tests", "data"))
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, "J0000+0000_degraded.dynspec")
    write_psrflux(build(), out)
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")


if __name__ == "__main__":
    main()
