#!/usr/bin/env python
"""Repo lint: no ``float64`` / ``complex128`` literals in the jax hot
paths (``scintools_tpu/ops/`` + ``scintools_tpu/parallel/`` +
``scintools_tpu/sim/``) without an explicit ``# host-f64`` annotation.

The compiled pipeline is an f32 machine: under the production x64-off
runtime a stray ``astype(np.float64)`` on a traced array either
silently truncates to f32 behind a UserWarning (the MULTICHIP_r05
incident in ops/nudft.py) or — with x64 enabled — doubles every byte of
a bandwidth-bound step.  Host-side numpy code legitimately runs in f64
(the reference parity paths, grid precomputation, oracle baselines):
those sites carry a ``# host-f64`` marker on the same line, which both
allowlists them here and documents WHY the wide dtype is correct.

Token-based, not regex: docstrings and comments that merely mention the
dtypes don't count; only a real NAME token does.  Enforced in tier-1
via tests/test_f32_discipline.py.

Coverage is the full ``ops/`` + ``parallel/`` + ``sim/`` walk — which
includes the Pallas kernel modules (``ops/pallas_common.py``,
``ops/sspec_pallas.py``, ``ops/resample_pallas.py``, the kernels in
``ops/nudft.py``): kernels are the EASIEST place to silently
reintroduce f64 temps (a host-precomputed phase matrix or window taper
flowing into VMEM doubles the very bytes the kernel exists to save),
so tests/test_f32_discipline.py pins those files as present in the
walk.  ``sim/`` joined the walk when the synthetic route fused the
simulator INTO the compiled analysis step (sim/campaign.py): its
generators now trace straight into the device program, so a stray wide
dtype there is the same silent-truncation / 2x-bytes hazard as one in
ops/ (host-side mode tables and axis builders carry the annotation).
"""

from __future__ import annotations

import io
import os
import sys
import tokenize

WIDE = {"float64", "complex128"}
MARKER = "host-f64"
# stream/ joined the walk with the ISSUE 15 streaming ingest plane:
# the ring updater traces into the device program and the feed log
# stores the staged dtype — a stray wide dtype there doubles the very
# per-tick bytes the device-resident window exists to avoid.
# infer/ joined with the ISSUE 18 differentiable inference plane: the
# loss/optimiser/Fisher chain traces into ONE compiled program whose
# gradients double every wide dtype's cost twice over (forward AND
# backward pass)
#
# search/ joined with the ISSUE 19 acceleration-search plane: the
# correlation scores J templates x B epochs in one program — a wide
# dtype in the bank or the MAC multiplies the dominant traffic term
SUBTREES = ("infer", "ops", "parallel", "search", "sim", "stream")
# single modules outside the subtree walk that still sit on hot paths
# (the ISSUE 11 results plane streams every campaign row — a wide
# dtype sneaking into its encode/decode would double the bytes of the
# very plane built to cut them); extend alongside any new storage
# module, pinned by tests/test_f32_discipline.py::*_is_covered
EXTRA_FILES = (os.path.join("utils", "segments.py"),
               os.path.join("utils", "store.py"),
               # the ISSUE 13 pool controller (serve/ is outside this
               # lint's subtree walk): its hint math feeds claim-time
               # routing on byte counts — a wide dtype there is the
               # same silent 2x the storage modules guard against
               os.path.join("serve", "pool.py"),
               # the ISSUE 20 storage-driver seam sits under every
               # durable byte the planes write, and the fsck auditor
               # re-reads every plane it wrote (serve/ is outside this
               # lint's subtree walk)
               os.path.join("utils", "fsio.py"),
               os.path.join("serve", "fsck.py"))


def find_wide_literals(path: str) -> list:
    """(line, text) of every unannotated wide-dtype NAME token."""
    with open(path, "rb") as fh:
        src = fh.read()
    hits = []
    try:
        for tok in tokenize.tokenize(io.BytesIO(src).readline):
            if (tok.type == tokenize.NAME and tok.string in WIDE
                    and MARKER not in tok.line):
                hits.append((tok.start[0], tok.line.strip()))
    except tokenize.TokenError:  # pragma: no cover - unparseable file
        hits.append((0, "TokenError: could not tokenize"))
    return hits


def check_tree(pkg_dir: str) -> list:
    """All offending (path, line, text) under the jax-path subtrees
    plus the pinned EXTRA_FILES."""
    offenders = []
    for sub in SUBTREES:
        root_dir = os.path.join(pkg_dir, sub)
        for root, _dirs, files in os.walk(root_dir):
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(root, name)
                for line, text in find_wide_literals(path):
                    offenders.append((os.path.relpath(path, pkg_dir),
                                      line, text))
    for rel in EXTRA_FILES:
        path = os.path.join(pkg_dir, rel)
        if not os.path.exists(path):
            continue
        for line, text in find_wide_literals(path):
            offenders.append((rel, line, text))
    return offenders


def main() -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(here, "scintools_tpu")
    offenders = check_tree(pkg)
    for path, line, text in offenders:
        sys.stderr.write(f"{path}:{line}: wide dtype in a jax-path "
                         f"module (annotate host-side parity code with "
                         f"'# {MARKER}: <why>'): {text}\n")
    if offenders:
        sys.stderr.write(f"{len(offenders)} unannotated float64/"
                         f"complex128 literal(s) in "
                         f"{' + '.join(s + '/' for s in SUBTREES)}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
