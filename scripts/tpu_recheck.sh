#!/usr/bin/env bash
# One-shot device validation + measurement sequence, to run when TPU
# hardware is reachable.  SERIAL on purpose: concurrent device processes
# can wedge the axon tunnel (see .claude/skills/verify/SKILL.md).
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  # status must reflect the python probe (a wedged claim ignores
  # SIGTERM: escalate to SIGKILL), not the log filter's status
  local out
  out=$(timeout -k 5 180 python -u -c "
import numpy as np, jax, jax.numpy as jnp
print('tpu alive:', float(np.asarray(jnp.sum(jnp.ones((64,64))))))
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -1)
  echo "$out"
  [[ "$out" == *"tpu alive"* ]]
}

echo "== probe =="
probe || { echo "tunnel unreachable; aborting"; exit 1; }

echo "== pallas row-scrunch lowers on chip =="
# the fused row-scrunch kernel is the arc fitter's on-chip auto route
# since round 4 (wire verdict, 3.5x the scan); CI validates it in
# interpret mode only, so this is the real-Mosaic correctness gate.
# Gate on python's EXIT STATUS (the rel-err line prints before the
# assert, so grepping for it cannot detect a failure), captured to a
# file because the log-noise filter pipeline would otherwise own the
# status.  (The Pallas NUDFT that was also gated here was deleted in
# round 4: 0.44x the production einsum — benchmarks/pallas_ab.py.)
pallas_out=$(mktemp)
trap 'rm -f "$pallas_out"' EXIT
if ! timeout -k 10 600 python -u -c "
import numpy as np
from scintools_tpu.ops.resample_pallas import row_scrunch_pallas
rng = np.random.default_rng(0)
R, C, n = 96, 256, 128
rows = rng.standard_normal((R, C))
rows[7, :] = np.nan    # dead row + dead column: the NaN-mask path must
rows[:, 19] = np.nan   # survive real Mosaic, not just interpret mode
scales = np.sqrt(np.linspace(0.05, 1.0, R))
pos = np.clip((np.linspace(-1, 1, n)[None] * scales[:, None] * 0.5
               + 0.5) * (C - 1), 0, C - 2 + 0.999)
i0 = np.clip(np.floor(pos).astype(np.int32), 0, C - 2)
wgt = pos - i0
v0 = np.take_along_axis(rows, i0, axis=1)
v1 = np.take_along_axis(rows, i0 + 1, axis=1)
import warnings as _w
with _w.catch_warnings():
    _w.simplefilter('ignore')
    want2 = np.nanmean(v0 * (1 - wgt) + v1 * wgt, axis=0)
got2 = np.asarray(row_scrunch_pallas(rows, i0, wgt))
err2 = np.max(np.abs(got2 - want2)) / max(np.max(np.abs(want2)), 1e-30)
print('row-scrunch pallas on-chip rel err:', err2)
assert err2 < 5e-3, err2
" > "$pallas_out" 2>&1; then
  grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$pallas_out" | tail -5
  echo "pallas lowering check FAILED"
  exit 1
fi
grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$pallas_out" | tail -2

echo "== pallas prove-or-remove A/B =="
# regression guard for the wired row-scrunch route (docs/roadmap.md:
# wire a kernel only if it beats the production path by >= 1.15x with
# matching numerics; otherwise it gets deleted)
if ! timeout -k 10 1800 python benchmarks/pallas_ab.py --iters 10 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -4; then
  echo "pallas A/B FAILED"
  exit 1
fi

echo "== stage profile (bench shape) =="
timeout -k 10 1800 python benchmarks/profile_stages.py --b 256 --iters 5 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -10

echo "== auto-route A/B at the bench batch size (B=1024) =="
# the arc_scrunch_rows=-1 / scint_cuts=auto defaults were extrapolated
# from B=256; re-validate them at the size bench.py actually runs.
# ONE invocation (one jax init, one 512 MB batch): profile_stages
# exits nonzero if the row filter matches nothing (renamed rows must
# fail loudly, not skip the A/B)
if ! timeout -k 10 3600 python benchmarks/profile_stages.py --b 1024 \
  --iters 3 --only "rc=,cuts,lm_steps" \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -8; then
  echo "B=1024 auto-route A/B FAILED"
  exit 1
fi

echo "== f32 numerics budget on chip =="
# the committed budget test (tests/test_f32_budget.py) runs f32-on-CPU
# in CI; re-run its core loop with the f32 leg on the REAL chip so the
# documented budgets (docs/performance.md) are validated on hardware.
# The f64 oracle stays on host CPU (chips have no f64).
if ! timeout -k 10 1800 python -u -c "
import numpy as np, jax
from tests.test_f32_budget import BUDGET, REGIMES, _get
from scintools_tpu.io import from_simulation
from scintools_tpu.sim import Simulation
from scintools_tpu.parallel import PipelineConfig, make_pipeline
cpu = jax.local_devices(backend='cpu')[0]
step = None
worst = {k: 0.0 for k in BUDGET}
for rg in REGIMES:
    sim = Simulation(mb2=rg['mb2'], ns=128, nf=128, dlam=0.25,
                     seed=rg['seed'], ar=rg['ar'])
    d = from_simulation(sim, freq=1400.0, dt=8.0)
    if step is None:
        step = make_pipeline(np.asarray(d.freqs), np.asarray(d.times),
                             PipelineConfig(arc_numsteps=1000))
    dyn64 = np.asarray(d.dyn, np.float64)[None]
    r32 = step(dyn64.astype(np.float32))          # on chip, f32
    with jax.enable_x64(True), jax.default_device(cpu):
        r64 = step(dyn64)                         # host f64 oracle
    for name, budget in BUDGET.items():
        v64, v32 = _get(r64, name), _get(r32, name)
        rel = abs(v32 - v64) / abs(v64)
        worst[name] = max(worst[name], rel)
        assert rel <= budget, (name, rg, rel, budget)
print('on-chip f32 drift within budget; worst:',
      {k: f'{v:.2e}' for k, v in worst.items()})
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -3; then
  echo "f32 on-chip check FAILED"
  exit 1
fi

echo "== headline bench =="
timeout -k 10 2400 python bench.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2

echo "== all five configs =="
timeout -k 10 3600 python benchmarks/all_configs.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -6
