#!/usr/bin/env bash
# One-shot device validation + measurement sequence, to run when TPU
# hardware is reachable.  SERIAL on purpose: concurrent device processes
# can wedge the axon tunnel (see .claude/skills/verify/SKILL.md).
set -u -o pipefail
cd "$(dirname "$0")/.."

# single-flight mutual exclusion: hold .device.lock for the WHOLE
# flight, so any concurrently-started bench.py (e.g. the round
# driver's end-of-round run) WAITS on the same flock instead of
# double-claiming the tunnel (two concurrent device processes can
# wedge it for good).  bench.py skips its own acquisition when this
# env var says an ancestor already holds the lock.
# wait default sized ABOVE a concurrent bench.py's worst-case hold
# (1200 s device watchdog + baseline + margin): the opposing holder
# finishing and this flight then starting is the correct serialisation
exec 9>".device.lock"
if ! flock -w "${TPU_LOCK_WAIT:-2700}" 9; then
  echo "device single-flight lock busy >${TPU_LOCK_WAIT:-2700}s; aborting"
  exit 4
fi
export SCINT_DEVICE_LOCK_HELD=1

probe() {
  # status must reflect the python probe (a wedged claim ignores
  # SIGTERM: escalate to SIGKILL), not the log filter's status;
  # scripts/device_probe.py embeds the platform check so a silent CPU
  # fallback cannot greenlight the hour-scale "on-chip" capture
  local out
  out=$(timeout -k 5 180 python -u scripts/device_probe.py \
    2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2)
  echo "$out"
  [[ "$out" == *"tpu alive"* ]]
}

echo "== probe =="
probe || { echo "tunnel unreachable; aborting"; exit 1; }

# HEADLINE FIRST (round-4 lesson: the tunnel wedged mid-flight and
# took the un-run bench stage with it — the headline is the round's
# #1 deliverable, so it runs before the gates; a broken route would
# surface as a failed/NaN bench, which the later gates then explain)
echo "== headline bench =="
timeout -k 10 2400 python bench.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2

echo "== pallas row-scrunch lowers on chip =="
# the fused row-scrunch kernel is the arc fitter's on-chip auto route
# since round 4 (wire verdict, 3.5x the scan); CI validates it in
# interpret mode only, so this is the real-Mosaic correctness gate.
# Gate on python's EXIT STATUS (the rel-err line prints before the
# assert, so grepping for it cannot detect a failure), captured to a
# file because the log-noise filter pipeline would otherwise own the
# status.  (The Pallas NUDFT that was also gated here was deleted in
# round 4: 0.44x the production einsum — benchmarks/pallas_ab.py.)
pallas_out=$(mktemp)
trap 'rm -f "$pallas_out"' EXIT
if ! timeout -k 10 600 python -u -c "
import numpy as np
from scintools_tpu.ops.resample_pallas import row_scrunch_pallas
rng = np.random.default_rng(0)
R, C, n = 96, 256, 128
rows = rng.standard_normal((R, C))
rows[7, :] = np.nan    # dead row + dead column: the NaN-mask path must
rows[:, 19] = np.nan   # survive real Mosaic, not just interpret mode
scales = np.sqrt(np.linspace(0.05, 1.0, R))
pos = np.clip((np.linspace(-1, 1, n)[None] * scales[:, None] * 0.5
               + 0.5) * (C - 1), 0, C - 2 + 0.999)
i0 = np.clip(np.floor(pos).astype(np.int32), 0, C - 2)
wgt = pos - i0
v0 = np.take_along_axis(rows, i0, axis=1)
v1 = np.take_along_axis(rows, i0 + 1, axis=1)
import warnings as _w
with _w.catch_warnings():
    _w.simplefilter('ignore')
    want2 = np.nanmean(v0 * (1 - wgt) + v1 * wgt, axis=0)
got2 = np.asarray(row_scrunch_pallas(rows, i0, wgt))
err2 = np.max(np.abs(got2 - want2)) / max(np.max(np.abs(want2)), 1e-30)
print('row-scrunch pallas on-chip rel err:', err2)
assert err2 < 5e-3, err2
" > "$pallas_out" 2>&1; then
  # failure path: UNFILTERED tail — a backend-init hang emits only
  # INFO/axon lines, and the round-5 flight's filtered tail was empty,
  # leaving the wedge-vs-genuine-failure question undecidable from the log
  tail -12 "$pallas_out"
  echo "pallas lowering check FAILED (unfiltered tail above)"
  exit 1
fi
grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$pallas_out" | tail -2

echo "== nudft einsum on-chip accuracy (bf16-lowering guard) =="
# the round-4 A/B caught the vmapped einsum NUDFT silently lowering to
# bf16 MXU passes (2e-3 scaled error); _nudft_jax_reim now pins
# Precision.HIGHEST.  CPU CI cannot see this (einsum precision is exact
# there), so the on-chip oracle check lives here permanently.
if ! timeout -k 10 600 python -u -c "
import numpy as np, jax, jax.numpy as jnp
from scintools_tpu.ops.nudft import _nudft_numpy, _r_grid, nudft
rng = np.random.default_rng(1)
B, nt, nf = 4, 512, 256
dyn = rng.standard_normal((B, nt, nf)).astype(np.float32)
freqs = np.linspace(1300.0, 1500.0, nf)
fscale = freqs / freqs[nf // 2]
tsrc = np.arange(nt, dtype=np.float64)
r0, dr, nr = _r_grid(nt)
f = jax.jit(jax.vmap(lambda d: jnp.real(nudft(d, fscale, backend='jax'))**2
                     + jnp.imag(nudft(d, fscale, backend='jax'))**2))
a = np.asarray(f(dyn))
w = _nudft_numpy(dyn[0].astype(np.float64), fscale, tsrc, r0, dr, nr)
pw = np.abs(w) ** 2
err = float(np.max(np.abs(a[0] - pw)) / pw.max())
print('vmapped einsum nudft vs f64 oracle, scaled err:', err)
assert err < 2e-4, ('bf16 MXU lowering is back?', err)
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2; then
  echo "nudft einsum accuracy check FAILED"
  exit 1
fi

echo "== pallas prove-or-remove A/B =="
# regression guard for the wired row-scrunch route (docs/roadmap.md:
# wire a kernel only if it beats the production path by >= 1.15x with
# matching numerics; otherwise it gets deleted)
if ! timeout -k 10 1800 python benchmarks/pallas_ab.py --iters 10 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -4; then
  echo "pallas A/B FAILED"
  exit 1
fi

echo "== stage profile (bench shape) =="
timeout -k 10 1800 python benchmarks/profile_stages.py --b 256 --iters 5 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -13

echo "== auto-route A/B at the bench batch size (B=1024) =="
# the arc_scrunch_rows=-1 / scint_cuts=auto defaults were extrapolated
# from B=256; re-validate them at the size bench.py actually runs.
# ONE invocation (one jax init, one 512 MB batch): profile_stages
# exits nonzero if the row filter matches nothing (renamed rows must
# fail loudly, not skip the A/B)
if ! timeout -k 10 3600 python benchmarks/profile_stages.py --b 1024 \
  --iters 3 --only "rc=,cuts,lm_steps" \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -8; then
  echo "B=1024 auto-route A/B FAILED"
  exit 1
fi

echo "== arc measurement-tail A/B (exact vs fast, simulated arcs) =="
# the opt-in arc_tail="fast" knob ships only while its numerics hold:
# every healthy lane's eta within the fit's own etaerr of the exact
# tail, NaN quarantine identical (benchmarks/arc_tail_ab.py exits
# nonzero on a numerics-mismatch verdict)
if ! timeout -k 10 1800 python benchmarks/arc_tail_ab.py --b 256 --iters 5 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2; then
  echo "arc tail A/B FAILED"
  exit 1
fi

echo "== f32 numerics budget on chip =="
# hardware tier of the f32 drift suite: chip-f32 vs host-f64 oracle
# with degenerate-profile awareness (a weak-scattering epoch whose two
# arc lobes agree to <0.1 dB may legitimately flip under f32 — see
# benchmarks/f32_budget_onchip.py).  CI tier: tests/test_f32_budget.py.
if ! timeout -k 10 1800 python benchmarks/f32_budget_onchip.py \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -4; then
  echo "f32 on-chip check FAILED"
  exit 1
fi

echo "== all five configs =="
timeout -k 10 3600 python benchmarks/all_configs.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -6
