#!/usr/bin/env bash
# One-shot device validation + measurement sequence, to run when TPU
# hardware is reachable.  SERIAL on purpose: concurrent device processes
# can wedge the axon tunnel (see .claude/skills/verify/SKILL.md).
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  # status must reflect the python probe (a wedged claim ignores
  # SIGTERM: escalate to SIGKILL), not the log filter's status
  local out
  out=$(timeout -k 5 180 python -u -c "
import numpy as np, jax, jax.numpy as jnp
print('tpu alive:', float(np.asarray(jnp.sum(jnp.ones((64,64))))))
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -1)
  echo "$out"
  [[ "$out" == *"tpu alive"* ]]
}

echo "== probe =="
probe || { echo "tunnel unreachable; aborting"; exit 1; }

echo "== stage profile (bench shape) =="
timeout -k 10 1800 python benchmarks/profile_stages.py --b 256 --iters 5 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -8

echo "== headline bench =="
timeout -k 10 2400 python bench.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2

echo "== all five configs =="
timeout -k 10 3600 python benchmarks/all_configs.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -6
