#!/usr/bin/env bash
# One-shot device validation + measurement sequence, to run when TPU
# hardware is reachable.  SERIAL on purpose: concurrent device processes
# can wedge the axon tunnel (see .claude/skills/verify/SKILL.md).
set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 180 python -u -c "
import numpy as np, jax, jax.numpy as jnp
print('tpu alive:', float(np.asarray(jnp.sum(jnp.ones((64,64))))))
" 2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -1
}

echo "== probe =="
probe || { echo "tunnel unreachable; aborting"; exit 1; }

echo "== stage profile (bench shape) =="
timeout 1800 python benchmarks/profile_stages.py --b 256 --iters 5 \
  2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -8

echo "== headline bench =="
timeout 2400 python bench.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2

echo "== all five configs =="
timeout 3600 python benchmarks/all_configs.py 2>&1 \
  | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -6
