#!/usr/bin/env bash
# One-shot device validation + measurement sequence, to run when TPU
# hardware is reachable.  SERIAL on purpose: concurrent device processes
# can wedge the axon tunnel (see .claude/skills/verify/SKILL.md).
set -u -o pipefail
cd "$(dirname "$0")/.."

# single-flight mutual exclusion: hold .device.lock for the WHOLE
# flight, so any concurrently-started bench.py (e.g. the round
# driver's end-of-round run) WAITS on the same flock instead of
# double-claiming the tunnel (two concurrent device processes can
# wedge it for good).  bench.py skips its own acquisition when this
# env var says an ancestor already holds the lock.
# wait default sized ABOVE a concurrent bench.py's worst-case hold
# (1200 s device watchdog + baseline + margin): the opposing holder
# finishing and this flight then starting is the correct serialisation
exec 9>".device.lock"
if ! flock -w "${TPU_LOCK_WAIT:-2700}" 9; then
  echo "device single-flight lock busy >${TPU_LOCK_WAIT:-2700}s; aborting"
  exit 4
fi
export SCINT_DEVICE_LOCK_HELD=1

probe() {
  # status must reflect the python probe (a wedged claim ignores
  # SIGTERM: escalate to SIGKILL), not the log filter's status;
  # scripts/device_probe.py embeds the platform check so a silent CPU
  # fallback cannot greenlight the hour-scale "on-chip" capture
  local out
  out=$(timeout -k 5 180 python -u scripts/device_probe.py \
    2>&1 | grep -v -E 'INFO|WARN|axon_|Logging|E0000' | tail -2)
  echo "$out"
  [[ "$out" == *"tpu alive"* ]]
}

stage_out=$(mktemp)
trap 'rm -f "$stage_out"' EXIT

# gated <label> <timeout_s> <success_tail_n> <cmd...>: run the stage to
# a capture file; on success print the log-noise-filtered tail, on
# FAILURE print an UNFILTERED tail — a backend-init hang emits only
# INFO/axon lines, and the round-5 flight's filtered failure tail was
# empty, leaving wedge-vs-genuine-failure undecidable from the log.
# Exit status is the python process's own (pipefail cannot help here:
# the capture file, not a pipe, owns the output).
gated() {
  local label="$1" tmo="$2" tail_n="$3"
  shift 3
  if ! timeout -k 10 "$tmo" "$@" > "$stage_out" 2>&1; then
    tail -12 "$stage_out"
    echo "$label FAILED (unfiltered tail above)"
    exit 1
  fi
  # `|| true`: under pipefail an all-noise (fully filtered) success log
  # would otherwise turn grep's no-match status into a stage failure
  { grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$stage_out" || true; } \
    | tail -"$tail_n"
}

# triage <label> <timeout_s> <cmd...>: non-aborting variant of gated for
# the post-failure diagnosis path — prints the unfiltered tail and a
# PASS/FAIL verdict, returns the stage's status instead of exiting.
triage() {
  local label="$1" tmo="$2"
  shift 2
  if timeout -k 10 "$tmo" "$@" > "$stage_out" 2>&1; then
    { grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$stage_out" || true; } \
      | tail -2
    echo "$label: PASS"
    return 0
  fi
  tail -12 "$stage_out"
  echo "$label: FAIL (unfiltered tail above)"
  return 1
}

# the two sub-minute correctness gates, defined once so BOTH the normal
# stage sequence and the headline-failure triage run the same code
PALLAS_CODE="
import numpy as np
from scintools_tpu.ops.resample_pallas import row_scrunch_pallas
rng = np.random.default_rng(0)
R, C, n = 96, 256, 128
rows = rng.standard_normal((R, C))
rows[7, :] = np.nan    # dead row + dead column: the NaN-mask path must
rows[:, 19] = np.nan   # survive real Mosaic, not just interpret mode
scales = np.sqrt(np.linspace(0.05, 1.0, R))
pos = np.clip((np.linspace(-1, 1, n)[None] * scales[:, None] * 0.5
               + 0.5) * (C - 1), 0, C - 2 + 0.999)
i0 = np.clip(np.floor(pos).astype(np.int32), 0, C - 2)
wgt = pos - i0
v0 = np.take_along_axis(rows, i0, axis=1)
v1 = np.take_along_axis(rows, i0 + 1, axis=1)
import warnings as _w
with _w.catch_warnings():
    _w.simplefilter('ignore')
    want2 = np.nanmean(v0 * (1 - wgt) + v1 * wgt, axis=0)
got2 = np.asarray(row_scrunch_pallas(rows, i0, wgt))
err2 = np.max(np.abs(got2 - want2)) / max(np.max(np.abs(want2)), 1e-30)
print('row-scrunch pallas on-chip rel err:', err2)
assert err2 < 5e-3, err2
"

FUSED_CODE="
import numpy as np, jax
from scintools_tpu.ops.sspec import _sspec_numpy, sspec
from scintools_tpu.ops.sspec_pallas import sspec_fused
rng = np.random.default_rng(0)
nf, nt, crop = 256, 512, 64
dyn = rng.standard_normal((nf, nt)).astype(np.float32)
oracle = _sspec_numpy(dyn.astype(np.float64), True, 'blackman', 0.1,
                      False, 'pow2', crop)
sc = np.max(np.abs(oracle))
chain = np.asarray(jax.jit(lambda d: sspec(
    d, db=False, backend='jax', crop_rows=crop))(dyn))
# route='pallas' explicitly: the real-Mosaic prologue + tiled epilogue
# must lower and agree on chip, not only in CPU interpret mode
fusedp = np.asarray(jax.jit(lambda d: sspec_fused(
    d, db=False, crop_rows=crop, route='pallas'))(dyn))
err_c = float(np.max(np.abs(chain - oracle)) / sc)
err_f = float(np.max(np.abs(fusedp - oracle)) / sc)
print('fused sspec on-chip vs f64 oracle:', err_f, '(chain:', err_c, ')')
assert err_f < max(2 * err_c, 1e-4), (err_f, err_c)
"

SYNTH_CODE="
import numpy as np
from scintools_tpu import obs
from scintools_tpu.parallel import PipelineConfig, run_pipeline
from scintools_tpu.sim import SynthSpec
obs.enable()
spec = SynthSpec(kind='arc', n_epochs=4, nf=64, nt=64, dt=10.0)
buckets = run_pipeline(config=PipelineConfig(lamsteps=True),
                       synthetic=spec)
(_, res), = buckets
eta = np.asarray(res.arc.eta)
assert eta.shape == (4,) and np.isfinite(eta).all(), eta
h2d = int(obs.counters()['bytes_h2d'])
# zero-H2D contract: the staged input is 4 epochs x 2 uint32 key words
# (8 bytes/epoch) — independent of the (nf, nt) grid
assert h2d == 4 * 2 * 4, ('bytes_h2d is not keys-only', h2d)
print('synthetic generate->analyse on chip ok; bytes_h2d =', h2d)
"

DEVMEM_CODE="
import numpy as np
from scintools_tpu import obs
from scintools_tpu.obs import devmem
from scintools_tpu.parallel import PipelineConfig, run_pipeline
from scintools_tpu.sim import SynthSpec
obs.enable()
spec = SynthSpec(kind='arc', n_epochs=4, nf=64, nt=64, dt=10.0)
run_pipeline(config=PipelineConfig(lamsteps=True), synthetic=spec)
g = obs.get_registry().gauges()
assert g.get('hbm_bytes_in_use', 0) > 0, ('hbm gauges missing', g)
assert g.get('hbm_bytes_limit', 0) > 0, ('hbm limit missing', g)
peaks = {k: v for k, v in g.items() if k.startswith('step_hbm_peak[')}
assert peaks, ('no step_hbm_peak recorded', sorted(g))
# the fenced step cannot run below its own residency: the measured
# peak must cover at least the generated dynspec batch (4x64x64 f32)
model_floor = 4 * 64 * 64 * 4
assert max(peaks.values()) >= model_floor, (peaks, model_floor)
print('devmem plane on chip ok:', {k: int(v) for k, v in peaks.items()},
      'in_use =', int(g['hbm_bytes_in_use']))
"

POOL_CODE="
import os, tempfile, time
from scintools_tpu import obs
from scintools_tpu.serve import ClaimHints, JobQueue, PoolConfig, \
    PoolController
from scintools_tpu.serve import pool as pool_mod
obs.enable()
qdir = tempfile.mkdtemp(prefix='scint_pool_gate_')
q = JobQueue(qdir)
tmp = tempfile.mkdtemp(prefix='scint_pool_gate_files_')
files = []
for i in range(8):
    fn = os.path.join(tmp, 'e%02d.bin' % i)
    open(fn, 'wb').write(bytes([i]) * 64)
    files.append(fn)
for f in files[:6]:
    q.submit(f, {'lamsteps': True}, lane='bulk')
for f in files[6:]:
    q.submit(f, {'lamsteps': True}, lane='interactive')
order = [e[3] for e in q._claim_order({'interactive': 2, 'bulk': 1})]
assert order[:3] == ['interactive', 'interactive', 'bulk'], order
jobs = q.claim('w', n=8, lease_s=30.0)
assert [j.lane for j in jobs[:2]] == ['interactive', 'interactive']
c = obs.counters()
assert c['lane_claims[interactive]'] == 2, c
assert c['lane_claims[bulk]'] == 6, c
sig = jobs[0].sig
for j in jobs:
    q.fail(j, 'gate requeue', transient=True, now=time.time() - 10)
hinted = q.claim('cold', n=8, lease_s=30.0,
                 hints=ClaimHints(elsewhere=frozenset([sig]),
                                  defer_s=3600.0))
assert hinted == [], hinted
assert obs.counters()['affinity_deferred'] >= 1
warm = q.claim('warm', n=8, lease_s=30.0,
               hints=ClaimHints(prefer=frozenset([sig])))
assert len(warm) == 8 and obs.counters()['affinity_hits'] == 8

class P:
    pid = 1
    def poll(self): return None
    def kill(self): pass
    def terminate(self): pass
ctl = PoolController(qdir, PoolConfig(min_workers=1, max_workers=2,
                                      cooldown_s=0.0),
                     spawn=lambda wid: P())
st = ctl.poll_once()
assert st['decision'] == 'spawn_to_min', st
st = ctl.poll_once()   # leased depth 8, no drain -> bp 1 -> scale up
assert st['decision'] == 'scale_up', st
assert pool_mod.read_pool_status(qdir)['stats']['scale_up'] == 1
assert os.path.exists(pool_mod.hints_path(qdir))
print('pool gate ok: fair-claim + hints + scale decisions',
      {k: int(v) for k, v in obs.counters().items()
       if 'lane' in k or 'affinity' in k or k.startswith('pool_')})
"

STREAM_CODE="
import numpy as np, tempfile
from scintools_tpu import obs
from scintools_tpu.sim import thin_arc_epoch
from scintools_tpu.stream import FeedWriter, StreamSession
obs.enable()
W, HOP = 64, 16
ep = thin_arc_epoch(nf=64, nt=W + 6 * HOP, seed=1)
dyn = np.asarray(ep.dyn)
feed = tempfile.mkdtemp(prefix='scint_stream_gate_')
fw = FeedWriter(feed, freqs=ep.freqs, dt=ep.dt, name='gate')
sess = StreamSession(feed, {'lamsteps': True, 'arc_numsteps': 200,
                            'lm_steps': 6}, window=W, hop=HOP)
ticks, i, m0 = 0, 0, None
while i < dyn.shape[1]:
    fw.append(dyn[:, i:i + HOP]); i += HOP
    n = len(sess.poll())
    if n and m0 is None:    # first (compiling) tick done: snapshot
        m0 = obs.counters().get('jit_cache_miss', 0)
    ticks += n
fw.finalize()
ticks += len(sess.poll())
warm_miss = obs.counters().get('jit_cache_miss', 0) - m0
assert ticks >= 6, ('too few ticks', ticks)
assert warm_miss == 0, ('warm stream ticks recompiled', warm_miss)
lat = sorted(sess.tick_latencies)[len(sess.tick_latencies) // 2]
print('stream gate ok on chip: ticks=', ticks, 'warm_miss=0',
      'tick_p50_s=', round(lat, 4), 'lag_s=', round(sess.lag_s(), 4))
"

INC_CODE="
import numpy as np, tempfile
import bench
from scintools_tpu import obs
from scintools_tpu.sim import thin_arc_epoch
from scintools_tpu.stream import FeedWriter, StreamSession
obs.enable()

# resync parity first, at a small geometry: the incremental session's
# every-4th-tick exact resync must reproduce the full-recompute row
# byte-for-byte ON THIS CHIP (tier-1 pins the same contract on CPU;
# split_programs pinned on both so the fitter program is shared)
W, HOP = 64, 16
opts = {'lamsteps': True, 'arc_numsteps': 200, 'lm_steps': 6,
        'split_programs': True}
ep = thin_arc_epoch(nf=64, nt=W + 8 * HOP, seed=2)
dyn = np.asarray(ep.dyn)
rows = {}
for mode in ('full', 'inc'):
    feed = tempfile.mkdtemp(prefix='scint_inc_gate_')
    fw = FeedWriter(feed, freqs=ep.freqs, dt=ep.dt, name='gate')
    sess = StreamSession(
        feed, opts, window=W, hop=HOP,
        incremental=(mode == 'inc'),
        resync_every=4 if mode == 'inc' else None)
    out, i = [], 0
    while i < dyn.shape[1]:
        fw.append(dyn[:, i:i + HOP]); i += HOP
        out += sess.poll()
    fw.finalize()
    out += sess.poll()
    rows[mode] = out
assert len(rows['full']) == len(rows['inc'])
checked = 0
for rf, ri in zip(rows['full'], rows['inc']):
    if ri.get('incremental'):
        continue           # sliding ticks carry the drift budget, not parity
    for k in ('tau', 'dnu', 'betaeta'):
        a, b = rf.get(k), ri.get(k)
        assert (a == b) or (a != a and b != b), (rf['tick'], k, a, b)
    checked += 1
assert checked >= 3, ('too few resync/full ticks compared', checked)

# then the warm-tick A/B at a representative geometry: the sliding
# O(hop) update must beat the full recompute >= 3x at p50 with the
# zero-recompile contract intact in BOTH modes (acceptance criterion)
rec = bench.stream_throughput(n_ticks=12, window=512, nf=256)
inc = rec['incremental']
assert 'error' not in inc, inc
assert rec['warm_jit_cache_miss'] == 0, rec
assert inc['warm_jit_cache_miss'] == 0, inc
assert inc['inc_ticks'] >= 8 and inc['resyncs'] >= 1, inc
sp = rec['speedup_p50']
assert sp >= 3.0, ('incremental warm tick speedup below 3x', sp)
print('incremental gate ok on chip: resync_parity ticks=', checked,
      'speedup_p50=', round(sp, 2),
      'inc_p50_s=', round(inc['tick_latency_s']['p50'], 5),
      'full_p50_s=', round(rec['tick_latency_s']['p50'], 5),
      'inc_ticks=', inc['inc_ticks'], 'resyncs=', inc['resyncs'])
"

SLO_CODE="
import json, os, tempfile, time
from scintools_tpu import faults, obs
from scintools_tpu.obs import slo
from scintools_tpu.sim import thin_arc_epoch
from scintools_tpu.stream import FeedWriter, StreamSession
from scintools_tpu.utils.store import ResultsStore
obs.enable()
qdir = tempfile.mkdtemp(prefix='scint_slo_gate_')
json.dump([{'name': 'gate-fresh', 'kind': 'stream_lag_s',
            'key': 'gate', 'threshold_s': 0.25, 'fast_window_s': 1.5,
            'slow_window_s': 3.0, 'min_hold_s': 0.3}],
          open(slo.slo_path(qdir), 'w'))
specs = slo.load_slos(qdir)
ev = slo.SloEvaluator(specs)
engine = slo.AlertEngine(ResultsStore(os.path.join(qdir, 'results')))
# window >> appended samples: the gate never ticks (no device work) —
# it exercises the JUDGMENT plane, not the recompute plane
ep = thin_arc_epoch(nf=8, nt=64, seed=0)
import numpy as np
dyn = np.asarray(ep.dyn)
feed = tempfile.mkdtemp(prefix='scint_slo_feed_')
fw = FeedWriter(feed, freqs=ep.freqs, dt=ep.dt, name='gate')
sess = StreamSession(feed, {'lamsteps': True}, window=4096, hop=4096)
fw.append(dyn[:, :4]); sess.poll()          # consume: lag ~ 0
def judge():
    now = time.time()
    ev.observe(obs.get_registry().hists(), now=now)
    return {r['slo']: r for r in engine.step(ev.statuses(now=now),
                                             now=now)}
# inject the freshness breach: stream.poll faults block consumption
# while the per-poll lag sample keeps accumulating breach evidence
faults.inject('stream.poll', faults.FaultSpec(kind='transient',
                                              times=4))
fw.append(dyn[:, 4:8])
states = []
for _ in range(4):
    time.sleep(0.45)
    try:
        sess.poll()
    except faults.TransientError:
        pass
    states.append(judge()['gate-fresh']['state'])
assert 'pending' in states and states[-1] == 'firing', states
# durability: a FRESH store (new process's view of the same dir)
# reads the firing row back — the newest-wins contract the SIGKILL
# tier-1 test (tests/test_slo.py) proves across a real kill
rows = slo.read_alerts(qdir)
assert rows and rows[0]['state'] == 'firing', rows
# fault window exhausted -> consumption resumes on fresh appends ->
# lag collapses, the breach window ages out, the alert resolves
deadline = time.time() + 20.0
state = 'firing'
while state != 'resolved' and time.time() < deadline:
    fw.append(dyn[:, :2])
    sess.poll()
    time.sleep(0.3)
    state = judge()['gate-fresh']['state']
assert state == 'resolved', state
hist = [s for _, s in slo.read_alerts(qdir)[0]['history']]
assert hist[-3:] == ['pending', 'firing', 'resolved'], hist
print('slo gate ok: breach -> pending -> firing -> resolved,',
      'durable rows readable across stores')
"

FSCK_CODE="
import os, tempfile, time
from scintools_tpu.serve import fsck
from scintools_tpu.serve.queue import JobQueue
qdir = tempfile.mkdtemp(prefix='scint_fsck_gate_')
q = JobQueue(qdir, max_retries=5, backoff_s=0.0)
# seed three catalog classes: dead-pid atomic-write litter, a torn
# segment tail, and an expired lease
ep = os.path.join(qdir, 'gate.dat')
open(ep, 'w').write('gate\n' * 4)
q.submit(ep, {}, lane='bulk')
assert q.claim('w1', 1, lease_s=0.5)
q.results.put_new_buffered('rowk', {'x': 1.0})
q.results.flush()
segdir = q.results.segments.dir
seg = [n for n in os.listdir(segdir) if n.endswith('.seg')][0]
litter = os.path.join(qdir, 'control', 'hints.json.tmp999999')
open(litter, 'w').write('{half')
os.utime(litter, (time.time() - 600,) * 2)
with open(os.path.join(segdir, seg), 'r+b') as fh:
    fh.truncate(os.path.getsize(os.path.join(segdir, seg)) - 12)
future = time.time() + 3600.0
dry = fsck.run_fsck(qdir, now=future)
want = {'orphan_tmp', 'torn_segment', 'expired_lease'}
assert set(dry['classes']) == want, dry['classes']
rep = fsck.run_fsck(qdir, repair=True, now=future)
assert rep['clean'], rep['findings']
again = fsck.run_fsck(qdir, now=future)
assert again['clean'] and not again['findings'], again['findings']
assert fsck.read_fsck_status(qdir)['clean']
print('fsck gate ok: seeded', sorted(want), 'detected, repaired,',
      'second audit clean')
"

INFER_CODE="
import dataclasses
import numpy as np
from scintools_tpu import obs
from scintools_tpu.infer import InferSpec, infer_campaign
from scintools_tpu.sim import campaign
obs.enable()
spec = campaign.SynthSpec(kind='acf', n_epochs=8, nf=128, nt=128,
                          dt=8.0, df=0.5, tau_s=48.0, dnu_mhz=2.0)
out = infer_campaign(spec, InferSpec())
tru = campaign.injected_truth(spec)
te = float(abs(np.asarray(out['params']['tau']).mean()
               - np.mean(tru['tau'])) / np.mean(tru['tau']))
de = float(abs(np.asarray(out['params']['dnu']).mean()
               - np.mean(tru['dnu'])) / np.mean(tru['dnu']))
assert te < 0.10, ('tau recovery off on chip', te)
assert de < 0.15, ('dnu recovery off on chip', de)
assert int(np.asarray(out['converged']).sum()) == 8, out['converged']
m0 = obs.counters().get('jit_cache_miss', 0)
warm = dataclasses.replace(spec, n_epochs=5, seed=7)
infer_campaign(warm, InferSpec(), opt_steps_rt=200)
miss = obs.counters().get('jit_cache_miss', 0) - m0
assert miss == 0, ('warm infer rerun recompiled', miss)
print('infer gate ok on chip: tau_rel_err=', round(te, 4),
      'dnu_rel_err=', round(de, 4), 'warm_miss=0')
"

SEARCH_CODE="
import dataclasses
import numpy as np
from scintools_tpu import obs
from scintools_tpu.search import SearchSpec, search_campaign
from scintools_tpu.sim import campaign
obs.enable()
spec = campaign.SynthSpec(kind='arc', n_epochs=6, nf=128, nt=128,
                          dt=10.0, df=0.5, seed=11, arc_frac=0.8)
srch = SearchSpec(n_trials=1024, top_k=16, decim=8)
out = search_campaign(spec, srch, {'lamsteps': False})
tru = campaign.injected_truth(spec, lamsteps=False)['eta']
rel = np.abs(np.asarray(out['eta']) - tru) / tru
assert float(rel.max()) < 0.10, ('curvature recovery off on chip',
                                 out['eta'], tru)
naive = search_campaign(spec, srch, {'lamsteps': False}, naive=True)
nrel = np.abs(np.asarray(naive['eta']) - tru) / tru
assert float(nrel.max()) < 0.10, ('exhaustive reference off on chip',
                                  naive['eta'], tru)
g = obs.get_registry().gauges()
pb = [v for k, v in g.items() if k.startswith('step_bytes[search.step')]
nb = [v for k, v in g.items() if k.startswith('step_bytes[search.naive')]
assert pb and nb, ('search cost analysis missing on chip', sorted(g))
assert pb[0] <= 0.5 * nb[0], ('pruned path moves too many bytes',
                              pb[0], nb[0])
m0 = obs.counters().get('jit_cache_miss', 0)
warm = dataclasses.replace(spec, n_epochs=5, seed=7)
search_campaign(warm, srch, {'lamsteps': False}, top_k_rt=8,
                decim_rt=16)
miss = obs.counters().get('jit_cache_miss', 0) - m0
assert miss == 0, ('warm search rerun recompiled', miss)
print('search gate ok on chip: eta_rel_err=', round(float(rel.max()),
      4), 'bytes_ratio=', round(float(pb[0] / nb[0]), 3),
      'warm_miss=0')
"

SPLIT_CODE="
import numpy as np
from scintools_tpu import obs
from scintools_tpu.parallel import PipelineConfig, run_pipeline
from scintools_tpu.data import DynspecData
obs.enable()
rng = np.random.default_rng(0)
def mk(nf, nt, b):
    freqs = np.linspace(1300.0, 1300.0 + 0.5 * nf, nf)
    times = np.arange(nt) * 10.0
    return [DynspecData(dyn=rng.standard_normal((nf, nt)) + 5.0,
                        freqs=freqs, times=times, mjd=58000.0 + i,
                        df=0.5, dt=10.0, bw=0.5 * nf,
                        freq=float(freqs.mean()), tobs=10.0 * nt,
                        name='e%d' % i) for i in range(b)]
cfg = PipelineConfig(lamsteps=True, split_programs=True)
run_pipeline(mk(64, 64, 2), cfg)     # warm the fitter (back) programs
c0 = dict(obs.counters())
run_pipeline(mk(96, 48, 2), cfg)     # never-seen (nf, nt)
c1 = dict(obs.counters())
bm = (c1.get('jit_cache_miss[pipeline.back]', 0)
      - c0.get('jit_cache_miss[pipeline.back]', 0))
fm = (c1.get('jit_cache_miss[pipeline.front]', 0)
      - c0.get('jit_cache_miss[pipeline.front]', 0))
assert bm == 0, ('novel shape recompiled the fitter back-end', bm)
assert fm >= 1, ('front-end should have (cheaply) recompiled', fm)
print('split gate ok on chip: novel shape back_miss=0, front_miss=',
      fm)
"

NUDFT_CODE="
import numpy as np, jax, jax.numpy as jnp
from scintools_tpu.ops.nudft import _nudft_numpy, _r_grid, nudft
rng = np.random.default_rng(1)
B, nt, nf = 4, 512, 256
dyn = rng.standard_normal((B, nt, nf)).astype(np.float32)
freqs = np.linspace(1300.0, 1500.0, nf)
fscale = freqs / freqs[nf // 2]
tsrc = np.arange(nt, dtype=np.float64)
r0, dr, nr = _r_grid(nt)
f = jax.jit(jax.vmap(lambda d: jnp.real(nudft(d, fscale, backend='jax'))**2
                     + jnp.imag(nudft(d, fscale, backend='jax'))**2))
a = np.asarray(f(dyn))
w = _nudft_numpy(dyn[0].astype(np.float64), fscale, tsrc, r0, dr, nr)
pw = np.abs(w) ** 2
err = float(np.max(np.abs(a[0] - pw)) / pw.max())
print('vmapped einsum nudft vs f64 oracle, scaled err:', err)
assert err < 2e-4, ('bf16 MXU lowering is back?', err)
"

echo "== probe =="
probe || { echo "tunnel unreachable; aborting"; exit 1; }

# STAGE ORDER = MARGINAL EVIDENCE PER HEALTHY MINUTE.  The tunnel's
# healthy windows are minute-scale (the 2026-08-02 window lasted just
# long enough for the bench before wedging at the next stage), so:
#   1. headline bench         (round's #1 deliverable; landed 2026-08-02,
#                              a repeat in a healthier window raises it)
#   2-3. pallas gates (row-scrunch + fused sspec) + synthetic-lane
#        zero-H2D smoke + nudft bf16 guard (sub-minute CORRECTNESS
#        verdicts that validate every capture below; CPU CI cannot
#        see the Mosaic lowerings, and the on-chip bytes_h2d assert
#        proves the key-fed program stages no dynspec bytes)
#   4. f32 on-chip budget     (published figures' only missing capture)
#   5. all five configs       (configs 1-3 have no on-chip record)
#   6. B=256 stage profile    (repeat-healthy-flight evidence)
#   7. B=1024 auto-route A/B  (repeat-healthy-flight evidence)
#   8. arc-tail A/B           (fast-tail on-chip verdict)
#   9. pallas prove-or-remove A/B (perf regression guard; has a round-4
#      verdict already, so it rides last)
echo "== headline bench =="
# a bench that wedges or falls back to CPU exits nonzero, and every
# hour-scale stage below is then doomed (wedge) or suspect — abort
# rather than spending the window on a dead tunnel.  BUT a bench
# failure can also be a genuine repo regression (not weather), so
# before exiting nonzero still attempt the two SUB-MINUTE correctness
# gates: they cost ~a minute against a 2400 s bench budget, and their
# verdicts distinguish "tunnel dead" (both hang/fail to init) from
# "regression" (gates pass, bench genuinely broken) — ADVICE r5.
if ! timeout -k 10 2400 python bench.py > "$stage_out" 2>&1; then
  tail -12 "$stage_out"
  echo "headline bench FAILED (unfiltered tail above)"
  echo "== post-failure triage: sub-minute correctness gates =="
  triage "pallas lowering check" 600 python -u -c "$PALLAS_CODE"
  pallas_rc=$?
  triage "nudft einsum accuracy check" 600 python -u -c "$NUDFT_CODE"
  nudft_rc=$?
  if [ "$pallas_rc" -eq 0 ] && [ "$nudft_rc" -eq 0 ]; then
    echo "triage verdict: correctness gates PASS on chip — the bench" \
         "failure looks like a genuine regression, not tunnel weather"
  else
    echo "triage verdict: correctness gates also failing — consistent" \
         "with a wedged tunnel, not a repo regression"
  fi
  exit 1
fi
{ grep -v -E 'INFO|WARN|axon_|Logging|E0000' "$stage_out" || true; } \
  | tail -2

echo "== pallas row-scrunch lowers on chip =="
# the fused row-scrunch kernel is the arc fitter's on-chip auto route
# since round 4 (wire verdict, 3.5x the scan); CI validates it in
# interpret mode only, so this is the real-Mosaic correctness gate.
# Gated on python's EXIT STATUS (the rel-err line prints before the
# assert, so grepping for it cannot detect a failure).  (The Pallas
# NUDFT that was also gated here was deleted in round 4: 0.44x the
# production einsum — benchmarks/pallas_ab.py.)
gated "pallas lowering check" 600 2 python -u -c "$PALLAS_CODE"

echo "== fused sspec kernels lower on chip =="
# the --fused-sspec route (ops/sspec_pallas: prologue + crop-split DFT
# + tiled epilogue) is opt-in until its A/B wires it; this sub-minute
# gate proves the real-Mosaic lowering AND its oracle numerics before
# the hour-scale stages spend the window (CPU CI sees interpret only)
gated "fused sspec lowering check" 600 2 python -u -c "$FUSED_CODE"

echo "== synthetic lane: on-device generate->analyse + zero-H2D =="
# the zero-H2D campaign route (run_pipeline(synthetic=...)): one
# sub-minute smoke proves the fused generate->analyse program lowers
# and runs on real silicon AND that the staged traffic is keys-only
# (the bytes_h2d counter asserts O(keys), independent of nf x nt)
gated "synthetic lane check" 600 2 python -u -c "$SYNTH_CODE"

echo "== devmem plane: HBM gauges + per-signature peak on chip =="
# the device-memory plane (obs/devmem, ISSUE 12): CPU CI only proves
# the degraded no-op path (memory_stats() is None there), so this
# sub-minute gate is where the live plane is validated — gauges
# nonzero and the measured per-signature peak at least the staged
# batch's model bytes
gated "devmem plane check" 600 2 python -u -c "$DEVMEM_CODE"

echo "== pool controller: QoS lanes + affinity hints + scale math =="
# the fleet pool controller (ISSUE 13): weighted-fair lane claim
# order, hint-driven affinity deferral/hits, and the backpressure
# scale-up decision, exercised against the real queue dir on this
# host — sub-minute, no worker subprocesses spawned (a fake Popen
# stands in; the capacity lane SCINT_BENCH_FLEET=1 runs real ones)
gated "pool controller check" 600 2 python -u -c "$POOL_CODE"

echo "== program splitting: novel shape reuses warm fitter programs =="
# compile-unit splitting (ISSUE 14): warm one shape's fitter (back)
# programs, then hit a never-seen (nf, nt) — the shape-stable back-end
# must serve warm (jit_cache_miss[pipeline.back] == 0) while only the
# shape-volatile front-end recompiles.  CPU tier-1 proves the same
# contract; this proves it against the real TPU compiler/cache.
gated "split programs check" 600 2 python -u -c "$SPLIT_CODE"

echo "== streaming ingest: warm fixed-signature ticks on chip =="
# the ISSUE 15 streaming plane: a live feed consumed chunk-by-chunk
# through the device-resident ring must tick on ONE warm compiled
# window signature (jit_cache_miss stays 0 after the first tick) —
# CPU tier-1 pins the same contract; this proves it against the real
# TPU compiler, and prints the on-chip per-tick latency the live
# monitoring scenario actually gets
gated "streaming smoke check" 600 2 python -u -c "$STREAM_CODE"

echo "== incremental ticks: resync parity + warm speedup on chip =="
# the ISSUE 17 incremental hot path, sub-minute: (a) every resync tick
# of an incremental session reproduces the full-recompute row exactly
# on this chip, and (b) the bench A/B lane at a representative
# (nf=256, W=512) shows the O(hop) sliding update >= 3x faster at p50
# than full recompute with jit_cache_miss == 0 across the warm ticks
# of BOTH modes — the consolidated flight picks the verdict up free
gated "incremental stream check" 600 2 python -u -c "$INC_CODE"

echo "== slo plane: injected lag breach fires + resolves durably =="
# the ISSUE 16 judgment plane, end to end in under a minute: a
# stream.poll chaos fault (faults.py) stalls consumption, the per-poll
# lag samples burn the freshness budget, the durable alert walks
# pending -> firing (min-hold hysteresis) and back to resolved once
# the fault window exhausts — with the rows readable through a fresh
# store, the crash-survival contract tier-1 proves across a SIGKILL
gated "slo smoke check" 600 2 python -u -c "$SLO_CODE"

echo "== fsck: seeded corruption detected, repaired, converged =="
# the ISSUE 20 auditor, end to end in seconds: a queue dir seeded
# with dead-pid tmp litter, a torn segment tail and an expired lease
# is flagged on a dry run, healed by --repair, and a second dry run
# reports clean — the crash-point sweep itself is tier-1
# (tests/test_crashpoints.py); this proves the repair loop converges
# in the flight's environment too
gated "fsck repair convergence check" 300 2 python -u -c "$FSCK_CODE"

echo "== differentiable inference: closed-loop gradient fit on chip =="
# the ISSUE 18 inference plane, sub-minute: an acf campaign's injected
# (tau, dnu) truth must be recovered by gradient descent through the
# compiled simulator within the closed-loop budgets (10%/15% batch
# mean, every lane converged), and a warm rerun at a different batch
# size / seed / runtime step budget must serve from the SAME compiled
# program (jit_cache_miss == 0) — CPU tier-1 pins both contracts
# (tests/test_infer.py); this proves them against the real TPU
# compiler and its autodiff lowering
gated "differentiable inference check" 600 2 python -u -c "$INFER_CODE"

echo "== acceleration search: closed-loop matched filter on chip =="
# the ISSUE 19 search plane, sub-minute: an arc campaign's injected
# curvature must rank top-1 through the pruned coarse-to-fine path
# (within the 10% trial-grid tolerance), pruned verdicts must match
# the exhaustive reference, the measured pruned-program bytes must
# stay under half the naive pass (the cost_analysis bar the CPU
# tier-1 pins tighter in tests/test_search.py), and a warm rerun at a
# different n_epochs + runtime K/decim budget must serve from the
# SAME compiled program (jit_cache_miss == 0) — proved here against
# the real TPU compiler and its FFT/top_k lowering
gated "acceleration search check" 600 2 python -u -c "$SEARCH_CODE"

echo "== nudft einsum on-chip accuracy (bf16-lowering guard) =="
# the round-4 A/B caught the vmapped einsum NUDFT silently lowering to
# bf16 MXU passes (2e-3 scaled error); _nudft_jax_reim now pins
# Precision.HIGHEST.  CPU CI cannot see this (einsum precision is exact
# there), so the on-chip oracle check lives here permanently.
gated "nudft einsum accuracy check" 600 2 python -u -c "$NUDFT_CODE"

echo "== f32 numerics budget on chip =="
# hardware tier of the f32 drift suite: chip-f32 vs host-f64 oracle
# with degenerate-profile awareness (a weak-scattering epoch whose two
# arc lobes agree to <0.1 dB may legitimately flip under f32 — see
# benchmarks/f32_budget_onchip.py).  CI tier: tests/test_f32_budget.py.
gated "f32 on-chip check" 1800 4 python benchmarks/f32_budget_onchip.py

echo "== all five configs =="
gated "all five configs" 3600 6 python benchmarks/all_configs.py

echo "== stage profile (bench shape) =="
gated "B=256 stage profile" 1800 13 python benchmarks/profile_stages.py \
  --b 256 --iters 5

echo "== auto-route A/B at the bench batch size (B=1024) =="
# the arc_scrunch_rows=-1 / scint_cuts=auto defaults were extrapolated
# from B=256; re-validate them at the size bench.py actually runs.
# ONE invocation (one jax init, one 512 MB batch): profile_stages
# exits nonzero if the row filter matches nothing (renamed rows must
# fail loudly, not skip the A/B)
gated "B=1024 auto-route A/B" 3600 8 python benchmarks/profile_stages.py \
  --b 1024 --iters 3 --only "rc=,cuts,lm_steps"

echo "== arc measurement-tail A/B (exact vs fast, simulated arcs) =="
# the opt-in arc_tail="fast" knob ships only while its numerics hold:
# every healthy lane's eta within the fit's own etaerr of the exact
# tail, NaN quarantine identical (benchmarks/arc_tail_ab.py exits
# nonzero on a numerics-mismatch verdict)
gated "arc tail A/B" 1800 2 python benchmarks/arc_tail_ab.py --b 256 --iters 5

echo "== pallas prove-or-remove A/B =="
# regression guard for the wired row-scrunch route (docs/roadmap.md:
# wire a kernel only if it beats the production path by >= 1.15x with
# matching numerics; otherwise it gets deleted) — now three verdicts:
# row_scrunch (wired; keep-off = exit 3), sspec_fused and nudft_pallas
# (opt-in; their wire/keep-off lines decide whether the knobs graduate
# to auto defaults next round)
gated "pallas A/B" 1800 8 python benchmarks/pallas_ab.py --iters 10
