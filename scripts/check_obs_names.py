#!/usr/bin/env python
"""Repo lint: every literal counter/gauge/span/event/histogram name in
``scintools_tpu/`` must be registered in the closed catalog
(``scintools_tpu/obs/names.py``).

Why: a typo'd metric name — ``obs.inc("job_retires")`` — silently
creates a new series.  Nothing raises; the real counter stays zero,
`trace report`'s curated sections and the fleet rollup never see the
typo'd one, and every tier-1 assertion against the intended name reads
a stale 0.  The catalog turns that silence into a lint failure.

Mechanics (AST, not regex): walk every ``.py`` under the package for
``Call`` nodes whose func is ``obs.inc`` / ``obs.gauge`` / ``obs.span``
/ ``obs.observe`` / ``obs.event`` / ``obs.traced`` — or the
``core.``-spelled equivalents the obs package uses internally — and
check the FIRST argument:

* a string literal: exact membership (bracketed ``family[...]`` names
  check their family);
* an f-string: its leading constant prefix must extend a registered
  family, span prefix, or name (conservative prefix check);
* anything fully dynamic (a Name, a BinOp): skipped — the lint exists
  for the literal 95 %, and dynamic names are built from registered
  prefixes at their call sites.

Enforced in tier-1 via tests/test_obs_names.py.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "scintools_tpu")

# out-of-package emitters: repo-root bench.py stamps obs names from its
# env-gated lanes (SCINT_BENCH_SLO's disarmed-path probe among them) —
# a typo there silently benchmarks a nonexistent series
EXTRA_FILES = (os.path.join(REPO, "bench.py"),)

# the obs API surface whose first argument is a series name, and the
# module aliases it is reached through in this codebase
FUNCS = ("inc", "gauge", "span", "observe", "event", "traced")
OWNERS = ("obs", "core")


def _is_registered(func: str, literal: str, prefix_only: bool) -> bool:
    sys.path.insert(0, REPO)
    try:
        from scintools_tpu.obs import names
    finally:
        sys.path.pop(0)
    return names.is_registered(func, literal, prefix_only=prefix_only)


def _name_arg(call: ast.Call):
    """(literal, prefix_only) for the call's first arg, or None when
    the name is fully dynamic."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value, True
    return None


def find_unregistered(path: str) -> list:
    """(line, func, name) for every unregistered literal obs name."""
    with open(path, encoding="utf-8") as fh:
        try:
            tree = ast.parse(fh.read(), filename=path)
        except SyntaxError as e:  # pragma: no cover - unparseable file
            return [(e.lineno or 0, "parse", str(e))]
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in FUNCS
                and isinstance(func.value, ast.Name)
                and func.value.id in OWNERS):
            continue
        got = _name_arg(node)
        if got is None:
            continue
        literal, prefix_only = got
        if not _is_registered(func.attr, literal, prefix_only):
            hits.append((node.lineno, func.attr, literal))
    return hits


def check_tree(pkg_dir: str = PKG, extra_files=EXTRA_FILES) -> list:
    """All offending (relpath, line, func, name) under ``pkg_dir``
    plus the registered out-of-package emitters (``extra_files``)."""
    offenders = []
    paths = []
    for root, _dirs, files in os.walk(pkg_dir):
        for name in sorted(files):
            if name.endswith(".py"):
                paths.append(os.path.join(root, name))
    paths.extend(p for p in (extra_files or ()) if os.path.isfile(p))
    for path in paths:
        for line, func, literal in find_unregistered(path):
            rel = (os.path.relpath(path, pkg_dir)
                   if path.startswith(pkg_dir + os.sep)
                   else os.path.basename(path))
            offenders.append((rel, line, func, literal))
    return offenders


def main() -> int:
    offenders = check_tree()
    if offenders:
        print("unregistered observability names (add to "
              "scintools_tpu/obs/names.py or fix the typo):")
        for rel, line, func, literal in offenders:
            print(f"  {rel}:{line}: obs.{func}({literal!r})")
        return 1
    print("obs name catalog: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
