"""Tiny single-op device probe shared by tpu_watch.sh / tpu_recheck.sh.

Prints ``probe platform=<p> sum=<s>`` and, ONLY when the backend is a
real TPU and the op computed correctly, the success marker
``tpu alive`` — a silent CPU fallback must never greenlight the
hour-scale "on-chip" capture on the wrong device.  Callers wrap this
in ``timeout -k <grace> <t>`` (a wedged tunnel claim hangs forever and
ignores SIGTERM) and grep for the marker.
"""

import numpy as np

import jax
import jax.numpy as jnp

s = float(np.asarray(jnp.sum(jnp.ones((64, 64)))))
print("probe platform=%s sum=%s" % (jax.devices()[0].platform, s))
if jax.devices()[0].platform in ("tpu", "axon") and s == 4096.0:
    print("tpu alive")
