"""Generate examples/arc_modelling.ipynb — the runnable notebook form of
examples/arc_modelling.py (the reference ships arc_modelling.ipynb whose
data directory is missing, so it cannot run; ours runs on committed
simulated data end-to-end).

Usage: python scripts/make_notebook.py
"""

import os
import sys

import nbformat as nbf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MD = [
    """# Scintillation arc modelling — scintools-tpu walkthrough

The reference's `arc_modelling.ipynb` (J0437-4715, Reardon et al. 2019)
rebuilt on **simulated, committed data** so every cell actually runs.
Workflow: simulate → process → measure arc curvature → sum epochs →
curvature-normalise → scintillation parameters → annual curvature model.

Backends: every step runs on the `numpy` backend (bit-matching the
reference) or the `jax` backend (jit/vmap on TPU); `backend="auto"`
picks jax when an accelerator is attached.""",

    """## 1. Simulate an observing epoch

Anisotropic Kolmogorov phase screen (axial ratio 2, orientation 30°),
seeded for determinism — the reference's `scint_sim.Simulation`
(scint_sim.py:20) as a jit-able propagator.""",

    """## 2-3. Process and inspect

`Dynspec` keeps the reference's lazy calc→fit→plot UX on top of pure
functional kernels: trim → refill → ACF → λ-resample → secondary
spectrum, then bandpass correction.""",

    """## 4. Measure the arc curvature

`fit_arc` (norm_sspec method): curvature-normalise, fold the fdop arms,
smooth, peak-find, parabola fit with a noise-walk error bar —
numerically identical to the reference chain (see
tests/test_fit.py::test_fit_arc_bit_matches_reference_end_to_end).
The theta-theta cross-check (beyond-reference) measures the same
spectrum by eigenvalue concentration: tight agreement on sharp
anisotropic arcs, same-order on diffuse epochs like this one (the
power profile tracks the power-weighted mean curvature, the
concentration sweep the sharpest substructure).""",

    """### 4b. Accuracy gate: a planted arc with closed-form curvature

The diffuse-epoch spread above is screen physics, not estimator
freedom — so pin BOTH estimators to ground truth on a synthetic
thin-arc epoch whose curvature is known in closed form
(`sim.synth.thin_arc_betaeta`).  Theta-theta lands within a few
percent of truth; the power profile carries a documented 10–45%
power-weighted envelope bias on this epoch type (this is the bound
`tests/test_example.py` enforces).""",

    """## 5. Sum epochs

`+` concatenates in time with the MJD gap zero-filled
(dynspec.py:47-97) and the summed spectrum is re-measured.""",

    """## 6. Curvature-normalised secondary spectrum""",

    """## 7. Scintillation parameters and the annual curvature model

tau_d/dnu_d from the ACF cuts, then the thin-screen annual curvature
prediction from the built-in analytic ephemeris (no astropy needed).""",

    """## 8. Wavefield retrieval (holography)

No reference analogue: chunked theta-theta holography reconstructs the
COMPLEX scattered E-field from the dynamic spectrum.  A strongly
anisotropic screen gives the thin arc the rank-1 model the method
needs; the field's own secondary spectrum then puts power at the
scattered images themselves (a sharp single parabola) instead of the
intensity spectrum's filled pairwise-difference manifold.""",

    """## 9. Posterior scintillation parameters (MCMC)

The reference's lmfit-emcee + corner option, rebuilt as a jitted
ensemble sampler (no lmfit/emcee/corner dependency): every
`get_scint_params` method accepts `mcmc=True`; the post-burn chain
lands on `ds.mcmc_chain` for corner export via
`plotting.plot_posterior`.""",

    """## 10. Real-format dirty data: the survey cleaning recipe

The committed psrflux fixture (`tests/data/J0000+0000_degraded.dynspec`)
carries the defects real survey data has and clean simulations don't:
dead band edges, a dropout gap, narrowband + impulsive RFI, a
drifting-gain channel, receiver gain drift and bandpass ripple.  The
chain below recovers the arc to ~2% of the clean-simulation truth —
note `zap(method="channels")`, the per-channel triage that catches the
drifting-gain channel pixel thresholds cannot (without it the arc
fitter quarantines; `tests/test_dirty_fixture.py` locks both
behaviours).""",
]

CODE = [
    # boot
    """import os, sys
sys.path.insert(0, os.path.abspath(".."))  # run from examples/
sys.path.insert(0, os.path.abspath("."))   # or from the repo root
from scintools_tpu.backend import honor_platform_env
honor_platform_env()
import numpy as np
import matplotlib.pyplot as plt
from scintools_tpu import Dynspec
from scintools_tpu.io import from_simulation
from scintools_tpu.sim import Simulation""",

    """sim = Simulation(mb2=2, ns=256, nf=256, ar=2, psi=30, dlam=0.25, seed=64)
data = from_simulation(sim, freq=1400.0, dt=8.0)
data.info_str()""",

    """ds = Dynspec(data=data, process=True, lamsteps=True)
ds.correct_band()
ds.calc_sspec(lamsteps=True)
ds.plot_dyn(display=False);""",

    """ds.fit_arc(lamsteps=True, numsteps=4000)
print(f"betaeta = {ds.betaeta:.3f} +/- {ds.betaetaerr:.3f}")
ds.plot_sspec(plotarc=True, display=False)
saved = (ds.betaeta, ds.betaetaerr)
tt = ds.fit_arc(method="thetatheta", lamsteps=True,
                etamin=ds.betaeta / 5, etamax=ds.betaeta * 5, numsteps=128)
ds.betaeta, ds.betaetaerr = saved  # later cells normalise by the
#                                    power-profile measurement
print(f"theta-theta cross-check: {float(tt.eta):.3f} +/- {float(tt.etaerr):.3f}");""",

    """from scintools_tpu.sim import thin_arc_epoch
from scintools_tpu.sim.synth import thin_arc_betaeta

sharp = Dynspec(data=thin_arc_epoch(nf=96, nt=96, seed=23),
                process=False, lamsteps=True)
truth = thin_arc_betaeta(sharp.freqs)
sharp.fit_arc(lamsteps=True, numsteps=2000)
ns_planted = float(sharp.betaeta)
tt_sharp = sharp.fit_arc(method="thetatheta", lamsteps=True,
                         etamin=truth / 3, etamax=truth * 3, numsteps=128)
print(f"planted truth {truth:.3f}  theta-theta {float(tt_sharp.eta):.3f}"
      f"  norm_sspec {ns_planted:.3f}")
assert abs(float(tt_sharp.eta) - truth) / truth < 0.10;""",

    """sim2 = Simulation(mb2=2, ns=256, nf=256, ar=2, psi=30, dlam=0.25, seed=65)
data2 = from_simulation(sim2, freq=1400.0, dt=8.0,
                        mjd=data.mjd + (data.tobs + 30.0) / 86400.0)
summed = Dynspec(data=data, process=False) + Dynspec(data=data2, process=False)
summed.refill()
summed.lamsteps = True
summed.fit_arc(lamsteps=True, numsteps=4000)
print(f"summed: betaeta = {summed.betaeta:.3f} +/- {summed.betaetaerr:.3f}")

# Campaign alternative (beyond the reference): instead of concatenating
# the DYNSPECS in time, stack the epochs' normalised power-vs-curvature
# PROFILES and measure once — weak-arc S/N grows as sqrt(epochs), and a
# whole campaign runs as one jit'd batch.  (The batched engine is the
# one jax-backed step in this walkthrough; a numpy-only install keeps
# every other cell runnable.)
try:
    from scintools_tpu import fit_arc_campaign
    camp = fit_arc_campaign([data, data2], numsteps=2000)
    print(f"campaign: betaeta = {float(camp.eta):.3f} "
          f"+/- {float(camp.etaerr):.3f}")
except ModuleNotFoundError:
    print("campaign stacking uses the batched jax engine "
          "(pip install scintools-tpu[tpu])")""",

    """from scintools_tpu.plotting import plot_norm_sspec
ns = ds.norm_sspec(maxnormfac=2, numsteps=1024)
plot_norm_sspec(ns, display=False);""",

    """from scintools_tpu.astro import get_earth_velocity, get_true_anomaly
from scintools_tpu.models.velocity import arc_curvature_model

sp = ds.get_scint_params()
print(f"tau_d = {ds.tau:.1f} s   dnu_d = {ds.dnu:.3f} MHz")

pars = {"T0": 50000.0, "PB": 5.741, "ECC": 0.0879, "A1": 3.3667,
        "OM": 1.0, "KIN": 137.6, "KOM": 207.0, "PMRA": 121.4,
        "PMDEC": -71.5, "d": 0.157, "s": 0.7}
mjds = 53000.0 + np.linspace(0, 365.25, 120)
nu = get_true_anomaly(mjds, pars)
v_ra, v_dec = get_earth_velocity(mjds, 1.2098, -0.8243)
eta_annual = arc_curvature_model(pars, nu, v_ra, v_dec)
fig, ax = plt.subplots(figsize=(8, 4))
ax.plot(mjds - 53000.0, eta_annual, "k-")
ax.set_xlabel("Days"); ax.set_ylabel(r"$\\eta$ (1/(m mHz$^2$))");""",

    """from scintools_tpu.plotting import plot_sspec, plot_wavefield

sim_h = Simulation(mb2=20, ns=192, nf=192, ar=10, psi=90, dlam=0.25,
                   seed=77)
ds_h = Dynspec(data=from_simulation(sim_h, freq=1400.0, dt=8.0),
               process=True)
ds_h.fit_arc(method="thetatheta", lamsteps=False, etamin=1e-3,
             etamax=10.0, numsteps=96)
wf = ds_h.retrieve_wavefield(chunk_nf=32, chunk_nt=32)
corr = np.corrcoef(np.asarray(ds_h.data.dyn, float).ravel(),
                   wf.model_dynspec.ravel())[0, 1]
print(f"eta = {ds_h.eta:.3f};  |E|^2 reconstruction corr = {corr:.2f}")
plot_wavefield(wf, display=False)
plot_sspec(wf.secspec(), eta=ds_h.eta, display=False);""",

    """from scintools_tpu.plotting import plot_posterior

sp_post = ds.get_scint_params(method="acf1d", mcmc=True)
print(f"posterior: tau = {sp_post.tau:.1f} +/- {sp_post.tauerr:.1f} s")
plot_posterior(ds.mcmc_chain, labels=["tau", "dnu", "amp", "wn"],
               display=False);""",

    """fixture = None
for root in (".", ".."):
    cand = os.path.join(root, "tests", "data", "J0000+0000_degraded.dynspec")
    if os.path.isfile(cand):
        fixture = cand
        break
if fixture:
    dirty = Dynspec(filename=fixture, process=False)
    dirty.trim_edges().zap(method="channels", sigma=4).zap(sigma=5) \\
         .refill().correct_band(frequency=True, time=True)
    dirty.fit_arc(lamsteps=True, numsteps=2000)
    print(f"dirty fixture: betaeta = {dirty.betaeta:.1f} "
          f"(clean-sim truth 266.0)")
    dirty.plot_dyn(display=False);""",
]


def main():
    import hashlib

    nb = nbf.v4.new_notebook()
    nb.metadata["kernelspec"] = {"name": "python3",
                                 "display_name": "Python 3",
                                 "language": "python"}
    cells = [nbf.v4.new_markdown_cell(MD[0]), nbf.v4.new_code_cell(CODE[0])]
    for md, code in zip(MD[1:], CODE[1:]):
        cells.append(nbf.v4.new_markdown_cell(md))
        cells.append(nbf.v4.new_code_cell(code))
    # deterministic cell ids (index+content hash): regenerating an
    # unchanged notebook must produce a byte-identical file, not id
    # churn; the index keeps ids unique even for identical cell sources
    # (duplicate ids are invalid nbformat)
    for i, c in enumerate(cells):
        c["id"] = hashlib.sha1(
            f"{i}:{c['source']}".encode()).hexdigest()[:12]
    nb.cells = cells
    out = os.path.join(REPO, "examples", "arc_modelling.ipynb")
    with open(out, "w") as f:
        nbf.write(nb, f)
    print(f"wrote {out} ({len(cells)} cells)")


if __name__ == "__main__":
    sys.exit(main())
