#!/usr/bin/env python
"""Build / unpack / verify the relocatable warm compile-cache artifact.

The cold-start fix's CI half (ROADMAP item 2): one machine runs
``scintools-tpu warmup --catalog`` over the closed shape-bucket ladder
(scintools_tpu.buckets) and packs the resulting ``SCINT_COMPILE_CACHE``
into a tarball keyed on (jax/jaxlib/backend versions, package source
fingerprint, catalog digest); every FRESH pod then unpacks it and
serves its first result in seconds instead of paying minutes of XLA
compilation (BENCH_r05: compile_s 324.68 vs measure_s 0.54).

Usage::

    # build: warm the catalog for these template epochs, then pack
    python scripts/build_warm_cache.py build --out warm_cache.tgz \
        templates/*.dynspec -- --lamsteps --batch 64

    # fresh pod: verify + unpack into SCINT_COMPILE_CACHE, then serve
    python scripts/build_warm_cache.py unpack warm_cache.tgz
    python scripts/build_warm_cache.py verify warm_cache.tgz

``build`` runs the warmup in a SUBPROCESS (a genuinely cold process, so
the packed cache contains everything a fresh consumer needs — including
entries this process would have satisfied from its in-memory jit
cache); everything after ``--`` is passed through to ``scintools-tpu
warmup`` verbatim (estimator flags, --batch, --mesh, ...).  The
``--catalog`` flag is added automatically.

Exit codes: 0 on success; 1 on a failed warmup, a version-skewed
artifact (unpack without --force), or a verify mismatch.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _env(cache: str | None) -> dict:
    env = dict(os.environ)
    if cache:
        env["SCINT_COMPILE_CACHE"] = cache
    # the warmup child wires jax's cache dir itself, but an ambient
    # JAX_COMPILATION_CACHE_DIR would win over it (compile_cache's
    # ambient-wins rule) and the XLA entries would land OUTSIDE the
    # dir we pack — drop it so the child fills exactly the packed dir
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _last_json_line(text: str) -> dict:
    """Last parseable JSON object line of a child's stdout (scanning
    backwards past any trailing log/truncated noise — the same
    tolerance bench.py's record parsing uses)."""
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            return rec
    return {}


def cmd_build(args) -> int:
    from scintools_tpu import compile_cache

    # the warmup child runs with cwd=REPO: template paths given
    # relative to the OPERATOR's cwd must survive the hop
    templates = [os.path.abspath(t) for t in args.templates]
    warmup_args = ["warmup", "--catalog"] + templates + args.warmup_args
    code = ("import sys\n"
            "from scintools_tpu.cli import main\n"
            "sys.exit(main(%r))\n" % (warmup_args,))
    try:
        proc = subprocess.run([sys.executable, "-c", code], text=True,
                              capture_output=True, env=_env(args.cache),
                              cwd=REPO, timeout=args.timeout)
    except subprocess.TimeoutExpired:
        # keep the JSON-line/rc-1 contract: CI parses stdout
        print(json.dumps({"error": f"warmup --catalog exceeded "
                          f"{args.timeout}s (--timeout); a chip-scale "
                          "catalog can take minutes per signature"}))
        return 1
    rec = _last_json_line(proc.stdout)
    if proc.returncode != 0 or not rec.get("signatures"):
        print(json.dumps({"error": "warmup --catalog failed",
                          "rc": proc.returncode,
                          "stderr": proc.stderr.strip()[-500:],
                          "warmup": rec}))
        return 1
    if args.cache:
        os.environ["SCINT_COMPILE_CACHE"] = args.cache
    man = compile_cache.pack_warm_cache(
        args.out, cache=args.cache,
        catalog_digest=rec.get("catalog_digest"))
    print(json.dumps({"out": os.path.abspath(args.out),
                      "manifest": man, "warmup": {
                          "signatures": len(rec["signatures"]),
                          "cache_dir": rec.get("cache_dir"),
                          "evictions": rec.get("evictions", 0)}}))
    return 0


def cmd_unpack(args) -> int:
    from scintools_tpu import compile_cache

    if args.cache:
        os.environ["SCINT_COMPILE_CACHE"] = args.cache
    try:
        man = compile_cache.unpack_warm_cache(args.artifact,
                                              cache=args.cache,
                                              force=args.force)
    except ValueError as e:
        print(json.dumps({"error": str(e)}))
        return 1
    print(json.dumps({"cache_dir": compile_cache.cache_dir(),
                      "manifest": man}))
    return 0


def cmd_verify(args) -> int:
    import tarfile

    from scintools_tpu import compile_cache

    try:
        with tarfile.open(args.artifact, "r:gz") as tar:
            fh = tar.extractfile(compile_cache.MANIFEST_NAME)
            if fh is None:
                raise ValueError("manifest member is not a file")
            man = json.load(fh)
    except (OSError, KeyError, ValueError, TypeError) as e:
        print(json.dumps({"error": f"{args.artifact}: not a warm-cache "
                          f"artifact ({e})"}))
        return 1
    mismatches = compile_cache.verify_artifact(man)
    print(json.dumps({"manifest": man, "mismatches": mismatches,
                      "usable": not mismatches}))
    return 0 if not mismatches else 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    q = sub.add_parser("build", help="warm the catalog (subprocess) "
                                     "and pack the cache")
    q.add_argument("templates", nargs="+",
                   help="template psrflux file(s), one per observing "
                        "setup; flags after `--` pass through to "
                        "`scintools-tpu warmup`")
    q.add_argument("--out", default="warm_cache.tgz",
                   help="output tarball path")
    q.add_argument("--cache", default=None,
                   help="cache dir to warm+pack (default: the ambient "
                        "SCINT_COMPILE_CACHE resolution)")
    q.add_argument("--timeout", type=int, default=7200,
                   help="warmup subprocess timeout (seconds)")
    q.set_defaults(fn=cmd_build)

    q = sub.add_parser("unpack", help="verify + unpack an artifact "
                                      "into SCINT_COMPILE_CACHE")
    q.add_argument("artifact")
    q.add_argument("--cache", default=None,
                   help="destination cache dir (default: ambient "
                        "SCINT_COMPILE_CACHE resolution)")
    q.add_argument("--force", action="store_true",
                   help="unpack even on a version mismatch (stale keys "
                        "miss and recompile — slow, never wrong)")
    q.set_defaults(fn=cmd_unpack)

    q = sub.add_parser("verify", help="print an artifact's manifest "
                                      "and runtime-compatibility")
    q.add_argument("artifact")
    q.set_defaults(fn=cmd_verify)
    return p


def main(argv: list | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # argparse swallows flags after the template list; split at `--`
    # ourselves so warmup flags pass through verbatim
    passthrough: list = []
    if "--" in argv:
        i = argv.index("--")
        argv, passthrough = argv[:i], argv[i + 1:]
    args = build_parser().parse_args(argv)
    args.warmup_args = passthrough
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
