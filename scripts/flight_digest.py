"""Summarise a tpu_recheck flight log: one line per captured stage.

Post-flight workflow helper: the capture window is minutes-scale, so
landing the evidence into docs/PARITY quickly matters.  Prints every
JSON record and every stage-profile row found in the log, prefixed by
the stage banner it appeared under, plus a PASS/FAIL verdict per gate.

Usage: python scripts/flight_digest.py benchmarks/flights/<log> [...]
"""

from __future__ import annotations

import json
import re
import sys


def digest(path: str) -> int:
    stage = "(preamble)"
    n_rec = 0
    print(f"== {path} ==")
    with open(path, errors="replace") as fh:
        for raw in fh:
            line = raw.strip()
            m = re.match(r"^==\s*(.+?)\s*==$", line)
            if m:
                stage = m.group(1)
                continue
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                n_rec += 1
                keys = ("metric", "kernel", "config", "value", "unit",
                        "vs_baseline", "speedup", "verdict", "device",
                        "tunnel_weather_suspect", "error")
                brief = {k: rec[k] for k in keys if k in rec}
                print(f"  [{stage}] {brief}")
            elif re.match(r"^\S.*\sms/batch\s", line):
                print(f"  [{stage}] {line}")
            elif "FAILED" in line or "rel err" in line or "alive" in line:
                print(f"  [{stage}] {line}")
    print(f"  ({n_rec} JSON records)")
    return 0 if n_rec else 1


if __name__ == "__main__":
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    try:
        sys.exit(max(digest(p) for p in sys.argv[1:]))
    except BrokenPipeError:  # `| head` closing the pipe is fine
        sys.exit(0)
