"""Non-uniform DFT of a dynamic spectrum along frequency-scaled time.

Capability parity with the reference's ``slow_FT`` (scint_utils.py:317-398)
and its native kernel ``comp_dft_for_secspec`` (fit_1d-response.c:16-48):
transforming along ``t * (f / fref)`` removes the chromatic smearing of
scintillation arcs before the Doppler axis is formed.  The math:

    out[r, f] = sum_t exp(+2j*pi * (r0 + r*dr) * tsrc[t] * fscale[f])
                * power[t, f]

followed (in :func:`slow_ft`) by a Doppler-axis flip and an ordinary FFT +
shift along frequency, exactly like the reference's working C path.  The
reference's pure-numpy fallback is broken (undefined ``t``, different
shift/sign — scint_utils.py:389-392); ours is fixed and tested against the
native path.

Execution paths (all agree to float64 tolerances; see tests/test_nudft.py):

* ``numpy``  — Doppler-chunked broadcast einsum (bounded memory);
* native C++ — OpenMP rotation-recurrence kernel
  (scintools_tpu/native/nudft.cc), auto-built, used by the numpy backend
  when available;
* ``jax``    — frequency-chunked batched matvec under ``lax.map``: for each
  frequency the phase matrix is a dense [nr, nt] complex operator, so the
  contraction is MXU-shaped and XLA pipelines chunk-by-chunk without ever
  materialising the full [nr, nt, nf] phase tensor.  (A Pallas VMEM-phase
  kernel was A/B'd on-chip in round 4 and deleted: 0.44x the einsum.)
* ``jax`` + ``route="pallas"`` — OPT-IN rotation-recurrence Pallas tile
  (:func:`_nudft_pallas_reim`, end of this file): blocked on-chip
  accumulation with one complex multiply per sample instead of cos+sin,
  the native kernels' trick brought on-chip.  Gated by the
  prove-or-remove A/B (benchmarks/pallas_ab.py) before it can become a
  default.
"""

from __future__ import annotations

import functools

import numpy as np

from ..backend import resolve

__all__ = ["nudft", "slow_ft", "slow_ft_power", "slow_ft_power_sharded"]


def _r_grid(ntime: int) -> tuple[float, float, int]:
    """Doppler grid of the reference driver (scint_utils.py:363-366):
    fftfreq spacing, starting at its minimum, one bin per time sample."""
    r = np.fft.fftfreq(ntime)
    return float(r.min()), float(r[1] - r[0]), ntime


def _nudft_numpy(power, fscale, tsrc, r0, dr, nr, chunk_r: int = 32):
    power = np.asarray(power, dtype=np.float64)  # host-f64: numpy oracle path
    fscale = np.asarray(fscale, dtype=np.float64)  # host-f64: numpy oracle path
    tsrc = np.asarray(tsrc, dtype=np.float64)  # host-f64: numpy oracle path
    ntime, nfreq = power.shape
    rvals = r0 + dr * np.arange(nr)
    tf = tsrc[:, None] * fscale[None, :]  # [nt, nf]
    out = np.empty((nr, nfreq), dtype=np.complex128)  # host-f64: numpy oracle path
    for start in range(0, nr, chunk_r):
        rc = rvals[start:start + chunk_r]
        phase = 2j * np.pi * rc[:, None, None] * tf[None, :, :]
        out[start:start + chunk_r] = np.einsum(
            "rtf,tf->rf", np.exp(phase), power, optimize=True)
    return out


def _nudft_jax_reim(power, fscale, tsrc, r0, dr, nr, chunk_f: int = 16):
    """jax path returning ``(re, im)`` real arrays.

    Real dtypes only at every boundary, and the contraction is two REAL
    batched matvecs rather than one complex einsum: the axon TPU backend
    does not implement complex transfers or complex dots (and the MXU is a
    real systolic array anyway) — see memory note tpu-complex-unsupported.
    """
    import jax.numpy as jnp
    from jax import lax

    power = jnp.asarray(power)
    if not jnp.issubdtype(power.dtype, jnp.floating):
        power = power.astype(jnp.float32)
    fscale = jnp.asarray(fscale, dtype=power.dtype)
    tsrc = jnp.asarray(tsrc, dtype=power.dtype)
    ntime, nfreq = power.shape
    pad = (-nfreq) % chunk_f
    fs = jnp.pad(fscale, (0, pad))
    pw = jnp.pad(power, ((0, 0), (0, pad)))
    nchunks = (nfreq + pad) // chunk_f
    fs = fs.reshape(nchunks, chunk_f)
    pw = jnp.moveaxis(pw.reshape(ntime, nchunks, chunk_f), 1, 0)  # [nc,nt,cf]
    rvals = (r0 + dr * jnp.arange(nr)).astype(power.dtype)

    def one_chunk(operand):
        fs_c, p_c = operand  # [cf], [nt, cf]
        # [nr, nt, cf] phases built per chunk only; never the full tensor.
        phase = (2 * jnp.pi) * (
            rvals[:, None, None] * tsrc[None, :, None] * fs_c[None, None, :])
        # HIGHEST precision: under vmap XLA lowers these to batched MXU
        # matmuls whose default bf16 passes cost ~100x accuracy (2e-3 vs
        # 2.7e-5 scaled error against the f64 oracle, measured on-chip at
        # 512x256) — the f32 passes keep the batched pipeline's slow_ft
        # at the same accuracy as the unbatched call
        re = jnp.einsum("rtc,tc->rc", jnp.cos(phase), p_c,
                        precision=lax.Precision.HIGHEST)
        im = jnp.einsum("rtc,tc->rc", jnp.sin(phase), p_c,
                        precision=lax.Precision.HIGHEST)
        return re, im

    re, im = lax.map(one_chunk, (fs, pw))         # each [nc, nr, cf]
    re = jnp.moveaxis(re, 0, 1).reshape(nr, nfreq + pad)[:, :nfreq]
    im = jnp.moveaxis(im, 0, 1).reshape(nr, nfreq + pad)[:, :nfreq]
    return re, im


def nudft(power, fscale, tsrc=None, r0=None, dr=None, nr=None,
          backend: str = "numpy", use_native: bool | None = None,
          route: str = "einsum", interpret=False):
    """NUDFT core: ``out[r, f] = sum_t cis(2*pi*(r0+r*dr)*tsrc[t]*fscale[f])
    * power[t, f]``.

    Defaults reproduce the reference driver's grid (tsrc = sample index,
    Doppler bins = fftfreq(ntime) sorted ascending — scint_utils.py:360-366).
    ``use_native=None`` tries the C++ library on the numpy backend and falls
    back silently.

    ``route`` selects the jax lowering: ``"einsum"`` (the production
    chunked-matvec path) or ``"pallas"`` (the rotation-recurrence tile,
    :func:`_nudft_pallas_reim` — OPT-IN until its on-chip A/B returns a
    "wire" verdict; requires a uniform host ``tsrc`` grid).
    """
    if route not in ("einsum", "pallas"):
        raise ValueError(f"nudft route must be 'einsum' or 'pallas', "
                         f"got {route!r}")
    if route == "pallas" and resolve(backend) != "jax":
        # same contract as sspec(fused=True, backend="numpy"): silently
        # running the numpy/native path would let an A/B believe it
        # exercised the tile
        raise ValueError("nudft(route='pallas') is a jax-path knob; "
                         "the numpy/native backends have no Pallas "
                         "lowering")
    ntime = power.shape[0]
    if tsrc is None:
        tsrc = np.arange(ntime, dtype=np.float64)  # host-f64: host grid precompute
    if r0 is None or dr is None or nr is None:
        g0, gd, gn = _r_grid(ntime)
        r0 = g0 if r0 is None else r0
        dr = gd if dr is None else dr
        nr = gn if nr is None else nr
    if resolve(backend) == "jax":
        from jax import lax

        if route == "pallas":
            re, im = _nudft_pallas_reim(power, fscale, tsrc, r0, dr, nr,
                                        interpret=interpret)
        else:
            re, im = _nudft_jax_reim(power, fscale, tsrc, r0, dr, nr)
        # complex assembled ON DEVICE (supported on TPU); callers on real
        # TPU must not transfer it directly — use slow_ft_power, or
        # jnp.real/jnp.imag before the transfer (tpu-complex-unsupported).
        return lax.complex(re, im)
    if use_native is None or use_native:
        from ..native import nudft_native

        out = nudft_native(power, fscale, tsrc, r0, dr, nr)
        if out is not None:
            return out
        if use_native:
            raise RuntimeError("native NUDFT library unavailable")
    return _nudft_numpy(power, fscale, tsrc, r0, dr, nr)


def slow_ft(dyn, freqs, backend: str = "numpy", use_native: bool | None = None,
            as_numpy: bool = False):
    """Arc-sharpened secondary-spectrum field of ``dyn`` [ntime, nfreq].

    Pipeline parity with the reference's working (C) branch
    (scint_utils.py:356-397): scale time by f/fref (fref = centre channel),
    NUDFT along scaled time, flip the Doppler axis, then FFT + fftshift along
    frequency.  Returns complex [ntime, nfreq].
    """
    dyn = np.asarray(dyn) if resolve(backend) == "numpy" else dyn
    ntime, nfreq = dyn.shape
    freqs = np.asarray(freqs, dtype=np.float64)  # host-f64: host grid precompute
    fscale = freqs / freqs[nfreq // 2]
    out = nudft(dyn, fscale, backend=backend, use_native=use_native)
    if resolve(backend) == "jax":
        import jax.numpy as jnp

        out = out[::-1]
        out = jnp.fft.fftshift(jnp.fft.fft(out, axis=1), axes=1)
        if as_numpy:
            # transfer real and imaginary planes separately: complex
            # host<->device copies are unimplemented on the axon TPU
            return (np.asarray(jnp.real(out))
                    + 1j * np.asarray(jnp.imag(out)))
        return out
    out = np.asarray(out)[::-1]
    return np.fft.fftshift(np.fft.fft(out, axis=1), axes=1)


def slow_ft_power(dyn, freqs, db: bool = True, backend: str = "jax"):
    """|slow_ft|^2 with real dtypes at every boundary — the TPU-safe,
    jit-composable form of the arc-sharpened secondary spectrum.

    The reference exposes only the complex field (scint_utils.py:317); its
    consumers immediately take power.  Returns real [ntime, nfreq]
    (10*log10 when ``db``).
    """
    if resolve(backend) != "jax":
        ss = slow_ft(dyn, freqs, backend="numpy")
        p = np.abs(ss) ** 2
        return 10 * np.log10(p) if db else p
    import jax.numpy as jnp

    ss = slow_ft(dyn, freqs, backend="jax")
    p = jnp.real(ss) ** 2 + jnp.imag(ss) ** 2
    return 10 * jnp.log10(p) if db else p


def slow_ft_power_sharded(dyn, freqs, mesh, axis: str = "data",
                          db: bool = True):
    """Mesh-sharded arc-sharpened secondary spectrum (SURVEY.md §5
    "long-context" analogue: the NUDFT as a device-sharded einsum).

    The O(ntime * nfreq * nr) NUDFT decomposes output-parallel over the
    Doppler axis: shard ``axis`` devices each build only their own
    [nr/n, nt, chunk_f] phase slabs (zero communication — each Doppler
    block depends on the whole dynspec, which is replicated, the way DP
    replicates activations).  The frequency-axis FFT that follows is
    along an unsharded axis, so XLA runs it locally per shard; only the
    Doppler flip moves data between devices.  Use when a single spectrum
    is too large for one device's HBM budget, or to cut single-spectrum
    latency across a pod slice.

    Returns the real power spectrum [ntime, nfreq] (10*log10 when
    ``db``), sharded [axis, None] over the mesh.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    # prefer the stable location (jax.shard_map); experimental fallback
    # for older jax (same pattern as parallel/mesh.py)
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    ntime, nfreq = dyn.shape
    freqs = np.asarray(freqs, dtype=np.float64)  # host-f64: host grid precompute
    fscale = freqs / freqs[nfreq // 2]
    tsrc = np.arange(ntime, dtype=np.float64)  # host-f64: host grid precompute
    r0, dr, nr = _r_grid(ntime)
    n = mesh.shape[axis]
    nr_pad = (-nr) % n
    nr_p = nr + nr_pad  # extra top bins computed then dropped
    nr_local = nr_p // n

    def local_block(dyn_rep):
        idx = lax.axis_index(axis)
        # the runtime's float dtype explicitly (f32 under the
        # production x64-off runtime, f64 on x64-enabled hosts):
        # requesting float64 unconditionally only triggered jax's
        # truncation UserWarning under x64-off before being cast to f32
        # anyway (the MULTICHIP_r05 tail incident; the suite now
        # promotes that warning to an error)
        r0_local = r0 + dr * (idx * nr_local).astype(
            jnp.result_type(float))
        return _nudft_jax_reim(dyn_rep, fscale, tsrc, r0_local, dr, nr_local)

    dyn_rep = jax.device_put(jnp.asarray(dyn),
                             NamedSharding(mesh, P(None, None)))
    re, im = shard_map(local_block, mesh=mesh, in_specs=P(None, None),
                       out_specs=P(axis, None))(dyn_rep)
    field = lax.complex(re, im)[:nr][::-1]  # flip = ppermute across shards
    field = jnp.fft.fftshift(jnp.fft.fft(field, axis=1), axes=1)
    p = jnp.real(field) ** 2 + jnp.imag(field) ** 2
    return 10 * jnp.log10(p) if db else p


# ---------------------------------------------------------------------------
# Pallas NUDFT tile: rotation-recurrence blocked accumulation
# ---------------------------------------------------------------------------
#
# History: a first Pallas NUDFT kernel (VMEM-generated cos/sin phase
# slabs feeding the MXU) lived here through round 4; it lowered and ran
# correctly on real Mosaic but measured 0.44x the production chunked
# einsum above (pallas_ab.py round-4 verdict "keep-off") and was
# deleted per the prove-or-remove policy.  The tile below is a
# DIFFERENT design — the rotation-recurrence trick of the reference's
# own native kernel (fit_1d-response.c) and ours (native/nudft.cc): on
# a uniform time grid the per-sample phase STEP is constant per (r, f),
# so the inner loop is one complex multiply-accumulate instead of
# cos+sin per element; the transcendentals run only at block init and
# at a periodic resync that bounds f32 drift.  It stays OPT-IN
# (``route="pallas"``) until the on-chip A/B (benchmarks/pallas_ab.py,
# driver scripts/tpu_recheck.sh) returns a "wire" verdict — the same
# gate that killed its predecessor.


def _nudft_pallas_kernel(power_ref, fs_ref, re_ref, im_ref, *,
                         block_r: int, ntime: int, r0: float, dr: float,
                         t0: float, dt: float, resync: int):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    dtype = re_ref.dtype
    fs = fs_ref[0:1, :]                                  # [1, Fb]
    r_idx = (i * block_r
             + lax.broadcasted_iota(jnp.int32, (block_r, 1), 0))
    rv = r0 + dr * r_idx.astype(dtype)                   # [Rb, 1]
    w = (2.0 * np.pi) * rv * fs                          # [Rb, Fb]
    # e^{+i w dt}: the constant per-(r,f) rotation of one time step
    step_re = jnp.cos(w * dt)
    step_im = jnp.sin(w * dt)
    zeros = jnp.zeros(w.shape, dtype)
    nchunks = -(-ntime // resync)

    def chunk(c, acc):
        acc_re, acc_im = acc
        t_base = c * resync
        # exact phasor at the chunk head: cos/sin once per resync
        # window, bounding the recurrence's f32 drift to ~resync*eps
        ph0 = w * (t0 + t_base.astype(dtype) * dt)
        state = (acc_re, acc_im, jnp.cos(ph0), jnp.sin(ph0))

        def t_body(k, st):
            a_re, a_im, p_re, p_im = st
            p = power_ref[pl.ds(t_base + k, 1), :]       # [1, Fb]
            a_re = a_re + p * p_re
            a_im = a_im + p * p_im
            # rotate: phasor *= e^{i w dt}
            n_re = p_re * step_re - p_im * step_im
            n_im = p_re * step_im + p_im * step_re
            return (a_re, a_im, n_re, n_im)

        n_in = jnp.minimum(resync, ntime - t_base)
        acc_re, acc_im, _, _ = lax.fori_loop(0, n_in, t_body, state)
        return (acc_re, acc_im)

    acc_re, acc_im = lax.fori_loop(0, nchunks, chunk, (zeros, zeros))
    re_ref[...] = acc_re
    im_ref[...] = acc_im


def _nudft_pallas_reim(power, fscale, tsrc, r0, dr, nr,
                       block_r: int = 64, block_f: int = 128,
                       resync: int = 64, interpret=False):
    """Pallas NUDFT tile returning ``(re, im)`` — blocked on-chip
    accumulation replacing the dense-matmul lowering: output tiles
    [block_r, block_f] accumulate over time IN VMEM (the [nr, nt, nf]
    phase tensor never exists anywhere), with the rotation recurrence
    replacing per-sample cos/sin.

    Requires a UNIFORM host-side ``tsrc`` (the driver's grid is
    ``arange``): the recurrence needs a constant time step.  Real
    dtypes only at every boundary, like :func:`_nudft_jax_reim`."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from .pallas_common import resolve_interpret, round_up

    tsrc = np.asarray(tsrc, dtype=np.float64)  # host-f64: uniform-grid check
    if tsrc.ndim != 1 or tsrc.size < 2:
        raise ValueError(f"pallas NUDFT needs a 1-D host tsrc grid of "
                         f">= 2 samples, got shape {tsrc.shape}")
    steps = np.diff(tsrc)
    dt_t = float(steps[0])
    if not np.allclose(steps, dt_t, rtol=1e-12, atol=0.0):
        raise ValueError("pallas NUDFT requires a uniform tsrc grid "
                         "(the rotation recurrence needs a constant "
                         "time step); use the einsum route")
    power = jnp.asarray(power)
    if not jnp.issubdtype(power.dtype, jnp.floating):
        power = power.astype(jnp.float32)
    ntime, nfreq = power.shape
    fscale = jnp.asarray(fscale, dtype=power.dtype)
    nf_pad = round_up(nfreq, block_f)
    nr_pad = round_up(nr, block_r)
    pw = jnp.pad(power, ((0, 0), (0, nf_pad - nfreq)))
    fs = jnp.pad(fscale, (0, nf_pad - nfreq))[None, :]   # [1, nf_pad]
    grid = (nr_pad // block_r, nf_pad // block_f)
    re, im = pl.pallas_call(
        functools.partial(
            _nudft_pallas_kernel, block_r=block_r, ntime=int(ntime),
            r0=float(r0), dr=float(dr), t0=float(tsrc[0]), dt=dt_t,
            resync=int(resync)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ntime, block_f), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_f), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, block_f), lambda i, j: (i, j)),
            pl.BlockSpec((block_r, block_f), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nr_pad, nf_pad), power.dtype),
            jax.ShapeDtypeStruct((nr_pad, nf_pad), power.dtype),
        ],
        interpret=resolve_interpret(interpret),
    )(pw, fs)
    return re[:nr, :nfreq], im[:nr, :nfreq]
