"""Secondary spectrum: 2-D power spectrum of the dynamic spectrum.

Reference: ``Dynspec.calc_sspec`` (dynspec.py:1228-1335).  Pipeline:

    mean-subtract -> split edge window -> mean-subtract again ->
    prewhiten (2x2 second difference) -> fft2 padded to next-pow2*2 ->
    |.|^2 -> fftshift -> keep positive delays -> postdarken (divide by the
    sin^2 response of the prewhitening filter) -> 10*log10

Axes (dynspec.py:1291-1299): fdop in mHz, tdel in us, and beta in 1/m when
the input is in uniform-wavelength steps.

The reference prewhitens with ``convolve2d([[1,-1],[-1,1]], dyn, 'valid')``
(dynspec.py:1282), which equals the separable second difference
``d[1:,1:] - d[1:,:-1] - d[:-1,1:] + d[:-1,:-1]``; the numpy path keeps
scipy's convolve2d for bit-matching, the jax path uses the difference form
(XLA fuses it into the FFT's pad).

Quirks preserved on both paths (SURVEY.md "hard parts"): the double mean
subtraction (dynspec.py:1251,1280), asymmetric window insertion, and the
postdark singular rows/cols forced to 1 (dynspec.py:1308-1309).
"""

from __future__ import annotations

import functools

import numpy as np
from scipy.signal import convolve2d

from .. import obs
from ..backend import resolve
from .windows import apply_2d_window


def next_pow2_fft_lens(nf: int, nt: int) -> tuple[int, int]:
    """FFT lengths: next power of two, doubled (dynspec.py:1277-1279)."""
    nrfft = int(2 ** (np.ceil(np.log2(nf)) + 1))
    ncfft = int(2 ** (np.ceil(np.log2(nt)) + 1))
    return nrfft, ncfft


def next_fast_len(n: int) -> int:
    """Smallest EVEN 5-smooth composite (2^a * 3^b * 5^c, a >= 1) >= n.

    XLA's FFT (like FFTW/pocketfft) runs mixed-radix 2/3/5 plans at
    near-pow2 efficiency, so padding a 300-channel epoch (2n = 600) to
    600 (2^3*3*5^2) instead of 1024 cuts the padded grid — and every FFT
    pass and elementwise byte over it — by 41% (the transform-sizing lever of
    GPU pulsar FFT work, arXiv:1711.10855, and FFTArray's length
    engineering, arXiv:2508.03697).  Evenness is required downstream:
    the spectrum keeps ``nrfft/2`` positive-delay rows and the Doppler
    fftshift assumes a symmetric grid."""
    if n <= 2:
        return 2
    best = 1
    while best < n:  # next power of two: the fallback ceiling
        best *= 2
    p5 = 1
    while p5 < best:
        p35 = p5
        while p35 < best:
            # smallest even power-of-two multiple of p35 reaching n
            m = p35 * 2
            while m < n:
                m *= 2
            best = min(best, m)
            p35 *= 3
        p5 *= 5
    return int(best)


def fft_lens(nf: int, nt: int, mode: str = "pow2") -> tuple[int, int]:
    """Padded secondary-spectrum FFT lengths for one [nf, nt] epoch.

    ``mode="pow2"`` is the reference's next-pow2-doubled rule (the
    parity path, bit-identical to dynspec.py:1277-1279); ``"fast"``
    pads to the smallest even 5-smooth composite >= 2n per axis — never
    longer than pow2, identical to it when n is a power of two, and up
    to ~38% shorter per axis otherwise (different spectral sampling:
    an opt-in performance knob, not a parity path)."""
    if mode == "pow2":
        return next_pow2_fft_lens(nf, nt)
    if mode == "fast":
        return next_fast_len(2 * nf), next_fast_len(2 * nt)
    raise ValueError(f"fft_lens mode must be 'pow2' or 'fast', got "
                     f"{mode!r}")


def sspec_axes(nf: int, nt: int, dt, df, dlam=None, lens: str = "pow2"):
    """fdop (mHz), tdel (us), beta (1/m, when dlam given).

    Mirrors dynspec.py:1291-1299. ``dt``/``df``/``dlam`` may be traced
    scalars under vmap; shapes depend only on static nf/nt (and the
    static ``lens`` padding mode, which must match the ``sspec`` call).
    """
    nrfft, ncfft = fft_lens(nf, nt, lens)
    td = np.arange(nrfft // 2)
    fd = np.arange(-ncfft // 2, ncfft // 2)
    fdop = fd * 1e3 / (ncfft * dt)
    tdel = td / (nrfft * df)
    beta = None if dlam is None else td / (nrfft * dlam)
    return fdop, tdel, beta


def sspec(dyn, prewhite: bool = True, window: str | None = "blackman",
          window_frac: float = 0.1, db: bool = True, backend: str = "numpy",
          lens: str = "pow2", crop_rows: int | None = None,
          fused: bool = False):
    """Secondary spectrum of ``dyn`` [..., nf, nt].

    Returns sec [..., nrfft/2, ncfft] in dB (positive delays only).
    Use :func:`sspec_axes` for the fdop/tdel/beta axes (same ``lens``).

    ``lens`` selects the padded FFT lengths (:func:`fft_lens`):
    ``"pow2"`` is the reference parity path, ``"fast"`` the 5-smooth
    composite padding.  ``crop_rows`` (static) keeps only the first
    ``crop_rows`` delay rows — the postdark/dB elementwise tail then
    touches ONLY the consumed sub-region, so a consumer that reads a
    delay window (the arc fitter's delmax crop) never round-trips the
    full padded spectrum through HBM.

    ``fused=True`` (jax backend only — ``PipelineConfig.fused_sspec``)
    dispatches to the fused prologue/epilogue kernels of
    :mod:`scintools_tpu.ops.sspec_pallas` (Pallas on a real TPU, an
    equivalently-restructured XLA lowering elsewhere).  Opt-in and NOT
    bit-identical to this chain — fits agree within the documented 2 %
    budget; the default path below is byte-for-byte unchanged.
    """
    backend = resolve(backend)
    if fused and backend != "jax":
        raise ValueError("sspec(fused=True) is a jax-path knob (the "
                         "Pallas/XLA fused kernels); the numpy parity "
                         "path stays unfused by contract")
    shape = np.shape(dyn)  # works for lists and device arrays alike
    if len(shape) < 2 or shape[-2] < 2 or shape[-1] < 2:
        raise ValueError(f"secondary spectrum needs at least a 2x2 "
                         f"dynspec, got {shape} (prewhitening "
                         f"differences both axes)")
    # span semantics: eager calls time real kernel work (fenced on the
    # jax path); calls from inside a jit trace (the batched step) time
    # TRACE construction and land inside that step's .compile span
    with obs.span("ops.sspec", backend=backend, shape=list(shape)):
        if backend == "numpy":
            arr = np.asarray(dyn, dtype=np.float64)  # host-f64: parity path
            if arr.ndim > 2:  # batched: per-epoch host loop (jax on device)
                lead = arr.shape[:-2]
                flat = arr.reshape((-1,) + arr.shape[-2:])
                out = np.stack([_sspec_numpy(a, prewhite, window,
                                             window_frac, db, lens,
                                             crop_rows)
                                for a in flat])
                return out.reshape(lead + out.shape[-2:])
            return _sspec_numpy(arr, prewhite, window, window_frac, db,
                                lens, crop_rows)
        if fused:
            return obs.fence(_sspec_fused_jit()(dyn, prewhite, window,
                                                window_frac, db, lens,
                                                crop_rows))
        return obs.fence(_sspec_jax()(dyn, prewhite, window, window_frac,
                                      db, lens, crop_rows))


def _postdark(nrfft: int, ncfft: int, xp=np):
    """sin^2 response of the 2x2 prewhitening filter on the cropped grid.

    dynspec.py:1301-1309: outer product of sin^2(pi*fd/ncfft) and
    sin^2(pi*td/nrfft), transposed to [nrfft/2, ncfft]; the fdop=0 column
    and tdel=0 row are forced to 1 to avoid 0/0.
    """
    td = xp.arange(nrfft // 2)
    fd = xp.arange(-ncfft // 2, ncfft // 2)
    vec1 = xp.sin(xp.pi / ncfft * fd) ** 2  # [ncfft]
    vec2 = xp.sin(xp.pi / nrfft * td) ** 2  # [nrfft/2]
    pd = vec2[:, None] * vec1[None, :]
    if xp is np:
        pd[:, ncfft // 2] = 1
        pd[0, :] = 1
    else:
        pd = pd.at[:, ncfft // 2].set(1.0)
        pd = pd.at[0, :].set(1.0)
    return pd


def _sspec_numpy(dyn, prewhite, window, window_frac, db, lens="pow2",
                 crop_rows=None):
    nf, nt = dyn.shape[-2], dyn.shape[-1]
    dyn = dyn - np.mean(dyn)
    if window is not None:
        dyn = apply_2d_window(dyn, window, window_frac, backend="numpy")
    nrfft, ncfft = fft_lens(nf, nt, lens)
    dyn = dyn - np.mean(dyn)
    if prewhite:
        simpw = convolve2d([[1, -1], [-1, 1]], dyn, mode="valid")
    else:
        simpw = dyn
    simf = np.fft.fft2(simpw, s=[nrfft, ncfft])
    sec = np.real(simf * np.conj(simf))
    sec = np.fft.fftshift(sec)
    sec = sec[nrfft // 2:, :]
    if crop_rows is not None:
        sec = sec[:crop_rows, :]
    if prewhite:
        pd = _postdark(nrfft, ncfft)
        sec = sec / (pd if crop_rows is None else pd[:crop_rows])
    if db:
        # zero-power pad bins legitimately map to -inf dB (the reference
        # produces the same values, warning unsuppressed); downstream
        # consumers mask by power, so the divide warning is just noise
        with np.errstate(divide="ignore"):
            sec = 10 * np.log10(sec)
    return sec


@functools.lru_cache(maxsize=1)
def _sspec_fused_jit():
    """jit wrapper of the fused route (ops/sspec_pallas.sspec_fused)
    mirroring :func:`_sspec_jax`'s static-argument layout, so eager
    callers get one compiled program per option set and traced callers
    (the batched step) inline it."""
    import jax

    from .sspec_pallas import sspec_fused

    @functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
    def impl(dyn, prewhite, window, window_frac, db, lens, crop_rows):
        return sspec_fused(dyn, prewhite=prewhite, window=window,
                           window_frac=window_frac, db=db, lens=lens,
                           crop_rows=crop_rows, route="auto",
                           interpret="auto")
    return impl


@functools.lru_cache(maxsize=1)
def _sspec_jax():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
    def impl(dyn, prewhite, window, window_frac, db, lens, crop_rows):
        nf, nt = dyn.shape[-2], dyn.shape[-1]
        dyn = dyn - jnp.mean(dyn, axis=(-2, -1), keepdims=True)
        if window is not None:
            dyn = apply_2d_window(dyn, window, window_frac, backend="jax")
        nrfft, ncfft = fft_lens(nf, nt, lens)
        dyn = dyn - jnp.mean(dyn, axis=(-2, -1), keepdims=True)
        if prewhite:
            # separable 2nd difference == convolve2d([[1,-1],[-1,1]], 'valid')
            simpw = (dyn[..., 1:, 1:] - dyn[..., 1:, :-1]
                     - dyn[..., :-1, 1:] + dyn[..., :-1, :-1])
        else:
            simpw = dyn
        # real input + positive-delay crop -> real FFT over the delay (row)
        # axis: rfftn computes u = 0..nrfft/2 directly, halving the work of
        # the reference's full complex fft2 (dynspec.py:1286-1289) whose
        # negative delays are discarded anyway.  Row r of the reference's
        # fftshift-then-crop output is u = r (delay axis unshifted), column
        # c is v = c - ncfft/2 (Doppler axis shifted).
        simf = jnp.fft.rfftn(simpw, s=(ncfft, nrfft), axes=(-1, -2))
        if crop_rows is not None:
            # static delay-window crop straight off the FFT output: the
            # |.|^2 / fftshift / postdark / log10 passes below only ever
            # touch the consumed rows, so the full padded spectrum is
            # never written back to HBM (the driver computes crop_rows
            # from the arc fitter's own delmax rule)
            simf = simf[..., :crop_rows, :]
        sec = jnp.real(simf) ** 2 + jnp.imag(simf) ** 2
        sec = jnp.fft.fftshift(sec, axes=-1)[..., : nrfft // 2, :]
        if prewhite:
            pd = _postdark(nrfft, ncfft, xp=jnp).astype(sec.dtype)
            if crop_rows is not None:
                pd = pd[:crop_rows]
            sec = sec / pd
        if db:
            sec = 10.0 * jnp.log10(sec)
        return sec

    return impl
