from .acf import acf  # noqa: F401
from .clean import (correct_band, crop, refill, refill_fixed_point,  # noqa: F401
                    trim_edges, zap)
from .nudft import (nudft, slow_ft, slow_ft_power,  # noqa: F401
                    slow_ft_power_sharded)
from .scale import scale_lambda, scale_trapezoid  # noqa: F401
from .sspec import next_pow2_fft_lens, sspec, sspec_axes  # noqa: F401
from .sspec_pallas import sspec_fused  # noqa: F401
from .svd import svd_model  # noqa: F401
from .windows import apply_2d_window, split_window  # noqa: F401
