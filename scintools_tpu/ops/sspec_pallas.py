"""Fused on-chip secondary-spectrum kernels (Pallas) + the fused route.

BENCH_r05 measured the compiled step bandwidth-bound at 5.98 % of the
TPU v5 lite roofline (AI ~ 6 flop/byte): ``ops/sspec.py``'s jax path is
a chain of discrete XLA ops (mean-sub -> window -> prewhiten diff ->
rfftn -> |.|^2 -> fftshift -> postdark -> log10) each round-tripping the
padded grid through HBM.  The GPU FDAS literature (arXiv:1711.10855,
arXiv:1804.05335) got its wins by fusing FFT-domain prologues/epilogues
instead of running op chains — this module is that shape for the
secondary spectrum:

* :func:`sspec_prologue_pallas` — mean-subtract + split-edge window +
  2x2 prewhiten second-difference + zero-pad in ONE pass, writing
  directly into the FFT-input buffer (one HBM write instead of four
  intermediate round-trips).
* :func:`sspec_epilogue_pallas` — |.|^2 + Doppler fftshift + postdark
  divide + 10*log10 + delay-row crop off the FFT output tile-by-tile.
  The fftshift costs ZERO extra traffic: with column tiles of half the
  Doppler axis, the shift is pure block-index remapping (out tile j
  reads in tile 1-j), and the postdark response is generated from iota
  on-core instead of read from a precomputed HBM array.
* :func:`sspec_fused` — the routed fused op ``PipelineConfig.
  fused_sspec`` dispatches to: the Pallas kernels on a real TPU, an
  equivalently-restructured pure-XLA lowering elsewhere (interpret-mode
  Pallas emulation inflates the very HBM traffic being engineered —
  measured: a 4-step grid costs grid x full-buffer dynamic-update-slice
  passes on the CPU backend).

The structural win both lowerings share — the crop-fused FFT split: when
the arc fitter's delay window keeps R <= nrfft/4 rows, the delay-axis
transform runs as an exact R-row DFT MATMUL over only the nf-1 nonzero
input rows (zero padding contributes nothing to the sum), the Doppler
FFT then transforms ONLY those R rows, and the full padded spectrum is
never materialised.  Measured XLA ``cost_analysis()`` bytes-accessed at
the 256x512 pow2 signature (CPU backend, tier-1-asserted in
tests/test_sspec_pallas.py): crop=64 11.30 MB -> 7.21 MB (-36 %),
crop=45 -44 %; the matmul is MXU-shaped on TPU (``Precision.HIGHEST``
pinned — the same bf16-lowering guard as ops/nudft.py's einsum).

Parity contract: the fused route is opt-in and NOT bit-identical to the
chain (fp association differs through the split transform); tau/dnu/eta
agree within the documented 2 % fit budget (tier-1-tested) and the
unfused/numpy paths are untouched.  The prove-or-remove A/B lives in
``benchmarks/pallas_ab.py`` (driver: scripts/tpu_recheck.sh) — a fused
kernel that does not move measured ``step_bytes``/``roofline_pct`` gets
reverted per ROADMAP.
"""

from __future__ import annotations

import functools

import numpy as np

from .pallas_common import (SUBLANE, pick_row_block, resident_spec,
                            resolve_interpret, round_up, row_tile_spec)
from .windows import split_window

__all__ = [
    "sspec_fused",
    "sspec_prologue_pallas",
    "sspec_epilogue_pallas",
    "fused_route_default",
    "use_dft_pass1",
]


# ---------------------------------------------------------------------------
# routing rules
# ---------------------------------------------------------------------------


def use_dft_pass1(crop_rows: int | None, nrfft: int) -> bool:
    """Whether the crop-fused FFT split pays: the R-row DFT matmul +
    R-row Doppler FFT beats the full-grid rfftn only while the kept
    delay window is small — measured break-even on the CPU cost model
    at R ~ nrfft/4 (R = nrfft/8 -> -36 % bytes, R = nrfft/4 -> ~-12 %,
    above that the R x ncfft complex pad round-trip wins back).  One
    rule site shared by both lowerings and the byte-drop test."""
    return crop_rows is not None and int(crop_rows) <= int(nrfft) // 4


def _pallas_conforming(nrfft: int, ncfft: int) -> bool:
    """Shapes the real-Mosaic kernels tile: the epilogue's fftshift
    block remap needs half the Doppler axis to be a 128-lane multiple
    (pow2 grids >= 256 always conform; 5-smooth "fast" grids like 600
    do not and take the XLA lowering instead — same demotion style as
    resample_pallas's 128-lane gather gate)."""
    return (ncfft % 256 == 0 and nrfft % SUBLANE == 0
            and nrfft >= 2 * SUBLANE)


def fused_route_default(nrfft: int, ncfft: int) -> str:
    """Trace-time route resolution for ``sspec_fused(route="auto")``:
    Pallas kernels on a real TPU with conforming grids, the
    restructured XLA lowering everywhere else (CPU CI, the f64-oracle
    re-trace, non-conforming fast-composite grids)."""
    from .pallas_common import pallas_interpret_default

    if pallas_interpret_default():
        return "xla"
    return "pallas" if _pallas_conforming(nrfft, ncfft) else "xla"


@functools.lru_cache(maxsize=32)
def _window_vectors(nf: int, nt: int, window: str | None,
                    window_frac: float) -> tuple:
    """Host-side split-window row/column tapers (ones when windowing is
    off) plus their product sum — static per template, folded into the
    trace as constants exactly like the chain's apply_2d_window."""
    if window is None:
        fw = np.ones(nf)
        tw = np.ones(nt)
    else:
        fw = split_window(nf, window, window_frac)
        tw = split_window(nt, window, window_frac)
    return fw, tw, float(fw.sum() * tw.sum())


@functools.lru_cache(maxsize=32)
def _dft_mats(R: int, rows: int, nrfft: int) -> tuple:
    """cos/sin DFT matrices [R, rows] of the delay-axis transform
    (``X[r] = sum_k pw[k] * e^{-2pi i r k / nrfft}``), built host-side
    in f64 and cast to f32 constants (phase accuracy must not depend on
    f32 evaluation of large 2*pi*r*k products)."""
    ph = (2.0 * np.pi / nrfft) * np.outer(np.arange(R, dtype=np.float64),  # host-f64: DFT phase precompute
                                          np.arange(rows, dtype=np.float64))  # host-f64: DFT phase precompute
    return (np.cos(ph).astype(np.float32),
            np.sin(ph).astype(np.float32))


# ---------------------------------------------------------------------------
# prologue kernel: mean-sub + window + prewhiten + zero-pad, one pass
# ---------------------------------------------------------------------------


def _prologue_kernel(dp_ref, fw_ref, tw_ref, m2_ref, out_ref, *,
                     rb: int, nf: int, nt: int, prewhite: bool,
                     out_cols: int):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    r0 = i * rb
    valid_rows = nf - 1 if prewhite else nf
    dtype = out_ref.dtype

    @pl.when(r0 < valid_rows)
    def _compute():
        # rb+1 rows cover the second-difference stencil; the input is
        # padded past nf so the slice never clamps (clamping would
        # misalign the straddle block's valid rows)
        a = dp_ref[pl.ds(r0, rb + 1), :]              # [rb+1, nt]
        wv = fw_ref[pl.ds(r0, rb + 1), :]             # [rb+1, 1]
        dw = a * wv * tw_ref[0:1, :] - m2_ref[0:1, 0:1]
        if prewhite:
            # separable 2nd difference == convolve2d([[1,-1],[-1,1]])
            blk = (dw[1:, 1:] - dw[1:, :-1]
                   - dw[:-1, 1:] + dw[:-1, :-1])      # [rb, nt-1]
            ncols_v = nt - 1
        else:
            blk = dw[:rb, :]
            ncols_v = nt
        rows = r0 + lax.broadcasted_iota(jnp.int32, (rb, 1), 0)
        blk = jnp.where(rows < valid_rows, blk, jnp.zeros((), dtype))
        if out_cols > ncols_v:
            blk = jnp.pad(blk, ((0, 0), (0, out_cols - ncols_v)))
        out_ref[...] = blk

    @pl.when(r0 >= valid_rows)
    def _zero_pad():
        out_ref[...] = jnp.zeros((rb, out_cols), dtype)


def sspec_prologue_pallas(dyn, m1, m2, window: str | None = "blackman",
                          window_frac: float = 0.1, *, out_rows: int,
                          out_cols: int, prewhite: bool = True,
                          block_rows: int | None = None,
                          interpret=False):
    """Fused FFT prologue: ``(dyn - m1) * W - m2``, prewhitened
    (2x2 second difference) and zero-padded to ``[out_rows, out_cols]``
    — the delay-axis FFT's input buffer — in ONE kernel pass.

    ``dyn`` [nf, nt] f32; ``m1``/``m2`` the chain's two mean
    subtractions (traced scalars — the caller computes them as fused
    reductions, see :func:`sspec_fused`); the window tapers are static
    host-side constants.  The chain's four elementwise intermediates
    (mean-sub, windowed, re-centred, prewhitened) never touch HBM.
    vmap over a batch axis works (pallas batching rule).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dyn = jnp.asarray(dyn)
    nf, nt = dyn.shape
    out_rows = round_up(out_rows, SUBLANE)
    rb = (pick_row_block(out_rows) if block_rows is None
          else int(block_rows))
    if out_rows % rb:
        raise ValueError(f"block_rows={rb} must divide out_rows="
                         f"{out_rows}")
    fw, tw, _sw = _window_vectors(nf, nt, window, float(window_frac))
    # input padded past the last stencil read so dynamic slices never
    # clamp (pad rows are masked out of the output anyway)
    nf_pad = round_up(nf + rb + 1, SUBLANE)
    dp = jnp.pad(dyn - m1, ((0, nf_pad - nf), (0, 0)))
    fwp = jnp.zeros((nf_pad, 1), dyn.dtype).at[:nf, 0].set(
        jnp.asarray(fw, dyn.dtype))
    twp = jnp.asarray(tw, dyn.dtype)[None, :]
    m2a = jnp.full((1, 1), 1.0, dyn.dtype) * m2
    grid = (out_rows // rb,)
    return pl.pallas_call(
        functools.partial(_prologue_kernel, rb=rb, nf=nf, nt=nt,
                          prewhite=bool(prewhite), out_cols=int(out_cols)),
        grid=grid,
        in_specs=[
            resident_spec((nf_pad, nt)),
            resident_spec((nf_pad, 1)),
            resident_spec((1, nt)),
            resident_spec((1, 1)),
        ],
        out_specs=row_tile_spec(rb, int(out_cols)),
        out_shape=jax.ShapeDtypeStruct((out_rows, int(out_cols)),
                                       dyn.dtype),
        interpret=resolve_interpret(interpret),
    )(dp, fwp, twp, m2a)


# ---------------------------------------------------------------------------
# epilogue kernel: |.|^2 + fftshift + postdark + log10 + crop, tiled
# ---------------------------------------------------------------------------


def _epilogue_kernel(re_ref, im_ref, out_ref, *, rb: int, H: int,
                     nrfft: int, ncfft: int, prewhite: bool, db: bool):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    j = pl.program_id(1)
    dtype = out_ref.dtype
    re = re_ref[...]
    im = im_ref[...]
    sec = re * re + im * im
    if prewhite:
        # postdark generated on-core from iota: the sin^2 response of
        # the 2x2 prewhitening filter, singular fdop=0 column / tdel=0
        # row forced to 1 (dynspec.py:1308-1309).  Output col c of
        # block j is c = j*H + l -> fd = c - H = (j-1)*H + l, already
        # the well-conditioned +-H/2-centred argument (evaluating
        # sin(pi*c/ncfft) near pi instead loses the small postdark
        # values to cancellation — measured 1e-4-scale spectrum errors)
        row = (i * rb
               + lax.broadcasted_iota(jnp.int32, (rb, H), 0))
        fd = ((j - 1) * H
              + lax.broadcasted_iota(jnp.int32, (rb, H), 1))
        v2 = jnp.sin((np.pi / nrfft) * row.astype(dtype)) ** 2
        v1 = jnp.sin((np.pi / ncfft) * fd.astype(dtype)) ** 2
        pd = jnp.where((row == 0) | (fd == 0), jnp.ones((), dtype),
                       v2 * v1)
        sec = sec / pd
    if db:
        sec = 10.0 * jnp.log10(sec)
    out_ref[...] = sec


def sspec_epilogue_pallas(re, im, *, nrfft: int, ncfft: int,
                          prewhite: bool = True, db: bool = True,
                          block_rows: int | None = None,
                          interpret=False):
    """Fused FFT epilogue over the (already delay-cropped) Doppler-axis
    FFT output: power, Doppler fftshift, postdark divide and dB — all
    tile-by-tile, never materialising intermediate spectra.

    ``re``/``im`` [R, ncfft] f32 (real/imaginary planes — Mosaic has no
    complex dtype, and the axon TPU backend implements no complex ops;
    see ops/nudft.py's re/im convention).  Rows are the kept delay
    window (crop already applied by the caller's row slice — this
    kernel only ever touches consumed rows).  The Doppler fftshift is
    block-index remapping: output column tile ``j`` (of 2 half-axis
    tiles) reads input tile ``1-j`` — zero extra HBM traffic.  Returns
    [R, ncfft].  vmap over a batch axis works.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    re = jnp.asarray(re)
    im = jnp.asarray(im)
    if re.shape != im.shape:
        raise ValueError(f"re/im shape mismatch: {re.shape} vs {im.shape}")
    R, nc = re.shape
    if nc != ncfft:
        raise ValueError(f"expected {ncfft} Doppler columns, got {nc}")
    if ncfft % 2:
        raise ValueError(f"ncfft must be even (fftshift halves), got "
                         f"{ncfft}")
    H = ncfft // 2
    R_pad = round_up(R, SUBLANE)
    rb = (pick_row_block(R_pad) if block_rows is None else int(block_rows))
    if R_pad % rb:
        raise ValueError(f"block_rows={rb} must divide padded R={R_pad}")
    if R_pad != R:
        # pad value 1.0 keeps the padded rows' log10 finite (they are
        # sliced off below; -inf there would only trip jax_debug_nans
        # during exactly the hardware A/B this kernel exists for)
        re = jnp.pad(re, ((0, R_pad - R), (0, 0)), constant_values=1.0)
        im = jnp.pad(im, ((0, R_pad - R), (0, 0)), constant_values=0.0)
    shift_spec = pl.BlockSpec((rb, H), lambda i, j: (i, 1 - j))
    out = pl.pallas_call(
        functools.partial(_epilogue_kernel, rb=rb, H=H, nrfft=int(nrfft),
                          ncfft=int(ncfft), prewhite=bool(prewhite),
                          db=bool(db)),
        grid=(R_pad // rb, 2),
        in_specs=[shift_spec, shift_spec],
        out_specs=pl.BlockSpec((rb, H), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((R_pad, ncfft), re.dtype),
        interpret=resolve_interpret(interpret),
    )(re, im)
    return out[:R]


# ---------------------------------------------------------------------------
# the fused op
# ---------------------------------------------------------------------------


def _prewhiten2x2(dw):
    """Separable 2x2 second difference == convolve2d([[1,-1],[-1,1]],
    'valid') — ONE definition for both XLA branches, so a numerics fix
    can never split the two fused forms silently."""
    return dw[1:, 1:] - dw[1:, :-1] - dw[:-1, 1:] + dw[:-1, :-1]


def _means(d, fw, tw, sw: float, window, chain_exact: bool):
    """The chain's two mean subtractions: ``(m1, m2, dwc)`` where
    ``dwc`` is the fully re-centred windowed array (or None).

    ``chain_exact`` (the XLA lowering) materialises the windowed array
    for m2 exactly like the chain — XLA fuses it away, and the parity
    vs the chain tightens ~4x at small postdark-amplified crops.  The
    Pallas lowering computes m2 as one weighted reduction instead
    (``(sum(d*W) - m1*sum(W)) / N``), preserving the prologue's
    one-write contract; the difference is fp-rounding-level and inside
    the fused route's documented fit budget."""
    import jax.numpy as jnp

    nf, nt = d.shape[-2], d.shape[-1]
    m1 = jnp.mean(d)
    if chain_exact:
        dw = d - m1
        if window is not None:
            dw = dw * tw[None, :] * fw[:, None]
        m2 = jnp.mean(dw)
        # the second subtraction is analytically a no-op under the
        # prewhitening difference, but its fp residue is postdark-
        # amplified at low delays — keep it, exactly like the chain
        return m1, m2, dw - m2
    m2 = (jnp.sum(d * fw[:, None] * tw[None, :])
          - m1 * jnp.asarray(sw, d.dtype)) / (nf * nt)
    return m1, m2, None


def _pass2_and_epilogue(X, R: int, nrfft: int, ncfft: int, prewhite: bool,
                        db: bool, route: str, interpret) -> "object":
    """Doppler-axis FFT epilogue shared by both pass-1 forms: ``X``
    [R, ncfft] complex -> shifted/postdarkened/dB [R, ncfft] real."""
    import jax.numpy as jnp
    from jax import lax

    if route == "pallas":
        return sspec_epilogue_pallas(jnp.real(X), jnp.imag(X),
                                     nrfft=nrfft, ncfft=ncfft,
                                     prewhite=prewhite, db=db,
                                     interpret=interpret)
    sec = jnp.real(X) ** 2 + jnp.imag(X) ** 2
    if prewhite:
        td = lax.iota(sec.dtype, R)[:, None]
        cc = lax.iota(jnp.int32, ncfft)[None, :]
        # well-conditioned postdark argument: evaluate sin at the
        # +-H-centred Doppler index, not near pi (see epilogue kernel)
        fd = (cc - jnp.where(cc >= ncfft // 2, ncfft, 0)).astype(sec.dtype)
        pd = jnp.where((td == 0) | (cc == 0), jnp.ones((), sec.dtype),
                       jnp.sin((np.pi / nrfft) * td) ** 2
                       * jnp.sin((np.pi / ncfft) * fd) ** 2)
        sec = sec / pd
    if db:
        sec = 10.0 * jnp.log10(sec)
    # ONE roll moves the unshifted-order result into the chain's
    # fftshifted layout (the pallas epilogue does this as block-index
    # remapping instead)
    return jnp.roll(sec, ncfft // 2, axis=-1)


def _sspec_fused_2d(d, prewhite: bool, window, window_frac: float,
                    db: bool, lens: str, crop_rows, route: str,
                    interpret):
    """One-epoch fused secondary spectrum (see :func:`sspec_fused`)."""
    import jax.numpy as jnp
    from jax import lax

    from .sspec import fft_lens

    nf, nt = d.shape
    nrfft, ncfft = fft_lens(nf, nt, lens)
    R = nrfft // 2 if crop_rows is None else int(crop_rows)
    if route == "auto":
        route = fused_route_default(nrfft, ncfft)
    fw_np, tw_np, sw = _window_vectors(nf, nt, window, float(window_frac))
    fw = jnp.asarray(fw_np, d.dtype)
    tw = jnp.asarray(tw_np, d.dtype)
    m1, m2, dw = _means(d, fw, tw, sw, window,
                        chain_exact=(route != "pallas"))
    ntw = nt - 1 if prewhite else nt

    if use_dft_pass1(crop_rows, nrfft):
        # crop-fused FFT split: the delay transform evaluates ONLY the
        # R kept rows, as an exact DFT matmul over the nf-1 nonzero
        # input rows (zero padding contributes nothing to the sum) —
        # MXU-shaped on TPU, and the full [nrfft/2, ncfft] spectrum is
        # never materialised
        if route == "pallas":
            rows = round_up(nf - 1 if prewhite else nf, SUBLANE)
            pw = sspec_prologue_pallas(
                d, m1, m2, window, window_frac, out_rows=rows,
                out_cols=ntw, prewhite=prewhite, interpret=interpret)
        else:
            pw = _prewhiten2x2(dw) if prewhite else dw
            rows = pw.shape[0]
        C, S = _dft_mats(R, int(rows), nrfft)
        hi = lax.Precision.HIGHEST
        # HIGHEST precision: the MXU's default bf16 passes cost ~100x
        # accuracy on exactly this contraction class (the ops/nudft.py
        # on-chip finding; scripts/tpu_recheck.sh guards it there)
        re1 = jnp.matmul(jnp.asarray(C, d.dtype), pw, precision=hi)
        im1 = -jnp.matmul(jnp.asarray(S, d.dtype), pw, precision=hi)
        X = jnp.fft.fft(lax.complex(re1, im1), n=ncfft, axis=-1)
        return _pass2_and_epilogue(X, R, nrfft, ncfft, prewhite, db,
                                   route, interpret)

    # wide-window form: same padded-grid rfftn as the chain (the real
    # delay axis is already Hermitian-halved there; a transform split
    # would only add a complex-pad round-trip), with the prologue fused
    # into one padded write and the epilogue restructured/tiled
    if route == "pallas":
        P = sspec_prologue_pallas(
            d, m1, m2, window, window_frac, out_rows=nrfft,
            out_cols=ncfft, prewhite=prewhite, interpret=interpret)
        X = jnp.fft.rfftn(P, axes=(-1, -2))[:R, :]
    else:
        pw = _prewhiten2x2(dw) if prewhite else dw
        X = jnp.fft.rfftn(pw, s=(ncfft, nrfft), axes=(-1, -2))[:R, :]
    return _pass2_and_epilogue(X, R, nrfft, ncfft, prewhite, db,
                               route, interpret)


def sspec_fused(dyn, prewhite: bool = True, window: str | None = "blackman",
                window_frac: float = 0.1, db: bool = True,
                lens: str = "pow2", crop_rows: int | None = None,
                route: str = "auto", interpret=False):
    """Fused secondary spectrum of ``dyn`` [..., nf, nt] — the
    ``PipelineConfig.fused_sspec`` jax-path implementation.

    Same contract as :func:`scintools_tpu.ops.sspec.sspec` (jax
    backend): returns [..., R, ncfft] in dB, positive delays only,
    ``crop_rows`` keeping the first R rows.  NOT bit-identical to the
    chain (fp association differs through the fused/split transform);
    fit-level parity is the documented 2 % budget.

    ``route``: ``"pallas"`` (real-Mosaic kernels; ``interpret=True``
    for CPU parity tests), ``"xla"`` (the restructured pure-XLA
    lowering), or ``"auto"`` (trace-time: pallas on a real TPU with
    conforming grids, xla elsewhere).
    """
    import jax
    import jax.numpy as jnp

    dyn = jnp.asarray(dyn)
    if dyn.ndim < 2 or dyn.shape[-2] < 2 or dyn.shape[-1] < 2:
        raise ValueError(f"secondary spectrum needs at least a 2x2 "
                         f"dynspec, got {dyn.shape} (prewhitening "
                         f"differences both axes)")
    if route not in ("auto", "pallas", "xla"):
        raise ValueError(f"sspec_fused route must be 'auto', 'pallas' "
                         f"or 'xla', got {route!r}")
    core = functools.partial(_sspec_fused_2d, prewhite=bool(prewhite),
                             window=window, window_frac=float(window_frac),
                             db=bool(db), lens=lens, crop_rows=crop_rows,
                             route=route, interpret=interpret)
    if dyn.ndim == 2:
        return core(dyn)
    lead = dyn.shape[:-2]
    flat = dyn.reshape((-1,) + dyn.shape[-2:])
    out = jax.vmap(core)(flat)
    return out.reshape(lead + out.shape[-2:])
