"""Arc row-resample + delay-scrunch: production scan path + Pallas kernel.

The arc fitter's hot op (fit/arc_fit.py) is, per epoch: gather each
delay row of the secondary spectrum onto a row-specific normalised
Doppler grid (static indices/weights [R, n]) and nanmean over rows.

* :func:`row_scrunch_scan` — the PRODUCTION path for
  ``arc_scrunch_rows > 0`` (the auto default on every target): a ``lax.scan`` over
  row blocks that bounds the working set to [block_r, n].  The arc
  fitter calls it directly.
* :func:`row_scrunch_pallas` — EXPERIMENTAL fused kernel: gather +
  interpolate + NaN-masked accumulation in VMEM so the [rb, n]
  intermediates never touch HBM.  Validated in INTERPRET mode only
  (tests/test_resample_pallas.py is CPU); `scripts/tpu_recheck.sh`
  carries the real-Mosaic lowering gate (the per-lane
  ``take_along_axis`` is exactly the op Mosaic may refuse or
  serialise) and `benchmarks/pallas_ab.py` races it against
  row_scrunch_scan for the wire/remove decision.  NOT wired into
  make_arc_fitter until it measures faster on hardware.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["row_scrunch_pallas", "row_scrunch_scan"]


def row_scrunch_scan(rows, i0, w, block_r: int = 64):
    """PRODUCTION delay-scrunch: NaN-skipping nanmean of row-resampled
    spectra via a ``lax.scan`` over ``block_r``-row blocks (the arc
    fitter's TPU auto default — bounds the working set to [block_r, n]
    instead of materialising [R, n] gathers; fit/arc_fit.py calls this,
    and benchmarks/pallas_ab.py A/Bs ``row_scrunch_pallas`` against it,
    so kernel and baseline can never drift apart silently).

    Same arguments as :func:`row_scrunch_pallas`; same math modulo
    floating-point association.  NaN-padded tail rows contribute
    nothing; a -inf value (zero-power dB pixel) poisons its bin's mean
    exactly as the full-gather path would.
    """
    import jax
    import jax.numpy as jnp

    rows = jnp.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(
            f"row_scrunch_scan expects 2-D [R, C] rows, got shape "
            f"{rows.shape}; batched callers must vmap (as the arc "
            f"fitter and the A/B harness do)")
    i0 = jnp.asarray(i0, dtype=jnp.int32)
    R, C = rows.shape
    n = i0.shape[-1]
    w = jnp.asarray(w, dtype=rows.dtype)
    block_r = min(block_r, R)
    nb = -(-R // block_r)
    pad = nb * block_r - R
    rows_b = jnp.pad(rows, ((0, pad), (0, 0)),
                     constant_values=np.nan).reshape(nb, block_r, C)
    i0_b = jnp.pad(i0, ((0, pad), (0, 0))).reshape(nb, block_r, n)
    w_b = jnp.pad(w, ((0, pad), (0, 0))).reshape(nb, block_r, n)

    def body(carry, xs):
        s, c = carry
        rc, ic, wc = xs
        v0 = jnp.take_along_axis(rc, ic, axis=1)
        v1 = jnp.take_along_axis(rc, ic + 1, axis=1)
        nrm = v0 * (1.0 - wc) + v1 * wc
        # nanmean semantics exactly: skip NaN only
        keep = ~jnp.isnan(nrm)
        s = s + jnp.sum(jnp.where(keep, nrm, 0.0), axis=0)
        c = c + jnp.sum(keep.astype(s.dtype), axis=0)
        return (s, c), None

    (s, c), _ = jax.lax.scan(
        body, (jnp.zeros(n, rows.dtype), jnp.zeros(n, rows.dtype)),
        (rows_b, i0_b, w_b))
    return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)


def _kernel(rows_ref, i0_ref, w_ref, sum_ref, cnt_ref):
    import jax.numpy as jnp

    rows = rows_ref[...]                       # [rb, C]
    i0 = i0_ref[...]                           # [rb, n]
    w = w_ref[...].astype(rows.dtype)          # [rb, n]
    v0 = jnp.take_along_axis(rows, i0, axis=1)
    v1 = jnp.take_along_axis(rows, i0 + 1, axis=1)
    nrm = v0 * (1.0 - w) + v1 * w
    keep = ~jnp.isnan(nrm)
    sum_ref[...] = jnp.sum(jnp.where(keep, nrm, 0.0), axis=0,
                           keepdims=True)
    cnt_ref[...] = jnp.sum(keep.astype(rows.dtype), axis=0,
                           keepdims=True)


@functools.lru_cache(maxsize=8)
def _build(R: int, C: int, n: int, block_r: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nb = -(-R // block_r)

    def run(rows, i0, w):
        pad_r = nb * block_r - R
        # NaN row padding contributes nothing (keep=False lanes)
        rows_p = jnp.pad(rows, ((0, pad_r), (0, 0)),
                         constant_values=np.nan)
        i0_p = jnp.pad(i0, ((0, pad_r), (0, 0)))
        w_p = jnp.pad(w, ((0, pad_r), (0, 0)))
        s, c = pl.pallas_call(
            _kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block_r, C), lambda b: (b, 0)),
                pl.BlockSpec((block_r, n), lambda b: (b, 0)),
                pl.BlockSpec((block_r, n), lambda b: (b, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, n), lambda b: (b, 0)),
                pl.BlockSpec((1, n), lambda b: (b, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((nb, n), rows.dtype),
                jax.ShapeDtypeStruct((nb, n), rows.dtype),
            ],
            interpret=interpret,
        )(rows_p, i0_p, w_p)
        cnt = jnp.sum(c, axis=0)
        # guarded denominator, as the production scan path does: the 0/0
        # of an all-NaN bin is discarded by the where but would trip
        # jax_debug_nans during exactly the hardware A/B this exists for
        return jnp.where(cnt > 0,
                         jnp.sum(s, axis=0) / jnp.maximum(cnt, 1.0),
                         jnp.nan)

    return jax.jit(run)


def row_scrunch_pallas(rows, i0, w, block_r: int = 64,
                       interpret: bool = False):
    """NaN-skipping delay-scrunch of row-resampled spectra.

    ``rows`` [R, C] (one epoch's masked sspec rows), ``i0``/``w``
    [R, n] static gather indices and linear-interp weights (from the
    arc fitter's `_row_interp_pattern`).  Returns the [n] profile:
    nanmean over rows of ``rows[r, i0[r, j]] * (1-w) + rows[r, i0+1] * w``
    — bit-compatible with the production paths' math (modulo f.p.
    association).  vmap over a batch axis works (pallas batching rule).
    """
    import jax.numpy as jnp

    rows = jnp.asarray(rows)
    i0 = jnp.asarray(i0, dtype=jnp.int32)
    w = jnp.asarray(w)
    R, C = rows.shape[-2], rows.shape[-1]
    if C < 2:
        raise ValueError(f"rows needs >= 2 columns to interpolate, got {C}")
    n = i0.shape[-1]
    if i0.shape[-2] != R or w.shape[-2:] != (R, n):
        raise ValueError(f"shape mismatch: rows [{R},{C}], i0 "
                         f"{i0.shape}, w {w.shape}")
    # public A/B entry point: an out-of-range gather inside a real
    # Mosaic kernel is UB that interpret-mode tests cannot catch.  Guard
    # by clamping the anchor in range with the weight pinned to the edge
    # sample, still evaluated through the same lerp as in-range lanes —
    # so a NaN edge NEIGHBOUR NaN-poisons the lane exactly as the
    # production paths' math would (NaN*0 is NaN), which is the
    # bit-compat contract; this is edge-value clamping only for finite
    # neighbourhoods, not a full select
    w = jnp.where(i0 > C - 2, w.dtype.type(1),
                  jnp.where(i0 < 0, w.dtype.type(0), w))
    i0 = jnp.clip(i0, 0, C - 2)
    return _build(int(R), int(C), int(n), int(min(block_r, R)),
                  bool(interpret))(rows, i0, w)
