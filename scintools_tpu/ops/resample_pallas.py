"""Arc row-resample + delay-scrunch: production scan path + Pallas kernel.

The arc fitter's hot op (fit/arc_fit.py) is, per epoch: gather each
delay row of the secondary spectrum onto a row-specific normalised
Doppler grid (static indices/weights [R, n]) and nanmean over rows.

* :func:`row_scrunch_pallas` — the on-chip PRODUCTION path since round
  4 (`arc_scrunch_rows=-1` auto on TPU): gather + interpolate +
  NaN-masked accumulation fused in VMEM so the [rb, n] intermediates
  never touch HBM.  Measured 3.5x the scan path at the bench shape
  with 1e-7 agreement (benchmarks/pallas_ab.py, the regression guard);
  `scripts/tpu_recheck.sh` carries the real-Mosaic correctness gate.
  CPU executions (CI, forced route) run it in interpret mode.
* :func:`row_scrunch_scan` — the host-CPU auto route
  (``arc_scrunch_rows > 0``): a ``lax.scan`` over row blocks that
  bounds the working set to [block_r, n].  Also the fallback for
  Doppler widths the Mosaic gather decomposition cannot tile
  (ncol >= 128 and not a multiple of 128 — unreachable via the
  pipeline, whose FFT grids are pow2).
"""

from __future__ import annotations

import functools

import numpy as np

# the "am I on a real TPU" trace-time probe moved to the shared helper
# layer (ops/pallas_common) when the fused sspec kernels joined; the
# re-export keeps the historical import site working (the arc fitter
# and tests import it from here)
from .pallas_common import pallas_interpret_default  # noqa: F401

__all__ = ["row_scrunch_pallas", "row_scrunch_scan",
           "pallas_interpret_default"]


def row_scrunch_scan(rows, i0, w, block_r: int = 64):
    """PRODUCTION delay-scrunch: NaN-skipping nanmean of row-resampled
    spectra via a ``lax.scan`` over ``block_r``-row blocks (the arc
    fitter's TPU auto default — bounds the working set to [block_r, n]
    instead of materialising [R, n] gathers; fit/arc_fit.py calls this,
    and benchmarks/pallas_ab.py A/Bs ``row_scrunch_pallas`` against it,
    so kernel and baseline can never drift apart silently).

    Same arguments as :func:`row_scrunch_pallas`; same math modulo
    floating-point association.  NaN-padded tail rows contribute
    nothing; a -inf value (zero-power dB pixel) poisons its bin's mean
    exactly as the full-gather path would.
    """
    import jax
    import jax.numpy as jnp

    rows = jnp.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(
            f"row_scrunch_scan expects 2-D [R, C] rows, got shape "
            f"{rows.shape}; batched callers must vmap (as the arc "
            f"fitter and the A/B harness do)")
    i0 = jnp.asarray(i0, dtype=jnp.int32)
    R, C = rows.shape
    n = i0.shape[-1]
    w = jnp.asarray(w, dtype=rows.dtype)
    block_r = min(block_r, R)
    nb = -(-R // block_r)
    pad = nb * block_r - R
    rows_b = jnp.pad(rows, ((0, pad), (0, 0)),
                     constant_values=np.nan).reshape(nb, block_r, C)
    i0_b = jnp.pad(i0, ((0, pad), (0, 0))).reshape(nb, block_r, n)
    w_b = jnp.pad(w, ((0, pad), (0, 0))).reshape(nb, block_r, n)

    # Column-reduction strategy (round-5 CPU finding, measured in
    # docs/performance.md): jnp.sum(axis=0) over a fused masked block
    # lowers on XLA CPU to a scalarised strided loop ~4.4x slower than
    # a GEMM, and the gathers themselves are cheap — the old
    # sum/count accumulation was the CPU fallback's binder.  So each
    # block stacks FOUR inf-free row groups — the inf-clamped values,
    # the not-NaN mask, and the -inf/+inf indicators — into one
    # materialised [4*block_r, n] matrix (the concat is the fusion
    # barrier that stops XLA folding the mask math back into the
    # reduction loop) and reduces all four with ONE [4, 4*block_r]
    # GEMM.  The inf counts reconstruct nanmean's exact semantics
    # afterwards (-inf poisons its bin, +inf likewise, both -> NaN),
    # because a 0-weight times an infinity inside the GEMM would be
    # NaN.  Oracle-tested against np.nanmean over the lerp, including
    # the inf hazards (tests/test_resample_pallas.py::
    # test_row_scrunch_scan_inf_nan_oracle).  Precision pinned so the
    # TPU route cannot silently take a bf16 MXU pass (same guard as
    # the NUDFT einsum, ops/nudft.py).
    # block-identity selector: row g sums group g's block_r rows
    sel = jnp.kron(jnp.eye(4, dtype=rows.dtype),
                   jnp.ones(block_r, rows.dtype))
    hi = jax.lax.Precision.HIGHEST

    def body(acc, xs):
        rc, ic, wc = xs
        # mode="clip": the indices are host-clamped to [0, ncol-2]
        # (arc_fit._row_interp_pattern), so the default fill mode's
        # out-of-bounds masks are dead weight — and XLA constant-folds
        # those [R, n] masks at COMPILE time, which measured ~8 s of
        # the step's cold compile at a 2000-point eta grid (values are
        # identical either way; tier-1 pins the profile bytes)
        v0 = jnp.take_along_axis(rc, ic, axis=1, mode="clip")
        v1 = jnp.take_along_axis(rc, ic + 1, axis=1, mode="clip")
        nrm = v0 * (1.0 - wc) + v1 * wc
        keep = ~jnp.isnan(nrm)
        fin = jnp.isfinite(nrm)
        st = jnp.concatenate([
            jnp.where(fin, nrm, 0.0),
            keep.astype(rows.dtype),
            (nrm == -jnp.inf).astype(rows.dtype),
            (nrm == jnp.inf).astype(rows.dtype)], axis=0)
        return acc + jnp.matmul(sel, st, precision=hi), None

    acc, _ = jax.lax.scan(body, jnp.zeros((4, n), rows.dtype),
                          (rows_b, i0_b, w_b))
    s, c, nneg, npos = acc[0], acc[1], acc[2], acc[3]
    s = jnp.where(nneg > 0, jnp.where(npos > 0, jnp.nan, -jnp.inf),
                  jnp.where(npos > 0, jnp.inf, s))
    return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)


def _kernel(rows_ref, i0_ref, w_ref, sum_ref, cnt_ref, *, L):
    import jax.numpy as jnp

    rows = rows_ref[...]                       # [rb, C]
    C = rows.shape[1]
    n_pad = i0_ref.shape[1]                    # padded to a multiple of L
    # Real-Mosaic gather constraints (probed on the axon TPU): the
    # index array must MATCH the operand shape, and tpu.dynamic_gather
    # compiles only within one 128-lane vector register — a 256-lane
    # same-shape gather dies in the backend.  So the n resample lanes
    # are walked in L(=128)-lane chunks, and each chunk gathers from
    # every 128-lane source segment with local indices, keeping the
    # in-range segment's lanes (static unrolled double loop; selects
    # are VPU-cheap next to the HBM traffic this kernel avoids).
    for k in range(n_pad // L):
        i0 = i0_ref[:, k * L:(k + 1) * L]      # [rb, L] static slice
        w = w_ref[:, k * L:(k + 1) * L].astype(rows.dtype)
        v0 = jnp.zeros(i0.shape, rows.dtype)
        v1 = jnp.zeros(i0.shape, rows.dtype)
        for s in range(C // L):
            seg = rows[:, s * L:(s + 1) * L]   # [rb, L] register-width
            loc0 = i0 - s * L
            g0 = jnp.take_along_axis(seg, jnp.clip(loc0, 0, L - 1),
                                     axis=1, mode="clip")
            v0 = jnp.where((loc0 >= 0) & (loc0 < L), g0, v0)
            loc1 = loc0 + 1
            g1 = jnp.take_along_axis(seg, jnp.clip(loc1, 0, L - 1),
                                     axis=1, mode="clip")
            v1 = jnp.where((loc1 >= 0) & (loc1 < L), g1, v1)
        nrm = v0 * (1.0 - w) + v1 * w
        keep = ~jnp.isnan(nrm)
        # Mosaic also requires the last two block dims to be (8k, 128k)
        # or the full array dims — a [1, n] per-block row violates the
        # sublane rule — so each block's partials are broadcast across
        # one full 8-sublane tile; the host-side reducer reads sublane 0.
        sm = jnp.sum(jnp.where(keep, nrm, 0.0), axis=0, keepdims=True)
        ct = jnp.sum(keep.astype(rows.dtype), axis=0, keepdims=True)
        sum_ref[0, :, k * L:(k + 1) * L] = jnp.broadcast_to(sm, (8, L))
        cnt_ref[0, :, k * L:(k + 1) * L] = jnp.broadcast_to(ct, (8, L))


@functools.lru_cache(maxsize=8)
def _build(R: int, C: int, n: int, block_r: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nb = -(-R // block_r)

    L = min(128, C)                          # gather register width
    if C % L:
        raise ValueError(
            f"row_scrunch_pallas requires C to be a multiple of 128 (or "
            f"C < 128), got C={C}: the Mosaic dynamic_gather decomposition "
            f"works in 128-lane segments; use row_scrunch_scan instead")
    n_pad = -(-n // L) * L                   # chunked same-shape gathers

    def run(rows, i0, w):
        pad_r = nb * block_r - R
        # NaN row padding contributes nothing (keep=False lanes); lane
        # padding gathers index 0 with weight 0 and is sliced off below
        rows_p = jnp.pad(rows, ((0, pad_r), (0, 0)),
                         constant_values=np.nan)
        i0_p = jnp.pad(i0, ((0, pad_r), (0, n_pad - n)))
        w_p = jnp.pad(w, ((0, pad_r), (0, n_pad - n)))
        s, c = pl.pallas_call(
            functools.partial(_kernel, L=L),
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block_r, C), lambda b: (b, 0)),
                pl.BlockSpec((block_r, n_pad), lambda b: (b, 0)),
                pl.BlockSpec((block_r, n_pad), lambda b: (b, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 8, n_pad), lambda b: (b, 0, 0)),
                pl.BlockSpec((1, 8, n_pad), lambda b: (b, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((nb, 8, n_pad), rows.dtype),
                jax.ShapeDtypeStruct((nb, 8, n_pad), rows.dtype),
            ],
            interpret=interpret,
        )(rows_p, i0_p, w_p)
        s, c = s[:, 0, :n], c[:, 0, :n]  # sublanes 1-7 are tile copies
        cnt = jnp.sum(c, axis=0)
        # guarded denominator, as the production scan path does: the 0/0
        # of an all-NaN bin is discarded by the where but would trip
        # jax_debug_nans during exactly the hardware A/B this exists for
        return jnp.where(cnt > 0,
                         jnp.sum(s, axis=0) / jnp.maximum(cnt, 1.0),
                         jnp.nan)

    return jax.jit(run)


def row_scrunch_pallas(rows, i0, w, block_r: int = 64,
                       interpret=False):
    """NaN-skipping delay-scrunch of row-resampled spectra.

    ``rows`` [R, C] (one epoch's masked sspec rows), ``i0``/``w``
    [R, n] static gather indices and linear-interp weights (from the
    arc fitter's `_row_interp_pattern`).  Returns the [n] profile:
    nanmean over rows of ``rows[r, i0[r, j]] * (1-w) + rows[r, i0+1] * w``
    — bit-compatible with the production paths' math (modulo f.p.
    association).  vmap over a batch axis works (pallas batching rule).
    """
    import jax.numpy as jnp

    rows = jnp.asarray(rows)
    i0 = jnp.asarray(i0, dtype=jnp.int32)
    w = jnp.asarray(w)
    R, C = rows.shape[-2], rows.shape[-1]
    if C < 2:
        raise ValueError(f"rows needs >= 2 columns to interpolate, got {C}")
    n = i0.shape[-1]
    if i0.shape[-2] != R or w.shape[-2:] != (R, n):
        raise ValueError(f"shape mismatch: rows [{R},{C}], i0 "
                         f"{i0.shape}, w {w.shape}")
    # public A/B entry point: an out-of-range gather inside a real
    # Mosaic kernel is UB that interpret-mode tests cannot catch.  Guard
    # by clamping the anchor in range with the weight pinned to the edge
    # sample, still evaluated through the same lerp as in-range lanes —
    # so a NaN edge NEIGHBOUR NaN-poisons the lane exactly as the
    # production paths' math would (NaN*0 is NaN), which is the
    # bit-compat contract; this is edge-value clamping only for finite
    # neighbourhoods, not a full select
    w = jnp.where(i0 > C - 2, w.dtype.type(1),
                  jnp.where(i0 < 0, w.dtype.type(0), w))
    i0 = jnp.clip(i0, 0, C - 2)
    if interpret == "auto":
        # resolved at TRACE time, so a TPU-built fitter re-traced under
        # jax.default_device(cpu) (the f64-oracle pattern) flips to
        # interpret mode instead of failing to lower
        interpret = pallas_interpret_default()
    return _build(int(R), int(C), int(n), int(min(block_r, R)),
                  bool(interpret))(rows, i0, w)
