"""2-D autocovariance of a dynamic spectrum (Wiener–Khinchin).

Reference: ``Dynspec.calc_acf`` (dynspec.py:1337-1360): mean-subtract ->
``fft2`` zero-padded to [2nf, 2nt] -> |.|^2 -> ``ifft2`` -> ``fftshift`` ->
real part.

numpy path reproduces that exactly (including taking the mean over valid
pixels only, dynspec.py:1344).  jax path is the same math on ``jnp.fft``,
jit-compiled, operating on the last two axes so it vmaps over a batch of
epochs for free.
"""

from __future__ import annotations

import functools

import numpy as np

from .. import obs
from ..backend import resolve


def _acf_pad_lens(nf: int, nt: int, lens: str) -> tuple[int, int]:
    """Padded Wiener–Khinchin FFT lengths.  ``"exact"`` is the
    reference's [2nf, 2nt] (dynspec.py:1348; the parity path).
    ``"fast"`` rounds each up to the next even 5-smooth composite —
    the linear autocovariance has support < 2n per axis, so any >= 2n
    zero-padding computes IDENTICAL values (the output is centre-cropped
    back to [2nf, 2nt]); the longer-but-smooth plan is faster whenever
    2n has a large prime factor."""
    if lens == "exact":
        return 2 * nf, 2 * nt
    if lens == "fast":
        from .sspec import next_fast_len

        return next_fast_len(2 * nf), next_fast_len(2 * nt)
    raise ValueError(f"acf lens must be 'exact' or 'fast', got {lens!r}")


def acf(dyn, backend: str = "numpy", subtract_mean: bool = True,
        lens: str = "exact"):
    """Autocovariance, output shape [..., 2*nf, 2*nt].

    ``lens="fast"`` (jax path) pads the internal FFT pair to 5-smooth
    composite lengths instead of exactly [2nf, 2nt]; the zero-padded
    linear autocovariance is unchanged (the extra bins are cropped), so
    values agree to FFT rounding — the plan, not the math, changes.
    """
    backend = resolve(backend)
    shape = np.shape(dyn)  # works for lists and device arrays alike
    if len(shape) < 2 or shape[-2] < 2 or shape[-1] < 2:
        raise ValueError(f"ACF needs at least a 2x2 dynspec, got {shape}")
    # eager calls time real (fenced) kernel work; calls under a jit trace
    # time trace construction inside the enclosing .compile span
    with obs.span("ops.acf", backend=backend, shape=list(shape)):
        if backend == "numpy":
            # numpy path = the reference parity path: always exact-2n
            return _acf_numpy(np.asarray(dyn), subtract_mean)
        return obs.fence(_acf_jax()(dyn, subtract_mean, lens))


def _acf_numpy(arr: np.ndarray, subtract_mean: bool) -> np.ndarray:
    if subtract_mean:
        # per-epoch valid-pixel mean (matches the jax path on batched input;
        # identical to the reference's global mean for a single epoch)
        valid = np.isfinite(arr)
        denom = np.maximum(valid.sum(axis=(-2, -1), keepdims=True), 1)
        mean = np.where(valid, arr, 0).sum(axis=(-2, -1), keepdims=True) / denom
        arr = arr - mean
    nf, nt = arr.shape[-2], arr.shape[-1]
    a = np.fft.fft2(arr, s=[2 * nf, 2 * nt])
    a = np.abs(a)
    a **= 2
    a = np.fft.ifft2(a)
    a = np.fft.fftshift(a, axes=(-2, -1))
    return np.real(a)


def _masked_mean_subtract(arr, jnp):
    """jit-friendly masked mean subtraction (no boolean indexing): invalid
    pixels are excluded via where=; matches numpy on gap-free input."""
    valid = jnp.isfinite(arr)
    denom = jnp.maximum(jnp.sum(valid, axis=(-2, -1), keepdims=True), 1)
    mean = (jnp.sum(jnp.where(valid, arr, 0.0), axis=(-2, -1),
                    keepdims=True) / denom)
    return arr - mean


@functools.lru_cache(maxsize=1)
def _acf_jax():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def impl(arr, subtract_mean, lens):
        if subtract_mean:
            arr = _masked_mean_subtract(arr, jnp)
        nf, nt = arr.shape[-2], arr.shape[-1]
        Lf, Lt = _acf_pad_lens(nf, nt, lens)
        # real input -> half-spectrum rfft2 (2x the work/memory of the
        # reference's complex fft2 pair, dynspec.py:1351-1356, saved); the
        # power spectrum of a real array is even, so irfft2 of the half
        # plane reconstructs the full autocovariance exactly
        a = jnp.fft.rfft2(arr, s=(Lf, Lt))
        p = jnp.real(a) ** 2 + jnp.imag(a) ** 2
        out = jnp.fft.irfft2(p, s=(Lf, Lt))
        out = jnp.fft.fftshift(out, axes=(-2, -1))
        if (Lf, Lt) != (2 * nf, 2 * nt):
            # centre crop back to the reference's [2nf, 2nt] window: the
            # extra padded bins are zero lags beyond the linear support
            r0, c0 = Lf // 2 - nf, Lt // 2 - nt
            out = out[..., r0:r0 + 2 * nf, c0:c0 + 2 * nt]
        return out

    return impl


@functools.lru_cache(maxsize=1)
def _acf_cuts_jax():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def impl(arr, subtract_mean, lens):
        if subtract_mean:
            arr = _masked_mean_subtract(arr, jnp)
        nf, nt = arr.shape[-2], arr.shape[-1]
        Lf, Lt = _acf_pad_lens(nf, nt, lens)
        # freq cut: sum over t of each column's padded 1-D autocovariance
        F = jnp.fft.rfft(arr, n=Lf, axis=-2)
        Sf = jnp.sum(jnp.real(F) ** 2 + jnp.imag(F) ** 2, axis=-1)
        cut_f = jnp.fft.irfft(Sf, n=Lf, axis=-1)[..., :nf]
        # time cut: sum over f of each row's padded 1-D autocovariance
        T = jnp.fft.rfft(arr, n=Lt, axis=-1)
        St = jnp.sum(jnp.real(T) ** 2 + jnp.imag(T) ** 2, axis=-2)
        cut_t = jnp.fft.irfft(St, n=Lt, axis=-1)[..., :nt]
        return cut_t, cut_f

    return impl


def _diag_sums(C, jnp):
    """Positive-offset diagonal sums of square matrices on the last two
    axes: out[..., k] = sum_i C[..., i, i+k] for k = 0..n-1."""
    n = C.shape[-1]
    i = jnp.arange(n)
    idx = i[:, None] + i[None, :]              # [row i, lag k] -> i + k
    mask = idx < n
    idx = jnp.where(mask, idx, 0)
    shape = (1,) * (C.ndim - 2) + (n, n)
    g = jnp.take_along_axis(C, idx.reshape(shape), axis=-1)
    return jnp.sum(jnp.where(mask.reshape(shape), g, 0.0), axis=-2)


@functools.lru_cache(maxsize=1)
def _acf_cuts_matmul_jax():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1,))
    def impl(arr, subtract_mean):
        if subtract_mean:
            arr = _masked_mean_subtract(arr, jnp)
        # Gram matrices on the MXU: the zero-time-lag freq cut is the
        # k-th-diagonal sum of X X^T, the zero-freq-lag time cut of
        # X^T X (both are the padded-FFT cuts' linear correlations,
        # written as dense contractions so they ride the systolic array
        # instead of the VPU FFT path).
        hi = jax.lax.Precision.HIGHEST
        Cf = jnp.einsum("...ft,...gt->...fg", arr, arr, precision=hi)
        Ct = jnp.einsum("...ft,...fs->...ts", arr, arr, precision=hi)
        return _diag_sums(Ct, jnp), _diag_sums(Cf, jnp)

    return impl


def acf_cuts_direct(dyn, backend: str = "jax", subtract_mean: bool = True,
                    method: str = "fft", lens: str = "exact"):
    """The central positive-lag 1-D cuts of the 2-D ACF, computed WITHOUT
    the 2-D transform.

    The scint-parameter fit consumes only ``acf[nchan:, nsub]`` and
    ``acf[nchan, nsub:]`` (dynspec.py:949-952).  Those cuts are exactly

        C(df, 0) = sum_t acf1d_freq(column t),
        C(0, dt) = sum_f acf1d_time(row f),

    so batched padded 1-D FFTs + a reduction give bit-identical values at
    a fraction of the 2-D pair's FLOPs and without materialising the
    [B, 2nf, 2nt] array (the dominant cost of the batched fit path).
    Returns (cut_t [..., nt], cut_f [..., nf]).

    ``method="matmul"`` computes the same cuts as diagonal sums of the
    Gram matrices X X^T / X^T X — identical linear correlations, but as
    dense f32 contractions that map onto the TPU MXU instead of the VPU
    FFT pipeline (HIGHEST precision; agrees with the FFT path to normal
    f32 contraction error).  ``method`` selects between the two jax
    routes only: the numpy backend always slices the cuts out of the
    reference-exact 2-D ACF (same values either way).  ``lens`` pads
    the 1-D FFTs as :func:`acf` does ("fast" = 5-smooth composite
    lengths; the positive-lag cut values are unchanged).
    """
    if method not in ("fft", "matmul"):
        raise ValueError(f"acf_cuts_direct: unknown method {method!r} "
                         "(expected 'fft' or 'matmul')")
    backend = resolve(backend)
    if backend == "numpy":
        a = _acf_numpy(np.asarray(dyn), subtract_mean)
        nf, nt = np.asarray(dyn).shape[-2:]
        return a[..., nf, nt:], a[..., nf:, nt]
    if method == "matmul":
        return _acf_cuts_matmul_jax()(dyn, subtract_mean)
    return _acf_cuts_jax()(dyn, subtract_mean, lens)
