"""Shared grid/block-spec helpers for this package's Pallas TPU kernels.

Factored out of the round-4 idiom template (``ops/resample_pallas.py``)
so the fused secondary-spectrum kernels (``ops/sspec_pallas.py``) and
the NUDFT tile (``ops/nudft.py``) state their Mosaic constraints once:

* **Tiling** — the last two dims of every block must be multiples of
  the (8, 128) f32 register tile or the full array dims (probed on the
  axon TPU; violating the sublane rule dies in the backend, not in
  tracing).  :func:`round_up` / :func:`pick_row_block` size row grids
  accordingly.
* **Residency** — a small operand revisited by every grid step uses a
  constant-index BlockSpec (:func:`resident_spec`): Pallas keeps the
  block in VMEM across steps instead of re-fetching per step.
* **Interpret-mode routing** — :func:`pallas_interpret_default` is THE
  trace-time "am I on a real TPU" probe every kernel's ``interpret=
  "auto"`` resolves through (moved here from resample_pallas; the
  f64-oracle re-trace contract is documented on the function).

Everything here is host-side shape math plus spec construction — no
device work, importable without jax installed until a spec is built.
"""

from __future__ import annotations

__all__ = [
    "LANE",
    "SUBLANE",
    "round_up",
    "pick_row_block",
    "resident_spec",
    "row_tile_spec",
    "pallas_interpret_default",
    "resolve_interpret",
]

# f32 register tile: (sublane, lane).  bf16 doubles the sublane minimum,
# but every kernel in this package computes in f32 (the bf16_io policy
# upcasts at the step top — scripts/check_f32_discipline.py guards it).
LANE = 128
SUBLANE = 8


def round_up(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= ``n`` (>= 1)."""
    n = max(int(n), 1)
    multiple = max(int(multiple), 1)
    return -(-n // multiple) * multiple


def pick_row_block(rows: int, candidates: tuple = (64, 32, 16, 8)) -> int:
    """Largest candidate row-block size that divides ``rows`` (which the
    caller has already rounded up to a SUBLANE multiple).  Falls back to
    SUBLANE — every SUBLANE-multiple is divisible by it."""
    rows = int(rows)
    for c in candidates:
        if rows % int(c) == 0 and rows >= int(c):
            return int(c)
    return SUBLANE


def resident_spec(shape: tuple):
    """BlockSpec pinning the FULL array as one block with a constant
    index map (the variadic lambda fits any grid rank): the operand
    stays VMEM-resident across every grid step (the revisit idiom —
    small inputs read by all blocks)."""
    from jax.experimental import pallas as pl

    zeros = (0,) * len(shape)
    return pl.BlockSpec(tuple(int(s) for s in shape),
                        lambda *_i: zeros)


def row_tile_spec(block_rows: int, ncols: int):
    """BlockSpec tiling a [rows, ncols] array over a 1-D row grid:
    block ``i`` covers rows ``[i*block_rows, (i+1)*block_rows)`` and the
    full lane axis (full-dim lanes satisfy Mosaic for any ncols)."""
    from jax.experimental import pallas as pl

    return pl.BlockSpec((int(block_rows), int(ncols)), lambda i: (i, 0))


def pallas_interpret_default() -> bool:
    """True when Pallas must run in interpret mode: the execution target
    is not a real TPU.  Reads ``jax.default_device`` overrides first —
    ``jax.default_backend()`` still reports "tpu" inside a
    ``with jax.default_device(cpu)`` block, which is exactly how the f64
    oracle re-traces a TPU-built pipeline on host."""
    import jax

    dev = getattr(jax.config, "jax_default_device", None)
    # jax.default_device accepts a Device object OR a platform string
    platform = (dev if isinstance(dev, str)
                else getattr(dev, "platform", None)) or jax.default_backend()
    return platform != "tpu"


def resolve_interpret(interpret) -> bool:
    """Resolve a kernel's ``interpret`` argument: ``"auto"`` probes the
    execution target at TRACE time (so a TPU-built pipeline re-traced
    under ``jax.default_device(cpu)`` flips to interpret mode instead of
    failing to lower); booleans pass through."""
    if interpret == "auto":
        return pallas_interpret_default()
    return bool(interpret)
