"""Cleaning / preprocessing ops: trim, refill, bandpass, zap, crop.

These are host-side, shape-changing operations in the reference, so the
default implementations are numpy functions over :class:`DynspecData`.
For the jit'd TPU batch pipeline (fixed shapes), :func:`refill_fixed_point`
provides a mask-based gap filler that compiles.

Reference mapping:
    trim_edges   dynspec.py:1129-1163 (incl. its rowsum/colsum quirk, fixed)
    refill       dynspec.py:1165-1187
    correct_band dynspec.py:1189-1226
    zap          dynspec.py:1389-1400
    crop_dyn     dynspec.py:1362-1387
"""

from __future__ import annotations

import functools
import logging

import numpy as np
from scipy.interpolate import griddata
from scipy.signal import medfilt, savgol_filter
from scipy.spatial import QhullError

from .. import obs
from ..data import DynspecData
from ..utils.log import get_logger, log_event


def trim_edges(d: DynspecData) -> DynspecData:
    """Strip all-zero / all-NaN rows and columns from the band/time edges.

    The reference walks one edge row/col at a time with while-loops
    (dynspec.py:1129-1157); note its left/right column loops test the stale
    ``rowsum`` instead of ``colsum`` (dynspec.py:1148,1154) — a bug we fix
    (SURVEY.md §7 "known reference bugs").  Metadata is recomputed as at
    dynspec.py:1158-1163.
    """
    dyn = np.asarray(d.dyn)
    freqs = np.asarray(d.freqs)
    times = np.asarray(d.times)

    def dead(v):  # all-zero or any-NaN edge vector, as `sum==0 or isnan(sum)`
        s = np.sum(np.abs(v))
        return s == 0 or np.isnan(s)

    lo = 0
    while lo < dyn.shape[0] - 1 and dead(dyn[lo, :]):
        lo += 1
    hi = dyn.shape[0]
    while hi > lo + 1 and dead(dyn[hi - 1, :]):
        hi -= 1
    dyn, freqs = dyn[lo:hi], freqs[lo:hi]

    left = 0
    while left < dyn.shape[1] - 1 and dead(dyn[:, left]):
        left += 1
    right = dyn.shape[1]
    while right > left + 1 and dead(dyn[:, right - 1]):
        right -= 1
    t0 = times[left]
    dyn, times = dyn[:, left:right], times[left:right]

    return d.replace(
        dyn=dyn, freqs=freqs, times=times,
        bw=round(float(freqs.max() - freqs.min()) + d.df, 2),
        freq=round(float(np.mean(freqs)), 2),
        tobs=round(float(times.max() - times.min()) + d.dt, 2),
        mjd=d.mjd + t0 / 86400.0,
    )


def refill(d: DynspecData, linear: bool = True,
           zeros: bool = True) -> DynspecData:
    """Replace NaN (and optionally zero) pixels by 2-D linear interpolation
    over valid pixels, residual NaNs by the mean (dynspec.py:1165-1187)."""
    arr = np.array(d.dyn, dtype=np.float64)  # host-f64: numpy parity path (reference zap)
    if zeros:
        arr[arr == 0] = np.nan
    mask = ~np.isfinite(arr)
    if linear and mask.any() and (~mask).sum() >= 4:
        x = np.arange(arr.shape[1])
        y = np.arange(arr.shape[0])
        xx, yy = np.meshgrid(x, y)
        try:
            arr = griddata((xx[~mask], yy[~mask]), arr[~mask], (xx, yy),
                           method="linear")
        except (QhullError, ValueError):
            # degenerate triangulation (e.g. all valid pixels collinear
            # after heavy RFI zapping -> Qhull precision error): fall
            # through to the mean fill below
            pass
    good = np.isfinite(arr)
    if not good.any():
        raise ValueError("refill: dynamic spectrum has no finite pixels")
    arr[~good] = np.mean(arr[good])
    log = get_logger()
    if obs.enabled() or log.isEnabledFor(logging.DEBUG):
        n_gaps = int(mask.sum())
        obs.inc("refill_calls")
        obs.inc("refill_pixels", n_gaps)
        log_event(log, "refill", level=logging.DEBUG, n_filled=n_gaps,
                  shape=f"{arr.shape[0]}x{arr.shape[1]}")
    return d.replace(dyn=arr)


@functools.lru_cache(maxsize=1)
def _refill_fixed_point_jax():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def impl(dyn, iters, zeros):
        invalid = ~jnp.isfinite(dyn)
        if zeros:
            invalid = invalid | (dyn == 0)
        valid = ~invalid
        denom = jnp.maximum(jnp.sum(valid, axis=(-2, -1), keepdims=True), 1)
        mean = jnp.sum(jnp.where(valid, dyn, 0.0), axis=(-2, -1),
                       keepdims=True) / denom
        arr = jnp.where(valid, dyn, mean)

        def body(_, a):
            # 4-neighbour Jacobi relaxation on masked pixels -> harmonic
            # interpolant, the fixed-shape analogue of Delaunay-linear
            # griddata (dynspec.py:1183).
            p = jnp.pad(a, [(0, 0)] * (a.ndim - 2) + [(1, 1), (1, 1)],
                        mode="edge")
            nb = (p[..., :-2, 1:-1] + p[..., 2:, 1:-1]
                  + p[..., 1:-1, :-2] + p[..., 1:-1, 2:]) / 4.0
            return jnp.where(invalid, nb, a)

        return jax.lax.fori_loop(0, iters, body, arr)

    return impl


def refill_fixed_point(dyn, iters: int = 50, zeros: bool = True):
    """jit/vmap-compatible gap filler for the TPU batch pipeline.

    Same role as :func:`refill` but fixed-shape: masked pixels relax to the
    harmonic (Laplace) interpolant of their neighbours, which the reference's
    Delaunay-linear interpolation approximates.  Not bit-identical to the
    numpy path; equivalence is asserted statistically in tests.
    """
    return _refill_fixed_point_jax()(dyn, iters, zeros)


def correct_band_array(arr, frequency: bool = True, time: bool = False,
                       nsmooth: int | None = 5) -> np.ndarray:
    """Bandpass / gain correction of a raw [nf, nt] array: divide by
    savgol-smoothed row means (frequency) and/or column means (time)
    (dynspec.py:1189-1226).  Array-level so it also serves the
    lambda-resampled dynspec (the reference's ``lamsteps=True`` branch,
    dynspec.py:1195-1198)."""
    dyn = np.array(arr, dtype=np.float64)  # host-f64: numpy parity path (refill)
    dyn[np.isnan(dyn)] = 0
    if frequency:
        bandpass = np.mean(dyn, axis=1)
        bandpass[bandpass == 0] = np.mean(bandpass)
        if nsmooth is not None:
            bandpass = savgol_filter(bandpass, nsmooth, 1)
        dyn = dyn / bandpass[:, None]
    if time:
        ts = np.mean(dyn, axis=0)
        ts[ts == 0] = np.mean(ts)
        if nsmooth is not None:
            ts = savgol_filter(ts, nsmooth, 1)
        dyn = dyn / ts[None, :]
    return dyn


def correct_band(d: DynspecData, frequency: bool = True, time: bool = False,
                 nsmooth: int | None = 5) -> DynspecData:
    """Bandpass / gain correction of ``d.dyn`` (dynspec.py:1189-1226)."""
    return d.replace(dyn=correct_band_array(d.dyn, frequency=frequency,
                                            time=time, nsmooth=nsmooth))


def _robust_z(x):
    """|x - median| in units of the MAD-estimated sigma (1.4826*MAD);
    non-finite entries read as the median (z = 0)."""
    x = np.where(np.isfinite(x), x, np.nanmedian(x))
    c = np.median(x)
    s = np.median(np.abs(x - c)) * 1.4826
    return np.abs(x - c) / max(s, 1e-30)


def zap(d: DynspecData, method: str = "median", sigma: float = 7,
        m: int = 3) -> DynspecData:
    """RFI zapping (dynspec.py:1389-1400): ``median`` NaNs out pixels more
    than ``sigma`` median-absolute-deviations from the median; ``medfilt``
    median-filters the array; ``channels`` excises whole channels whose
    per-channel statistics are anomalous; ``subints`` (round-4) is the
    time-axis mirror — whole anomalous subintegrations (broadband
    impulsive RFI).

    ``channels`` covers the RFI class pixel thresholds cannot: a channel
    with a slowly drifting gain (e.g. a saturating receiver) stays inside
    the global pixel threshold at every sample, yet its residual
    low-Doppler ridge after bandpass correction can bury a scintillation
    arc (demonstrated by tests/data/J0000+0000_degraded.dynspec).  The
    reference delegates this to the external coast_guard "surgical"
    cleaner (scint_utils.py:19-56); here it is native: robust z-scores of
    per-channel median, spread (IQR) and linear time-trend, any of which
    beyond ``sigma`` flags the channel (NaN, to be repaired by refill)."""
    dyn = np.array(d.dyn, dtype=np.float64)  # host-f64: numpy parity path (bandpass)
    if method == "median":
        dev = np.abs(dyn - np.median(dyn[~np.isnan(dyn)]))
        mdev = np.median(dev[~np.isnan(dev)])
        dyn[dev / mdev > sigma] = np.nan
    elif method == "medfilt":
        dyn = medfilt(dyn, kernel_size=m)
    elif method == "channels":
        with np.errstate(invalid="ignore"):
            t = np.arange(dyn.shape[1], dtype=np.float64)  # host-f64: numpy parity path (bandpass)
            t = (t - t.mean()) / max(t.std(), 1.0)
            med = np.nanmedian(dyn, axis=1)
            q75, q25 = (np.nanpercentile(dyn, 75, axis=1),
                        np.nanpercentile(dyn, 25, axis=1))
            spread = q75 - q25
            valid = np.isfinite(dyn)
            dyn0 = np.where(valid, dyn, 0.0)
            n = np.maximum(valid.sum(axis=1), 1)
            # per-channel linear trend vs normalised time (covariance
            # with a unit-variance regressor), scale-normalised
            mean_c = dyn0.sum(axis=1) / n
            trend = ((dyn0 - mean_c[:, None] * valid) * t).sum(axis=1) / n
            # No per-channel normalisation: dividing each channel's trend
            # by |its own mean| distorts relative z-scores and, on
            # mean-subtracted / band-corrected dynspecs (channel means
            # ~ 0), explodes them and falsely excises clean channels.
            # _robust_z is invariant to any GLOBAL positive scale,
            # so the raw covariance (flux-units trend per unit
            # normalised time) is the right statistic as-is.

        bad = ((_robust_z(med) > sigma) | (_robust_z(spread) > sigma)
               | (_robust_z(trend) > sigma))
        dyn[bad, :] = np.nan
    elif method == "subints":
        # round-4: the TIME-axis mirror of "channels" — excise whole
        # subintegrations whose per-subint median or spread is anomalous
        # (broadband impulsive RFI: a lightning strike / radar sweep
        # lifts EVERY channel for one subint).  A whole-subint excision
        # removes the impulse without clipping bright scintles the way a
        # global pixel threshold does (bright scintillation maxima are
        # heavy-tailed REAL signal; zapping them biases tau low).
        with np.errstate(invalid="ignore"):
            med = np.nanmedian(dyn, axis=0)
            q75, q25 = (np.nanpercentile(dyn, 75, axis=0),
                        np.nanpercentile(dyn, 25, axis=0))
            spread = q75 - q25
        bad = (_robust_z(med) > sigma) | (_robust_z(spread) > sigma)
        dyn[:, bad] = np.nan
    else:
        raise ValueError(f"unknown zap method {method!r}")
    log = get_logger()
    if obs.enabled() or log.isEnabledFor(logging.DEBUG):
        # telemetry only: the NaN scans and float64 view are not worth
        # paying on the per-epoch hot path when nobody is listening
        before = np.asarray(d.dyn, dtype=np.float64)  # host-f64: host telemetry only
        n_zapped = max(int(np.isnan(dyn).sum())
                       - int(np.isnan(before).sum()), 0)
        obs.inc("zap_calls")
        obs.inc("zap_pixels", n_zapped)
        log_event(log, "zap", level=logging.DEBUG, method=method,
                  sigma=sigma, n_zapped=n_zapped)
    return d.replace(dyn=dyn)


def crop(d: DynspecData, fmin: float = 0, fmax: float = np.inf,
         tmin: float = 0, tmax: float = np.inf) -> DynspecData:
    """Crop to [fmin, fmax] MHz and [tmin, tmax] minutes
    (dynspec.py:1362-1387; reference uses strict inequalities and rebuilds
    the time axis centred on dt/2)."""
    dyn = np.asarray(d.dyn)
    freqs = np.asarray(d.freqs)
    times = np.asarray(d.times)

    fkeep = (freqs > fmin) & (freqs < fmax)
    dyn, freqs = dyn[fkeep, :], freqs[fkeep]

    tmin_s, tmax_s = tmin * 60, tmax * 60
    tobs = (tmax_s - tmin_s) if tmax_s < d.tobs else (d.tobs - tmin_s)
    tkeep = (times > tmin_s) & (times < tmax_s)
    dyn = dyn[:, tkeep]
    nsub = dyn.shape[1]
    times = np.linspace(d.dt / 2, tobs - d.dt / 2, nsub)
    return d.replace(
        dyn=dyn, freqs=freqs, times=times, tobs=tobs,
        bw=round(float(freqs.max() - freqs.min()) + d.df, 2),
        freq=round(float(np.mean(freqs)), 2),
        mjd=d.mjd + tmin_s / 86400.0,
    )
