"""Axis rescaling: frequency -> uniform wavelength steps, and trapezoid.

Reference: ``Dynspec.scale_dyn`` (dynspec.py:1402-1476).

``lambda`` mode resamples every time column from the (uniform-frequency)
channel grid onto a uniform-wavelength grid with cubic interpolation
(dynspec.py:1412-1428); output is flipped so wavelength decreases with row
index, matching ascending frequency.  The reference loops over columns with
``interp1d(kind='cubic')``; scipy's interpolator handles the whole 2-D array
at once (identical splines), so the numpy path is loop-free.  The jax path
implements a *natural* cubic spline with a dense solve (nchan is small) so
it jits and vmaps; it differs from scipy's not-a-knot boundary only in the
outermost two channels (tolerance asserted in tests).

``trapezoid`` mode time-resamples each row by f/fmin (dynspec.py:1429-1476).
"""

from __future__ import annotations

import functools

import numpy as np
from scipy.interpolate import interp1d

from ..backend import resolve
from ..data import DynspecData, _C_M_S
from .windows import apply_2d_window


def lambda_grid(freqs: np.ndarray):
    """Uniform wavelength grid spanning the band (dynspec.py:1418-1420):
    step = max |diff(lambda)|, which the reference takes so the lambda grid
    never oversamples the coarsest channel spacing."""
    lams = _C_M_S / (np.asarray(freqs) * 1e6)
    dlam = np.max(np.abs(np.diff(lams)))
    lam_eq = np.arange(np.min(lams), np.max(lams), dlam)
    return lam_eq, dlam


def scale_lambda(d: DynspecData, backend: str = "numpy") -> tuple:
    """Return (lamdyn [nlam, nt], lam [nlam], dlam).

    lamdyn rows are flipped (descending wavelength = ascending frequency),
    matching dynspec.py:1427-1428.
    """
    backend = resolve(backend)
    freqs = np.asarray(d.freqs)
    lam_eq, dlam = lambda_grid(freqs)
    feq = _C_M_S / lam_eq / 1e6
    if backend == "numpy":
        f = interp1d(freqs, np.asarray(d.dyn), kind="cubic", axis=0)
        arout = f(feq)
    else:
        arout = _cubic_interp_jax()(d.dyn, np.asarray(freqs, dtype=np.float64),  # host-f64: host axes
                                    np.asarray(feq, dtype=np.float64))  # host-f64: host axes
    return arout[::-1], lam_eq[::-1], dlam


@functools.lru_cache(maxsize=1)
def _cubic_interp_jax():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def impl(y, x, xq):
        """Natural cubic spline along axis 0, evaluated at xq.

        x is a static-shape 1-D grid.  Dense tridiagonal solve: nchan is a
        few hundred, so an O(n^2) solve is negligible next to the FFTs and
        keeps the code mesh-shardable.  Differs from scipy's not-a-knot
        boundary only in the outermost two channels (documented tolerance in
        tests).
        """
        n = x.shape[0]
        # solve in the wider of (data, grid) dtypes: scattering f64 grid
        # spacings into an f32 system is a FutureWarning -> error in jax
        dtype = jnp.result_type(y.dtype, x.dtype)
        y = y.astype(dtype)
        x = x.astype(dtype)
        xq = xq.astype(dtype)
        h = jnp.diff(x)  # [n-1]
        # Build the natural-spline system A m = rhs for second derivatives m.
        A = jnp.zeros((n, n), dtype=dtype)
        A = A.at[0, 0].set(1.0)
        A = A.at[n - 1, n - 1].set(1.0)
        idx = jnp.arange(1, n - 1)
        A = A.at[idx, idx - 1].set(h[:-1])
        A = A.at[idx, idx].set(2.0 * (h[:-1] + h[1:]))
        A = A.at[idx, idx + 1].set(h[1:])
        dy = jnp.diff(y, axis=0)
        slope = dy / h[:, None]
        rhs = jnp.zeros_like(y)
        rhs = rhs.at[1:-1].set(6.0 * (slope[1:] - slope[:-1]))
        m = jnp.linalg.solve(A, rhs)  # [n, nt] second derivatives

        j = jnp.clip(jnp.searchsorted(x, xq, side="right") - 1, 0, n - 2)
        xj, xj1 = x[j], x[j + 1]
        hj = (xj1 - xj)[:, None]
        t0 = (x[j + 1][:, None] - xq[:, None])
        t1 = (xq[:, None] - xj[:, None])
        yj, yj1, mj, mj1 = y[j], y[j + 1], m[j], m[j + 1]
        return (mj * t0 ** 3 / (6 * hj) + mj1 * t1 ** 3 / (6 * hj)
                + (yj / hj - mj * hj / 6) * t0
                + (yj1 / hj - mj1 * hj / 6) * t1)

    return impl


def natural_cubic_interp_numpy(y: np.ndarray, x: np.ndarray,
                               xq: np.ndarray) -> np.ndarray:
    """Host-side natural cubic spline along axis 0 — the exact numpy
    transcription of the jax solver above (same boundary conditions, so
    the two agree to rounding).  Used where device execution must be
    avoided at build time (e.g. precomputing resampling weights while
    the accelerator is untouched/unreachable)."""
    y = np.asarray(y, dtype=np.float64)  # host-f64: numpy parity path (spline solve)
    x = np.asarray(x, dtype=np.float64)  # host-f64: numpy parity path (spline solve)
    xq = np.asarray(xq, dtype=np.float64)  # host-f64: numpy parity path (spline solve)
    n = x.shape[0]
    h = np.diff(x)
    A = np.zeros((n, n))
    A[0, 0] = A[n - 1, n - 1] = 1.0
    idx = np.arange(1, n - 1)
    A[idx, idx - 1] = h[:-1]
    A[idx, idx] = 2.0 * (h[:-1] + h[1:])
    A[idx, idx + 1] = h[1:]
    slope = np.diff(y, axis=0) / h[:, None]
    rhs = np.zeros_like(y)
    rhs[1:-1] = 6.0 * (slope[1:] - slope[:-1])
    m = np.linalg.solve(A, rhs)

    j = np.clip(np.searchsorted(x, xq, side="right") - 1, 0, n - 2)
    hj = (x[j + 1] - x[j])[:, None]
    t0 = (x[j + 1][:, None] - xq[:, None])
    t1 = (xq[:, None] - x[j][:, None])
    yj, yj1, mj, mj1 = y[j], y[j + 1], m[j], m[j + 1]
    return (mj * t0 ** 3 / (6 * hj) + mj1 * t1 ** 3 / (6 * hj)
            + (yj / hj - mj * hj / 6) * t0
            + (yj1 / hj - mj1 * hj / 6) * t1)


def scale_trapezoid(d: DynspecData, window: str | None = "hanning",
                    window_frac: float = 0.1) -> np.ndarray:
    """Trapezoid time-rescaling (dynspec.py:1429-1476): mean-subtract,
    window, then per-row resample the time axis by a frequency-dependent
    maximum time, zero-padding the tail."""
    dyn = np.array(d.dyn, dtype=np.float64)  # host-f64: numpy parity path
    dyn -= np.mean(dyn)
    if window is not None:
        dyn = apply_2d_window(dyn, window, window_frac, backend="numpy")
    nf = dyn.shape[0]
    times = np.asarray(d.times)
    freqs = np.asarray(d.freqs)
    scalefrac = 1 / (freqs.max() / freqs.min())
    timestep = times.max() * (1 - scalefrac) / (nf + 1)
    trapdyn = np.empty_like(dyn)
    for ii in range(nf):
        maxtime = times.max() - (nf - (ii + 1)) * timestep
        nkeep = int(np.sum(times <= maxtime))
        newline = np.interp(np.linspace(times.min(), times.max(), nkeep),
                            times, dyn[ii, :])
        trapdyn[ii, :] = np.concatenate([newline,
                                         np.zeros(dyn.shape[1] - nkeep)])
    return trapdyn
