"""Matplotlib views of dynamic spectra and their products.

The reference interleaves plotting into compute methods on ``Dynspec``
(``plot_dyn``/``plot_acf``/``plot_sspec``/``plot_all``,
dynspec.py:200-412, and ``Simulation.plot_*``, scint_sim.py:266-335).
Here plotting is a separate presentation layer that only *consumes*
results (SURVEY.md §7 architecture), so the compute path stays pure and
jit-friendly.  Every function returns the matplotlib Figure; pass
``filename=`` to save and ``display=False`` for headless use.
"""

from __future__ import annotations

import numpy as np

from .backend import to_numpy
from .data import DynspecData, SecSpec


def _finish(fig, filename: str | None, display: bool):
    if filename is not None:
        fig.savefig(filename, dpi=150, bbox_inches="tight",
                    pad_inches=0.1)
    if display:  # pragma: no cover - interactive only
        import matplotlib.pyplot as plt

        plt.show()
    return fig


def _pclim(arr):
    """Robust dB colour limits: 5th-99.9th percentile of finite values
    (None, None when nothing is finite — matplotlib autoscales)."""
    finite = arr[np.isfinite(arr)]
    if finite.size == 0:
        return None, None
    return tuple(np.percentile(finite, [5, 99.9]))


def _clim(arr, nsig_lo: float = 3, nsig_hi: float = 5):
    """Median +- sigma colour limits, the reference's robust scaling
    (dynspec.py:234-238: median +- 2/5 x MAD-derived std)."""
    a = arr[np.isfinite(arr)]
    med, std = np.median(a), np.std(a)
    return med - nsig_lo * std, med + nsig_hi * std


def plot_dyn(d: DynspecData, ax=None, filename: str | None = None,
             display: bool = False, cmap: str = "viridis",
             dyn=None, y=None, ylabel: str | None = None):
    """Dynamic spectrum pcolormesh, time in minutes vs frequency in MHz
    (dynspec.py:200-247).  ``dyn``/``y``/``ylabel`` override the plotted
    array and vertical axis — used for the reference's lamsteps/trap
    views (dynspec.py:206-229) where the rows are wavelength or rescaled
    time rather than frequency."""
    import matplotlib.pyplot as plt

    dyn = to_numpy(d.dyn if dyn is None else dyn)
    y = to_numpy(d.freqs if y is None else y)
    if ax is None:
        fig, ax = plt.subplots(figsize=(9, 6))
    else:
        fig = ax.figure
    vmin, vmax = _clim(dyn, 2, 5)
    mesh = ax.pcolormesh(to_numpy(d.times) / 60.0, y, dyn,
                         vmin=vmin, vmax=vmax, cmap=cmap, shading="auto")
    ax.set_xlabel("Time (mins)")
    ax.set_ylabel(ylabel or "Frequency (MHz)")
    ax.set_title(d.name)
    fig.colorbar(mesh, ax=ax, label="Flux (arb.)")
    return _finish(fig, filename, display)


def plot_acf(acf2d, d: DynspecData | None = None, scint_params=None,
             ax=None, filename: str | None = None, display: bool = False,
             crop_frac: float = 1.0, cmap: str = "viridis",
             contour: bool = False, wn_method: str = "reference"):
    """2-D ACF with the zero-lag white-noise spike suppressed.

    ``wn_method="reference"`` (default) subtracts the lag0-lag1 drop
    from the centre pixel exactly as the reference does
    (dynspec.py:267-270: ``wn = arr[0][0] - arr[0][1]`` on the
    ifftshifted array, i.e. the spike is set to the first time-lag
    neighbour's value); ``wn_method="neighbours"`` replaces it with the
    four neighbours' mean (slightly smoother on noisy ACFs).

    ``contour=True`` draws filled contours instead of pcolormesh
    (reference ``contour=`` option, dynspec.py:276-277).

    With ``scint_params``, adds the reference's scint-scaled TWIN AXES
    (dynspec.py:283-292): a second y axis in units of the fitted dnu_d
    and a second x axis in units of tau_d, plus the guide lines."""
    import matplotlib.pyplot as plt

    a = np.array(to_numpy(acf2d), dtype=np.float64)
    nf, nt = a.shape
    cf, ct = nf // 2, nt // 2
    if wn_method == "reference":
        # wn = lag0 - lag1; lag0 -= wn  ==  set spike to the first
        # time-lag neighbour (dynspec.py:267-270 on the unshifted array)
        a[cf, ct] = a[cf, ct + 1]
    elif wn_method == "neighbours":
        a[cf, ct] = (a[cf, ct - 1] + a[cf, ct + 1]
                     + a[cf - 1, ct] + a[cf + 1, ct]) / 4
    else:
        raise ValueError(f"unknown wn_method {wn_method!r} "
                         "(expected 'reference' or 'neighbours')")
    if ax is None:
        fig, ax = plt.subplots(figsize=(7, 6))
    else:
        fig = ax.figure
    if d is not None:
        tlag = (np.arange(nt) - ct) * d.dt / 60.0
        flag = (np.arange(nf) - cf) * d.df
    else:
        tlag = np.arange(nt) - ct
        flag = np.arange(nf) - cf
    if crop_frac < 1.0:
        it = int(ct * crop_frac)
        if_ = int(cf * crop_frac)
        a = a[cf - if_:cf + if_, ct - it:ct + it]
        tlag = tlag[ct - it:ct + it]
        flag = flag[cf - if_:cf + if_]
    if contour:
        mesh = ax.contourf(tlag, flag, a, cmap=cmap)
    else:
        mesh = ax.pcolormesh(tlag, flag, a, cmap=cmap, shading="auto")
    ax.set_xlabel("Time lag (mins)" if d is not None else "Time lag")
    ax.set_ylabel("Frequency lag (MHz)" if d is not None
                  else "Frequency lag")
    if scint_params is not None:
        tau = float(to_numpy(scint_params.tau)) / 60.0
        dnu = float(to_numpy(scint_params.dnu))
        ax.axvline(tau, color="w", ls=":", lw=1, alpha=0.7)
        ax.axhline(dnu, color="w", ls=":", lw=1, alpha=0.7)
        ax.set_title(f"tau_d={tau:.2f} min, dnu_d={dnu:.4f} MHz")
        # scint-scaled twin axes (reference dynspec.py:283-292)
        if dnu != 0 and tau != 0:
            miny, maxy = ax.get_ylim()
            ax2 = ax.twinx()
            ax2.set_ylim(miny / dnu, maxy / dnu)
            ax2.set_ylabel(f"Frequency lag / (dnu_d = {round(dnu, 2)})")
            minx, maxx = ax.get_xlim()
            ax3 = ax.twiny()
            ax3.set_xlim(minx / tau, maxx / tau)
            ax3.set_xlabel(f"Time lag / (tau_d = {round(tau, 2)})")
    fig.colorbar(mesh, ax=ax, pad=0.15 if scint_params is not None
                 else 0.05, label="ACF")
    return _finish(fig, filename, display)


def plot_sspec(sec: SecSpec, eta: float | None = None, ax=None,
               filename: str | None = None, display: bool = False,
               maxfdop=np.inf, cmap: str = "viridis"):
    """Secondary spectrum in dB with percentile colour limits and an
    optional fitted-arc overlay ``tdel = eta fdop^2`` (dynspec.py:308-379).
    """
    import matplotlib.pyplot as plt

    s = to_numpy(sec.sspec)
    fdop = to_numpy(sec.fdop)
    yaxis = to_numpy(sec.beta if sec.lamsteps else sec.tdel)
    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 6))
    else:
        fig = ax.figure
    vmin, vmax = _pclim(s)
    keep = np.abs(fdop) <= maxfdop
    mesh = ax.pcolormesh(fdop[keep], yaxis, s[:, keep], vmin=vmin,
                         vmax=vmax, cmap=cmap, shading="auto")
    if eta is not None:
        xf = np.linspace(fdop[keep].min(), fdop[keep].max(), 256)
        ax.plot(xf, eta * xf ** 2, "r--", lw=1, alpha=0.8)
        ax.set_ylim(yaxis.min(), yaxis.max())
    ax.set_xlabel("f_t (mHz)")
    ax.set_ylabel(r"$\beta$ (m$^{-1}$)" if sec.lamsteps
                  else r"$\tau$ ($\mu$s)")
    fig.colorbar(mesh, ax=ax, label="Power (dB)")
    return _finish(fig, filename, display)


def plot_norm_sspec(ns, ax=None, filename: str | None = None,
                    display: bool = False, unscrunched: bool = False,
                    powerspec: bool = False, lamsteps: bool = True):
    """Curvature-normalised secondary-spectrum views (dynspec.py:869-925):
    the delay-scrunched profile, plus (``unscrunched``) the 2-D normalised
    spectrum and (``powerspec``) the delay power spectrum vs sqrt(tdel) —
    the reference's three panels."""
    import matplotlib.pyplot as plt

    npanels = 1 + int(unscrunched) + int(powerspec)
    if ax is None:
        fig, axes = plt.subplots(1, npanels,
                                 figsize=(6 * npanels, 4), squeeze=False)
        axes = list(axes[0])
    else:
        fig, axes = ax.figure, [ax]
    a = axes.pop(0)
    a.plot(to_numpy(ns.fdopnew), to_numpy(ns.normsspecavg), "k-", lw=1)
    for x in (-1, 1):
        a.axvline(x, color="r", ls=":", lw=1)
    a.set_xlabel("Normalised f_t")
    a.set_ylabel("Mean power (dB)")
    ylab = (r"$f_\lambda$ (m$^{-1}$)" if lamsteps
            else r"$f_\nu$ ($\mu$s)")
    if unscrunched and axes:
        a = axes.pop(0)
        arr = to_numpy(ns.normsspec)
        vmin, vmax = _pclim(arr)
        mesh = a.pcolormesh(to_numpy(ns.fdopnew), to_numpy(ns.tdel), arr,
                            vmin=vmin, vmax=vmax, shading="auto")
        for x in (-1, 1):
            a.axvline(x, color="r", ls=":", lw=1)
        a.set_xlabel("Normalised f_t")
        a.set_ylabel(ylab)
        fig.colorbar(mesh, ax=a, label="Power (dB)")
    if powerspec and axes:
        a = axes.pop(0)
        a.loglog(np.sqrt(to_numpy(ns.tdel)), to_numpy(ns.powerspec))
        a.set_xlabel(ylab.replace("(", "$^{1/2}$ ("))
        a.set_ylabel("Mean power (dB)")
    fig.tight_layout()
    return _finish(fig, filename, display)


def plot_arc_profile(fit, ax=None, filename: str | None = None,
                     display: bool = False):
    """Power vs curvature profile with the fitted eta (fit_arc products)."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 4))
    else:
        fig = ax.figure
    x = to_numpy(fit.profile_eta)
    ax.plot(x, to_numpy(fit.profile_power), color="0.6", lw=0.8,
            label="profile")
    ax.plot(x, to_numpy(fit.profile_power_filt), "k-", lw=1.2,
            label="smoothed")
    eta = float(to_numpy(fit.eta))
    ax.axvline(eta, color="r", ls="--",
               label=f"eta={eta:.3g}")
    ax.set_xscale("log")
    ax.set_xlabel(r"Curvature $\eta$")
    ax.set_ylabel("Mean power (dB)")
    ax.legend(loc="best", fontsize=8)
    return _finish(fig, filename, display)


def plot_posterior(chain, labels=None, truths=None, bins: int = 40,
                   filename: str | None = None, display: bool = False):
    """Corner plot of an MCMC chain — the posterior export the reference
    gets from the ``corner`` package after ``lmfit.Minimizer.emcee``
    (dynspec.py:1025-1031), rebuilt on bare matplotlib.

    ``chain`` is ``[steps, nwalkers, ndim]`` (as the ``return_chain``
    outputs of the fit.mcmc samplers) or an already-flat ``[N, ndim]``.
    Diagonal: marginal histograms with median and ±1σ quantile lines;
    off-diagonal: 2-D histograms.  ``truths`` draws reference values.
    """
    import matplotlib.pyplot as plt

    chain = np.asarray(chain)
    if chain.ndim == 3:
        chain = chain.reshape(-1, chain.shape[-1])
    if chain.ndim != 2:
        raise ValueError(f"chain must be [steps, walkers, ndim] or "
                         f"[N, ndim], got shape {chain.shape}")
    ndim = chain.shape[1]
    if labels is None:
        labels = [f"p{i}" for i in range(ndim)]
    if len(labels) != ndim:
        raise ValueError(f"{len(labels)} labels for {ndim} parameters")
    if truths is not None and len(truths) != ndim:
        raise ValueError(f"{len(truths)} truths for {ndim} parameters")
    fig, axes = plt.subplots(ndim, ndim,
                             figsize=(2.2 * ndim, 2.2 * ndim),
                             squeeze=False)
    q16, q50, q84 = np.percentile(chain, [16, 50, 84], axis=0)
    for i in range(ndim):
        for j in range(ndim):
            ax = axes[i, j]
            if j > i:
                ax.axis("off")
                continue
            if i == j:
                ax.hist(chain[:, i], bins=bins, color="0.6",
                        histtype="stepfilled")
                ax.axvline(q50[i], color="k", ls="-", lw=1)
                ax.axvline(q16[i], color="k", ls="--", lw=0.8)
                ax.axvline(q84[i], color="k", ls="--", lw=0.8)
                if truths is not None:
                    ax.axvline(truths[i], color="r", lw=1)
                ax.set_yticks([])
                ax.set_title(f"{labels[i]} = {q50[i]:.3g}"
                             f"$^{{+{q84[i] - q50[i]:.2g}}}"
                             f"_{{-{q50[i] - q16[i]:.2g}}}$",
                             fontsize=9)
            else:
                ax.hist2d(chain[:, j], chain[:, i], bins=bins,
                          cmap="Greys")
                if truths is not None:
                    ax.axvline(truths[j], color="r", lw=0.8)
                    ax.axhline(truths[i], color="r", lw=0.8)
            if i == ndim - 1:
                ax.set_xlabel(labels[j])
            else:
                ax.set_xticklabels([])
            if j == 0 and i > 0:
                ax.set_ylabel(labels[i])
            elif j > 0:
                ax.set_yticklabels([])
    fig.tight_layout()
    return _finish(fig, filename, display)


def plot_all(d: DynspecData, acf2d, sec: SecSpec, fit=None,
             filename: str | None = None, display: bool = False):
    """2x2 summary: dynspec, ACF, secondary spectrum, arc profile
    (dynspec.py:381-412; the reference's fourth panel is the norm-sspec
    profile — here the arc profile when a fit is given, else blank)."""
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(2, 2, figsize=(14, 10))
    plot_dyn(d, ax=axes[0, 0])
    plot_acf(acf2d, d, ax=axes[0, 1])
    plot_sspec(sec, eta=None if fit is None else float(to_numpy(fit.eta)),
               ax=axes[1, 0])
    if fit is not None:
        plot_arc_profile(fit, ax=axes[1, 1])
    else:
        axes[1, 1].axis("off")
    fig.tight_layout()
    return _finish(fig, filename, display)


def plot_thetatheta(sec: SecSpec, eta: float, ntheta: int = 129,
                    theta_max: float | None = None, startbin: int = 3,
                    cutmid: int = 3, conc_curve=None, ax=None,
                    filename: str | None = None, display: bool = False):
    """Theta-theta map at curvature ``eta`` (fit.thetatheta), optionally
    with the eta concentration curve as an inset panel.  Pass the same
    theta_max/startbin/cutmid used for the fit so the rendered map is the
    one the measurement actually saw."""
    import matplotlib.pyplot as plt

    from .fit.thetatheta import theta_theta_map

    M = theta_theta_map(sec, eta, ntheta=ntheta, theta_max=theta_max,
                        startbin=startbin, cutmid=cutmid)
    if ax is None:
        fig, ax = plt.subplots(figsize=(7, 6))
    else:
        fig = ax.figure
    with np.errstate(divide="ignore"):
        img = 10 * np.log10(M ** 2)  # back to power dB for display
    vmin, vmax = _pclim(img)
    mesh = ax.imshow(img, origin="lower", cmap="viridis", vmin=vmin,
                     vmax=vmax, extent=(-1, 1, -1, 1))
    ax.set_xlabel(r"$\theta_2$ / $\theta_{max}$")
    ax.set_ylabel(r"$\theta_1$ / $\theta_{max}$")
    ax.set_title(rf"$\theta$-$\theta$ @ $\eta$={eta:.3g}")
    fig.colorbar(mesh, ax=ax, label="Power (dB)")
    if conc_curve is not None:
        etas, conc = conc_curve
        ins = ax.inset_axes([0.62, 0.72, 0.35, 0.25])
        ins.semilogx(etas, conc, "w-", lw=1)
        ins.axvline(eta, color="r", lw=0.8)
        ins.set_xticks([])
        ins.set_yticks([])
        ins.patch.set_alpha(0.25)
    return _finish(fig, filename, display)


def plot_wavefield(wf, ax=None, filename: str | None = None,
                   display: bool = False):
    """Retrieved wavefield (fit.wavefield): amplitude, phase, and the
    |E|^2 reconstruction — compare the latter against ``plot_dyn`` of
    the input spectrum.  ``ax`` may be a single Axes (amplitude panel
    only, matching the module convention) or a length-3 sequence."""
    import matplotlib.pyplot as plt

    f = wf.freqs
    t = wf.times / 60.0
    ext = (t[0], t[-1], f[0], f[-1])
    field = to_numpy(wf.field)
    title = (rf"wavefield @ $\eta$={wf.eta:.3g}; "
             rf"conc={np.mean(wf.conc):.2f}")
    if ax is not None and not np.iterable(ax):
        fig = ax.figure
        mesh = ax.imshow(np.abs(field), origin="lower", aspect="auto",
                         cmap="magma", extent=ext)
        ax.set_xlabel("Time (mins)")
        ax.set_ylabel("Frequency (MHz)")
        ax.set_title(title)
        fig.colorbar(mesh, ax=ax, label="|E|")
        return _finish(fig, filename, display)
    if ax is None:
        fig, axs = plt.subplots(1, 3, figsize=(15, 4.2), sharey=True)
    else:
        axs = list(ax)
        fig = axs[0].figure
    panels = (
        (np.abs(field), "magma", "|E|", axs[0]),
        (np.angle(field), "twilight", "arg E (rad)", axs[1]),
        (np.abs(field) ** 2, "magma", r"$|E|^2$", axs[2]),
    )
    for img, cmap, label, a in panels:
        mesh = a.imshow(img, origin="lower", aspect="auto", cmap=cmap,
                        extent=ext)
        a.set_xlabel("Time (mins)")
        fig.colorbar(mesh, ax=a, label=label)
    axs[0].set_ylabel("Frequency (MHz)")
    axs[1].set_title(title)
    return _finish(fig, filename, display)


# -- simulation views (scint_sim.py:266-335) --------------------------------

def plot_screen(sim, ax=None, filename: str | None = None,
                display: bool = False):
    """Phase screen (scint_sim.py:266-280)."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(7, 6))
    else:
        fig = ax.figure
    x = np.arange(sim.nx) * sim.dx
    y = np.arange(sim.ny) * sim.dy
    mesh = ax.pcolormesh(x, y, to_numpy(sim.xyp).T, cmap="RdBu_r",
                         shading="auto")
    ax.set_xlabel("x (Fresnel scales)")
    ax.set_ylabel("y (Fresnel scales)")
    fig.colorbar(mesh, ax=ax, label="Phase (rad)")
    return _finish(fig, filename, display)


def plot_intensity(sim, ax=None, filename: str | None = None,
                   display: bool = False):
    """Simulated intensity vs position and frequency channel
    (scint_sim.py:282-298)."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 6))
    else:
        fig = ax.figure
    spi = to_numpy(sim.spi)
    mesh = ax.pcolormesh(np.arange(spi.shape[1]), np.arange(spi.shape[0]),
                         spi, cmap="magma", shading="auto")
    ax.set_xlabel("Frequency channel")
    ax.set_ylabel("Position")
    fig.colorbar(mesh, ax=ax, label="Intensity")
    return _finish(fig, filename, display)


def plot_efield(sim, ax=None, filename: str | None = None,
                display: bool = False):
    """Real part of the propagated E-field (scint_sim.py:317-331)."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 6))
    else:
        fig = ax.figure
    mesh = ax.pcolormesh(np.real(to_numpy(sim.spe)), cmap="RdBu_r",
                         shading="auto")
    ax.set_xlabel("Frequency channel")
    ax.set_ylabel("Position")
    fig.colorbar(mesh, ax=ax, label="Re E")
    return _finish(fig, filename, display)
