"""scintools-tpu: TPU-native pulsar-scintillation analysis framework.

A ground-up JAX/XLA rebuild of the capabilities of ramain/scintools
(reference mounted at /root/reference): dynamic-spectrum ingest and
cleaning, ACF and secondary spectra, scintillation-parameter and arc-
curvature fitting, and Kolmogorov phase-screen simulation — with every
kernel behind a ``backend=`` registry (numpy = reference-compatible CPU
path; jax = jit/vmap/shard_map TPU path) and batch drivers that scale over
device meshes.

Unlike the reference's single mutable ``Dynspec`` class with plotting
interleaved into compute (dynspec.py:29), the layers here are:

    ops/       pure-functional kernels (numpy + jax backends)
    models/    closed-form fit models + physics
    fit/       fixed-iteration least squares, vmappable
    sim/       phase-screen Monte Carlo (jit'd FFT propagator)
    parallel/  mesh + sharding policy, padded batch pipeline
    io/        psrflux / par / results / adapters (host-side)
    astro/     analytic ephemeris (no astropy dependency)
    obs/       tracing & metrics (spans, counters, JSONL trace sink)
    pipeline   thin stateful Dynspec wrapper preserving the reference UX
    plotting   matplotlib views, consuming results only
"""

__version__ = "0.1.0"

from . import obs  # noqa: F401  (tracing/metrics; no-op until enabled)
from .backend import jax_available, resolve, xp  # noqa: F401
from .data import ArcFit, DynspecData, ScintParams, SecSpec  # noqa: F401
from .pipeline import Dynspec, fit_arc_campaign, sort_dyn  # noqa: F401
