"""Closed shape-bucket catalog: canonicalise arbitrary survey shapes
onto a small fixed set of compiled step signatures.

BENCH_r05 measured ``compile_s: 324.68`` against ``measure_s: 0.54`` —
the compiled step is ~600x faster to run than to build, so every NEW
input signature a survey presents costs minutes of XLA work before the
first result.  The fix GPU real-time pipelines use (arXiv:1804.05335:
one resident FDAS transform fed canonicalised inputs; arXiv:2606.01547
documents recompilation as the dominant practical cost of JAX ports) is
a CLOSED set of compiled signatures: arbitrary inputs are padded into
the nearest member and the padding masked out of the results.

What is (and is not) bucketed
-----------------------------
Only the BATCH axis is padded.  The per-epoch axes ``(nf, nt)`` — and
the frequency/time *values* behind them — are baked into the compiled
program as host-side constants (df/fc/lambda grids, FFT lengths, eta
grids), so padding them would change every epoch's science.  The batch
axis, by contrast, is provably lane-independent: the driver's
``pad_to`` / ``pad_chunks`` machinery already pads it with mask-invalid
lanes that are sliced off at gather, byte-identical for real lanes
(tested since PR 2/3).  The catalog is therefore a geometric ladder of
batch sizes per (axes identity, config, staging dtype) — a survey of
ANY epoch count executes one of ``len(ladder)`` programs per observing
setup instead of one per distinct count.

The ladder
----------
``batch_ladder(multiple, top)`` = ``multiple * 2^k`` for every rung
below ``top``, plus ``top`` itself (so a production serve batch size
that is not a power of two is still a catalog member).  ``top``
defaults to ``SCINT_BUCKET_TOP`` (env, default 64); surveys larger than
``top`` chunk at the top rung with uniform-chunk padding — still
exactly ONE compiled program.  Every rung is a multiple of the mesh's
data-axis size, as divisibility requires.

Precision/config awareness: a :class:`BucketSignature` carries the
staging dtype (``driver.stage_dtype`` of the config's precision policy)
and a config digest, so ``bf16_io`` and ``f32`` jobs land in separate
catalog entries — they ARE different compiled programs.

Consumers: ``parallel.run_pipeline(bucket=True)`` (pads each shape
bucket onto the ladder), ``compile_cache.plan_steps(catalog=True)`` /
``scintools-tpu warmup --catalog`` (pre-compiles the whole ladder so a
warm worker serves any shape with ``jit_cache_miss == 0``), the serve
batcher (partial flushes pad to the nearest rung instead of the full
batch size), and ``scripts/build_warm_cache.py`` (ships the compiled
catalog as a relocatable artifact keyed on :func:`catalog_digest`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np

# largest pad-to rung (and the chunk size above it); env-overridable so
# a serve fleet with bigger warm batches widens its catalog
TOP_ENV = "SCINT_BUCKET_TOP"
DEFAULT_TOP = 64


def default_top() -> int:
    """The ladder's top rung from the environment (``SCINT_BUCKET_TOP``,
    default 64)."""
    try:
        top = int(os.environ.get(TOP_ENV, DEFAULT_TOP))
    except ValueError:
        raise ValueError(f"{TOP_ENV} must be an integer, got "
                         f"{os.environ.get(TOP_ENV)!r}")
    if top < 1:
        raise ValueError(f"{TOP_ENV} must be >= 1, got {top}")
    return top


def batch_ladder(multiple: int = 1, top: int | None = None) -> tuple:
    """The closed set of padded batch sizes: ``multiple * 2^k`` for
    every value below ``top``, plus ``top`` itself (adjusted up to a
    multiple of ``multiple``).  Always non-empty and sorted."""
    multiple = max(int(multiple), 1)
    top = default_top() if top is None else int(top)
    # top must itself be a legal batch (mesh divisibility)
    top = -(-max(top, 1) // multiple) * multiple
    rungs = []
    r = multiple
    while r < top:
        rungs.append(r)
        r *= 2
    rungs.append(top)
    return tuple(rungs)


def rung_for(n: int, multiple: int = 1, top: int | None = None) -> int:
    """Smallest ladder rung >= ``n`` — the padded batch size ``n``
    epochs canonicalise onto.  Counts above the top rung return the top
    rung (the caller chunks at it; see :func:`bucket_plan`)."""
    if n < 1:
        raise ValueError(f"rung_for: need n >= 1, got {n}")
    for r in batch_ladder(multiple, top):
        if r >= n:
            return r
    return batch_ladder(multiple, top)[-1]


def bucket_plan(n: int, multiple: int = 1, top: int | None = None) -> dict:
    """How ``run_pipeline`` executes ``n`` epochs on the catalog:
    ``{"pad_to": rung}`` when one padded step covers them, or
    ``{"chunk": top, "pad_chunks": True}`` when the survey is larger
    than the top rung (uniform chunks of the top rung — still ONE
    compiled program).  Both reuse the driver's existing mask-invalid
    lane machinery, so real-lane results are byte-identical to the
    unbucketed run."""
    r = rung_for(n, multiple, top)
    if n <= r:
        return {"pad_to": r}
    return {"chunk": r, "pad_chunks": True}


# ---------------------------------------------------------------------------
# mini vector ladder (ISSUE 14): closed rung lengths for the split
# pipeline's canonicalised fitter inputs.  The split back-end consumes
# tail-padded cut vectors whose REAL length is (nf + nt)-derived and
# therefore shape-volatile; padding onto this small geometric ladder
# makes the padded length — and with it the fitter program — a member
# of a closed set, so virtually every survey shape maps onto an
# already-compiled fitter.  Mirrors the batch ladder above: pow2 rungs
# from a floor, unbounded top (a cut vector is O(nf+nt) floats — the
# pad waste is bytes, not a device-memory hazard like batch lanes).
# ---------------------------------------------------------------------------

# smallest rung: below this every observing grid shares one program
VECTOR_RUNG_MIN = 256


def vector_rung(n: int, minimum: int = VECTOR_RUNG_MIN) -> int:
    """Smallest pow2-ladder rung >= ``n``: the padded length a
    ``n``-element fitter input canonicalises onto."""
    if n < 1:
        raise ValueError(f"vector_rung: need n >= 1, got {n}")
    r = max(int(minimum), 1)
    while r < n:
        r *= 2
    return r


def vector_ladder(n_max: int, minimum: int = VECTOR_RUNG_MIN) -> tuple:
    """Every vector rung up to (and including) the one covering
    ``n_max`` — the closed fitter-input length set a warmup should
    pre-compile."""
    out = []
    r = max(int(minimum), 1)
    top = vector_rung(n_max, minimum)
    while r <= top:
        out.append(r)
        r *= 2
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class BucketSignature:
    """One catalog member: the padded step signature a canonicalised
    batch executes (batch rung x exact epoch axes x staging dtype x
    config digest)."""

    batch: int
    nf: int
    nt: int
    dtype: str
    axes_digest: str = ""
    cfg_digest: str = ""
    chunked: bool = False

    @property
    def label(self) -> str:
        """Compact per-signature key, matching the obs gauge/counter
        label convention (``BxNFxNT:dtype``)."""
        return f"{self.batch}x{self.nf}x{self.nt}:{self.dtype}"


def _cfg_digest(config) -> str:
    return hashlib.sha256(repr(config).encode()).hexdigest()[:12]


def _axes_digest(freqs, times) -> str:
    f = np.ascontiguousarray(np.asarray(freqs, dtype=np.float64))  # host-f64: catalog key
    t = np.ascontiguousarray(np.asarray(times, dtype=np.float64))  # host-f64: catalog key
    return hashlib.sha256(f.tobytes() + t.tobytes()).hexdigest()[:12]


def canonicalize(epoch_shape, config, multiple: int = 1,
                 top: int | None = None, freqs=None,
                 times=None) -> BucketSignature:
    """Map an arbitrary ``(B, nf, nt)`` survey shape onto its catalog
    member: the batch axis rounds UP to the nearest ladder rung (or the
    top rung, chunk-covered, for bigger surveys); ``(nf, nt)`` pass
    through untouched (axes identity is sacrosanct — see the module
    docstring).  ``config`` decides the staging dtype (precision policy)
    and the config digest, so ``bf16_io`` and ``f32`` surveys land in
    DIFFERENT catalog entries."""
    from .parallel.driver import stage_dtype

    b, nf, nt = (int(s) for s in epoch_shape)
    r = rung_for(b, multiple, top)
    return BucketSignature(
        batch=r, nf=nf, nt=nt,
        dtype=str(np.dtype(stage_dtype(config.precision))),
        axes_digest=(_axes_digest(freqs, times)
                     if freqs is not None and times is not None else ""),
        cfg_digest=_cfg_digest(config),
        chunked=b > r)


def catalog(epochs, config, mesh=None, top: int | None = None) -> list:
    """The FULL closed signature set for these observing setups: one
    :class:`BucketSignature` per (axes bucket, ladder rung), top rung
    additionally marked ``chunked`` (the chunk loop donates its input
    on TPU, which is part of the compile-cache key).  This is what
    ``warmup --catalog`` compiles and :func:`catalog_digest` keys the
    warm-cache artifact on."""
    from .parallel import mesh as mesh_mod
    from .parallel.driver import _bucket_epochs, stage_dtype

    multiple = 1
    if mesh is not None:
        multiple = mesh.shape[mesh_mod.DATA_AXIS]
    dtype = str(np.dtype(stage_dtype(config.precision)))
    cfgd = _cfg_digest(config)
    out = []
    for key in _bucket_epochs(epochs):
        (nf,), (nt,) = key[0], key[1]
        axes = hashlib.sha256(key[2] + key[3]).hexdigest()[:12]
        ladder = batch_ladder(multiple, top)
        for r in ladder:
            out.append(BucketSignature(batch=r, nf=nf, nt=nt, dtype=dtype,
                                       axes_digest=axes, cfg_digest=cfgd,
                                       chunked=False))
        # the top rung also runs through the chunk loop for
        # bigger-than-top surveys; donation differs there (TPU), so it
        # is its own compiled signature
        out.append(BucketSignature(batch=ladder[-1], nf=nf, nt=nt,
                                   dtype=dtype, axes_digest=axes,
                                   cfg_digest=cfgd, chunked=True))
    return out


def catalog_digest(keys) -> str:
    """Stable digest of a compiled catalog — the warm-cache artifact's
    identity.  ``keys`` are the compile-cache step keys (which already
    fold in axes, config, mesh, dtype, donation and the jax/jaxlib/
    backend versions), so two catalogs digest equal iff they compile
    the exact same program set."""
    h = hashlib.sha256()
    for k in sorted(str(k) for k in keys):
        h.update(k.encode())
        h.update(b"\n")
    return h.hexdigest()[:16]


def pad_waste(real_lanes: int, issued_lanes: int) -> float:
    """Padded-elements / real-elements ratio of one bucket execution —
    the over-padding visibility metric ``trace report`` surfaces per
    catalog entry (0.0 = perfect fill)."""
    if real_lanes <= 0:
        return 0.0
    return round(max(issued_lanes - real_lanes, 0) / real_lanes, 4)
