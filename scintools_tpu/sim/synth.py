"""Thin-arc synthetic epochs: scattered-image wavefields with a KNOWN
curvature.

Complementary to the Kolmogorov phase-screen simulator: instead of
propagating a random screen, build the scattered field directly as a sum
of images along ``tau = eta fd^2`` (the same construction the wavefield
ground-truth tests use) and observe its intensity.  The secondary
spectrum then carries a sharp arc at a curvature you chose — ideal for
fitter validation, demos, and smoke batches: the reference's arc fitter
(and the batched fitter that emulates it bit-for-bit, fit/arc_fit.py)
raises/quarantines on small noisy phase-screen sims for most seeds,
while these epochs fit for every seed (verified at 32x32 and 64x64).
"""

from __future__ import annotations

import numpy as np

from ..data import DynspecData

__all__ = ["thin_arc_betaeta", "thin_arc_epoch", "thin_arc_eta"]


def thin_arc_eta(arc_frac: float = 0.5, df: float = 0.5,
                 dt: float = 10.0, **_ignored) -> float:
    """The curvature (us/mHz^2) thin_arc_epoch injects for these
    parameters — the single source of truth for tests that bracket the
    true arc (extra kwargs like nimg/core are accepted and ignored so a
    tuning dict can be passed wholesale)."""
    fd_max = 1e3 / (2 * dt)
    tau_max = 1 / (2 * df)
    return arc_frac * tau_max / (0.4 * fd_max) ** 2


def thin_arc_epoch(nf: int = 64, nt: int = 64, seed: int = 0,
                   arc_frac: float = 0.5, nimg: int = 32,
                   core: float = 8.0, noise: float = 0.005,
                   env: float = 0.3, df: float = 0.5,
                   dt: float = 10.0) -> DynspecData:
    """One synthetic epoch whose secondary spectrum carries a thin arc.

    ``arc_frac`` places the arc's delay extent at that fraction of the
    delay Nyquist (curvature ``eta = arc_frac * tau_nyq / (0.4 *
    fd_nyq)**2`` in us/mHz^2); ``nimg`` images sit on the arc with a
    Gaussian envelope of width ``env * fd_nyq`` and a bright core
    (+``core``); ``noise`` is fractional multiplicative noise.
    """
    rng = np.random.default_rng(seed)
    freqs = 1400.0 + np.arange(nf) * df
    times = np.arange(nt) * dt
    fd_max = 1e3 / (2 * dt)
    eta = thin_arc_eta(arc_frac=arc_frac, df=df, dt=dt)
    th = np.linspace(-0.4 * fd_max, 0.4 * fd_max, nimg)
    mu = ((rng.normal(size=nimg) + 1j * rng.normal(size=nimg))
          * np.exp(-0.5 * (th / (env * fd_max)) ** 2))
    mu[nimg // 2] += core
    f_rel = (freqs - freqs[0])[:, None]
    t_abs = times[None, :]
    E = sum(mu[j] * np.exp(2j * np.pi * ((eta * th[j] ** 2) * f_rel
                                         + th[j] * 1e-3 * t_abs))
            for j in range(nimg))
    dyn = np.abs(E) ** 2 * (1 + noise * rng.standard_normal((nf, nt)))
    return DynspecData(dyn=dyn, freqs=freqs, times=times,
                       name=f"synth{seed}", mjd=53000.0 + seed)


def thin_arc_betaeta(freqs, arc_frac: float = 0.5, df: float = 0.5,
                     dt: float = 10.0, ref_freq: float = 1400.0,
                     **_ignored) -> float:
    """:func:`thin_arc_eta` converted to the lamsteps fitter's beta-eta
    units at this epoch's mean frequency — the closed-form ground truth
    a lamsteps arc fit on :func:`thin_arc_epoch` should recover.
    Inverse of the unit conversion ``fit_arc`` applies to non-lamsteps
    constraints (fit/arc_fit.py; reference dynspec.py:470-491)."""
    from ..fit.arc_fit import _beta_to_eta_factor

    f = float(np.mean(np.asarray(freqs)))
    b2e = _beta_to_eta_factor(f, ref_freq)
    return (thin_arc_eta(arc_frac=arc_frac, df=df, dt=dt)
            / b2e * (f / ref_freq) ** 2)
