"""On-device synthetic campaigns: the phase-screen simulator as a
first-class production workload (ROADMAP item 5).

BENCH_r05 pinned the pipeline bandwidth-bound, and for synthetic
campaigns a large share of those bytes are pure waste: ``sim/``
generates dynspecs on host and the driver re-stages them over PCIe,
even though both ends are jit'd JAX.  This module closes the loop the
way the GPU real-time search literature keeps its transform pipeline
resident (arXiv:1804.05335): the compiled analysis step's INPUT becomes
a batch of PRNG keys (+ optional bitcast sweep values) and the dynspec
batch is *born in HBM* inside the same jit'd program — generate →
sspec/ACF → fit with zero H2D traffic in the hot loop
(``bytes_h2d`` drops from ``O(B · nf · nt · 4 B)`` to ``O(B keys)``,
counter-asserted in tier-1).

:class:`SynthSpec` describes a campaign; ``parallel.run_pipeline(
synthetic=spec)`` runs it through the SAME driver machinery as
file-backed epochs (mesh data-axis sharding, chunking, bucket-catalog
canonicalisation, compile-cache/AOT artifacts, obs counters).  Three
generator kinds:

* ``"screen"`` — Kolmogorov phase screens via the jit'd simulator
  (:func:`~scintools_tpu.sim.simulation.simulate_intensity`), the
  physics-grade production load generator; supports ``SimParams``
  float-field sweeps (one compiled program covers a physics grid, the
  values ride as bitcast traced inputs) and the low-k compensation
  knobs (``subharmonics`` / ``pac``).
* ``"arc"`` — the thin-arc scattered-image construction
  (sim/synth.py) with a CLOSED-FORM injected curvature: robustly
  arc-fittable at small sizes, the eta half of the closed-loop
  validation gate.
* ``"acf"`` — a circular-Gaussian field whose intensity ACF is EXACTLY
  the scint fitter's model (``exp(-(dt/tau)^alpha)`` in time,
  half-power ``dnu`` in frequency): ``tau_s`` / ``dnu_mhz`` are the
  injected ground truth in the fitter's own parameterisation — the
  tau/dnu half of the closed-loop gate.

Epoch identity is ``(seed, index)``: epoch ``i`` of a campaign stages
the raw threefry key ``[seed, i]`` (uint32), so resume, chunking,
padding and serve-side idempotency all address epochs stably without
any device work at staging time.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .simulation import SimParams, _SWEEPABLE

_KINDS = ("screen", "arc", "acf")

# epoch mjd base for synthetic rows (sim/synth.py convention)
_MJD0 = 53000.0


@dataclasses.dataclass(frozen=True)
class SynthSpec:
    """One synthetic campaign: generator kind + physics + epoch count.

    Hashable/frozen: a canonicalised spec (:func:`generator_id`) is part
    of the compiled step's jit and compile-cache identity.  Fields that
    do not apply to ``kind`` are ignored (and canonicalised away from
    the program identity)."""

    kind: str = "screen"
    n_epochs: int = 1
    seed: int = 0
    # observing-axis mapping (all kinds): for "screen" the frequency
    # axis comes from (freq, params.nf, params.dlam) exactly as
    # io.from_simulation builds it; for "arc"/"acf" freq is the BASE
    # frequency of an nf-channel axis with df spacing
    freq: float = 1400.0
    dt: float = 8.0
    # --- kind="screen" -----------------------------------------------------
    params: SimParams = SimParams()
    freq_chunk: int = 0    # bound the per-epoch [chunk, nx, ny] FFT space
    screen_chunk: int = 0  # lax.map chunk over epochs INSIDE the step
    #                        (0 = vmap the whole per-step batch; the
    #                        driver's `chunk` already bounds that batch)
    sweep: tuple = ()      # ((field, (v0, ... v_{n_epochs-1})), ...):
    #                        per-epoch physics values, traced (bitcast
    #                        into the staged key rows) so one compiled
    #                        program covers the whole grid
    # --- kind="arc"/"acf" --------------------------------------------------
    nf: int = 64
    nt: int = 64
    df: float = 0.5        # MHz channel width
    # thin-arc knobs (sim/synth.thin_arc_epoch)
    arc_frac: float = 0.5
    nimg: int = 32
    core: float = 8.0
    noise: float = 0.005
    env: float = 0.3
    # acf-kind injected ground truth (the fitter's parameterisation)
    tau_s: float = 200.0
    dnu_mhz: float = 2.0
    acf_alpha: float = 5 / 3


def validate_spec(spec: SynthSpec) -> None:
    """Reject specs the generator would deterministically reject —
    shared by ``run_pipeline(synthetic=...)``, the serve ``simulate``
    submit path and the CLI, so a bad campaign fails at the caller."""
    if not isinstance(spec, SynthSpec):
        raise TypeError(f"expected SynthSpec, got {type(spec).__name__}")
    if spec.kind not in _KINDS:
        raise ValueError(f"SynthSpec.kind: unknown generator "
                         f"{spec.kind!r} (expected one of {_KINDS})")
    if spec.n_epochs < 1:
        raise ValueError(f"SynthSpec.n_epochs must be >= 1, got "
                         f"{spec.n_epochs}")
    if not 0 <= spec.seed < 2 ** 32:
        # the staged key word is uint32: a silently-truncated larger
        # seed would reproduce another campaign's data under a
        # different identity (resume key / job id / row names)
        raise ValueError(f"SynthSpec.seed must be in [0, 2^32), got "
                         f"{spec.seed} (it is staged as one uint32 "
                         "key word)")
    if not isinstance(spec.params, SimParams):
        raise TypeError("SynthSpec.params must be a SimParams")
    if spec.kind == "screen":
        if spec.screen_chunk < 0 or spec.freq_chunk < 0:
            raise ValueError("screen_chunk/freq_chunk must be >= 0")
        for name, vals in spec.sweep:
            if name not in _SWEEPABLE:
                raise ValueError(
                    f"cannot sweep {name!r}; sweepable float fields "
                    f"are {_SWEEPABLE}")
            if len(vals) != spec.n_epochs:
                raise ValueError(
                    f"sweep {name!r} carries {len(vals)} values for "
                    f"{spec.n_epochs} epochs (one value per epoch)")
        if spec.sweep and (spec.params.subharmonics or spec.params.pac):
            raise ValueError(
                "swept campaigns do not support subharmonics/pac "
                "(host-side mode tables); sweep the plain FFT screens")
    else:
        if spec.sweep:
            raise ValueError("sweep applies to kind='screen' only")
        if spec.nf < 2 or spec.nt < 2:
            raise ValueError(f"nf/nt must be >= 2, got "
                             f"{spec.nf}x{spec.nt}")
        if spec.kind == "arc" and spec.nimg < 1:
            raise ValueError("arc kind needs nimg >= 1")
        if spec.kind == "acf" and (spec.tau_s <= 0 or spec.dnu_mhz <= 0):
            raise ValueError("acf kind needs tau_s > 0 and dnu_mhz > 0")


def synth_shape(spec: SynthSpec) -> tuple[int, int]:
    """The (nf, nt) grid the generator produces — the analysis step's
    per-epoch shape."""
    if spec.kind == "screen":
        return (spec.params.nf, spec.params.nx)
    return (spec.nf, spec.nt)


def synth_axes(spec: SynthSpec) -> tuple[np.ndarray, np.ndarray]:
    """Host-side (freqs, times) template axes of the campaign's epochs
    — what the analysis pipeline's host-side grid builders consume, in
    place of a loaded epoch's axes."""
    nf, nt = synth_shape(spec)
    if spec.kind == "screen":
        from ..io.adapters import _freqs_from_dlam

        freqs = _freqs_from_dlam(spec.freq, nf, spec.params.dlam)
    else:
        freqs = spec.freq + np.arange(nf, dtype=np.float64) * spec.df  # host-f64: host axes
    times = float(spec.dt) * np.arange(nt, dtype=np.float64)  # host-f64: host axes
    return np.ascontiguousarray(np.asarray(freqs, dtype=np.float64)), times  # host-f64: host axes


def stage_width(spec: SynthSpec) -> int:
    """Columns of the staged key batch: 2 key words + one bitcast
    float32 per swept field."""
    return 2 + (len(spec.sweep) if spec.kind == "screen" else 0)


def stage_batch(spec: SynthSpec) -> np.ndarray:
    """The campaign's staged input: uint32 ``[n_epochs, 2 + F]`` rows of
    ``[seed, epoch_index, bitcast sweep values...]``.  This — not the
    dynspec batch — is all that ever crosses PCIe on the synthetic
    route; everything downstream (mesh sharding, divisibility/rung
    padding by repeating the last row, chunk slicing) operates on the
    leading axis exactly as it does for a staged dynspec batch."""
    rows = np.zeros((spec.n_epochs, stage_width(spec)), dtype=np.uint32)
    rows[:, 0] = np.uint32(spec.seed)   # validate_spec pins [0, 2^32)
    rows[:, 1] = np.arange(spec.n_epochs, dtype=np.uint32)
    if spec.kind == "screen":
        for j, (_name, vals) in enumerate(spec.sweep):
            rows[:, 2 + j] = np.asarray(vals,
                                        dtype=np.float32).view(np.uint32)
    return rows


def generator_id(spec: SynthSpec) -> SynthSpec:
    """The PROGRAM identity of a spec: everything that shapes the traced
    generator, with run-only fields (n_epochs, seed, and the sweep
    VALUES — a traced input) and the other kinds' knobs canonicalised
    to defaults, so campaigns over the same generator share one
    compiled step, one compile-cache artifact and one warm signature."""
    kw = {"kind": spec.kind, "dt": float(spec.dt),
          "freq": float(spec.freq)}
    if spec.kind == "screen":
        kw.update(params=spec.params, freq_chunk=int(spec.freq_chunk),
                  screen_chunk=int(spec.screen_chunk),
                  sweep=tuple((name, ()) for name, _vals in spec.sweep))
    else:
        kw.update(nf=int(spec.nf), nt=int(spec.nt), df=float(spec.df))
        if spec.kind == "arc":
            kw.update(arc_frac=float(spec.arc_frac), nimg=int(spec.nimg),
                      core=float(spec.core), noise=float(spec.noise),
                      env=float(spec.env))
        else:
            kw.update(tau_s=float(spec.tau_s),
                      dnu_mhz=float(spec.dnu_mhz),
                      acf_alpha=float(spec.acf_alpha))
    return SynthSpec(**kw)


# ---------------------------------------------------------------------------
# traced generators
# ---------------------------------------------------------------------------


def _thin_arc_intensity(key, g: SynthSpec):
    """jax port of sim/synth.thin_arc_epoch (same construction, jax
    RNG): ``[nf, nt]`` intensity whose secondary spectrum carries an
    arc at the closed-form curvature ``synth.thin_arc_eta(g.arc_frac,
    g.df, g.dt)`` — the injected truth :func:`injected_truth` reports.
    The per-image factors are separable, so the field is one einsum
    over host-constant mode tables."""
    import jax
    import jax.numpy as jnp

    from .synth import thin_arc_eta

    fd_max = 1e3 / (2 * g.dt)
    eta = thin_arc_eta(arc_frac=g.arc_frac, df=g.df, dt=g.dt)
    th = np.linspace(-0.4 * fd_max, 0.4 * fd_max, g.nimg)
    env = np.exp(-0.5 * (th / (g.env * fd_max)) ** 2)
    # E = sum_j mu_j u_j(f) v_j(t): host-constant complex mode tables
    u = np.exp(2j * np.pi * eta * th[:, None] ** 2
               * (np.arange(g.nf) * g.df)[None, :])          # [nimg, nf]
    v = np.exp(2j * np.pi * 1e-3 * th[:, None]
               * (np.arange(g.nt) * g.dt)[None, :])          # [nimg, nt]
    k1, k2, k3 = jax.random.split(key, 3)
    mu = (jax.random.normal(k1, (g.nimg,))
          + 1j * jax.random.normal(k2, (g.nimg,))) * env
    mu = mu.at[g.nimg // 2].add(g.core)
    E = jnp.einsum("j,jf,jt->ft", mu, jnp.asarray(u), jnp.asarray(v))
    dyn = jnp.real(E) ** 2 + jnp.imag(E) ** 2
    return dyn * (1 + g.noise * jax.random.normal(k3, (g.nf, g.nt)))


def _acf_model_intensity(key, g: SynthSpec):
    """``[nf, nt]`` intensity of a circular-Gaussian field whose
    ensemble intensity ACF is EXACTLY the scint fitter's model:
    ``exp(-(dt/tau)^alpha)`` on the time cut and half-power bandwidth
    ``dnu`` on the frequency cut (models/acf_models.py conventions) —
    so ``g.tau_s`` and ``g.dnu_mhz`` are injected ground truth in the
    fitter's own parameterisation.

    Construction: the target FIELD covariance is the square root of the
    intensity ACF (|C_E|^2 = ACF_I for circular-Gaussian E); its FFT
    gives exact per-mode variances on the periodic grid, and
    ``E = fft2(w z)`` realises them."""
    import jax
    import jax.numpy as jnp

    lt = np.minimum(np.arange(g.nt), g.nt - np.arange(g.nt)) * g.dt
    lf = np.minimum(np.arange(g.nf), g.nf - np.arange(g.nf)) * g.df
    a_t = np.exp(-0.5 * (lt / g.tau_s) ** g.acf_alpha)
    a_f = np.exp(-0.5 * lf / (g.dnu_mhz / np.log(2)))
    cov = a_f[:, None] * a_t[None, :]                        # [nf, nt]
    s = np.clip(np.real(np.fft.fft2(cov)), 0.0, None)
    w = np.sqrt(s / (2.0 * g.nf * g.nt))
    k1, k2 = jax.random.split(key)
    z = (jax.random.normal(k1, (g.nf, g.nt))
         + 1j * jax.random.normal(k2, (g.nf, g.nt)))
    E = jnp.fft.fft2(jnp.asarray(w) * z)
    return jnp.real(E) ** 2 + jnp.imag(E) ** 2


def injected_truth(spec: SynthSpec, lamsteps: bool = True) -> dict:
    """The closed-form ground truth a closed-loop gate checks fits
    against: ``{"betaeta"| "eta": ...}`` for the arc kind (via
    sim/synth's unit conversions), ``{"tau": ..., "dnu": ...}`` for the
    acf kind.  The screen kind has no closed-form single-epoch truth
    (its validation is statistical — see the pac slope test)."""
    if spec.kind == "arc":
        from .synth import thin_arc_betaeta, thin_arc_eta

        freqs, _times = synth_axes(spec)
        if lamsteps:
            return {"betaeta": thin_arc_betaeta(
                freqs, arc_frac=spec.arc_frac, df=spec.df, dt=spec.dt)}
        return {"eta": thin_arc_eta(arc_frac=spec.arc_frac, df=spec.df,
                                    dt=spec.dt)}
    if spec.kind == "acf":
        return {"tau": float(spec.tau_s), "dnu": float(spec.dnu_mhz)}
    return {}


def synth_generator(gen: SynthSpec):
    """Build the traced generator of a generator_id-canonical spec:
    ``raw uint32 [B, 2+F] -> dyn [B, nf, nt]``, composed into the
    analysis step by ``parallel.driver._make_pipeline_cached`` so the
    dynspec batch never exists host-side."""
    import jax
    import jax.numpy as jnp

    nf, nt = synth_shape(gen)
    width = stage_width(gen)

    if gen.kind == "screen":
        p = gen.params
        fields = tuple(name for name, _vals in gen.sweep)
        if fields:
            from .simulation import _sweep_screen_intensity

            swept_one = _sweep_screen_intensity(p, fields)

            def one(row):
                vals = jax.lax.bitcast_convert_type(row[2:], jnp.float32)
                return swept_one(row[:2], vals).T
        else:
            from .simulation import simulate_intensity

            def one(row):
                return simulate_intensity(
                    row[:2], p, freq_chunk=gen.freq_chunk or None).T
    elif gen.kind == "arc":
        def one(row):
            return _thin_arc_intensity(row[:2], gen)
    else:
        def one(row):
            return _acf_model_intensity(row[:2], gen)

    chunk = gen.screen_chunk if gen.kind == "screen" else 0

    def generate(raw):
        raw = jnp.asarray(raw)
        if raw.ndim != 2 or raw.shape[1] != width:
            raise ValueError(
                f"synthetic step input must be [B, {width}] uint32 key "
                f"rows, got {raw.shape}")
        B = raw.shape[0]
        if not chunk or chunk >= B:
            return jax.vmap(one)(raw)
        # lax.map over screen_chunk-sized slabs bounds the generator's
        # [chunk, nx, ny] FFT workspace; pad rows are re-simulations of
        # cycled keys, sliced off before the analysis stages
        from .simulation import _pad_cycle

        rows = _pad_cycle(raw, chunk)
        kc = rows.reshape(-1, chunk, rows.shape[1])
        out = jax.lax.map(lambda r: jax.vmap(one)(r), kc)
        return out.reshape(-1, nf, nt)[:B]

    return generate


# ---------------------------------------------------------------------------
# spec <-> dict (serve job payload / CLI), rows, identity keys
# ---------------------------------------------------------------------------


def spec_to_dict(spec: SynthSpec) -> dict:
    """Canonical sparse JSON-able form of a spec — the serve job
    payload and the CLI's resume-key ingredient.  Only non-default
    fields are kept (so sparse client dicts and fully-materialised CLI
    dicts share one job identity), with SimParams nested sparsely under
    ``"params"`` and sweeps as ``[[field, [values...]], ...]``."""
    out: dict = {}
    d0 = SynthSpec()
    p0 = SimParams()
    for f in dataclasses.fields(SynthSpec):
        v = getattr(spec, f.name)
        if f.name == "params":
            pd = {pf.name: getattr(v, pf.name)
                  for pf in dataclasses.fields(SimParams)
                  if getattr(v, pf.name) != getattr(p0, pf.name)}
            if pd:
                out["params"] = pd
        elif f.name == "sweep":
            if v:
                out["sweep"] = [[name, [float(x) for x in vals]]
                                for name, vals in v]
        elif v != getattr(d0, f.name):
            out[f.name] = v
    return out


def spec_from_dict(d: dict) -> SynthSpec:
    """Inverse of :func:`spec_to_dict`, validating loudly: unknown keys
    raise (a typo'd job payload must fail at submit, not burn a serve
    retry budget discovering it)."""
    d = dict(d or {})
    names = {f.name for f in dataclasses.fields(SynthSpec)}
    pnames = {f.name for f in dataclasses.fields(SimParams)}
    params = d.pop("params", None)
    sweep = d.pop("sweep", None)
    unknown = set(d) - names
    if unknown:
        raise ValueError(f"unknown SynthSpec field(s): {sorted(unknown)}")
    kw = dict(d)
    if params is not None:
        bad = set(params) - pnames
        if bad:
            raise ValueError(f"unknown SimParams field(s): {sorted(bad)}")
        kw["params"] = SimParams(**params)
    if sweep is not None:
        kw["sweep"] = tuple((str(name), tuple(float(x) for x in vals))
                            for name, vals in sweep)
    spec = SynthSpec(**kw)
    validate_spec(spec)
    return spec


def epoch_name(spec: SynthSpec, i: int) -> str:
    """Deterministic per-epoch row name (the CSV ``name`` column)."""
    return f"synth-{spec.kind}-s{spec.seed}-{int(i):05d}"


def synth_meta(spec: SynthSpec) -> dict:
    """The name-less metadata columns every epoch of this campaign
    shares (results_row's derivations, computed from the synthetic axes
    the way DynspecData derives them from loaded axes)."""
    freqs, times = synth_axes(spec)
    df = float(freqs[1] - freqs[0])
    dt = float(times[1] - times[0])
    return dict(freq=float(np.mean(freqs)),
                bw=float(abs(freqs[-1] - freqs[0])) + abs(df),
                tobs=float(times[-1] - times[0]) + abs(dt),
                dt=dt, df=df)


def synth_row_key(base: str, i: int) -> str:
    """Results-store key of epoch ``i`` under campaign identity
    ``base`` — shared by the serve ``simulate`` job runner and its
    dedup probe, and shaped so a campaign's rows sort in epoch order
    (CSV export order is key order)."""
    return f"{base}.{int(i):05d}"


def synthetic_rows(spec: SynthSpec, opts: dict, mesh=None,
                   async_exec: bool = True, chunk: int | None = None,
                   pad_chunks: bool = False,
                   bucket: bool = False) -> list:
    """Generate + analyse the campaign on-device and build one result
    row per epoch (``None`` for lanes whose fits came back non-finite —
    the quarantine rule the batched CLI engine applies).  The ONE row
    builder shared by the CLI synthetic engine and the serve
    ``simulate`` job runner, so served CSV rows are byte-identical to a
    direct run's."""
    from ..io.results import batch_lane_row, row_fit_values
    from ..parallel import run_pipeline
    from ..serve.worker import config_from_opts

    cfg = config_from_opts(opts)
    buckets = run_pipeline(config=cfg, mesh=mesh, chunk=chunk,
                           async_exec=async_exec, pad_chunks=pad_chunks,
                           bucket=bucket, synthetic=spec)
    meta = synth_meta(spec)
    rows: list = [None] * spec.n_epochs
    for idx, res in buckets:
        for lane, i in enumerate(idx):
            row = dict(meta)
            row["name"] = epoch_name(spec, i)
            row["mjd"] = _MJD0 + int(i)
            row.update(batch_lane_row(res, lane, cfg.lamsteps))
            fitvals = row_fit_values(row)
            if fitvals and not np.all(np.isfinite(fitvals)):
                continue   # NaN lane: quarantined (rows[i] stays None)
            rows[int(i)] = row
    return rows
