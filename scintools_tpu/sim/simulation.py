"""Kolmogorov phase-screen scintillation simulator.

Reference: ``scint_sim.Simulation`` (scint_sim.py:20-264), itself a port of
Coles et al. (2010): synthesise an anisotropic power-law random phase screen,
propagate a plane wave through it with a Fresnel filter at each observing
frequency, and record the intensity along a spatial cut -> dynamic spectrum.

Two paths:

* numpy (:class:`Simulation`): reproduces the reference pipeline including
  its seeded RNG call order (``np.random.seed`` then two ``randn`` draws,
  scint_sim.py:148,176), so seeded outputs can be compared against the
  reference implementation run on the same machine.

* jax (:func:`simulate`): a jit'd pure function of ``(key, SimParams)``.
  The screen weights use the intended signed-FFT-frequency grid (the
  reference builds the same interior values with index loops at
  scint_sim.py:157-173, with off-by-one quirks on the kx/ky axis lines that
  we do not reproduce); the per-frequency Fresnel propagation loop
  (scint_sim.py:188-204) becomes a batched FFT over a frequency axis —
  embarrassingly parallel, MXU/VPU-friendly, vmappable over seeds for
  Monte-Carlo ensembles.

The Fresnel filter: the reference multiplies the four FFT quadrants by
``exp(-i q^2)`` with per-quadrant index arithmetic (frfilt3,
scint_sim.py:247-264).  On the full FFT grid that is exactly
``exp(-i (ffconx qx^2 + ffcony qy^2) scale)`` with ``q = min(i, n-i)`` the
absolute FFT frequency index; both paths use that closed form (verified
against the quadrant construction in tests).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np
from numpy.fft import fft2, ifft2
from scipy.special import gamma as _gamma


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static simulation parameters (hashable -> usable as jit static arg).

    Mirrors Simulation.__init__ kwargs (scint_sim.py:22-57).
    """

    mb2: float = 2.0       # Born parameter: scattering strength
    rf: float = 1.0        # Fresnel scale
    dx: float = 0.01       # spatial step / rf
    dy: float = 0.01
    alpha: float = 5 / 3   # structure-function exponent (Kolmogorov)
    ar: float = 1.0        # anisotropy axial ratio
    psi: float = 0.0       # anisotropy position angle (deg)
    inner: float = 0.001   # inner scale / rf
    nx: int = 256
    ny: int = 256
    nf: int = 256
    dlam: float = 0.25     # fractional bandwidth
    lamsteps: bool = False
    subharmonics: int = 0  # low-k compensation octaves (0 = reference
    #                        behaviour).  FFT-synthesised screens miss all
    #                        power below the fundamental grid frequency,
    #                        which for steep Kolmogorov spectra truncates
    #                        the large-scale structure function (see e.g.
    #                        arXiv:2208.06060 and Lane et al. 1992).  Each
    #                        octave adds the 8 modes at (p,q)*dq/3^o,
    #                        |p|,|q|<=1, with spectrum-consistent weights.
    #                        jax screen path only; the numpy path stays
    #                        reference-exact and ignores this field.
    pac: bool = False      # Gaussian phase-autocovariance compensated
    #                        weights (arXiv:2208.06060): instead of
    #                        sampling the power-law spectrum on the FFT
    #                        grid (which drops ALL power below the grid
    #                        fundamental), build the per-mode variances
    #                        from the FFT of the closed-form Kolmogorov
    #                        phase covariance evaluated on the periodic
    #                        grid — the screen is then an exact Gaussian
    #                        process whose structure function follows
    #                        (r/s0)^alpha out to the wrap scale (the
    #                        measurable low-frequency accuracy fix; see
    #                        screen_weights_pac and the slope acceptance
    #                        test).  Opt-in, jax screen path only,
    #                        mutually exclusive with ``subharmonics``.


def derived_constants(p: SimParams) -> dict:
    """Fresnel-filter factors, spectrum normalisation, coherence scale s0
    and refractive scale sref (set_constants, scint_sim.py:112-142).
    Host-side scalar algebra; folded into jit traces as constants."""
    ns = 1
    lenx, leny = p.nx * p.dx, p.ny * p.dy
    a2 = p.alpha * 0.5
    aa, ab = 1.0 + a2, 1.0 - a2
    cdrf = 2.0 ** p.alpha * np.cos(p.alpha * np.pi * 0.25) * _gamma(aa) / p.mb2
    cmb2 = p.alpha * p.mb2 / (4 * np.pi * _gamma(ab)
                              * np.cos(p.alpha * np.pi * 0.25) * ns)
    dqx, dqy = 2 * np.pi / lenx, 2 * np.pi / leny
    return dict(
        ffconx=(2.0 / (ns * lenx * lenx)) * (np.pi * p.rf) ** 2,
        ffcony=(2.0 / (ns * leny * leny)) * (np.pi * p.rf) ** 2,
        dqx=dqx, dqy=dqy,
        consp=cmb2 * dqx * dqy / (p.rf ** p.alpha),
        scnorm=1.0 / (p.nx * p.ny),
        s0=p.rf * cdrf ** (1.0 / p.alpha),
        sref=p.rf ** 2 / (p.rf * cdrf ** (1.0 / p.alpha)),
    )


def _aniso_coeffs(p: SimParams, xp=np):
    """The det-1 anisotropy quadratic form's (a, b, c): ``q2 = a kx^2 +
    b ky^2 + c kx ky`` in k-space (swdsp, scint_sim.py:235-241) — ONE
    derivation shared by the spectral amplitude, the lag-space inverse
    form and the pac compensator's mode spectrum, so an anisotropy-
    convention fix can never diverge them."""
    cs = xp.cos(p.psi * xp.pi / 180)
    sn = xp.sin(p.psi * xp.pi / 180)
    r = p.ar
    a = cs ** 2 / r + r * sn ** 2
    b = r * cs ** 2 + sn ** 2 / r
    c = 2 * cs * sn * (1 / r - r)
    return a, b, c


def _swdsp(p: SimParams, consp: float, kx, ky, xp=np):
    """Anisotropic power-law spectral amplitude with inner-scale cutoff
    (swdsp, scint_sim.py:229-245)."""
    con = xp.sqrt(consp)
    alf = -(p.alpha + 2) / 4
    a, b, c = _aniso_coeffs(p, xp=xp)
    q2 = a * kx ** 2 + b * ky ** 2 + c * kx * ky
    # q2=0 at DC -> inf weight; callers zero the DC bin explicitly (the
    # screen has no mean-phase term).  np.errstate only affects numpy
    # ufunc warnings, so it is a harmless no-op under jax tracing.
    with np.errstate(divide="ignore"):
        w = con * q2 ** alf
    return w * xp.exp(-(kx ** 2 + ky ** 2) * p.inner ** 2 / 2)


def _abs_freq_index(n: int, xp=np):
    """|fftfreq| * n: [0, 1, ..., n/2, n/2-1, ..., 1]."""
    i = xp.arange(n)
    return xp.minimum(i, n - i)


def _signed_freq_index(n: int, xp=np):
    i = xp.arange(n)
    return xp.where(i < n // 2 + 1, i, i - n)


def screen_weights(p: SimParams, xp=np) -> np.ndarray:
    """Full-grid spectral weights w[nx, ny] on the signed FFT-frequency
    grid, zero at DC — the intended form of get_screen's loop construction
    (scint_sim.py:153-173)."""
    c = derived_constants(p)
    kx = _signed_freq_index(p.nx, xp)[:, None] * c["dqx"]
    ky = _signed_freq_index(p.ny, xp)[None, :] * c["dqy"]
    w = _swdsp(p, c["consp"], kx, ky, xp=xp)
    if xp is np:
        w[0, 0] = 0.0
    else:
        w = w.at[0, 0].set(0.0)
    return w


def screen_weights_reference(p: SimParams) -> np.ndarray:
    """Weights built with the reference's exact index arithmetic
    (get_screen, scint_sim.py:153-173), vectorised but semantically
    identical — including its quirks: the DC element is never assigned, the
    ky=0 mirror line copies values shifted by one row (``w[nx+1-k,0]=w[k,0]``
    reads the *unshifted* row, zeroing the Nyquist row), and Nyquist lines
    take +k rather than signed frequencies.  Used by the seeded numpy path
    so outputs match the reference run with the same seed."""
    c = derived_constants(p)
    nx, ny = p.nx, p.ny
    nx2, ny2 = nx // 2 + 1, ny // 2 + 1
    dqx, dqy = c["dqx"], c["dqy"]
    sw = functools.partial(_swdsp, p, c["consp"], xp=np)

    w = np.zeros([nx, ny])
    k = np.arange(2, nx2 + 1)
    w[k - 1, 0] = sw((k - 1) * dqx, 0)
    w[nx + 1 - k, 0] = w[k, 0]
    ll = np.arange(2, ny2 + 1)
    w[0, ll - 1] = sw(0, (ll - 1) * dqy)
    w[0, ny + 1 - ll] = w[0, ll - 1]
    kp = np.arange(2, nx2 + 1)
    k = np.arange(nx2 + 1, nx + 1)
    km = -(nx - k + 1)
    for il in range(2, ny2 + 1):
        w[kp - 1, il - 1] = sw((kp - 1) * dqx, (il - 1) * dqy)
        w[k - 1, il - 1] = sw(km * dqx, (il - 1) * dqy)
        w[nx + 1 - kp, ny + 1 - il] = w[kp - 1, il - 1]
        w[nx + 1 - k, ny + 1 - il] = w[k - 1, il - 1]
    return w


def _aniso_lag(p: SimParams, x, y, xp=np):
    """Effective separation ``r'`` under the INVERSE of `_swdsp`'s
    spectral quadratic form (the det-1 anisotropy matrix: ``q2 = a kx^2
    + b ky^2 + c kx ky`` in k-space maps to ``r'^2 = b x^2 + a y^2 -
    c x y`` in lag space), so ``D(x, y) = (r'/s0)^alpha``."""
    a, b, cc = _aniso_coeffs(p, xp=xp)
    # positive definite (det 1); clamp float ripple at near-zero lags
    return xp.sqrt(xp.maximum(b * x ** 2 + a * y ** 2 - cc * x * y, 0.0))


def phase_structure_function(p: SimParams, x, y, xp=np):
    """Closed-form theoretical phase structure function ``D(x, y) =
    (r'/s0)^alpha`` of the anisotropic Kolmogorov spectrum `_swdsp`
    samples, with (x, y) in the same physical units as ``s0``
    (Fresnel-scale units times ``rf``)."""
    c = derived_constants(p)
    return (_aniso_lag(p, x, y, xp=xp) / c["s0"]) ** p.alpha


@functools.lru_cache(maxsize=None)
def pac_fit(p: SimParams) -> tuple[float, float]:
    """Fit the Gaussian phase-autocovariance compensator
    (arXiv:2208.06060): the ``(s2, w)`` of ``B_g(r) = s2 exp(-(r/w)^2)``
    whose structure-function contribution ``2 s2 (1 - exp(-(r/w)^2))``
    best repairs the FFT screen's low-frequency deficit.

    The deficit is computed EXACTLY, not modelled: the synthesis
    ``Re fft2(w z)`` realises covariance ``C(r) = sum_k w_k^2
    cos(2 pi k r / N) = N ifft2(w^2)``, so one FFT of the sampled
    weights gives the grid's actual ``D_fft = 2 (C(0) - C(r))``, and
    the residual against the closed-form Kolmogorov ``(r'/s0)^alpha``
    is what the Gaussian is least-squares fitted to (closed-form
    amplitude per candidate width, 1-D width search).  A Gaussian is
    the right shape because the missing sub-fundamental band
    contributes quadratically at small ``r`` — exactly a Gaussian
    covariance's small-lag behaviour."""
    wf2 = screen_weights(p) ** 2
    cov = np.real(np.fft.ifft2(wf2)) * (p.nx * p.ny)
    d_fft = 2.0 * (cov[0, 0] - cov)
    # wrap-periodic anisotropic lag grid, in the synthesis's own grid
    # units (x = i dx — the same units dq and the mode phases use, and
    # the units ``s0`` is normalised to through consp's rf^-alpha)
    lx = np.asarray(_abs_freq_index(p.nx)) * float(p.dx)
    ly = np.asarray(_abs_freq_index(p.ny)) * float(p.dy)
    r = _aniso_lag(p, lx[:, None], ly[None, :], xp=np)
    d_th = (r / derived_constants(p)["s0"]) ** p.alpha
    resid = np.maximum(d_th - d_fft, 0.0)
    extent = float(max(lx.max(), ly.max()))
    best = None
    for w in np.geomspace(extent / 16.0, 8.0 * extent, 49):
        m = 1.0 - np.exp(-((r / w) ** 2))
        mm = float(np.sum(m * m))
        if mm <= 0:
            continue
        s2 = max(float(np.sum(m * resid)) / (2.0 * mm), 0.0)
        err = float(np.sum((2.0 * s2 * m - resid) ** 2))
        if best is None or err < best[0]:
            best = (err, s2, w)
    return float(best[1]), float(best[2])


# sampling resolution of the compensator's sub-fundamental mode grid:
# (2*_PAC_M + 1)^2 - 1 explicit modes cover the Gaussian spectrum's
# support (or the sub-fundamental square, whichever is smaller)
_PAC_M = 8


@functools.lru_cache(maxsize=None)
def pac_modes(p: SimParams) -> tuple[np.ndarray, np.ndarray]:
    """Explicit low-k mode table realising the fitted Gaussian
    compensator (:func:`pac_fit`): wavenumbers [M, 2] and amplitude
    weights [M], consumed by the same separable-outer-product synthesis
    as :func:`subharmonic_modes`.

    The fitted compensator typically lives almost entirely BELOW the
    grid fundamental (that is the deficit being repaired), so it cannot
    ride the periodic FFT grid at all — like the subharmonic scheme, it
    must be added as explicit non-periodic modes.  The mode grid spans
    ``|k| <= min(dq, ~6 sigma_k)`` per axis (beyond ~6/w the Gaussian
    spectrum is dead; beyond dq the FFT grid already carries the power
    law), sampled at ``(2M+1)^2 - 1`` points with per-mode amplitude
    ``sqrt(S_g(k) dkx dky) / (2 pi)`` where ``S_g(k) = s2 pi w^2
    exp(-q2(k) w^2 / 4)`` is the (anisotropic) Gaussian's spectrum —
    the continuous-transform pair of ``B_g``."""
    s2, w = pac_fit(p)
    c = derived_constants(p)
    if s2 <= 0.0:
        return np.zeros((0, 2)), np.zeros((0,))
    # the aniso form q2 = a kx^2 + ... has eigenvalues in [1/ar, ar]:
    # the spectrum is dead beyond q2 w^2/4 ~ 9, i.e. |k| ~ 6 sqrt(ar)/w
    kdead = 6.0 * np.sqrt(max(p.ar, 1.0 / p.ar)) / w
    kx_max = min(c["dqx"], kdead)
    ky_max = min(c["dqy"], kdead)
    m = _PAC_M
    dkx, dky = kx_max / m, ky_max / m
    a, b, cc = _aniso_coeffs(p)
    ii = np.arange(-m, m + 1)
    kx = (ii * dkx)[:, None] + np.zeros((1, 2 * m + 1))
    ky = (ii * dky)[None, :] + np.zeros((2 * m + 1, 1))
    q2 = a * kx ** 2 + b * ky ** 2 + cc * kx * ky
    sg = s2 * np.pi * w ** 2 * np.exp(-q2 * w ** 2 / 4.0)
    amp = np.sqrt(sg * dkx * dky) / (2.0 * np.pi)
    keep = ~((kx == 0.0) & (ky == 0.0))   # no mean-phase mode
    ks = np.stack([kx[keep], ky[keep]], axis=-1)
    return ks, amp[keep]


def fresnel_filter(p: SimParams, scale, xp=np):
    """exp(-i q^2(scale)) on the full FFT grid (frfilt3 closed form)."""
    c = derived_constants(p)
    q2x = _abs_freq_index(p.nx, xp)[:, None] ** 2 * (c["ffconx"] * scale)
    q2y = _abs_freq_index(p.ny, xp)[None, :] ** 2 * (c["ffcony"] * scale)
    q2 = q2x + q2y
    return xp.cos(q2) - 1j * xp.sin(q2)


def frequency_scales(p: SimParams, xp=np):
    """Per-channel phase scaling factors (scint_sim.py:192-198):
    lambda steps scale the phase linearly; frequency steps by 1/f."""
    ifreq = xp.arange(p.nf)
    if p.lamsteps:
        return 1.0 + p.dlam * (ifreq - 1 - (p.nf / 2)) / p.nf
    return 1.0 / (1.0 + p.dlam * (-0.5 + ifreq / p.nf))


# ---------------------------------------------------------------------------
# numpy reference-compatible class
# ---------------------------------------------------------------------------


class Simulation:
    """Reference-compatible simulator (scint_sim.py:20).

    Runs the full pipeline in the constructor and exposes the attributes the
    adapters consume: ``xyp`` (screen phase), ``spe`` (E-field [nx, nf]),
    ``spi`` (intensity), ``dyn`` dyn-like fields via
    :func:`scintools_tpu.io.from_simulation`.
    """

    def __init__(self, mb2=2, rf=1, ds=0.01, alpha=5 / 3, ar=1, psi=0,
                 inner=0.001, ns=256, nf=256, dlam=0.25, lamsteps=False,
                 seed=None, nx=None, ny=None, dx=None, dy=None,
                 verbose=False, backend: str = "numpy",
                 subharmonics: int = 0, pac: bool = False):
        if (subharmonics or pac) and backend != "jax":
            raise ValueError(
                "low-k compensation (subharmonics / pac) is implemented on "
                "the jax screen path only (the numpy path stays "
                "reference-exact); pass backend='jax'")
        self.params = SimParams(
            mb2=mb2, rf=rf, dx=dx if dx is not None else ds,
            dy=dy if dy is not None else ds, alpha=alpha, ar=ar, psi=psi,
            inner=inner, nx=nx if nx is not None else ns,
            ny=ny if ny is not None else ns, nf=nf, dlam=dlam,
            lamsteps=lamsteps, subharmonics=int(subharmonics),
            pac=bool(pac))
        # reference-compatible attribute aliases
        p = self.params
        self.mb2, self.rf, self.alpha, self.ar, self.psi = \
            p.mb2, p.rf, p.alpha, p.ar, p.psi
        self.inner, self.nx, self.ny, self.nf, self.dlam = \
            p.inner, p.nx, p.ny, p.nf, p.dlam
        self.dx, self.dy, self.lamsteps, self.seed = p.dx, p.dy, p.lamsteps, seed
        for k, v in derived_constants(p).items():
            setattr(self, k, v)

        # progress goes through the structured channel (the reference
        # prints from its compute loop, scint_sim.py:62-69): one event
        # per simulation, INFO when verbose= asks for it, DEBUG otherwise
        import logging
        import time as _time

        from .. import obs
        from ..utils.log import get_logger, log_event

        t0 = _time.perf_counter()
        with obs.span("sim.simulation", backend=backend, nx=p.nx,
                      ny=p.ny, nf=p.nf):
            if backend == "jax":
                import jax

                key = jax.random.PRNGKey(0 if seed is None else seed)
                spe, xyp = simulate(key, p, return_screen=True)
                self.xyp = np.asarray(xyp)
                self.spe = np.asarray(spe)
                # last-frequency full intensity field, kept
                # attribute-compatible with the numpy path (reference
                # sets it in get_intensity)
                self.xyi = np.abs(self.spe[:, -1:]) ** 2
            else:
                self.xyp = self._screen_numpy(seed)
                self.spe = self._intensity_numpy()
            self.spi = np.real(self.spe * np.conj(self.spe))
        obs.inc("screens_simulated")
        log_event(get_logger(), "sim",
                  level=logging.INFO if verbose else logging.DEBUG,
                  backend=backend, nx=p.nx, ny=p.ny, nf=p.nf, mb2=p.mb2,
                  seed=seed, dur_ms=(_time.perf_counter() - t0) * 1e3)

    def _screen_numpy(self, seed) -> np.ndarray:
        """Seeded screen: weights on the signed-frequency grid times a
        complex gaussian field, real part of fft2 (scint_sim.py:144-181).
        RNG call order matches the reference exactly."""
        p = self.params
        np.random.seed(seed)
        w = screen_weights_reference(p)
        z = np.random.randn(p.nx, p.ny) + 1j * np.random.randn(p.nx, p.ny)
        return np.real(fft2(w * z))

    def _intensity_numpy(self) -> np.ndarray:
        """Per-frequency Fresnel propagation, centre-row cut
        (get_intensity, scint_sim.py:183-210)."""
        p = self.params
        spe = np.zeros([p.nx, p.nf], dtype=np.complex64)
        scales = frequency_scales(p, xp=np)
        for ifreq in range(p.nf):
            scale = scales[ifreq]
            xye = fft2(np.exp(1j * self.xyp * scale))
            # the reference stores the filter as complex64 (frfilt3,
            # scint_sim.py:250); cast to match its rounding
            xye = xye * fresnel_filter(p, scale, xp=np).astype(np.complex64)
            xye = ifft2(xye)
            spe[:, ifreq] = xye[:, p.ny // 2]
        self.xyi = np.real(xye * np.conj(xye))  # last-frequency intensity
        return spe


# ---------------------------------------------------------------------------
# jax functional path
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def subharmonic_modes(p: SimParams) -> tuple[np.ndarray, np.ndarray]:
    """Host-side mode table for low-k screen compensation: wavenumbers
    [M, 2] and amplitude weights [M] for ``p.subharmonics`` octaves of the
    3x3 subharmonic scheme.  Weight = swdsp(k)/3^o: the amplitude carries
    sqrt(cell area), and each octave's cells are (dq/3^o)^2."""
    c = derived_constants(p)
    ks, ws = [], []
    for o in range(1, p.subharmonics + 1):
        f = 3.0 ** -o
        for pp in (-1, 0, 1):
            for qq in (-1, 0, 1):
                if pp == qq == 0:
                    continue
                kx, ky = pp * c["dqx"] * f, qq * c["dqy"] * f
                ks.append((kx, ky))
                ws.append(float(_swdsp(p, c["consp"], kx, ky, xp=np)) * f)
    return (np.asarray(ks, dtype=np.float64),  # host-f64: host mode table
            np.asarray(ws, dtype=np.float64))  # host-f64: host mode table


@functools.lru_cache(maxsize=None)
def _simulate_jax(p: SimParams, return_screen: bool, freq_chunk: int | None):
    import jax
    import jax.numpy as jnp

    if p.pac and p.subharmonics:
        raise ValueError(
            "SimParams.pac and SimParams.subharmonics are two low-k "
            "compensation schemes for the same deficit; enable one")
    # Closure constants stay numpy: jnp constants created here would be tied
    # to whatever trace first builds this (cached) closure and leak.
    w = screen_weights(p, xp=np)
    scales = np.asarray(frequency_scales(p, xp=np))
    filt_consts = derived_constants(p)
    qx2 = np.asarray(_abs_freq_index(p.nx)) ** 2 * filt_consts["ffconx"]
    qy2 = np.asarray(_abs_freq_index(p.ny)) ** 2 * filt_consts["ffcony"]
    # low-k compensation: both schemes yield an explicit mode table
    # consumed by the same separable-outer-product synthesis below
    modes = None
    if p.subharmonics:
        modes = subharmonic_modes(p)
    elif p.pac:
        modes = pac_modes(p)
    if modes is not None and modes[1].size:
        sub_k, sub_w = modes
        # mode phase on the spatial grid (x = i*dx): [M, nx], [M, ny]
        sub_px = sub_k[:, 0:1] * (np.arange(p.nx) * p.dx)[None, :]
        sub_py = sub_k[:, 1:2] * (np.arange(p.ny) * p.dy)[None, :]
    else:
        modes = None

    def one_freq(xyp, scale):
        q2 = (qx2[:, None] + qy2[None, :]) * scale
        filt = jnp.exp(-1j * q2)
        xye = jnp.fft.ifft2(jnp.fft.fft2(jnp.exp(1j * xyp * scale)) * filt)
        return xye[:, p.ny // 2]

    @jax.jit
    def impl(key):
        kr, ki = jax.random.split(key)
        z = (jax.random.normal(kr, (p.nx, p.ny))
             + 1j * jax.random.normal(ki, (p.nx, p.ny)))
        xyp = jnp.real(jnp.fft.fft2(w * z))
        if modes is not None:
            ks1, ks2 = jax.random.split(jax.random.fold_in(key, 7))
            M = sub_w.shape[0]
            gr = jax.random.normal(ks1, (M,))
            gi = jax.random.normal(ks2, (M,))
            # Re[w g e^{i(kx x + ky y)}] summed over modes, as separable
            # outer products (cheap: M ~ 8*octaves modes)
            cx, sx = jnp.cos(sub_px), jnp.sin(sub_px)  # [M, nx]
            cy, sy = jnp.cos(sub_py), jnp.sin(sub_py)  # [M, ny]
            wgr = sub_w * gr
            wgi = sub_w * gi
            xyp = xyp + (
                jnp.einsum("m,mx,my->xy", wgr, cx, cy)
                - jnp.einsum("m,mx,my->xy", wgr, sx, sy)
                - jnp.einsum("m,mx,my->xy", wgi, sx, cy)
                - jnp.einsum("m,mx,my->xy", wgi, cx, sy))
        if freq_chunk is None or freq_chunk >= p.nf:
            spe = jax.vmap(one_freq, in_axes=(None, 0), out_axes=1)(
                xyp, scales)
        else:
            # chunked over frequency to bound the [chunk, nx, ny] FFT
            # workspace in HBM; nf must divide evenly or pad
            nchunks = -(-p.nf // freq_chunk)
            pad = nchunks * freq_chunk - p.nf
            sc = jnp.pad(scales, (0, pad)).reshape(nchunks, freq_chunk)
            spe = jax.lax.map(
                lambda s: jax.vmap(one_freq, in_axes=(None, 0), out_axes=1)(
                    xyp, s), sc)  # [nchunks, nx, freq_chunk]
            spe = jnp.moveaxis(spe, 0, 1).reshape(p.nx, -1)[:, :p.nf]
        return (spe, xyp) if return_screen else spe

    return impl


def simulate(key, params: SimParams, return_screen: bool = False,
             freq_chunk: int | None = None):
    """jit'd simulation: PRNGKey -> complex E-field ``spe`` [nx, nf]
    (optionally also the screen phase).  vmap over ``key`` for ensembles."""
    return _simulate_jax(params, return_screen, freq_chunk)(key)


def simulate_intensity(key, params: SimParams,
                       freq_chunk: int | None = None):
    """PRNGKey -> intensity dynamic spectrum ``spi`` [nx(time), nf]."""
    import jax.numpy as jnp

    spe = simulate(key, params, freq_chunk=freq_chunk)
    return jnp.real(spe) ** 2 + jnp.imag(spe) ** 2


@functools.lru_cache(maxsize=None)
def _ensemble_jax(p: SimParams, screen_chunk: int):
    import jax

    @jax.jit
    def impl(keys):
        def chunk_fn(kc):
            return jax.vmap(lambda k: simulate_intensity(k, p))(kc)

        n = keys.shape[0]
        nchunks = n // screen_chunk
        kc = keys[: nchunks * screen_chunk].reshape(
            nchunks, screen_chunk, *keys.shape[1:])
        out = jax.lax.map(chunk_fn, kc)
        return out.reshape(nchunks * screen_chunk, p.nx, p.nf)

    return impl


# float physics fields that may be TRACED (swept) without retracing: all
# enter the weights/filters as plain arithmetic.  alpha is excluded (it
# feeds scipy gamma at trace-build time), ints/bools shape the program.
_SWEEPABLE = ("mb2", "rf", "dx", "dy", "ar", "psi", "inner", "dlam")


def _pad_cycle(arr, multiple: int):
    """Pad the leading axis up to the next ``multiple`` by cycling the
    existing rows (pad rows are computed and discarded by callers).
    Works for any pad size, even pad > n."""
    import jax.numpy as jnp

    n = arr.shape[0]
    pad = (-n) % multiple
    if not pad:
        return arr
    reps = int(np.ceil(pad / n))
    filler = jnp.concatenate([arr] * reps, axis=0)[:pad]
    return jnp.concatenate([arr, filler], axis=0)


def _sweep_screen_intensity(p: SimParams, fields: tuple):
    """Single-screen intensity with the named float fields TRACED:
    ``one(key, vals[F]) -> spi [nx, nf]``.  The building block shared by
    :func:`simulate_sweep` and the on-device synthetic route's swept
    generator (sim/campaign.py) — one compiled program covers a whole
    physics grid."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    def one(key, vals):
        # the replaced instance holds TRACERS in its float fields; it is
        # a data carrier only (never hashed / used as a jit static arg)
        q = _dc.replace(p, **dict(zip(fields, vals)))
        w = screen_weights(q, xp=jnp)
        scales = frequency_scales(q, xp=jnp)

        kr, ki = jax.random.split(key)
        z = (jax.random.normal(kr, (p.nx, p.ny))
             + 1j * jax.random.normal(ki, (p.nx, p.ny)))
        xyp = jnp.real(jnp.fft.fft2(w * z))

        def one_freq(scale):
            # the SAME closed-form filter the static path folds as a
            # constant (fresnel_filter), here traced through q
            filt = fresnel_filter(q, scale, xp=jnp)
            xye = jnp.fft.ifft2(jnp.fft.fft2(jnp.exp(1j * xyp * scale))
                                * filt)
            return xye[:, p.ny // 2]

        spe = jax.vmap(one_freq, out_axes=1)(scales)
        return jnp.real(spe) ** 2 + jnp.imag(spe) ** 2

    return one


@functools.lru_cache(maxsize=None)
def _simulate_sweep_jax(p: SimParams, fields: tuple, point_chunk: int):
    import jax

    one = _sweep_screen_intensity(p, fields)

    @jax.jit
    def impl(keys, vals):
        kc = keys.reshape(-1, point_chunk, *keys.shape[1:])
        vc = vals.reshape(-1, point_chunk, vals.shape[-1])
        out = jax.lax.map(lambda kv: jax.vmap(one)(kv[0], kv[1]),
                          (kc, vc))
        return out.reshape(-1, p.nx, p.nf)

    return impl


def simulate_sweep(keys, params: SimParams, sweep: dict,
                   point_chunk: int = 4):
    """Parameter-grid Monte Carlo: simulate B screens whose PHYSICS
    parameters vary per point, in ONE compiled program.

    ``sweep`` maps float field names (any of mb2/rf/dx/dy/ar/psi/inner/
    dlam) to [B] arrays (scalars broadcast); ``keys`` is [B] PRNGKeys,
    one per point.  The swept fields are traced, not static, so a
    100-point (mb2, ar) grid costs one compile — the building block for
    simulation-based inference over screen parameters.  Other fields
    come from ``params`` (alpha/shape fields stay static; subharmonics
    is unsupported here because its mode table is built host-side).

    Returns intensities [B, nx, nf].
    """
    import jax.numpy as jnp

    if params.subharmonics or params.pac:
        raise ValueError("simulate_sweep does not support subharmonics/"
                         "pac (host-side mode table / covariance FFT); "
                         "use simulate_ensemble per parameter point "
                         "instead")
    fields = tuple(sorted(sweep))
    if not fields:
        raise ValueError("sweep must name at least one field")
    for f in fields:
        if f not in _SWEEPABLE:
            raise ValueError(f"cannot sweep {f!r}; sweepable float "
                             f"fields are {_SWEEPABLE}")
    n = keys.shape[0]
    vals = np.stack([np.broadcast_to(
        np.asarray(sweep[f], dtype=np.float64), (n,))  # host-f64: host staging (canonicalised on transfer)
        for f in fields], axis=-1)
    keys = _pad_cycle(keys, point_chunk)
    vals = _pad_cycle(jnp.asarray(vals), point_chunk)
    # canonicalise the cached trace key: the swept fields' base values
    # are overwritten by tracers immediately, so they must not fork the
    # compile cache (SBI loops often rebuild SimParams per call)
    import dataclasses as _dc

    params_c = _dc.replace(params, **{f: 0.0 for f in fields})
    out = _simulate_sweep_jax(params_c, fields, int(point_chunk))(
        keys, vals)
    return out[:n]


def simulate_ensemble(keys, params: SimParams, screen_chunk: int = 8):
    """Monte-Carlo ensemble: [B] PRNGKeys -> [B, nx, nf] intensities,
    lax.map'd in chunks of vmapped screens (BASELINE config 5: 10k
    screens).  Any B: keys are padded to the chunk multiple internally
    (pad screens are simulated and discarded)."""
    n = keys.shape[0]
    keys = _pad_cycle(keys, screen_chunk)
    out = _ensemble_jax(params, screen_chunk)(keys)
    return out[:n]
