from .campaign import SynthSpec  # noqa: F401
from .simulation import (SimParams, Simulation, derived_constants,  # noqa: F401
                         fresnel_filter, frequency_scales, pac_fit,
                         pac_modes, phase_structure_function,
                         screen_weights, screen_weights_reference,
                         simulate, simulate_ensemble, simulate_intensity,
                         simulate_sweep)
from .synth import thin_arc_epoch  # noqa: F401
