"""The compiled acceleration-search programs: ONE fused jit per
(generator, grid, bank size, rung).

Both programs run the whole chain on device — ``uint32 key rows ->
generator -> cropped secondary spectrum (db off, R delay rows straight
off the PR 7 crop-split row DFT) -> per-row z-score -> Doppler-axis
rFFT -> frequency-domain multiply-accumulate against the resident bank
-> correlation scores`` — wrapped in ``obs.instrument_jit`` so warm
reruns are counter-auditable (``jit_cache_miss == 0``) and the
measured ``step_bytes``/``step_flops`` gauges carry each program's XLA
cost analysis (the pruned-vs-naive byte split the perf gate asserts).

* the PRUNED program (``search.step``) scores the FULL bank on a
  decimated coarse grid (the first ``F/decim`` Fourier bins of the
  correlation — a smoothed, short-lag pass), gathers only the top-K
  trial neighbourhoods and re-scores those at full resolution.  K and
  the decimation ride as TRACED runtime inputs within the compiled
  ``top_k``/``decim`` envelope: tuning recall/cost never recompiles.
* the NAIVE program (``search.naive``) scores every template at full
  resolution — the exhaustive reference the A/B lane and the recall
  tests compare against (it shares the epoch prologue by
  construction, so the split it measures is pure scoring traffic).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import obs
from ..ops.sspec import fft_lens
from ..sim import campaign
from .bank import SearchSpec, bank_delay_rows, bank_resident

__all__ = ["search_grid", "search_program", "search_step_fn",
           "program_dims"]

# program memo: one compiled step per (generator identity, analysis
# fingerprint, bank statics, batch rung, pruned|naive) — the search
# plane's analogue of the infer plane's _PROGRAMS memo
_PROGRAMS: dict = {}


def _cfg_fingerprint(config) -> tuple:
    """The analysis-config fields the search program's trace consumes —
    its share of the program identity (everything else is inert).  The
    spectrum runs db-OFF (linear power: the correlation normalises per
    delay row, and log of zero-power pad bins would poison it) on the
    default jax sspec chain."""
    return ("search", bool(config.prewhite), config.window,
            float(config.window_frac), config.fft_lens)


def search_grid(spec) -> tuple[int, int, float, float]:
    """(nf, nt, dt, df) of the campaign's epochs — the grid the bank
    and the correlation programs are built over (synth_meta's own
    spacing derivations, so bank axes match the served rows' metadata)."""
    nf, nt = campaign.synth_shape(spec)
    freqs, times = campaign.synth_axes(spec)
    return nf, nt, float(times[1] - times[0]), float(freqs[1] - freqs[0])


def program_dims(spec, config, srch: SearchSpec) -> dict:
    """The static correlation dimensions shared by bank residency, both
    programs and the runtime-knob validation: R delay rows, C Doppler
    columns, correlation length L, F (full) and Fc (coarse) Fourier
    bins, Lc coarse lag grid."""
    nf, nt, dt, df = search_grid(spec)
    R = bank_delay_rows(nf, nt, config.fft_lens, srch)
    _nrfft, C = fft_lens(nf, nt, config.fft_lens)
    from ..ops.sspec import next_fast_len

    L = next_fast_len(C)
    F = L // 2 + 1
    Fc = F // int(srch.decim)
    if Fc < 2:
        raise ValueError(
            f"decim={srch.decim} leaves {Fc} coarse Fourier bins (< 2) "
            f"at this grid (F={F}); lower decim or enlarge the grid")
    return {"nf": nf, "nt": nt, "dt": dt, "df": df, "R": R, "C": C,
            "L": L, "F": F, "Fc": Fc, "Lc": max(2 * (Fc - 1), 2)}


def search_step_fn(spec, config, srch: SearchSpec, naive: bool = False):
    """The raw (un-jitted) step callable — shared by
    :func:`search_program` and the warmup plane, which lowers it
    against ShapeDtypeStructs to land the persistent-cache entry
    without executing a campaign.

    Pruned signature: ``step(raw, bank_hat, top_k_rt, decim_rt)``;
    naive: ``step(raw, bank_hat)``.  Both return dicts of
    ``[B]``-leading arrays: winning ``trial`` index into the bank's
    eta grid, its full-resolution ``score`` (matched-filter peak),
    ``snr`` ((peak - mean)/std over correlation lags), ``coarse``
    score and peak ``shift`` (Doppler lag bin)."""
    import jax
    import jax.numpy as jnp

    from ..ops.sspec import sspec as sspec_op

    gid = campaign.generator_id(spec)
    gen = campaign.synth_generator(gid)
    dims = program_dims(spec, config, srch)
    R, L, F, Fc, Lc = (dims["R"], dims["L"], dims["F"], dims["Fc"],
                       dims["Lc"])
    K = int(srch.top_k)

    def _epoch_spectra(raw):
        """keys -> z-scored cropped spectra -> Doppler rFFT [B, R, F]."""
        dyn = gen(raw).astype(jnp.float32)
        # linear power, R rows straight off the crop-split row DFT: the
        # elementwise tail and everything downstream touch only the
        # delay window the bank scores
        sec = sspec_op(dyn, prewhite=config.prewhite,
                       window=config.window,
                       window_frac=config.window_frac, db=False,
                       backend="jax", lens=config.fft_lens, crop_rows=R)
        # per-delay-row z-score: whitens the steep delay falloff (and
        # the postdark-boosted low rows) so every row contributes at
        # comparable scale — the bank is normalised the same way
        mu = jnp.mean(sec, axis=-1, keepdims=True)
        sd = jnp.std(sec, axis=-1, keepdims=True)
        sec = (sec - mu) / (sd + 1e-6)
        return jnp.fft.rfft(sec, n=L, axis=-1)

    def _lag_stats(corr):
        """(peak, snr, argmax lag) over the trailing lag axis."""
        peak = jnp.max(corr, axis=-1)
        mean = jnp.mean(corr, axis=-1)
        sd = jnp.std(corr, axis=-1)
        return peak, (peak - mean) / (sd + 1e-6), \
            jnp.argmax(corr, axis=-1).astype(jnp.int32)

    if naive:
        def step(raw, bank_hat):
            S = _epoch_spectra(raw)
            # exhaustive full-resolution frequency-domain MAC: every
            # template, every Fourier bin — the traffic ceiling the
            # pruned program's cost analysis is measured against
            corr = jnp.fft.irfft(
                jnp.einsum("brf,jrf->bjf", S, bank_hat), n=L, axis=-1)
            score, snr, lag = _lag_stats(corr)          # [B, J] each
            best = jnp.argmax(score, axis=-1)           # [B]

            def _take(a):
                return jnp.take_along_axis(a, best[:, None],
                                           axis=1)[:, 0]
            return {"trial": best.astype(jnp.int32),
                    "score": _take(score), "snr": _take(snr),
                    "coarse": _take(score), "shift": _take(lag)}
        return step

    def step(raw, bank_hat, top_k_rt, decim_rt):
        S = _epoch_spectra(raw)
        # coarse pass: the full bank on the first Fc Fourier bins — a
        # decimated (smoothed) correlation whose lag grid is Lc long.
        # decim_rt >= decim zeroes bins beyond F/decim_rt at runtime:
        # a coarser budget without recompiling
        keep = (jnp.arange(Fc, dtype=jnp.uint32)
                < (jnp.uint32(F) // decim_rt))
        coarse_corr = jnp.fft.irfft(
            jnp.einsum("brf,jrf->bjf", S[..., :Fc], bank_hat[..., :Fc])
            * keep.astype(bank_hat.dtype), n=Lc, axis=-1)
        coarse = jnp.max(coarse_corr, axis=-1)          # [B, J]
        cvals, idx = jax.lax.top_k(coarse, K)           # [B, K]
        # fine pass: only the K surviving trial neighbourhoods at full
        # resolution (the gathered bank slice is K/J of the bank)
        fine_corr = jnp.fft.irfft(
            jnp.einsum("brf,bkrf->bkf", S, bank_hat[idx]), n=L, axis=-1)
        score, snr, lag = _lag_stats(fine_corr)         # [B, K] each
        # top_k_rt <= top_k masks the unfunded fine lanes out of the
        # verdict (runtime recall/cost knob, same program)
        lane_ok = jnp.arange(K, dtype=jnp.uint32) < top_k_rt
        masked = jnp.where(lane_ok[None, :], score, -jnp.inf)
        best = jnp.argmax(masked, axis=-1)              # [B]

        def _take(a):
            return jnp.take_along_axis(a, best[:, None], axis=1)[:, 0]
        return {"trial": _take(idx).astype(jnp.int32),
                "score": _take(score), "snr": _take(snr),
                "coarse": _take(cvals), "shift": _take(lag)}
    return step


def search_program(spec, config, srch: SearchSpec, rung: int,
                   naive: bool = False):
    """Memoised instrumented jit of :func:`search_step_fn` — ONE
    compiled signature per (generator identity, analysis fingerprint,
    bank statics, batch rung, pruned|naive), riding the bucket-ladder
    catalog exactly like the simulate/infer steps."""
    import jax

    gid = campaign.generator_id(spec)
    key = (gid, int(rung), _cfg_fingerprint(config),
           dataclasses.astuple(srch), bool(naive))
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog
    step = search_step_fn(spec, config, srch, naive=naive)
    name = "search.naive" if naive else "search.step"
    prog = obs.instrument_jit(jax.jit(step), name)
    _PROGRAMS[key] = prog
    return prog
