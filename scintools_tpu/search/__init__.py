"""Fourier-domain acceleration-search plane (ISSUE 19).

Batched matched-filter scoring of synthetic campaigns against an
HBM-resident bank of curvature-trial templates: the GPU FDAS
correlation shape (arXiv:1804.05335 / arXiv:1711.10855 — resident
bank + frequency-domain multiply-accumulate) ported onto the bucket
ladder, the PR 7 crop-split row DFT and the serve identity stack.
Coarse-to-fine pruning (decimated full-bank pass, top-K re-scored at
full resolution) keeps the scored traffic a small fraction of the
exhaustive reference; K and the decimation are runtime inputs, so
re-budgeting recall/cost never recompiles.  Served as the ``search``
job kind (``JobQueue.submit_search`` / ``scint-tpu submit QDIR
--search``) and runnable directly (``scint-tpu process --synthetic N
--search``).

See docs/search.md for bank geometry, the recall/cost trade-off and
measured throughput.
"""

from .bank import (SearchSpec, bank_delay_rows, bank_resident,
                   build_bank, trial_etas, validate_search)
from .engine import program_dims, search_grid, search_program, \
    search_step_fn
from .runner import (search_campaign, search_from_dict, search_rows,
                     search_to_dict, validate_search_config,
                     warm_search)

__all__ = [
    "SearchSpec", "validate_search", "bank_delay_rows", "trial_etas",
    "build_bank", "bank_resident",
    "search_grid", "program_dims", "search_step_fn", "search_program",
    "search_campaign", "search_rows", "search_to_dict",
    "search_from_dict", "validate_search_config", "warm_search",
]
