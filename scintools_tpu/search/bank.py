"""Template banks for the Fourier-domain acceleration search.

One bank = one grid of curvature trials rendered as drifting-feature
kernels over the secondary spectrum's (tdel, fdop) plane — the
matched-filter analogue of the FDAS template banks GPU pulsar pipelines
keep resident next to their FFT stage (arXiv:1804.05335; the bank
layout + residency discipline is the dominant lever of its optimised
successor, arXiv:1711.10855).  Three contracts:

* **determinism** — templates are a closed-form function of the grid
  and the :class:`SearchSpec` bank geometry (no RNG): two processes
  building the same (grid, spec) produce bit-identical banks, so bank
  identity can ride content keys and compile-cache keys;
* **residency** — :func:`bank_resident` memoises the bank's rFFT
  device-side per (grid, bank geometry): ONE host build + ONE H2D per
  process, shared by every epoch batch and every rung of the same
  search (the ``bank_bytes`` gauge reports the resident footprint);
* **dtype discipline** — the resident bank is complex64 from float32
  templates (the compiled correlation is an f32 machine; host-side
  grid math runs in default numpy precision like every axis builder).

Trial curvatures are geometric between ``eta_min``/``eta_max`` in the
secondary spectrum's native units (us / mHz^2 — ``ops.sspec.sspec_axes``
conventions).  ``eta_min = eta_max = 0`` selects the AUTO range derived
from the grid itself: from the corner curvature (an arc that just
reaches the top usable delay row at the Doppler edge) up to the arc
that sits four Doppler pixels from center at the top row (the steepest
trial the grid resolves).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import obs
from ..ops.sspec import fft_lens, next_fast_len, sspec_axes

__all__ = ["SearchSpec", "validate_search", "bank_delay_rows",
           "trial_etas", "build_bank", "bank_resident"]


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """Bank geometry + pruning knobs of one acceleration search.

    All fields are PROGRAM statics.  ``top_k`` and ``decim`` are only
    the compiled envelope: the EXECUTED fine-lane count and coarse
    decimation are runtime inputs (``top_k_rt <= top_k``,
    ``decim_rt >= decim`` — see :func:`~scintools_tpu.search.runner.
    search_campaign`), so re-budgeting recall/cost never recompiles —
    the ``opt_steps``/``opt_steps_rt`` ceiling discipline of the infer
    plane applied to pruning."""

    n_trials: int = 256     # J: curvature trials in the bank
    eta_min: float = 0.0    # trial range, us/mHz^2 (0 = auto from grid)
    eta_max: float = 0.0    # trial range, us/mHz^2 (0 = auto from grid)
    width: float = 1.0      # ridge Gaussian sigma, Doppler pixels
    delay_rows: int = 0     # R delay rows scored (0 = auto: nrfft/4)
    min_row: int = 1        # zero template rows below this (DC delay)
    top_k: int = 16         # compiled fine-lane ceiling per epoch
    decim: int = 8          # compiled coarse decimation (Fourier bins)


def validate_search(srch: SearchSpec) -> None:
    """Loud validation at submit/build time (the serve contract: a bad
    payload must fail before it burns a retry budget)."""
    if not 2 <= int(srch.n_trials) <= 65536:
        raise ValueError(f"n_trials must be in [2, 65536], got "
                         f"{srch.n_trials}")
    if (float(srch.eta_min) > 0) != (float(srch.eta_max) > 0):
        raise ValueError(
            "eta_min/eta_max: set both (an explicit trial range) or "
            "neither (0/0 = the auto range derived from the grid)")
    if srch.eta_min < 0 or srch.eta_max < 0:
        raise ValueError("eta_min/eta_max must be >= 0")
    if srch.eta_min > 0 and not srch.eta_max > srch.eta_min:
        raise ValueError(f"eta_max must exceed eta_min, got "
                         f"[{srch.eta_min}, {srch.eta_max}]")
    if not srch.width > 0:
        raise ValueError(f"width must be > 0, got {srch.width}")
    if srch.delay_rows < 0:
        raise ValueError(f"delay_rows must be >= 0 (0 = auto), got "
                         f"{srch.delay_rows}")
    if srch.min_row < 0:
        raise ValueError(f"min_row must be >= 0, got {srch.min_row}")
    if not 1 <= int(srch.top_k) <= int(srch.n_trials):
        raise ValueError(f"top_k must be in [1, n_trials="
                         f"{srch.n_trials}], got {srch.top_k}")
    if int(srch.decim) < 1:
        raise ValueError(f"decim must be >= 1, got {srch.decim}")


def bank_delay_rows(nf: int, nt: int, lens: str, srch: SearchSpec) -> int:
    """R — the delay rows the search scores.  Defaults to ``nrfft/4``
    (the crop-split discipline: arcs of interest live in the lower
    delay quarter, and the PR 7 cropped row DFT then materialises only
    those rows), capped by the spectrum's ``nrfft/2`` physical rows."""
    nrfft, _ncfft = fft_lens(nf, nt, lens)
    rows = int(srch.delay_rows) or nrfft // 4
    if rows > nrfft // 2:
        raise ValueError(
            f"delay_rows={rows} exceeds the spectrum's {nrfft // 2} "
            f"positive-delay rows at this grid (nrfft={nrfft})")
    if srch.min_row >= rows:
        raise ValueError(f"min_row={srch.min_row} leaves no usable "
                         f"delay rows (delay_rows={rows})")
    return rows


def trial_etas(nf: int, nt: int, dt: float, df: float, lens: str,
               srch: SearchSpec) -> np.ndarray:
    """The bank's curvature trials: geometric spacing over
    [eta_min, eta_max] in us/mHz^2, with the 0/0 AUTO range spanning
    the grid's corner curvature up to the steepest arc the Doppler
    resolution separates from the axis (four pixels at the top row)."""
    rows = bank_delay_rows(nf, nt, lens, srch)
    fdop, tdel, _beta = sspec_axes(nf, nt, dt, df, lens=lens)
    lo, hi = float(srch.eta_min), float(srch.eta_max)
    if lo == 0.0:
        fd_max = abs(float(fdop[0]))          # Doppler half-span, mHz
        dfd = float(fdop[1] - fdop[0])        # Doppler pixel, mHz
        tdel_top = float(tdel[rows - 1])      # top scored delay, us
        lo = tdel_top / fd_max ** 2
        hi = tdel_top / (4.0 * dfd) ** 2
        if not hi > lo:
            raise ValueError(
                f"grid too small for an auto trial range (ncfft="
                f"{len(fdop)} Doppler bins); set eta_min/eta_max")
    return np.geomspace(lo, hi, int(srch.n_trials))


def build_bank(nf: int, nt: int, dt: float, df: float, lens: str,
               srch: SearchSpec) -> tuple[np.ndarray, np.ndarray]:
    """(etas [J], templates [J, R, ncfft] float32) — the deterministic
    host-side bank build.

    Template j is a pair of Gaussian ridges (sigma = ``width`` Doppler
    pixels) along both branches of the arc ``fdop = +-sqrt(tdel /
    eta_j)`` over the scored delay rows, rows below ``min_row`` zeroed
    (the DC delay row carries the core's self-power, not the arc),
    then zero-meaned and L2-normalised so matched-filter scores are
    comparable across trials of very different support."""
    rows = bank_delay_rows(nf, nt, lens, srch)
    etas = trial_etas(nf, nt, dt, df, lens, srch)
    fdop, tdel, _beta = sspec_axes(nf, nt, dt, df, lens=lens)
    sigma = float(srch.width) * float(fdop[1] - fdop[0])
    td = np.asarray(tdel[:rows])
    # ridge centers per (trial, row): [J, R]
    fd_arc = np.sqrt(td[None, :] / etas[:, None])
    z = (np.asarray(fdop)[None, None, :] - fd_arc[:, :, None]) / sigma
    zm = (np.asarray(fdop)[None, None, :] + fd_arc[:, :, None]) / sigma
    bank = np.exp(-0.5 * z ** 2) + np.exp(-0.5 * zm ** 2)
    bank[:, :srch.min_row, :] = 0.0
    bank -= bank.mean(axis=(1, 2), keepdims=True)
    norm = np.sqrt((bank ** 2).sum(axis=(1, 2), keepdims=True))
    bank /= np.maximum(norm, 1e-12)
    return etas, np.ascontiguousarray(bank.astype(np.float32))


def _bank_key(nf: int, nt: int, dt: float, df: float, lens: str,
              srch: SearchSpec) -> tuple:
    """Residency key: the grid plus the bank GEOMETRY half of the spec
    — the pruning knobs (top_k/decim) never fork the resident bank, so
    a re-budgeted search reuses the same HBM buffer."""
    return (int(nf), int(nt), float(dt), float(df), str(lens),
            int(srch.n_trials), float(srch.eta_min),
            float(srch.eta_max), float(srch.width),
            int(srch.delay_rows), int(srch.min_row))


# resident-bank memo: one device buffer per (grid, bank geometry) per
# process — built once, one H2D, shared across every epoch batch, rung
# and runtime re-budget of the same search (the HBM-residency layer)
_BANKS: dict = {}


def bank_resident(nf: int, nt: int, dt: float, df: float, lens: str,
                  srch: SearchSpec):
    """(etas [J] host, bank_hat [J, R, F] complex64 device, L).

    ``bank_hat`` is the CONJUGATED Doppler-axis rFFT of the templates
    at correlation length ``L = next_fast_len(ncfft)`` (equal to ncfft
    itself on both padding modes — the spectrum's Doppler grid is
    already 5-smooth by construction, so frequency-domain bins multiply
    directly with the epochs' spectra, no second padding pass).  The
    ``bank_bytes`` gauge reports the resident footprint on build."""
    key = _bank_key(nf, nt, dt, df, lens, srch)
    hit = _BANKS.get(key)
    if hit is not None:
        # re-report the footprint: a warm process's bench/gauge readers
        # see the resident bytes even when the build was paid earlier
        obs.gauge("bank_bytes", int(hit[1].nbytes))
        return hit
    import jax.numpy as jnp

    etas, bank = build_bank(nf, nt, dt, df, lens, srch)
    L = next_fast_len(bank.shape[-1])
    hat = np.conj(np.fft.rfft(bank, n=L, axis=-1)).astype(np.complex64)
    bank_hat = jnp.asarray(hat)
    obs.gauge("bank_bytes", int(bank_hat.nbytes))
    _BANKS[key] = (etas, bank_hat, L)
    return _BANKS[key]
