"""The search campaign engine: batched matched-filter scoring of a
synthetic campaign against a resident curvature-trial bank.

Ties the plane together (ISSUE 19): a :class:`SearchSpec` of bank
geometry + pruning knobs rides next to a synthetic campaign spec; the
pair (plus the analysis-config fields the spectrum consumes) keys ONE
memoised jit program per (generator identity, grid, bank statics,
batch rung) — :mod:`scintools_tpu.search.engine`.  Identity discipline
mirrors the simulate/infer routes:

* the batch axis pads to the bucket ladder rung (``buckets.rung_for``)
  by repeating the last key row — every campaign size within a rung
  shares one compiled program, pad lanes are sliced off;
* the executed fine-lane count and coarse decimation ride as TRACED
  runtime inputs (``top_k_rt``/``decim_rt``) within the compiled
  ``top_k``/``decim`` envelope, so re-budgeting recall/cost never
  recompiles;
* :func:`search_rows` is the ONE row builder shared by the CLI
  ``--search`` engine and the serve ``search`` job runner — served CSV
  bytes are identical to a direct run's by construction.  The winning
  trial's curvature exports through the standard ``eta``/``etaerr``
  columns (``etaerr`` = the trial grid's half-step quantisation);
  SNR, scores and pruning diagnostics ride as store-only
  ``search_*`` columns.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import buckets, obs
from ..sim import campaign
from .bank import SearchSpec, bank_resident, validate_search
from .engine import program_dims, search_grid, search_program

__all__ = ["search_to_dict", "search_from_dict",
           "validate_search_config", "search_campaign", "search_rows",
           "warm_search"]


def search_to_dict(srch: SearchSpec) -> dict:
    """Canonical sparse JSON-able form (the serve job payload under
    ``cfg["search"]`` and the CLI resume-key ingredient): only
    non-default fields, so sparse client dicts and materialised CLI
    dicts share one job identity (the spec_to_dict convention)."""
    d0 = SearchSpec()
    return {f.name: getattr(srch, f.name)
            for f in dataclasses.fields(SearchSpec)
            if getattr(srch, f.name) != getattr(d0, f.name)}


def search_from_dict(d: dict | None) -> SearchSpec:
    """Inverse of :func:`search_to_dict`; unknown keys raise."""
    d = dict(d or {})
    names = {f.name for f in dataclasses.fields(SearchSpec)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(f"unknown SearchSpec field(s): "
                         f"{sorted(unknown)}")
    srch = SearchSpec(**d)
    validate_search(srch)
    return srch


def validate_search_config(spec, srch: SearchSpec, config) -> None:
    """Cross-field validation of (campaign, bank, analysis) — the
    shared gate of the CLI engine and ``JobQueue.submit_search``."""
    validate_search(srch)
    if config.lamsteps:
        raise ValueError(
            "search scores the frequency-grid secondary spectrum "
            "(trial curvature eta in us/mHz^2); lambda-resampled "
            "(beta-eta) banks are roadmap follow-up work")
    # grid cross-checks (delay window, coarse-bin floor, auto range)
    # raise here — at submit — with the bank plane's own messages
    program_dims(spec, config, srch)
    nf, nt, dt, df = search_grid(spec)
    from .bank import trial_etas

    trial_etas(nf, nt, dt, df, config.fft_lens, srch)


def search_campaign(spec, srch=None, opts=None, *, bucket: bool = True,
                    top_k_rt: int | None = None,
                    decim_rt: int | None = None,
                    naive: bool = False) -> dict:
    """Run one acceleration-search campaign on device and return the
    per-epoch best-trial candidates.

    ``spec``/``srch`` accept dataclasses or (sparse) dicts.  ``bucket``
    pads the epoch axis to the catalog rung (default: the serve/warm
    contract); ``top_k_rt``/``decim_rt`` re-budget the pruning within
    the compiled envelope without recompiling; ``naive=True`` runs the
    exhaustive full-resolution reference program instead (the A/B
    lane — identical output contract, no pruning knobs).

    Returns ``{"kind", "eta": [B], "etaerr": [B], "snr": [B],
    "score": [B], "coarse": [B], "trial": [B], "shift": [B],
    "trials": J, "survivors": K_rt}`` with ``shift`` the signed
    Doppler-lag bin of the correlation peak.
    """
    from .. import compile_cache
    from ..serve.worker import config_from_opts

    if not isinstance(spec, campaign.SynthSpec):
        spec = campaign.spec_from_dict(spec)
    if not isinstance(srch, SearchSpec):
        srch = search_from_dict(srch)
    config = config_from_opts(dict(opts or {}))
    validate_search_config(spec, srch, config)
    # the direct `process --batched --search` path reaches the compile
    # below without the driver/worker entrypoints that wire the
    # persistent XLA cache — wire it here (idempotent) so a
    # `warmup --search` entry is actually hit
    compile_cache.enable_persistent_cache()
    dims = program_dims(spec, config, srch)
    k_rt = srch.top_k if top_k_rt is None else int(top_k_rt)
    if not 0 < k_rt <= srch.top_k:
        raise ValueError(f"top_k_rt must be in [1, {srch.top_k}] (the "
                         f"compiled ceiling), got {k_rt}")
    d_rt = srch.decim if decim_rt is None else int(decim_rt)
    if d_rt < srch.decim:
        raise ValueError(f"decim_rt must be >= {srch.decim} (the "
                         f"compiled coarse grid), got {d_rt}")
    if dims["F"] // d_rt < 2:
        raise ValueError(f"decim_rt={d_rt} leaves fewer than 2 coarse "
                         f"Fourier bins (F={dims['F']})")
    B = int(spec.n_epochs)
    rung = buckets.rung_for(B) if bucket else B
    raw = campaign.stage_batch(spec)
    if rung > B:
        raw = np.concatenate([raw, np.repeat(raw[-1:], rung - B,
                                             axis=0)], axis=0)
    nf, nt, dt, df = (dims["nf"], dims["nt"], dims["dt"], dims["df"])
    etas, bank_hat, _L = bank_resident(nf, nt, dt, df, config.fft_lens,
                                       srch)
    prog = search_program(spec, config, srch, rung, naive=naive)
    J = int(srch.n_trials)
    obs.inc("search_epochs", B)
    obs.inc("bytes_h2d", raw.nbytes)
    # every epoch scores the full bank coarsely plus K_rt survivors
    # finely; the naive reference scores the bank once, exhaustively
    obs.inc("templates_scored", B * J if naive else B * (J + k_rt))
    if not naive:
        obs.inc("prune_survivors", B * k_rt)
    with obs.span("search.score", kind=spec.kind, epochs=B, rung=rung,
                  trials=J, top_k=k_rt, decim=d_rt, naive=bool(naive)):
        if naive:
            out = prog(raw, bank_hat)
        else:
            out = prog(raw, bank_hat, np.uint32(k_rt), np.uint32(d_rt))
    out = {k: np.asarray(v)[:B] for k, v in out.items()}
    trial = out["trial"].astype(int)
    eta = np.asarray(etas)[trial]
    # trial-grid quantisation as the reported uncertainty: half a
    # geometric step on either side of the winning trial
    g = float(etas[1] / etas[0]) if len(etas) > 1 else 1.0
    etaerr = eta * (g - 1.0) / 2.0
    shift = out["shift"].astype(int)
    L = dims["L"]
    shift = np.where(shift > L // 2, shift - L, shift)
    return {"kind": spec.kind, "eta": eta, "etaerr": etaerr,
            "snr": out["snr"], "score": out["score"],
            "coarse": out["coarse"], "trial": trial, "shift": shift,
            "trials": J, "survivors": int(k_rt)}


def search_rows(spec, srch=None, opts=None, mesh=None,
                async_exec: bool = True, bucket: bool = True) -> list:
    """One candidate row per epoch (``None`` for quarantined non-finite
    lanes) — the ONE row builder shared by the CLI ``--search`` engine
    and the serve ``search`` job runner, so served CSV rows are
    byte-identical to a direct run's (the simulate-route contract).

    ``mesh``/``async_exec`` are accepted for runner-signature symmetry
    with ``synthetic_rows``; the search program is single-host today
    (sharded search is roadmap follow-up).
    """
    from ..io.results import row_fit_values

    del mesh, async_exec
    if not isinstance(spec, campaign.SynthSpec):
        spec = campaign.spec_from_dict(spec)
    if not isinstance(srch, SearchSpec):
        srch = search_from_dict(srch)
    res = search_campaign(spec, srch, opts, bucket=bucket)
    meta = campaign.synth_meta(spec)
    rows: list = [None] * spec.n_epochs
    emitted = 0
    for i in range(spec.n_epochs):
        row = dict(meta)
        row["name"] = campaign.epoch_name(spec, i)
        row["mjd"] = campaign._MJD0 + int(i)
        row["eta"] = float(res["eta"][i])
        row["etaerr"] = float(res["etaerr"][i])
        row["search_snr"] = float(res["snr"][i])
        row["search_score"] = float(res["score"][i])
        row["search_coarse"] = float(res["coarse"][i])
        row["search_trial"] = int(res["trial"][i])
        row["search_shift"] = int(res["shift"][i])
        row["search_survivors"] = int(res["survivors"])
        fitvals = row_fit_values(row)
        if (fitvals and not np.all(np.isfinite(fitvals))) \
                or not np.isfinite(res["score"][i]):
            continue   # NaN lane: quarantined (rows[i] stays None)
        rows[i] = row
        emitted += 1
    obs.inc("candidates_emitted", emitted)
    return rows


def warm_search(spec, srch=None, opts=None, *, batch: int | None = None,
                catalog: bool = False) -> list:
    """Pre-compile the search program set for a campaign + bank spec
    (the ``warmup --search`` engine): lowers the PRUNED step against
    ShapeDtypeStructs — no bank build, no campaign execution — and
    compiles it with whatever persistent XLA cache the caller enabled,
    so a later ``process --batched --search`` or served `search` job
    pays zero compile.  ``catalog`` warms every bucket rung up to the
    campaign's (the serve worker's any-epoch-count contract);
    ``batch`` overrides the planned epoch count.

    Returns one ``{"rung", "key", "status", "compile_s"}`` record per
    signature (``key`` = the bank-dimension compile-cache key,
    :func:`scintools_tpu.compile_cache.search_key`)."""
    import time

    import jax

    from .. import compile_cache
    from ..serve.worker import config_from_opts
    from .engine import search_step_fn

    if not isinstance(spec, campaign.SynthSpec):
        spec = campaign.spec_from_dict(spec)
    if not isinstance(srch, SearchSpec):
        srch = search_from_dict(srch)
    config = config_from_opts(dict(opts or {}))
    validate_search_config(spec, srch, config)
    dims = program_dims(spec, config, srch)
    B = int(batch or spec.n_epochs)
    top = buckets.rung_for(B)
    rungs = ([r for r in buckets.batch_ladder() if r <= top] or [top]) \
        if catalog else [top]
    width = campaign.stage_width(spec)
    J = int(srch.n_trials)
    sigs = []
    for rung in rungs:
        step = search_step_fn(spec, config, srch)
        raw_s = jax.ShapeDtypeStruct((int(rung), width), np.uint32)
        bank_s = jax.ShapeDtypeStruct((J, dims["R"], dims["F"]),
                                      np.complex64)
        scalar = jax.ShapeDtypeStruct((), np.uint32)
        key = compile_cache.search_key(spec, config, srch, int(rung))
        t0 = time.perf_counter()
        jax.jit(step).lower(raw_s, bank_s, scalar, scalar).compile()
        sigs.append({"rung": int(rung), "key": key,
                     "status": "compiled",
                     "compile_s": round(time.perf_counter() - t0, 3)})
    return sigs
