"""Fleet pool controller: elastic autoscaling, QoS-aware drain, and
warm/memory-affinity claim hints for the serve queue (ISSUE 13 — the
"millions of users" control plane of ROADMAP item 1).

The controller is a PURE CONSUMER of telemetry the system already
emits: merged worker heartbeats (PR 10 — per-beat ``jobs_done`` deltas
-> drain rate; warm signatures; PR 12 device-memory headroom) plus the
live queue depth, folded into the documented ``backpressure =
depth / (depth + drain * 60 s)`` scalar.  Three responsibilities:

1. **Elasticity.**  Spawn worker subprocesses (``scintools-tpu serve
   ... --worker-id pool-<pid>-<n> --ignore-drain``) when backpressure
   crosses the high-water threshold; drain one (the per-worker drain
   marker — the worker stops claiming, finishes the batches it holds,
   consumes the marker, exits) below the low-water one.  Min/max
   bounds, a scale cooldown, and STALE-worker replacement (a live
   process whose heartbeat froze is killed and respawned — the
   GPU real-time search stacks' "keep the resident pipeline fed or
   replace it" discipline, arXiv 1804.05335).

2. **Claim hints.**  Each round the controller folds every fresh
   heartbeat's ``warm_sigs`` (the bucket/config signatures that worker
   has already executed — the warm-affinity signal) and ``devmem``
   headroom into ONE atomically-rewritten ``control/hints.json``;
   workers read it (mtime-gated) and honour it inside
   ``JobQueue.claim``: claim warm-here jobs eagerly
   (``affinity_hits``), briefly defer jobs warm elsewhere
   (``affinity_deferred`` -> the warm worker lands them instead of
   recompiling), and leave jobs bigger than the published headroom for
   a roomier worker (``pool_mem_deferred``) — time-bounded, so hints
   delay placement but never starve a job.

3. **Operator surface.**  Every round lands an atomic
   ``control/pool.json`` snapshot (decisions, worker table, lane
   depths, backpressure) that ``fleet status`` / ``trace report
   --fleet`` render, plus obs counters ``pool_scale_up/down`` /
   ``pool_stale_replaced`` and the ``pool_workers`` gauge.

Failure model: chaos sites ``pool.spawn`` and ``pool.drain`` (PR 5
registry) prove a failed spawn/drain degrades to a logged, counted
skip — and scale-down can never lose a job, because the drain marker
only ever asks a worker to STOP CLAIMING; anything already leased is
finished by that worker or lease-reaped by the survivors.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

from .. import faults, obs
from ..obs import fleet
from ..utils import fsio
from ..utils.log import get_logger, log_event
from .queue import (DEFAULT_AFFINITY_DEFER_S, DEFAULT_MEM_DEFER_S,
                    DEFAULT_PIN_DEFER_S, ClaimHints, JobQueue)

HINTS_BASENAME = "hints.json"
POOL_STATUS_BASENAME = "pool.json"
HINTS_VERSION = 1
# cap the per-worker preferred-signature list a hints file carries (a
# long-lived worker accumulates warm signatures without bound; the
# newest are the ones still resident)
MAX_PREFER_SIGS = 64


def hints_path(queue_dir: str) -> str:
    """Path of the claim-hints file under a queue dir."""
    return os.path.join(queue_dir, "control", HINTS_BASENAME)


def pool_status_path(queue_dir: str) -> str:
    """Path of the controller status snapshot under a queue dir."""
    return os.path.join(queue_dir, "control", POOL_STATUS_BASENAME)


def _write_json(path: str, payload: dict) -> str:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fsio.put_atomic(path, json.dumps(payload, default=str))
    return path


def _read_json(path: str) -> dict | None:
    try:
        data = json.loads(fsio.read(path))
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def write_hints(queue_dir: str, workers: dict,
                defer_s: float = DEFAULT_AFFINITY_DEFER_S,
                mem_defer_s: float = DEFAULT_MEM_DEFER_S,
                pin_defer_s: float = DEFAULT_PIN_DEFER_S) -> str:
    """Atomically rewrite the claim-hints file: ``workers`` maps
    worker id -> ``{"prefer": [sig, ...], "max_bytes": int | None,
    "pins": [feed path, ...]}`` (every entry key optional)."""
    return _write_json(hints_path(queue_dir), {
        "kind": "pool_hints", "v": HINTS_VERSION,
        "ts": round(time.time(), 6), "pid": os.getpid(),
        "defer_s": float(defer_s), "mem_defer_s": float(mem_defer_s),
        "pin_defer_s": float(pin_defer_s),
        "workers": workers})


def read_hints(queue_dir: str) -> dict | None:
    """The current hints payload; torn/missing/foreign degrades to
    None (hints are advisory — a reader must never fail on them)."""
    data = _read_json(hints_path(queue_dir))
    if data is None or data.get("kind") != "pool_hints":
        return None
    return data


def claim_hints_for(data: dict | None,
                    worker_id: str) -> ClaimHints | None:
    """This worker's :class:`~.queue.ClaimHints` view of a hints
    payload: its own preferred signatures + headroom bound + pinned
    feeds, and the union of every OTHER worker's preferences/pins (the
    defer sets).  None when the payload carries no workers (claim runs
    unhinted)."""
    workers = (data or {}).get("workers") or {}
    if not isinstance(workers, dict) or not workers:
        return None
    mine = workers.get(worker_id) or {}
    prefer = frozenset(str(s) for s in (mine.get("prefer") or ()))
    elsewhere = frozenset(
        str(s) for wid, ent in workers.items()
        if wid != worker_id and isinstance(ent, dict)
        for s in (ent.get("prefer") or ())) - prefer
    pinned = frozenset(str(p) for p in (mine.get("pins") or ()))
    pinned_elsewhere = frozenset(
        str(p) for wid, ent in workers.items()
        if wid != worker_id and isinstance(ent, dict)
        for p in (ent.get("pins") or ())) - pinned
    max_bytes = mine.get("max_bytes")
    if not isinstance(max_bytes, (int, float)):
        max_bytes = None
    return ClaimHints(
        prefer=prefer, elsewhere=elsewhere,
        max_bytes=int(max_bytes) if max_bytes is not None else None,
        defer_s=float(data.get("defer_s", DEFAULT_AFFINITY_DEFER_S)),
        mem_defer_s=float(data.get("mem_defer_s",
                                   DEFAULT_MEM_DEFER_S)),
        pinned=pinned, pinned_elsewhere=pinned_elsewhere,
        # the pin deferral window runs from the hints file's OWN write
        # stamp (a stream job's queue age is useless for grace — see
        # queue.DEFAULT_PIN_DEFER_S)
        pin_ts=float(data.get("ts", 0.0) or 0.0),
        pin_defer_s=float(data.get("pin_defer_s",
                                   DEFAULT_PIN_DEFER_S)))


def read_pool_status(queue_dir: str) -> dict | None:
    """The controller's last ``control/pool.json`` snapshot (None when
    no controller has run here / the file is torn)."""
    data = _read_json(pool_status_path(queue_dir))
    if data is None or data.get("kind") != "pool":
        return None
    return data


def hints_from_heartbeats(heartbeats, now: float) -> dict:
    """Per-worker hint entries from FRESH heartbeats: ``warm_sigs``
    (published by the worker, newest-capped) -> ``prefer``; the devmem
    headroom (PR 12 — in-use vs limit, the same figure the predictive
    OOM admission trusts) -> ``max_bytes``; registered live-feed dirs
    (the ``streams`` payload's per-session ``dir``) -> ``pins``, the
    feed->worker affinity ``JobQueue.claim`` honours ahead of warm
    sigs (ISSUE 17).  Stale workers publish no hints (a frozen
    heartbeat describes a process that may be gone), and a DRAINING
    worker's feeds are deliberately unpinned — its final beat
    advertises the hand-back so the survivors re-pin instead of
    deferring to a worker that is exiting."""
    out: dict[str, dict] = {}
    for hb in heartbeats:
        wid = hb.get("worker")
        if not wid or fleet.heartbeat_stale(hb, now):
            continue
        ent: dict = {}
        sigs = hb.get("warm_sigs")
        if isinstance(sigs, (list, tuple)) and sigs:
            ent["prefer"] = [str(s) for s in sigs][-MAX_PREFER_SIGS:]
        mem = hb.get("devmem")
        if isinstance(mem, dict):
            head = mem.get("headroom")
            if isinstance(head, (int, float)) and head > 0:
                ent["max_bytes"] = int(head)
        streams = hb.get("streams")
        if isinstance(streams, dict) and not hb.get("draining"):
            pins = sorted({str(s["dir"]) for s in streams.values()
                           if isinstance(s, dict) and s.get("dir")})
            if pins:
                ent["pins"] = pins
        if ent:
            out[str(wid)] = ent
    return out


@dataclasses.dataclass(frozen=True)
class PoolConfig:
    """Controller thresholds.  ``high_water``/``low_water`` are
    backpressure bounds (0.5 = backlog equals one 60 s horizon of
    drain — the documented natural scale-up point); ``cooldown_s``
    spaces scale DECISIONS so one burst cannot slam the pool between
    bounds; ``stale_grace_s`` is how long a fresh spawn may run before
    a stale/absent heartbeat makes it replaceable."""

    min_workers: int = 1
    max_workers: int = 4
    high_water: float = 0.5
    low_water: float = 0.1
    cooldown_s: float = 15.0
    poll_s: float = 1.0
    stale_grace_s: float = 60.0
    drain_grace_s: float = 60.0
    # replacement threshold for a FROZEN heartbeat: a worker blocked in
    # one long execute/compile (on-chip cold compiles have measured
    # minutes) writes no beats while it works, so the kill rule must be
    # far more conservative than the 3x-interval STALE *rendering* —
    # beat age must exceed max(3x interval, stale_kill_s)
    stale_kill_s: float = 300.0
    # SLO breach prediction (obs/slo.py — ISSUE 16): each round the
    # controller fits a linear trend over the declared SLO metrics'
    # recent timeline (per-feed stream lag from heartbeats, estimated
    # queue wait = depth/drain) and scales UP when the trend crosses a
    # declared threshold within predict_horizon_s — before the error
    # budget burns, alongside (ahead of) raw backpressure
    predict_horizon_s: float = 60.0
    predict_window_s: float = 300.0
    predict_min_points: int = 3

    def __post_init__(self):
        if self.min_workers < 0:
            raise ValueError(f"min_workers={self.min_workers}: "
                             "must be >= 0")
        if self.max_workers < max(self.min_workers, 1):
            raise ValueError(
                f"max_workers={self.max_workers}: must be >= "
                f"max(min_workers, 1) = {max(self.min_workers, 1)}")
        if not 0.0 <= self.low_water < self.high_water <= 1.0:
            raise ValueError(
                f"watermarks must satisfy 0 <= low < high <= 1, got "
                f"low={self.low_water} high={self.high_water}")
        if self.predict_horizon_s < 0.0 or self.predict_window_s <= 0.0:
            raise ValueError(
                "predict_horizon_s must be >= 0 and predict_window_s "
                f"> 0, got horizon={self.predict_horizon_s} "
                f"window={self.predict_window_s}")
        if self.predict_min_points < 2:
            raise ValueError(
                f"predict_min_points={self.predict_min_points}: a "
                "trend needs >= 2 points")


class PoolController:
    """One control process per queue directory (``scintools-tpu pool
    QDIR``).  ``spawn`` is injectable for tests: ``spawn(worker_id) ->
    Popen-like`` (``poll``/``terminate``/``kill``/``pid``); the
    default launches ``scintools-tpu serve`` subprocesses with
    ``worker_args`` appended (stdout/stderr to
    ``control/worker-logs/<id>.log``)."""

    def __init__(self, queue_dir: str, config: PoolConfig | None = None,
                 spawn=None, worker_args=()):
        self.queue = JobQueue(queue_dir)
        self.cfg = config or PoolConfig()
        self.worker_args = list(worker_args)
        self.spawn = spawn if spawn is not None else self._default_spawn
        # worker_id -> {"proc", "spawned_at", "draining", "drained_at"}
        self.workers: dict[str, dict] = {}
        self._n = 0
        self._last_scale = float("-inf")
        self.stats = {"rounds": 0, "scale_up": 0, "scale_down": 0,
                      "stale_replaced": 0, "spawn_failed": 0,
                      "drain_failed": 0, "worker_exits": 0,
                      "predicted_breach": 0}
        self._last_hint_entries: dict | None = None
        self._last_decision: str | None = None
        # SLO registry (slo.json, mtime-gated) + per-SLO metric
        # timelines for the breach predictor (ISSUE 16)
        self._slo_specs: list = []
        self._slo_stamp = None
        self._trends: dict[str, list] = {}
        self._last_predict: dict | None = None
        self.log = get_logger()

    # -- spawning ----------------------------------------------------------
    def _next_worker_id(self) -> str:
        self._n += 1
        return f"pool-{os.getpid()}-{self._n}"

    def _default_spawn(self, worker_id: str):
        logdir = os.path.join(self.queue.dir, "control", "worker-logs")
        os.makedirs(logdir, exist_ok=True)
        cmd = [sys.executable, "-m", "scintools_tpu", "serve",
               self.queue.dir, "--worker-id", worker_id,
               "--ignore-drain"] + self.worker_args
        # --ignore-drain: pool workers' lifecycle belongs to the
        # CONTROLLER (per-worker markers + shutdown); racing N workers
        # at the one global marker would stop an arbitrary subset
        with open(os.path.join(logdir, f"{worker_id}.log"),
                  "a") as logfh:
            return subprocess.Popen(cmd, stdout=logfh,
                                    stderr=subprocess.STDOUT)

    def _spawn_one(self, reason: str,
                   now: float | None = None) -> str | None:
        wid = self._next_worker_id()
        try:
            # chaos site (kind="error"): a spawn failure (exec error,
            # fork limit) must degrade to a counted, logged skip the
            # next round retries — never crash the control loop
            faults.check("pool.spawn")
            proc = self.spawn(wid)
        except Exception as e:
            self.stats["spawn_failed"] += 1
            obs.inc("pool_spawn_failed")
            log_event(self.log, "pool_spawn_failed", worker=wid,
                      reason=reason, error=repr(e))
            return None
        self.workers[wid] = {"proc": proc,
                             "spawned_at": (time.time() if now is None
                                            else now),
                             "draining": False, "drained_at": None}
        log_event(self.log, "pool_spawn", worker=wid, reason=reason,
                  pid=getattr(proc, "pid", None))
        return wid

    # -- lifecycle bookkeeping ---------------------------------------------
    def _reap_children(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        for wid, w in list(self.workers.items()):
            rc = w["proc"].poll()
            if rc is None:
                if w["draining"] and w["drained_at"] is not None and \
                        now - w["drained_at"] > self.cfg.drain_grace_s:
                    # polite drain ignored (wedged worker): terminate,
                    # then ESCALATE to kill on the next expiry — a
                    # worker stuck in uninterruptible IO must not stay
                    # a zombie in the pool forever; its leased jobs
                    # are reclaimed by lease expiry either way
                    if w.get("term_sent"):
                        w["proc"].kill()
                    else:
                        w["proc"].terminate()
                        w["term_sent"] = True
                    w["drained_at"] = now          # re-arm the grace
                continue
            del self.workers[wid]
            self.queue.clear_worker_drain(wid)
            self.stats["worker_exits"] += 1
            log_event(self.log, "pool_worker_exit", worker=wid, rc=rc,
                      draining=bool(w["draining"]))

    def _replace_stale(self, heartbeats: dict, now: float) -> None:
        for wid, w in list(self.workers.items()):
            if w["draining"]:
                continue
            if now - w["spawned_at"] < self.cfg.stale_grace_s:
                continue   # still starting up (compiles, imports)
            hb = heartbeats.get(wid)
            if hb is not None:
                iv = hb.get("interval_s")
                iv = float(iv) if isinstance(iv, (int, float)) else 0.0
                age = now - hb.get("ts", now)
                # the STALE rendering threshold (3x interval) excludes
                # a worker from the drain rate; KILLING it demands a
                # frozen beat far beyond any legitimate blocking window
                # (the worker beats only between poll rounds, so one
                # long execute/compile — minutes on a cold chip —
                # freezes the file while the worker is hard at work)
                if age <= max(3.0 * iv, self.cfg.stale_kill_s):
                    continue
            # a live process whose heartbeat froze (or never appeared)
            # past every legitimate window is not serving: kill it —
            # its leases reap — and respawn
            try:
                w["proc"].kill()
            except Exception as e:  # fault-ok: already-dead race
                log_event(self.log, "pool_kill_failed", worker=wid,
                          error=repr(e))
            del self.workers[wid]
            self.stats["stale_replaced"] += 1
            obs.inc("pool_stale_replaced")
            log_event(self.log, "pool_stale_replaced", worker=wid)
            self._spawn_one("stale_replacement", now)

    def _alive(self) -> list[str]:
        return [wid for wid, w in self.workers.items()
                if not w["draining"]]

    # -- SLO breach prediction (ISSUE 16) ----------------------------------
    def _reload_slos(self) -> None:
        """Mtime-gated reload of the declared SLO registry (the same
        ``slo.json`` the workers evaluate; one stat per round).  A
        malformed file logs and disarms the predictor — the reactive
        backpressure branch still protects the pool."""
        from ..obs import slo as slo_mod

        try:
            st = os.stat(slo_mod.slo_path(self.queue.dir))
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            stamp = ()
        if stamp == self._slo_stamp:
            return
        self._slo_stamp = stamp
        try:
            self._slo_specs = slo_mod.load_slos(self.queue.dir)
        except ValueError as e:
            log_event(self.log, "pool_slo_load_failed", error=repr(e))
            self._slo_specs = []
        self._trends = {s["name"]: [] for s in self._slo_specs}

    def _metric_value(self, spec: dict, heartbeats: dict,
                      depth: int, drain) -> float | None:
        """One current observation of an SLO's metric, from telemetry
        the controller already holds: per-feed lag from the heartbeat
        ``streams`` payloads (worst across workers), and estimated
        wait ``depth / drain`` for the queue-side kinds."""
        kind, key = spec["kind"], spec["key"]
        if kind == "stream_lag_s":
            lags = []
            for hb in heartbeats.values():
                for st in (hb.get("streams") or {}).values():
                    if key is not None and st.get("feed") != key:
                        continue
                    v = st.get("lag_s")
                    if isinstance(v, (int, float)):
                        lags.append(float(v))
            return max(lags) if lags else None
        if kind in ("queue_wait_s", "job_latency_s"):
            if isinstance(drain, (int, float)) and drain > 0:
                return depth / float(drain)
            return None
        return None

    def _predict_breaches(self, heartbeats: dict, depth: int,
                          drain, now: float) -> list:
        """Advance every SLO's metric timeline and return the names
        whose linear trend crosses the declared threshold within
        ``predict_horizon_s`` — the scale-up signal that leads the
        error budget instead of chasing it."""
        if not self._slo_specs:
            self._last_predict = None
            return []
        from ..obs import slo as slo_mod

        breaches = []
        predict = {}
        for spec in self._slo_specs:
            if spec["kind"] == "heartbeat":
                continue
            value = self._metric_value(spec, heartbeats, depth, drain)
            tl = self._trends.setdefault(spec["name"], [])
            if value is not None:
                tl.append((now, float(value)))
            edge = now - self.cfg.predict_window_s
            while tl and tl[0][0] < edge:
                tl.pop(0)
            pred = None
            if len(tl) >= self.cfg.predict_min_points:
                pred = slo_mod.predict_value(
                    tl, self.cfg.predict_horizon_s)
            breach = (pred is not None
                      and pred >= spec["threshold_s"])
            predict[spec["name"]] = {
                "metric": slo_mod.metric_name(spec),
                "value": tl[-1][1] if tl else None,
                "predicted": (round(pred, 6) if pred is not None
                              else None),
                "threshold_s": spec["threshold_s"],
                "horizon_s": self.cfg.predict_horizon_s,
                "breach": breach}
            if breach:
                breaches.append(spec["name"])
        self._last_predict = predict
        return breaches

    def _pick_drain(self, alive, heartbeats: dict) -> str:
        """The scale-down victim: the idlest worker (largest last-claim
        age from its heartbeat), tiebroken toward the youngest spawn —
        drain the one doing the least, keep the warmed-up veterans."""
        def idle_key(wid):
            hb = heartbeats.get(wid) or {}
            age = hb.get("last_claim_age_s")
            idle = age if isinstance(age, (int, float)) else -1.0
            return (idle, self.workers[wid]["spawned_at"])

        return max(alive, key=idle_key)

    # -- one control round -------------------------------------------------
    def poll_once(self, now: float | None = None) -> dict:
        """Reap -> replace-stale -> scale -> publish hints + status.
        Returns the status snapshot written to ``control/pool.json``."""
        now = time.time() if now is None else now
        self.stats["rounds"] += 1
        self._reap_children(now)
        hb_dir = os.path.join(self.queue.dir, fleet.HEARTBEAT_DIRNAME)
        heartbeats = {hb.get("worker"): hb
                      for hb in fleet.read_heartbeats(hb_dir)}
        self._replace_stale(heartbeats, now)
        counts = self.queue.counts()
        depth = counts["queued"] + counts["leased"]
        merged = fleet.merge_heartbeats(heartbeats.values(), now=now)
        bp = fleet.backpressure(depth, merged["drain_rate_per_s"])
        self._reload_slos()
        predicted = self._predict_breaches(
            heartbeats, depth, merged["drain_rate_per_s"], now)
        alive = self._alive()
        decision = None
        cooled = now - self._last_scale >= self.cfg.cooldown_s
        if len(alive) < self.cfg.min_workers:
            # the floor is unconditional: a pool below min is not a
            # scaling judgment, it is a hole (first round, crashed
            # worker) — refill immediately, no cooldown, no counter
            if self._spawn_one("min_floor", now) is not None:
                decision = "spawn_to_min"
        elif (predicted and len(alive) < self.cfg.max_workers
              and cooled):
            # predicted SLO breach (ISSUE 16): a declared metric's
            # trend crosses its threshold within the horizon — spawn
            # BEFORE the budget burns, even while raw backpressure
            # still sits below high_water
            if self._spawn_one("predicted_breach", now) is not None:
                self.stats["predicted_breach"] += 1
                obs.inc("pool_predicted_breach")
                self._last_scale = now
                decision = "scale_up_predicted"
                log_event(self.log, "pool_predicted_breach",
                          slos=",".join(predicted),
                          backpressure=round(bp, 4))
        elif (bp >= self.cfg.high_water
              and len(alive) < self.cfg.max_workers and cooled):
            if self._spawn_one("backpressure", now) is not None:
                self.stats["scale_up"] += 1
                obs.inc("pool_scale_up")
                self._last_scale = now
                decision = "scale_up"
        elif (bp <= self.cfg.low_water and not predicted
              and len(alive) > self.cfg.min_workers and cooled):
            # `not predicted`: a live predicted breach vetoes the
            # drain — low raw backpressure is exactly what the leading
            # signal is warning will not last
            wid = self._pick_drain(alive, heartbeats)
            try:
                # chaos site (kind="error"): a failed drain request
                # must leave the worker serving and the queue intact —
                # scale-down is advisory, jobs are never at risk
                faults.check("pool.drain")
                self.queue.request_worker_drain(wid)
            except Exception as e:
                self.stats["drain_failed"] += 1
                log_event(self.log, "pool_drain_failed", worker=wid,
                          error=repr(e))
            else:
                self.workers[wid]["draining"] = True
                self.workers[wid]["drained_at"] = now
                self.stats["scale_down"] += 1
                obs.inc("pool_scale_down")
                self._last_scale = now
                decision = "scale_down"
                log_event(self.log, "pool_drain", worker=wid)
        if decision:
            self._last_decision = decision
        entries = hints_from_heartbeats(heartbeats.values(), now)
        # rewrite only on CHANGE (or a vanished file): every worker
        # stat-gates its reparse on (mtime, size) — an every-round
        # rewrite with a fresh ts would defeat that fast path
        if entries != self._last_hint_entries \
                or not os.path.exists(hints_path(self.queue.dir)):
            try:
                write_hints(self.queue.dir, entries)
                self._last_hint_entries = entries
            except OSError as e:  # fault-ok: hints are advisory
                # visible fleet-wide, not just in this log: a
                # controller that silently stops steering claims
                # shows up as fsio_write_errors[hints]
                obs.inc("fsio_write_errors")
                obs.inc("fsio_write_errors[hints]")
                log_event(self.log, "hints_write_failed",
                          error=repr(e))
        obs.gauge("pool_workers", len(self.workers))
        status = {
            "kind": "pool", "v": 1, "ts": round(now, 6),
            "pid": os.getpid(),
            "backpressure": bp, "depth": depth,
            "drain_rate_per_s": merged["drain_rate_per_s"],
            "min_workers": self.cfg.min_workers,
            "max_workers": self.cfg.max_workers,
            "high_water": self.cfg.high_water,
            "low_water": self.cfg.low_water,
            "workers": {wid: {"pid": getattr(w["proc"], "pid", None),
                              "draining": bool(w["draining"])}
                        for wid, w in self.workers.items()},
            "lane_depths": self.queue.lane_depths(),
            "decision": decision,
            "last_decision": self._last_decision,
            "slo_predict": self._last_predict,
            "stats": dict(self.stats),
        }
        try:
            _write_json(pool_status_path(self.queue.dir), status)
        except OSError as e:  # fault-ok: status snapshot only
            obs.inc("fsio_write_errors")
            obs.inc("fsio_write_errors[pool]")
            log_event(self.log, "pool_status_write_failed",
                      error=repr(e))
        return status

    # -- the resident control loop -----------------------------------------
    def run(self, max_rounds: int | None = None,
            exit_on_drain: bool = True) -> dict:
        """Control until told to stop: ``max_rounds`` rounds executed
        (tests/smokes), or — with ``exit_on_drain`` — a GLOBAL drain
        request with the queue empty (the controller then drains its
        workers, consumes the marker and exits, mirroring the single-
        worker drain contract)."""
        log_event(self.log, "pool_start", queue=self.queue.dir,
                  min=self.cfg.min_workers, max=self.cfg.max_workers,
                  high=self.cfg.high_water, low=self.cfg.low_water)
        try:
            while True:
                self.poll_once()
                if max_rounds is not None \
                        and self.stats["rounds"] >= max_rounds:
                    break
                if exit_on_drain and self.queue.drain_requested() \
                        and self.queue.empty():
                    self.shutdown()
                    self.queue.clear_drain()
                    break
                time.sleep(self.cfg.poll_s)
        finally:
            log_event(self.log, "pool_exit", **self.stats)
        return dict(self.stats)

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Drain every worker politely, then terminate stragglers.
        Leased jobs are never lost: a drained worker finishes what it
        holds before exiting, and a terminated one's leases are
        reclaimed by ``reap_expired`` wherever the queue next runs."""
        for wid in list(self.workers):
            try:
                self.queue.request_worker_drain(wid)
            except OSError as e:  # fault-ok: terminate path below
                log_event(self.log, "pool_drain_failed", worker=wid,
                          error=repr(e))
        deadline = time.time() + timeout_s
        while self.workers and time.time() < deadline:
            self._reap_children()
            if self.workers:
                time.sleep(0.1)
        for wid, w in list(self.workers.items()):
            try:
                w["proc"].terminate()
                w["proc"].wait(timeout=5.0)
            except Exception as e:  # fault-ok: best-effort teardown
                log_event(self.log, "pool_terminate_failed",
                          worker=wid, error=repr(e))
                try:
                    w["proc"].kill()
                except Exception:  # fault-ok: already dead
                    pass
            self.queue.clear_worker_drain(wid)
            del self.workers[wid]
