"""Resident survey worker: the warm process that serves the queue.

PR 2 made a warm process cheap (persistent compile cache, AOT
``warmup``, async chunk execution); this loop keeps that process
RESIDENT and feeds it a continuous stream of epochs — claim leased
jobs, coalesce them through the :class:`~.batcher.DynamicBatcher` onto
the warm compiled signatures, execute ONE padded step per shape
bucket (``run_pipeline(pad_to=batch_size)``), write content-keyed
result rows (idempotent — utils.store), and finalise the queue state.
Per-job failures (unreadable file, degenerate epoch, NaN lane) are
isolated from the batch: the job retries with backoff until the
queue's retry budget poisons it to ``failed/``; the batch's other
lanes complete normally.

Observability (all via :mod:`scintools_tpu.obs`, visible in ``trace
report``): gauges ``queue_depth`` / ``batch_fill_ratio``; counters
``queue_wait_s`` (submit->claim wait, summed), ``serve_jobs_claimed``,
``serve_batches``, ``serve_lanes_filled`` / ``serve_lanes_total``
(mean fill), ``jobs_done`` / ``jobs_failed`` / ``job_retries``; spans
``serve.poll`` / ``serve.load`` / ``serve.batch``.
"""

from __future__ import annotations

import dataclasses
import os
import time

from .. import faults, obs
from ..health import PreflightError
from ..obs import devmem
from ..obs.fleet import FLIGHT_DIRNAME, HEARTBEAT_DIRNAME, HeartbeatWriter
from ..utils.log import get_logger, log_event
from ..utils.timing import trace_annotation
from .batcher import Batch, DynamicBatcher
from .queue import JobQueue, stream_feed_of

# late-joining feed threshold (ISSUE 17): a registration whose backlog
# holds at least this many live-cadence ticks catches up through the
# bulk backfill lane instead of replaying the history tick-by-tick on
# the live (latency-budgeted) path
BACKFILL_MIN_TICKS = 8
# how long a reaper keeps a dead worker's feed pinned to ITSELF after
# requeueing its expired stream lease: long enough to win the next few
# claim rounds, short enough that an unclaimed feed (this worker died
# too / is saturated) falls back to the open pool
REAPED_PIN_TTL_S = 60.0


def config_from_opts(opts: dict):
    """PipelineConfig from a job's stored option dict — the one
    builder shared with the CLI (``cmd_process``/``cmd_warmup`` build
    the same dict from argparse flags), so a served epoch runs exactly
    the config a ``process --batched`` survey would."""
    from ..parallel import PipelineConfig

    opts = dict(opts or {})
    pkw = dict(lamsteps=bool(opts.get("lamsteps", False)),
               fit_arc=not opts.get("no_arc", False),
               fit_scint=not opts.get("no_scint", False),
               fit_scint_2d=bool(opts.get("scint_2d", False)),
               arc_asymm=bool(opts.get("arc_asymm", False)),
               arc_method=opts.get("arc_method", "norm_sspec"),
               arc_stack=bool(opts.get("arc_stack", False)))
    bracket = opts.get("arc_bracket")
    if bracket is not None:
        pkw["arc_constraint"] = (float(bracket[0]), float(bracket[1]))
    # performance-policy knobs: absent keys keep the PipelineConfig
    # defaults, so legacy job dicts build the identical config (and the
    # identical job identity — cfg_signature drops nothing here because
    # _estimator_opts only materialises non-default values)
    if opts.get("precision") is not None:
        pkw["precision"] = str(opts["precision"])
    if opts.get("fft_lens") is not None:
        pkw["fft_lens"] = str(opts["fft_lens"])
    if opts.get("sspec_crop"):
        pkw["sspec_crop"] = True
    if opts.get("fused_sspec"):
        pkw["fused_sspec"] = True
    if opts.get("split_programs"):
        # placement knob (cfg_signature strips it from the job
        # identity, like `bucket`): results are bit-identical, only
        # the compile-unit granularity changes
        pkw["split_programs"] = True
    # sizing knobs (client API; the CLI keeps the survey defaults)
    for k in ("arc_numsteps", "lm_steps"):
        if opts.get(k) is not None:
            pkw[k] = int(opts[k])
    return PipelineConfig(**pkw)


def load_epoch(path: str, clean: bool = False, preflight: bool = True):
    """Host-side load+clean of one epoch — the same chain as the
    batched CLI engine (trim/refill, plus the --clean triage), so a
    served epoch enters the pipeline bit-identical to a direct run.

    ``preflight`` (default on) runs the health checks on the RAW
    post-trim epoch — before ``refill`` repairs dead bands / NaN gaps
    by interpolation — raising :class:`~scintools_tpu.health.
    PreflightError` with machine-readable reason codes; callers route
    it to their quarantine path (deterministic, so it never burns the
    serve retry budget)."""
    from ..health import quarantine_check
    from ..io.psrflux import read_psrflux
    from ..ops.clean import correct_band, refill, trim_edges, zap

    d = trim_edges(read_psrflux(path))
    if preflight:
        quarantine_check(d, name=os.path.basename(path))
    d = refill(d)
    if clean:
        d = correct_band(refill(zap(
            zap(d, method="channels", sigma=5),
            method="subints", sigma=5)))
    if d.nchan < 2 or d.nsub < 2:
        raise ValueError(f"degenerate after trim: {d.nchan}x{d.nsub}")
    return d


def synthetic_runner(spec_dict: dict, opts: dict, mesh=None,
                     async_exec: bool = True,
                     bucket: bool = False) -> list:
    """Default `simulate`-job executor: the whole campaign as ONE
    zero-H2D on-device generate→analyse run (``run_pipeline(
    synthetic=...)``), rows built by the same helper as the CLI's
    synthetic engine (``campaign.synthetic_rows``) — served CSV rows
    are byte-identical to a direct run of the same keys/params.
    ``bucket`` mirrors the worker's --bucket knob: the campaign's
    batch canonicalises onto the catalog ladder, so a `warmup
    --synthetic --catalog`-warmed worker keeps jit_cache_miss = 0 for
    ANY epoch count (results byte-identical either way — a placement
    knob, never job identity).  Returns one row dict (or None for a
    quarantined NaN lane) per epoch, in epoch order."""
    from ..sim import campaign

    spec = campaign.spec_from_dict(spec_dict)
    return campaign.synthetic_rows(spec, opts, mesh=mesh,
                                   async_exec=async_exec, bucket=bucket)


def infer_job_runner(spec_dict: dict, infer_dict: dict, opts: dict,
                     mesh=None, async_exec: bool = True,
                     bucket: bool = False) -> list:
    """Default `infer`-job executor (ISSUE 18): the gradient-inference
    campaign as ONE on-device forward+backward program, rows built by
    the same helper as the CLI's ``--infer`` engine
    (``scintools_tpu.infer.infer_rows``) — served CSV rows are
    byte-identical to a direct run of the same payloads.  The infer
    program always canonicalises its batch onto the catalog ladder
    (results byte-identical at any rung), so the worker's ``bucket``
    knob is forwarded for signature symmetry only."""
    from ..infer import infer_from_dict, infer_rows
    from ..sim import campaign

    del bucket
    spec = campaign.spec_from_dict(spec_dict)
    return infer_rows(spec, infer_from_dict(infer_dict), opts,
                      mesh=mesh, async_exec=async_exec)


def search_job_runner(spec_dict: dict, search_dict: dict, opts: dict,
                      mesh=None, async_exec: bool = True,
                      bucket: bool = False) -> list:
    """Default `search`-job executor (ISSUE 19): the acceleration
    search as ONE fused correlation program against the resident
    template bank, rows built by the same helper as the CLI's
    ``--search`` engine (``scintools_tpu.search.search_rows``) —
    served CSV rows are byte-identical to a direct run of the same
    payloads.  The search program always canonicalises its batch onto
    the catalog ladder (results byte-identical at any rung), so the
    worker's ``bucket`` knob is forwarded for signature symmetry
    only."""
    from ..search import search_from_dict, search_rows
    from ..sim import campaign

    del bucket
    spec = campaign.spec_from_dict(spec_dict)
    return search_rows(spec, search_from_dict(search_dict), opts,
                       mesh=mesh, async_exec=async_exec)


def pipeline_runner(batch: Batch, batch_size: int, mesh=None,
                    async_exec: bool = True) -> list:
    """Default batch executor: ONE padded compiled step over the
    bucket (``pad_to`` holds the warm signature), rows built by the
    same helpers as the CLI's batched engine.  Returns one row dict
    (or None for a failed lane) per job, in job order."""
    from ..io.results import batch_lane_row, results_row
    from ..parallel import run_pipeline

    cfg = config_from_opts(batch.cfg)
    buckets = run_pipeline(list(batch.epochs), cfg, mesh=mesh,
                           async_exec=async_exec, pad_to=batch_size)
    rows: list = [None] * len(batch.jobs)
    for idx, res in buckets:
        for lane, i in enumerate(idx):
            row = results_row(batch.epochs[i])
            row.update(batch_lane_row(res, lane, cfg.lamsteps))
            row["name"] = os.path.basename(batch.jobs[i].file)
            rows[i] = row
    return rows


@dataclasses.dataclass
class _StreamState:
    """One registered live-feed session (`stream` job kind): the
    leased job record (its ``span`` advances per tick hop) + the
    resident :class:`~scintools_tpu.stream.StreamSession`."""

    job: object
    session: object
    last_renew: float


class ServeWorker:
    """One resident worker process bound to a queue directory.

    ``runner`` is injectable for tests (``runner(batch, batch_size,
    mesh, async_exec) -> [row|None, ...]``); the default is the real
    padded ``run_pipeline`` executor above.
    """

    def __init__(self, queue: JobQueue, batch_size: int = 8,
                 max_wait_s: float = 2.0, lease_s: float = 60.0,
                 poll_s: float = 0.2, mesh=None, runner=None,
                 async_exec: bool = True, worker_id: str | None = None,
                 bucket: bool = False, synth_runner=None,
                 heartbeat_s: float = 10.0,
                 lane_budgets: dict | None = None, infer_runner=None,
                 search_runner=None):
        self.queue = queue
        self.batch_size = int(batch_size)
        mult = 1
        if mesh is not None:
            from ..parallel import mesh as mesh_mod

            mult = int(dict(mesh.shape).get(mesh_mod.DATA_AXIS, 1))
            if self.batch_size % mult:
                # fail fast HERE (one rule site, CLI and API alike):
                # run_pipeline's pad_to would otherwise reject every
                # batch at runtime and poison the whole queue
                raise ValueError(
                    f"batch_size={self.batch_size} must be a multiple "
                    f"of the mesh data axis ({mult}) — the padded "
                    "batch is the compiled signature")
        self.max_wait_s = float(max_wait_s)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.mesh = mesh
        self.async_exec = bool(async_exec)
        # catalog bucketing: partial flushes pad to the nearest
        # batch-ladder rung (a `warmup --catalog` signature) instead of
        # the full batch_size — same results (mask-invalid pad lanes),
        # less pad waste, still zero tracing on a warmed worker.
        # Results are byte-identical either way, so the flag is a
        # WORKER knob, never part of job identity (queue.cfg_signature
        # strips it defensively).
        self.bucket = bool(bucket)
        self.runner = runner if runner is not None else pipeline_runner
        # `simulate`-job executor (injectable for tests, like runner)
        self.synth_runner = (synth_runner if synth_runner is not None
                             else synthetic_runner)
        # `infer`-job executor (ISSUE 18; injectable like synth_runner)
        self.infer_runner = (infer_runner if infer_runner is not None
                             else infer_job_runner)
        # `search`-job executor (ISSUE 19; injectable like the others)
        self.search_runner = (search_runner if search_runner is not None
                              else search_job_runner)
        self.worker_id = worker_id or f"{os.uname().nodename}:{os.getpid()}"
        self.batcher = DynamicBatcher(batch_size=self.batch_size,
                                      max_wait_s=self.max_wait_s,
                                      bucket=self.bucket, multiple=mult)
        self.log = get_logger()
        self.stats = {"batches": 0, "jobs_done": 0, "jobs_failed": 0,
                      "job_retries": 0, "job_transient_retries": 0,
                      "lanes_filled": 0, "lanes_total": 0,
                      "segment_flushes": 0, "rows_flushed": 0,
                      "stream_ticks": 0}
        # registered live-feed sessions (`stream` job kind — ISSUE 15):
        # job_id -> _StreamState; polled between batch claims, released
        # back to the queue on drain/idle exit
        self._streams: dict[str, "_StreamState"] = {}
        # QoS claim weighting (ISSUE 13): per-cycle lane budgets passed
        # to JobQueue.claim (None = the queue's documented defaults)
        self.lane_budgets = dict(lane_budgets) if lane_budgets else None
        # warm-affinity signal: the job signatures this worker has
        # EXECUTED (published in each heartbeat as `warm_sigs`; the
        # pool controller folds them into claim hints); insertion-
        # ordered so the hints cap keeps the newest
        self._warm_sigs: dict[str, None] = {}
        # pool-controller claim hints (control/hints.json), mtime-gated
        self._hints = None
        self._hints_stamp = None
        # reaper re-pin (ISSUE 17): feed path -> reap stamp for stream
        # jobs THIS worker requeued off an expired lease — folded into
        # the claim hints as self-pins so the dead worker's feeds land
        # here (the reaper already proved it is alive and polling)
        self._reaped_pins: dict[str, float] = {}
        # set once the worker has handed its registered feeds back
        # (drain/exit): the next forced heartbeat advertises it, so
        # the pool controller drops this worker's pins immediately
        self._draining = False
        # SLO & alerting plane (obs/slo.py — ISSUE 16): armed only when
        # the queue dir declares objectives (slo.json / SCINT_SLOS);
        # every hot-path hook below is behind one `is not None` check,
        # so an undeclared queue pays a single flag test
        self._slo = None
        self._slo_engine = None
        self._slo_stamp = None
        self._slo_traces: dict[str, str] = {}
        self._reload_slos()
        # fleet liveness: one atomically-overwritten snapshot file per
        # worker under <queue>/heartbeat/ (obs/fleet.py; heartbeat_s=0
        # disables).  Written by run()'s loop — counters/hists inside
        # are whatever the obs registry holds (empty when untraced;
        # pid/last-claim liveness works regardless).
        self._last_claim_at: float | None = None
        # single-shot flight-dump latch: a SIGTERM handler that dumps
        # and then raises must not dump AGAIN from the crash handler
        self._flight_dumped = False
        self.heartbeat = (HeartbeatWriter(
            os.path.join(queue.dir, HEARTBEAT_DIRNAME), self.worker_id,
            interval_s=heartbeat_s) if heartbeat_s and heartbeat_s > 0
            else None)

    # -- one scheduling round ----------------------------------------------
    def _load_hints(self):
        """The pool controller's claim hints for THIS worker, re-parsed
        only when ``control/hints.json`` changes (one stat per poll;
        absent file = unhinted claim, zero further cost), plus this
        worker's own REAPED-feed pins merged in (reaper re-pin works
        with or without a controller writing hints)."""
        from . import pool
        from .queue import ClaimHints

        path = pool.hints_path(self.queue.dir)
        try:
            st = os.stat(path)
        except OSError:
            self._hints = None
            self._hints_stamp = None
        else:
            stamp = (st.st_mtime_ns, st.st_size)
            if stamp != self._hints_stamp:
                self._hints_stamp = stamp
                self._hints = pool.claim_hints_for(pool.read_hints(
                    self.queue.dir), self.worker_id)
        hints = self._hints
        if self._reaped_pins:
            mine = frozenset(self._reaped_pins)
            base = hints if hints is not None else ClaimHints()
            # a reaped feed is pinned HERE even if a stale hints file
            # still lists the dead worker: the reap is newer evidence
            hints = dataclasses.replace(
                base, pinned=base.pinned | mine,
                pinned_elsewhere=base.pinned_elsewhere - mine)
        return hints

    def _reload_slos(self) -> None:
        """Arm/refresh the SLO plane when ``<queue>/slo.json`` changes
        (one stat per heartbeat, the ``_load_hints`` stamp pattern;
        ``SCINT_SLOS`` alone can arm it at startup).  A malformed
        registry logs and disarms — judgment is optional, serving is
        not."""
        from ..obs import slo as slo_mod

        try:
            st = os.stat(slo_mod.slo_path(self.queue.dir))
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            stamp = ()
        if stamp == self._slo_stamp:
            return
        self._slo_stamp = stamp
        try:
            specs = slo_mod.load_slos(self.queue.dir)
        except ValueError as e:
            log_event(self.log, "slo_load_failed", error=repr(e))
            specs = []
        if specs:
            self._slo = slo_mod.SloEvaluator(specs)
            self._slo_engine = slo_mod.AlertEngine(self.queue.results)
        else:
            self._slo = None
            self._slo_engine = None

    def _slo_tick(self, now: float | None = None) -> dict | None:
        """One evaluator step: sample the live histogram registry,
        advance the durable alert machines, and return the heartbeat
        snapshot (window deltas — the fleet's associative fold input).
        None when the plane is disarmed."""
        if self._slo is None:
            return None
        now = time.time() if now is None else now
        self._slo.observe(obs.get_registry().hists(), now)
        statuses = self._slo.statuses(now)
        try:
            self._slo_engine.step(statuses, now,
                                  trace_ids=self._slo_traces)
        except OSError as e:  # fault-ok: judgment must not kill serving
            log_event(self.log, "slo_step_failed", error=repr(e))
        return self._slo.wire(now)

    def poll_once(self, now: float | None = None,
                  force_flush: bool = False, claim: bool = True) -> int:
        """Reap -> claim -> load -> batch -> execute.  Returns the
        number of batches executed this round.  An injected ``now``
        (tests/replay) drives EVERY clock read in the round, flush
        deadlines included; live runs re-read the wall clock at flush
        so epoch-load time counts toward a partial bucket's wait.
        ``claim=False`` (the per-worker drain path) skips reap+claim
        and only flushes/executes what the batcher already holds."""
        injected = now is not None
        now = time.time() if now is None else now
        jobs = []
        with obs.span("serve.poll"):
            if claim:
                requeued, poisoned = self.queue.reap_expired(now)
                self._count_retries(requeued, poisoned,
                                    reason="lease_expired")
                # a dead pinned worker's feeds re-pin to their REAPER:
                # the pins land in this round's _load_hints, so the
                # claim below takes the orphaned streams first
                self._repin_reaped(requeued, now)
                jobs = self.queue.claim(self.worker_id,
                                        n=self.batch_size,
                                        lease_s=self._claim_lease_s(),
                                        now=now,
                                        lane_budgets=self.lane_budgets,
                                        hints=self._load_hints())
            # counts() is listdir-only; status() would open and parse
            # every queued job file per poll just to discard its
            # oldest-age readout
            counts = self.queue.counts()
            obs.gauge("queue_depth", counts["queued"] + counts["leased"])
        if jobs:
            self._last_claim_at = now
        ran_synth = 0
        for job in jobs:
            obs.inc("serve_jobs_claimed")
            wait = round(max(now - job.submitted_at, 0.0), 6)
            obs.inc("queue_wait_s", wait)
            # the mergeable fleet form of the same quantity: heartbeat
            # snapshots ship this histogram, the rollup merges it —
            # the per-lane breakdown is the queue-wait SLO's series
            obs.observe("queue_wait_s", wait)
            obs.observe(f"queue_wait_s[{job.lane}]", wait)
            if self._slo is not None and job.trace_id:
                self._slo_traces["queue_wait_s"] = job.trace_id
                self._slo_traces[f"queue_wait_s[{job.lane}]"] = \
                    job.trace_id
            if job.cfg.get("stream") is not None:
                # `stream` job kind (ISSUE 15): a live feed is not a
                # unit of work but a REGISTRATION — the session stays
                # resident and is polled between batch claims below
                self._register_stream(job)
                continue
            if job.cfg.get("backfill") is not None:
                # `backfill` job kind (ISSUE 17): a late-joined feed's
                # committed backlog, replayed through the chunked
                # batch path on the bulk lane — live streams keep
                # ticking between its chunks
                self._execute_backfill(job)
                ran_synth += 1
                continue
            if job.cfg.get("compact"):
                # `compact` job kind: results-plane maintenance —
                # merges small segment files; no epochs, no batcher
                self._execute_compact(job)
                ran_synth += 1
                continue
            if job.cfg.get("infer") is not None:
                # `infer` job kind (ISSUE 18): a gradient-inference
                # campaign — routed BEFORE the simulate check (its cfg
                # carries both payloads), executed directly like one
                self._execute_infer(job)
                ran_synth += 1
                continue
            if job.cfg.get("search") is not None:
                # `search` job kind (ISSUE 19): a matched-filter
                # acceleration search — routed BEFORE the simulate
                # check (its cfg carries both payloads), executed
                # directly like one
                self._execute_search(job)
                ran_synth += 1
                continue
            if job.cfg.get("synthetic") is not None:
                # `simulate` job kind: a campaign IS its own batch (the
                # compiled step's input is the key array) — never
                # coalesced with file-backed epochs, executed directly
                self._execute_synthetic(job)
                ran_synth += 1
                continue
            try:
                # trace_id attr makes the load span (and anything
                # nested under it) part of the job's distributed trace
                with obs.span("serve.load", file=job.file,
                              trace_id=job.trace_id, parent=job.span):
                    # chaos site: the injected fault classifies
                    # transient (real load errors — FileNotFoundError,
                    # parse failures — stay deterministic/unknown and
                    # keep the bounded-retry path)
                    faults.check("worker.load")
                    epoch = load_epoch(job.file,
                                       clean=bool(job.cfg.get("clean")))
            except PreflightError as e:
                # preflight quarantine: a structurally-bad epoch is
                # routed out with machine-readable reason codes BEFORE
                # it can NaN-poison a batch lane — deterministic, so
                # straight to failed/ with no retry budget burned
                # discovering it (counters emitted at the raise site)
                job = self.queue._hop(job, "job.preflight",
                                      reasons=",".join(e.reasons))
                state = self.queue.fail(job, str(e), retryable=False)
                if state == "failed":
                    self.stats["jobs_failed"] += 1
                    obs.inc("jobs_failed")
                log_event(self.log, "job_quarantined", job=job.id,
                          file=os.path.basename(job.file),
                          reasons=",".join(e.reasons), state=state)
                continue
            except Exception as e:
                self._job_failed(job, f"load failed: {e!r}", exc=e)
                continue
            self.batcher.add(job, epoch, now)
        drain = self.queue.drain_requested()
        batches = self.batcher.pop_ready(now if injected else time.time(),
                                         force=force_flush or drain)
        for batch in batches:
            self._execute(batch)
        # registered live feeds tick between batch claims (ISSUE 15)
        ran_stream = self._poll_streams(now if injected else None)
        return len(batches) + ran_synth + ran_stream

    def _claim_lease_s(self) -> float:
        # the lease must cover the batcher's wait AND one execution
        return self.lease_s + self.max_wait_s

    def _count_retries(self, requeued, poisoned, reason: str) -> None:
        for job in requeued:
            self.stats["job_retries"] += 1
            obs.inc("job_retries")
            log_event(self.log, "job_requeued", job=job.id,
                      attempts=job.attempts, reason=reason)
        for job in poisoned:
            self.stats["jobs_failed"] += 1
            obs.inc("jobs_failed")
            log_event(self.log, "job_poisoned", job=job.id,
                      attempts=job.attempts, error=job.error)

    def _repin_reaped(self, requeued, now: float) -> None:
        """Pin every stream job THIS worker just requeued off an
        expired lease to itself (ISSUE 17): a dead pinned worker's
        feed state is gone, the replay has to land SOMEWHERE alive,
        and the reaper is — by construction — alive and polling.  The
        self-pin is short-lived (:data:`REAPED_PIN_TTL_S`): once
        claimed it turns into a real registration (the heartbeat's
        ``streams`` payload re-pins it through the controller), and an
        unclaimed one falls back to the open pool."""
        changed = False
        for job in requeued:
            feed = stream_feed_of(job)
            if feed is not None:
                self._reaped_pins[feed] = now
                changed = True
                log_event(self.log, "stream_repinned", job=job.id,
                          feed=feed, worker=self.worker_id)
        if changed or self._reaped_pins:
            for feed, ts in list(self._reaped_pins.items()):
                if now - ts > REAPED_PIN_TTL_S:
                    del self._reaped_pins[feed]

    def _job_failed(self, job, error: str, exc=None) -> None:
        """Route a job failure through the error taxonomy
        (faults.classify_error): transient infra faults requeue WITHOUT
        burning the bounded retry budget; poison/unknown keep the
        existing bounded-retry -> ``failed/`` path."""
        transient = (exc is not None
                     and faults.classify_error(exc) == "transient")
        # mirror of queue.fail's budget-free condition: once a job has
        # exhausted max_transients, a transient-classified failure
        # ESCALATES to the attempts-burning path and must be counted/
        # logged as such — an operator watching job_transient_retries
        # vs job_retries has to see the escalation happen
        budget_free = (transient
                       and job.transients < self.queue.max_transients)
        state = self.queue.fail(job, error, transient=transient)
        if state == "done":
            # completed by another worker under the at-least-once race;
            # the stale local failure is dropped, nothing to count
            return
        if state == "failed":
            self.stats["jobs_failed"] += 1
            obs.inc("jobs_failed")
            log_event(self.log, "job_poisoned", job=job.id, error=error)
        elif budget_free:
            self.stats["job_transient_retries"] += 1
            obs.inc("job_transient_retries")
            log_event(self.log, "job_requeued_transient", job=job.id,
                      error=error)
        else:
            self.stats["job_retries"] += 1
            obs.inc("job_retries")
            log_event(self.log, "job_requeued", job=job.id, error=error)

    def _execute(self, batch: Batch) -> None:
        from ..io.results import row_fit_values

        import numpy as np

        n = len(batch.jobs)
        # the padded compiled signature this flush executes: the full
        # batch_size, or — under catalog bucketing — the batcher's
        # chosen ladder rung (batcher.Batch.pad_to)
        pad = batch.pad_to or self.batch_size
        # long compiles must not outlive the claim lease mid-execution
        self.queue.renew(batch.jobs, self._claim_lease_s())
        obs.gauge("batch_fill_ratio", round(batch.fill_ratio, 4))
        obs.inc("serve_batches")
        obs.inc("serve_lanes_filled", n)
        obs.inc("serve_lanes_total", pad)
        self.stats["batches"] += 1
        self.stats["lanes_filled"] += n
        self.stats["lanes_total"] += pad
        jobs = batch.jobs
        try:
            # the batch span carries EVERY member's trace id (one span,
            # N jobs), so the pipeline.* / *.step.compile/execute spans
            # nested under it reassemble into each member's trace; each
            # job also records a "job.batch" hop chaining its claim hop
            # to this execution
            tids = [j.trace_id for j in jobs if j.trace_id]
            with obs.span("serve.batch", jobs=n,
                          fill=round(batch.fill_ratio, 4),
                          trace_ids=tids) as bsp:
                if obs.enabled():
                    jobs = tuple(self.queue._hop(
                        j, "job.batch", lanes=n, pad=pad,
                        batch_span=getattr(bsp, "span_id", None))
                        for j in jobs)
                # chaos site: an infra fault mid-batch (device
                # preemption, OOM past the driver's backoff floor)
                faults.check("worker.batch_execute")
                # labeled device timeline: an --xprof capture shows
                # each served batch as a named region
                with trace_annotation("serve.batch"):
                    rows = self.runner(batch, pad, self.mesh,
                                       self.async_exec)
        except Exception as e:
            if faults.classify_error(e) == "transient":
                # infrastructure fault: EVERY member requeues without
                # burning its bounded retry budget, un-marked (the same
                # batch composition is expected to succeed on the next
                # attempt/worker — no reason to shatter it solo).  A
                # member already past max_transients ESCALATES to the
                # attempts-burning path (misclassified deterministic
                # error), so it goes solo like the non-transient branch
                # — otherwise the batch re-coalesces each round and
                # burns one attempt per member until ALL poison together
                for job in jobs:
                    if job.transients >= self.queue.max_transients:
                        job = dataclasses.replace(job, solo=True)
                    self._job_failed(job, f"batch transient: {e!r}",
                                     exc=e)
                log_event(self.log, "batch_transient", jobs=n,
                          error=repr(e))
                return
            # whole-batch failure (pipeline error): requeue every member
            # marked SOLO, so retries run as singleton batches — the
            # poison member exhausts its own budget alone and healthy
            # members complete alone instead of re-coalescing into the
            # same failing batch until all are poisoned together
            for job in jobs:
                self._job_failed(dataclasses.replace(job, solo=True),
                                 f"batch failed: {e!r}")
            log_event(self.log, "batch_failed", jobs=n, error=repr(e))
            return
        finished = []
        for job, row in zip(jobs, rows):
            fitvals = row_fit_values(row) if row is not None else []
            if row is None or (fitvals
                               and not np.all(np.isfinite(fitvals))):
                self._job_failed(job, "non-finite fit (NaN lane)")
                continue
            # buffered write-once row: the whole batch lands as ONE
            # segment at the flush below (O(flushes) files, not O(B))
            self.queue.results.put_new_buffered(job.id, row)
            finished.append((job, row))
        # rows must be DURABLE before their jobs complete: a crash
        # between complete() and a later flush would finalise jobs
        # whose rows never hit disk (the row would silently re-execute
        # under the done/ terminal-state guard — i.e. never)
        self._flush_rows()
        for job, row in finished:
            job = self.queue._hop(job, "job.row")
            self.queue.complete(job)
            self._mark_warm(job)
            self._job_latency(job)
            self.stats["jobs_done"] += 1
            obs.inc("jobs_done")
            log_event(self.log, "job_done", job=job.id,
                      file=os.path.basename(job.file),
                      tau=row.get("tau"),
                      eta=row.get("betaeta", row.get("eta")))

    def _job_latency(self, job, now: float | None = None) -> None:
        """Submit -> complete end-to-end wall seconds (total + the
        per-lane breakdown): the ``job_latency_s`` SLO's bucket-ladder
        series, observed once per completed job of any kind."""
        wall = time.time() if now is None else now
        lat = round(max(wall - job.submitted_at, 0.0), 6)
        obs.observe("job_latency_s", lat)
        obs.observe(f"job_latency_s[{job.lane}]", lat)
        if self._slo is not None and job.trace_id:
            self._slo_traces["job_latency_s"] = job.trace_id
            self._slo_traces[f"job_latency_s[{job.lane}]"] = \
                job.trace_id

    def _mark_warm(self, job) -> None:
        """Record an executed job's affinity signature — the
        `warm_sigs` heartbeat payload the pool controller folds into
        claim hints (insertion-ordered; re-execution refreshes a sig's
        recency).  Bounded to the controller's own newest-N cap: a
        long-lived worker on a heterogeneous queue must not grow its
        heartbeat (and every reader's parse) without bound."""
        from .pool import MAX_PREFER_SIGS

        if getattr(job, "sig", None):
            self._warm_sigs.pop(job.sig, None)
            self._warm_sigs[job.sig] = None
            while len(self._warm_sigs) > MAX_PREFER_SIGS:
                del self._warm_sigs[next(iter(self._warm_sigs))]

    def _flush_rows(self) -> int:
        """Flush the store's buffered rows as one sealed segment and
        keep the worker's own stats in step (the heartbeat payload for
        UNTRACED workers; the obs ``segment_flushes``/``segment_rows``
        counters are the traced source of truth and also count any
        size-triggered auto-flush inside a huge campaign)."""
        flushed = self.queue.results.flush()
        if flushed:
            self.stats["segment_flushes"] += 1
            self.stats["rows_flushed"] += flushed
        return flushed

    def _execute_synthetic(self, job) -> None:
        """Run one `simulate` job: the campaign executes as ONE
        zero-H2D generate→analyse step batch and lands
        ``n_epochs`` idempotent rows keyed ``<job_id>.<index>``.
        Failures route through the same taxonomy as batch failures
        (transient infra faults requeue budget-free)."""
        from ..sim.campaign import spec_from_dict, synth_row_key

        spec_dict = job.cfg["synthetic"]
        try:
            n_epochs = int(spec_from_dict(spec_dict).n_epochs)
        except Exception as e:
            # a torn/invalid payload is deterministic poison
            state = self.queue.fail(job, f"bad synthetic spec: {e!r}",
                                    retryable=False)
            if state == "failed":
                self.stats["jobs_failed"] += 1
                obs.inc("jobs_failed")
            log_event(self.log, "job_poisoned", job=job.id,
                      error=f"bad synthetic spec: {e!r}")
            return
        obs.inc("serve_synth_jobs")
        # a campaign compiles+runs like a batch: keep the lease ahead
        self.queue.renew([job], self._claim_lease_s())
        self.stats["batches"] += 1
        try:
            with obs.span("serve.batch", jobs=1, synthetic=True,
                          epochs=n_epochs,
                          trace_ids=[t for t in (job.trace_id,) if t]
                          ) as bsp:
                if obs.enabled():
                    job = self.queue._hop(
                        job, "job.batch", synthetic=True,
                        batch_span=getattr(bsp, "span_id", None))
                # chaos site shared with file batches: an infra fault
                # mid-campaign classifies transient
                faults.check("worker.batch_execute")
                rows = self.synth_runner(spec_dict, job.cfg, self.mesh,
                                         self.async_exec, self.bucket)
        except Exception as e:
            # _job_failed classifies: transient infra faults requeue
            # budget-free, deterministic errors burn the bounded budget
            self._job_failed(job, f"synthetic campaign failed: {e!r}",
                             exc=e)
            log_event(self.log, "synth_job_failed", job=job.id,
                      error=repr(e))
            return
        stored = 0
        for i, row in enumerate(rows):
            if row is None:   # NaN lane: quarantined by the row builder
                continue
            # buffered: the campaign streams out in flush_rows-sized
            # segments (auto-flush bounds memory at 10^6 epochs), the
            # tail sealed below BEFORE the job completes
            self.queue.results.put_new_buffered(synth_row_key(job.id, i),
                                                row)
            stored += 1
        self._flush_rows()
        obs.inc("serve_synth_rows", stored)
        job = self.queue._hop(job, "job.row", rows=stored)
        self.queue.complete(job)
        self._mark_warm(job)
        self._job_latency(job)
        self.stats["jobs_done"] += 1
        obs.inc("jobs_done")
        log_event(self.log, "synth_job_done", job=job.id,
                  epochs=n_epochs, rows=stored,
                  quarantined=n_epochs - stored)

    def _execute_infer(self, job) -> None:
        """Run one `infer` job (ISSUE 18): the gradient-inference
        campaign executes as ONE forward+backward device program and
        lands ``n_epochs`` idempotent rows keyed ``<job_id>.<index>``
        (the simulate-job storage contract; failures route through the
        same taxonomy)."""
        from ..infer import infer_from_dict
        from ..sim.campaign import spec_from_dict, synth_row_key

        spec_dict = job.cfg.get("synthetic")
        infer_dict = job.cfg.get("infer")
        try:
            n_epochs = int(spec_from_dict(spec_dict).n_epochs)
            infer_from_dict(infer_dict)
        except Exception as e:
            # a torn/invalid payload is deterministic poison
            state = self.queue.fail(job, f"bad infer payload: {e!r}",
                                    retryable=False)
            if state == "failed":
                self.stats["jobs_failed"] += 1
                obs.inc("jobs_failed")
            log_event(self.log, "job_poisoned", job=job.id,
                      error=f"bad infer payload: {e!r}")
            return
        obs.inc("infer_jobs")
        # the MAP loop compiles+runs like a batch: keep the lease ahead
        self.queue.renew([job], self._claim_lease_s())
        self.stats["batches"] += 1
        try:
            with obs.span("serve.batch", jobs=1, infer=True,
                          epochs=n_epochs,
                          trace_ids=[t for t in (job.trace_id,) if t]
                          ) as bsp:
                if obs.enabled():
                    job = self.queue._hop(
                        job, "job.batch", infer=True,
                        batch_span=getattr(bsp, "span_id", None))
                # chaos site shared with file batches: an infra fault
                # mid-campaign classifies transient
                faults.check("worker.batch_execute")
                rows = self.infer_runner(spec_dict, infer_dict, job.cfg,
                                         self.mesh, self.async_exec,
                                         self.bucket)
        except Exception as e:
            # _job_failed classifies: transient infra faults requeue
            # budget-free, deterministic errors burn the bounded budget
            self._job_failed(job, f"infer campaign failed: {e!r}",
                             exc=e)
            log_event(self.log, "infer_job_failed", job=job.id,
                      error=repr(e))
            return
        stored = 0
        for i, row in enumerate(rows):
            if row is None:   # NaN lane: quarantined by the row builder
                continue
            self.queue.results.put_new_buffered(synth_row_key(job.id, i),
                                                row)
            stored += 1
        self._flush_rows()
        obs.inc("serve_synth_rows", stored)
        job = self.queue._hop(job, "job.row", rows=stored)
        self.queue.complete(job)
        self._mark_warm(job)
        self._job_latency(job)
        self.stats["jobs_done"] += 1
        obs.inc("jobs_done")
        log_event(self.log, "infer_job_done", job=job.id,
                  epochs=n_epochs, rows=stored,
                  quarantined=n_epochs - stored)

    def _execute_search(self, job) -> None:
        """Run one `search` job (ISSUE 19): the acceleration search
        executes as ONE fused correlation program against the resident
        template bank and lands ``n_epochs`` idempotent candidate rows
        keyed ``<job_id>.<index>`` (the simulate-job storage contract;
        failures route through the same taxonomy)."""
        from ..search import search_from_dict
        from ..sim.campaign import spec_from_dict, synth_row_key

        spec_dict = job.cfg.get("synthetic")
        search_dict = job.cfg.get("search")
        try:
            n_epochs = int(spec_from_dict(spec_dict).n_epochs)
            search_from_dict(search_dict)
        except Exception as e:
            # a torn/invalid payload is deterministic poison
            state = self.queue.fail(job, f"bad search payload: {e!r}",
                                    retryable=False)
            if state == "failed":
                self.stats["jobs_failed"] += 1
                obs.inc("jobs_failed")
            log_event(self.log, "job_poisoned", job=job.id,
                      error=f"bad search payload: {e!r}")
            return
        obs.inc("search_jobs")
        # bank build + correlation compile+run like a batch: keep the
        # lease ahead
        self.queue.renew([job], self._claim_lease_s())
        self.stats["batches"] += 1
        try:
            with obs.span("serve.batch", jobs=1, search=True,
                          epochs=n_epochs,
                          trace_ids=[t for t in (job.trace_id,) if t]
                          ) as bsp:
                if obs.enabled():
                    job = self.queue._hop(
                        job, "job.batch", search=True,
                        batch_span=getattr(bsp, "span_id", None))
                # chaos site shared with file batches: an infra fault
                # mid-campaign classifies transient
                faults.check("worker.batch_execute")
                rows = self.search_runner(spec_dict, search_dict,
                                          job.cfg, self.mesh,
                                          self.async_exec, self.bucket)
        except Exception as e:
            # _job_failed classifies: transient infra faults requeue
            # budget-free, deterministic errors burn the bounded budget
            self._job_failed(job, f"search campaign failed: {e!r}",
                             exc=e)
            log_event(self.log, "search_job_failed", job=job.id,
                      error=repr(e))
            return
        stored = 0
        for i, row in enumerate(rows):
            if row is None:   # NaN lane: quarantined by the row builder
                continue
            self.queue.results.put_new_buffered(synth_row_key(job.id, i),
                                                row)
            stored += 1
        self._flush_rows()
        obs.inc("serve_synth_rows", stored)
        job = self.queue._hop(job, "job.row", rows=stored)
        self.queue.complete(job)
        self._mark_warm(job)
        self._job_latency(job)
        self.stats["jobs_done"] += 1
        obs.inc("jobs_done")
        log_event(self.log, "search_job_done", job=job.id,
                  epochs=n_epochs, rows=stored,
                  quarantined=n_epochs - stored)

    # -- the `stream` job kind (ISSUE 15) ----------------------------------
    def _stream_meta(self, job_id: str) -> str:
        return f"stream.{job_id}"

    def _register_stream(self, job) -> None:
        """Claiming a `stream` job REGISTERS its feed: the session
        stays resident (polled by :meth:`_poll_streams` between batch
        claims) until the feed finalizes and the job completes.  A
        durable cursor (``meta.stream.<job>`` in the results store,
        written only after each tick batch's flush) resumes a crashed
        or re-claimed registration from the feed manifest with no
        duplicate and no lost versioned rows."""
        if job.id in self._streams:
            # duplicate claim of an already-registered feed (the
            # at-least-once lease window): one session is enough
            self.queue.renew([job], self._claim_lease_s())
            return
        from ..stream import StreamSession

        obs.inc("serve_stream_jobs")
        spec = job.cfg["stream"]
        try:
            session = StreamSession(
                spec["feed"], job.cfg, window=spec["window"],
                hop=spec["hop"],
                incremental=bool(spec.get("incremental", False)),
                resync_every=spec.get("resync_every"))
        except Exception as e:
            # a vanished feed / torn manifest classifies through the
            # taxonomy (FeedError = ValueError = poison; transient IO
            # keeps its budget-free path)
            self._job_failed(job, f"stream register failed: {e!r}",
                             exc=e)
            return
        meta = self.queue.results.get_meta(self._stream_meta(job.id))
        if meta:
            try:
                session.restore(meta)
            except Exception as e:  # fault-ok: a corrupt cursor only
                # costs a from-scratch replay, never the stream
                log_event(self.log, "stream_restore_failed",
                          job=job.id, error=repr(e))
        else:
            # fresh registration: a deep backlog catches up through
            # the bulk backfill lane instead of replaying live
            self._maybe_backfill(job, session, spec)
        self._streams[job.id] = _StreamState(job=job, session=session,
                                             last_renew=time.time())
        if self._slo is not None and job.trace_id:
            # freshness alerts on this feed link back to the stream
            # job's distributed trace
            self._slo_traces[f"stream_lag_s[{session.name}]"] = \
                job.trace_id
        log_event(self.log, "stream_registered", job=job.id,
                  feed=session.name, window=session.window,
                  hop=session.hop, resumed=bool(meta))

    def _poll_streams(self, now: float | None = None) -> int:
        """Advance every registered feed: consume newly committed
        chunks, run due ticks, publish each tick's eta/tau/dnu as
        VERSIONED rows (history key per window end + a `.live` key the
        monitoring consumer polls), flush, THEN persist the resume
        cursor — the durability order that makes crash replay
        idempotent.  Returns the tick count (the worker's idle logic
        treats ticks as work)."""
        if not self._streams:
            return 0
        wall = time.time() if now is None else now
        ran = 0
        for jid, st in list(self._streams.items()):
            job = st.job
            if wall - st.last_renew > self.lease_s / 2.0:
                # the registration outlives any one poll: keep the
                # lease ahead so a live stream is never reaped from
                # under its own worker
                self.queue.renew([job], self._claim_lease_s())
                st.last_renew = wall
            try:
                rows = st.session.poll()
            except Exception as e:
                self._streams.pop(jid, None)
                self._job_failed(job, f"stream poll failed: {e!r}",
                                 exc=e)
                log_event(self.log, "stream_poll_failed", job=jid,
                          error=repr(e))
                continue
            if rows:
                # a tick batch may have included the first (compiling)
                # tick: re-arm the lease right after the long work, so
                # the next reap pass finds it fresh (the lease, like
                # the batch contract, must be sized to cover one tick)
                self.queue.renew([job], self._claim_lease_s())
                st.last_renew = time.time() if now is None else now
                for row in rows:
                    key = f"{jid}.w{int(row['window_end']):09d}"
                    self.queue.results.put_versioned(key, row,
                                                     series=jid)
                    self.queue.results.put_versioned(f"{jid}.live",
                                                     row, series=jid)
                self._flush_rows()
                self.queue.results.put_meta(self._stream_meta(jid),
                                            st.session.state())
                st.job = job = self.queue._hop(
                    job, "job.tick", ticks=len(rows),
                    window_end=int(rows[-1]["window_end"]))
                ran += len(rows)
                self.stats["stream_ticks"] += len(rows)
            if st.session.complete:
                job = self.queue._hop(job, "job.row",
                                      rows=st.session.tick_seq)
                self.queue.complete(job)
                self._mark_warm(job)
                self._job_latency(job, now=wall)
                self._streams.pop(jid, None)
                self.stats["jobs_done"] += 1
                obs.inc("jobs_done")
                log_event(self.log, "stream_job_done", job=jid,
                          feed=st.session.name,
                          ticks=st.session.tick_seq,
                          quarantined=sum(
                              st.session.quarantined.values()))
        return ran

    def _release_streams(self, reason: str = "exit") -> None:
        """Hand every registered (unfinished) stream back to the queue
        with its budget untouched (``JobQueue.release``) so the next
        worker resumes it from the durable cursor — the scale-down/
        idle-exit path.  A crash skips this; lease expiry + the cursor
        cover that case identically."""
        for jid, st in list(self._streams.items()):
            try:
                self.queue.results.put_meta(self._stream_meta(jid),
                                            st.session.state())
            except OSError:  # fault-ok: replay covers a lost cursor
                pass
            self.queue.release(st.job)
            log_event(self.log, "stream_released", job=jid,
                      reason=reason)
        if self._streams:
            # advertise the hand-back: the next (forced) heartbeat
            # carries draining=true + an empty streams payload, so the
            # pool controller drops this worker's pins and a survivor
            # re-pins the feeds instead of deferring to a ghost
            self._draining = True
        self._streams.clear()

    def _maybe_backfill(self, job, session, spec: dict) -> None:
        """Late-joining feed (ISSUE 17): when the already-committed
        backlog holds at least :data:`BACKFILL_MIN_TICKS` live-cadence
        ticks, enqueue ONE bulk backfill job for the history (chunked
        batch replay, versioned rows on the live job's keys) and
        fast-forward the live session past it — registration-to-first-
        live-row latency stays O(window), not O(backlog).  The newest
        hop stays live so the feed publishes immediately.  Submission
        failure degrades to the old behaviour (live replay)."""
        from ..stream.window import backfill_tick_ends

        upto = session.reader.total_samples - session.hop
        if upto < session.window:
            return
        ends = [e for e in backfill_tick_ends(
            session.reader, session.window, session.hop, upto)
            if e[0] > session.consumed]
        if len(ends) < BACKFILL_MIN_TICKS:
            return
        try:
            bf_id, state = self.queue.submit_backfill(
                spec["feed"], cfg=dict(job.cfg),
                window=session.window, hop=session.hop, upto=upto,
                parent=job.id)
        except Exception as e:  # fault-ok: the live path replays
            log_event(self.log, "backfill_submit_failed", job=job.id,
                      error=repr(e))
            return
        session.skip_ticks_until(upto)
        obs.inc("backfill_jobs")
        log_event(self.log, "backfill_submitted", job=bf_id,
                  parent=job.id, feed=session.name, ticks=len(ends),
                  upto=upto, state=state)

    def _execute_backfill(self, job) -> None:
        """Run one `backfill` job: replay every live-cadence window of
        the feed's committed prefix (``<= upto``) through the CHUNKED
        batch path, publishing the same versioned row per window-end
        key the live session would have (``parent`` = the live stream
        job whose row keys/series this catch-up fills in).  Registered
        live streams tick BETWEEN chunks, so catch-up throughput never
        buys head-of-line live latency."""
        import numpy as np

        from ..data import DynspecData
        from ..io.results import batch_lane_row
        from ..parallel import run_pipeline
        from ..parallel.driver import stage_dtype
        from ..stream.ingest import FeedReader
        from ..stream.window import (backfill_tick_ends,
                                     read_feed_window, stream_row_base)

        spec = job.cfg["backfill"]
        self.queue.renew([job], self._claim_lease_s())
        try:
            reader = FeedReader(spec["feed"])
            window, hop = int(spec["window"]), int(spec["hop"])
            upto = int(spec.get("upto", 0))
            parent = spec.get("parent")
            opts = {k: v for k, v in job.cfg.items()
                    if k != "backfill"}
            cfg = config_from_opts(opts)
            cfg.validate()
            ends = backfill_tick_ends(reader, window, hop, upto)
        except Exception as e:
            self._job_failed(job, f"backfill setup failed: {e!r}",
                             exc=e)
            return
        obs.inc("serve_backfill_jobs")
        dtype = np.dtype(stage_dtype(cfg.precision))
        series = str(parent) if parent else job.id
        group_n = max(int(self.batch_size), 1)
        done = 0
        self.stats["batches"] += 1
        try:
            with obs.span("serve.backfill", feed=reader.name,
                          ticks=len(ends),
                          trace_ids=[t for t in (job.trace_id,) if t]
                          ) as bsp:
                if obs.enabled():
                    job = self.queue._hop(
                        job, "job.batch", backfill=True,
                        ticks=len(ends),
                        batch_span=getattr(bsp, "span_id", None))
                for i in range(0, len(ends), group_n):
                    group = ends[i:i + group_n]
                    epochs = [DynspecData(
                        dyn=read_feed_window(reader, end, window,
                                             dtype).astype(np.float64),
                        freqs=reader.freqs(),
                        times=reader.times(window),
                        mjd=float(reader.manifest.get("mjd", 50000.0)),
                        name=f"{reader.name}@w{end}")
                        for end, _tick in group]
                    for idx, res in run_pipeline(epochs, cfg,
                                                 async_exec=False):
                        for lane, ei in enumerate(np.asarray(idx)):
                            end, tick = group[int(ei)]
                            row = stream_row_base(reader, window,
                                                  reader.dt, end,
                                                  tick, final=False)
                            row["backfill"] = True
                            row.update(batch_lane_row(res, lane,
                                                      cfg.lamsteps))
                            self.queue.results.put_versioned(
                                f"{series}.w{end:09d}", row,
                                series=series)
                            done += 1
                    self._flush_rows()
                    self.queue.renew([job], self._claim_lease_s())
                    # live feeds keep their latency budget: one stream
                    # poll between backlog chunks
                    self._poll_streams()
        except Exception as e:
            self._job_failed(job, f"backfill failed: {e!r}", exc=e)
            log_event(self.log, "backfill_failed", job=job.id,
                      error=repr(e))
            return
        job = self.queue._hop(job, "job.row", rows=done)
        self.queue.complete(job)
        self._mark_warm(job)
        self._job_latency(job)
        self.stats["jobs_done"] += 1
        obs.inc("jobs_done")
        log_event(self.log, "backfill_done", job=job.id,
                  feed=reader.name, rows=done, upto=upto)

    def _execute_compact(self, job) -> None:
        """Run one `compact` job: merge the results store's small
        segment files into one (utils/segments).  Idempotent and
        row-less — a compaction finding nothing to merge completes
        with ``compacted=0``.  Failures route through the same
        taxonomy as batch failures."""
        self.queue.renew([job], self._claim_lease_s())
        self.stats["batches"] += 1
        try:
            with obs.span("serve.compact",
                          trace_ids=[t for t in (job.trace_id,) if t]
                          ) as bsp:
                if obs.enabled():
                    job = self.queue._hop(
                        job, "job.batch", compact=True,
                        batch_span=getattr(bsp, "span_id", None))
                stats = self.queue.results.compact()
        except Exception as e:
            self._job_failed(job, f"compact failed: {e!r}", exc=e)
            return
        self.queue.complete(job)
        self._job_latency(job)
        self.stats["jobs_done"] += 1
        obs.inc("jobs_done")
        log_event(self.log, "compact_done", job=job.id, **stats)

    # -- flight recorder + signal diagnostics ------------------------------
    def _dump_flight(self, error: str, classification: str | None = None,
                     extra: dict | None = None) -> str | None:
        """Single-shot guarded flight dump: the obs event ring + a
        classified header land beside the queue exactly ONCE per
        worker life (a SIGTERM handler that dumps and then raises must
        not dump again from the crash handler).  The dump itself is
        guarded — crashes correlate with exactly the IO failures
        (deleted queue dir, full disk) that would make the dump raise,
        and the recorder must never REPLACE the error it explains."""
        if self._flight_dumped:
            return None
        self._flight_dumped = True
        try:
            return obs.dump_flight(
                os.path.join(self.queue.dir, FLIGHT_DIRNAME),
                error=error, classification=classification,
                extra={"worker": self.worker_id,
                       "stats": dict(self.stats), **(extra or {})})
        except Exception as dump_err:  # fault-ok: recorder only
            return f"flight dump failed: {dump_err!r}"

    def _install_signal_dump(self):
        """Dump a flight record on SIGTERM/SIGINT too (ISSUE 12
        satellite): a politely stopped worker must leave the same
        diagnostics as a crashed one — graceful drains are where
        operators look FIRST when a fleet misbehaves.  The handler
        dumps once (the latch guards signal-then-raise double dumps)
        and then takes the polite exit: KeyboardInterrupt for SIGINT
        (the CLI's existing graceful path), SystemExit(128+sig) for
        SIGTERM, so ``finally`` blocks (final heartbeat) still run.
        Returns a restore callable; degrades to a no-op off the main
        thread, where ``signal.signal`` is unavailable."""
        import signal as signal_mod

        previous: dict = {}

        def handler(signum, frame):
            name = signal_mod.Signals(signum).name
            path = self._dump_flight(f"signal: {name}",
                                     classification="signal")
            if path is not None:
                log_event(self.log, "worker_signal",
                          worker=self.worker_id, signal=name,
                          flight=path)
            if signum == signal_mod.SIGINT:
                raise KeyboardInterrupt
            raise SystemExit(128 + signum)

        try:
            for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
                previous[sig] = signal_mod.signal(sig, handler)
        except ValueError:  # fault-ok: not the main thread
            for sig, prev in previous.items():
                signal_mod.signal(sig, prev)
            return lambda: None

        def restore():
            for sig, prev in previous.items():
                try:
                    signal_mod.signal(sig, prev)
                except ValueError:  # fault-ok: thread moved under us
                    pass
        return restore

    # -- the resident loop -------------------------------------------------
    def run(self, max_batches: int | None = None,
            exit_on_drain: bool = True,
            idle_exit_s: float | None = None) -> dict:
        """Serve until told to stop.  Exit conditions: ``max_batches``
        executed; a drain request with the queue empty and no pending
        partial batches (``exit_on_drain``); or ``idle_exit_s`` with no
        work arriving.  Returns the worker's stats dict."""
        log_event(self.log, "serve_start", worker=self.worker_id,
                  batch=self.batch_size, max_wait_s=self.max_wait_s,
                  lease_s=self.lease_s, queue=self.queue.dir)
        idle_since = None
        restore_signals = self._install_signal_dump()
        try:
            while True:
                self._beat()
                # chaos site (kind="error"): an unhandled crash of the
                # resident loop itself — proves the flight-recorder
                # dump below actually fires (docs/reliability.md)
                faults.check("worker.poll")
                if self.queue.worker_drain_requested(self.worker_id):
                    # pool scale-down (ISSUE 13): stop CLAIMING, flush
                    # and execute every batch we already hold (each
                    # claimed job completes or routes through the
                    # normal failure taxonomy — nothing is stranded),
                    # consume OUR marker, exit.  Other workers keep
                    # serving; the global drain marker is untouched.
                    while self.batcher.pending:
                        self.poll_once(force_flush=True, claim=False)
                    # live feeds hand back to the queue (budget
                    # untouched) so a surviving worker resumes them
                    self._release_streams(reason="worker_drain")
                    self.queue.clear_worker_drain(self.worker_id)
                    # the hand-back beat (draining=true, no streams):
                    # without it the released feeds stay pinned to
                    # this exiting worker until its heartbeat goes
                    # stale — exactly the stranding window the
                    # re-pin protocol exists to close
                    self._beat(force=True)
                    log_event(self.log, "worker_drained",
                              worker=self.worker_id)
                    break
                ran = self.poll_once()
                if ran:
                    idle_since = None
                    if max_batches is not None and \
                            self.stats["batches"] >= max_batches:
                        break
                    continue
                if self.batcher.pending:
                    # partial bucket waiting on its deadline: short sleep
                    time.sleep(min(self.poll_s, self.max_wait_s / 4 or
                                   self.poll_s))
                    continue
                if exit_on_drain and self.queue.drain_requested() \
                        and self.queue.empty():
                    # CONSUME the drain request: a drain-then-start flow
                    # ("finish this queue and exit") must work, so the
                    # marker is honoured whenever present and cleared by
                    # the worker that completes it — the next serving
                    # session starts resident again
                    self.queue.clear_drain()
                    break
                now = time.time()
                idle_since = now if idle_since is None else idle_since
                if idle_exit_s is not None \
                        and now - idle_since >= idle_exit_s:
                    break
                time.sleep(self.poll_s)
            # any exit path that falls out of the loop releases the
            # registered (unfinished) streams: nothing stays leased
            # behind a politely-stopped worker
            self._release_streams()
        except Exception as e:
            # crash flight recorder: an UNHANDLED failure of the
            # resident loop (per-job failures never reach here) dumps
            # the obs event ring buffer + a classified header next to
            # the queue, so the fleet rollup can read the dead
            # worker's last moments; the error still propagates.  An
            # OOM crash additionally attaches a device-memory profile
            # snapshot (obs/devmem.memory_profile_dump — the live
            # HBM buffers at death, pprof-loadable), the answer to
            # "what was resident when it died".
            extra = {}
            if faults.is_oom_error(e):
                mp = devmem.memory_profile_dump(
                    os.path.join(self.queue.dir, FLIGHT_DIRNAME),
                    tag="oom")
                if mp is not None:
                    extra["memory_profile"] = mp
            path = self._dump_flight(
                repr(e), classification=faults.classify_error(e),
                extra=extra)
            log_event(self.log, "worker_crash", worker=self.worker_id,
                      error=repr(e), flight=path)
            raise
        finally:
            restore_signals()
            self._beat(force=True)
        log_event(self.log, "serve_exit", worker=self.worker_id,
                  **self.stats)
        return dict(self.stats)

    def _beat(self, force: bool = False) -> None:
        """Write a heartbeat snapshot if due (obs/fleet.py); heartbeat
        IO must never take the worker down — a full disk degrades to a
        log line, not a crash that poisons the queue's liveness."""
        # SLO evaluation rides the heartbeat cadence: reload-check the
        # registry (one stat), advance the alert machines, and stamp
        # the window-delta snapshot into this beat's extra payload
        self._reload_slos()
        slo_snapshot = self._slo_tick()
        if self.heartbeat is None:
            return
        try:
            # warm_sigs = the affinity signal the pool controller
            # routes on (empty until something has executed);
            # streams = the per-feed liveness payload the fleet
            # rollup's streams section renders
            extra = {}
            if self._warm_sigs:
                extra["warm_sigs"] = list(self._warm_sigs)
            if self._streams:
                extra["streams"] = {jid: st.session.stats()
                                    for jid, st in
                                    self._streams.items()}
            if self._draining:
                # released our feeds: the controller must drop this
                # worker's pins NOW, not at heartbeat staleness
                extra["draining"] = True
            if slo_snapshot is not None:
                extra["slo"] = slo_snapshot
            self.heartbeat.beat(force=force,
                                last_claim_at=self._last_claim_at,
                                stats=self.stats,
                                extra=extra or None)
        except OSError as e:  # fault-ok: liveness reporting only
            # counted, not just logged: a worker whose heartbeats are
            # silently failing (full disk, dead NFS) must surface in
            # `fleet status` as fsio_write_errors[heartbeat], not only
            # in its own local log
            obs.inc("fsio_write_errors")
            obs.inc("fsio_write_errors[heartbeat]")
            log_event(self.log, "heartbeat_failed", worker=self.worker_id,
                      error=repr(e))
