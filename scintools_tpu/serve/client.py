"""Filesystem-protocol client for the resident survey service.

No network dependency: the queue directory is the API, so any process
that can see the filesystem can submit work, poll status, collect
results, and drain the worker — the CLI verbs ``submit`` / ``status``
/ ``drain`` are thin wrappers over this class.
"""

from __future__ import annotations

import os
import time
from typing import Sequence

from .queue import DONE, FAILED, JobQueue


class SurveyClient:
    """Submit / wait / drain / export against a serve queue directory
    (everything a shell or notebook needs to drive a resident worker)."""

    def __init__(self, queue_dir: str):
        self.queue = JobQueue(queue_dir)

    # -- submission --------------------------------------------------------
    def submit(self, paths: Sequence[str], opts: dict | None = None,
               lane: str | None = None) -> list[dict]:
        """Submit epoch files for processing under ``opts`` (the
        estimator options a ``process --batched`` run would take).
        Idempotent per (file content, opts): re-submitting reports the
        existing state instead of duplicating.  A nonexistent path
        (typo, unexpanded glob) reports ``status="missing"`` with
        ``job=None`` instead of poisoning the queue.  ``lane`` picks
        the QoS lane (default interactive; scheduling only — never job
        identity).  Returns one record per path: ``{file, job,
        status}``."""
        opts = dict(opts or {})
        out = []
        for p in paths:
            if not os.path.exists(p):
                out.append({"file": p, "job": None, "status": "missing"})
                continue
            job_id, status = self.queue.submit(p, opts, lane=lane)
            out.append({"file": p, "job": job_id, "status": status})
        return out

    def submit_synthetic(self, spec: dict, opts: dict | None = None,
                         lane: str | None = None) -> dict:
        """Submit one on-device synthetic campaign (`simulate` job
        kind): ``spec`` is a sparse ``sim.campaign.spec_to_dict``
        payload (e.g. ``{"kind": "screen", "n_epochs": 1024}``),
        ``opts`` the estimator options.  Idempotent per (canonical
        spec, opts).  ``lane`` defaults to bulk — campaigns are the
        traffic the QoS lanes keep from starving live submits.
        Returns ``{spec, job, status}``."""
        job_id, status = self.queue.submit_synthetic(
            spec, dict(opts or {}), lane=lane)
        return {"spec": dict(spec), "job": job_id, "status": status}

    def submit_infer(self, spec: dict, infer: dict | None = None,
                     opts: dict | None = None,
                     lane: str | None = None) -> dict:
        """Submit one gradient-inference campaign (`infer` job kind,
        ISSUE 18): ``spec`` is the synthetic-campaign forward model,
        ``infer`` the sparse optimiser knobs
        (``scintools_tpu.infer.infer_to_dict``), ``opts`` the pipeline
        options the loss geometry derives from.  Idempotent per
        (canonical spec, canonical infer, opts) — a distinct identity
        from a plain simulate of the same campaign.  ``lane`` defaults
        to bulk.  Returns ``{spec, infer, job, status}``."""
        job_id, status = self.queue.submit_infer(
            spec, infer, dict(opts or {}), lane=lane)
        return {"spec": dict(spec), "infer": dict(infer or {}),
                "job": job_id, "status": status}

    def submit_search(self, spec: dict, search: dict | None = None,
                      opts: dict | None = None,
                      lane: str | None = None) -> dict:
        """Submit one acceleration-search campaign (`search` job kind,
        ISSUE 19): ``spec`` is the synthetic campaign whose epochs are
        scored, ``search`` the sparse bank/pruning knobs
        (``scintools_tpu.search.search_to_dict``), ``opts`` the
        pipeline options the spectrum derives from.  Idempotent per
        (canonical spec, canonical search, opts) — a distinct identity
        from the simulate and infer jobs of the same campaign.
        ``lane`` defaults to bulk.  Returns
        ``{spec, search, job, status}``."""
        job_id, status = self.queue.submit_search(
            spec, search, dict(opts or {}), lane=lane)
        return {"spec": dict(spec), "search": dict(search or {}),
                "job": job_id, "status": status}

    def compact(self) -> dict:
        """Submit one results-plane compaction (`compact` job kind):
        the worker merges small segment files into one so long
        campaigns keep bounded per-lookup segment counts.  Returns
        ``{job, status}``."""
        job_id, status = self.queue.submit_compact()
        return {"job": job_id, "status": status}

    def submit_stream(self, feed_dir: str, opts: dict | None = None,
                      window: int | None = None, hop: int | None = None,
                      lane: str | None = None,
                      incremental: bool | None = None,
                      resync_every: int | None = None) -> dict:
        """Register one live feed (`stream` job kind — ISSUE 15): the
        worker follows the append-mode feed directory between batch
        claims, re-fitting the last ``window`` time samples every
        ``hop`` new ones and publishing eta/tau/dnu per tick as
        VERSIONED rows — poll ``result(f"{job}.live")`` for the
        current values, or export the whole tracked series.  The job
        completes when the producer finalizes the feed.  Idempotent
        per (feed path, opts, window/hop and the incremental knobs
        when set).  ``incremental=True`` asks the worker for O(hop)
        sliding-update ticks with periodic exact resync every
        ``resync_every`` ticks (docs/streaming.md).  Returns ``{feed,
        job, status}``."""
        job_id, status = self.queue.submit_stream(
            feed_dir, dict(opts or {}), window=window, hop=hop,
            lane=lane, incremental=incremental,
            resync_every=resync_every)
        return {"feed": feed_dir, "job": job_id, "status": status}

    # -- inspection --------------------------------------------------------
    def status(self) -> dict:
        return self.queue.status()

    def result(self, job_id: str) -> dict | None:
        return self.queue.results.get(job_id)

    def wait(self, job_ids: Sequence[str], timeout: float = 60.0,
             poll_s: float = 0.2, poll_cap_s: float = 5.0) -> dict:
        """Block until every job is terminal (done or failed) or the
        timeout lapses.  Returns ``{done: [...], failed: [...],
        pending: [...]}``.

        Poll cadence backs off EXPONENTIALLY while nothing changes
        (x1.6 per idle tick, capped at ``poll_cap_s``, with ±25 %
        jitter so a fleet of waiting clients decorrelates instead of
        hammering the queue directory in lockstep) and snaps back to
        ``poll_s`` the moment any job goes terminal — a long idle
        campaign costs one directory walk per cap interval, while an
        actively-draining one is tracked at full resolution."""
        import random

        deadline = time.time() + timeout
        pending = list(job_ids)
        done: list[str] = []
        failed: list[str] = []
        delay = float(poll_s)
        while pending and time.time() < deadline:
            still = []
            # one queued-directory walk per tick answers "still queued"
            # for the whole pending set; state_of (whose stamped-name
            # fallback scans that directory per job) then only runs for
            # jobs in transit between state dirs
            queued = self.queue.queued_ids()
            for job_id in pending:
                if job_id in self.queue.results:
                    done.append(job_id)
                    continue
                if job_id in queued:
                    still.append(job_id)
                    continue
                # ONE state lookup per job per poll (state_of walks the
                # queue directories; calling it twice doubled the cost)
                state = self.queue.state_of(job_id)
                if state == FAILED:
                    failed.append(job_id)
                elif state == DONE:
                    done.append(job_id)
                else:
                    still.append(job_id)
            progressed = len(still) < len(pending)
            pending = still
            if pending:
                # the cap never undercuts an explicitly slower poll_s
                cap = max(float(poll_cap_s), float(poll_s))
                delay = (float(poll_s) if progressed
                         else min(delay * 1.6, cap))
                time.sleep(min(delay * (0.75 + 0.5 * random.random()),
                               max(deadline - time.time(), 0.0)))
        return {"done": done, "failed": failed, "pending": pending}

    # -- results -----------------------------------------------------------
    def export_csv(self, filename: str, full: bool = False,
                   latest_only: bool = False) -> int:
        """Write every stored result row to CSV (reference schema by
        default; ``full=True`` adds the beyond-reference columns) —
        the same exporter as ``process --store``, so a served survey's
        CSV is directly comparable to a direct run's.
        ``latest_only=True`` collapses each versioned stream series to
        its newest row (the final values per live feed)."""
        return self.queue.results.export_csv(filename, full=full,
                                             latest_only=latest_only)

    # -- drain -------------------------------------------------------------
    def drain(self, timeout: float | None = None,
              poll_s: float = 0.2) -> dict:
        """Ask the worker(s) to finish and stop: set the drain marker,
        then (``timeout is not None``) wait for the queue to empty.
        Returns the final status plus ``drained``."""
        self.queue.request_drain()
        if timeout is not None:
            deadline = time.time() + timeout
            while not self.queue.empty() and time.time() < deadline:
                time.sleep(poll_s)
        st = self.queue.status()
        st["drained"] = self.queue.empty()
        return st
