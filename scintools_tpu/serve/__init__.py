"""scintools_tpu.serve — resident survey service.

A durable filesystem job queue (one JSON file per job, atomic writes,
worker leases with expiry, bounded retries with exponential backoff and
a terminal poison state), a dynamic batcher that coalesces compatible
queued epochs onto the warm compiled step signatures PR 2's
warmup/compile-cache already paid for, a resident worker loop, and a
filesystem-protocol client — the substrate for serving a continuous
stream of observing epochs from one warm process (CLI verbs
``scintools-tpu serve`` / ``submit`` / ``status`` / ``drain``; see
docs/serving.md).
"""

from .batcher import Batch, DynamicBatcher, bucket_key  # noqa: F401
from .client import SurveyClient  # noqa: F401
from .pool import PoolConfig, PoolController  # noqa: F401
from .queue import (DEFAULT_MAX_RETRIES, LANES, ClaimHints,  # noqa: F401
                    Job, JobQueue, cfg_signature, job_key, job_sig,
                    parse_lane_budgets)
from .worker import (ServeWorker, config_from_opts,  # noqa: F401
                     load_epoch, pipeline_runner, synthetic_runner)
