"""Dynamic batching of queued epochs onto warm compiled step signatures.

The whole point of a *resident* worker is that PR 2's fixed-cost layer
(compile_cache + ``warmup``) already paid for ONE compiled program per
(axes, config, padded batch shape) signature — so the batcher's job is
to coalesce compatible queued epochs into exactly those signatures and
nothing else, the dynamic-batching discipline GPU pulsar front-ends use
to keep the FFT engine saturated (arXiv:1804.05335).

Grouping key = (config signature, full axis identity): two epochs with
equal (nf, nt) but different bands must not share a compiled step —
the same rule as ``parallel.driver._bucket_epochs``.  A bucket flushes
when it reaches ``batch_size`` (the warmed signature) or when its
oldest member has waited ``max_wait_s`` (bounded latency); partial
flushes are padded up to ``batch_size`` by the driver's mask-invalid
lane machinery (``run_pipeline(pad_to=...)``), so the worker executes
ONE resident compiled program per shape bucket regardless of fill.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any

import numpy as np

from .queue import Job, cfg_signature


@dataclasses.dataclass(frozen=True)
class Batch:
    """One flushable unit: jobs + their loaded epochs, single bucket.

    ``pad_to`` is the padded COMPILED signature this flush executes
    (the worker passes it to ``run_pipeline(pad_to=...)``): the full
    ``batch_size`` normally, or — under catalog bucketing — the nearest
    batch-ladder rung, so a 3-job flush pads to 4 lanes instead of 8
    and still hits a ``warmup --catalog`` signature."""

    jobs: tuple
    epochs: tuple
    cfg: dict
    key: tuple
    fill_ratio: float
    waited_s: float
    pad_to: int = 0


def bucket_key(cfg: dict, epoch) -> tuple:
    """(config signature, axes digest, shape) — epochs sharing it can
    ride one compiled step."""
    f = np.ascontiguousarray(np.asarray(epoch.freqs, dtype=np.float64))
    t = np.ascontiguousarray(np.asarray(epoch.times, dtype=np.float64))
    axes = hashlib.sha1(f.tobytes() + t.tobytes()).hexdigest()[:16]
    return (cfg_signature(cfg), axes, f.shape + t.shape)


class DynamicBatcher:
    """Accumulates (job, epoch) pairs into shape/config buckets and
    yields :class:`Batch` flushes on max-batch or max-wait."""

    def __init__(self, batch_size: int = 8, max_wait_s: float = 2.0,
                 bucket: bool = False, multiple: int = 1):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = int(batch_size)
        self.max_wait_s = float(max_wait_s)
        # catalog bucketing (scintools_tpu.buckets): partial flushes
        # pad to the nearest batch-ladder rung <= batch_size instead of
        # the full batch_size — less pad waste per flush, and every
        # rung is a `warmup --catalog` signature so the worker still
        # never traces.  ``multiple`` is the mesh data-axis size the
        # rungs must divide by.
        self.bucket = bool(bucket)
        self.multiple = max(int(multiple), 1)
        # key -> [(added_at, job, epoch), ...] — PER-ITEM stamps, so a
        # tail left over after a full-slice flush waits its own
        # max_wait rather than inheriting the flushed head's deadline
        self._buckets: dict[tuple, list] = {}

    def _pad_to(self, n: int) -> int:
        """The padded compiled signature an ``n``-job flush executes."""
        if not self.bucket:
            return self.batch_size
        from .. import buckets as buckets_mod

        return buckets_mod.rung_for(n, self.multiple,
                                    top=self.batch_size)

    def add(self, job: Job, epoch: Any, now: float | None = None) -> None:
        now = time.time() if now is None else now
        key = bucket_key(job.cfg, epoch)
        if job.solo:
            # whole-batch-failure retry: a private singleton bucket, so
            # the poison member fails alone and healthy members succeed
            # alone (the padded step signature is the same either way)
            key = key + (("solo", job.id),)
        self._buckets.setdefault(key, []).append((now, job, epoch))

    @property
    def pending(self) -> int:
        return sum(len(items) for items in self._buckets.values())

    def oldest_wait_s(self, now: float | None = None) -> float:
        now = time.time() if now is None else now
        if not self._buckets:
            return 0.0
        return max(now - items[0][0] for items in self._buckets.values())

    def pop_ready(self, now: float | None = None,
                  force: bool = False) -> list[Batch]:
        """Full buckets always flush; partial buckets flush when their
        OLDEST member's deadline has passed or ``force`` is set
        (drain).  A bucket that overfilled between polls flushes in
        ``batch_size`` slices — every flush is at most one compiled
        signature wide — and the leftover tail restarts the wait from
        its own members' add times."""
        now = time.time() if now is None else now
        out: list[Batch] = []
        for key in list(self._buckets):
            items = self._buckets[key]
            while len(items) >= self.batch_size or (
                    items and (force
                               or (now - items[0][0]) >= self.max_wait_s)):
                take, items = (items[:self.batch_size],
                               items[self.batch_size:])
                jobs = tuple(j for _, j, _ in take)
                epochs = tuple(e for _, _, e in take)
                pad = self._pad_to(len(take))
                out.append(Batch(
                    jobs=jobs, epochs=epochs, cfg=dict(jobs[0].cfg),
                    key=key,
                    fill_ratio=len(take) / float(pad),
                    waited_s=max(now - take[0][0], 0.0),
                    pad_to=pad))
            if items:
                self._buckets[key] = items
            else:
                del self._buckets[key]
        return out
