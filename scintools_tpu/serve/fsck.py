"""Crash-consistency audit + repair of a serve queue directory.

``scintools-tpu fsck QDIR`` walks every durable plane a queue
directory holds — the queued/leased/done/failed job records, the
results row + segment planes, the control markers, worker heartbeats,
and the feeds of live `stream` registrations — and checks the
invariant catalog below (normative prose in docs/reliability.md).
Dry-run by default: findings are REPORTED, nothing is touched.
``--repair`` applies only recovery actions the planes already ship
(the same code paths crash recovery runs), so a repair can never
invent state the system would not have reconverged to on its own;
a second dry-run after ``--repair`` reports clean.

Invariant catalog (class -> violated invariant -> repair):

``orphan_tmp``
    A ``*.tmp<pid>`` atomic-write staging file whose writer pid is
    dead: ``fsio.put_atomic`` crashed between tmp write and rename.
    The target path never saw a torn byte — the tmp is garbage.
    Repair: delete.
``orphan_open``
    An ``*.open`` segment whose writer is gone (dead pid past the
    salvage age, or live pid past the flush grace): a SIGKILL between
    block appends and ``seal``.  Repair: the store's own salvage —
    recover the checksum-valid block prefix into a fresh sealed
    segment, quarantine the original as ``.corrupt``.
``torn_segment``
    A sealed ``*.seg`` whose footer fails :func:`segments.read_footer`
    (truncated tail, checksum mismatch).  Repair: same salvage path.
``corrupt_record``
    An unparseable job-state JSON record.  Records are written
    atomically, so this is real corruption, not a mid-write race.
    Repair: quarantine aside as ``.corrupt`` (the row store's rule).
``queued_terminal_twin``
    A queued record for a job already in ``done/``/``failed/`` (the
    racing-submitter crash window).  Repair: remove — ``claim``'s own
    terminal-survivor GC, run eagerly.
``queued_misplaced``
    A queued record at a path the O(1) removal probes can never hit:
    wrong shard dir for its id, a filename stamp disagreeing with the
    record's ``submitted_at``, or a filename id disagreeing with the
    record.  (Legacy flat/laneless layouts are VALID — still drained —
    not findings.)  Repair: rewrite at the canonical lane-sharded path
    (``JobQueue._write``) and remove the misplaced file.
``expired_lease``
    A leased record whose lease has run out (SIGKILLed worker).
    Repair: ``JobQueue.reap_expired`` — requeue with attempts+1 and
    backoff, or poison once retries are exhausted.
``stale_drain``
    A per-worker ``control/drain.<worker>`` marker for a worker with
    no live heartbeat, older than the consume grace: the pool asked a
    worker to scale down and the worker died first.  Repair:
    ``JobQueue.clear_worker_drain``.
``stream_cursor_ahead``
    A live stream registration's durable cursor claims more consumed
    samples than the feed manifest has committed (manifest rolled
    back).  Repair: reset the cursor to the empty state — exactly the
    from-scratch replay ``window.restore`` falls back to when it
    meets this cursor (versioned rows make the replay idempotent).
``feed_orphan_chunk``
    A live stream job's feed holds chunk files the manifest never
    committed (producer crashed between chunk rename and manifest
    rewrite).  Repair: reopen the feed writer — ``FeedWriter._recover``
    adopts whole orphans in seq order and quarantines torn ones.
``versioned_series_gap``
    ADVISORY (never blocks a clean report, no repair): a live stream's
    window-end row series has holes relative to its own hop spacing.
    The versioned replay heals gaps when the stream re-runs; fsck only
    surfaces them.

Every run writes a trimmed snapshot to ``control/fsck.json``
(rendered by ``fleet status``) and counts ``fsck_runs`` /
``fsck_findings[<class>]`` / ``fsck_repairs[<class>]``.
"""

from __future__ import annotations

import json
import os
import re
import time

from .. import obs
from ..utils import fsio
from ..utils.log import get_logger, log_event
from ..utils.segments import (OPEN_EXT, OPEN_GRACE_S,
                              OPEN_SALVAGE_MIN_AGE_S, SEG_EXT,
                              SegmentError, _pid_alive, read_footer,
                              segment_pid)
from .queue import DONE, FAILED, LEASED, QUEUED, Job, JobQueue

FSCK_BASENAME = "fsck.json"

# a dead-pid *.tmp younger than this may belong to a REMOTE writer
# (pid liveness doesn't cross hosts) whose rename lands momentarily —
# same reasoning as the segment plane's OPEN_SALVAGE_MIN_AGE_S
TMP_GRACE_S = 5.0
# a drain marker younger than this may target a worker that simply
# hasn't beaten yet (scale-down races its own heartbeat)
STALE_DRAIN_GRACE_S = 60.0

_TMP_RE = re.compile(r"\.tmp(\d+)$")
_CLS_ORDER = ("orphan_tmp", "orphan_open", "torn_segment",
              "corrupt_record", "queued_terminal_twin",
              "queued_misplaced", "expired_lease", "stale_drain",
              "stream_cursor_ahead", "feed_orphan_chunk")


def _snapshot_path(qdir: str) -> str:
    return os.path.join(qdir, "control", FSCK_BASENAME)


def read_fsck_status(qdir: str) -> dict | None:
    """The last audit's ``control/fsck.json`` snapshot (the ``fleet
    status`` readout), or None."""
    try:
        snap = json.loads(fsio.read(_snapshot_path(qdir)))
    except (OSError, ValueError):  # fault-ok: advisory snapshot
        return None
    return snap if isinstance(snap, dict) \
        and snap.get("kind") == "fsck" else None


class _Audit:
    """One fsck pass over ``qdir`` (:func:`run_fsck` drives it)."""

    def __init__(self, qdir: str, repair: bool, now: float):
        self.qdir = qdir
        self.repair = repair
        self.now = now
        self.q = JobQueue(qdir)
        self.log = get_logger()
        self.findings: list[dict] = []
        self.advisories: list[dict] = []

    def _find(self, cls: str, path: str, detail: str,
              action: str) -> dict:
        f = {"cls": cls, "path": path, "detail": detail,
             "action": action, "repaired": False}
        self.findings.append(f)
        return f

    def _repair_failed(self, f: dict, exc: BaseException) -> None:
        f["detail"] += f" (repair failed: {exc!r})"
        log_event(self.log, "fsck_repair_failed", cls=f["cls"],
                  path=f["path"], error=repr(exc))

    # -- orphaned atomic-write staging files -------------------------------
    def check_orphan_tmp(self) -> None:
        for dirpath, _dirnames, filenames in os.walk(self.qdir):
            for fname in sorted(filenames):
                m = _TMP_RE.search(fname)
                if m is None:
                    continue
                pid = int(m.group(1))
                if pid == os.getpid() or _pid_alive(pid):
                    continue        # a live writer mid-replace
                path = os.path.join(dirpath, fname)
                try:
                    age = self.now - os.path.getmtime(path)
                except OSError:  # fault-ok: renamed away = completed
                    continue
                if age < TMP_GRACE_S:
                    continue        # possibly a remote writer's
                f = self._find(
                    "orphan_tmp", path,
                    f"dead writer pid {pid}, age {age:.1f}s",
                    "delete (target path never saw a torn byte)")
                if self.repair:
                    try:
                        fsio.delete(path)
                        f["repaired"] = True
                    except OSError as e:
                        self._repair_failed(f, e)

    # -- segment plane ------------------------------------------------------
    def check_segments(self) -> None:
        store = self.q.results.segments
        try:
            names = sorted(fsio.list(store.dir))
        except OSError:  # fault-ok: no segment plane written yet
            return
        for name in names:
            path = os.path.join(store.dir, name)
            if name.endswith(OPEN_EXT):
                pid = segment_pid(name)
                if pid == os.getpid():
                    continue
                grace = (OPEN_GRACE_S
                         if pid is not None and _pid_alive(pid)
                         else OPEN_SALVAGE_MIN_AGE_S)
                try:
                    age = self.now - os.path.getmtime(path)
                except OSError:  # fault-ok: sealed/salvaged mid-scan
                    continue
                if age < grace:
                    continue
                f = self._find(
                    "orphan_open", path,
                    f"writer pid {pid} gone, age {age:.1f}s",
                    "salvage valid block prefix, quarantine original")
            elif name.endswith(SEG_EXT):
                try:
                    read_footer(path)
                    continue
                except SegmentError as e:
                    f = self._find(
                        "torn_segment", path, str(e),
                        "salvage valid block prefix, quarantine "
                        "original")
            else:
                continue
            if self.repair:
                try:
                    store._salvage(path)
                    f["repaired"] = True
                except (OSError, SegmentError, ValueError) as e:
                    self._repair_failed(f, e)

    # -- job-state records --------------------------------------------------
    def _corrupt_record(self, path: str, exc: Exception) -> None:
        f = self._find("corrupt_record", path, repr(exc),
                       "quarantine aside as .corrupt")
        if self.repair:
            try:
                fsio.rename_if_absent(path, path + ".corrupt")
                f["repaired"] = True
            except OSError as e:
                self._repair_failed(f, e)

    def check_queued(self) -> None:
        q = self.q
        for lane, d in q._queued_dirs():
            try:
                names = sorted(fsio.list(d))
            except OSError:  # fault-ok: dir vanished mid-scan
                continue
            for fname in names:
                if not fname.endswith(".json") or ".tmp" in fname:
                    continue
                path = os.path.join(d, fname)
                stamp, jid = q._split_queued_name(fname)
                if os.path.exists(q._path(DONE, jid)) \
                        or os.path.exists(q._path(FAILED, jid)):
                    f = self._find(
                        "queued_terminal_twin", path,
                        f"job {jid} is terminal",
                        "remove (claim's terminal-survivor GC)")
                    if self.repair:
                        q._remove_file(path)
                        f["repaired"] = True
                    continue
                try:
                    raw = fsio.read(path)
                except OSError:  # fault-ok: claimed/removed mid-scan
                    continue
                try:
                    job = Job.from_record(json.loads(raw))
                except (ValueError, TypeError) as e:
                    self._corrupt_record(path, e)
                    continue
                self._check_queued_placement(lane, d, path, fname,
                                             stamp, jid, job)

    def _check_queued_placement(self, lane, d, path, fname, stamp,
                                jid, job) -> None:
        """Flag a queued record the O(1) removal probes
        (``_remove_queued``) and the bounded id scans
        (``_find_queued_all``) can never hit; legacy flat/laneless
        names stay valid."""
        q = self.q
        expected = q._queued_path(job.id, job.submitted_at,
                                  q._lane_of(job))
        if jid != job.id:
            why = f"filename id {jid} != record id {job.id}"
        elif lane is not None:
            if os.path.abspath(path) == os.path.abspath(expected):
                return
            why = "lane/shard/stamp disagree with the record"
        elif stamp is not None and fname.split("-", 1)[0] \
                != q._stamp_prefix(job.submitted_at):
            why = "filename stamp disagrees with submitted_at"
        elif os.path.basename(d).isdigit() \
                and int(os.path.basename(d)) != q._shard_of(jid):
            why = "wrong legacy shard dir for this id"
        else:
            return
        f = self._find(
            "queued_misplaced", path, f"{why}; canonical {expected}",
            "rewrite at canonical path, remove misplaced record")
        if self.repair:
            try:
                q._write(QUEUED, job)
                if os.path.abspath(path) != os.path.abspath(expected):
                    q._remove_file(path)
                f["repaired"] = True
            except OSError as e:
                self._repair_failed(f, e)

    def check_state_records(self) -> None:
        for state in (LEASED, DONE, FAILED):
            d = os.path.join(self.qdir, state)
            try:
                names = sorted(fsio.list(d))
            except OSError:  # fault-ok: rollup must survive churn
                continue
            for fname in names:
                if not fname.endswith(".json") or ".tmp" in fname:
                    continue
                path = os.path.join(d, fname)
                try:
                    raw = fsio.read(path)
                except OSError:  # fault-ok: finalised mid-scan
                    continue
                try:
                    Job.from_record(json.loads(raw))
                except (ValueError, TypeError) as e:
                    self._corrupt_record(path, e)

    def check_leases(self) -> None:
        q = self.q
        expired = []
        for jid in q._ids(LEASED):
            job = q._read(LEASED, jid)
            if job is None:
                continue
            exp = job.lease_expires_at
            if exp is None:
                # mid-claim record (rename done, lease stamp pending):
                # same mtime grace the reap itself applies
                try:
                    exp = os.path.getmtime(q._path(LEASED, jid)) + 30.0
                except OSError:  # fault-ok: finalised mid-scan
                    continue
            if exp > self.now:
                continue
            expired.append(self._find(
                "expired_lease", q._path(LEASED, jid),
                f"worker {job.lease_worker}, expired "
                f"{self.now - exp:.1f}s ago",
                "reap_expired: requeue with backoff, or poison once "
                "retries exhaust"))
        if expired and self.repair:
            try:
                q.reap_expired(self.now)
                for f in expired:
                    f["repaired"] = True
            except OSError as e:
                for f in expired:
                    self._repair_failed(f, e)

    # -- control markers ----------------------------------------------------
    def _live_workers(self) -> set:
        """Sanitised names of workers with a heartbeat whose pid still
        runs (fleet's heartbeat plane under ``qdir/heartbeat/``)."""
        from ..obs.fleet import HEARTBEAT_DIRNAME, read_heartbeats

        out = set()
        for hb in read_heartbeats(
                os.path.join(self.qdir, HEARTBEAT_DIRNAME)):
            pid = hb.get("pid")
            if isinstance(pid, int) and not _pid_alive(pid):
                continue
            out.add(self.q._safe_worker(str(hb.get("worker"))))
        return out

    def check_drain_markers(self) -> None:
        cdir = os.path.join(self.qdir, "control")
        try:
            names = sorted(fsio.list(cdir))
        except OSError:  # fault-ok: no control plane yet
            return
        live = None
        for fname in names:
            if not fname.startswith("drain.") or ".tmp" in fname:
                continue
            wname = fname[len("drain."):]
            path = os.path.join(cdir, fname)
            try:
                age = self.now - os.path.getmtime(path)
            except OSError:  # fault-ok: consumed mid-scan
                continue
            if age < STALE_DRAIN_GRACE_S:
                continue
            if live is None:
                live = self._live_workers()
            if wname in live:
                continue
            f = self._find(
                "stale_drain", path,
                f"worker {wname} has no live heartbeat, marker age "
                f"{age:.1f}s", "clear_worker_drain")
            if self.repair:
                self.q.clear_worker_drain(wname)
                f["repaired"] = True

    # -- streaming plane ----------------------------------------------------
    def _stream_jobs(self) -> list:
        jobs = []
        for state in (QUEUED, LEASED):
            for jid in self.q._ids(state):
                job = self.q._read(state, jid)
                if job is not None \
                        and isinstance(job.cfg.get("stream"), dict) \
                        and not job.cfg.get("backfill"):
                    jobs.append(job)
        return jobs

    def check_streams(self) -> None:
        from ..stream.ingest import _CHUNK_RE, _read_manifest

        stream_jobs = self._stream_jobs()
        for job in stream_jobs:
            feed = str(job.cfg["stream"].get("feed"))
            try:
                man = _read_manifest(feed, missing_ok=True)
            except (OSError, ValueError):  # fault-ok: a broken feed
                # poisons at register with the stream plane's own
                # message — not a queue-dir invariant
                continue
            if man is None:
                continue
            total = sum(int(c.get("nt", 0)) for c in man["chunks"])
            meta = self.q.results.get_meta(f"stream.{job.id}")
            consumed = (int(meta.get("consumed", 0))
                        if isinstance(meta, dict) else 0)
            if consumed > total:
                f = self._find(
                    "stream_cursor_ahead",
                    os.path.join(self.q.results.dir,
                                 f"meta.stream.{job.id}"),
                    f"cursor {consumed} > committed {total} "
                    f"(feed {feed})",
                    "reset cursor to empty state (restore's own "
                    "from-scratch replay; versioned rows dedup)")
                if self.repair:
                    try:
                        self.q.results.put_meta(f"stream.{job.id}", {})
                        f["repaired"] = True
                    except OSError as e:
                        self._repair_failed(f, e)
            committed = {int(c["seq"]) for c in man["chunks"]}
            try:
                names = sorted(fsio.list(feed))
            except OSError:  # fault-ok: feed vanished; register path
                continue     # reports it with its own taxonomy
            orphans = [n for n in names
                       if (m := _CHUNK_RE.match(n)) is not None
                       and int(m.group(1)) not in committed]
            if orphans:
                f = self._find(
                    "feed_orphan_chunk", feed,
                    f"{len(orphans)} uncommitted chunk(s): "
                    + " ".join(orphans[:4])
                    + ("..." if len(orphans) > 4 else ""),
                    "reopen feed: _recover adopts whole orphans, "
                    "quarantines torn ones")
                if self.repair:
                    try:
                        from ..stream.ingest import FeedWriter

                        FeedWriter(feed)
                        f["repaired"] = True
                    except (OSError, ValueError) as e:
                        self._repair_failed(f, e)
        self._check_series_gaps(stream_jobs)

    def _check_series_gaps(self, stream_jobs) -> None:
        """ADVISORY: holes in a live stream's window-end row series
        relative to its own smallest hop.  The versioned replay heals
        gaps when the stream re-runs; no repair action exists, so
        gaps never block a clean report."""
        if not stream_jobs:
            return
        keys = self.q.results.keys()
        for job in stream_jobs:
            pref = f"{job.id}.w"
            ends = sorted(int(k[len(pref):]) for k in keys
                          if k.startswith(pref)
                          and k[len(pref):].isdigit())
            if len(ends) < 3:
                continue
            diffs = [b - a for a, b in zip(ends, ends[1:])]
            hop = min(diffs)
            missing = sum(d // hop - 1 for d in diffs
                          if hop > 0 and d % hop == 0 and d > hop)
            if missing:
                self.advisories.append({
                    "cls": "versioned_series_gap", "path": pref + "*",
                    "detail": f"{missing} missing window end(s) at "
                              f"hop {hop} over {len(ends)} rows"})

    # -- drive --------------------------------------------------------------
    def run(self) -> dict:
        self.check_orphan_tmp()
        self.check_segments()
        self.check_queued()
        self.check_state_records()
        self.check_leases()
        self.check_drain_markers()
        self.check_streams()
        classes: dict[str, int] = {}
        repaired = 0
        for f in self.findings:
            classes[f["cls"]] = classes.get(f["cls"], 0) + 1
            repaired += bool(f["repaired"])
        order = {c: i for i, c in enumerate(_CLS_ORDER)}
        self.findings.sort(
            key=lambda f: (order.get(f["cls"], len(order)), f["path"]))
        return {
            "kind": "fsck", "v": 1, "qdir": self.qdir,
            "ts": round(self.now, 3), "repair": self.repair,
            "findings": self.findings, "advisories": self.advisories,
            "classes": classes, "repaired": repaired,
            "clean": all(f["repaired"] for f in self.findings),
        }


def run_fsck(qdir: str, repair: bool = False,
             now: float | None = None) -> dict:
    """Audit ``qdir``'s on-disk invariants (dry-run) or audit+repair.

    Returns the report dict (module docstring catalog); ``clean`` is
    True when no finding remains unrepaired.  Always writes the
    trimmed ``control/fsck.json`` snapshot ``fleet status`` renders,
    and counts ``fsck_runs``/``fsck_findings``/``fsck_repairs``."""
    now = time.time() if now is None else now
    audit = _Audit(qdir, repair=bool(repair), now=now)
    report = audit.run()
    obs.inc("fsck_runs")
    for f in report["findings"]:
        obs.inc("fsck_findings")
        obs.inc(f"fsck_findings[{f['cls']}]")
        if f["repaired"]:
            obs.inc("fsck_repairs")
            obs.inc(f"fsck_repairs[{f['cls']}]")
    log_event(audit.log, "fsck_done", qdir=qdir, repair=bool(repair),
              findings=len(report["findings"]),
              repaired=report["repaired"], clean=report["clean"])
    snap = {k: report[k] for k in ("kind", "v", "ts", "repair",
                                   "classes", "repaired", "clean")}
    snap["findings"] = len(report["findings"])
    snap["advisories"] = len(report["advisories"])
    try:
        os.makedirs(os.path.join(qdir, "control"), exist_ok=True)
        fsio.put_atomic(_snapshot_path(qdir), json.dumps(snap))
    except OSError as e:  # fault-ok: the snapshot is advisory; the
        # report (and exit code) already carry the audit
        log_event(audit.log, "fsck_snapshot_failed", error=repr(e))
    return report


def render_report(report: dict) -> str:
    """Human rendering of a :func:`run_fsck` report (the CLI's
    non-``--json`` output)."""
    mode = "repair" if report["repair"] else "dry-run"
    lines = [f"fsck {report['qdir']} ({mode}):"]
    if not report["findings"] and not report["advisories"]:
        lines.append("  clean: every invariant holds")
        return "\n".join(lines)
    for f in report["findings"]:
        state = ("repaired" if f["repaired"]
                 else "would repair" if not report["repair"]
                 else "UNREPAIRED")
        lines.append(f"  {f['cls']}: {f['path']}")
        lines.append(f"    {f['detail']}")
        lines.append(f"    {state}: {f['action']}")
    for a in report["advisories"]:
        lines.append(f"  advisory {a['cls']}: {a['path']}")
        lines.append(f"    {a['detail']}")
    n = len(report["findings"])
    lines.append(f"  {n} finding(s), {report['repaired']} repaired, "
                 f"{len(report['advisories'])} advisory; "
                 + ("clean" if report["clean"] else "NOT clean"))
    return "\n".join(lines)
