"""Durable on-disk job queue for the resident survey service.

The queue directory IS the protocol (no network dependency): clients
and workers on the same filesystem coordinate purely through atomic
file operations, the way the reference's append-mode CSV made a killed
batch run resumable (scint_utils.py:75-108) — here generalised to a
real work queue with leases, as real-time pulsar-search pipelines front
their persistent accelerator workers (arXiv:1804.05335 §real-time
operation).

Layout (all JSON, one file per job, written tmp+``os.replace`` so a
crash can never leave a torn record)::

    qdir/
      queued/<lane>/<ss>/<stamp>-<job_id>.json
                              submitted, waiting for a worker.
                              <lane> = the job's QoS lane (ISSUE 13):
                              ``interactive`` or ``bulk`` — claim order
                              is weighted-fair over the lanes, so a
                              million-epoch bulk campaign can never
                              starve a live observer's job.  <ss> =
                              the job's SHARD, crc32(job_id) mod N —
                              the flat queued/ dir was the listdir/
                              rename contention point at production
                              depth (ROADMAP item 1), so the namespace
                              is hashed over N subdirectories; N is
                              persisted in control/shards at queue
                              creation so every process agrees.
                              <stamp> = 17-digit submit microseconds,
                              so each shard's sorted listdir IS its
                              FIFO order; claim merges the shard heads
                              by stamp, preserving per-lane submit
                              order while every directory op (submit,
                              the claim rename, the O(1) unlink
                              probes) lands in a dir of depth/N
                              entries.  Legacy pre-lane
                              queued/<ss>/..., flat
                              queued/<stamp>-<id>.json and unstamped
                              queued/<id>.json records are still read
                              and drained — as the BULK lane.
      leased/<job_id>.json    claimed by a worker, lease expiry inside
      done/<job_id>.json      completed (result row in results/)
      failed/<job_id>.json    terminal: retries exhausted (poison input)
      results/                utils.store.ResultsStore (idempotent rows;
                              segment plane under results/segments/)
      control/drain           drain marker (serve exits when empty)
      control/drain.<worker>  per-worker drain marker (ISSUE 13): the
                              pool controller's scale-down handle — the
                              named worker stops claiming, finishes the
                              batches it holds, consumes the marker and
                              exits; every other worker ignores it
      control/shards          persisted queued-shard count
      control/hints.json      pool-controller claim hints (serve/pool):
                              per-worker preferred warm signatures +
                              max admissible batch bytes, honoured by
                              :meth:`JobQueue.claim`
      control/pool.json       pool-controller status snapshot (rendered
                              by ``fleet status``)

Semantics:

* **Idempotent submit** — ``job_id = content_key(file bytes, config)``
  (utils/store.py), so re-submitting the same epoch+config is a no-op
  in every state, including ``done`` (the result row already exists in
  ``results/``).
* **Leases, not locks** — ``claim`` moves ``queued/ -> leased/`` with
  an expiry stamp; the move is an ``os.rename`` whose atomicity picks
  exactly one winner among racing workers.  A SIGKILLed worker's
  leased jobs are reclaimed by ``reap_expired`` after the lease runs
  out: back to ``queued/`` with ``attempts + 1`` and exponential
  backoff, or to ``failed/`` once ``max_retries`` is exhausted.
* **At-least-once execution, exactly-once results** — a lease can
  expire under a live worker (long compile), so the same job may
  execute twice; the content-keyed results store makes the second
  write idempotent, and ``complete`` finalises from whichever state
  dir the job landed in.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import zlib
from typing import Sequence

from .. import faults, obs
from ..obs.fleet import new_trace_id
from ..utils import fsio
from ..utils.store import ResultsStore, content_key

# job states = subdirectories
QUEUED, LEASED, DONE, FAILED = "queued", "leased", "done", "failed"
_STATES = (QUEUED, LEASED, DONE, FAILED)

DEFAULT_MAX_RETRIES = 3
DEFAULT_BACKOFF_S = 1.0
BACKOFF_CAP_S = 300.0
# transient (budget-preserving) requeues per job before further
# transient failures ESCALATE to the bounded attempts path, as a
# multiple of max_retries: a misclassified deterministic error (or a
# pool where the fault is effectively permanent) must eventually reach
# failed/ instead of livelocking the queue — generous, because real
# infra faults clear in one or two placements
TRANSIENT_ESCALATION_FACTOR = 10

# queued-namespace shard fan-out for a FRESH queue dir (override with
# JobQueue(shards=...) or SCINT_QUEUE_SHARDS); an existing queue's
# persisted control/shards value always wins, so every client/worker
# process probes the same shard paths
DEFAULT_QUEUE_SHARDS = 8
MAX_QUEUE_SHARDS = 256

# QoS lanes (ISSUE 13).  The lane is a SCHEDULING attribute, never part
# of the job identity (the same epoch+options submitted on either lane
# dedups to one job): "interactive" for live observers' submits,
# "bulk" for campaign traffic (`simulate` jobs default here).  Legacy
# laneless queued records drain as bulk.
LANE_INTERACTIVE, LANE_BULK = "interactive", "bulk"
LANES = (LANE_INTERACTIVE, LANE_BULK)
# weighted-fair claim budgets: per claim cycle, up to budget[lane]
# candidates are taken from each lane in LANES order before the cycle
# repeats — so an interactive head job is claimed after at most
# budget[bulk] bulk jobs (the pinned starvation bound), while bulk
# still progresses whenever interactive work is thinner than its
# budget (unused slots fall through within the same cycle)
DEFAULT_LANE_BUDGETS = {LANE_INTERACTIVE: 3, LANE_BULK: 1}

# affinity-hint deferral (serve/pool claim hints): a job whose warm
# signature is preferred by ANOTHER worker is left on the queue for
# this grace window so the warm worker can claim it first; memory-unfit
# jobs (est_bytes over the worker's hinted headroom) wait the longer
# window below before any worker takes them anyway (a hint must delay
# placement, never starve a job no worker advertises room for)
DEFAULT_AFFINITY_DEFER_S = 2.0
DEFAULT_MEM_DEFER_S = 30.0
# feed-pin deferral (ISSUE 17): a stream job pinned to ANOTHER live
# worker is left alone for this window measured from the PIN's OWN
# timestamp (hints-file mtime), not the job's queue age — a long-lived
# stream registration is hours old by the time a drain releases it, so
# an age-bounded grace would be a no-op.  After the window the feed is
# claimable by anyone (a pin must route placement, never strand a feed
# whose pinned worker is gone but not yet reaped).
DEFAULT_PIN_DEFER_S = 15.0

_LAST_STAMP = 0.0


def _submit_stamp() -> float:
    """Strictly-increasing submit timestamps within one process, so
    FIFO claim order equals submit order even when ``time.time()``
    ties across a tight submit loop (claim's tiebreak would otherwise
    fall back to hash order).  The 2 µs step keeps the stamps distinct
    after the queued-FILENAME encoding's microsecond truncation too
    (float64 rounding at ~1.7e15 µs can eat up to half a microsecond,
    never a whole one)."""
    global _LAST_STAMP
    t = time.time()
    if t <= _LAST_STAMP:
        t = _LAST_STAMP + 2e-6
    _LAST_STAMP = t
    return t


def validate_job_cfg(cfg: dict) -> None:
    """Reject option dicts the worker would deterministically reject
    (``make_pipeline`` raises on them), so a misconfigured submit fails
    at the CLIENT instead of enqueueing a job that burns its whole
    retry/backoff budget into ``failed/`` poison.

    ONE rule site (ISSUE 14 satellite): the option dict is built into
    the worker's own :class:`~scintools_tpu.parallel.PipelineConfig`
    (``serve.worker.config_from_opts`` — the identical builder the
    worker runs) and validated by ``PipelineConfig.validate`` — the
    method ``make_pipeline`` itself calls — so split/crop/arc rules
    can NEVER drift between CLI, driver and serve.  ``JobQueue.submit``
    calls this for the Python API and the CLI's
    ``_validate_estimator_flags`` delegates here for process/warmup/
    submit (flag spellings map 1:1 onto the dict keys)."""
    from .worker import config_from_opts

    config_from_opts(cfg).validate()
    if cfg.get("synthetic") is not None:
        # simulate-job payload: fail the bad campaign at submit, with
        # the driver's own one-rule-site messages (spec validity +
        # the synthetic route's config exclusions)
        from ..parallel.driver import _validate_synth_config
        from ..sim import campaign

        campaign.spec_from_dict(cfg["synthetic"])
        _validate_synth_config(config_from_opts(cfg), mesh=None,
                               chan_sharded=None)
    if cfg.get("infer") is not None and cfg.get("search") is not None:
        # the cross-engine rule outranks either engine's own checks: a
        # two-engine cfg is malformed whatever each payload says
        raise ValueError(
            "a job is one engine: cfg['search'] and cfg['infer'] "
            "are mutually exclusive (submit two jobs)")
    if cfg.get("infer") is not None:
        # infer-job payload (ISSUE 18): the optimiser spec and its
        # cross-field rules (supported kinds, lamsteps for arc) fail at
        # submit with the infer plane's own one-rule-site messages
        from ..infer import infer_from_dict, validate_infer_config
        from ..sim import campaign

        if cfg.get("synthetic") is None:
            raise ValueError(
                "infer jobs ride a synthetic campaign payload: "
                "cfg['synthetic'] is required beside cfg['infer']")
        validate_infer_config(campaign.spec_from_dict(cfg["synthetic"]),
                              infer_from_dict(cfg["infer"]),
                              config_from_opts(cfg))
    if cfg.get("search") is not None:
        # search-job payload (ISSUE 19): the bank spec and its grid
        # cross-field rules (delay window, coarse-bin floor, auto trial
        # range, lamsteps exclusion) fail at submit with the search
        # plane's own one-rule-site messages
        from ..search import search_from_dict, validate_search_config
        from ..sim import campaign

        if cfg.get("synthetic") is None:
            raise ValueError(
                "search jobs ride a synthetic campaign payload: "
                "cfg['synthetic'] is required beside cfg['search']")
        validate_search_config(
            campaign.spec_from_dict(cfg["synthetic"]),
            search_from_dict(cfg["search"]), config_from_opts(cfg))


def cfg_signature(cfg: dict) -> tuple:
    """Canonical hashable form of a job's processing options: sorted
    (key, value) pairs with lists normalised to tuples AND defaults
    dropped — ``None``, boolean ``False`` (every serve boolean option
    defaults off) and the string knobs' defaults (``arc_method``,
    ``precision``, ``fft_lens``) — so a sparse dict
    (``{"lamsteps": True}``) and the CLI's fully-materialised option
    dict hash to the SAME job identity (the idempotent-submit
    contract), regardless of dict ordering or JSON round-trips."""
    def norm(v):
        if isinstance(v, (list, tuple)):
            return tuple(norm(x) for x in v)
        if isinstance(v, dict):
            # nested payloads (the simulate-job SynthSpec dict) must
            # hash order-independently and survive JSON round-trips
            return tuple((str(k), norm(val))
                         for k, val in sorted(v.items()))
        return v

    _string_defaults = {"arc_method": "norm_sspec", "precision": "f32",
                        "fft_lens": "pow2"}
    # execution-placement knobs that change NO result byte: catalog
    # bucketing pads with mask-invalid lanes the driver slices off,
    # and program splitting (ISSUE 14) runs the same math as two
    # compiled units with a bit-identical CSV (both tested) — so a job
    # submitted by a knob-aware client must dedup/batch with the
    # identical job from a legacy client: strip them from the identity
    # entirely
    _placement_keys = ("bucket", "split_programs")
    out = []
    for k, v in sorted((cfg or {}).items()):
        if v is None or v is False:
            continue
        if k in _placement_keys:
            continue
        if _string_defaults.get(k) == v:
            continue
        out.append((str(k), norm(v)))
    return tuple(out)


def job_key(path: str, cfg: dict) -> str:
    """The job's identity AND its results-store key: a content hash of
    the input file's bytes + the processing options.  Identical epochs
    submitted under different path spellings dedup to one job."""
    return content_key(path, ("serve",) + cfg_signature(cfg))


def job_sig(cfg: dict) -> str:
    """The job's WARM-AFFINITY signature: a short digest of the
    canonical option dict (which, for `simulate` jobs, embeds the
    whole campaign spec).  Jobs sharing it run the same pipeline
    config — the dominant recompile driver across a mixed queue — so a
    worker that has executed one is warm for the rest.  Coarser than
    the compiled step signature on purpose: the axes identity needs
    the epoch LOADED, and the hint must be computable from the job
    record alone at claim time."""
    return content_key(("sig",) + cfg_signature(cfg))[:12]


def validate_lane(lane: str | None, default: str) -> str:
    """Normalise/validate a submit-time lane choice."""
    if lane is None:
        return default
    if lane not in LANES:
        raise ValueError(f"lane={lane!r}: expected one of "
                         f"{'/'.join(LANES)}")
    return lane


def parse_lane_budgets(text: str) -> dict:
    """``"interactive=3,bulk=1"`` -> budgets dict (the serve
    ``--lane-budgets`` flag).  A zero budget starves that lane only
    while other lanes have work (claim falls back when every budgeted
    lane is empty)."""
    out: dict[str, int] = {}
    for part in str(text).split(","):
        part = part.strip()
        if not part:
            continue
        lane, sep, val = part.partition("=")
        lane = lane.strip()
        if not sep or lane not in LANES:
            raise ValueError(f"--lane-budgets entry {part!r}: expected "
                             f"LANE=N with LANE in {'/'.join(LANES)}")
        try:
            n = int(val)
        except ValueError:
            raise ValueError(f"--lane-budgets {lane}: {val!r} is not "
                             "an integer")
        if n < 0:
            raise ValueError(f"--lane-budgets {lane}: budget must be "
                             ">= 0")
        out[lane] = n
    return out


@dataclasses.dataclass(frozen=True)
class ClaimHints:
    """Pool-controller claim hints for ONE worker (serve/pool.py builds
    these from ``control/hints.json``): ``prefer`` = warm signatures
    this worker should claim eagerly; ``elsewhere`` = signatures some
    OTHER worker is warm for (deferred for ``defer_s`` so the warm
    worker lands them instead of this one recompiling); ``max_bytes`` =
    the admissible staged/generated batch size from this worker's
    published HBM headroom (bigger jobs wait ``mem_defer_s`` for a
    roomier worker, then run anyway under the driver's OOM backoff)."""

    prefer: frozenset = frozenset()
    elsewhere: frozenset = frozenset()
    max_bytes: int | None = None
    defer_s: float = DEFAULT_AFFINITY_DEFER_S
    mem_defer_s: float = DEFAULT_MEM_DEFER_S
    # feed->worker pinning (ISSUE 17): feed paths whose ring +
    # incremental transform state is resident on THIS worker
    # (``pinned`` — claim eagerly, ahead of every warm-sig hint) or on
    # some other live worker (``pinned_elsewhere`` — defer for
    # ``pin_defer_s`` measured from ``pin_ts``, the hints file's own
    # write stamp)
    pinned: frozenset = frozenset()
    pinned_elsewhere: frozenset = frozenset()
    pin_ts: float = 0.0
    pin_defer_s: float = DEFAULT_PIN_DEFER_S


def stream_feed_of(job: "Job") -> str | None:
    """The feed path a LIVE `stream` job is bound to — the pinning
    key; None for every other job kind.  Backfill jobs deliberately
    don't count: they run the stateless batch path and should land on
    whatever bulk capacity is free, NOT compete with the pinned
    worker's live ticks."""
    spec = job.cfg.get("stream")
    if isinstance(spec, dict) and not job.cfg.get("backfill"):
        feed = spec.get("feed")
        return str(feed) if feed else None
    return None


@dataclasses.dataclass(frozen=True)
class Job:
    """One queued unit of work (an observing epoch + its options)."""

    id: str
    file: str
    cfg: dict
    submitted_at: float
    attempts: int = 0
    not_before: float = 0.0
    lease_worker: str | None = None
    lease_expires_at: float | None = None
    error: str | None = None
    # retry in a singleton batch: set when a WHOLE batch failed, so the
    # members cannot re-coalesce into the same failing batch and burn
    # every healthy member's retry budget alongside the poison one
    solo: bool = False
    # count of TRANSIENT requeues (infra faults: OOM, lease races,
    # injected chaos — faults.classify_error): observability only, it
    # never gates the bounded ``attempts`` poison budget, but it does
    # drive the transient path's own exponential backoff
    transients: int = 0
    # distributed-trace identity (ISSUE 10): ``trace_id`` is minted
    # ONCE at submit and never changes; ``span`` is the obs event id of
    # the job's LATEST lifecycle hop — each new hop records an event
    # with parent=span and persists its own id here, so the causal
    # chain survives crossing worker processes (SIGKILL, reap, requeue)
    trace_id: str | None = None
    span: str | None = None
    # QoS lane (ISSUE 13): scheduling only, never job identity.  None =
    # legacy record, drained as bulk.
    lane: str | None = None
    # warm-affinity signature (job_sig) + a rough staged/generated-batch
    # byte estimate: the claim-time routing inputs the pool controller's
    # hints compare against (both optional — legacy records route
    # normally)
    sig: str | None = None
    est_bytes: int | None = None

    def to_record(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_record(cls, rec: dict) -> "Job":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in rec.items() if k in fields})


class JobQueue:
    """Durable filesystem job queue: atomic state files, rename-arbited
    claims with expiring leases, bounded-retry requeues, and a
    content-keyed results store (see the module docstring for the
    directory protocol)."""

    def __init__(self, directory: str,
                 max_retries: int = DEFAULT_MAX_RETRIES,
                 backoff_s: float = DEFAULT_BACKOFF_S,
                 max_transients: int | None = None,
                 shards: int | None = None):
        self.dir = directory
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_transients = (int(max_transients)
                               if max_transients is not None
                               else TRANSIENT_ESCALATION_FACTOR
                               * max(self.max_retries, 1))
        for sub in _STATES + ("control",):
            os.makedirs(os.path.join(directory, sub), exist_ok=True)
        self.nshards = self._init_shards(shards)
        self._shard_width = max(2, len(str(self.nshards - 1)))
        for lane in LANES:
            for i in range(self.nshards):
                os.makedirs(self._lane_shard_dir(lane, i), exist_ok=True)
        self.results = ResultsStore(os.path.join(directory, "results"))

    # -- queued-namespace sharding -----------------------------------------
    def _shards_path(self) -> str:
        return os.path.join(self.dir, "control", "shards")

    def _init_shards(self, shards: int | None) -> int:
        """The queue's shard count: the value persisted at creation
        wins (every process must probe the same shard paths — a
        mismatched count would make `_remove_queued`'s O(1) probes
        miss); a fresh dir persists the constructor/env/default value
        atomically, first creator wins under a race."""
        path = self._shards_path()
        try:
            with open(path) as fh:
                return self._valid_shards(fh.read().strip())
        except (OSError, ValueError):
            pass
        n = self._valid_shards(
            shards if shards is not None
            else os.environ.get("SCINT_QUEUE_SHARDS",
                                DEFAULT_QUEUE_SHARDS))
        try:
            if not os.path.exists(path):
                fsio.put_atomic(path, str(n))
        except OSError:  # fault-ok: a racing creator persisted first
            pass
        try:
            with open(path) as fh:
                return self._valid_shards(fh.read().strip())
        except (OSError, ValueError):
            return n

    @staticmethod
    def _valid_shards(value) -> int:
        n = int(value)
        if not 1 <= n <= MAX_QUEUE_SHARDS:
            raise ValueError(f"queue shards={n}: expected "
                             f"1..{MAX_QUEUE_SHARDS}")
        return n

    def _shard_of(self, job_id: str) -> int:
        return zlib.crc32(job_id.encode("utf-8")) % self.nshards

    def _shard_name(self, shard: int) -> str:
        return f"{shard:0{self._shard_width}d}"

    def _shard_dir(self, shard: int) -> str:
        """The LEGACY (pre-lane) shard dir — still read/drained."""
        return os.path.join(self.dir, QUEUED, self._shard_name(shard))

    def _lane_shard_dir(self, lane: str, shard: int) -> str:
        return os.path.join(self.dir, QUEUED, lane,
                            self._shard_name(shard))

    @staticmethod
    def _lane_of(job: "Job") -> str:
        """The lane a record WRITES into (legacy/None -> bulk — the
        documented drain lane for laneless records, and deterministic
        so ``_remove_queued``'s probes stay O(1))."""
        return job.lane if job.lane in LANES else LANE_BULK

    def _queued_dirs(self) -> list[tuple[str | None, str]]:
        """Every ``(lane, directory)`` queued records can live in: the
        lane x shard dirs, the legacy pre-lane shard dirs and the flat
        ``queued/`` root (both legacy layouts keep draining, as the
        bulk lane: ``lane=None`` here).  Subdir names never end in
        ``.json`` so the flat walks skip them for free."""
        out: list[tuple[str | None, str]] = []
        for lane in LANES:
            out.extend((lane, self._lane_shard_dir(lane, i))
                       for i in range(self.nshards))
        out.extend((None, self._shard_dir(i))
                   for i in range(self.nshards))
        out.append((None, os.path.join(self.dir, QUEUED)))
        return out

    # -- paths / low-level records -----------------------------------------
    # Queued jobs are named "<17-digit-microsecond-stamp>-<job_id>.json"
    # so a plain sorted listdir IS the FIFO claim order: claim() no
    # longer opens every queued record per poll (the PR 3 O(depth)
    # review finding), only the ~batch_size head candidates.  Leased/
    # done/failed keep plain "<job_id>.json" names, and every read path
    # still accepts legacy unstamped queued files (queues written by
    # earlier versions keep draining).
    _STAMP_DIGITS = 17  # microseconds since epoch; covers year ~5138

    def _stamp_prefix(self, submitted_at: float) -> str:
        return f"{int(max(submitted_at, 0.0) * 1e6):0{self._STAMP_DIGITS}d}"

    @classmethod
    def _split_queued_name(cls, fname: str) -> tuple[float | None, str]:
        """(submit stamp or None for legacy names, job_id)."""
        stem = fname[:-5]  # drop ".json"
        stamp, sep, jid = stem.partition("-")
        if sep and jid and stamp.isdigit() \
                and len(stamp) == cls._STAMP_DIGITS:
            return int(stamp) / 1e6, jid
        return None, stem

    def _path(self, state: str, job_id: str) -> str:
        return os.path.join(self.dir, state, f"{job_id}.json")

    def _queued_path(self, job_id: str, submitted_at: float,
                     lane: str = LANE_BULK) -> str:
        return os.path.join(
            self._lane_shard_dir(lane, self._shard_of(job_id)),
            f"{self._stamp_prefix(submitted_at)}-{job_id}.json")

    def _find_queued_all(self, job_id: str) -> list[str]:
        """EVERY queued file for ``job_id`` (stamped and/or legacy) —
        normally one, but a crash inside ``_write``'s stamped-write →
        legacy-unlink window (or a duplicate-submit race) can leave
        more.  Read paths (``_read``/``state_of``) use this scan;
        removal stays O(1) (``_remove_queued``) because any survivor
        of a finished job is garbage-collected by ``claim``'s
        terminal-state guard instead of re-executing.  Bounded
        directory-name scans (the id's OWN shard per lane + the legacy
        shard + the flat root), no file opens."""
        suffix = f"-{job_id}.json"
        out = []
        plain = self._path(QUEUED, job_id)
        if os.path.exists(plain):
            out.append(plain)
        shard = self._shard_of(job_id)
        for d in ([self._lane_shard_dir(lane, shard) for lane in LANES]
                  + [self._shard_dir(shard),
                     os.path.join(self.dir, QUEUED)]):
            try:
                with os.scandir(d) as it:
                    for e in it:
                        if e.name.endswith(suffix) \
                                and ".tmp" not in e.name:
                            out.append(os.path.join(d, e.name))
            except OSError:
                pass
        return out

    def _find_queued(self, job_id: str) -> str | None:
        """Existing queued file for ``job_id`` (stamped or legacy)."""
        hits = self._find_queued_all(job_id)
        return hits[0] if hits else None

    def _write(self, state: str, job: Job) -> None:
        path = (self._queued_path(job.id, job.submitted_at,
                                  self._lane_of(job))
                if state == QUEUED else self._path(state, job.id))
        fsio.put_atomic(path, json.dumps(job.to_record()))
        if state == QUEUED:
            # legacy duplicates must not survive a lane-sharded
            # rewrite: the flat unstamped name (pre-stamp queues), the
            # flat STAMPED name (pre-shard queues) and the laneless
            # sharded name (pre-lane queues) — three O(1) probes
            # (requeue of a legacy job after its claim consumed the old
            # file is the normal path; this covers direct ones)
            stamped = f"{self._stamp_prefix(job.submitted_at)}-" \
                      f"{job.id}.json"
            for stale in (self._path(QUEUED, job.id),
                          os.path.join(self.dir, QUEUED, stamped),
                          os.path.join(
                              self._shard_dir(self._shard_of(job.id)),
                              stamped)):
                if stale != path and os.path.exists(stale):
                    self._remove_file(stale)

    def _read_file(self, path: str) -> Job | None:
        try:
            return Job.from_record(json.loads(fsio.read(path)))
        except (OSError, ValueError, TypeError):
            return None

    def _read(self, state: str, job_id: str) -> Job | None:
        if state == QUEUED:
            path = self._find_queued(job_id)
            return None if path is None else self._read_file(path)
        return self._read_file(self._path(state, job_id))

    def _ids(self, state: str) -> list[str]:
        if state == QUEUED:
            out = []
            for _lane, d in self._queued_dirs():
                try:
                    names = fsio.list(d)
                except OSError:
                    continue
                out.extend(self._split_queued_name(f)[1] for f in names
                           if f.endswith(".json") and ".tmp" not in f)
            return sorted(out)
        d = os.path.join(self.dir, state)
        names = [f for f in fsio.list(d)
                 if f.endswith(".json") and ".tmp" not in f]
        return sorted(os.path.splitext(f)[0] for f in names)

    def _queued_entries(self) -> list[tuple[float, str, str, str]]:
        """Sorted ``(submit stamp, job_id, path, lane)`` for every
        queued record — the queued-namespace walk shared by
        :meth:`claim` (FIFO order) and :meth:`status` (oldest age).
        Each shard's stamped names sort without being opened and the
        per-shard FIFO lists merge by stamp, so order-within-a-lane
        equals submit order; only legacy unstamped records pay a read
        to learn their submit time.  Lane comes from the DIRECTORY (no
        file open); both legacy layouts report as the bulk lane."""
        entries = []
        for lane, d in self._queued_dirs():
            try:
                # a lane/shard dir can vanish mid-scan (compaction /
                # fsck / tooling race): re-sync by skipping, never
                # classify as corruption — the next poll resees it
                names = fsio.list(d)
            except OSError:
                continue
            for fname in names:
                if not fname.endswith(".json") or ".tmp" in fname:
                    continue
                stamp, jid = self._split_queued_name(fname)
                path = os.path.join(d, fname)
                if stamp is None:
                    job = self._read_file(path)
                    if job is None:
                        continue
                    stamp = job.submitted_at
                entries.append((stamp, jid, path, lane or LANE_BULK))
        entries.sort()
        return entries

    def _claim_order(self, lane_budgets: dict | None
                     ) -> list[tuple[float, str, str, str]]:
        """Queued entries in WEIGHTED-FAIR claim order: repeat cycles
        that take up to ``budgets[lane]`` FIFO candidates from each
        lane in :data:`LANES` order.  The starvation bound this pins:
        a lane's head candidate appears after at most
        ``sum(other lanes' budgets)`` foreign candidates, however deep
        the other lanes' backlogs run.  A zero budget parks a lane
        while any budgeted lane still has entries (and drains it
        otherwise — budgets shape priority, they never deadlock the
        queue)."""
        entries = self._queued_entries()
        by_lane: dict[str, list] = {}
        for e in entries:
            by_lane.setdefault(e[3], []).append(e)
        if len(by_lane) <= 1:
            return entries
        budgets = dict(DEFAULT_LANE_BUDGETS)
        budgets.update(lane_budgets or {})
        order: list = []
        cursors = {lane: 0 for lane in by_lane}

        def _remaining(lane):
            return len(by_lane[lane]) - cursors[lane]

        while any(_remaining(lane) for lane in by_lane):
            took = 0
            for lane in LANES:
                if lane not in by_lane:
                    continue
                take = min(max(int(budgets.get(lane, 1)), 0),
                           _remaining(lane))
                if take:
                    i = cursors[lane]
                    order.extend(by_lane[lane][i:i + take])
                    cursors[lane] = i + take
                    took += take
            if not took:
                # every lane with work has budget 0: drain FIFO-by-
                # stamp anyway rather than deadlocking the claim
                tail = []
                for lane in by_lane:
                    tail.extend(by_lane[lane][cursors[lane]:])
                    cursors[lane] = len(by_lane[lane])
                order.extend(sorted(tail))
                break
        return order

    @staticmethod
    def _count_json(d: str) -> int:
        try:
            names = os.listdir(d)
        except OSError:
            return 0
        return sum(1 for f in names
                   if f.endswith(".json") and ".tmp" not in f)

    def shard_depths(self) -> dict[str, int]:
        """Per-shard queued depth summed over the lanes + the legacy
        laneless shard dir (one listdir each; the flat legacy root
        reports under ``"flat"`` only when non-empty) — the ``fleet
        status`` readout for depth concentrating in one shard."""
        out: dict[str, int] = {}
        for i in range(self.nshards):
            n = self._count_json(self._shard_dir(i))
            for lane in LANES:
                n += self._count_json(self._lane_shard_dir(lane, i))
            out[self._shard_name(i)] = n
        flat = self._count_json(os.path.join(self.dir, QUEUED))
        if flat:
            out["flat"] = flat
        return out

    def _lane_depth(self, lane: str) -> int:
        """One lane's queued depth; bulk folds in the legacy laneless
        layouts (pre-lane shard dirs + the flat root)."""
        n = sum(self._count_json(self._lane_shard_dir(lane, i))
                for i in range(self.nshards))
        if lane == LANE_BULK:
            n += sum(self._count_json(self._shard_dir(i))
                     for i in range(self.nshards))
            n += self._count_json(os.path.join(self.dir, QUEUED))
        return n

    def lane_depths(self) -> dict[str, int]:
        """Per-lane queued depth (legacy laneless records count as
        bulk) — the ``fleet status`` / pool-controller readout for a
        bulk backlog building behind the interactive lane."""
        return {lane: self._lane_depth(lane) for lane in LANES}

    def queued_ids(self) -> set[str]:
        """Every queued job id — ONE directory-name walk, no file
        opens (stamped names carry the id; legacy names ARE the id).
        The bulk-wait poll's fast path: membership here answers
        "still queued" for a whole pending set at once, where per-job
        ``state_of`` would pay its stamped-name fallback scan of this
        same directory once PER job."""
        return set(self._ids(QUEUED))

    def state_of(self, job_id: str) -> str | None:
        # O(1) probes first: the plain-named states (leased/done/failed
        # + a legacy-named queued record) are single stat calls; only a
        # job in none of them pays the queued-directory NAME scan for
        # its stamped record (no file opens — a fresh bulk submit costs
        # one listdir walk per submit, which is the cheap half of the
        # old claim()'s open-every-record cost)
        if os.path.exists(self._path(QUEUED, job_id)):
            return QUEUED
        for state in (LEASED, DONE, FAILED):
            if os.path.exists(self._path(state, job_id)):
                return state
        if self._find_queued(job_id) is not None:
            return QUEUED
        return None

    def get(self, job_id: str) -> Job | None:
        for state in _STATES:
            job = self._read(state, job_id)
            if job is not None:
                return job
        return None

    # -- fleet telemetry hooks (ISSUE 10/11) -------------------------------
    def _depth_gauge(self, job_id: str | None = None,
                     lane: str | None = None) -> None:
        """Stamp ``queue_depth`` at a state TRANSITION (submit/
        complete/fail): a timeline sampled only inside ``serve.poll``
        aliases at low poll rates — the transition points are where
        depth actually changes (test-pinned).  Streamed, so each stamp
        is a timestamped gauge event in the trace, not just the
        registry's latest-value cell.  With ``job_id``, the
        transitioning job's SHARD depth is stamped too as the
        ``queue_depth[<shard>]`` family — only that shard's count
        changed, so stamping just it keeps the per-shard timelines
        complete without N events per transition (ISSUE 11: `fleet
        status` backpressure must stay truthful when depth concentrates
        in one shard).  Disabled tracing: one flag check, no listdir.
        Enabled: bounded listdirs (queued shards + leased/ only — depth
        never reads the unbounded done/ and failed/ directories, which
        grow with survey length)."""
        if not obs.enabled():
            return
        depth = len(self._ids(QUEUED)) + len(self._ids(LEASED))
        obs.gauge("queue_depth", depth, stream=True)
        if job_id is not None:
            shard = self._shard_of(job_id)
            n = self._count_json(self._shard_dir(shard))
            for ln in LANES:
                n += self._count_json(self._lane_shard_dir(ln, shard))
            obs.gauge(f"queue_depth[{self._shard_name(shard)}]", n,
                      stream=True)
        if lane is not None:
            self._lane_gauge(lane)

    def _lane_gauge(self, lane: str) -> None:
        """Stamp the transitioning job's LANE depth as a streamed
        ``queue_depth[lane:<lane>]`` gauge event (same family as the
        per-shard stamps; only the lane whose count changed is
        stamped).  Bulk folds the legacy laneless layouts in
        (``_lane_depth``) — the timeline and the ``lane_depths``
        status readout must agree on a mid-migration queue."""
        if not obs.enabled():
            return
        obs.gauge(f"queue_depth[lane:{lane}]", self._lane_depth(lane),
                  stream=True)

    def _hop(self, job: Job, name: str, **attrs) -> Job:
        """Record one lifecycle hop of ``job``'s distributed trace (an
        obs event carrying ``trace_id`` + a parent link to the previous
        hop) and return the job with ``span`` advanced to the new
        event id — the link the NEXT hop (possibly in another process)
        chains from.  No-op passthrough when tracing is disabled or
        the job predates trace minting (legacy queue records)."""
        if job.trace_id is None:
            return job
        sid = obs.event(name, parent=job.span, trace_id=job.trace_id,
                        job=job.id, **attrs)
        return job if sid is None else dataclasses.replace(job, span=sid)

    # -- client side -------------------------------------------------------
    def submit(self, path: str, cfg: dict | None = None,
               lane: str | None = None) -> tuple[str, str]:
        """Enqueue one epoch file.  Returns ``(job_id, status)``:
        ``"submitted"`` for a fresh submission, or — for an idempotent
        dedup hit — the job's existing state (``queued/leased/done/
        failed``); a result row already in the store reports ``"done"``
        without touching the queue at all (the dedup-against-the-store
        contract).  ``lane`` (default interactive for file submits)
        picks the QoS lane — scheduling only, never job identity, so a
        re-submit on the other lane dedups instead of forking."""
        if not os.path.exists(path):
            # fail fast: content_key would silently hash the path
            # SPELLING (an unmatched glob pattern, a typo) and the
            # worker would burn its whole retry budget discovering it
            raise FileNotFoundError(f"cannot submit {path!r}: no such "
                                    "file")
        lane = validate_lane(lane, LANE_INTERACTIVE)
        cfg = dict(cfg or {})
        validate_job_cfg(cfg)
        job_id = job_key(path, cfg)
        if job_id in self.results:
            return job_id, DONE
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        try:
            est = int(os.path.getsize(path))
        except OSError:  # fault-ok: best-effort routing hint only
            est = None
        trace = new_trace_id()
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=os.path.basename(path), lane=lane)
        self._write(QUEUED, Job(id=job_id, file=os.path.abspath(path),
                                cfg=cfg, submitted_at=_submit_stamp(),
                                trace_id=trace, span=root, lane=lane,
                                sig=job_sig(cfg), est_bytes=est))
        self._depth_gauge(job_id, lane=lane)
        return job_id, "submitted"

    @staticmethod
    def _synth_est_bytes(spec) -> int | None:
        """Rough generated-batch footprint of a `simulate` job (the
        dynspec batch materialises in HBM even though the staged input
        is keys-only) — the memory-fit routing hint.  The grid comes
        from the campaign's own shape rule (one source per kind).
        Best-effort: None when the grid is not derivable."""
        from ..sim.campaign import synth_shape

        try:
            nf, nt = synth_shape(spec)
            return int(spec.n_epochs) * int(nf) * int(nt) * 4
        except (AttributeError, TypeError,
                ValueError):  # fault-ok: routing hint only
            return None

    def submit_synthetic(self, spec: dict, cfg: dict | None = None,
                         lane: str | None = None) -> tuple[str, str]:
        """Enqueue one on-device synthetic campaign (`simulate` job
        kind): ``spec`` is a sparse :func:`scintools_tpu.sim.campaign.
        spec_to_dict` payload, ``cfg`` the estimator options a normal
        job would carry.  The job has no input file — its identity is
        the content hash of (canonical spec, canonical options), and
        its result is ``spec["n_epochs"]`` idempotent rows keyed
        ``<job_id>.<epoch_index>`` in the results store.  Never batched
        with file-backed jobs: the spec rides inside the option dict,
        so ``cfg_signature`` separates the identities by construction
        (and the worker routes simulate jobs around the batcher
        entirely).  Idempotent like :meth:`submit`: a campaign whose
        epoch-0 row already exists reports ``done``.  ``lane`` defaults
        to BULK — campaigns are the traffic class the QoS lanes exist
        to keep from starving live submits."""
        from ..sim import campaign

        lane = validate_lane(lane, LANE_BULK)
        cfg = dict(cfg or {})
        # canonicalise through the spec class: sparse and materialised
        # payloads of the same campaign must share one job identity
        spec_obj = campaign.spec_from_dict(spec)
        cfg["synthetic"] = campaign.spec_to_dict(spec_obj)
        validate_job_cfg(cfg)
        job_id = content_key("synthetic", ("serve",) + cfg_signature(cfg))
        if campaign.synth_row_key(job_id, 0) in self.results:
            return job_id, DONE
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        kind = cfg["synthetic"].get("kind", "screen")
        trace = new_trace_id()
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=f"synthetic:{kind}", lane=lane)
        self._write(QUEUED, Job(id=job_id, file=f"synthetic:{kind}",
                                cfg=cfg, submitted_at=_submit_stamp(),
                                trace_id=trace, span=root, lane=lane,
                                sig=job_sig(cfg),
                                est_bytes=self._synth_est_bytes(
                                    spec_obj)))
        self._depth_gauge(job_id, lane=lane)
        return job_id, "submitted"

    def submit_infer(self, spec: dict, infer: dict | None = None,
                     cfg: dict | None = None,
                     lane: str | None = None) -> tuple[str, str]:
        """Enqueue one gradient-inference campaign (`infer` job kind,
        ISSUE 18): ``spec`` is the synthetic-campaign payload the
        forward model runs (the closed-form oracle kinds), ``infer``
        the sparse :func:`scintools_tpu.infer.infer_to_dict` optimiser
        knobs.  Both ride inside the option dict (``cfg["synthetic"]``
        + ``cfg["infer"]``) so ``cfg_signature`` separates infer jobs
        from plain simulate jobs of the same campaign by construction.
        Identity, dedup, idempotent rows, est-bytes routing and the
        BULK lane default all follow the simulate-job contract; rows
        key ``<job_id>.<epoch_index>`` and the served CSV is
        byte-identical to a direct ``process --infer`` run (one shared
        row builder, :func:`scintools_tpu.infer.infer_rows`)."""
        from ..infer import infer_from_dict, infer_to_dict
        from ..sim import campaign

        lane = validate_lane(lane, LANE_BULK)
        cfg = dict(cfg or {})
        # canonicalise both payloads: sparse and materialised dicts of
        # the same (campaign, optimiser) must share one job identity
        spec_obj = campaign.spec_from_dict(spec)
        cfg["synthetic"] = campaign.spec_to_dict(spec_obj)
        cfg["infer"] = infer_to_dict(infer_from_dict(infer))
        validate_job_cfg(cfg)
        job_id = content_key("infer", ("serve",) + cfg_signature(cfg))
        if campaign.synth_row_key(job_id, 0) in self.results:
            return job_id, DONE
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        kind = cfg["synthetic"].get("kind", "screen")
        trace = new_trace_id()
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=f"infer:{kind}", lane=lane)
        self._write(QUEUED, Job(id=job_id, file=f"infer:{kind}",
                                cfg=cfg, submitted_at=_submit_stamp(),
                                trace_id=trace, span=root, lane=lane,
                                sig=job_sig(cfg),
                                est_bytes=self._synth_est_bytes(
                                    spec_obj)))
        self._depth_gauge(job_id, lane=lane)
        return job_id, "submitted"

    def submit_search(self, spec: dict, search: dict | None = None,
                      cfg: dict | None = None,
                      lane: str | None = None) -> tuple[str, str]:
        """Enqueue one acceleration-search campaign (`search` job kind,
        ISSUE 19): ``spec`` is the synthetic-campaign payload whose
        epochs are scored, ``search`` the sparse
        :func:`scintools_tpu.search.search_to_dict` bank/pruning knobs.
        Both ride inside the option dict (``cfg["synthetic"]`` +
        ``cfg["search"]``) so ``cfg_signature`` separates search jobs
        from the simulate AND infer jobs of the same campaign by
        construction.  Identity, dedup, idempotent rows, est-bytes
        routing and the BULK lane default all follow the simulate-job
        contract; rows key ``<job_id>.<epoch_index>`` and the served
        CSV is byte-identical to a direct ``process --search`` run
        (one shared row builder,
        :func:`scintools_tpu.search.search_rows`)."""
        from ..search import search_from_dict, search_to_dict
        from ..sim import campaign

        lane = validate_lane(lane, LANE_BULK)
        cfg = dict(cfg or {})
        # canonicalise both payloads: sparse and materialised dicts of
        # the same (campaign, bank) must share one job identity
        spec_obj = campaign.spec_from_dict(spec)
        cfg["synthetic"] = campaign.spec_to_dict(spec_obj)
        cfg["search"] = search_to_dict(search_from_dict(search))
        validate_job_cfg(cfg)
        job_id = content_key("search", ("serve",) + cfg_signature(cfg))
        if campaign.synth_row_key(job_id, 0) in self.results:
            return job_id, DONE
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        kind = cfg["synthetic"].get("kind", "screen")
        trace = new_trace_id()
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=f"search:{kind}", lane=lane)
        self._write(QUEUED, Job(id=job_id, file=f"search:{kind}",
                                cfg=cfg, submitted_at=_submit_stamp(),
                                trace_id=trace, span=root, lane=lane,
                                sig=job_sig(cfg),
                                est_bytes=self._synth_est_bytes(
                                    spec_obj)))
        self._depth_gauge(job_id, lane=lane)
        return job_id, "submitted"

    def submit_compact(self) -> tuple[str, str]:
        """Enqueue one results-plane compaction (`compact` job kind):
        the worker merges the store's small segment files into one
        (utils/segments.SegmentStore.compact) — the background
        maintenance pass that keeps per-lookup segment counts bounded
        over a long campaign.  Not content-addressed: every submit is
        a fresh job (compaction is idempotent and cheap when there is
        nothing to merge), identified by its submit stamp.  Routed
        around the batcher like `simulate` jobs; writes no result
        rows."""
        stamp = _submit_stamp()
        cfg = {"compact": True}
        job_id = content_key(("compact", stamp), cfg_signature(cfg))
        trace = new_trace_id()
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file="compact:", lane=LANE_BULK)
        self._write(QUEUED, Job(id=job_id, file="compact:", cfg=cfg,
                                submitted_at=stamp, lane=LANE_BULK,
                                trace_id=trace, span=root))
        self._depth_gauge(job_id, lane=LANE_BULK)
        return job_id, "submitted"

    def submit_stream(self, feed_dir: str, cfg: dict | None = None,
                      window: int | None = None, hop: int | None = None,
                      lane: str | None = None,
                      incremental: bool | None = None,
                      resync_every: int | None = None
                      ) -> tuple[str, str]:
        """Register one live feed (`stream` job kind — ISSUE 15):
        ``feed_dir`` is an append-mode feed directory
        (scintools_tpu.stream.ingest) a producer grows chunk-by-chunk;
        the claiming worker keeps the job REGISTERED, polling the feed
        between batch claims and publishing one VERSIONED result row
        per sliding-window tick (``window`` samples, re-fit every
        ``hop`` new ones) until the feed finalizes — live curvature/
        timescale tracking across the observation.

        The job's identity is (feed path, estimator options, window/
        hop, plus the incremental-tick knobs when set): re-submitting
        the same registration dedups; the same feed under different
        options or window geometry is a different stream (different
        results).  The feed must already exist with
        a readable manifest — a typo'd path fails HERE, not after
        burning the retry budget.  ``lane`` defaults to interactive
        (a live observer's feed is exactly what the QoS lanes protect
        from bulk backlogs)."""
        from ..stream.window import validate_stream_spec

        lane = validate_lane(lane, LANE_INTERACTIVE)
        cfg = dict(cfg or {})
        if cfg.get("synthetic") is not None or cfg.get("compact"):
            raise ValueError("a stream job carries only estimator "
                             "options (no synthetic/compact payload)")
        if cfg.get("arc_stack"):
            raise ValueError("arc_stack is a campaign knob; a stream "
                             "tick fits one window")
        spec = validate_stream_spec({"feed": feed_dir,
                                     **({"window": window}
                                        if window is not None else {}),
                                     **({"hop": hop}
                                        if hop is not None else {}),
                                     **({"incremental": incremental}
                                        if incremental is not None
                                        else {}),
                                     **({"resync_every": resync_every}
                                        if resync_every is not None
                                        else {})})
        # fail fast on a non-feed: FeedReader raises FeedError
        # (ValueError) on a missing/torn manifest
        from ..stream.ingest import FeedReader

        reader = FeedReader(spec["feed"])
        cfg["stream"] = spec
        validate_job_cfg(cfg)
        job_id = content_key(("stream", spec["feed"]),
                             ("serve",) + cfg_signature(cfg))
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        est = reader.nf * spec["window"] * 4   # the resident window
        trace = new_trace_id()
        fname = f"stream:{os.path.basename(spec['feed'])}"
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=fname, lane=lane)
        self._write(QUEUED, Job(id=job_id, file=fname, cfg=cfg,
                                submitted_at=_submit_stamp(),
                                trace_id=trace, span=root, lane=lane,
                                sig=job_sig(cfg), est_bytes=est))
        self._depth_gauge(job_id, lane=lane)
        return job_id, "submitted"

    def submit_backfill(self, feed_dir: str, cfg: dict | None = None,
                        window: int | None = None,
                        hop: int | None = None, upto: int = 0,
                        parent: str | None = None) -> tuple[str, str]:
        """Enqueue the catch-up lane for a LATE-registered feed
        (ISSUE 17): one bulk-lane job that replays the already-
        committed backlog through the chunked batch path — every
        window whose end sample is ``<= upto`` — publishing the same
        versioned tick rows the live session would have, while the
        live registration fast-forwards its cursor past ``upto`` and
        keeps its tick-latency budget.  Identity is (feed, options,
        geometry, upto): re-registering the same late feed dedups; a
        later registration with a bigger backlog is a NEW backfill
        covering the longer prefix (rows are versioned by window-end
        key, so overlapping publishes merge instead of duplicating)."""
        cfg = dict(cfg or {})
        if cfg.get("synthetic") is not None or cfg.get("compact"):
            raise ValueError("a backfill job carries only estimator "
                             "options (no synthetic/compact payload)")
        from ..stream.window import validate_stream_spec

        spec = validate_stream_spec({"feed": feed_dir,
                                     **({"window": window}
                                        if window is not None else {}),
                                     **({"hop": hop}
                                        if hop is not None else {})})
        cfg.pop("stream", None)   # stateless batch replay, not a feed
        cfg["backfill"] = {**spec, "upto": int(upto),
                           **({"parent": str(parent)} if parent else {})}
        validate_job_cfg(cfg)
        job_id = content_key(("backfill", spec["feed"]),
                             ("serve",) + cfg_signature(cfg))
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        trace = new_trace_id()
        fname = f"backfill:{os.path.basename(spec['feed'])}"
        root = obs.event("job.submit", trace_id=trace, job=job_id,
                         file=fname, lane=LANE_BULK)
        est = spec["window"] * 4 * 8   # a few windows staged per chunk
        self._write(QUEUED, Job(id=job_id, file=fname, cfg=cfg,
                                submitted_at=_submit_stamp(),
                                trace_id=trace, span=root,
                                lane=LANE_BULK, sig=job_sig(cfg),
                                est_bytes=est))
        self._depth_gauge(job_id, lane=LANE_BULK)
        return job_id, "submitted"

    # -- worker side -------------------------------------------------------
    def _hint_defer(self, job: Job, hints: ClaimHints,
                    now: float) -> bool:
        """Whether claim hints say to LEAVE this candidate for another
        worker this poll.  Feed pins outrank every other hint: a feed
        pinned HERE is never deferred (its state lives on this
        worker), a feed pinned to another LIVE worker is left for it
        within the pin's own freshness window.  The sig/memory
        deferrals stay time-bounded by the job's queue age, so a hint
        can delay placement but never starve a job nothing else will
        take."""
        feed = stream_feed_of(job)
        if feed is not None:
            if feed in hints.pinned:
                return False
            if (feed in hints.pinned_elsewhere
                    and now - hints.pin_ts < hints.pin_defer_s):
                obs.inc("feed_pin_deferred")
                return True
        age = now - job.submitted_at
        if (hints.max_bytes is not None and job.est_bytes
                and job.est_bytes > hints.max_bytes
                and age < hints.mem_defer_s):
            obs.inc("pool_mem_deferred")
            return True
        if (job.sig and job.sig in hints.elsewhere
                and job.sig not in hints.prefer
                and age < hints.defer_s):
            obs.inc("affinity_deferred")
            return True
        return False

    def claim(self, worker: str, n: int, lease_s: float,
              now: float | None = None,
              lane_budgets: dict | None = None,
              hints: ClaimHints | None = None) -> list[Job]:
        """Lease up to ``n`` runnable queued jobs (weighted-fair over
        the QoS lanes via :meth:`_claim_order`, FIFO by submit time
        within a lane, backoff-eligible only).  The queued->leased
        ``os.rename`` is the race arbiter: a loser's rename raises and
        it simply moves on.  The winner immediately rewrites the
        leased record with the lease stamp (worker id + expiry).

        The submit stamp is encoded in the queued FILENAME, so the
        sorted listdir itself is FIFO and only the head candidates are
        opened — ~``n`` file reads per poll plus any skipped
        (backoff/leased-dup/hint-deferred) jobs ahead of them, instead
        of the whole queue depth.  Legacy unstamped names (queues
        written before this scheme) are still honoured: only those pay
        a read to learn their submit time, and they merge into the
        bulk lane's FIFO order.

        ``hints`` (pool-controller affinity/memory routing) defer
        candidates that are warm elsewhere or too big for this
        worker's headroom — counted as ``affinity_deferred`` /
        ``pool_mem_deferred``; a claimed candidate counts
        ``affinity_hits`` (warm here) or ``affinity_misses`` (was warm
        elsewhere, taken after its grace window anyway)."""
        now = time.time() if now is None else now
        claimed: list[Job] = []
        taken: set[str] = set()

        def runnable(jid, path):
            """The shared claim-candidate gate: duplicate-lease,
            terminal-survivor and backoff checks; the job record or
            None."""
            # a queued duplicate of a still-leased job (crash window of
            # a requeue) must not double-execute while the lease lives
            if os.path.exists(self._path(LEASED, jid)):
                return None
            # a queued survivor of a TERMINAL job is garbage, not work:
            # two racing submitters can each land a different-stamp
            # file for one id, and complete()/fail() unlink only the
            # stamp of the record they finished — the survivor is
            # collected here (two O(1) stats per head candidate per
            # poll) instead of re-executing a done or poison job
            if os.path.exists(self._path(DONE, jid)) \
                    or os.path.exists(self._path(FAILED, jid)):
                self._remove_file(path)
                return None
            job = self._read_file(path)
            if job is None or job.not_before > now:
                return None
            return job

        def attempt(jid, path, lane, job):
            """Rename-race for one candidate; the leased record or
            None on a lost race."""
            try:
                # chaos site (kind="oserror"): a lost claim race — the
                # winner-take-one rename semantics must skip, not fail
                faults.check("queue.claim_rename")
                fsio.rename_if_absent(path, self._path(LEASED, jid))
            except OSError:
                return None  # another worker won this one
            obs.inc("queue_shard_claims"
                    f"[{self._shard_name(self._shard_of(jid))}]")
            obs.inc(f"lane_claims[{lane}]")
            if hints is not None and job.sig:
                if job.sig in hints.prefer:
                    obs.inc("affinity_hits")
                elif job.sig in hints.elsewhere:
                    obs.inc("affinity_misses")
            # stamp the lease onto the record we actually renamed, not
            # the pre-rename read: another worker may have failed+
            # requeued this job in the read->rename window, and its
            # attempts/backoff must survive the claim
            fresh = self._read(LEASED, jid) or job
            fresh = self._hop(fresh, "job.claim", worker=worker,
                              attempt=fresh.attempts)
            leased = dataclasses.replace(fresh, lease_worker=worker,
                                         lease_expires_at=now + lease_s)
            self._write(LEASED, leased)
            return leased

        order = list(self._claim_order(lane_budgets))
        if hints is not None and hints.pinned:
            # pinned pre-pass: a feed whose device state lives HERE is
            # claimed ahead of lane budgets and warm-sig hints — its
            # tick latency is the whole point of the pin.  This pass
            # reads candidate records beyond the usual head window,
            # but only while pins exist (a reap/re-registration
            # transient, not steady state).
            for stamp, jid, path, lane in order:
                if len(claimed) >= n:
                    break
                job = runnable(jid, path)
                if job is None:
                    continue
                feed = stream_feed_of(job)
                if feed is None or feed not in hints.pinned:
                    continue
                leased = attempt(jid, path, lane, job)
                if leased is not None:
                    obs.inc("feed_pins")
                    claimed.append(leased)
                    taken.add(jid)
        for stamp, jid, path, lane in order:
            if len(claimed) >= n:
                break
            if jid in taken:
                continue
            job = runnable(jid, path)
            if job is None:
                continue
            if hints is not None and self._hint_defer(job, hints, now):
                continue
            leased = attempt(jid, path, lane, job)
            if leased is not None:
                claimed.append(leased)
        return claimed

    def renew(self, jobs: Sequence[Job], lease_s: float,
              now: float | None = None) -> None:
        """Extend the lease on jobs this worker still holds (called
        right before a long batch execution so a compile cannot outlive
        the lease)."""
        now = time.time() if now is None else now
        for job in jobs:
            held = self._read(LEASED, job.id)
            if held is not None and held.lease_worker == job.lease_worker:
                self._write(LEASED, dataclasses.replace(
                    held, lease_expires_at=now + lease_s))

    def release(self, job: Job) -> None:
        """Voluntarily hand a LEASED job back to the queue with its
        whole retry budget untouched (``attempts`` AND ``transients``
        unchanged, no backoff) — the stream worker's drain/idle
        handback: a long-lived `stream` registration is not a failure
        when its worker is asked to scale down, and must be claimable
        by the next worker immediately.  A job another worker already
        holds (our lease expired and was re-claimed) is left alone —
        and a job that reached a TERMINAL state under the
        at-least-once race (our lease expired, the reap requeued it,
        another worker finished it) is never resurrected: done/failed
        win, exactly as :meth:`fail` tolerates the same race."""
        if os.path.exists(self._path(DONE, job.id)) \
                or os.path.exists(self._path(FAILED, job.id)):
            self._remove(LEASED, job.id)
            return
        held = self._read(LEASED, job.id)
        if held is not None and held.lease_worker is not None \
                and held.lease_worker != job.lease_worker:
            return
        rec = held if held is not None else job
        rec = self._hop(rec, "job.requeue", reason="released")
        self._write(QUEUED, dataclasses.replace(
            rec, lease_worker=None, lease_expires_at=None,
            not_before=0.0))
        self._remove(LEASED, job.id)
        self._depth_gauge(job.id, lane=self._lane_of(rec))

    def reap_expired(self, now: float | None = None
                     ) -> tuple[list[Job], list[Job]]:
        """Requeue (or poison) every leased job whose lease has run out
        — the SIGKILLed-worker recovery path.  Returns ``(requeued,
        poisoned)``.  A leased record still inside the claim's
        rename-then-rewrite window (no expiry stamp yet) is given a
        grace period from the file's mtime."""
        now = time.time() if now is None else now
        requeued, poisoned = [], []
        for job_id in self._ids(LEASED):
            job = self._read(LEASED, job_id)
            if job is None:
                continue
            exp = job.lease_expires_at
            if exp is None:
                try:
                    exp = os.path.getmtime(self._path(LEASED, job_id)) + 30.0
                except OSError:
                    continue
            if exp > now:
                continue
            attempts = job.attempts + 1
            back = dataclasses.replace(
                job, attempts=attempts, lease_worker=None,
                lease_expires_at=None,
                error=f"lease expired (attempt {attempts})")
            if attempts > self.max_retries:
                back = self._hop(back, "job.poison",
                                 reason="lease_expired",
                                 attempt=attempts)
                self._write(FAILED, back)
                poisoned.append(back)
            else:
                # the reap hop is taken by whichever process noticed
                # the expiry — its event links to the DEAD worker's
                # claim hop, stitching the trace across the SIGKILL
                back = self._hop(back, "job.requeue",
                                 reason="lease_expired",
                                 attempt=attempts)
                back = dataclasses.replace(
                    back, not_before=now + self._backoff(attempts))
                self._write(QUEUED, back)
                requeued.append(back)
            self._remove(LEASED, job_id)
        return requeued, poisoned

    def _backoff(self, attempts: int) -> float:
        return min(self.backoff_s * (2.0 ** max(attempts - 1, 0)),
                   BACKOFF_CAP_S)

    def _remove_file(self, path: str | None) -> None:
        if path is None:
            return
        try:
            fsio.delete(path)
        except OSError:
            pass

    def _remove(self, state: str, job_id: str) -> None:
        self._remove_file(self._path(state, job_id))

    def _remove_queued(self, job: Job) -> None:
        """Drop ``job``'s queued record(s) in O(1): the lane-sharded
        stamped filename is deterministic from the record (requeues
        never mutate ``submitted_at`` or ``lane``, JSON round-trips
        the float exactly, and the shard is a pure hash of the id
        against the persisted shard count), and the only other
        variants any version ever wrote are the laneless sharded name
        (pre-lane), the flat stamped name (pre-shard) and the flat
        plain name (pre-stamp) — four unlink probes cover every layout
        plus the crash window between ``_write``'s lane-sharded write
        and its legacy unlinks, with no directory scan
        (``complete``/``fail`` run this once per job in the worker's
        hot loop)."""
        stamped = f"{self._stamp_prefix(job.submitted_at)}-{job.id}.json"
        self._remove_file(self._queued_path(job.id, job.submitted_at,
                                            self._lane_of(job)))
        self._remove_file(os.path.join(
            self._shard_dir(self._shard_of(job.id)), stamped))
        self._remove_file(os.path.join(self.dir, QUEUED, stamped))
        self._remove_file(self._path(QUEUED, job.id))

    def complete(self, job: Job) -> None:
        """Finalise a job whose result row is stored.  Tolerates the
        at-least-once window: the job may have been requeued from under
        an expired lease, so finalise from whichever state dir holds it
        (and drop any queued duplicate)."""
        job = self._hop(job, "job.complete")
        self._write(DONE, dataclasses.replace(
            job, lease_worker=None, lease_expires_at=None, error=None))
        self._remove(LEASED, job.id)
        self._remove_queued(job)
        self._remove(FAILED, job.id)
        self._depth_gauge(job.id, lane=self._lane_of(job))

    def fail(self, job: Job, error: str, retryable: bool = True,
             transient: bool = False, now: float | None = None) -> str:
        """Record a job failure: requeue with exponential backoff while
        retries remain (and the failure is retryable), else move to the
        terminal ``failed/`` state.  Returns the resulting state.

        ``transient=True`` marks an INFRASTRUCTURE failure (device OOM,
        lease race, preemption — faults.classify_error): the job
        requeues with ``attempts`` UNCHANGED, so an unlucky placement
        can never burn the bounded retry budget into ``failed/`` poison
        for an error that succeeds on the next worker.  Transient
        requeues count (and exponentially back off) through the
        separate ``transients`` field.  They are bounded too — once a
        job has taken ``max_transients`` budget-free requeues
        (default 10x ``max_retries``), further transient failures
        ESCALATE to the normal attempts-burning path, so a
        misclassified deterministic error still terminates in
        ``failed/`` instead of livelocking drain/wait forever.

        A job another worker already COMPLETED (the at-least-once race:
        this worker's lease expired mid-batch, the job was requeued and
        finished elsewhere) is never un-completed — the stale failure
        is dropped and ``done`` wins, symmetric with ``complete``'s
        tolerance of requeued copies."""
        now = time.time() if now is None else now
        if job.id in self.results \
                or os.path.exists(self._path(DONE, job.id)):
            self._remove(LEASED, job.id)
            self._remove_queued(job)
            self._depth_gauge(job.id, lane=self._lane_of(job))
            return DONE
        if transient and retryable \
                and job.transients < self.max_transients:
            transients = job.transients + 1
            job = self._hop(job, "job.requeue", reason="transient",
                            transients=transients, error=error[:200])
            self._write(QUEUED, dataclasses.replace(
                job, transients=transients, error=error,
                lease_worker=None, lease_expires_at=None,
                not_before=now + self._backoff(transients)))
            self._remove(LEASED, job.id)
            self._depth_gauge(job.id, lane=self._lane_of(job))
            return QUEUED
        attempts = job.attempts + 1
        rec = dataclasses.replace(job, attempts=attempts, error=error,
                                  lease_worker=None, lease_expires_at=None)
        if not retryable or attempts > self.max_retries:
            rec = self._hop(rec, "job.fail", attempt=attempts,
                            error=error[:200])
            self._write(FAILED, rec)
            state = FAILED
        else:
            rec = self._hop(rec, "job.requeue", reason="attempt",
                            attempt=attempts, error=error[:200])
            self._write(QUEUED, dataclasses.replace(
                rec, not_before=now + self._backoff(attempts)))
            state = QUEUED
        self._remove(LEASED, job.id)
        if state == FAILED:
            self._remove_queued(job)
        self._depth_gauge(job.id, lane=self._lane_of(job))
        return state

    # -- introspection / control -------------------------------------------
    def counts(self) -> dict:
        return {state: len(self._ids(state)) for state in _STATES}

    def status(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        st = self.counts()
        st["results"] = len(self.results.keys())
        st["depth"] = st[QUEUED] + st[LEASED]
        st["drain_requested"] = self.drain_requested()
        st["shards"] = self.nshards
        entries = self._queued_entries()
        # per-lane depths fall out of the same walk for free (legacy
        # layouts already report as bulk) — no second listdir pass
        lanes = {lane: 0 for lane in LANES}
        for e in entries:
            lanes[e[3]] = lanes.get(e[3], 0) + 1
        st["lanes"] = lanes
        # submit ages straight from the filename stamps (shared walk
        # with claim; only legacy records were opened)
        oldest = (now - entries[0][0]) if entries else None
        st["oldest_queued_s"] = round(oldest, 3) if oldest is not None \
            else None
        return st

    def empty(self) -> bool:
        return not self._ids(QUEUED) and not self._ids(LEASED)

    def jobs(self, state: str) -> list[Job]:
        return [j for j in (self._read(state, i) for i in self._ids(state))
                if j is not None]

    # drain: a marker file — any client can request it, the worker exits
    # once the queue is empty (serve/worker.py honours it)
    def _drain_path(self) -> str:
        return os.path.join(self.dir, "control", "drain")

    def request_drain(self) -> None:
        fsio.put_atomic(self._drain_path(), str(time.time()))

    def clear_drain(self) -> None:
        try:
            fsio.delete(self._drain_path())
        except OSError:
            pass

    def drain_requested(self) -> bool:
        return os.path.exists(self._drain_path())

    # per-worker drain (ISSUE 13): the pool controller's scale-down
    # handle — same tmp+replace marker protocol as the global drain,
    # but only the NAMED worker honours it (stop claiming, finish the
    # batches it holds, consume the marker, exit); the queue keeps
    # draining through every other worker, so scale-down can never
    # lose or strand a job
    @staticmethod
    def _safe_worker(worker_id: str) -> str:
        return "".join(c if (c.isalnum() or c in "._-") else "_"
                       for c in worker_id) or "worker"

    def _worker_drain_path(self, worker_id: str) -> str:
        return os.path.join(self.dir, "control",
                            f"drain.{self._safe_worker(worker_id)}")

    def request_worker_drain(self, worker_id: str) -> None:
        fsio.put_atomic(self._worker_drain_path(worker_id),
                        str(time.time()))

    def worker_drain_requested(self, worker_id: str) -> bool:
        return os.path.exists(self._worker_drain_path(worker_id))

    def clear_worker_drain(self, worker_id: str) -> None:
        try:
            fsio.delete(self._worker_drain_path(worker_id))
        except OSError:
            pass
