"""Durable on-disk job queue for the resident survey service.

The queue directory IS the protocol (no network dependency): clients
and workers on the same filesystem coordinate purely through atomic
file operations, the way the reference's append-mode CSV made a killed
batch run resumable (scint_utils.py:75-108) — here generalised to a
real work queue with leases, as real-time pulsar-search pipelines front
their persistent accelerator workers (arXiv:1804.05335 §real-time
operation).

Layout (all JSON, one file per job, written tmp+``os.replace`` so a
crash can never leave a torn record)::

    qdir/
      queued/<job_id>.json    submitted, waiting for a worker
      leased/<job_id>.json    claimed by a worker, lease expiry inside
      done/<job_id>.json      completed (result row in results/)
      failed/<job_id>.json    terminal: retries exhausted (poison input)
      results/                utils.store.ResultsStore (idempotent rows)
      control/drain           drain marker (serve exits when empty)

Semantics:

* **Idempotent submit** — ``job_id = content_key(file bytes, config)``
  (utils/store.py), so re-submitting the same epoch+config is a no-op
  in every state, including ``done`` (the result row already exists in
  ``results/``).
* **Leases, not locks** — ``claim`` moves ``queued/ -> leased/`` with
  an expiry stamp; the move is an ``os.rename`` whose atomicity picks
  exactly one winner among racing workers.  A SIGKILLed worker's
  leased jobs are reclaimed by ``reap_expired`` after the lease runs
  out: back to ``queued/`` with ``attempts + 1`` and exponential
  backoff, or to ``failed/`` once ``max_retries`` is exhausted.
* **At-least-once execution, exactly-once results** — a lease can
  expire under a live worker (long compile), so the same job may
  execute twice; the content-keyed results store makes the second
  write idempotent, and ``complete`` finalises from whichever state
  dir the job landed in.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Sequence

from ..utils.store import ResultsStore, content_key

# job states = subdirectories
QUEUED, LEASED, DONE, FAILED = "queued", "leased", "done", "failed"
_STATES = (QUEUED, LEASED, DONE, FAILED)

DEFAULT_MAX_RETRIES = 3
DEFAULT_BACKOFF_S = 1.0
BACKOFF_CAP_S = 300.0

_LAST_STAMP = 0.0


def _submit_stamp() -> float:
    """Strictly-increasing submit timestamps within one process, so
    FIFO claim order equals submit order even when ``time.time()``
    ties across a tight submit loop (claim's tiebreak would otherwise
    fall back to hash order)."""
    global _LAST_STAMP
    t = time.time()
    if t <= _LAST_STAMP:
        t = _LAST_STAMP + 1e-6
    _LAST_STAMP = t
    return t


def cfg_signature(cfg: dict) -> tuple:
    """Canonical hashable form of a job's processing options: sorted
    (key, value) pairs with lists normalised to tuples AND defaults
    dropped — ``None``, boolean ``False`` (every serve boolean option
    defaults off) and the default ``arc_method`` — so a sparse dict
    (``{"lamsteps": True}``) and the CLI's fully-materialised option
    dict hash to the SAME job identity (the idempotent-submit
    contract), regardless of dict ordering or JSON round-trips."""
    def norm(v):
        if isinstance(v, (list, tuple)):
            return tuple(norm(x) for x in v)
        return v

    out = []
    for k, v in sorted((cfg or {}).items()):
        if v is None or v is False:
            continue
        if k == "arc_method" and v == "norm_sspec":
            continue
        out.append((str(k), norm(v)))
    return tuple(out)


def job_key(path: str, cfg: dict) -> str:
    """The job's identity AND its results-store key: a content hash of
    the input file's bytes + the processing options.  Identical epochs
    submitted under different path spellings dedup to one job."""
    return content_key(path, ("serve",) + cfg_signature(cfg))


@dataclasses.dataclass(frozen=True)
class Job:
    """One queued unit of work (an observing epoch + its options)."""

    id: str
    file: str
    cfg: dict
    submitted_at: float
    attempts: int = 0
    not_before: float = 0.0
    lease_worker: str | None = None
    lease_expires_at: float | None = None
    error: str | None = None
    # retry in a singleton batch: set when a WHOLE batch failed, so the
    # members cannot re-coalesce into the same failing batch and burn
    # every healthy member's retry budget alongside the poison one
    solo: bool = False

    def to_record(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_record(cls, rec: dict) -> "Job":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in rec.items() if k in fields})


class JobQueue:
    """Durable filesystem job queue: atomic state files, rename-arbited
    claims with expiring leases, bounded-retry requeues, and a
    content-keyed results store (see the module docstring for the
    directory protocol)."""

    def __init__(self, directory: str,
                 max_retries: int = DEFAULT_MAX_RETRIES,
                 backoff_s: float = DEFAULT_BACKOFF_S):
        self.dir = directory
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        for sub in _STATES + ("control",):
            os.makedirs(os.path.join(directory, sub), exist_ok=True)
        self.results = ResultsStore(os.path.join(directory, "results"))

    # -- paths / low-level records -----------------------------------------
    def _path(self, state: str, job_id: str) -> str:
        return os.path.join(self.dir, state, f"{job_id}.json")

    def _write(self, state: str, job: Job) -> None:
        path = self._path(state, job.id)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(job.to_record(), fh)
        os.replace(tmp, path)

    def _read(self, state: str, job_id: str) -> Job | None:
        try:
            with open(self._path(state, job_id)) as fh:
                return Job.from_record(json.load(fh))
        except (OSError, ValueError, TypeError):
            return None

    def _ids(self, state: str) -> list[str]:
        d = os.path.join(self.dir, state)
        return sorted(os.path.splitext(f)[0] for f in os.listdir(d)
                      if f.endswith(".json"))

    def state_of(self, job_id: str) -> str | None:
        for state in _STATES:
            if os.path.exists(self._path(state, job_id)):
                return state
        return None

    def get(self, job_id: str) -> Job | None:
        for state in _STATES:
            job = self._read(state, job_id)
            if job is not None:
                return job
        return None

    # -- client side -------------------------------------------------------
    def submit(self, path: str, cfg: dict | None = None) -> tuple[str, str]:
        """Enqueue one epoch file.  Returns ``(job_id, status)``:
        ``"submitted"`` for a fresh submission, or — for an idempotent
        dedup hit — the job's existing state (``queued/leased/done/
        failed``); a result row already in the store reports ``"done"``
        without touching the queue at all (the dedup-against-the-store
        contract)."""
        if not os.path.exists(path):
            # fail fast: content_key would silently hash the path
            # SPELLING (an unmatched glob pattern, a typo) and the
            # worker would burn its whole retry budget discovering it
            raise FileNotFoundError(f"cannot submit {path!r}: no such "
                                    "file")
        cfg = dict(cfg or {})
        job_id = job_key(path, cfg)
        if job_id in self.results:
            return job_id, DONE
        existing = self.state_of(job_id)
        if existing is not None:
            return job_id, existing
        self._write(QUEUED, Job(id=job_id, file=os.path.abspath(path),
                                cfg=cfg, submitted_at=_submit_stamp()))
        return job_id, "submitted"

    # -- worker side -------------------------------------------------------
    def claim(self, worker: str, n: int, lease_s: float,
              now: float | None = None) -> list[Job]:
        """Lease up to ``n`` runnable queued jobs (FIFO by submit time,
        backoff-eligible only).  The queued->leased ``os.rename`` is
        the race arbiter: a loser's rename raises and it simply moves
        on.  The winner immediately rewrites the leased record with
        the lease stamp (worker id + expiry)."""
        now = time.time() if now is None else now
        claimed: list[Job] = []
        candidates = []
        for job_id in self._ids(QUEUED):
            job = self._read(QUEUED, job_id)
            if job is None or job.not_before > now:
                continue
            # a queued duplicate of a still-leased job (crash window of
            # a requeue) must not double-execute while the lease lives
            if os.path.exists(self._path(LEASED, job_id)):
                continue
            candidates.append(job)
        candidates.sort(key=lambda j: (j.submitted_at, j.id))
        for job in candidates:
            if len(claimed) >= n:
                break
            try:
                os.rename(self._path(QUEUED, job.id),
                          self._path(LEASED, job.id))
            except OSError:
                continue  # another worker won this one
            # stamp the lease onto the record we actually renamed, not
            # the pre-rename read: another worker may have failed+
            # requeued this job in the read->rename window, and its
            # attempts/backoff must survive the claim
            fresh = self._read(LEASED, job.id) or job
            leased = dataclasses.replace(fresh, lease_worker=worker,
                                         lease_expires_at=now + lease_s)
            self._write(LEASED, leased)
            claimed.append(leased)
        return claimed

    def renew(self, jobs: Sequence[Job], lease_s: float,
              now: float | None = None) -> None:
        """Extend the lease on jobs this worker still holds (called
        right before a long batch execution so a compile cannot outlive
        the lease)."""
        now = time.time() if now is None else now
        for job in jobs:
            held = self._read(LEASED, job.id)
            if held is not None and held.lease_worker == job.lease_worker:
                self._write(LEASED, dataclasses.replace(
                    held, lease_expires_at=now + lease_s))

    def reap_expired(self, now: float | None = None
                     ) -> tuple[list[Job], list[Job]]:
        """Requeue (or poison) every leased job whose lease has run out
        — the SIGKILLed-worker recovery path.  Returns ``(requeued,
        poisoned)``.  A leased record still inside the claim's
        rename-then-rewrite window (no expiry stamp yet) is given a
        grace period from the file's mtime."""
        now = time.time() if now is None else now
        requeued, poisoned = [], []
        for job_id in self._ids(LEASED):
            job = self._read(LEASED, job_id)
            if job is None:
                continue
            exp = job.lease_expires_at
            if exp is None:
                try:
                    exp = os.path.getmtime(self._path(LEASED, job_id)) + 30.0
                except OSError:
                    continue
            if exp > now:
                continue
            attempts = job.attempts + 1
            back = dataclasses.replace(
                job, attempts=attempts, lease_worker=None,
                lease_expires_at=None,
                error=f"lease expired (attempt {attempts})")
            if attempts > self.max_retries:
                self._write(FAILED, back)
                poisoned.append(back)
            else:
                back = dataclasses.replace(
                    back, not_before=now + self._backoff(attempts))
                self._write(QUEUED, back)
                requeued.append(back)
            self._remove(LEASED, job_id)
        return requeued, poisoned

    def _backoff(self, attempts: int) -> float:
        return min(self.backoff_s * (2.0 ** max(attempts - 1, 0)),
                   BACKOFF_CAP_S)

    def _remove(self, state: str, job_id: str) -> None:
        try:
            os.remove(self._path(state, job_id))
        except OSError:
            pass

    def complete(self, job: Job) -> None:
        """Finalise a job whose result row is stored.  Tolerates the
        at-least-once window: the job may have been requeued from under
        an expired lease, so finalise from whichever state dir holds it
        (and drop any queued duplicate)."""
        self._write(DONE, dataclasses.replace(
            job, lease_worker=None, lease_expires_at=None, error=None))
        for state in (LEASED, QUEUED, FAILED):
            self._remove(state, job.id)

    def fail(self, job: Job, error: str, retryable: bool = True,
             now: float | None = None) -> str:
        """Record a job failure: requeue with exponential backoff while
        retries remain (and the failure is retryable), else move to the
        terminal ``failed/`` state.  Returns the resulting state.

        A job another worker already COMPLETED (the at-least-once race:
        this worker's lease expired mid-batch, the job was requeued and
        finished elsewhere) is never un-completed — the stale failure
        is dropped and ``done`` wins, symmetric with ``complete``'s
        tolerance of requeued copies."""
        now = time.time() if now is None else now
        if job.id in self.results \
                or os.path.exists(self._path(DONE, job.id)):
            for s in (LEASED, QUEUED):
                self._remove(s, job.id)
            return DONE
        attempts = job.attempts + 1
        rec = dataclasses.replace(job, attempts=attempts, error=error,
                                  lease_worker=None, lease_expires_at=None)
        if not retryable or attempts > self.max_retries:
            self._write(FAILED, rec)
            state = FAILED
        else:
            self._write(QUEUED, dataclasses.replace(
                rec, not_before=now + self._backoff(attempts)))
            state = QUEUED
        for s in (LEASED,) + ((QUEUED,) if state == FAILED else ()):
            self._remove(s, job.id)
        return state

    # -- introspection / control -------------------------------------------
    def counts(self) -> dict:
        return {state: len(self._ids(state)) for state in _STATES}

    def status(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        st = self.counts()
        st["results"] = len(self.results.keys())
        st["depth"] = st[QUEUED] + st[LEASED]
        st["drain_requested"] = self.drain_requested()
        oldest = None
        for job_id in self._ids(QUEUED):
            job = self._read(QUEUED, job_id)
            if job is not None:
                age = now - job.submitted_at
                oldest = age if oldest is None else max(oldest, age)
        st["oldest_queued_s"] = round(oldest, 3) if oldest is not None \
            else None
        return st

    def empty(self) -> bool:
        return not self._ids(QUEUED) and not self._ids(LEASED)

    def jobs(self, state: str) -> list[Job]:
        return [j for j in (self._read(state, i) for i in self._ids(state))
                if j is not None]

    # drain: a marker file — any client can request it, the worker exits
    # once the queue is empty (serve/worker.py honours it)
    def _drain_path(self) -> str:
        return os.path.join(self.dir, "control", "drain")

    def request_drain(self) -> None:
        path = self._drain_path()
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(str(time.time()))
        os.replace(tmp, path)

    def clear_drain(self) -> None:
        try:
            os.remove(self._drain_path())
        except OSError:
            pass

    def drain_requested(self) -> bool:
        return os.path.exists(self._drain_path())
