"""Batched MAP optimisation by Adam through a differentiable loss.

The engine half of the differentiable inference plane (ISSUE 18): a
shape-stable, jit-safe gradient-descent loop over a ``[B, S, P]`` state
— B independent epochs x S multi-start initialisations x P unconstrained
parameters — against any per-epoch scalar loss ``loss_fn(u, dat)``
built by :mod:`scintools_tpu.infer.loss`.

Shape discipline follows the split-backend style (PR 14 / fit/lm.py):

* ``steps`` is the STATIC loop ceiling — part of the compiled program's
  identity (one program per physics grid x optimiser config);
* ``steps_rt`` is a TRACED runtime input bounding the executed
  iterations at ``min(steps_rt, steps)`` — warm reruns with a different
  iteration budget never recompile (mirrors ``lm_fit_jax(steps_rt=)``);
* every lane carries its own convergence mask: a lane freezes (state
  stops updating, its step count stops) once its gradient norm drops to
  ``tol``, while the ``lax.while_loop`` keeps running lanes hot and
  exits early only when ALL lanes froze.

Uncertainty at the optimum is curvature-based: the Hessian of the loss
in the unconstrained coordinates, inverted (with a jitter floor) to a
covariance, scaled to physical units by the caller's transform
Jacobian (delta method) — see :func:`fisher_sigma_u`.
"""

from __future__ import annotations

import typing

__all__ = ["MapFitResult", "map_fit", "select_best", "fisher_sigma_u"]


class MapFitResult(typing.NamedTuple):
    """Full multi-start state at loop exit (all arrays lead ``[B, S]``)."""

    u: typing.Any          # [B, S, P] unconstrained params at exit
    loss: typing.Any       # [B, S] loss at exit
    grad_norm: typing.Any  # [B, S] gradient norm at exit
    converged: typing.Any  # [B, S] bool: grad_norm <= tol
    steps: typing.Any      # [B, S] int32 iterations each lane took


def _batched_value_and_grad(loss_fn):
    import jax

    # per-lane scalar loss -> [B, S] values / [B, S, P] grads; the data
    # pytree has one leading B axis shared by that epoch's S starts
    return jax.vmap(jax.vmap(jax.value_and_grad(loss_fn),
                             in_axes=(0, None)),
                    in_axes=(0, 0))


def map_fit(loss_fn, u0, dat, *, steps: int, steps_rt=None,
            lr: float = 0.05, tol: float = 1e-3,
            b1: float = 0.9, b2: float = 0.999,
            eps: float = 1e-8) -> MapFitResult:
    """Run masked batched Adam from ``u0 [B, S, P]`` against per-epoch
    data ``dat`` (a pytree whose leaves lead with the B axis).

    ``loss_fn(u [P], dat_slice) -> scalar`` must be jax-traceable; the
    whole loop is designed to run INSIDE the caller's jit (the infer
    program), so nothing here touches the host.
    """
    import jax
    import jax.numpy as jnp

    steps = int(steps)
    u0 = jnp.asarray(u0)
    B, S, P = u0.shape
    vg = _batched_value_and_grad(loss_fn)
    limit = (jnp.uint32(steps) if steps_rt is None
             else jnp.minimum(jnp.asarray(steps_rt, dtype=jnp.uint32),
                              jnp.uint32(steps)))
    zero = jnp.zeros_like(u0)

    def gnorm(g):
        return jnp.sqrt(jnp.sum(g * g, axis=-1))

    def cond(state):
        i, _u, _m, _v, active, _taken = state
        return jnp.logical_and(i < limit, jnp.any(active))

    def body(state):
        i, u, m, v, active, taken = state
        _val, g = vg(u, dat)
        # NaN gradients (a lane that wandered into a non-finite loss
        # region) freeze the lane rather than poisoning its state
        finite = jnp.all(jnp.isfinite(g), axis=-1)
        live = jnp.logical_and(active, jnp.logical_and(
            finite, gnorm(g) > tol))
        g = jnp.where(live[..., None], g, 0.0)
        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * g * g
        t = (i + 1).astype(u.dtype)
        mhat = m / (1.0 - b1 ** t)
        vhat = v / (1.0 - b2 ** t)
        du = lr * mhat / (jnp.sqrt(vhat) + eps)
        u = jnp.where(live[..., None], u - du, u)
        taken = taken + live.astype(taken.dtype)
        return (i + 1, u, m, v, live, taken)

    state = (jnp.uint32(0), u0, zero, zero,
             jnp.ones((B, S), dtype=bool),
             jnp.zeros((B, S), dtype=jnp.int32))
    _i, u, _m, _v, _active, taken = jax.lax.while_loop(cond, body, state)
    loss, g = vg(u, dat)
    gn = gnorm(g)
    return MapFitResult(u=u, loss=loss, grad_norm=gn,
                        converged=gn <= tol, steps=taken)


def select_best(res: MapFitResult) -> dict:
    """Pick each epoch's best start: minimum FINITE loss over the S
    axis (non-finite lanes rank last; an epoch whose every start
    diverged keeps start 0 and reports its non-finite loss, which the
    row builder quarantines).  Returns ``[B]``-leading arrays."""
    import jax.numpy as jnp

    loss = jnp.where(jnp.isfinite(res.loss), res.loss, jnp.inf)
    best = jnp.argmin(loss, axis=1)                          # [B]
    take = jnp.take_along_axis
    pick = best[:, None]
    return {
        "u": take(res.u, pick[..., None], axis=1)[:, 0, :],  # [B, P]
        "loss": take(res.loss, pick, axis=1)[:, 0],
        "grad_norm": take(res.grad_norm, pick, axis=1)[:, 0],
        "converged": take(res.converged, pick, axis=1)[:, 0],
        "steps": take(res.steps, pick, axis=1)[:, 0],
        "start": best,
    }


def fisher_sigma_u(loss_fn, u_best, dat, nobs: float | None = None,
                   jitter: float = 1e-6) -> typing.Any:
    """Curvature (observed-Fisher) 1-sigma in the UNCONSTRAINED
    coordinates at each epoch's optimum ``u_best [B, P]``.

    ``H = hessian(loss)`` per epoch; ``cov = inv(H + jitter I)``.  When
    the loss is half the (normalised) residual sum of squares, passing
    ``nobs`` scales the covariance by the reduced chi-square
    ``2 L / (nobs - P)`` — the standard least-squares error estimate
    (the LM fitter's convention).  Negative curvature directions clip
    to zero variance rather than going imaginary.  The caller maps to
    physical units via its transform Jacobian (delta method).
    """
    import jax
    import jax.numpy as jnp

    u_best = jnp.asarray(u_best)
    P = u_best.shape[-1]
    hess = jax.vmap(jax.hessian(loss_fn), in_axes=(0, 0))
    H = hess(u_best, dat)                                    # [B, P, P]
    H = H + jitter * jnp.eye(P, dtype=H.dtype)
    cov = jnp.linalg.inv(H)
    var = jnp.clip(jnp.diagonal(cov, axis1=-2, axis2=-1), 0.0, None)
    if nobs is not None:
        loss = jax.vmap(loss_fn, in_axes=(0, 0))(u_best, dat)
        s2 = 2.0 * loss / jnp.maximum(float(nobs) - P, 1.0)
        var = var * jnp.clip(s2, 0.0, None)[:, None]
    return jnp.sqrt(var)                                     # [B, P]
