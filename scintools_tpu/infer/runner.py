"""The infer campaign engine: one compiled MAP program per physics grid.

Ties the plane together (ISSUE 18): a :class:`InferSpec` of optimiser
knobs rides next to a synthetic campaign spec; the pair (plus the
analysis config fields the loss geometry consumes) keys ONE memoised
jit program per (generator identity, grid, optimiser statics, batch
rung).  The program is the full forward-and-backward chain on device —
``uint32 key rows -> generator -> (sspec profile | ACF cuts) ->
multi-start Adam -> Fisher errors`` — wrapped in
``obs.instrument_jit(step, "infer.step")`` so warm reruns are
counter-auditable (``jit_cache_miss == 0``).

Identity discipline mirrors the simulate route:

* the batch axis pads to the bucket ladder rung (``buckets.rung_for``)
  by repeating the last key row — every campaign size within a rung
  shares one compiled program, pad lanes are sliced off;
* the iteration budget executes as the TRACED input ``opt_steps_rt``
  (ceiling = the static ``opt_steps`` program key), so rerunning with a
  shorter budget never recompiles;
* :func:`infer_rows` is the ONE row builder shared by the CLI ``--infer``
  engine and the serve ``infer`` job runner — served CSV bytes are
  identical to a direct run's by construction.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import buckets, obs
from ..sim import campaign
from .loss import make_acf_loss, make_arc_loss
from .map_fit import fisher_sigma_u, map_fit, select_best

__all__ = ["InferSpec", "validate_infer", "infer_to_dict",
           "infer_from_dict", "validate_infer_config",
           "infer_campaign", "infer_rows"]


@dataclasses.dataclass(frozen=True)
class InferSpec:
    """Optimiser knobs of one infer campaign.  All fields are PROGRAM
    statics except that ``opt_steps`` is only the compiled ceiling —
    the executed budget is the runtime input (see module docstring)."""

    opt_steps: int = 400   # static Adam iteration ceiling (program key)
    starts: int = 8        # multi-start inits per epoch
    lr: float = 0.05       # Adam step size in unconstrained coords
    tol: float = 1e-3      # per-lane freeze threshold on |grad|
    spread: float = 0.25   # multi-start lattice scale (u-space)
    seed: int = 0          # lattice seed (host-side, deterministic)


def validate_infer(inf: InferSpec) -> None:
    """Loud validation at submit/build time (the serve contract: a bad
    payload must fail before it burns a retry budget)."""
    if not 1 <= int(inf.opt_steps) <= 100_000:
        raise ValueError(f"opt_steps must be in [1, 100000], got "
                         f"{inf.opt_steps}")
    if not 1 <= int(inf.starts) <= 256:
        raise ValueError(f"starts must be in [1, 256], got {inf.starts}")
    if not inf.lr > 0:
        raise ValueError(f"lr must be > 0, got {inf.lr}")
    if not inf.tol > 0:
        raise ValueError(f"tol must be > 0, got {inf.tol}")
    if inf.spread < 0:
        raise ValueError(f"spread must be >= 0, got {inf.spread}")
    if not 0 <= int(inf.seed) < 2 ** 32:
        raise ValueError(f"seed must be a uint32, got {inf.seed}")


def infer_to_dict(inf: InferSpec) -> dict:
    """Canonical sparse JSON-able form (the serve job payload under
    ``cfg["infer"]`` and the CLI resume-key ingredient): only
    non-default fields, so sparse client dicts and materialised CLI
    dicts share one job identity (the spec_to_dict convention)."""
    d0 = InferSpec()
    return {f.name: getattr(inf, f.name)
            for f in dataclasses.fields(InferSpec)
            if getattr(inf, f.name) != getattr(d0, f.name)}


def infer_from_dict(d: dict | None) -> InferSpec:
    """Inverse of :func:`infer_to_dict`; unknown keys raise."""
    d = dict(d or {})
    names = {f.name for f in dataclasses.fields(InferSpec)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(f"unknown InferSpec field(s): {sorted(unknown)}")
    inf = InferSpec(**d)
    validate_infer(inf)
    return inf


def validate_infer_config(spec, inf: InferSpec, config) -> None:
    """Cross-field validation of (campaign, optimiser, analysis) — the
    shared gate of the CLI engine and ``JobQueue.submit_infer``."""
    validate_infer(inf)
    if spec.kind not in ("arc", "acf"):
        raise ValueError(
            f"infer supports the closed-form synthetic kinds 'arc' and "
            f"'acf' (kind={spec.kind!r}; screen-kind gradient fits are "
            f"roadmap follow-up work)")
    if spec.kind == "arc" and not config.lamsteps:
        raise ValueError(
            "arc-kind infer requires lamsteps=True: the bounded-log "
            "curvature transform and the injected truth are both in "
            "beta-eta units")


_PARAM_NAMES = {"arc": ("betaeta",), "acf": ("tau", "dnu", "amp", "wn")}

# program cache: one compiled step per (generator identity, analysis
# fingerprint, optimiser statics, batch rung) — the infer plane's
# analogue of the driver's _make_pipeline_cached memo
_PROGRAMS: dict = {}


def _cfg_fingerprint(config, kind: str) -> tuple:
    """The analysis-config fields the infer program's trace consumes —
    its share of the program identity (everything else is inert)."""
    if kind == "acf":
        return ("acf", config.fft_lens)
    return ("arc", bool(config.lamsteps), bool(config.prewhite),
            config.window, float(config.window_frac), config.fft_lens,
            bool(config.fused_sspec), int(config.arc_numsteps),
            int(config.arc_startbin), int(config.arc_cutmid),
            config.arc_delmax,
            tuple(float(x) for x in config.arc_constraint),
            float(config.ref_freq), int(config.arc_nsmooth),
            config.arc_tail)


def _build_acf_loss(spec, config, inf: InferSpec):
    nf, nt = campaign.synth_shape(spec)
    freqs, times = campaign.synth_axes(spec)
    acf_lens = "fast" if config.fft_lens == "fast" else "exact"
    L = make_acf_loss(nf, nt, dt=float(times[1] - times[0]),
                      df=float(freqs[1] - freqs[0]), lens=acf_lens,
                      starts=inf.starts, spread=inf.spread,
                      seed=inf.seed)
    return L, L.prep


def _build_arc_loss(spec, config, inf: InferSpec):
    import jax
    import jax.numpy as jnp

    from ..fit.arc_fit import make_arc_fitter
    from ..ops.sspec import sspec as sspec_op, sspec_axes
    from ..parallel.driver import lambda_resample_matrix

    freqs, times = campaign.synth_axes(spec)
    nsub = len(times)
    df = float(freqs[1] - freqs[0])
    dt = float(times[1] - times[0])
    fc = float(np.mean(freqs))
    W, _lam, dlam = lambda_resample_matrix(freqs)
    nf_s = W.shape[0]
    fdop, tdel, beta = sspec_axes(nf_s, nsub, dt, df, dlam=dlam,
                                  lens=config.fft_lens)
    # the summary fitter's own per-epoch profile extraction — the loss
    # optimises over EXACTLY the profile the argmax fitter measures
    # (norm_sspec method regardless of config.arc_method: only that
    # flavour exposes profile_of)
    fitter = make_arc_fitter(
        fdop=fdop, yaxis=beta, tdel=tdel, freq=fc, lamsteps=True,
        method="norm_sspec", numsteps=config.arc_numsteps,
        startbin=config.arc_startbin, cutmid=config.arc_cutmid,
        nsmooth=config.arc_nsmooth, delmax=config.arc_delmax,
        constraint=config.arc_constraint, ref_freq=config.ref_freq,
        arc_tail=config.arc_tail)
    L = make_arc_loss(fdop, beta, tdel, fc, ref_freq=config.ref_freq,
                      delmax=config.arc_delmax,
                      numsteps=config.arc_numsteps,
                      startbin=config.arc_startbin,
                      cutmid=config.arc_cutmid,
                      constraint=config.arc_constraint,
                      starts=inf.starts, spread=inf.spread,
                      seed=inf.seed)
    W_np = np.asarray(W)

    def prep(dyn_batch):
        fft_in = jnp.einsum("lf,bft->blt", jnp.asarray(W_np), dyn_batch)
        sec_b = sspec_op(fft_in, prewhite=config.prewhite,
                         window=config.window,
                         window_frac=config.window_frac, db=True,
                         backend="jax", lens=config.fft_lens,
                         fused=config.fused_sspec)
        prof, _noise = jax.vmap(fitter.profile_of)(sec_b)
        return L.prep(prof)

    return L, prep


def _infer_program(spec, config, inf: InferSpec, rung: int):
    """Memoised jit'd step ``(raw uint32 [rung, 2+F], opt_steps_rt) ->
    dict of [rung]-leading result arrays``."""
    import jax

    gid = campaign.generator_id(spec)
    key = (gid, int(rung), _cfg_fingerprint(config, spec.kind),
           dataclasses.astuple(inf))
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog

    import jax.numpy as jnp

    gen = campaign.synth_generator(gid)
    build = _build_acf_loss if spec.kind == "acf" else _build_arc_loss
    L, prep = build(spec, config, inf)

    def step(raw, opt_steps_rt):
        dyn = gen(raw).astype(jnp.float32)
        dat = prep(dyn)
        u0 = L.init(dat)
        res = map_fit(L.loss_fn, u0, dat, steps=inf.opt_steps,
                      steps_rt=opt_steps_rt, lr=inf.lr, tol=inf.tol)
        best = select_best(res)
        sigma_u = fisher_sigma_u(L.loss_fn, best["u"], dat, nobs=L.nobs)
        return {"params": L.phys(best["u"]),
                "errs": L.sigma_phys(best["u"], sigma_u),
                "loss": best["loss"], "grad_norm": best["grad_norm"],
                "converged": best["converged"], "steps": best["steps"],
                "start": best["start"]}

    prog = obs.instrument_jit(jax.jit(step), "infer.step")
    _PROGRAMS[key] = prog
    return prog


def infer_campaign(spec, inf=None, opts=None, *, bucket: bool = True,
                   opt_steps_rt: int | None = None) -> dict:
    """Run one gradient-inference campaign on device and return the
    per-epoch MAP estimates.

    ``spec``/``inf`` accept dataclasses or (sparse) dicts.  ``bucket``
    pads the epoch axis to the catalog rung (default: the serve/warm
    contract); ``opt_steps_rt`` caps the executed Adam iterations below
    the compiled ``inf.opt_steps`` ceiling without recompiling.

    Returns ``{"kind", "params": {name: [B]}, "errs": {name+"err":
    [B]}, "loss", "grad_norm", "converged", "steps", "start"}``.
    """
    from ..serve.worker import config_from_opts

    if not isinstance(spec, campaign.SynthSpec):
        spec = campaign.spec_from_dict(spec)
    if not isinstance(inf, InferSpec):
        inf = infer_from_dict(inf)
    config = config_from_opts(dict(opts or {}))
    validate_infer_config(spec, inf, config)
    B = int(spec.n_epochs)
    rung = buckets.rung_for(B) if bucket else B
    raw = campaign.stage_batch(spec)
    if rung > B:
        raw = np.concatenate([raw, np.repeat(raw[-1:], rung - B,
                                             axis=0)], axis=0)
    steps_rt = inf.opt_steps if opt_steps_rt is None else opt_steps_rt
    if not 0 < int(steps_rt) <= inf.opt_steps:
        raise ValueError(f"opt_steps_rt must be in [1, {inf.opt_steps}] "
                         f"(the compiled ceiling), got {steps_rt}")
    prog = _infer_program(spec, config, inf, rung)
    obs.inc("infer_epochs", B)
    obs.inc("bytes_h2d", raw.nbytes)
    with obs.span("infer.fit", kind=spec.kind, epochs=B, rung=rung,
                  starts=inf.starts, opt_steps_rt=int(steps_rt)):
        out = prog(raw, np.uint32(steps_rt))
    out = {k: np.asarray(v)[:B] for k, v in out.items()}
    finite = np.all(np.isfinite(out["params"]), axis=-1) \
        & np.isfinite(out["loss"])
    obs.inc("opt_steps", int(out["steps"].sum()))
    obs.inc("infer_converged", int(np.sum(out["converged"] & finite)))
    obs.inc("infer_diverged", int(np.sum(~finite)))
    names = _PARAM_NAMES[spec.kind]
    return {"kind": spec.kind,
            "params": {nm: out["params"][:, i]
                       for i, nm in enumerate(names)},
            "errs": {nm + "err": out["errs"][:, i]
                     for i, nm in enumerate(names)},
            "loss": out["loss"], "grad_norm": out["grad_norm"],
            "converged": out["converged"], "steps": out["steps"],
            "start": out["start"]}


# CSV columns per kind: the io/results reference schema's fit columns
# (amp/wn are optimiser nuisance parameters — stored, never exported)
_ROW_COLS = {"arc": ("betaeta",), "acf": ("tau", "dnu")}


def infer_rows(spec, inf=None, opts=None, mesh=None,
               async_exec: bool = True, bucket: bool = True) -> list:
    """One result row per epoch (``None`` for quarantined non-finite
    lanes) — the ONE row builder shared by the CLI ``--infer`` engine
    and the serve ``infer`` job runner, so served CSV rows are
    byte-identical to a direct run's (the simulate-route contract).

    ``mesh``/``async_exec`` are accepted for runner-signature symmetry
    with ``synthetic_rows``; the infer program is single-host today
    (sharded infer is roadmap follow-up).
    """
    from ..io.results import row_fit_values

    del mesh, async_exec
    if not isinstance(spec, campaign.SynthSpec):
        spec = campaign.spec_from_dict(spec)
    if not isinstance(inf, InferSpec):
        inf = infer_from_dict(inf)
    res = infer_campaign(spec, inf, opts, bucket=bucket)
    meta = campaign.synth_meta(spec)
    names = _PARAM_NAMES[spec.kind]
    cols = _ROW_COLS[spec.kind]
    rows: list = [None] * spec.n_epochs
    for i in range(spec.n_epochs):
        row = dict(meta)
        row["name"] = campaign.epoch_name(spec, i)
        row["mjd"] = campaign._MJD0 + int(i)
        for nm in names:
            key = nm if nm in cols else f"infer_{nm}"
            row[key] = float(res["params"][nm][i])
            row[key + "err"] = float(res["errs"][nm + "err"][i])
        row["infer_loss"] = float(res["loss"][i])
        row["infer_converged"] = int(res["converged"][i])
        row["infer_steps"] = int(res["steps"][i])
        row["infer_start"] = int(res["start"][i])
        fitvals = row_fit_values(row)
        if fitvals and not np.all(np.isfinite(fitvals)):
            continue   # NaN lane: quarantined (rows[i] stays None)
        rows[i] = row
    return rows
