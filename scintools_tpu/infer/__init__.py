"""Differentiable inference plane (ISSUE 18).

Gradient-based MAP fits of physical scattering parameters THROUGH the
compiled forward model: the PR 9 synthetic generators run inside the
same jit as a differentiable loss (sspec-profile or ACF-cut space) and
a vmapped multi-start Adam loop, so ``jax.grad`` flows end to end —
screen params -> dynspec -> data likelihood.  Served as the batched
``infer`` job kind (``JobQueue.submit_infer`` /
``scint-tpu submit QDIR --infer``) and runnable directly
(``scint-tpu process --synthetic N --infer``).

See docs/inference.md for the loss geometry, transform/multi-start
semantics, and when to prefer the gradient path over the summary fits.
"""

from .loss import (InferLoss, bounded_log_phys, bounded_log_sigma,
                   log_phys, log_sigma, make_acf_loss, make_arc_loss)
from .map_fit import MapFitResult, fisher_sigma_u, map_fit, select_best
from .runner import (InferSpec, infer_campaign, infer_from_dict,
                     infer_rows, infer_to_dict, validate_infer,
                     validate_infer_config)

__all__ = [
    "InferLoss", "InferSpec", "MapFitResult",
    "bounded_log_phys", "bounded_log_sigma", "log_phys", "log_sigma",
    "make_acf_loss", "make_arc_loss",
    "map_fit", "select_best", "fisher_sigma_u",
    "infer_campaign", "infer_rows", "infer_to_dict", "infer_from_dict",
    "validate_infer", "validate_infer_config",
]
