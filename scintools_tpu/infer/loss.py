"""Differentiable data likelihoods for gradient-based inference.

Two loss geometries, one per closed-form synthetic oracle kind
(ISSUE 18):

* **acf** — the scint fitter's own least-squares objective, made
  end-to-end differentiable: the central positive-lag ACF cuts
  (``ops.acf.acf_cuts_direct``, the batched pipeline's cut route)
  against ``models.acf_models.scint_acf_model`` on the reference's
  ``linspace(0, n, n)`` lag axes, normalised per epoch so the loss is
  scale-free.  Parameters (tau, dnu, amp, wn) ride a log transform —
  the optimiser is unconstrained, positivity is structural.

* **arc** — the normalised-secondary-spectrum profile geometry of
  ``fit.arc_fit``: the delay rows come from the SAME
  ``norm_sspec_row_window`` rule the summary fitter (and the driver's
  fused sspec crop) resolves, and the loss is the negative of a
  Gaussian-kernel smooth sample of the FOLDED profile at the arm
  position ``x(eta) = sqrt(emin / eta)`` — the coordinate at which a
  parabola of curvature ``eta`` lands on the normalised grid (the
  fitter's own ``eta_array = emin / etafrac**2`` mapping, inverted).
  Its gradient therefore climbs toward exactly the profile peak the
  summary fitter's argmax measures.  eta rides a bounded-log (logit in
  log space) transform pinned to the searchable window
  ``[emin, emax] ∩ constraint``, so every optimiser iterate stays on
  the physically measurable branch.

Both factories return an :class:`InferLoss` bundle consumed by
``infer.runner``: a traced ``prep`` (per-epoch data extraction), the
scalar ``loss_fn(u, dat)``, a deterministic multi-start ``init`` (host
lattice, static seed — no runtime RNG, so reruns are bit-stable), and
the transform's ``phys`` / ``sigma_phys`` maps (delta method).
"""

from __future__ import annotations

import typing

import numpy as np

__all__ = ["InferLoss", "log_phys", "log_sigma", "bounded_log_phys",
           "bounded_log_sigma", "make_acf_loss", "make_arc_loss"]


class InferLoss(typing.NamedTuple):
    """One kind's differentiable-inference bundle."""

    prep: typing.Any        # dyn-derived per-epoch data -> dat pytree
    loss_fn: typing.Any     # (u [P], dat slice) -> scalar
    init: typing.Any        # dat -> u0 [B, S, P] multi-start inits
    phys: typing.Any        # u [..., P] -> physical params [..., P]
    sigma_phys: typing.Any  # (u, sigma_u) -> physical 1-sigma
    names: tuple            # physical parameter names, order of P
    nobs: typing.Any        # residual count for chi2 error scaling


# ---------------------------------------------------------------------------
# parameter transforms (unconstrained u <-> physical)
# ---------------------------------------------------------------------------


def log_phys(u, xp=np):
    """Log transform: ``phys = exp(u)`` (positivity is structural)."""
    return xp.exp(u)


def log_sigma(u, sigma_u, xp=np):
    """Delta method through the log transform: ``d phys/d u = phys``."""
    return xp.exp(u) * sigma_u


def bounded_log_phys(u, log_lo: float, log_hi: float, xp=np):
    """Bounded-log (logit-in-log-space) transform:
    ``phys = exp(lo + (hi - lo) * sigmoid(u))`` — unconstrained ``u``
    covers ``(exp(lo), exp(hi))`` exactly, uniformly in log."""
    s = 1.0 / (1.0 + xp.exp(-u))
    return xp.exp(log_lo + (log_hi - log_lo) * s)


def bounded_log_sigma(u, sigma_u, log_lo: float, log_hi: float, xp=np):
    """Delta method through :func:`bounded_log_phys`."""
    s = 1.0 / (1.0 + xp.exp(-u))
    jac = bounded_log_phys(u, log_lo, log_hi, xp=xp) \
        * (log_hi - log_lo) * s * (1.0 - s)
    return xp.abs(jac) * sigma_u


def _start_lattice(starts: int, p: int, seed: int) -> np.ndarray:
    """Deterministic host-side multi-start offsets ``[S, P]``: a fixed
    standard-normal lattice with row 0 zeroed, so start 0 is always the
    exact data-driven (or grid-center) initial guess."""
    lat = np.random.default_rng(int(seed)).standard_normal(
        (int(starts), int(p))).astype(np.float32)
    lat[0] = 0.0
    return lat


# ---------------------------------------------------------------------------
# acf kind: differentiable scint_acf_model least squares on the cuts
# ---------------------------------------------------------------------------


def make_acf_loss(nf: int, nt: int, dt: float, df: float, *,
                  alpha: float = 5 / 3, lens: str = "exact",
                  starts: int = 8, spread: float = 0.25,
                  seed: int = 0) -> InferLoss:
    """The scint summary fit's residuals as a differentiable loss."""
    import jax
    import jax.numpy as jnp

    from ..fit.scint_fit import initial_guesses
    from ..models.acf_models import scint_acf_model
    from ..ops.acf import acf_cuts_direct

    # the reference's linspace(0, n, n) lag-axis quirk, kept so the
    # gradient path optimises the EXACT objective the LM summary fit
    # solves (scint_fit.acf_cuts / scint_cat_front)
    x_t = np.asarray(float(dt) * np.linspace(0, int(nt), int(nt)),
                     dtype=np.float32)
    x_f = np.asarray(float(df) * np.linspace(0, int(nf), int(nf)),
                     dtype=np.float32)
    # the fractional power (x/tau)**alpha has no second derivative at
    # x = 0 (0**(alpha-2) -> inf under jax.hessian), which would NaN
    # the Fisher errors; a sub-resolution nudge of the zero-lag time
    # sample keeps the curvature analytic at negligible model bias
    # (the zero-lag value is wn-spike dominated anyway)
    x_t[0] = 1e-3 * float(dt)
    lat = _start_lattice(starts, 4, seed)
    nobs = int(nt) + int(nf)

    def prep(dyn_batch):
        cut_t, cut_f = acf_cuts_direct(dyn_batch, backend="jax",
                                       method="fft", lens=lens)
        y = jnp.concatenate([cut_t, cut_f], axis=-1)
        # per-epoch normalisation: the loss (and its convergence tol)
        # is scale-free in the dynspec's arbitrary intensity units
        scale = jnp.maximum(jnp.sum(y * y, axis=-1), 1e-20)
        return {"y": y, "cut_t": cut_t, "cut_f": cut_f, "scale": scale}

    def loss_fn(u, d):
        p = jnp.exp(u)
        model = scint_acf_model(jnp.asarray(x_t), jnp.asarray(x_f),
                                p[0], p[1], p[2], p[3], alpha, xp=jnp)
        r = d["y"] - model
        return 0.5 * jnp.sum(r * r) / d["scale"]

    def init(d):
        tau0, dnu0, amp0, wn0 = initial_guesses(
            jnp.asarray(x_t), d["cut_t"], jnp.asarray(x_f), d["cut_f"],
            xp=jnp)
        # floors: the argmin-based guesses can land on the zero-lag
        # sample (tau/dnu = 0) or a negative first-lag drop (wn <= 0) —
        # both outside the log transform's range
        y0 = jnp.maximum(d["y"][..., 0], 1e-20)
        tau0 = jnp.maximum(tau0, float(dt))
        dnu0 = jnp.maximum(dnu0, float(df))
        amp0 = jnp.maximum(amp0, 1e-4 * y0)
        wn0 = jnp.maximum(wn0, 1e-4 * y0)
        u_c = jnp.log(jnp.stack([tau0, dnu0, amp0, wn0], axis=-1))
        return u_c[:, None, :] + float(spread) * jnp.asarray(lat)[None]

    return InferLoss(prep=prep, loss_fn=loss_fn, init=init,
                     phys=lambda u: log_phys(u, xp=jnp),
                     sigma_phys=lambda u, s: log_sigma(u, s, xp=jnp),
                     names=("tau", "dnu", "amp", "wn"), nobs=nobs)


# ---------------------------------------------------------------------------
# arc kind: folded norm_sspec profile sampled at x(eta)
# ---------------------------------------------------------------------------


def make_arc_loss(fdop, yaxis, tdel, freq: float, *,
                  ref_freq: float = 1400.0, delmax=None,
                  numsteps: int = 1024, startbin: int = 3,
                  cutmid: int = 3, constraint=(0, np.inf),
                  starts: int = 8, spread: float = 0.25, seed: int = 0,
                  kernel_cells: float = 1.5) -> InferLoss:
    """Arc-curvature loss on the normalised-sspec folded profile.

    The geometry is the arc fitter's own, derived from the SAME shared
    row rule (``norm_sspec_row_window``) so the loss sees exactly the
    delay window the summary fitter measures.  lamsteps-only: the
    fitted curvature is beta-eta, the arc oracle's injected truth.
    """
    import jax.numpy as jnp

    from ..fit.arc_fit import norm_sspec_row_window

    fdop = np.asarray(fdop)
    yaxis = np.asarray(yaxis)
    tdel = np.asarray(tdel)
    ind, _ind_norm, _dmax_raw = norm_sspec_row_window(
        tdel, freq, ref_freq=ref_freq, delmax=delmax)
    ymax = yaxis[ind]
    yc = yaxis[:ind]
    # emin/emax exactly as _make_arc_fitter_cached (lamsteps branch)
    emax = float(ymax / ((fdop[1] - fdop[0]) * cutmid) ** 2)
    emin = float((yc[1] - yc[0]) * startbin / np.max(fdop) ** 2)
    lo = max(emin, float(constraint[0]))
    hi = min(emax, float(constraint[1]))
    if not lo < hi:
        raise ValueError(
            f"arc infer: empty searchable window [{lo:.4g}, {hi:.4g}] "
            f"(emin={emin:.4g}, emax={emax:.4g}, "
            f"constraint={tuple(constraint)})")
    log_lo, log_hi = float(np.log(lo)), float(np.log(hi))

    # fold geometry: the fitter's static positive/negative arm indices
    # over the normalised grid etafrac = linspace(-1, 1, numsteps)
    n = int(numsteps)
    etafrac = np.linspace(-1.0, 1.0, n)
    ipos = np.where(etafrac > 1 / (2 * n))[0]
    ineg = np.where(etafrac < -1 / (2 * n))[0]
    xgrid = np.asarray(etafrac[ipos], dtype=np.float32)      # [M]
    h = float(kernel_cells) * 2.0 / (n - 1)
    # multi-start: a uniform grid over the bounded transform's range
    # (sigmoid centers at (k+1/2)/S), jittered by the fixed lattice
    s_c = (np.arange(int(starts)) + 0.5) / int(starts)
    base = np.log(s_c / (1.0 - s_c)).astype(np.float32)      # [S]
    lat = _start_lattice(starts, 1, seed)
    u0_const = (base[:, None]
                + float(spread) * lat).astype(np.float32)    # [S, 1]

    def prep(prof_batch):
        folded = 0.5 * (prof_batch[:, ipos]
                        + prof_batch[:, ineg][:, ::-1])      # [B, M]
        return {"folded": folded}

    def loss_fn(u, d):
        eta = bounded_log_phys(u[0], log_lo, log_hi, xp=jnp)
        x = jnp.sqrt(emin / eta)                  # arm position in (0, 1]
        w = jnp.exp(-0.5 * ((jnp.asarray(xgrid) - x) / h) ** 2)
        fin = jnp.isfinite(d["folded"])
        w = jnp.where(fin, w, 0.0)
        f = jnp.where(fin, d["folded"], 0.0)
        # negative smoothed profile power (dB): minimising it climbs
        # the folded profile toward the fitter's measured peak
        return -jnp.sum(w * f) / (jnp.sum(w) + 1e-12)

    def init(d):
        B = d["folded"].shape[0]
        return jnp.broadcast_to(jnp.asarray(u0_const)[None],
                                (B,) + u0_const.shape)

    return InferLoss(
        prep=prep, loss_fn=loss_fn, init=init,
        phys=lambda u: bounded_log_phys(u, log_lo, log_hi, xp=jnp),
        sigma_phys=lambda u, s: bounded_log_sigma(u, s, log_lo, log_hi,
                                                  xp=jnp),
        names=("betaeta",), nobs=None)
