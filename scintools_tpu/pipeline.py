"""Stateful ``Dynspec`` wrapper: the reference's UX on the functional core.

The reference's ``Dynspec`` class (dynspec.py:29) is a mutable state machine
— load, then call processing methods that set result attributes (``acf``,
``sspec``, ``lamsspec``, ``eta``, ``tau`` ...), with lazy recomputation when
a fit needs a product that does not exist yet (e.g. dynspec.py:426-443,
942-945).  This module preserves that workflow 1:1 for users migrating from
the reference, while all computation lives in the pure layers
(:mod:`scintools_tpu.ops`, :mod:`scintools_tpu.fit`):

    ds = Dynspec(filename="obs.dynspec", lamsteps=True)   # auto-process
    ds.fit_arc(lamsteps=True)                              # lazy sspec
    ds.get_scint_params()                                  # lazy acf
    print(ds.betaeta, ds.tau, ds.dnu)

Every method takes ``backend=`` (defaults to the instance's backend) so the
same script runs the numpy reference-parity path or the jit'd TPU path.

Also here: ``cut_dyn`` sub-band/sub-time tiling (dynspec.py:1035-1127) and
``sort_dyn`` batch triage (dynspec.py:1599-1660).
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

from .backend import resolve, to_numpy
from .data import ArcFit, DynspecData, ScintParams, SecSpec
from .fit.arc_fit import fit_arc as _fit_arc
from .fit.arc_fit import norm_sspec as _norm_sspec
from .fit.scint_fit import fit_scint_params as _fit_scint_params
from .io.adapters import concatenate_time, from_simulation
from .io.psrflux import read_psrflux, write_psrflux
from .ops.acf import acf as _acf
from .ops.clean import correct_band as _correct_band
from .ops.clean import crop as _crop
from .ops.clean import refill as _refill
from .ops.clean import trim_edges as _trim_edges
from .ops.clean import zap as _zap
from .ops.scale import scale_lambda, scale_trapezoid
from .ops.sspec import sspec as _sspec
from .ops.sspec import sspec_axes
from .ops.svd import svd_model as _svd_model


class Dynspec:
    """Mutable observation wrapper with the reference's method surface.

    Construct from a psrflux ``filename=``, a :class:`DynspecData`
    (``data=``), a dyn-like object with the reference's 13 duck-typed
    attributes (``dyn_obj=``, dynspec.py:158-186), or a
    :class:`scintools_tpu.sim.Simulation` (``sim=``).
    """

    def __init__(self, filename: str | None = None, data: DynspecData = None,
                 dyn_obj=None, sim=None, process: bool = True,
                 lamsteps: bool = False, backend: str = "numpy",
                 verbose: bool = False, **sim_kw):
        if sum(x is not None for x in (filename, data, dyn_obj, sim)) != 1:
            raise ValueError(
                "give exactly one of filename=, data=, dyn_obj=, sim=")
        if filename is not None:
            data = read_psrflux(filename)
        elif sim is not None:
            data = from_simulation(sim, **sim_kw)
        elif dyn_obj is not None:
            data = DynspecData(
                dyn=np.asarray(dyn_obj.dyn), freqs=np.asarray(dyn_obj.freqs),
                times=np.asarray(dyn_obj.times), mjd=float(dyn_obj.mjd),
                df=float(dyn_obj.df), dt=float(dyn_obj.dt),
                bw=float(dyn_obj.bw), freq=float(dyn_obj.freq),
                tobs=float(dyn_obj.tobs), name=str(dyn_obj.name),
                header=tuple(getattr(dyn_obj, "header", ())))
        self._data = data
        self.backend = resolve(backend)
        self.verbose = verbose
        self.lamsteps = lamsteps
        # result attributes, reference naming (dynspec.py attributes)
        self.acf = None
        self.sspec = None
        self.lamsspec = None
        self.fdop = self.tdel = self.beta = None
        self.lamdyn = self.lam = self.dlam = None
        self.trapdyn = None
        self.eta = self.etaerr = None
        self.betaeta = self.betaetaerr = None
        self.norm_sspec_result = None
        self.scint_params = None
        self.arc_fit = None
        self.wavefield = None
        if process:
            self.default_processing(lamsteps=lamsteps)

    # -- data attribute delegation (reference attribute names) -------------
    @property
    def data(self) -> DynspecData:
        return self._data

    def __getattr__(self, name):
        # delegate dyn/freqs/times/mjd/df/dt/bw/freq/tobs/name/header and
        # nchan/nsub to the wrapped DynspecData
        if name.startswith("_"):
            raise AttributeError(name)
        d = self.__dict__.get("_data")
        if d is not None and hasattr(d, name):
            return getattr(d, name)
        raise AttributeError(f"{type(self).__name__!s} has no attribute "
                             f"{name!r}")

    def __add__(self, other: "Dynspec") -> "Dynspec":
        """Time-concatenate two epochs, zero-filling the MJD gap
        (dynspec.py:47-97)."""
        out = concatenate_time(self._data, other._data)
        return Dynspec(data=out, process=False, lamsteps=self.lamsteps,
                       backend=self.backend, verbose=self.verbose)

    def info(self) -> str:
        """Human-readable observation metadata.  Returns the string
        (display is the caller's concern — the CLI ``info`` command
        prints it; the compute layers stay print-free, enforced by
        tests/test_no_print.py)."""
        return self._data.info_str()

    def write_file(self, filename: str) -> None:
        """Write the current dynamic spectrum as a psrflux file."""
        write_psrflux(self._data, filename)

    # -- processing steps (mutate wrapped data, return self for chaining) --
    def default_processing(self, lamsteps: bool = False) -> "Dynspec":
        """trim_edges -> refill -> calc_acf -> [scale_dyn] -> calc_sspec
        (dynspec.py:188-198)."""
        self.trim_edges().refill(linear=True)
        self.calc_acf()
        self.lamsteps = lamsteps
        if lamsteps:
            self.scale_dyn()
        self.calc_sspec(lamsteps=lamsteps)
        return self

    def trim_edges(self) -> "Dynspec":
        self._data = _trim_edges(self._data)
        return self

    def refill(self, linear: bool = True, zeros: bool = True) -> "Dynspec":
        self._data = _refill(self._data, linear=linear, zeros=zeros)
        return self

    def correct_band(self, frequency: bool = True, time: bool = False,
                     nsmooth: int | None = 5,
                     lamsteps: bool = False) -> "Dynspec":
        """Bandpass/gain correction (dynspec.py:1189-1226).  With
        ``lamsteps=True`` corrects the lambda-resampled dynspec instead
        (resampling it first if needed), as the reference does."""
        if lamsteps:
            from .ops.clean import correct_band_array

            if self.lamdyn is None:
                self.scale_dyn()
            self.lamdyn = correct_band_array(self.lamdyn,
                                             frequency=frequency,
                                             time=time, nsmooth=nsmooth)
            self.lamsspec = None  # stale: recompute on next use
        else:
            self._data = _correct_band(self._data, frequency=frequency,
                                       time=time, nsmooth=nsmooth)
        return self

    def zap(self, method: str = "median", sigma: float = 7,
            m: int = 3) -> "Dynspec":
        self._data = _zap(self._data, method=method, sigma=sigma, m=m)
        return self

    def crop_dyn(self, fmin: float = 0, fmax: float = np.inf,
                 tmin: float = 0, tmax: float = np.inf) -> "Dynspec":
        self._data = _crop(self._data, fmin=fmin, fmax=fmax, tmin=tmin,
                           tmax=tmax)
        return self

    def svd_model(self, nmodes: int = 1) -> "Dynspec":
        """Flatten the bandpass/gain with a rank-``nmodes`` SVD model
        (scint_utils.py:401-426)."""
        flat, _ = _svd_model(to_numpy(self._data.dyn), nmodes=nmodes,
                             backend=self.backend)
        self._data = self._data.replace(dyn=to_numpy(flat))
        return self

    def scale_dyn(self, scale: str = "lambda", window: str = "hanning",
                  window_frac: float = 0.1) -> "Dynspec":
        """Resample to uniform wavelength steps (``lambda``) or trapezoid
        time-rescaling (dynspec.py:1402-1476)."""
        if scale == "lambda":
            lamdyn, lam, dlam = scale_lambda(self._data,
                                             backend=self.backend)
            self.lamdyn, self.lam, self.dlam = (to_numpy(lamdyn), lam, dlam)
        elif scale == "trapezoid":
            self.trapdyn = scale_trapezoid(self._data, window=window,
                                           window_frac=window_frac)
        else:
            raise ValueError(f"unknown scale {scale!r}")
        return self

    # -- transforms --------------------------------------------------------
    def calc_acf(self, backend: str | None = None) -> "Dynspec":
        """2-D autocovariance via Wiener-Khinchin (dynspec.py:1337-1360)."""
        b = resolve(backend or self.backend)
        self.acf = to_numpy(_acf(np.asarray(to_numpy(self._data.dyn),
                                            dtype=np.float64), backend=b))
        return self

    def calc_sspec(self, prewhite: bool = True, window: str = "blackman",
                   window_frac: float = 0.1, lamsteps: bool = False,
                   trap: bool = False, backend: str | None = None
                   ) -> "Dynspec":
        """Secondary spectrum (dynspec.py:1228-1335); with
        ``lamsteps=True`` computes it from the lambda-resampled dynspec and
        stores it as ``lamsspec`` with the ``beta`` axis."""
        b = resolve(backend or self.backend)
        if lamsteps:
            if self.lamdyn is None:
                self.scale_dyn()
            arr = self.lamdyn
        elif trap:
            if self.trapdyn is None:
                self.scale_dyn(scale="trapezoid")
            arr = self.trapdyn
        else:
            arr = to_numpy(self._data.dyn)
        sec = to_numpy(_sspec(np.asarray(arr, dtype=np.float64),
                              prewhite=prewhite, window=window,
                              window_frac=window_frac, db=True, backend=b))
        nf, nt = arr.shape
        fdop, tdel, beta = sspec_axes(
            nf, nt, self._data.dt, self._data.df,
            dlam=self.dlam if lamsteps else None)
        self.fdop, self.tdel = fdop, tdel
        if lamsteps:
            self.lamsspec, self.beta = sec, beta
        else:
            self.sspec = sec
        return self

    def calc_sspec_slowft(self, backend: str | None = None) -> SecSpec:
        """Arc-sharpened secondary spectrum via the slow-FT NUDFT
        (scint_utils.py:317-398) as a ready-to-fit :class:`SecSpec`.

        The reference exposes ``slow_FT`` as a free function returning a
        raw complex field, leaving axes and integration to user scripts;
        here the scaled-time transform (which removes the arcs' chromatic
        smearing) is wired straight into the measurement chain: the
        result has true-delay ``tdel`` (us) / ``fdop`` (mHz) axes and
        positive delays only, so ``fit_arc``/``norm_sspec`` accept it
        unchanged.  Stored as ``self.slowft_sspec``.
        """
        from .ops.nudft import slow_ft

        b = resolve(backend or self.backend)
        dyn_tf = to_numpy(self._data.dyn).T  # [ntime, nfreq]
        ntime, nfreq = dyn_tf.shape
        field = slow_ft(dyn_tf, to_numpy(self._data.freqs), backend=b,
                        as_numpy=(b == "jax"))
        field = to_numpy(field)
        with np.errstate(divide="ignore"):
            power_db = 10 * np.log10(np.abs(field) ** 2)
        # axes: rows of `field` are Doppler, DESCENDING (slow_ft flips the
        # ascending NUDFT grid); cols are delay, fftshifted ascending
        fdop = np.sort(np.fft.fftfreq(ntime, d=self._data.dt)) * 1e3  # mHz
        delay = np.fft.fftshift(np.fft.fftfreq(nfreq, d=abs(self._data.df)))
        # orient [tdel, fdop]: transpose -> [delay asc, doppler desc];
        # keep positive delays, flip cols to ascending Doppler
        sspec = power_db.T[delay >= 0][:, ::-1]
        tdel = delay[delay >= 0]                        # us (1/MHz)
        sec = SecSpec(sspec=sspec, fdop=fdop, tdel=tdel, beta=None,
                      lamsteps=False)
        self.slowft_sspec = sec
        return sec

    def _secspec(self, lamsteps: bool) -> SecSpec:
        """Assemble a SecSpec, lazily computing what is missing
        (the reference's recompute-on-missing, dynspec.py:426-443)."""
        if lamsteps and self.lamsspec is None:
            self.calc_sspec(lamsteps=True)
        if not lamsteps and self.sspec is None:
            self.calc_sspec()
        return SecSpec(sspec=self.lamsspec if lamsteps else self.sspec,
                       fdop=self.fdop, tdel=self.tdel,
                       beta=self.beta if lamsteps else None,
                       lamsteps=lamsteps)

    def secspec(self, lamsteps: bool | None = None) -> SecSpec:
        """The secondary spectrum with its axes as one SecSpec record,
        computing it first if needed — the public accessor for code that
        consumes spectra directly (fit.fit_arc_thetatheta,
        plotting.plot_sspec, ...).  ``lamsteps`` defaults to this
        object's processing mode."""
        return self._secspec(self.lamsteps if lamsteps is None
                             else lamsteps)

    # -- measurements ------------------------------------------------------
    def fit_arc(self, method: str = "norm_sspec", lamsteps: bool | None
                = None, delmax=None, numsteps: int = 10000,
                startbin: int = 3, cutmid: int = 3, etamax=None, etamin=None,
                low_power_diff: float = -3.0, high_power_diff: float = -1.5,
                ref_freq: float = 1400.0, constraint=(0, np.inf),
                nsmooth: int = 5, noise_error: bool = True,
                asymm: bool = False,
                backend: str | None = None) -> ArcFit:
        """Arc-curvature measurement (dynspec.py:414-785).  Sets
        ``betaeta/betaetaerr`` (lamsteps) or ``eta/etaerr``; with
        ``asymm=True`` also fits each fdop arm (``eta_left/eta_right``)."""
        lamsteps = self.lamsteps if lamsteps is None else lamsteps
        sec = self._secspec(lamsteps)
        if np.ndim(etamin) == 1 or np.ndim(etamax) == 1:
            # multi-arc mode (reference: etamin/etamax arrays segment the
            # eta grid, dynspec.py:470-491): one fit per curvature window.
            # Scalars/None broadcast against the other bound; mismatched
            # array lengths are an error (zip would truncate silently).
            from .fit.arc_fit import fit_arcs_multi

            if asymm:
                raise ValueError(
                    "asymm=True is not supported in multi-arc mode "
                    "(secondary arcs are re-measured on the shared "
                    "profile); fit each arc individually with a "
                    "constraint window instead")
            n_arcs = max(np.size(etamin) if etamin is not None else 1,
                         np.size(etamax) if etamax is not None else 1)

            def as_bounds(x, default):
                if x is None:
                    return [default] * n_arcs
                arr = list(np.atleast_1d(x))
                if len(arr) == 1:
                    arr = arr * n_arcs
                if len(arr) != n_arcs:
                    raise ValueError(
                        f"etamin/etamax lengths differ: {np.size(etamin)} "
                        f"vs {np.size(etamax)}")
                return arr

            # honour an explicit constraint by intersecting it with every
            # window (it would otherwise be silently ignored in multi-arc
            # mode)
            c0, c1 = float(constraint[0]), float(constraint[1])
            brackets = [(max(lo, c0), min(hi, c1))
                        for lo, hi in zip(as_bounds(etamin, 0.0),
                                          as_bounds(etamax, np.inf))]
            fits = fit_arcs_multi(
                sec, freq=float(self._data.freq), brackets=brackets,
                method=method, delmax=delmax, numsteps=numsteps,
                startbin=startbin, cutmid=cutmid,
                low_power_diff=low_power_diff,
                high_power_diff=high_power_diff, ref_freq=ref_freq,
                nsmooth=nsmooth, noise_error=noise_error,
                backend=resolve(backend or self.backend))
            self.arc_fit = fits
            etas = np.array([float(to_numpy(f.eta)) for f in fits])
            errs = np.array([float(to_numpy(f.etaerr)) for f in fits])
            if lamsteps:
                self.betaeta, self.betaetaerr = etas, errs
            else:
                self.eta, self.etaerr = etas, errs
            return fits
        fit = _fit_arc(sec, freq=float(self._data.freq), method=method,
                       delmax=delmax, numsteps=numsteps, startbin=startbin,
                       cutmid=cutmid, etamax=etamax, etamin=etamin,
                       low_power_diff=low_power_diff,
                       high_power_diff=high_power_diff, ref_freq=ref_freq,
                       constraint=constraint, nsmooth=nsmooth,
                       noise_error=noise_error, asymm=asymm,
                       backend=resolve(backend or self.backend))
        self.arc_fit = fit
        if lamsteps:
            self.betaeta = float(to_numpy(fit.eta))
            self.betaetaerr = float(to_numpy(fit.etaerr))
        else:
            self.eta = float(to_numpy(fit.eta))
            self.etaerr = float(to_numpy(fit.etaerr))
        return fit

    def norm_sspec(self, eta: float | None = None, delmax=None,
                   startbin: int = 1, maxnormfac: float = 2,
                   cutmid: int = 3, lamsteps: bool | None = None,
                   numsteps: int | None = None, ref_freq: float = 1400.0):
        """Curvature-normalised secondary spectrum (dynspec.py:787-926)."""
        lamsteps = self.lamsteps if lamsteps is None else lamsteps
        if eta is None:
            eta = self.betaeta if lamsteps else self.eta
            if eta is None:
                self.fit_arc(lamsteps=lamsteps)
                eta = self.betaeta if lamsteps else self.eta
            # after a multi-arc fit the attribute is an array: normalise
            # by the primary (first-bracket) arc
            if np.ndim(eta) == 1:
                eta = float(eta[0])
        sec = self._secspec(lamsteps)
        ns = _norm_sspec(sec, freq=float(self._data.freq), eta=eta,
                         delmax=delmax, startbin=startbin,
                         maxnormfac=maxnormfac, cutmid=cutmid,
                         numsteps=numsteps, ref_freq=ref_freq)
        self.norm_sspec_result = ns
        return ns

    def get_scint_params(self, method: str = "acf1d", *,
                         alpha: float | None = 5 / 3, mcmc: bool = False,
                         backend: str | None = None) -> ScintParams:
        """tau_d / dnu_d from the ACF (dynspec.py:928-1033).  Sets
        ``tau/tauerr/dnu/dnuerr/talpha`` (and ``scint_params``).

        ``method='acf2d'`` fits the full 2-D ACF model incl. phase-gradient
        tilt (sets ``tilt/tilterr``); ``mcmc=True`` refines the acf1d fit
        with posterior sampling (the reference's lmfit-emcee option,
        dynspec.py:989-992, rebuilt as a jax ensemble sampler)."""
        if self.acf is None:
            self.calc_acf()
        b = resolve(backend or self.backend)
        kw = dict(dt=self._data.dt, df=abs(self._data.df),
                  nchan=self._data.nchan, nsub=self._data.nsub)
        # mcmc=True stores the post-burn chain as ``self.mcmc_chain``
        # for plotting.plot_posterior (the reference's corner export,
        # dynspec.py:1025-1031)
        if method == "acf1d":
            if mcmc:
                from .fit.mcmc import fit_scint_params_mcmc

                sp, self.mcmc_chain = fit_scint_params_mcmc(
                    self.acf, alpha=alpha, return_chain=True, **kw)
            else:
                sp = _fit_scint_params(self.acf, alpha=alpha, backend=b,
                                       **kw)
        elif method == "acf2d":
            if mcmc:
                from .fit.mcmc import fit_scint_params_2d_mcmc

                sp, tilt, tilterr, self.mcmc_chain = \
                    fit_scint_params_2d_mcmc(self.acf, alpha=alpha,
                                             return_chain=True, **kw)
            else:
                from .fit.scint_fit import fit_scint_params_2d

                sp, tilt, tilterr = fit_scint_params_2d(
                    self.acf, alpha=alpha, backend=b, **kw)
            self.tilt, self.tilterr = tilt, tilterr
        elif method == "sspec":
            if mcmc:
                from .fit.mcmc import fit_scint_params_sspec_mcmc

                sp, self.mcmc_chain = fit_scint_params_sspec_mcmc(
                    self.acf, alpha=alpha, return_chain=True, **kw)
            else:
                from .fit.scint_fit import fit_scint_params_sspec

                sp = fit_scint_params_sspec(self.acf, alpha=alpha,
                                            backend=b, **kw)
        else:
            raise ValueError(f"unknown method {method!r}; use 'acf1d', "
                             "'acf2d' or 'sspec'")
        self.scint_params = sp
        for k in ("tau", "tauerr", "dnu", "dnuerr", "talpha"):
            setattr(self, k, float(to_numpy(getattr(sp, k))))
        return sp

    # -- sub-band / sub-time analysis -------------------------------------
    def cut_dyn(self, fcuts: int = 0, tcuts: int = 0,
                backend: str | None = None):
        """Slice the dynspec into (fcuts+1) x (tcuts+1) tiles and compute
        each tile's ACF and secondary spectrum (dynspec.py:1035-1127).

        Sets ``cutdyn``, ``cutacf``, ``cutsspec`` (object arrays indexed
        [ifreq][itime]; tiles may differ in shape by one row/col) plus the
        per-tile centre ``cutmjd``/``cutfreq``.  Returns (cutdyn, cutsspec).
        """
        b = resolve(backend or self.backend)
        dyn = to_numpy(self._data.dyn)
        freqs = to_numpy(self._data.freqs)
        times = to_numpy(self._data.times)
        frows = np.array_split(np.arange(dyn.shape[0]), fcuts + 1)
        tcols = np.array_split(np.arange(dyn.shape[1]), tcuts + 1)
        nfr, ntc = len(frows), len(tcols)
        self.cutdyn = [[None] * ntc for _ in range(nfr)]
        self.cutacf = [[None] * ntc for _ in range(nfr)]
        self.cutsspec = [[None] * ntc for _ in range(nfr)]
        self.cutfreq = np.zeros(nfr)
        self.cutmjd = np.zeros(ntc)
        for i, fr in enumerate(frows):
            self.cutfreq[i] = float(np.mean(freqs[fr]))
            for j, tc in enumerate(tcols):
                tile = dyn[np.ix_(fr, tc)]
                self.cutdyn[i][j] = tile
                self.cutacf[i][j] = to_numpy(
                    _acf(np.asarray(tile, dtype=np.float64), backend=b))
                self.cutsspec[i][j] = to_numpy(
                    _sspec(np.asarray(tile, dtype=np.float64), backend=b))
        self.cutmjd[:] = [float(self._data.mjd
                                + np.mean(times[tc]) / 86400.0)
                          for tc in tcols]
        return self.cutdyn, self.cutsspec

    # -- results I/O -------------------------------------------------------
    def write_results(self, filename: str) -> None:
        """Append this observation's metadata and whichever measurements
        have been made (tau/dnu, eta, betaeta, each with errors) to the
        reference-schema CSV (scint_utils.py:75-108, which takes the
        Dynspec object the same way)."""
        from .io.results import results_row, write_results as _write

        meta = results_row(self._data)
        for a in ("tau", "dnu", "eta", "betaeta"):
            v = getattr(self, a, None)
            err = getattr(self, a + "err", None)
            # only write complete (value, error) pairs: a bare value with
            # no error would put a non-numeric token in the CSV and break
            # float_array_from_dict on read-back
            if v is not None and err is not None and np.ndim(v) == 0:
                meta[a] = float(v)
                meta[a + "err"] = float(err)
        _write(filename, meta)

    # -- plotting (delegates to the plotting module) -----------------------
    def plot_dyn(self, lamsteps: bool = False, trap: bool = False, **kw):
        """Dynamic spectrum view; ``lamsteps``/``trap`` plot the rescaled
        arrays (dynspec.py:206-229), resampling first if needed."""
        from . import plotting

        if lamsteps:
            if self.lamdyn is None:
                self.scale_dyn()
            return plotting.plot_dyn(self._data, dyn=self.lamdyn,
                                     y=self.lam,
                                     ylabel="Wavelength (m)", **kw)
        if trap:
            if self.trapdyn is None:
                self.scale_dyn(scale="trapezoid")
            return plotting.plot_dyn(self._data, dyn=self.trapdyn, **kw)
        return plotting.plot_dyn(self._data, **kw)

    def retrieve_wavefield(self, eta: float | None = None, **kw):
        """Chunked theta-theta wavefield retrieval (fit.wavefield).

        ``eta`` defaults to this object's fitted non-lamsteps curvature
        (us/mHz^2; the primary arc after a multi-arc fit).  Beyond-
        reference capability — the reference has no phase-retrieval
        path.
        """
        from .fit.wavefield import retrieve_wavefield as _retrieve

        if eta is None:
            eta = self.eta
            if eta is not None and np.ndim(eta) == 1:
                eta = float(eta[0])
        if eta is None:
            raise ValueError(
                "no curvature available: run fit_arc(lamsteps=False) or "
                "pass eta= (us/mHz^2 at the band centre frequency)")
        kw.setdefault("backend", resolve(self.backend))
        self.wavefield = _retrieve(self._data, float(eta), **kw)
        return self.wavefield

    def plot_acf(self, **kw):
        from . import plotting

        if self.acf is None:
            self.calc_acf()
        return plotting.plot_acf(self.acf, self._data,
                                 scint_params=self.scint_params, **kw)

    def plot_sspec(self, lamsteps: bool | None = None, **kw):
        from . import plotting

        lamsteps = self.lamsteps if lamsteps is None else lamsteps
        sec = self._secspec(lamsteps)
        eta = (self.betaeta if lamsteps else self.eta) \
            if kw.pop("plotarc", False) else None
        if eta is not None and np.ndim(eta) == 1:
            eta = float(eta[0])  # multi-arc: overlay the primary arc
        return plotting.plot_sspec(sec, eta=eta, **kw)

    def plot_all(self, **kw):
        from . import plotting

        sec = self._secspec(self.lamsteps)
        if self.acf is None:
            self.calc_acf()
        return plotting.plot_all(self._data, self.acf, sec, **kw)


def sort_dyn(dynfiles: Sequence[str], outdir: str | None = None,
             min_nsub: int = 10, min_nchan: int = 50,
             min_tsub: float = 10, min_freq: float = 0,
             max_freq: float = 5000, max_frac_bw: float = 2,
             remove_fracbw: float = 0.6, verbose: bool = False,
             backend: str = "numpy") -> tuple[list[str], list[str]]:
    """Batch triage of psrflux files into good/bad lists
    (dynspec.py:1599-1660): metadata filters (frequency range, fractional
    bandwidth, minimum channels/subints/duration), then a processing smoke
    test (trim -> refill -> time gain correction -> sspec) with an all-NaN
    quarantine.  Writes ``good_files.txt`` / ``bad_files.txt`` to
    ``outdir`` when given; returns (good, bad).
    """
    good, bad = [], []
    for fn in dynfiles:
        try:
            ds = Dynspec(filename=fn, process=False, backend=backend,
                         verbose=verbose)
            if not (min_freq < ds.freq < max_freq):
                raise ValueError(f"freq {ds.freq} outside range")
            if ds.bw / ds.freq > max_frac_bw:
                raise ValueError("fractional bandwidth too large")
            bw0 = ds.bw
            ds.trim_edges()
            if ds.nchan < min_nchan or ds.nsub < min_nsub:
                raise ValueError("too few channels/subints after trim")
            if ds.tobs < 60 * min_tsub:
                raise ValueError("observation too short")
            if ds.bw < remove_fracbw * bw0:
                raise ValueError("too much band trimmed away")
            ds.refill().correct_band(time=True)
            ds.calc_sspec()
            if np.all(np.isnan(ds.sspec)):
                raise ValueError("all-NaN secondary spectrum")
            good.append(fn)
        except Exception as e:  # quarantine, never crash the batch
            if verbose:
                from .utils.log import get_logger, log_event

                log_event(get_logger(), "sort_dyn_reject", file=fn,
                          error=repr(e))
            bad.append(fn)
    if outdir is not None:
        os.makedirs(outdir, exist_ok=True)
        for name, lst in (("good_files.txt", good), ("bad_files.txt", bad)):
            with open(os.path.join(outdir, name), "w") as f:
                f.writelines(x + "\n" for x in lst)
    return good, bad


def fit_arc_campaign(epochs, lamsteps: bool = True, numsteps: int = 2000,
                     constraint=(0.0, np.inf), mesh=None, **config_kw):
    """One campaign arc curvature from MANY epochs of the same source.

    Incoherent profile stacking (beyond the reference, whose fitter is
    one-file-at-a-time): every epoch's normalised delay-scrunched
    power-vs-curvature profile is nanmean-stacked before a single arc
    measurement, growing weak-arc S/N as sqrt(len(epochs)).  Epochs may
    be ``Dynspec`` wrappers, ``DynspecData``, or psrflux paths (paths
    get the batched engine's standard preparation: trim_edges ->
    refill); all epochs must land in ONE shape/axis bucket — mixed
    grids are a usage error, reported with the bucket split.  Returns a
    scalar :class:`~scintools_tpu.data.ArcFit` whose profile fields
    plot directly (``plotting.plot_arc_profile``).

    Extra keyword arguments become :class:`PipelineConfig` fields (e.g.
    ``arc_scrunch_rows``, ``prewhite``); execution delegates to
    ``parallel.run_pipeline`` (one jit for the whole campaign,
    NaN-filled divisibility pad-lanes, optional ``mesh=`` sharding).
    """
    from .io import read_psrflux
    from .ops import refill, trim_edges
    from .parallel import PipelineConfig, run_pipeline

    datas = []
    for e in epochs:
        if isinstance(e, str):
            datas.append(refill(trim_edges(read_psrflux(e))))
        elif isinstance(e, Dynspec):
            datas.append(e._data)
        else:
            datas.append(e)
    if not datas:
        raise ValueError("fit_arc_campaign needs at least one epoch")
    cfg = PipelineConfig(lamsteps=lamsteps, fit_scint=False,
                         arc_numsteps=numsteps, arc_constraint=constraint,
                         arc_stack=True, **config_kw)
    results = run_pipeline(datas, cfg, mesh=mesh)
    if len(results) != 1:
        raise ValueError(
            f"fit_arc_campaign epochs span {len(results)} shape/axis "
            f"buckets (sizes {[len(i) for i, _ in results]}) — a "
            f"campaign stack needs one shared grid; fit each bucket "
            f"separately")
    return results[0][1].arc_stacked
