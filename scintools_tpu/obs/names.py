"""The CLOSED catalog of observability names (ISSUE 10 satellite).

Every counter/gauge/span/event/histogram name a ``scintools_tpu``
module emits must be registered here: a typo'd metric name silently
creates a brand-new series — it vanishes from `trace report`'s curated
sections, from the fleet rollup, and from every tier-1 counter
assertion, and nothing ever fails.  The AST lint
(``scripts/check_obs_names.py``, enforced by
``tests/test_obs_names.py``) walks the package for literal first
arguments to ``obs.inc`` / ``obs.gauge`` / ``obs.span`` /
``obs.observe`` / ``obs.event`` / ``obs.traced`` (and the
``core.``-spelled equivalents inside ``obs/``) and fails on any name
missing from this catalog.

Conventions: units ride in the name (``*_s`` seconds, ``*_ms``
milliseconds, ``bytes_*``); per-key series use a bracketed FAMILY —
``family[<key>]`` — registered once in :data:`FAMILIES`; dynamic span
prefixes (``stage.<name>``) register in :data:`SPAN_PREFIXES`.

Documented in docs/observability.md; extend the relevant set in the
same change that adds the emitting call site.
"""

from __future__ import annotations

# -- counters (obs.inc) -----------------------------------------------------
COUNTERS = frozenset({
    # pipeline / driver
    "epochs_processed", "epochs_failed", "epochs_synthesized",
    "bytes_h2d", "jit_cache_miss", "prefetch_stall_s", "oom_backoff",
    # predictive OOM avoidance (obs/devmem + driver admission): chunk
    # rung step-downs taken BEFORE launching a chunk whose predicted
    # peak exceeds measured headroom (the reactive oom_backoff stays
    # the fallback)
    "oom_predicted_avoided",
    "lm_steps", "lsq_nfev", "lsq_fits",
    # ops / cleaning / sim
    "refill_calls", "refill_pixels", "zap_calls", "zap_pixels",
    "screens_simulated",
    # compile cache / warm artifacts
    "compile_cache_hit", "compile_cache_miss",
    "compile_cache_evictions", "cache_artifact_packed",
    "cache_artifact_unpacked", "cache_artifact_rejected",
    # serve
    "queue_wait_s", "serve_jobs_claimed", "serve_batches",
    "serve_lanes_filled", "serve_lanes_total", "jobs_done",
    "jobs_failed", "job_retries", "job_transient_retries",
    "serve_synth_jobs", "serve_synth_rows",
    # results plane (columnar segments — utils/segments.py)
    "segment_flushes", "segment_rows", "segment_bytes",
    "compactions", "segments_compacted",
    "segments_quarantined", "segment_salvaged_rows",
    # reliability
    "epochs_quarantined", "store_corrupt_rows", "faults_injected",
    # fleet pool controller (serve/pool.py — ISSUE 13): backpressure-
    # driven scale decisions, stale-worker replacement, spawn failures
    "pool_scale_up", "pool_scale_down", "pool_stale_replaced",
    "pool_spawn_failed",
    # claim-time affinity routing (JobQueue.claim under hints): warm-
    # here claims, warm-elsewhere claims taken after the grace window,
    # deferrals left for the warm worker, memory-unfit deferrals left
    # for a roomier worker
    "affinity_hits", "affinity_misses", "affinity_deferred",
    "pool_mem_deferred",
    # streaming ingest plane (scintools_tpu.stream — ISSUE 15):
    # sliding-window recompute ticks over live feeds, stream-job
    # registrations, and per-chunk data-quality quarantines (masked,
    # never fatal — reasons in the bracketed family)
    "stream_ticks", "serve_stream_jobs", "chunks_quarantined",
    # SLO & alerting plane (obs/slo.py — ISSUE 16): scale-ups taken on
    # the PREDICTED-breach signal (the trend-leading branch, beside the
    # reactive pool_scale_up backpressure one)
    "pool_predicted_breach",
    # incremental streaming hot path (ISSUE 17, stream/incremental.py +
    # stream/window.py): O(hop) sliding-window ticks vs full-path
    # resyncs, and the warm-started fitter's seed/fallback split —
    # the drift-bounding discipline made countable
    "incremental_ticks", "tick_resyncs",
    "warm_start_seeded", "warm_start_fallbacks",
    # feed->worker pinning + backfill lane (serve/queue.py +
    # serve/worker.py): pinned claims honoured, claims deferred for a
    # live pinned owner, and bulk-lane catch-up jobs for late feeds
    # backfill_jobs = catch-up jobs SUBMITTED at registration;
    # serve_backfill_jobs = backfill executions a worker ran
    "feed_pins", "feed_pin_deferred", "backfill_jobs",
    "serve_backfill_jobs",
    # differentiable inference plane (scintools_tpu.infer — ISSUE 18):
    # infer_jobs = gradient-inference campaigns executed (served or
    # direct CLI); infer_epochs = epochs entering the MAP fit;
    # opt_steps = Adam iterations actually taken by the winning starts;
    # infer_converged/infer_diverged = per-epoch outcome split
    # (diverged = best lane's loss non-finite -> row quarantined)
    "infer_jobs", "infer_epochs", "opt_steps",
    "infer_converged", "infer_diverged",
    # acceleration-search plane (scintools_tpu.search — ISSUE 19):
    # search_jobs = search campaigns executed (served or direct CLI);
    # search_epochs = epochs scored; templates_scored = (epoch,
    # template) correlations issued (coarse full bank + fine
    # survivors, or the full bank once on the naive reference);
    # prune_survivors = fine-lane trials that survived the coarse
    # pass; candidates_emitted = per-epoch candidate rows that
    # cleared the non-finite quarantine
    "search_jobs", "search_epochs", "templates_scored",
    "prune_survivors", "candidates_emitted",
    # crash-consistency plane (utils/fsio.py + serve/fsck.py — ISSUE
    # 20): fsio_write_errors = degraded best-effort plane writes
    # (heartbeat/hints/pool status) that used to be log-line-only;
    # fsck_runs/findings/repairs = audit executions, invariant
    # violations found, repairs applied (per-class breakdown rides
    # the bracketed families)
    "fsio_write_errors", "fsck_runs", "fsck_findings", "fsck_repairs",
})

# -- gauges (obs.gauge) -----------------------------------------------------
GAUGES = frozenset({
    "queue_depth", "batch_fill_ratio", "effective_chunk",
    "compile_cache_artifact",
    # device-memory plane (obs/devmem): summed over local devices;
    # hbm_bytes_in_use additionally streams timestamped events per
    # execute window (the headroom timeline)
    "hbm_bytes_in_use", "hbm_bytes_limit",
    # pool controller (serve/pool.py): live worker-process count
    "pool_workers",
    # streaming ingest plane (stream/window.py): wall seconds the
    # consumer runs behind the feed head (streamed timeline; the
    # per-feed breakdown rides the bracketed family)
    "stream_lag_s",
    # SLO & alerting plane (obs/slo.py): count of alerts currently in
    # the firing state (per-SLO burn/budget ride bracketed families)
    "alerts_firing",
    # acceleration-search plane (search/bank.py): resident template
    # bank footprint (the conjugated rFFT buffer held in HBM)
    "bank_bytes",
})

# -- spans (obs.span / obs.traced) ------------------------------------------
SPANS = frozenset({
    "pipeline.run", "pipeline.stage", "pipeline.prefetch",
    "pipeline.gather",
    "ops.sspec", "ops.acf",
    "fit.arc", "fit.scint", "fit.lsq_numpy",
    "sim.simulation",
    "serve.poll", "serve.load", "serve.batch", "serve.compact",
    # backfill lane: one bulk catch-up pass over a deep feed backlog
    "serve.backfill",
    # streaming ingest plane: one sliding-window recompute tick
    "stream.tick",
    # device-memory & profiler plane (obs/devmem, utils/timing):
    # the --xprof jax.profiler.trace bracket and the on-OOM
    # device_memory_profile snapshot dump
    "devmem.xprof", "devmem.memory_profile",
    # differentiable inference plane (infer/runner.py — ISSUE 18): one
    # span per MAP-fit campaign; the compiled step's compile/execute
    # sub-spans ride instrument_jit's dynamic "infer.step.*" names
    "infer.fit",
    # acceleration-search plane (search/runner.py — ISSUE 19): one
    # span per scored campaign; the compiled programs' compile/execute
    # sub-spans ride instrument_jit's dynamic "search.step.*" /
    # "search.naive.*" names
    "search.score",
    # repo-root bench.py (walked by the lint since ISSUE 16): the
    # headline measurement's own decomposition spans
    "bench.baseline_epoch", "bench.h2d", "bench.step.compile",
    "bench.step.compile.warm", "bench.step.execute",
})

# dynamic span-name prefixes: obs.span(f"<prefix><runtime part>") — the
# runtime part is caller-chosen (CLI StageTimers regions; instrument_jit
# derives "<step name>.compile/.execute" from its name argument)
SPAN_PREFIXES = ("stage.",)

# -- lifecycle events (obs.event) -------------------------------------------
EVENTS = frozenset({
    # distributed job trace hops (obs/fleet.py contract); job.tick =
    # one stream registration's tick batch (ISSUE 15)
    "job.submit", "job.claim", "job.preflight", "job.batch", "job.row",
    "job.complete", "job.fail", "job.requeue", "job.poison", "job.tick",
    # bench run correlation root (bench flight records embed the id)
    "bench.run",
    # alert lifecycle (obs/slo.py AlertEngine — ISSUE 16): one event
    # per durable state-machine transition, plus operator acks
    "alert.pending", "alert.firing", "alert.resolved", "alert.ack",
})

# -- histograms (obs.observe) -----------------------------------------------
HISTS = frozenset({
    "queue_wait_s",
    # put -> durable/visible latency of buffered result rows (the
    # segment plane's replacement for the end-of-campaign gather cliff)
    "row_visibility_s",
    # wall seconds of one sliding-window stream tick (consume ->
    # published row), the SCINT_BENCH_STREAM lane's p50/p95 source
    "tick_latency_s",
    # submit -> complete wall seconds of one serve job (the end-to-end
    # latency SLO source; per-lane breakdown rides the family)
    "job_latency_s",
})

# -- bracketed families: "<family>[<key>]" ----------------------------------
FAMILIES = frozenset({
    "compile_ms",                                   # counter
    # per-unit jit-cache misses beside the aggregate jit_cache_miss
    # counter (ISSUE 14 split pipeline: key = pipeline.front /
    # pipeline.back / pipeline.step — the split acceptance gate asserts
    # jit_cache_miss[pipeline.back] == 0 on a warmed process hitting a
    # novel shape)
    "jit_cache_miss",                               # counter
    "faults_injected", "epochs_quarantined",        # counters
    "bucket_hits", "bucket_lanes_real", "bucket_lanes_pad",  # counters
    "queue_shard_claims",                           # counter (per shard)
    "bucket_catalog", "step_flops", "step_bytes",   # gauges
    # measured per-signature peak HBM beside the step_bytes model
    # (obs/devmem window attribution; key = <stage>:<B>x<grid>:<dtype>)
    "step_hbm_peak",                                # gauge
    # per-shard AND per-lane queued depth beside the total queue_depth
    # gauge (the documented total+breakdown pair pattern; lane keys are
    # spelled "lane:<lane>" to stay distinct from shard numbers)
    "queue_depth",                                  # gauge (per shard)
    # per-QoS-lane claim counts (ISSUE 13 weighted-fair claim order)
    "lane_claims",                                  # counter (per lane)
    # streaming ingest plane (ISSUE 15): quarantine reasons and the
    # per-feed lag breakdown beside the totals above — since ISSUE 16
    # the per-feed lag ALSO feeds a bucket-ladder histogram of the
    # same family (freshness SLO source, merged via heartbeats)
    "chunks_quarantined",                           # counter (per reason)
    "stream_lag_s",                                 # gauge+hist (per feed)
    # SLO & alerting plane (ISSUE 16): per-lane queue-wait and
    # end-to-end job-latency histograms beside their totals, and the
    # per-SLO burn/budget gauges the trace-report SLO section reads
    "queue_wait_s",                                 # hist (per lane)
    "job_latency_s",                                # hist (per lane)
    "slo_burn_fast", "slo_burn_slow",               # gauges (per SLO)
    "slo_budget_remaining",                         # gauge (per SLO)
    # crash-consistency plane (ISSUE 20): which best-effort plane's
    # write degraded (heartbeat/hints/pool), and the per-invariant-
    # class finding/repair breakdown beside the fsck totals
    "fsio_write_errors",                            # counter (per plane)
    "fsck_findings", "fsck_repairs",                # counters (per class)
})

_SETS = {"inc": COUNTERS, "gauge": GAUGES, "span": SPANS,
         "traced": SPANS, "observe": HISTS, "event": EVENTS}


def is_registered(func: str, literal: str, prefix_only: bool = False) -> bool:
    """Whether a literal (or literal PREFIX of an f-string, when
    ``prefix_only``) first argument to ``obs.<func>(...)`` names a
    registered series.

    Bracketed families: any name containing ``[`` is checked as its
    family (the part before the bracket).  F-string prefixes: a prefix
    ending at ``[`` must be a family; otherwise it must extend a
    registered span prefix or be extensible to a registered exact name
    (conservative — the lint's job is catching typos in the common
    literal case, not proving dynamic names)."""
    names = _SETS.get(func)
    if names is None:
        return True
    if "[" in literal:
        return literal.split("[", 1)[0] in FAMILIES
    if not prefix_only:
        return (literal in names
                or (func in ("span", "traced")
                    and literal.startswith(SPAN_PREFIXES)))
    # f-string with a constant prefix and no bracket yet: accept a
    # registered dynamic span prefix, a family the bracket of which
    # starts in the dynamic part (rare; spelled "family[" above), or a
    # prefix of some registered exact name
    if func in ("span", "traced") and literal.startswith(SPAN_PREFIXES):
        return True
    return any(n.startswith(literal) for n in names | FAMILIES)


def all_names() -> dict:
    """The whole catalog, keyed by kind (docs/introspection)."""
    return {"counters": sorted(COUNTERS), "gauges": sorted(GAUGES),
            "spans": sorted(SPANS), "span_prefixes": list(SPAN_PREFIXES),
            "events": sorted(EVENTS), "hists": sorted(HISTS),
            "families": sorted(FAMILIES)}
