"""Pluggable trace sinks: JSONL file and the key=value logger.

Every sink receives the same event dicts the in-process registry
records (``kind`` = "span" | "counter" | "gauge"); the JSONL format is
the on-disk contract `scintools-tpu trace report` consumes (one JSON
object per line: ts, kind, name, dur_ms/value, attrs).
"""

from __future__ import annotations

import json
import threading


class JsonlSink:
    """Append one JSON event per line to ``path`` (thread-safe).

    Opened in append mode so a multi-command session (or a driver that
    re-enables tracing) accumulates one decomposable trace; ``trace
    report`` aggregates across everything in the file.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, event: dict) -> None:
        # default=str: attrs may carry shapes/dtypes/paths — never let a
        # non-JSON-native attr kill the traced pipeline.  Flushed per
        # line: event rate is per-stage (not per-sample), and bench.py
        # exits via os._exit, which would drop a buffered tail.
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class LogSink:
    """Mirror events onto the structured key=value logger
    (:func:`scintools_tpu.utils.log.log_event`), so traces interleave
    with the CLI's existing epoch/resume/routes events."""

    def __init__(self, logger=None):
        from ..utils.log import get_logger

        self._logger = logger if logger is not None else get_logger()

    def emit(self, event: dict) -> None:
        from ..utils.log import log_event

        kind = event.get("kind", "span")
        if kind == "span":
            fields = {"name": event["name"], "dur_ms": event["dur_ms"]}
            fields.update(event.get("attrs") or {})
            log_event(self._logger, "span", **fields)
        else:
            log_event(self._logger, kind, name=event["name"],
                      value=event.get("value"))

    def flush(self) -> None:
        pass
