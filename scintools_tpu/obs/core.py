"""Tracing/metrics core: spans, counters, gauges, and the in-process
registry (ISSUE 1 tentpole; the decomposable-timing layer SURVEY.md §5
"tracing" planned and GPU pulsar-search practice — arXiv:1711.10855 —
demands before any perf work).

Design constraints, in order:

1. **Disabled cost is one flag check.**  ``span()``/``inc()`` test a
   module-level bool first; disabled ``span()`` returns one shared
   ``_NULL_SPAN`` singleton (no allocation, enter/exit are constant
   methods), disabled ``inc()`` returns immediately.  Verified by
   tests/test_obs.py::test_disabled_span_is_shared_noop.
2. **Thread-safe collection.**  The registry mutates under one lock;
   span nesting uses a thread-local stack, so concurrent pipeline
   drivers / bench watchdog threads cannot corrupt each other's paths.
3. **jax-free.**  Importing this module never imports jax (device
   helpers live in :mod:`scintools_tpu.obs.jax_helpers`).

Spans are host-side wall-clock (``time.perf_counter``) regions.  Device
work dispatched asynchronously inside a span is only charged to it when
the caller fences (see ``jax_helpers.fence`` /
``jax_helpers.instrument_jit``, which block_until_ready before the span
closes) — raw spans around un-fenced jax dispatch measure dispatch, and
say nothing about device time.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import deque

from .hist import Hist

# Single source of the enabled flag.  Read via enabled()/the fast-path
# checks below; written only by enable()/disable() under _LOCK.
_ENABLED = False
_LOCK = threading.RLock()
_TLS = threading.local()

# bounded in-process event history: the crash flight recorder's ring
# buffer (dump_flight writes its tail) AND the tests' drill-down; the
# per-name duration lists in the registry are what summary() reads
_EVENT_HISTORY = 65536

# span/event ids: process-unique, cheap, and globally unique enough for
# fleet trace reassembly once prefixed with the pid (two workers on one
# host cannot collide; two hosts sharing a queue dir are distinguished
# by the hostname in attrs/worker ids, and id collisions across hosts
# would need equal pid AND equal counter — accepted for a trace tool)
_ID_COUNTER = itertools.count(1)


def _new_id() -> str:
    return f"{os.getpid():x}-{next(_ID_COUNTER):x}"


def _span_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class _NullSpan:
    """The disabled-mode span: a shared, stateless context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class Span:
    """One timed region.  Use via ``with span(name, **attrs):``.

    ``path`` is the '/'-joined nesting path ("pipeline.run/pipeline.stage")
    assigned at __enter__ from this thread's span stack; ``name`` is the
    aggregation key (``summary()`` groups by name, so the same stage
    reached through different parents still lands in one table row).
    """

    __slots__ = ("name", "attrs", "path", "dur_ms", "span_id",
                 "parent_id", "_t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.path = name
        self.dur_ms = None
        # causal identity (ISSUE 10 fleet tracing): every recorded span
        # carries its own id and its in-process parent's, so a merged
        # multi-process trace reassembles the hierarchy even where the
        # '/'-joined path is ambiguous (same stage reached twice)
        self.span_id = _new_id()
        self.parent_id = None
        self._t0 = 0.0

    def set(self, **attrs) -> "Span":
        """Attach attributes discovered inside the region (fit residuals,
        iteration counts, ...) before the span closes."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        stack = _span_stack()
        if stack:
            self.path = stack[-1].path + "/" + self.name
            self.parent_id = stack[-1].span_id
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ms = (time.perf_counter() - self._t0) * 1e3
        stack = _span_stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # unbalanced exit (generator half-closed)
            stack.remove(self)
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        _REGISTRY.record_span(self)
        return False


class Registry:
    """Thread-safe in-memory aggregation + fan-out to attached sinks."""

    def __init__(self):
        self._durs: dict[str, list] = {}
        self._counters: dict[str, float] = {}
        self._flushed: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Hist] = {}
        self._events = deque(maxlen=_EVENT_HISTORY)
        self._sinks: list = []

    # -- collection --------------------------------------------------------
    def record_span(self, span: Span) -> None:
        event = {"ts": time.time(), "kind": "span", "name": span.name,
                 "path": span.path, "dur_ms": round(span.dur_ms, 6),
                 "span": span.span_id, "pid": os.getpid(),
                 "attrs": span.attrs}
        if span.parent_id is not None:
            event["parent"] = span.parent_id
        with _LOCK:
            self._durs.setdefault(span.name, []).append(span.dur_ms)
            # mergeable twin of the duration list: the fixed-bucket
            # histogram heartbeats ship (per-stage latency buckets)
            h = self._hists.get(span.name)
            if h is None:
                h = self._hists[span.name] = Hist()
            h.observe(span.dur_ms)
            self._events.append(event)
            sinks = list(self._sinks)
        for s in sinks:
            s.emit(event)

    def record_event(self, name: str, parent: str | None = None,
                     attrs: dict | None = None) -> str:
        """A zero-duration lifecycle record (job submit/claim/requeue/
        complete hops): like a span it carries its own id + optional
        parent link and streams to sinks immediately, but it has no
        duration and never enters the span tables.  Returns the new
        id so callers can persist it as the NEXT hop's parent (the
        cross-process link a job record carries between workers)."""
        event = {"ts": time.time(), "kind": "event", "name": name,
                 "span": _new_id(), "pid": os.getpid(),
                 "attrs": dict(attrs or {})}
        if parent is not None:
            event["parent"] = parent
        with _LOCK:
            self._events.append(event)
            sinks = list(self._sinks)
        for s in sinks:
            s.emit(event)
        return event["span"]

    def inc(self, name: str, value=1) -> None:
        with _LOCK:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value, stream: bool = False) -> None:
        if not stream:
            with _LOCK:
                self._gauges[name] = value
            return
        # timeline gauges (queue_depth at submit/complete/fail
        # transitions, hbm_bytes_in_use per execute window): the
        # registry's latest-value cell aliases a sawtooth at low flush
        # rates, so transition points stream one timestamped gauge
        # event per change to the sinks AND into the event ring — the
        # in-process timeline render_summary()/dump_flight read
        event = {"ts": time.time(), "kind": "gauge", "name": name,
                 "value": value, "pid": os.getpid()}
        with _LOCK:
            self._gauges[name] = value
            self._events.append(event)
            sinks = list(self._sinks)
        for s in sinks:
            s.emit(event)

    def observe(self, name: str, value: float) -> None:
        """Feed one value into the named fixed-bucket histogram (the
        mergeable fleet form; e.g. per-job queue wait in seconds)."""
        with _LOCK:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Hist()
            h.observe(value)

    def add_sink(self, sink) -> None:
        with _LOCK:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with _LOCK:
            if sink in self._sinks:
                self._sinks.remove(sink)

    # -- readout -----------------------------------------------------------
    def events(self) -> list:
        with _LOCK:
            return list(self._events)

    def counters(self) -> dict:
        with _LOCK:
            return dict(self._counters)

    def gauges(self) -> dict:
        with _LOCK:
            return dict(self._gauges)

    def hists(self) -> dict:
        """{name: sparse hist dict} — the heartbeat wire form (see
        obs/hist.py); merge across processes with merge_hist_dicts."""
        with _LOCK:
            return {name: h.to_dict() for name, h in self._hists.items()}

    def hist_summaries(self) -> dict:
        """{name: {count, total, mean, p50, p95, p99, min, max}} from
        the fixed-bucket histograms (bench flight records embed these;
        quantiles are bucket-edge estimates, unlike summary()'s exact
        per-process p50/p95)."""
        with _LOCK:
            return {name: h.summary() for name, h in self._hists.items()}

    def span_names(self) -> list:
        with _LOCK:
            return list(self._durs)

    def summary(self) -> dict:
        """Per-stage stats: {name: {count, total_ms, mean_ms, p50_ms,
        p95_ms}}, insertion-ordered (first occurrence first)."""
        with _LOCK:
            durs = {k: list(v) for k, v in self._durs.items()}
        return {name: summarize_durations(d) for name, d in durs.items()}

    def flush(self) -> None:
        """Push counter DELTAS since the last flush (and current gauges)
        to the sinks, then flush them.  Deltas — not totals — so a
        process that flushes more than once (bench flushes at its exit
        points AND inside device_throughput for the fallback subprocess)
        never double-counts: ``trace report`` sums counter events, and a
        sum of deltas is the true total."""
        with _LOCK:
            sinks = list(self._sinks)
            deltas = {name: value - self._flushed.get(name, 0)
                      for name, value in self._counters.items()
                      if value != self._flushed.get(name, 0)}
            self._flushed.update(self._counters)
            gauges = dict(self._gauges)
        now = time.time()
        for s in sinks:
            for name, value in deltas.items():
                s.emit({"ts": now, "kind": "counter", "name": name,
                        "value": value})
            for name, value in gauges.items():
                s.emit({"ts": now, "kind": "gauge", "name": name,
                        "value": value})
            s.flush()

    def reset(self) -> None:
        with _LOCK:
            self._durs.clear()
            self._counters.clear()
            self._flushed.clear()
            self._gauges.clear()
            self._hists.clear()
            self._events.clear()

    def dump_flight(self, directory: str, error: str | None = None,
                    classification: str | None = None,
                    limit: int = 4096, extra: dict | None = None) -> str:
        """Crash flight recorder: write the event ring buffer's tail
        (newest ``limit`` records) plus a header snapshot (pid, error +
        faults.classify_error verdict, counters, gauges) to
        ``<directory>/flight_<pid>.jsonl``.  Called on unhandled worker
        failure (serve/worker.py) so the last moments of a dead process
        survive for the fleet rollup; the JSONL lines are the normal
        trace format, readable by ``trace report``."""
        import json

        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"flight_{os.getpid()}.jsonl")
        with _LOCK:
            tail = list(self._events)[-max(int(limit), 0):]
            header = {"ts": time.time(), "kind": "flight",
                      "pid": os.getpid(), "events": len(tail),
                      "counters": dict(self._counters),
                      "gauges": dict(self._gauges)}
        if error is not None:
            header["error"] = error
        if classification is not None:
            header["classification"] = classification
        if extra:
            header.update(extra)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(header, default=str) + "\n")
            for ev in tail:
                fh.write(json.dumps(ev, default=str) + "\n")
        os.replace(tmp, path)
        return path


def _quantile(sorted_durs: list, q: float) -> float:
    """Nearest-rank quantile of an already-sorted list (stdlib-only)."""
    i = int(round(q * (len(sorted_durs) - 1)))
    return sorted_durs[min(max(i, 0), len(sorted_durs) - 1)]


def summarize_durations(durs: list) -> dict:
    s = sorted(durs)
    total = sum(s)
    return {"count": len(s),
            "total_ms": round(total, 3),
            "mean_ms": round(total / len(s), 3),
            "p50_ms": round(_quantile(s, 0.50), 3),
            "p95_ms": round(_quantile(s, 0.95), 3)}


_REGISTRY = Registry()


# ---------------------------------------------------------------------------
# module-level API (the fast path)
# ---------------------------------------------------------------------------


def enabled() -> bool:
    return _ENABLED


def span(name: str, **attrs):
    """A timed region.  Disabled: the shared no-op singleton (the flag
    check is the entire cost).  Enabled: a fresh :class:`Span`."""
    if not _ENABLED:
        return _NULL_SPAN
    return Span(name, attrs)


def inc(name: str, value=1) -> None:
    """Add to a named counter (no-op when disabled)."""
    if _ENABLED:
        _REGISTRY.inc(name, value)


def gauge(name: str, value, stream: bool = False) -> None:
    """Set a named gauge to its latest value (no-op when disabled).
    ``stream=True`` additionally emits one timestamped gauge event to
    the sinks NOW — for timeline gauges (queue_depth transitions) whose
    latest-value cell would alias between flushes."""
    if _ENABLED:
        _REGISTRY.gauge(name, value, stream=stream)


def observe(name: str, value: float) -> None:
    """Feed one value into the named fixed-bucket histogram (no-op when
    disabled) — the mergeable fleet form of a latency sample."""
    if _ENABLED:
        _REGISTRY.observe(name, value)


def event(name: str, parent: str | None = None, **attrs) -> str | None:
    """Record a zero-duration lifecycle event with its own id and an
    optional cross-process parent link; returns the new id (None when
    disabled — callers persist it as the next hop's parent only when a
    trace is actually being taken)."""
    if not _ENABLED:
        return None
    return _REGISTRY.record_event(name, parent=parent, attrs=attrs)


def hist_summaries() -> dict:
    return _REGISTRY.hist_summaries()


def dump_flight(directory: str, error: str | None = None,
                classification: str | None = None,
                limit: int = 4096, extra: dict | None = None) -> str:
    """Dump the in-process event ring buffer (see
    Registry.dump_flight); works even when tracing is disabled — the
    header snapshot (pid/error/classification) still lands, the event
    tail is simply whatever the ring holds."""
    return _REGISTRY.dump_flight(directory, error=error,
                                 classification=classification,
                                 limit=limit, extra=extra)


def get_registry() -> Registry:
    return _REGISTRY


def traced(name: str):
    """Decorator form of :func:`span` for whole-function stages.

    Disabled cost is one flag check in the wrapper; enabled, the call
    runs inside a span named ``name``.
    """
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with Span(name, {}):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def enable(jsonl: str | None = None, log: bool = False,
           logger=None) -> None:
    """Turn tracing on, optionally attaching sinks.

    ``jsonl=`` appends one JSON event per line to that path (idempotent:
    enabling twice with the same path attaches one sink).  ``log=True``
    mirrors spans onto the key=value logger (``logger=`` overrides the
    default channel).
    """
    global _ENABLED
    import os

    from .sinks import JsonlSink, LogSink

    with _LOCK:
        # dedupe on the RESOLVED path: the CLI and bench may name the
        # same file with different spellings (relative vs absolute)
        if jsonl is not None and not any(
                isinstance(s, JsonlSink)
                and os.path.abspath(s.path) == os.path.abspath(jsonl)
                for s in _REGISTRY._sinks):
            _REGISTRY.add_sink(JsonlSink(jsonl))
        if log and not any(isinstance(s, LogSink)
                           for s in _REGISTRY._sinks):
            _REGISTRY.add_sink(LogSink(logger))
        _ENABLED = True


def disable(flush: bool = True) -> None:
    """Turn tracing off; by default flush counters to (and close) every
    attached sink.  The in-memory registry keeps its data until
    ``reset()`` so post-run ``summary()`` still works."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
        sinks = list(_REGISTRY._sinks)
    if flush:
        _REGISTRY.flush()
    for s in sinks:
        _REGISTRY.remove_sink(s)
        close = getattr(s, "close", None)
        if close is not None:
            close()


@contextlib.contextmanager
def tracing(jsonl: str | None = None, log: bool = False, reset: bool = True):
    """Scoped tracing for tests/benchmarks::

        with obs.tracing(jsonl="run.jsonl"):
            run_pipeline(epochs, cfg)
        print(obs.render_summary())
    """
    if reset:
        _REGISTRY.reset()
    enable(jsonl=jsonl, log=log)
    try:
        yield _REGISTRY
    finally:
        disable()


def summary() -> dict:
    return _REGISTRY.summary()


def counters() -> dict:
    return _REGISTRY.counters()


def reset() -> None:
    _REGISTRY.reset()


def flush() -> None:
    _REGISTRY.flush()


def render_summary() -> str:
    """The per-stage table + counters for the CURRENT in-process registry
    (same renderer as ``trace report``)."""
    from .report import render

    return render(summary(), counters(), gauges=_REGISTRY.gauges(),
                  events=_REGISTRY.events())
