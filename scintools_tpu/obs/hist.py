"""Fixed-bucket histograms that merge associatively (ISSUE 10).

The per-process registry keeps exact duration lists (``summary()``'s
p50/p95 stay exact), but a FLEET cannot merge quantiles — p95(A) and
p95(B) say nothing about p95(A ∪ B).  Bucket counts do merge: with one
CLOSED bucket ladder shared by every process, ``merge(A, B)`` is an
elementwise add, associative and commutative by construction, so N
workers' heartbeat snapshots fold into one fleet histogram in any
order (tested: tests/test_fleet.py::test_heartbeat_merge_associative).

The ladder is geometric at half-octave (√2) steps — quantiles read
from bucket edges carry at most ~41 % relative error, uniform across
the range (µs-scale span latencies to hour-scale queue waits), and the
exact ``count``/``total``/``min``/``max`` ride alongside so means stay
exact.  Values are unit-agnostic (spans feed milliseconds, queue waits
feed seconds); the metric NAME carries the unit, per the obs naming
convention (``*_ms`` / ``*_s``).
"""

from __future__ import annotations

# Closed bucket ladder: 2^(k/2) for k in [-28, 34] — 6.1e-5 .. 1.3e5,
# 63 edges -> 64 buckets (the last is the overflow bucket).  Part of
# the heartbeat wire format: changing it breaks cross-version merges,
# so heartbeats stamp BOUNDS_VERSION and merge() refuses a mismatch.
BOUNDS = tuple(2.0 ** (k / 2.0) for k in range(-28, 35))
BOUNDS_VERSION = 1


def _bucket_index(value: float) -> int:
    """Index of the first bucket whose upper edge >= value (bisect on
    the closed ladder; values above every edge land in overflow)."""
    lo, hi = 0, len(BOUNDS)
    while lo < hi:
        mid = (lo + hi) // 2
        if BOUNDS[mid] < value:
            lo = mid + 1
        else:
            hi = mid
    return lo


class Hist:
    """One fixed-bucket histogram: counts per ladder bucket plus exact
    count/total/min/max."""

    __slots__ = ("counts", "n", "total", "vmin", "vmax")

    def __init__(self):
        self.counts = [0] * (len(BOUNDS) + 1)
        self.n = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None

    def observe(self, value: float) -> None:
        value = float(value)
        self.counts[_bucket_index(value)] += 1
        self.n += 1
        self.total += value
        self.vmin = value if self.vmin is None else min(self.vmin, value)
        self.vmax = value if self.vmax is None else max(self.vmax, value)

    def quantile(self, q: float) -> float | None:
        """Upper edge of the bucket holding the q-quantile observation
        (exact min/max for the extremes; None when empty)."""
        if not self.n:
            return None
        if q <= 0.0:
            return self.vmin
        if q >= 1.0:
            return self.vmax
        rank = round(q * (self.n - 1))   # nearest-rank, like summary()
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                if i >= len(BOUNDS):   # overflow bucket: only max known
                    return self.vmax
                return min(BOUNDS[i], self.vmax)
        return self.vmax

    def summary(self) -> dict:
        """{count, total, mean, p50, p95, p99, min, max} — the rollup
        row shape shared by heartbeats, fleet tables and bench flight
        records."""
        if not self.n:
            return {"count": 0}
        return {"count": self.n,
                "total": round(self.total, 6),
                "mean": round(self.total / self.n, 6),
                "p50": round(self.quantile(0.50), 6),
                "p95": round(self.quantile(0.95), 6),
                "p99": round(self.quantile(0.99), 6),
                "min": round(self.vmin, 6),
                "max": round(self.vmax, 6)}

    # -- wire format (heartbeats) ------------------------------------------
    def to_dict(self) -> dict:
        """Sparse JSON form: only occupied buckets travel (bounded
        write amplification — a worker's heartbeat carries dozens of
        ints, not 64 zeros per metric)."""
        return {"v": BOUNDS_VERSION,
                "buckets": {str(i): c for i, c in enumerate(self.counts)
                            if c},
                "n": self.n, "total": round(self.total, 9),
                "min": self.vmin, "max": self.vmax}

    @classmethod
    def from_dict(cls, d: dict) -> "Hist":
        """Raises ValueError on ANY malformed payload (wrong bounds
        version, out-of-range bucket index, n > 0 without min/max) —
        one exception type, so fleet readers can catch-and-warn
        instead of dying mid-rollup on a corrupt heartbeat."""
        if int(d.get("v", 0)) != BOUNDS_VERSION:
            raise ValueError(
                f"histogram bounds version {d.get('v')!r} != "
                f"{BOUNDS_VERSION} (cross-version heartbeats do not "
                "merge; upgrade the older worker)")
        h = cls()
        for i, c in (d.get("buckets") or {}).items():
            idx = int(i)
            if not 0 <= idx < len(h.counts):
                raise ValueError(f"histogram bucket index {idx} out of "
                                 f"range [0, {len(h.counts)})")
            h.counts[idx] = int(c)
        h.n = int(d.get("n", 0))
        h.total = float(d.get("total", 0.0))
        h.vmin = d.get("min")
        h.vmax = d.get("max")
        if h.n > 0 and (h.vmin is None or h.vmax is None):
            raise ValueError("histogram with n > 0 but no min/max")
        return h

    def merge(self, other: "Hist") -> "Hist":
        """Elementwise-add merge (associative + commutative); returns a
        NEW Hist, operands untouched."""
        out = Hist()
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.n = self.n + other.n
        out.total = self.total + other.total
        mins = [v for v in (self.vmin, other.vmin) if v is not None]
        maxs = [v for v in (self.vmax, other.vmax) if v is not None]
        out.vmin = min(mins) if mins else None
        out.vmax = max(maxs) if maxs else None
        return out


def merge_hist_dicts(dicts) -> dict | None:
    """Fold sparse heartbeat histogram payloads into one summary dict
    (the fleet rollup's per-metric row); None when nothing merged."""
    acc = None
    for d in dicts:
        if not d:
            continue
        h = Hist.from_dict(d)
        acc = h if acc is None else acc.merge(h)
    return None if acc is None else acc.summary()
