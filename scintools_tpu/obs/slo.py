"""Declared SLOs, error-budget burn rates, and durable alerts
(ISSUE 16 — the judgment layer over the PR 10/12/15 telemetry stack).

The telemetry planes *measure* (per-feed ``stream_lag_s`` freshness,
per-lane queue waits, tick latencies — all through the closed
bucket-ladder histograms of :mod:`~scintools_tpu.obs.hist`); this
module *judges*: a declarative SLO registry, a multi-window
error-budget burn-rate evaluator, and durable alert state machines.

Three design rules keep the plane cheap and fleet-exact:

1. **No new sample transport.**  Every latency/freshness SLO evaluates
   over the histograms the workers already stamp into heartbeats.  The
   bad/good split at a threshold is PER-BUCKET (a bucket is "bad" when
   its lower ladder edge is >= the threshold), so classification
   commutes with :meth:`~scintools_tpu.obs.hist.Hist.merge` — the
   fleet-scope burn rate is an associative fold of per-worker (bad, n)
   deltas, equal to the single-process value on the same samples
   (tier-1 gated, tests/test_slo.py).  The effective threshold rounds
   UP to the next ladder edge (at most ~41 % — half an octave); pick
   thresholds on edges (powers of √2) for exactness.

2. **Multi-window burn rates.**  ``burn = (bad/n) / (1 - objective)``:
   1.0 means the error budget burns exactly at the rate that exhausts
   it over the window; an alert trips when the FAST window burns at
   >= ``fast_burn`` (page-grade: minutes to exhaustion) OR the SLOW
   window at >= ``slow_burn`` (ticket-grade: hours).  Budget remaining
   is read off the slow window.

3. **Durable alerts.**  One versioned newest-wins row per SLO
   (``alert.<name>``, the dedup key) in the PR 15 results store:
   pending → firing → resolved with ``min_hold_s`` hysteresis in BOTH
   directions, a bounded transition history, and trace-linked context
   (the ``trace_id`` of the breaching feed/lane job where one exists).
   Rows survive worker SIGKILL; any process (worker, pool controller,
   ``scintools-tpu alerts``) reads the same state.

Specs load from ``<queue dir>/slo.json`` (a list of spec dicts, or
``{"slos": [...]}``) with ``SCINT_SLOS`` env JSON overriding by name —
validated by :func:`validate_slo_spec` exactly like
``validate_stream_spec`` gates stream payloads.
"""

from __future__ import annotations

import json
import os
import time

from . import core
from .hist import BOUNDS, Hist, _bucket_index

SLO_FILENAME = "slo.json"
SLO_VERSION = 1

# freshness/latency kinds evaluate over the bucket-ladder histogram of
# the same name (per-key series via the bracketed family); "heartbeat"
# is the liveness kind, evaluated fleet-scope from beat ages instead
SLO_KINDS = ("stream_lag_s", "queue_wait_s", "job_latency_s",
             "heartbeat")

DEFAULT_OBJECTIVE = 0.99
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
# Google-style multiwindow multipliers: 14.4x on the fast window pages
# (budget gone in ~2 % of the slow window), 6x on the slow one tickets
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0
DEFAULT_MIN_HOLD_S = 15.0

ALERT_STATES = ("ok", "pending", "firing", "resolved")
ALERT_HISTORY_LIMIT = 32


# ---------------------------------------------------------------------------
# spec validation + loading
# ---------------------------------------------------------------------------

def validate_slo_spec(spec: dict) -> dict:
    """Normalise/validate ONE SLO spec dict — the single rule site
    shared by ``load_slos`` (file/env), the CLI override path, and the
    evaluator constructor (mirrors ``validate_stream_spec``).

    Canonical fields: ``name`` (dedup slug), ``kind`` (one of
    :data:`SLO_KINDS`), ``key`` (feed for ``stream_lag_s``, lane for
    the queue/job kinds; None = the total series), ``threshold_s``,
    ``objective``, ``fast_window_s``/``slow_window_s``,
    ``fast_burn``/``slow_burn``, ``min_hold_s``."""
    spec = dict(spec or {})
    name = str(spec.get("name") or "").strip()
    if not name or any(c.isspace() for c in name):
        raise ValueError("slo spec needs name=<unique slug, no "
                         "whitespace> (the alert dedup key)")
    kind = spec.get("kind")
    if kind not in SLO_KINDS:
        raise ValueError(f"slo {name}: kind={kind!r} not in "
                         f"{SLO_KINDS}")
    key = spec.get("key")
    key = None if key in (None, "") else str(key)
    if key is not None and ("[" in key or "]" in key):
        raise ValueError(f"slo {name}: key={key!r} may not contain "
                         "brackets (it becomes family[key])")
    try:
        threshold = float(spec.get("threshold_s"))
    except (TypeError, ValueError):
        raise ValueError(f"slo {name}: threshold_s="
                         f"{spec.get('threshold_s')!r} is not a number")
    if not threshold > 0.0:
        raise ValueError(f"slo {name}: threshold_s={threshold} must "
                         "be > 0")
    objective = float(spec.get("objective", DEFAULT_OBJECTIVE))
    if not 0.0 < objective < 1.0:
        raise ValueError(f"slo {name}: objective={objective} must be "
                         "in (0, 1) — it is the good-event fraction")
    fast = float(spec.get("fast_window_s", DEFAULT_FAST_WINDOW_S))
    slow = float(spec.get("slow_window_s", DEFAULT_SLOW_WINDOW_S))
    if not 0.0 < fast <= slow:
        raise ValueError(f"slo {name}: need 0 < fast_window_s "
                         f"({fast}) <= slow_window_s ({slow})")
    fast_burn = float(spec.get("fast_burn", DEFAULT_FAST_BURN))
    slow_burn = float(spec.get("slow_burn", DEFAULT_SLOW_BURN))
    if fast_burn <= 0.0 or slow_burn <= 0.0:
        raise ValueError(f"slo {name}: burn multipliers must be > 0")
    min_hold = float(spec.get("min_hold_s", DEFAULT_MIN_HOLD_S))
    if min_hold < 0.0:
        raise ValueError(f"slo {name}: min_hold_s={min_hold} must be "
                         ">= 0")
    return {"name": name, "kind": kind, "key": key,
            "threshold_s": threshold, "objective": objective,
            "fast_window_s": fast, "slow_window_s": slow,
            "fast_burn": fast_burn, "slow_burn": slow_burn,
            "min_hold_s": min_hold}


def metric_name(spec: dict) -> str:
    """The histogram series an SLO evaluates: the kind itself for the
    total series, ``kind[key]`` for a per-feed/per-lane one."""
    if spec.get("key"):
        return f"{spec['kind']}[{spec['key']}]"
    return spec["kind"]


def slo_path(directory: str) -> str:
    """``<queue dir>/slo.json`` — beside ``queued/`` and
    ``heartbeat/``, so every plane (worker, pool, CLI) reads one
    source of truth."""
    return os.path.join(directory, SLO_FILENAME)


def load_slos(directory: str | None, env: dict | None = None) -> list:
    """Load + validate the SLO registry for a queue dir: ``slo.json``
    first, then ``SCINT_SLOS`` (env JSON, same shape) overriding or
    extending BY NAME.  Returns canonical spec dicts (possibly empty);
    raises ValueError on a malformed file — a typo'd objective should
    fail loud, not silently disarm the plane."""
    specs: dict[str, dict] = {}
    if directory:
        path = slo_path(directory)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                try:
                    payload = json.load(fh)
                except json.JSONDecodeError as e:
                    raise ValueError(f"{path}: invalid JSON ({e})")
            if isinstance(payload, dict):
                payload = payload.get("slos", [])
            if not isinstance(payload, list):
                raise ValueError(f"{path}: expected a list of SLO "
                                 "specs or {'slos': [...]}")
            for raw in payload:
                s = validate_slo_spec(raw)
                specs[s["name"]] = s
    env = os.environ if env is None else env
    raw_env = env.get("SCINT_SLOS")
    if raw_env:
        try:
            payload = json.loads(raw_env)
        except json.JSONDecodeError as e:
            raise ValueError(f"SCINT_SLOS: invalid JSON ({e})")
        if isinstance(payload, dict):
            payload = payload.get("slos", [])
        for raw in payload:
            s = validate_slo_spec(raw)
            specs[s["name"]] = s
    return [specs[n] for n in sorted(specs)]


# ---------------------------------------------------------------------------
# burn-rate math over the closed bucket ladder
# ---------------------------------------------------------------------------

def bad_edge_index(threshold_s: float) -> int:
    """First ladder-bucket index whose LOWER edge is >= the threshold:
    every bucket at or above it holds only values > threshold.  The
    bucket containing the threshold counts as GOOD (the effective
    threshold rounds up to its upper edge) — a fixed per-bucket split,
    so bad counts add under histogram merge."""
    return _bucket_index(threshold_s) + 1


def hist_bad_good(hist_dict: dict | None,
                  threshold_s: float) -> tuple[int, int]:
    """(bad, n) of one sparse heartbeat-wire histogram payload at a
    threshold (``(0, 0)`` for an empty/missing payload)."""
    if not hist_dict:
        return (0, 0)
    h = Hist.from_dict(hist_dict)
    j = bad_edge_index(threshold_s)
    return (sum(h.counts[j:]), h.n)


def burn_rate(bad: int, n: int, objective: float) -> float:
    """``(bad/n) / (1 - objective)`` — 1.0 burns the whole error
    budget over the window; 0.0 when the window holds no events (no
    evidence is not a breach)."""
    if n <= 0:
        return 0.0
    return (bad / n) / max(1.0 - objective, 1e-12)


class SloEvaluator:
    """Per-process multi-window evaluator.

    Feed it the obs registry's cumulative histogram payloads
    (``obs.hists()``) at each heartbeat; it keeps a bounded timeline of
    cumulative (bad, n) per SLO and differences over the fast/slow
    windows.  :meth:`wire` returns the per-worker heartbeat snapshot —
    window DELTAS, which fold by addition across the fleet
    (:func:`merge_slo_snapshots`)."""

    def __init__(self, specs, now: float | None = None):
        self.specs = [validate_slo_spec(s) for s in specs]
        # name -> list of (ts, bad_cum, n_cum), oldest first, trimmed
        # to the slow window (+1 baseline entry past its left edge)
        self._timeline: dict[str, list] = {s["name"]: []
                                           for s in self.specs}

    def observe(self, hists: dict, now: float) -> None:
        """Record one cumulative sample point per SLO from the live
        histogram registry payloads (``{series: hist_dict}``)."""
        for spec in self.specs:
            if spec["kind"] == "heartbeat":
                continue
            bad, n = hist_bad_good(hists.get(metric_name(spec)),
                                   spec["threshold_s"])
            tl = self._timeline[spec["name"]]
            tl.append((float(now), bad, n))
            # trim: keep exactly one point at/left of the slow edge
            edge = float(now) - spec["slow_window_s"]
            while len(tl) >= 2 and tl[1][0] <= edge:
                tl.pop(0)

    def _window(self, spec: dict, window_s: float,
                now: float) -> tuple[int, int]:
        """(bad, n) DELTA over the trailing window: newest cumulative
        minus the newest point at/left of the window edge (zero
        baseline when the whole timeline is inside the window)."""
        tl = self._timeline[spec["name"]]
        if not tl:
            return (0, 0)
        edge = float(now) - window_s
        base_bad = base_n = 0
        for ts, bad, n in tl:
            if ts <= edge:
                base_bad, base_n = bad, n
            else:
                break
        _, bad, n = tl[-1]
        return (max(bad - base_bad, 0), max(n - base_n, 0))

    def statuses(self, now: float) -> list:
        """One status dict per histogram-kind SLO: burn per window,
        budget remaining, and the breach verdict (fast-burn OR
        slow-burn rule)."""
        out = []
        for spec in self.specs:
            if spec["kind"] == "heartbeat":
                continue
            out.append(status_from_counts(
                spec,
                self._window(spec, spec["fast_window_s"], now),
                self._window(spec, spec["slow_window_s"], now)))
        return out

    def wire(self, now: float) -> dict:
        """The heartbeat snapshot: per-SLO per-window (bad, n) deltas
        — pure counts, so the fleet fold is elementwise addition."""
        slos = {}
        for spec in self.specs:
            if spec["kind"] == "heartbeat":
                continue
            fb, fn = self._window(spec, spec["fast_window_s"], now)
            sb, sn = self._window(spec, spec["slow_window_s"], now)
            slos[spec["name"]] = {"fast": [fb, fn], "slow": [sb, sn]}
        return {"v": SLO_VERSION, "ts": float(now), "slos": slos}


def status_from_counts(spec: dict, fast: tuple, slow: tuple) -> dict:
    """Assemble one SLO status row from (bad, n) window counts — the
    shared shape of per-worker and fleet-folded evaluation."""
    fb, fn = fast
    sb, sn = slow
    burn_fast = burn_rate(fb, fn, spec["objective"])
    burn_slow = burn_rate(sb, sn, spec["objective"])
    breach = (burn_fast >= spec["fast_burn"]
              or burn_slow >= spec["slow_burn"])
    return {"slo": spec["name"], "kind": spec["kind"],
            "key": spec["key"], "metric": metric_name(spec),
            "threshold_s": spec["threshold_s"],
            "objective": spec["objective"],
            "windows": {
                "fast": {"window_s": spec["fast_window_s"],
                         "bad": fb, "n": fn,
                         "burn": round(burn_fast, 6),
                         "max_burn": spec["fast_burn"]},
                "slow": {"window_s": spec["slow_window_s"],
                         "bad": sb, "n": sn,
                         "burn": round(burn_slow, 6),
                         "max_burn": spec["slow_burn"]}},
            "min_hold_s": spec["min_hold_s"],
            "budget_remaining": round(
                max(1.0 - burn_slow, 0.0), 6),
            "breach": breach}


def merge_slo_snapshots(snapshots) -> dict | None:
    """Fold per-worker heartbeat SLO snapshots: elementwise-add the
    (bad, n) window deltas per SLO name — associative and commutative
    like the histogram merge they were cut from.  None when nothing
    carried a snapshot."""
    acc: dict[str, dict] = {}
    ts = None
    seen = False
    for snap in snapshots:
        if not snap or not isinstance(snap, dict):
            continue
        seen = True
        ts = max(ts or 0.0, float(snap.get("ts") or 0.0))
        for name, wins in (snap.get("slos") or {}).items():
            slot = acc.setdefault(name, {"fast": [0, 0],
                                         "slow": [0, 0]})
            for w in ("fast", "slow"):
                pair = wins.get(w) or [0, 0]
                slot[w][0] += int(pair[0])
                slot[w][1] += int(pair[1])
    if not seen:
        return None
    return {"v": SLO_VERSION, "ts": ts, "slos": acc}


def fleet_statuses(specs, merged_snapshot: dict | None,
                   heartbeats=(), now: float | None = None) -> list:
    """Fleet-scope SLO statuses: histogram kinds from the folded
    snapshot counts (exactly the single-process math on the summed
    windows), heartbeat-liveness kinds from beat ages — a worker is
    "bad" when its last beat is older than the SLO threshold."""
    import time as _time

    now = _time.time() if now is None else float(now)
    merged = (merged_snapshot or {}).get("slos") or {}
    out = []
    for raw in specs:
        spec = validate_slo_spec(raw)
        if spec["kind"] == "heartbeat":
            ages = [now - float(hb.get("ts", now))
                    for hb in heartbeats if isinstance(hb, dict)]
            bad = sum(1 for a in ages
                      if a > spec["threshold_s"])
            pair = (bad, len(ages))
            out.append(status_from_counts(spec, pair, pair))
            continue
        wins = merged.get(spec["name"]) or {}
        out.append(status_from_counts(
            spec,
            tuple(wins.get("fast") or (0, 0)),
            tuple(wins.get("slow") or (0, 0))))
    return out


# ---------------------------------------------------------------------------
# durable alert state machines
# ---------------------------------------------------------------------------

def alert_key(name: str) -> str:
    """The versioned-row dedup key of one SLO's alert: all processes
    write ``alert.<slo name>`` and newest-wins resolves the race."""
    return f"alert.{name}"


ALERTS_INDEX_META = "alerts"


class AlertEngine:
    """Durable pending → firing → resolved state machines over a
    results store (one versioned newest-wins row per SLO).

    Hysteresis is symmetric: a breach must HOLD ``min_hold_s`` before
    pending escalates to firing, and the all-clear must hold
    ``min_hold_s`` before firing resolves — flapping burn rates sit in
    pending/firing instead of paging on every poll.  Each transition
    appends to the row's bounded history (the ``alerts history`` CLI
    verb) and emits an ``alert.<state>`` obs event."""

    def __init__(self, store):
        self.store = store

    def _row(self, name: str) -> dict:
        row = self.store.get(alert_key(name))
        if row and row.get("kind") == "alert":
            return dict(row)
        return {"kind": "alert", "v": 1, "slo": name,
                "state": "ok", "since_ts": None, "fired_ts": None,
                "resolved_ts": None, "clear_since_ts": None,
                "ack": False, "history": []}

    def step(self, statuses, now: float,
             trace_ids: dict | None = None) -> list:
        """Advance every SLO's machine one tick from its status row;
        persist rows whose state changed (newest-wins, flushed — the
        row survives SIGKILL the moment step returns).  Returns the
        current rows."""
        rows = []
        dirty = False
        firing = 0
        for st in statuses:
            name = st["slo"]
            row = self._row(name)
            prev = row["state"]
            hold = float(st.get("min_hold_s", DEFAULT_MIN_HOLD_S))
            if st["breach"]:
                row["clear_since_ts"] = None
                if row["state"] in ("ok", "resolved"):
                    row["state"] = "pending"
                    row["since_ts"] = float(now)
                elif (row["state"] == "pending"
                        and float(now) - float(
                            now if row["since_ts"] is None
                            else row["since_ts"]) >= hold):
                    row["state"] = "firing"
                    row["fired_ts"] = float(now)
            else:
                if row["state"] == "pending":
                    # a breach that never held min_hold_s clears
                    # straight back to ok — it never paged
                    row["state"] = "ok"
                    row["since_ts"] = None
                elif row["state"] == "firing":
                    if row.get("clear_since_ts") is None:
                        row["clear_since_ts"] = float(now)
                    elif (float(now) - float(row["clear_since_ts"])
                            >= hold):
                        row["state"] = "resolved"
                        row["resolved_ts"] = float(now)
            # live context rides every persisted row
            row["burn_fast"] = st["windows"]["fast"]["burn"]
            row["burn_slow"] = st["windows"]["slow"]["burn"]
            row["budget_remaining"] = st["budget_remaining"]
            row["threshold_s"] = st["threshold_s"]
            row["metric"] = st["metric"]
            row["ts"] = float(now)
            tid = (trace_ids or {}).get(st["metric"])
            if tid:
                row["trace_id"] = tid
            if row["state"] != prev:
                row["history"] = (list(row.get("history") or [])
                                  + [[float(now), row["state"]]])
                del row["history"][:-ALERT_HISTORY_LIMIT]
                if row["state"] == "pending":
                    row["ack"] = False
                core.event(f"alert.{row['state']}", slo=name,
                           metric=st["metric"],
                           burn_fast=row["burn_fast"],
                           burn_slow=row["burn_slow"],
                           trace_id=row.get("trace_id"))
                self._persist(row)
                dirty = True
            elif row["state"] != "ok":
                # refresh live burn context on active alerts
                self._persist(row)
                dirty = True
            if row["state"] == "firing":
                firing += 1
            core.gauge(f"slo_burn_fast[{name}]", row["burn_fast"])
            core.gauge(f"slo_burn_slow[{name}]", row["burn_slow"])
            core.gauge(f"slo_budget_remaining[{name}]",
                       row["budget_remaining"])
            rows.append(row)
        core.gauge("alerts_firing", firing)
        if dirty:
            self.store.flush()
        return rows

    def _persist(self, row: dict) -> None:
        name = row["slo"]
        self.store.put_versioned(alert_key(name), row, series="alerts")
        idx = self.store.get_meta(ALERTS_INDEX_META) or {}
        keys = set(idx.get("slos") or [])
        if name not in keys:
            keys.add(name)
            self.store.put_meta(ALERTS_INDEX_META,
                                {"slos": sorted(keys)})

    def ack(self, name: str, now: float | None = None) -> dict | None:
        """Mark one alert acknowledged (newest-wins row write; a later
        pending transition clears it).  None when no such alert."""
        row = self.store.get(alert_key(name))
        if not row or row.get("kind") != "alert":
            return None
        row = dict(row)
        row["ack"] = True
        row["ack_ts"] = float(time.time() if now is None else now)
        core.event("alert.ack", slo=name)
        self._persist(row)
        self.store.flush()
        return row


def read_alerts(directory: str) -> list:
    """Newest-wins alert rows of a queue dir (worker-written index +
    the declared registry's names), sorted firing-first — the
    ``scintools-tpu alerts`` / ``fleet status`` read path.  Empty list
    when the plane never armed."""
    from ..utils.store import ResultsStore

    results_dir = os.path.join(directory, "results")
    if not os.path.isdir(results_dir):
        return []
    store = ResultsStore(results_dir)
    names = set()
    idx = store.get_meta(ALERTS_INDEX_META) or {}
    names.update(idx.get("slos") or [])
    try:
        names.update(s["name"] for s in load_slos(directory))
    except ValueError:
        pass
    rows = []
    for name in sorted(names):
        row = store.get(alert_key(name))
        if row and row.get("kind") == "alert":
            rows.append(row)
    order = {"firing": 0, "pending": 1, "resolved": 2, "ok": 3}
    rows.sort(key=lambda r: (order.get(r.get("state"), 9),
                             r.get("slo") or ""))
    return rows


# ---------------------------------------------------------------------------
# predicted breach (the autoscaler's leading signal)
# ---------------------------------------------------------------------------

def linear_trend(points) -> tuple[float, float] | None:
    """Least-squares (value_now, slope_per_s) over ``(ts, value)``
    points — the PoolController's breach predictor input.  None with
    fewer than two distinct timestamps."""
    pts = [(float(t), float(v)) for t, v in points
           if v is not None]
    if len(pts) < 2:
        return None
    n = len(pts)
    mt = sum(t for t, _ in pts) / n
    mv = sum(v for _, v in pts) / n
    den = sum((t - mt) ** 2 for t, _ in pts)
    if den <= 0.0:
        return None
    slope = sum((t - mt) * (v - mv) for t, v in pts) / den
    return (pts[-1][1], slope)


def predict_value(points, horizon_s: float) -> float | None:
    """The trend's value ``horizon_s`` from the newest point (never
    below the newest observation when the trend still rises — the
    predictor leads, it does not discount a live breach)."""
    got = linear_trend(points)
    if got is None:
        return None
    value, slope = got
    return value + max(slope, 0.0) * float(horizon_s)
