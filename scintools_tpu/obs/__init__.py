"""scintools_tpu.obs — pipeline-wide tracing & metrics.

Spans (nested, monotonic-clock, thread-safe), counters/gauges
(``epochs_processed``, ``bytes_h2d``, ``jit_cache_miss``, ...),
JAX-aware compile-vs-execute accounting with block-until-ready fencing,
and pluggable sinks (key=value logger, JSONL trace file, in-process
registry with a per-stage ``summary()``).

Usage::

    from scintools_tpu import obs

    obs.enable(jsonl="trace.jsonl")        # or: with obs.tracing(...):
    with obs.span("my.stage", epochs=8):
        ...
    obs.inc("epochs_processed", 8)
    print(obs.render_summary())
    obs.disable()

Disabled (the default), every hook is a single flag check — see
docs/observability.md for the span taxonomy and the trace CLI.
"""

from . import devmem  # noqa: F401  (the device-memory plane)
from .core import (Registry, counters, disable,  # noqa: F401
                   dump_flight, enable, enabled, event, flush, gauge,
                   get_registry, hist_summaries, inc, observe,
                   render_summary, reset, span, summary, traced, tracing)
from .fleet import (HeartbeatWriter, assemble_traces,  # noqa: F401
                    attach_slo_status, backpressure, fleet_report,
                    fleet_rollup, heartbeat_stale, merge_heartbeats,
                    new_trace_id, read_heartbeats, render_fleet)
from .hist import Hist, merge_hist_dicts  # noqa: F401
from .jax_helpers import (bytes_of, fence,  # noqa: F401
                          instrument_jit, xla_cost_analysis)
from .report import (aggregate, catalog_section,  # noqa: F401
                     compile_profile, compile_split, devmem_section,
                     filter_events, load_events, load_trace_files,
                     measured_roofline, parse_duration, parse_when,
                     reliability_section, render, report, report_many,
                     serve_section, slo_section)
from .slo import (AlertEngine, SloEvaluator, alert_key,  # noqa: F401
                  fleet_statuses, linear_trend, load_slos,
                  merge_slo_snapshots, metric_name, predict_value,
                  read_alerts, slo_path, validate_slo_spec)
from .sinks import JsonlSink, LogSink  # noqa: F401
