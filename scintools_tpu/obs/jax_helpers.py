"""JAX-aware tracing helpers: honest device timing and compile/execute
split.

jit dispatch is asynchronous — a host span around an un-fenced jit call
measures dispatch latency, not device work — and a jit entry point's
first call bundles trace+compile with execution.  Two helpers fix both:

* :func:`fence` — block_until_ready when tracing is enabled (identity
  otherwise, and a transparent pass-through for tracers / non-arrays),
  so span-closed == work-done.
* :func:`instrument_jit` — wraps a jit'd callable so each distinct input
  signature records a ``<name>.compile`` span (``fn.lower().compile()``
  — trace+compile only, no execution) and every call records a
  ``<name>.execute`` span fenced on completion, plus a
  ``jit_cache_miss`` counter per fresh signature and per-signature
  ``step_flops[...]`` / ``step_bytes[...]`` gauges read from the
  executable's own XLA cost analysis (the measured-roofline source
  consumed by ``trace report`` and bench.py).  Disabled tracing
  short-circuits to the raw callable: identical dispatch path, identical
  results (the AOT executable and the jit cache compile the same
  program, asserted bit-identical by tests/test_obs.py).
"""

from __future__ import annotations

from . import core, devmem

# wrapper memo keyed by id(fn); the wrapper closes over fn (strong ref),
# so the id cannot be recycled while the entry lives.  Steps from
# parallel.make_pipeline are themselves lru_cached, so repeated
# run_pipeline calls reuse one wrapper (and its compiled-executable
# cache) per step.
_WRAPPERS: dict = {}


def fence(value):
    """block_until_ready(value) when tracing is enabled; returns value.

    Safe on pytrees, numpy arrays, and jax tracers (no-op for anything
    that cannot block).
    """
    if not core.enabled():
        return value
    try:
        import jax

        return jax.block_until_ready(value)
    except Exception:
        return value


def bytes_of(tree) -> int:
    """Total nbytes over a pytree's array leaves (host or device) — the
    unit of the ``bytes_h2d`` transfer counter."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = [tree]
    return int(sum(getattr(x, "nbytes", 0) for x in leaves))


def xla_cost_analysis(compiled) -> dict | None:
    """{'flops': F, 'bytes_accessed': B} from an XLA executable's own
    cost analysis, or None when the backend doesn't report one.

    These are XLA's MEASURED per-execution counts for the exact compiled
    program — the numbers the roofline accounting should trust over the
    analytic model (utils/roofline.py), whose byte counts are a
    deliberate lower bound.  Handles both cost_analysis() return shapes
    (a dict on current jax, a one-element list of dicts on older)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    flops = ca.get("flops")
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops"] = float(flops)
    byts = ca.get("bytes accessed")
    if isinstance(byts, (int, float)) and byts > 0:
        out["bytes_accessed"] = float(byts)
    return out or None


def _sig_label(key) -> str:
    """Compact 'BxNFxNT:dtype' label of a call signature's first array
    leaf — the per-signature key of the step_flops/step_bytes gauges."""
    for item in key:
        if (isinstance(item, tuple) and len(item) == 2
                and isinstance(item[0], tuple)):
            shape, dtype = item
            return "x".join(str(int(s)) for s in shape) + f":{dtype}"
    return "scalar"


def _record_cost_analysis(name: str, key, compiled, memo: dict) -> None:
    """Publish per-signature measured cost gauges: ``step_flops[<name>:
    <shape>:<dtype>]`` / ``step_bytes[...]`` — one pair per compiled
    signature, consumed by ``trace report``'s measured-roofline section
    and by tests.  Gauges (not counters): the cost is a property of the
    program, not an accumulating total.

    ``memo`` caches the extracted costs per signature so the EXECUTE
    path can re-emit them on every traced call: a trace enabled after
    the (memoised, lru-cached) step was first compiled — the normal
    warm-process case — must still carry the costs of the programs it
    actually ran."""
    costs = memo.get(key)
    if costs is None:
        costs = memo[key] = xla_cost_analysis(compiled) or {}
    if not costs:
        return
    label = f"{name}:{_sig_label(key)}"
    if "flops" in costs:
        core.gauge(f"step_flops[{label}]", costs["flops"])
    if "bytes_accessed" in costs:
        core.gauge(f"step_bytes[{label}]", costs["bytes_accessed"])


def _signature(args, kwargs):
    """Shape/dtype signature of a call — the jit-cache key proxy."""
    try:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = [str(treedef)]
    except Exception:
        leaves, sig = list(args) + sorted(kwargs.items()), []
    for x in leaves:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sig.append((tuple(x.shape), str(x.dtype)))
        else:
            sig.append(repr(x))
    return tuple(sig)


def instrument_jit(fn, name: str, aot: bool = False):
    """Wrap jit'd ``fn`` with compile/execute span accounting.

    Cheap when tracing is disabled (one flag check, then the raw
    callable).  Memoised on ``fn`` so the compiled-executable cache
    survives across calls; wrapping the same function twice returns the
    same wrapper (first name wins).

    ``aot=True`` marks ``fn`` as a step served from the persistent
    compile cache (scintools_tpu.compile_cache): a fresh signature is a
    WARM start, so it records a ``<name>.compile.warm`` span instead of
    ``<name>.compile`` and does NOT count a ``jit_cache_miss`` — the
    warmup-then-run contract is ``jit_cache_miss == 0``, and ``trace
    report`` decomposes cold vs warm compile time from the two span
    names.
    """
    cached = _WRAPPERS.get(id(fn))
    if cached is not None and cached.__wrapped__ is fn:
        return cached

    compiled_cache: dict = {}
    cost_memo: dict = {}
    compile_span = name + (".compile.warm" if aot else ".compile")
    compile_mode = "warm" if aot else "cold"

    def _profile_compile(key, sp, mode: str | None = None) -> None:
        # per-stage compile attribution: which jit'd stage/signature
        # the remaining compile wall time belongs to, as an
        # accumulating counter `compile_ms[<stage>:<sig>:<cold|warm>]`
        # (trace report's compile-profile section; span tables only
        # aggregate by name, which loses the signature)
        ms = getattr(sp, "dur_ms", None)
        if ms is not None:
            core.inc(f"compile_ms[{name}:{_sig_label(key)}"
                     f":{mode or compile_mode}]", round(ms, 3))

    def traced_call(*args, **kwargs):
        import jax

        key = _signature(args, kwargs)
        compiled = compiled_cache.get(key)
        if compiled is None:
            if not aot:
                core.inc("jit_cache_miss")
                # per-unit attribution (ISSUE 14): the split pipeline's
                # acceptance contract is jit_cache_miss[pipeline.back]
                # == 0 on a warmed process hitting a novel shape — the
                # aggregate counter cannot say WHICH unit missed
                core.inc(f"jit_cache_miss[{name}]")
            compiled = _compile(key, *args, **kwargs)
        if compiled is fn:
            # no AOT path: the first (compiling) call was already timed
            # and executed inside _compile; later calls land here
            win = devmem.begin_window()
            with core.span(name + ".execute"):
                out = fn(*args, **kwargs)
                jax.block_until_ready(out)
            devmem.end_window(win, f"{name}:{_sig_label(key)}")
            return out
        if isinstance(compiled, tuple):  # first call's output rides along
            compiled_cache[key] = compiled[0] if compiled[0] is not None \
                else fn
            return compiled[1]
        try:
            # re-emit the signature's measured cost gauges per traced
            # call: tracing may have been enabled AFTER the warm step
            # compiled (memoised steps outlive any one trace window)
            _record_cost_analysis(name, key, compiled, cost_memo)
            # device-memory window (obs/devmem): the execute region is
            # fenced, so the window's peak HBM attributes to exactly
            # this signature — the measured footprint beside the
            # step_bytes model (no-op on backends without memory_stats)
            win = devmem.begin_window()
            with core.span(name + ".execute"):
                out = compiled(*args, **kwargs)
                jax.block_until_ready(out)
            devmem.end_window(win, f"{name}:{_sig_label(key)}")
            return out
        except Exception:
            # AOT executables can be stricter about input placement than
            # jit; fall back rather than fail the pipeline, and remember
            # the fallback so later calls do not re-pay the failed
            # dispatch.  The failed .execute span records with an error
            # attr; the fallback pays jit's FULL trace+compile, so it
            # records under the COLD span/profile even on an aot
            # wrapper — a fleet whose artifacts fail to load must show
            # up as cold-compile regression, not as "warm" time.
            compiled_cache[key] = fn
            with core.span(name + ".compile", signature=str(key)[:200],
                           includes_first_execute=True,
                           aot_fallback=aot) as sp:
                out = fn(*args, **kwargs)
                jax.block_until_ready(out)
            _profile_compile(key, sp, mode="cold")
            return out

    def _compile(key, *args, **kwargs):
        import jax

        lower = getattr(fn, "lower", None)
        if lower is not None:
            try:
                with core.span(compile_span,
                               signature=str(key)[:200]) as sp:
                    executable = lower(*args, **kwargs).compile()
                _profile_compile(key, sp)
                compiled_cache[key] = executable
                # measured roofline source: XLA's own per-execution
                # flop/byte counts for this exact signature
                _record_cost_analysis(name, key, executable, cost_memo)
                return executable
            except Exception:
                pass
        # fallback (non-jit callable / lowering unsupported): the first
        # call IS trace+compile+execute; record it as compile so the
        # steady-state .execute rows stay uncontaminated
        with core.span(compile_span, signature=str(key)[:200],
                       includes_first_execute=True) as sp:
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        _profile_compile(key, sp)
        return (None, out)

    def wrapper(*args, **kwargs):
        if not core.enabled():
            return fn(*args, **kwargs)
        return traced_call(*args, **kwargs)

    wrapper.__wrapped__ = fn
    wrapper.__name__ = getattr(fn, "__name__", name)
    _WRAPPERS[id(fn)] = wrapper
    return wrapper
