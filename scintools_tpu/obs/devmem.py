"""Device-memory observability plane (ISSUE 12 tentpole): HBM gauges,
per-signature peak attribution, and the predicted-footprint source the
driver's predictive chunk admission consults.

The r05 flight proved the chip path fast (405.9x reference) but blind
to the resource that bounds it: HBM.  The reactive OOM backoff (PR 5)
throws away a chunk's work AFTER ``RESOURCE_EXHAUSTED``; the roofline
gauges (PR 4) carry XLA's byte *model*, never measured residency.
This module closes both gaps from one sampling surface:

* **Gauges** — :func:`sample` reads ``device.memory_stats()`` over the
  local devices and publishes ``hbm_bytes_in_use`` /
  ``hbm_bytes_limit`` (summed across devices; a mesh-sharded step's
  residency is divided over them, so totals compare against totals).
  ``stream=True`` additionally stamps a timestamped gauge event — the
  headroom timeline ``trace report``'s memory section renders.
* **Per-signature peaks** — ``obs.instrument_jit`` opens a
  :func:`begin_window` / :func:`end_window` pair around every fenced
  ``.execute`` region, attributing the window's peak HBM to the
  compiled signature as a ``step_hbm_peak[<stage>:<sig>]`` gauge —
  a MEASURED footprint next to the modeled ``step_bytes[...]``.

  **Fencing caveat** (documented in docs/observability.md): PJRT
  exposes no peak-counter reset on current jax, so a window is only
  exactly attributable when it RAISES the process high-water mark
  (then the new peak is the window's own — the execute region is
  fenced, so no other dispatch overlaps it).  A window that stays
  under an earlier signature's peak records the fenced in-use bytes
  as a LOWER-BOUND estimate instead (never overwriting an exact
  record).  Where a backend does expose a reset hook, every window
  is exact.
* **Prediction** — :func:`predicted_peak` answers "what will this
  signature cost?" for the driver's admission check: a recorded peak
  for the exact signature, a recorded peak at another batch size
  scaled linearly in the batch, the ``step_bytes[...]`` cost-analysis
  model, in that order of trust.

**Degradation contract**: a backend whose ``memory_stats()`` returns
None (CPU) disables the whole plane — the first probe memoises the
negative, every later call is one flag check, and pipeline output is
bit-identical with the plane on or off (tests/test_devmem.py).  All
hooks are additionally gated on ``obs.enabled()``: untraced runs never
pay a stats read per step.
"""

from __future__ import annotations

import threading

from . import core

# availability memo: None = unprobed, False = backend reports no
# memory stats (CPU) — the permanent no-op fast path, True = live.
# reset() clears it (tests swap the provider mid-process).
_AVAILABLE: bool | None = None
# whether the backend exposes a peak-counter reset (probed once);
# current jax/PJRT does not — the estimate path below is the norm
_RESET_SUPPORTED: bool | None = None
# test seam: a callable returning True after resetting every device's
# peak counter (real backends lack one; fakes install it here)
_RESET_HOOK = None

_LOCK = threading.Lock()
# label -> best known window peak (bytes); labels in _ESTIMATED carry
# the lower-bound caveat (no reset + window under the high-water mark)
_PEAKS: dict[str, float] = {}
_ESTIMATED: set[str] = set()
# label -> the window's INCREMENTAL cost (peak minus the pre-window
# in-use bytes) for EXACT windows only — the quantity that scales
# linearly in the batch.  Scaling the absolute peak would multiply
# the ambient residency along with it and over-predict.
_DELTAS: dict[str, float] = {}


def _device_stats() -> list[dict] | None:
    """Raw per-device ``memory_stats()`` dicts, or None when the
    backend does not report them (CPU returns None; a backend without
    jax at all degrades the same way).  The test seam: fakes
    monkeypatch this function."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:  # fault-ok: capability probe (no backend => no plane)
        return None
    out = []
    for d in devs:
        try:
            st = d.memory_stats()
        except Exception:  # fault-ok: capability probe per device
            st = None
        if not isinstance(st, dict) or "bytes_in_use" not in st:
            return None
        out.append(st)
    return out or None


def snapshot() -> dict | None:
    """One aggregated reading over the local devices:
    ``{bytes_in_use, peak_bytes_in_use, bytes_limit, n_devices}``
    (sums — a sharded step divides its residency over the devices), or
    None when the backend reports nothing.  Updates the availability
    memo either way."""
    global _AVAILABLE
    if _AVAILABLE is False:
        return None
    stats = _device_stats()
    if stats is None:
        _AVAILABLE = False
        return None
    _AVAILABLE = True
    agg = {"bytes_in_use": 0, "peak_bytes_in_use": 0, "bytes_limit": 0,
           "n_devices": len(stats)}
    for st in stats:
        in_use = int(st.get("bytes_in_use", 0))
        agg["bytes_in_use"] += in_use
        agg["peak_bytes_in_use"] += int(st.get("peak_bytes_in_use",
                                               in_use))
        agg["bytes_limit"] += int(st.get("bytes_limit", 0))
    return agg


def available() -> bool:
    """Whether the backend exposes memory stats (memoised probe)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        snapshot()
    return bool(_AVAILABLE)


def headroom() -> float | None:
    """``bytes_limit - bytes_in_use`` summed over local devices — the
    admission signal — or None when the plane is degraded (CPU)."""
    snap = snapshot()
    if snap is None or not snap["bytes_limit"]:
        return None
    return float(snap["bytes_limit"] - snap["bytes_in_use"])


def sample(stream: bool = False) -> dict | None:
    """Publish the HBM gauges from one snapshot (no-op when the plane
    or tracing is off).  ``stream=True`` stamps ``hbm_bytes_in_use``
    as a timestamped gauge event too — the headroom-timeline points
    ``trace report``'s memory section renders."""
    if not core.enabled():
        return None
    snap = snapshot()
    if snap is None:
        return None
    core.gauge("hbm_bytes_in_use", snap["bytes_in_use"], stream=stream)
    core.gauge("hbm_bytes_limit", snap["bytes_limit"])
    return snap


def _reset_peak() -> bool:
    """Best-effort per-device peak-counter reset; returns whether one
    happened.  Current jax/PJRT devices expose none (the probe
    memoises the negative), but the seam keeps the EXACT attribution
    path testable and ready for runtimes that grow one."""
    global _RESET_SUPPORTED
    if _RESET_SUPPORTED is False:
        return False
    if _RESET_HOOK is not None:
        try:
            ok = bool(_RESET_HOOK())
        except Exception:  # fault-ok: capability probe
            ok = False
        _RESET_SUPPORTED = ok
        return ok
    try:
        import jax

        ok = False
        for d in jax.local_devices():
            for attr in ("reset_peak_bytes_in_use", "reset_memory_stats"):
                fn = getattr(d, attr, None)
                if fn is not None:
                    fn()
                    ok = True
                    break
    except Exception:  # fault-ok: capability probe
        ok = False
    _RESET_SUPPORTED = ok
    return ok


def begin_window():
    """Open a peak-attribution window around a fenced execute region
    (called by ``obs.instrument_jit``).  Returns opaque state for
    :func:`end_window`, or None when the plane is inactive (degraded
    backend, or tracing disabled) — the no-op fast path is one flag
    compare plus one ``core.enabled()`` check."""
    if _AVAILABLE is False or not core.enabled():
        return None
    pre = snapshot()
    if pre is None:
        return None
    return (pre, _reset_peak())


def end_window(win, label: str) -> float | None:
    """Close a window and attribute its peak HBM to ``label``
    (``<stage>:<B>x<nf>x<nt>:<dtype>`` — the instrument_jit signature
    label).  Publishes the signature's best-known peak as the
    ``step_hbm_peak[<label>]`` gauge and streams one HBM gauge sample
    (a headroom-timeline point per step).  Returns the window's peak
    bytes, or None when inactive."""
    if win is None:
        return None
    pre, did_reset = win
    post = snapshot()
    if post is None:
        return None
    if did_reset:
        peak, estimated = post["peak_bytes_in_use"], False
    elif post["peak_bytes_in_use"] > pre["peak_bytes_in_use"]:
        # the fenced window raised the process high-water mark, so the
        # new peak is the window's own measurement
        peak, estimated = post["peak_bytes_in_use"], False
    else:
        # fencing caveat: no reset and the window stayed under an older
        # peak — the true window peak is unknowable, record the fenced
        # residency as a lower bound
        peak = max(post["bytes_in_use"], pre["bytes_in_use"])
        estimated = True
    with _LOCK:
        prev = _PEAKS.get(label)
        prev_est = label in _ESTIMATED
        if (prev is None or (prev_est and not estimated)
                or (estimated == prev_est and peak > prev)):
            _PEAKS[label] = float(peak)
            if estimated:
                _ESTIMATED.add(label)
            else:
                _ESTIMATED.discard(label)
        if not estimated:
            delta = max(float(peak) - float(pre["bytes_in_use"]), 0.0)
            _DELTAS[label] = max(_DELTAS.get(label, 0.0), delta)
        best = _PEAKS[label]
    core.gauge(f"step_hbm_peak[{label}]", best)
    # publish the HBM gauges from the post reading already in hand (a
    # third memory_stats sweep per step would be pure overhead); the
    # streamed in-use stamp is the headroom-timeline point
    if core.enabled():
        core.gauge("hbm_bytes_in_use", post["bytes_in_use"],
                   stream=True)
        core.gauge("hbm_bytes_limit", post["bytes_limit"])
    return float(peak)


def recorded_peaks() -> dict:
    """``{label: {"bytes": peak, "estimated": bool}}`` — the
    per-signature measured footprints (heartbeats ship this; the
    admission check and ``trace report`` read the gauges)."""
    with _LOCK:
        return {label: {"bytes": v, "estimated": label in _ESTIMATED}
                for label, v in _PEAKS.items()}


def _parse_label(label: str):
    """``(stage, batch, grid)`` from ``<stage>:<B>x<dims...>:<dtype>``
    or None for labels that do not follow the signature form."""
    parts = label.split(":")
    if len(parts) < 2:
        return None
    dims = parts[1].split("x")
    if not dims or not all(d.isdigit() for d in dims):
        return None
    return parts[0], int(dims[0]), tuple(int(d) for d in dims[1:])


# sources whose values are ABSOLUTE residency totals (they were read
# as summed bytes_in_use, ambient allocations included) — the
# admission check compares these against bytes_limit; every other
# source is INCREMENTAL (bytes the chunk itself adds) and compares
# against headroom.  Mixing the units double-counts what is already
# resident and forces spurious step-downs.  "measured-scaled" is
# INCREMENTAL by construction: it scales the recorded window DELTA
# (peak minus pre-window in-use), never the absolute peak — scaling
# an absolute total would multiply the ambient residency with it.
ABSOLUTE_PEAK_SOURCES = frozenset({"measured", "estimated-floor"})


def predicted_peak(stage: str, batch: int, grid,
                   gauges: dict | None = None):
    """Predicted peak HBM bytes for the signature
    ``<stage>:<batch>x<grid...>:*`` and the source of the prediction,
    or None when nothing is known.  Trust order:

    1. ``("measured", ...)`` — an EXACT recorded window peak for the
       stage/batch/grid (any dtype; an ABSOLUTE residency total);
    2. ``("measured-scaled", ...)`` — the exact window's INCREMENTAL
       delta (peak − pre-window in-use) for the same stage+grid at
       another batch size, scaled linearly in the batch (the batch
       axis is the only one that varies on the ladder; the ambient
       residency must NOT scale with it);
    3. ``("model", ...)`` / ``("model-scaled", ...)`` — the
       ``step_bytes[...]`` XLA cost-analysis gauge, same two ways
       (bytes *accessed*, an upper-ish proxy for residency);
    4. ``("estimated-floor", ...)`` — a LOWER-BOUND window estimate
       (the fencing caveat).  Last on purpose: an under-estimate
       admitted as "measured" would shadow a possibly-accurate model
       and launch a chunk straight into the reactive OOM path.

    Sources in :data:`ABSOLUTE_PEAK_SOURCES` are residency totals
    (compare vs ``bytes_limit``); the rest are incremental (compare vs
    headroom).  ``gauges`` defaults to the live registry's (the driver
    passes nothing); injectable for tests and offline analysis."""
    grid = tuple(int(g) for g in grid)

    def match(records):
        exact, scaled = None, None
        for label, value in records:
            parsed = _parse_label(label)
            if parsed is None:
                continue
            lstage, lbatch, lgrid = parsed
            if lstage != stage or lgrid != grid:
                continue
            if lbatch == batch:
                exact = max(exact or 0.0, float(value))
            elif lbatch > 0:
                est = float(value) * batch / lbatch
                scaled = max(scaled or 0.0, est)
        return exact, scaled

    with _LOCK:
        exact_recs = [(la, v) for la, v in _PEAKS.items()
                      if la not in _ESTIMATED]
        delta_recs = list(_DELTAS.items())
        floor_recs = [(la, v) for la, v in _PEAKS.items()
                      if la in _ESTIMATED]
    exact, _ = match(exact_recs)
    if exact is not None:
        return exact, "measured"
    _, scaled = match(delta_recs)
    if scaled is not None:
        return scaled, "measured-scaled"
    from .report import bracketed_values

    if gauges is None:
        gauges = core.get_registry().gauges()
    exact, scaled = match(bracketed_values(gauges,
                                           "step_bytes[").items())
    if exact is not None:
        return exact, "model"
    if scaled is not None:
        return scaled, "model-scaled"
    exact, scaled = match(floor_recs)
    if exact is not None:
        return exact, "estimated-floor"
    if scaled is not None:
        return scaled, "estimated-floor"
    return None


def memory_profile_dump(directory: str, tag: str = "") -> str | None:
    """Write ``jax.profiler.device_memory_profile()`` (a gzipped pprof
    protobuf of live device buffers) to
    ``<directory>/memprof_<pid>[_<tag>].pb`` — the on-OOM snapshot the
    crash flight recorder attaches (docs/observability.md).  Returns
    the path, or None when the profiler (or jax) is unavailable; never
    raises — a diagnostics dump must not replace the error it
    explains."""
    import os

    try:
        import jax

        with core.span("devmem.memory_profile"):
            blob = jax.profiler.device_memory_profile()
        os.makedirs(directory, exist_ok=True)
        name = f"memprof_{os.getpid()}{('_' + tag) if tag else ''}.pb"
        path = os.path.join(directory, name)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
        return path
    except Exception:  # fault-ok: diagnostics only, caller logs None
        return None


def reset() -> None:
    """Clear every memo and record (tests swap providers between
    cases; a long-lived process never needs this)."""
    global _AVAILABLE, _RESET_SUPPORTED
    with _LOCK:
        _PEAKS.clear()
        _ESTIMATED.clear()
        _DELTAS.clear()
    _AVAILABLE = None
    _RESET_SUPPORTED = None
