"""Trace readout: JSONL -> per-stage table (count/total/p50/p95).

One renderer serves both the in-process ``obs.render_summary()`` and the
``scintools-tpu trace report out.jsonl`` CLI, so a live run and its
persisted trace read identically.
"""

from __future__ import annotations

import json

from .core import summarize_durations


def load_events(path: str) -> list:
    """Parse a JSONL trace, skipping non-JSON noise lines (a trace file
    may interleave with logger output when both target one stream)."""
    events = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                events.append(ev)
    return events


def aggregate(events: list) -> tuple:
    """(spans, counters, gauges): spans is {name: {count, total_ms,
    mean_ms, p50_ms, p95_ms}} keyed in first-appearance order; counters
    sum across events (a multi-run trace file accumulates); gauges keep
    the last value."""
    durs: dict[str, list] = {}
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    for ev in events:
        kind = ev.get("kind", "span")
        name = ev.get("name")
        if name is None:
            continue
        if kind == "span" and isinstance(ev.get("dur_ms"), (int, float)):
            durs.setdefault(name, []).append(float(ev["dur_ms"]))
        elif kind == "counter" and isinstance(ev.get("value"),
                                              (int, float)):
            counters[name] = counters.get(name, 0) + ev["value"]
        elif kind == "gauge" and "value" in ev:
            gauges[name] = ev["value"]
    spans = {name: summarize_durations(d) for name, d in durs.items()}
    return spans, counters, gauges


def render(spans: dict, counters: dict | None = None,
           gauges: dict | None = None) -> str:
    """Fixed-width per-stage table, longest-total first, then counters."""
    lines = []
    if spans:
        w = max(len("stage"), max(len(n) for n in spans))
        lines.append(f"{'stage':<{w}}  {'count':>7}  {'total_ms':>12}  "
                     f"{'mean_ms':>10}  {'p50_ms':>10}  {'p95_ms':>10}")
        lines.append("-" * (w + 58))
        order = sorted(spans, key=lambda n: spans[n]["total_ms"],
                       reverse=True)
        for name in order:
            s = spans[name]
            lines.append(
                f"{name:<{w}}  {s['count']:>7d}  {s['total_ms']:>12.3f}  "
                f"{s['mean_ms']:>10.3f}  {s['p50_ms']:>10.3f}  "
                f"{s['p95_ms']:>10.3f}")
    else:
        lines.append("(no spans)")
    if counters:
        lines.append("")
        lines.append("counters:")
        for name in sorted(counters):
            v = counters[name]
            v = int(v) if float(v).is_integer() else v
            lines.append(f"  {name} = {v}")
    if gauges:
        lines.append("")
        lines.append("gauges:")
        for name in sorted(gauges):
            lines.append(f"  {name} = {gauges[name]}")
    return "\n".join(lines)


def report(path: str) -> str:
    """The ``trace report`` payload for one JSONL trace file."""
    spans, counters, gauges = aggregate(load_events(path))
    return render(spans, counters, gauges)
