"""Trace readout: JSONL -> per-stage table (count/total/p50/p95).

One renderer serves both the in-process ``obs.render_summary()`` and the
``scintools-tpu trace report out.jsonl`` CLI, so a live run and its
persisted trace read identically.
"""

from __future__ import annotations

import json

from .core import summarize_durations


def load_events(path: str, skipped: list | None = None) -> list:
    """Parse a JSONL trace, skipping non-JSON noise lines (a trace file
    may interleave with logger output when both target one stream, and
    a SIGKILLed writer leaves a torn final line).  ``skipped``, when
    given, collects one ``(line_number, snippet)`` per dropped line so
    callers can warn instead of silently under-reporting."""
    events = []
    with open(path, encoding="utf-8", errors="replace") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                if skipped is not None:
                    skipped.append((lineno, line[:60]))
                continue
            if isinstance(ev, dict):
                events.append(ev)
            elif skipped is not None:
                skipped.append((lineno, line[:60]))
    return events


def load_trace_files(paths) -> tuple[list, list]:
    """Merge events from MANY trace files — each entry may be a literal
    path or a glob pattern (``trace report`` accepts both; the fleet
    rollup feeds a directory's worth).  Degrades gracefully: an
    unreadable file or a torn/truncated line becomes a warning string,
    never an exception mid-report.  Returns ``(events, warnings)``."""
    import glob as glob_mod

    expanded: list[str] = []
    warnings: list[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p)) if glob_mod.has_magic(p) else [p]
        if not hits:
            warnings.append(f"{p}: no files match")
        expanded.extend(hits)
    events: list = []
    for path in expanded:
        skipped: list = []
        try:
            events.extend(load_events(path, skipped=skipped))
        except OSError as e:
            warnings.append(f"{path}: unreadable ({e}); skipped")
            continue
        if skipped:
            warnings.append(
                f"{path}: skipped {len(skipped)} torn/non-JSON line(s) "
                f"(first at line {skipped[0][0]}: {skipped[0][1]!r})")
    return events, warnings


def parse_when(value) -> float:
    """``--since`` argument: unix seconds, or an ISO date/datetime
    (``2026-08-04`` / ``2026-08-04T12:30:00``), as a unix timestamp."""
    try:
        return float(value)
    except (TypeError, ValueError):
        pass
    import datetime as dt

    try:
        return dt.datetime.fromisoformat(str(value)).timestamp()
    except ValueError:
        raise ValueError(f"--since: {value!r} is neither a unix "
                         "timestamp nor an ISO date/datetime")


def parse_duration(value) -> float:
    """``--last`` argument: seconds, with an optional ``s``/``m``/
    ``h``/``d`` suffix (``90``, ``15m``, ``2h``, ``1d``)."""
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    v = str(value).strip()
    mult = 1.0
    if v and v[-1].lower() in units:
        mult = units[v[-1].lower()]
        v = v[:-1]
    try:
        out = float(v) * mult
    except ValueError:
        raise ValueError(f"--last: {value!r} is not a duration "
                         "(N[s|m|h|d])")
    if out <= 0:
        raise ValueError(f"--last: {value!r} must be positive")
    return out


def filter_events(events, since: float | None = None,
                  last: float | None = None) -> list:
    """Event-time filter for multi-day merged JSONL (ISSUE 12
    satellite — every record already rides a ``ts`` stamp): keep
    records stamped at/after ``since`` (unix seconds) and/or within
    the trailing ``last`` seconds of the NEWEST stamped record (event
    time, not wall clock — a report over yesterday's trace still has
    a meaningful ``--last 1h``).  Records without a stamp are dropped
    while a filter is active: they are unplaceable in time."""
    if since is None and last is None:
        return events
    stamped = [ev for ev in events
               if isinstance(ev.get("ts"), (int, float))]
    cut = float(since) if since is not None else float("-inf")
    if last is not None and stamped:
        newest = max(ev["ts"] for ev in stamped)
        cut = max(cut, newest - float(last))
    return [ev for ev in stamped if ev["ts"] >= cut]


def gauge_timeline(events, name: str, limit: int = 12,
                   streamed_only: bool = False) -> list:
    """(ts, value) points of a gauge's timestamped events, evenly
    down-sampled to ``limit`` points for rendering — the ONE resampler
    behind the queue-depth and HBM-in-use timelines.
    ``streamed_only`` keeps only transition-stamped events (they carry
    the writer ``pid``; flush-time latest-value gauges do not)."""
    pts = [(ev.get("ts", 0.0), ev.get("value")) for ev in events
           if ev.get("kind") == "gauge" and ev.get("name") == name
           and (not streamed_only or "pid" in ev)
           and isinstance(ev.get("value"), (int, float))]
    pts.sort(key=lambda p: p[0])
    if len(pts) <= limit:
        return pts
    step = (len(pts) - 1) / (limit - 1)
    return [pts[round(i * step)] for i in range(limit)]


def aggregate(events: list) -> tuple:
    """(spans, counters, gauges): spans is {name: {count, total_ms,
    mean_ms, p50_ms, p95_ms}} keyed in first-appearance order; counters
    sum across events (a multi-run trace file accumulates); gauges keep
    the last value."""
    durs: dict[str, list] = {}
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    for ev in events:
        kind = ev.get("kind", "span")
        name = ev.get("name")
        if name is None:
            continue
        if kind == "span" and isinstance(ev.get("dur_ms"), (int, float)):
            durs.setdefault(name, []).append(float(ev["dur_ms"]))
        elif kind == "counter" and isinstance(ev.get("value"),
                                              (int, float)):
            counters[name] = counters.get(name, 0) + ev["value"]
        elif kind == "gauge" and "value" in ev:
            gauges[name] = ev["value"]
    spans = {name: summarize_durations(d) for name, d in durs.items()}
    return spans, counters, gauges


def compile_split(spans: dict, counters: dict | None = None) -> dict | None:
    """Cold/warm compile decomposition of a span table: cold compiles
    (``*.compile`` — full trace+XLA), warm compiles (``*.compile.warm``
    — AOT-deserialized steps whose XLA work is served by the persistent
    cache), and execute time, plus the compile-cache counters.  None
    when the trace has no compile spans at all."""
    counters = counters or {}

    def total(pred):
        rows = [s for n, s in spans.items() if pred(n)]
        return (round(sum(s["total_ms"] for s in rows), 3),
                sum(s["count"] for s in rows))

    cold = total(lambda n: n.endswith(".compile"))
    warm = total(lambda n: n.endswith(".compile.warm"))
    execute = total(lambda n: n.endswith(".execute"))
    if not (cold[1] or warm[1]):
        return None
    return {
        "cold_compile_ms": cold[0], "cold_compile_spans": cold[1],
        "warm_compile_ms": warm[0], "warm_compile_spans": warm[1],
        "execute_ms": execute[0], "execute_spans": execute[1],
        "compile_cache_hit": int(counters.get("compile_cache_hit", 0)),
        "compile_cache_miss": int(counters.get("compile_cache_miss", 0)),
        "jit_cache_miss": int(counters.get("jit_cache_miss", 0)),
    }


def compile_profile(counters: dict | None,
                    gauges: dict | None = None) -> dict | None:
    """Per-stage compile attribution from the ``compile_ms[<stage>:
    <sig>:<cold|warm>]`` counters (recorded by ``obs.instrument_jit``
    per compiled signature): which jit'd stage/signature dominates the
    remaining compile wall time, split cold (full XLA) vs warm
    (persistent-cache / AOT-deserialized), plus warm-cache-artifact
    provenance (the ``compile_cache_artifact`` gauge set by
    ``run_pipeline`` when the cache dir carries a MANIFEST, and the
    ``cache_artifact_unpacked`` / ``cache_artifact_rejected``
    counters).  None when the trace carries no compile attribution."""
    counters = counters or {}
    gauges = gauges or {}
    stages: dict[str, dict] = {}
    for label, value in bracketed_values(counters,
                                         "compile_ms[").items():
        rest, _, mode = label.rpartition(":")
        stage, _, sig = rest.partition(":")
        if mode not in ("cold", "warm") or not stage:
            continue
        row = stages.setdefault(stage, {"cold_ms": 0.0, "warm_ms": 0.0,
                                        "signatures": {}})
        row[f"{mode}_ms"] = round(row[f"{mode}_ms"] + float(value), 3)
        srow = row["signatures"].setdefault(
            sig or "scalar", {"cold_ms": 0.0, "warm_ms": 0.0})
        srow[f"{mode}_ms"] = round(srow[f"{mode}_ms"] + float(value), 3)
    artifact = {
        "digest": gauges.get("compile_cache_artifact"),
        "unpacked": int(counters.get("cache_artifact_unpacked", 0)),
        "rejected": int(counters.get("cache_artifact_rejected", 0)),
        "evictions": int(counters.get("compile_cache_evictions", 0)),
    }
    if not stages and artifact["digest"] is None \
            and not (artifact["unpacked"] or artifact["rejected"]
                     or artifact["evictions"]):
        return None
    out = {"stages": stages, "artifact": artifact}
    # program-splitting rollup (ISSUE 14): when the run executed split
    # units, quantify the RECOMPILED slice (shape-volatile front-end)
    # against the REUSED one (shape-stable fitter back-end) — the
    # number the split exists to improve.  Per-unit jit_cache_miss
    # comes from the bracketed family obs.instrument_jit records.
    misses = bracketed_values(counters, "jit_cache_miss[")
    if "pipeline.front" in stages or "pipeline.back" in stages:
        front = stages.get("pipeline.front", {"cold_ms": 0.0})
        back = stages.get("pipeline.back", {"cold_ms": 0.0})
        out["split"] = {
            "front_cold_ms": front.get("cold_ms", 0.0),
            "front_misses": int(misses.get("pipeline.front", 0)),
            "back_cold_ms": back.get("cold_ms", 0.0),
            "back_misses": int(misses.get("pipeline.back", 0)),
        }
    return out


def catalog_section(counters: dict | None,
                    gauges: dict | None = None) -> dict | None:
    """Shape-bucket catalog fill (scintools_tpu.buckets): per compiled
    signature, how many batches hit it this run, the real vs padded
    lane split and the pad-waste ratio (padded / real elements), plus
    catalog entries that exist but were never hit — so over-padding and
    dead rungs are visible rather than silent.  None when the run
    never bucketed."""
    counters = counters or {}
    gauges = gauges or {}
    hits = bracketed_values(counters, "bucket_hits[")
    real = bracketed_values(counters, "bucket_lanes_real[")
    pad = bracketed_values(counters, "bucket_lanes_pad[")
    exist = bracketed_values(gauges, "bucket_catalog[")
    if not hits and not exist:
        return None
    from ..buckets import pad_waste

    rows = {}
    for label in sorted(set(hits) | set(exist)):
        r, p = int(real.get(label, 0)), int(pad.get(label, 0))
        rows[label] = {
            "hits": int(hits.get(label, 0)),
            "lanes_real": r,
            "lanes_pad": p,
            "pad_waste": pad_waste(r, r + p),
        }
    return rows


def measured_roofline(gauges: dict | None) -> dict | None:
    """Per-signature MEASURED step costs from the ``step_flops[...]`` /
    ``step_bytes[...]`` gauges (XLA cost analysis, recorded by
    ``obs.instrument_jit`` at compile time), each compared against the
    analytic model (utils/roofline.py) evaluated at the signature's
    parsed [B, nf, nt] shape with the default pipeline config.

    Returns ``{label: {flops, bytes, ai, model_flops?, model_bytes?,
    flops_vs_model?, bytes_vs_model?}}`` or None when the trace carries
    no cost gauges.  The model column is a default-config estimate (the
    trace does not record the PipelineConfig); bench.py's record
    computes the same comparison with its exact config.
    """
    gauges = gauges or {}
    rows: dict[str, dict] = {}
    for prefix, field in (("step_flops[", "flops"),
                          ("step_bytes[", "bytes")):
        for label, value in bracketed_values(gauges, prefix).items():
            rows.setdefault(label, {})[field] = value
    if not rows:
        return None
    for label, row in rows.items():
        if row.get("flops") and row.get("bytes"):
            row["ai"] = round(row["flops"] / row["bytes"], 2)
        # label format: "<span name>:<B>x<nf>x<nt>:<dtype>"
        parts = label.split(":")
        dims = parts[1].split("x") if len(parts) >= 2 else []
        if len(dims) == 3 and all(d.isdigit() for d in dims):
            try:
                from ..utils.roofline import pipeline_epoch_model

                b, nf, nt = (int(d) for d in dims)
                model = pipeline_epoch_model(nf, nt)
                m = model["total"]
                row["model_flops"] = b * m["flops"]
                row["model_bytes"] = b * m["bytes"]
                if row.get("flops"):
                    row["flops_vs_model"] = round(
                        row["flops"] / row["model_flops"], 2)
                if row.get("bytes"):
                    row["bytes_vs_model"] = round(
                        row["bytes"] / row["model_bytes"], 2)
                # per-stage BYTES split beside the flop split (one
                # batch's worth, model-attributed): on a bandwidth-
                # bound step the byte attribution is what makes a
                # fused-vs-chain HBM-traffic claim readable from the
                # trace rather than only from bench JSON
                row["model_stage_gflop"] = {
                    k: round(b * v["flops"] / 1e9, 3)
                    for k, v in model.items() if k != "total"}
                row["model_stage_gbytes"] = {
                    k: round(b * v["bytes"] / 1e9, 3)
                    for k, v in model.items() if k != "total"}
            except Exception:  # model must never sink the report
                pass
    return rows


def bracketed_values(src: dict, prefix: str) -> dict:
    """``{key: float(value)}`` for every ``<family>[<key>]`` entry of a
    counter/gauge dict — the ONE parser of the bracketed-family naming
    convention (obs/names.py FAMILIES), shared by the report sections,
    the fleet rollup and devmem's prediction lookup."""
    return {name[len(prefix):-1]: float(v) for name, v in src.items()
            if name.startswith(prefix) and name.endswith("]")
            and isinstance(v, (int, float))}


def devmem_section(counters: dict | None, gauges: dict | None = None,
                   events=None) -> dict | None:
    """Device-memory readout (obs/devmem): the HBM gauges, every
    signature's MEASURED peak residency beside its modeled
    ``step_bytes`` (cost-analysis) bytes, the predicted-avoided vs
    suffered OOM counts, and — when the event stream is available —
    the in-use/headroom timeline from the streamed ``hbm_bytes_in_use``
    gauge stamps.  None when the plane never sampled (CPU backends:
    ``memory_stats()`` is None and no gauge ever lands)."""
    counters = counters or {}
    gauges = gauges or {}
    peaks = bracketed_values(gauges, "step_hbm_peak[")
    in_use = gauges.get("hbm_bytes_in_use")
    limit = gauges.get("hbm_bytes_limit")
    avoided = int(counters.get("oom_predicted_avoided", 0))
    if in_use is None and not peaks and not avoided:
        return None
    numeric = all(isinstance(v, (int, float)) for v in (in_use, limit))
    out = {
        "bytes_in_use": in_use, "bytes_limit": limit,
        "headroom": (limit - in_use if numeric and limit else None),
        "oom_predicted_avoided": avoided,
        "oom_backoff": int(counters.get("oom_backoff", 0)),
    }
    model = bracketed_values(gauges, "step_bytes[")
    sigs = {}
    for label in sorted(peaks):
        row = {"peak_bytes": peaks[label]}
        if label in model:
            row["model_bytes"] = model[label]
            if model[label]:
                row["peak_vs_model"] = round(peaks[label]
                                             / model[label], 2)
        sigs[label] = row
    if sigs:
        out["signatures"] = sigs
    if events:
        pts = gauge_timeline(events, "hbm_bytes_in_use",
                             streamed_only=True)
        if pts:
            out["in_use_timeline"] = pts
    return out


def serve_section(counters: dict | None,
                  gauges: dict | None = None) -> dict | None:
    """Resident-service readout (scintools_tpu.serve): job outcomes,
    mean dynamic-batch fill, and queue wait, derived from the worker's
    counters.  None when the trace carries no serve activity."""
    counters = counters or {}
    gauges = gauges or {}
    lanes_total = counters.get("serve_lanes_total", 0)
    claimed = counters.get("serve_jobs_claimed", 0)
    if not (lanes_total or claimed or counters.get("jobs_done")
            or counters.get("jobs_failed")):
        return None
    out = {
        "batches": int(counters.get("serve_batches", 0)),
        "jobs_done": int(counters.get("jobs_done", 0)),
        "jobs_failed": int(counters.get("jobs_failed", 0)),
        "job_retries": int(counters.get("job_retries", 0)),
        "batch_fill_ratio": (
            round(counters.get("serve_lanes_filled", 0) / lanes_total, 4)
            if lanes_total else None),
        "queue_wait_s_mean": (
            round(counters.get("queue_wait_s", 0.0) / claimed, 6)
            if claimed else None),
    }
    if "queue_depth" in gauges:
        out["queue_depth_last"] = gauges["queue_depth"]
    return out


def stream_section(counters: dict | None,
                   gauges: dict | None = None) -> dict | None:
    """Streaming-ingest readout (scintools_tpu.stream — ISSUE 15):
    sliding-window recompute ticks, per-feed processing lag, and
    per-chunk quarantine reasons.  None when the trace carries no
    streaming activity."""
    counters = counters or {}
    gauges = gauges or {}
    ticks = int(counters.get("stream_ticks", 0))
    jobs = int(counters.get("serve_stream_jobs", 0))
    quarantined = int(counters.get("chunks_quarantined", 0))
    if not (ticks or jobs or quarantined):
        return None
    out = {"stream_jobs": jobs, "stream_ticks": ticks,
           "chunks_quarantined": quarantined}
    reasons = {k: int(v) for k, v in bracketed_values(
        counters, "chunks_quarantined[").items()}
    if reasons:
        out["quarantine_reasons"] = reasons
    if "stream_lag_s" in gauges:
        out["stream_lag_s_last"] = gauges["stream_lag_s"]
    feeds = bracketed_values(gauges, "stream_lag_s[")
    if feeds:
        out["feed_lag_s"] = {k: round(float(v), 3)
                             for k, v in feeds.items()}
    return out


def reliability_section(counters: dict | None,
                        gauges: dict | None = None) -> dict | None:
    """Self-healing readout (docs/reliability.md): OOM chunk backoffs
    (+ the surviving ``effective_chunk``), preflight quarantines broken
    out by reason code, budget-preserving transient requeues, corrupt
    store rows, and fired chaos injections.  None when the trace shows
    no degradation at all — a healthy run's report stays unchanged."""
    counters = counters or {}
    gauges = gauges or {}
    quarantined = {k: int(v) for k, v in bracketed_values(
        counters, "epochs_quarantined[").items()}
    out = {
        "oom_backoff": int(counters.get("oom_backoff", 0)),
        "epochs_quarantined": int(counters.get("epochs_quarantined", 0)),
        "job_transient_retries": int(
            counters.get("job_transient_retries", 0)),
        "store_corrupt_rows": int(counters.get("store_corrupt_rows", 0)),
        "faults_injected": int(counters.get("faults_injected", 0)),
    }
    if not any(out.values()):
        return None
    if quarantined:
        out["quarantine_reasons"] = quarantined
    if "effective_chunk" in gauges:
        out["effective_chunk"] = gauges["effective_chunk"]
    return out


def slo_section(counters: dict | None, gauges: dict | None = None,
                events=None) -> dict | None:
    """SLO readout (obs/slo — ISSUE 16): per-objective fast/slow
    burn rates and remaining error budget from the AlertEngine's
    flush-time gauges, the firing-alert count, and — when the raw
    event stream is available — the alert lifecycle timeline
    (``alert.pending``/``alert.firing``/``alert.resolved``/
    ``alert.ack`` events in time order).  None when the trace carries
    no SLO activity at all — an un-SLO'd run's report is unchanged."""
    counters = counters or {}
    gauges = gauges or {}
    fast = bracketed_values(gauges, "slo_burn_fast[")
    slow = bracketed_values(gauges, "slo_burn_slow[")
    budget = bracketed_values(gauges, "slo_budget_remaining[")
    firing = gauges.get("alerts_firing")
    transitions = []
    for ev in events or ():
        name = ev.get("name", "")
        if ev.get("kind") == "event" and name.startswith("alert."):
            transitions.append((ev.get("ts", 0.0), name,
                                (ev.get("attrs") or {}).get("slo")))
    if not (fast or slow or budget or transitions or firing):
        return None
    slos = {}
    for name in sorted(set(fast) | set(slow) | set(budget)):
        slos[name] = {"burn_fast": fast.get(name),
                      "burn_slow": slow.get(name),
                      "budget_remaining": budget.get(name)}
    out: dict = {"slos": slos}
    if firing is not None:
        out["alerts_firing"] = int(firing)
    if transitions:
        transitions.sort(key=lambda t: t[0])
        out["alert_timeline"] = transitions
    return out


def render(spans: dict, counters: dict | None = None,
           gauges: dict | None = None, events=None) -> str:
    """Fixed-width per-stage table, longest-total first, then the
    cold/warm compile split, then the serve, device-memory and
    reliability sections, then counters.  ``events`` (optional — the
    raw record stream) feeds the memory section's headroom timeline."""
    lines = []
    if spans:
        w = max(len("stage"), max(len(n) for n in spans))
        lines.append(f"{'stage':<{w}}  {'count':>7}  {'total_ms':>12}  "
                     f"{'mean_ms':>10}  {'p50_ms':>10}  {'p95_ms':>10}")
        lines.append("-" * (w + 58))
        order = sorted(spans, key=lambda n: spans[n]["total_ms"],
                       reverse=True)
        for name in order:
            s = spans[name]
            lines.append(
                f"{name:<{w}}  {s['count']:>7d}  {s['total_ms']:>12.3f}  "
                f"{s['mean_ms']:>10.3f}  {s['p50_ms']:>10.3f}  "
                f"{s['p95_ms']:>10.3f}")
    else:
        lines.append("(no spans)")
    split = compile_split(spans, counters)
    if split:
        lines.append("")
        lines.append("cold/warm compile split:")
        lines.append(f"  cold compile  total_ms = "
                     f"{split['cold_compile_ms']:.3f}  "
                     f"({split['cold_compile_spans']} span(s))")
        lines.append(f"  warm compile  total_ms = "
                     f"{split['warm_compile_ms']:.3f}  "
                     f"({split['warm_compile_spans']} span(s))")
        lines.append(f"  execute       total_ms = "
                     f"{split['execute_ms']:.3f}  "
                     f"({split['execute_spans']} span(s))")
        lines.append(f"  compile_cache_hit = {split['compile_cache_hit']}, "
                     f"compile_cache_miss = {split['compile_cache_miss']}, "
                     f"jit_cache_miss = {split['jit_cache_miss']}")
    prof = compile_profile(counters, gauges)
    if prof:
        lines.append("")
        lines.append("compile profile (per jit'd stage/signature, "
                     "cold = full XLA, warm = cache-served):")
        order = sorted(prof["stages"],
                       key=lambda s: (prof["stages"][s]["cold_ms"]
                                      + prof["stages"][s]["warm_ms"]),
                       reverse=True)
        for stage in order:
            row = prof["stages"][stage]
            lines.append(f"  {stage}: cold_ms = {row['cold_ms']:.3f}, "
                         f"warm_ms = {row['warm_ms']:.3f}")
            for sig in sorted(row["signatures"],
                              key=lambda s: (row["signatures"][s]["cold_ms"]
                                             + row["signatures"][s]["warm_ms"]),
                              reverse=True):
                srow = row["signatures"][sig]
                lines.append(f"    {sig}: cold_ms = "
                             f"{srow['cold_ms']:.3f}, warm_ms = "
                             f"{srow['warm_ms']:.3f}")
        sp = prof.get("split")
        if sp:
            lines.append(
                f"  program split: recompiled slice (front) = "
                f"{sp['front_cold_ms']:.3f} ms over "
                f"{sp['front_misses']} signature(s); reused fitter "
                f"(back) = {sp['back_cold_ms']:.3f} ms over "
                f"{sp['back_misses']} signature(s)"
                + (" — every novel shape served by warm fitters"
                   if sp["back_misses"] == 0 else ""))
        art = prof["artifact"]
        if art["digest"] is not None:
            lines.append(f"  warm-cache artifact: digest = "
                         f"{art['digest']} (cache seeded from a packed "
                         "artifact)")
        elif art["unpacked"] or art["rejected"]:
            lines.append(f"  warm-cache artifact: unpacked = "
                         f"{art['unpacked']}, rejected = "
                         f"{art['rejected']}")
        else:
            lines.append("  warm-cache artifact: none "
                         "(scripts/build_warm_cache.py ships one)")
        if art["evictions"]:
            lines.append(f"  compile_cache_evictions = "
                         f"{art['evictions']}")
    cat = catalog_section(counters, gauges)
    if cat:
        lines.append("")
        lines.append("shape-bucket catalog (hits / real vs padded lanes "
                     "/ pad-waste):")
        for label, row in cat.items():
            if row["hits"]:
                lines.append(
                    f"  {label}: hits = {row['hits']}, lanes = "
                    f"{row['lanes_real']} real + {row['lanes_pad']} pad, "
                    f"pad_waste = {row['pad_waste']}")
            else:
                lines.append(f"  {label}: in catalog, not hit this run")
    meas = measured_roofline(gauges)
    if meas:
        lines.append("")
        lines.append("measured roofline (XLA cost_analysis, per compiled "
                     "signature; model = analytic default-config "
                     "estimate):")
        for label, row in meas.items():
            gfl = row.get("flops", 0.0) / 1e9
            gby = row.get("bytes", 0.0) / 1e9
            part = (f"  {label}: {gfl:.3f} GFLOP, {gby:.3f} GB"
                    + (f", AI={row['ai']}" if "ai" in row else ""))
            if "flops_vs_model" in row or "bytes_vs_model" in row:
                part += (f"  [vs model: flops x"
                         f"{row.get('flops_vs_model', '?')}, bytes x"
                         f"{row.get('bytes_vs_model', '?')}]")
            lines.append(part)
            # per-stage split, flops AND bytes: the bytes column is the
            # one a bandwidth-bound step's fusion work answers to
            stages = row.get("model_stage_gbytes")
            if stages:
                gf = row.get("model_stage_gflop", {})
                lines.append("    stage split (model): " + ", ".join(
                    f"{k} {gf.get(k, 0.0):.3f} GFLOP / {v:.3f} GB"
                    for k, v in stages.items()))
    mem = devmem_section(counters, gauges, events)
    if mem:
        def _gib(v):
            return (f"{v / 2**30:.3f} GiB"
                    if isinstance(v, (int, float)) else "-")

        lines.append("")
        lines.append("device memory (measured HBM, obs/devmem):")
        if mem["bytes_in_use"] is not None:
            lines.append(
                f"  in_use = {_gib(mem['bytes_in_use'])}, limit = "
                f"{_gib(mem['bytes_limit'])}, headroom = "
                f"{_gib(mem['headroom'])}")
        for label, row in mem.get("signatures", {}).items():
            part = f"  {label}: peak = {_gib(row['peak_bytes'])}"
            if "model_bytes" in row:
                part += f", model = {_gib(row['model_bytes'])}"
                if "peak_vs_model" in row:
                    part += f" [peak/model x{row['peak_vs_model']}]"
            lines.append(part)
        lines.append(
            f"  oom_predicted_avoided = {mem['oom_predicted_avoided']}"
            f", oom_backoff (reactive) = {mem['oom_backoff']}")
        tl = mem.get("in_use_timeline")
        if tl:
            lines.append("  hbm_bytes_in_use timeline: "
                         + " ".join(f"{int(v)}" for _, v in tl))
    serve = serve_section(counters, gauges)
    if serve:
        lines.append("")
        lines.append("serve (resident survey service):")
        lines.append(f"  batches = {serve['batches']}, "
                     f"jobs_done = {serve['jobs_done']}, "
                     f"jobs_failed = {serve['jobs_failed']}, "
                     f"job_retries = {serve['job_retries']}")
        if serve["batch_fill_ratio"] is not None:
            lines.append(f"  batch_fill_ratio (mean) = "
                         f"{serve['batch_fill_ratio']}")
        if serve["queue_wait_s_mean"] is not None:
            lines.append(f"  queue_wait_s (mean per job) = "
                         f"{serve['queue_wait_s_mean']}")
        if "queue_depth_last" in serve:
            lines.append(f"  queue_depth (last) = "
                         f"{serve['queue_depth_last']}")
    streams = stream_section(counters, gauges)
    if streams:
        lines.append("")
        lines.append("streams (live feeds, sliding-window recompute):")
        lines.append(f"  stream_jobs = {streams['stream_jobs']}, "
                     f"stream_ticks = {streams['stream_ticks']}")
        quar = (f"  chunks_quarantined = "
                f"{streams['chunks_quarantined']}")
        if streams.get("quarantine_reasons"):
            quar += " (" + ", ".join(
                f"{k}={v}" for k, v in
                sorted(streams["quarantine_reasons"].items())) + ")"
        lines.append(quar)
        if "stream_lag_s_last" in streams:
            lines.append(f"  stream_lag_s (last) = "
                         f"{streams['stream_lag_s_last']}")
        for feed, lag in sorted(streams.get("feed_lag_s",
                                            {}).items()):
            lines.append(f"    {feed}: lag = {lag} s")
    rel = reliability_section(counters, gauges)
    if rel:
        lines.append("")
        lines.append("reliability (self-healing events):")
        lines.append(f"  oom_backoff = {rel['oom_backoff']}"
                     + (f" (effective_chunk = {rel['effective_chunk']})"
                        if "effective_chunk" in rel else ""))
        quar = f"  epochs_quarantined = {rel['epochs_quarantined']}"
        if rel.get("quarantine_reasons"):
            quar += " (" + ", ".join(
                f"{k}={v}" for k, v in
                sorted(rel["quarantine_reasons"].items())) + ")"
        lines.append(quar)
        lines.append(f"  job_transient_retries = "
                     f"{rel['job_transient_retries']}, "
                     f"store_corrupt_rows = {rel['store_corrupt_rows']}, "
                     f"faults_injected = {rel['faults_injected']}")
    slo = slo_section(counters, gauges, events)
    if slo:
        lines.append("")
        lines.append("slo (error-budget burn, obs/slo):")
        if "alerts_firing" in slo:
            lines.append(f"  alerts_firing = {slo['alerts_firing']}")
        for name, row in slo["slos"].items():
            def _b(v):
                return f"{v:g}" if isinstance(v, (int, float)) else "-"
            lines.append(f"  {name}: burn fast = {_b(row['burn_fast'])}, "
                         f"slow = {_b(row['burn_slow'])}, budget "
                         f"remaining = {_b(row['budget_remaining'])}")
        for ts, name, slo_name in slo.get("alert_timeline", ()):
            who = f" ({slo_name})" if slo_name else ""
            lines.append(f"    {ts:.3f}  {name}{who}")
    if counters:
        lines.append("")
        lines.append("counters:")
        for name in sorted(counters):
            v = counters[name]
            v = int(v) if float(v).is_integer() else v
            lines.append(f"  {name} = {v}")
    if gauges:
        lines.append("")
        lines.append("gauges:")
        for name in sorted(gauges):
            lines.append(f"  {name} = {gauges[name]}")
    return "\n".join(lines)


def report(path: str) -> str:
    """The ``trace report`` payload for one JSONL trace file."""
    events = load_events(path)
    spans, counters, gauges = aggregate(events)
    return render(spans, counters, gauges, events)


def report_many(paths, since: float | None = None,
                last: float | None = None) -> tuple[str, list]:
    """The multi-file/glob ``trace report`` payload: one merged table
    over every matched trace, plus the degradation warnings.  Raises
    OSError only when NOTHING was readable (one bad path among many
    degrades to a warning).  ``since``/``last`` apply the event-time
    filters (:func:`filter_events`) before aggregation, so a multi-day
    merged file reports only the asked-for window."""
    events, warnings = load_trace_files(paths)
    if not events and warnings:
        raise OSError("; ".join(warnings))
    total = len(events)
    events = filter_events(events, since=since, last=last)
    if total and not events:
        warnings.append(f"time filter dropped all {total} record(s) "
                        "(nothing stamped inside the window)")
    spans, counters, gauges = aggregate(events)
    return render(spans, counters, gauges, events), warnings
