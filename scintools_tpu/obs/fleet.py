"""Fleet telemetry: distributed job traces, worker heartbeat snapshots,
and the merged operator rollup with a backpressure signal (ISSUE 10 —
the telemetry prerequisite of ROADMAP item 1's multi-worker serve pool).

Three layers, all filesystem-protocol like the serve queue itself:

1. **Distributed traces.**  ``JobQueue.submit`` mints a ``trace_id``
   and persists it in the job record; every lifecycle hop (claim, load,
   preflight, batch, row put, complete/fail/requeue — including
   lease-reap hops taken by a *different* process than the one that
   died) records an ``obs.event`` carrying ``trace_id`` plus a parent
   link to the previous hop's event id, which rides the job record
   between processes.  In-process spans (``serve.batch`` →
   ``pipeline.*`` → ``*.step.compile/execute``) chain through the span
   ``span``/``parent`` ids recorded by obs.core.  Merging every
   process's JSONL sink and calling :func:`assemble_traces` reassembles
   one causal trace per job — SIGKILL, reap, and requeue hops included.

2. **Heartbeats.**  Each worker atomically overwrites ONE file,
   ``heartbeat/<worker>.json`` (bounded write amplification: a fleet of
   N workers writes N small files per interval, never an append log):
   pid, counters (totals AND deltas since the previous beat), gauges,
   the mergeable fixed-bucket histograms (obs/hist.py) for queue wait
   and per-stage latency, last-claim age, and warm-affinity digests
   (warm-cache artifact / batch ladder).  :func:`merge_heartbeats` is
   associative and commutative — fold any subset in any order.

3. **Rollup + backpressure.**  ``trace report --fleet DIR`` /
   ``scintools-tpu fleet status DIR`` merge N heartbeats + any trace
   (or crash-flight) JSONL files into per-worker and aggregate tables,
   and compute the scalar :func:`backpressure` ∈ [0, 1] documented
   below — the admission-control input the serve-fleet item consumes.
"""

from __future__ import annotations

import glob as glob_mod
import json
import os
import time
import uuid

from . import core, devmem
from ..utils import fsio
from .hist import Hist, merge_hist_dicts

HEARTBEAT_DIRNAME = "heartbeat"
FLIGHT_DIRNAME = "flight"
HEARTBEAT_VERSION = 1

# ---------------------------------------------------------------------------
# trace ids + reassembly
# ---------------------------------------------------------------------------


def new_trace_id() -> str:
    """A fresh distributed-trace id (uuid4 hex): minted once per job at
    submit time and carried by every hop that touches the job."""
    return uuid.uuid4().hex


def assemble_traces(events) -> dict:
    """Reassemble per-job causal traces from a MERGED event stream
    (any number of processes' JSONL sinks, any order).

    Membership: an event/span belongs to a trace when its attrs carry
    ``trace_id``, or transitively when its parent id belongs to one —
    the top-level ``parent`` field (the in-process span chain), with
    an attrs-level ``parent`` as the CROSS-PROCESS fallback edge (a
    top-level span like ``serve.load`` links to the job's previous
    lifecycle hop, recorded by another process, through its attrs).
    A span touching several jobs (one serve.batch over N jobs)
    belongs to all of their traces.

    Returns ``{trace_id: {"events": [records sorted by ts], "pids":
    sorted pid list, "names": [event/span names in ts order],
    "orphans": [records whose parent id is missing from the merged
    stream]}}`` — ``orphans`` empty means the causal chain is complete
    (the cross-process reassembly acceptance)."""
    recs = [ev for ev in events
            if ev.get("kind") in ("span", "event") and ev.get("span")]
    by_id = {ev["span"]: ev for ev in recs}
    # seed: explicit trace_id attrs
    traces: dict[str, set] = {}
    membership: dict[str, set] = {}   # record id -> trace ids
    for ev in recs:
        attrs = ev.get("attrs") or {}
        tid = attrs.get("trace_id")
        if tid:
            membership.setdefault(ev["span"], set()).add(tid)
        # a batch span touches N jobs at once (serve.batch carries
        # every member's trace id); it and its nested pipeline spans
        # belong to all of them
        tids = attrs.get("trace_ids")
        if isinstance(tids, (list, tuple)):
            for t in tids:
                if t:
                    membership.setdefault(ev["span"], set()).add(t)
    # propagate down parent chains until fixpoint (children inherit
    # every trace their parent belongs to); the in-process span-stack
    # parent is the primary edge, the attrs-level parent (set by
    # workers on top-level spans to chain them to the job's previous
    # cross-process hop) the fallback
    children: dict[str, list] = {}
    for ev in recs:
        parent = ev.get("parent") or (ev.get("attrs") or {}).get("parent")
        if parent:
            children.setdefault(parent, []).append(ev["span"])
    frontier = list(membership)
    while frontier:
        nxt = []
        for rid in frontier:
            tids = membership.get(rid, ())
            for child in children.get(rid, ()):
                have = membership.setdefault(child, set())
                new = set(tids) - have
                if new:
                    have |= new
                    nxt.append(child)
        frontier = nxt
    for rid, tids in membership.items():
        for tid in tids:
            traces.setdefault(tid, set()).add(rid)
    out = {}
    for tid, rids in traces.items():
        evs = sorted((by_id[r] for r in rids),
                     key=lambda e: (e.get("ts", 0.0), e["span"]))
        orphans = [e for e in evs
                   if e.get("parent") and e["parent"] not in by_id]
        out[tid] = {"events": evs,
                    "pids": sorted({e.get("pid") for e in evs
                                    if e.get("pid") is not None}),
                    "names": [e.get("name") for e in evs],
                    "orphans": orphans}
    return out


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------


def _safe_name(worker_id: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_"
                   for c in worker_id) or "worker"


class HeartbeatWriter:
    """Periodic atomic snapshot of one worker's telemetry into
    ``<directory>/<worker>.json`` (tmp + ``os.replace`` — a reader can
    never see a torn heartbeat; each beat OVERWRITES the last, so the
    on-disk footprint is one bounded file per worker).

    ``beat()`` is cheap when the interval has not elapsed (one clock
    compare); the snapshot itself reads the obs registry (counters,
    gauges, hists — empty dicts when tracing is disabled: liveness
    still works untraced) plus whatever the worker passes in."""

    def __init__(self, directory: str, worker_id: str,
                 interval_s: float = 10.0):
        self.dir = directory
        self.worker_id = worker_id
        self.interval_s = float(interval_s)
        self.path = os.path.join(directory,
                                 f"{_safe_name(worker_id)}.json")
        self._last_beat = None
        self._last_counters: dict = {}
        self._seq = 0
        self._digests = None

    def _warm_digests(self) -> dict:
        """Warm-affinity signals, computed once: the warm-cache
        artifact digest this process's persistent cache was unpacked
        from (compile_cache MANIFEST — also the catalog digest when
        packed by ``warmup --catalog``)."""
        if self._digests is None:
            digests = {}
            try:
                from .. import compile_cache

                man = compile_cache.artifact_manifest()
                if man is not None:
                    digests["warm_cache"] = str(man.get("digest", "?"))
            except Exception:
                pass
            self._digests = digests
        return self._digests

    def due(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        return (self._last_beat is None
                or now - self._last_beat >= self.interval_s)

    def beat(self, now: float | None = None, force: bool = False,
             last_claim_at: float | None = None,
             stats: dict | None = None,
             extra: dict | None = None) -> str | None:
        """Write one heartbeat if due (or ``force``).  Returns the path
        written, else None."""
        now = time.time() if now is None else now
        if not force and not self.due(now):
            return None
        reg = core.get_registry()
        counters = reg.counters()
        # an UNTRACED worker (the default: obs.inc is a no-op) still
        # counts outcomes in its own stats dict — map them onto the
        # canonical counter names so the fleet rollup's jobs_done /
        # drain-rate / backpressure math works without --trace; a
        # traced worker's registry counters carry identical values
        # and win
        for stat_key, counter in (("jobs_done", "jobs_done"),
                                  ("jobs_failed", "jobs_failed"),
                                  ("job_retries", "job_retries"),
                                  ("job_transient_retries",
                                   "job_transient_retries"),
                                  ("batches", "serve_batches"),
                                  ("lanes_filled", "serve_lanes_filled"),
                                  ("lanes_total", "serve_lanes_total"),
                                  ("segment_flushes", "segment_flushes"),
                                  ("rows_flushed", "segment_rows"),
                                  ("stream_ticks", "stream_ticks")):
            v = (stats or {}).get(stat_key)
            if counter not in counters and isinstance(v, (int, float)):
                counters[counter] = v
        deltas = {k: v - self._last_counters.get(k, 0)
                  for k, v in counters.items()
                  if v != self._last_counters.get(k, 0)}
        elapsed = (None if self._last_beat is None
                   else round(now - self._last_beat, 6))
        self._seq += 1
        hb = {
            "kind": "heartbeat", "v": HEARTBEAT_VERSION,
            "worker": self.worker_id, "pid": os.getpid(),
            "ts": round(now, 6), "seq": self._seq,
            "interval_s": self.interval_s, "elapsed_s": elapsed,
            "counters": counters, "deltas": deltas,
            "gauges": reg.gauges(), "hists": reg.hists(),
            "last_claim_age_s": (round(now - last_claim_at, 6)
                                 if last_claim_at is not None else None),
            "digests": self._warm_digests(),
        }
        # device-memory plane (ISSUE 12): a DIRECT sample into the
        # heartbeat body, so an untraced worker (obs.gauge is a no-op
        # without --trace) still publishes its headroom — the
        # admission signal the pool controller routes on.  One
        # memory_stats read per beat; a backend without stats (CPU)
        # memoises the negative and this is one flag check.
        snap = devmem.snapshot()
        if snap is not None:
            mem = dict(snap)
            if mem.get("bytes_limit"):
                mem["headroom"] = mem["bytes_limit"] - mem["bytes_in_use"]
            peaks = devmem.recorded_peaks()
            if peaks:
                mem["step_peaks"] = peaks
            hb["devmem"] = mem
        if stats:
            hb["stats"] = dict(stats)
        if extra:
            hb.update(extra)
        os.makedirs(self.dir, exist_ok=True)
        fsio.put_atomic(self.path, json.dumps(hb, default=str))
        self._last_beat = now
        self._last_counters = counters
        return self.path


def read_heartbeats(directory: str) -> list[dict]:
    """Every readable heartbeat under ``directory`` (non-recursive);
    torn/foreign JSON files are skipped — a fleet readout must degrade,
    never raise, while workers are writing concurrently."""
    out = []
    try:
        names = sorted(fsio.list(directory))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json") or ".tmp" in name:
            continue
        try:
            hb = json.loads(fsio.read(os.path.join(directory, name)))
        except (OSError, ValueError):
            continue
        if isinstance(hb, dict) and hb.get("kind") == "heartbeat":
            out.append(hb)
    return out


def heartbeat_stale(hb: dict, now: float) -> bool:
    """Whether a heartbeat is STALE: beat age over 3x the worker's own
    ``interval_s`` (ISSUE 12 satellite).  A dead worker's last
    snapshot keeps its frozen ``deltas`` forever; folding them into
    the drain rate dilutes the fleet estimate with a rate the worker
    is no longer producing.  Heartbeats without an interval (foreign
    payloads) never read as stale."""
    iv = hb.get("interval_s")
    if not isinstance(iv, (int, float)) or iv <= 0:
        return False
    return (now - hb.get("ts", now)) > 3.0 * iv


def merge_heartbeats(heartbeats, now: float | None = None) -> dict:
    """Fold N worker heartbeats into one fleet aggregate — associative
    and commutative (counter sums, histogram bucket adds, last-writer
    gauges by timestamp), asserted by tests/test_fleet.py.

    Returns ``{workers, counters, hists (merged summaries), gauges,
    drain_rate_per_s, depth}``: ``drain_rate_per_s`` sums each
    worker's ``jobs_done`` delta over its beat interval (a worker's
    FIRST beat has no interval and contributes 0 — rate needs two
    observations); ``depth`` is the freshest ``queue_depth`` gauge.
    ``now`` (when given) excludes STALE workers — beat age > 3x their
    own interval, :func:`heartbeat_stale` — from the drain rate (and
    therefore from backpressure): a dead worker's frozen deltas must
    not read as live throughput.  Their counters still merge (totals
    stay truthful) and ``stale_workers`` counts them."""
    hbs = sorted((hb for hb in heartbeats),
                 key=lambda hb: (hb.get("ts", 0.0),
                                 str(hb.get("worker"))))
    counters: dict[str, float] = {}
    hists: dict[str, Hist] = {}
    gauges: dict = {}
    gauge_ts: dict = {}
    drain = 0.0
    stale = 0
    for hb in hbs:
        for k, v in (hb.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for name, d in (hb.get("hists") or {}).items():
            try:
                h = Hist.from_dict(d)
            except (ValueError, TypeError, KeyError):
                continue
            hists[name] = h if name not in hists else hists[name].merge(h)
        ts = hb.get("ts", 0.0)
        for k, v in (hb.get("gauges") or {}).items():
            if ts >= gauge_ts.get(k, -1.0):
                gauges[k], gauge_ts[k] = v, ts
        if now is not None and heartbeat_stale(hb, now):
            stale += 1
            continue     # frozen deltas: no drain contribution
        elapsed = hb.get("elapsed_s")
        done = (hb.get("deltas") or {}).get("jobs_done", 0)
        if elapsed and elapsed > 0 and isinstance(done, (int, float)):
            drain += max(float(done), 0.0) / float(elapsed)
    depth = gauges.get("queue_depth")
    # per-worker SLO window deltas (obs/slo.py — ISSUE 16) fold by
    # elementwise addition, like the histograms they were cut from
    from .slo import merge_slo_snapshots

    return {"workers": len(hbs),
            "stale_workers": stale,
            "counters": counters,
            "hists": {n: h.summary() for n, h in sorted(hists.items())},
            "gauges": gauges,
            "slo": merge_slo_snapshots(hb.get("slo") for hb in hbs),
            "drain_rate_per_s": round(drain, 6),
            "depth": depth}


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------

BACKPRESSURE_HORIZON_S = 60.0


def backpressure(depth, drain_rate_per_s,
                 horizon_s: float = BACKPRESSURE_HORIZON_S) -> float:
    """The fleet's admission-control scalar in [0, 1]:

        backpressure = depth / (depth + drain_rate_per_s * horizon_s)

    i.e. the fraction of the next ``horizon_s`` seconds the CURRENT
    backlog would consume at the CURRENT fleet drain rate.  Properties
    (pinned by tests/test_fleet.py):

    * 0.0 when the queue is empty (any drain rate);
    * monotonically increasing in ``depth`` at fixed drain;
    * monotonically decreasing in ``drain_rate_per_s`` at fixed depth;
    * 1.0 when depth > 0 and nothing is draining (stalled fleet);
    * 0.5 exactly when the backlog equals one horizon of drain —
      the natural "scale up" threshold.
    """
    d = max(float(depth or 0), 0.0)
    if d <= 0.0:
        return 0.0
    r = max(float(drain_rate_per_s or 0.0), 0.0)
    return round(d / (d + r * float(horizon_s)), 6)


# ---------------------------------------------------------------------------
# collection + rollup
# ---------------------------------------------------------------------------


def queue_extras(directory: str) -> dict:
    """Live queue-side readouts for a fleet rollup when ``directory``
    IS a serve queue dir: depth, per-shard/per-lane queued depths, and
    the pool controller's last ``control/pool.json`` snapshot (ISSUE
    13).  Empty for bare heartbeat dirs; every probe degrades rather
    than raising (the rollup must render mid-churn)."""
    out: dict = {}
    if not os.path.isdir(os.path.join(directory, "queued")):
        return out
    try:
        from ..serve.queue import JobQueue

        q = JobQueue(directory)
        c = q.counts()
        out["depth"] = c["queued"] + c["leased"]
        out["shard_depths"] = q.shard_depths()
        out["lane_depths"] = q.lane_depths()
    except (OSError, ValueError):  # fault-ok: live probe is optional
        pass
    try:
        from ..serve.pool import read_pool_status

        pool = read_pool_status(directory)
        if pool is not None:
            out["pool"] = pool
    except OSError:  # fault-ok: snapshot is advisory
        pass
    # last crash-consistency audit snapshot (serve/fsck — ISSUE 20)
    try:
        from ..serve.fsck import read_fsck_status

        fsck = read_fsck_status(directory)
        if fsck is not None:
            out["fsck"] = fsck
    except OSError:  # fault-ok: snapshot is advisory
        pass
    # declared SLO registry + durable alert rows (obs/slo.py — ISSUE
    # 16): present only when the queue declares objectives
    try:
        from .slo import load_slos, read_alerts

        alerts = read_alerts(directory)
        if alerts:
            out["alerts"] = alerts
        try:
            specs = load_slos(directory)
        except ValueError:  # malformed registry: rollup still renders
            specs = []
        if specs:
            out["slos"] = specs
    except OSError:  # fault-ok: judgment plane is optional
        pass
    return out


def collect_fleet(directory: str) -> tuple[list, list, list]:
    """Gather a fleet directory's telemetry: ``(heartbeats, events,
    warnings)``.

    ``directory`` is a serve queue dir (heartbeats under
    ``heartbeat/``, crash flights under ``flight/``) or a bare
    heartbeat dir; trace JSONL files directly inside it are merged
    too.  Unreadable/torn inputs are skipped with a warning string —
    the rollup never dies on a file a live worker is mid-writing."""
    from .report import load_trace_files

    heartbeats = read_heartbeats(directory)
    hb_sub = os.path.join(directory, HEARTBEAT_DIRNAME)
    if os.path.isdir(hb_sub):
        heartbeats += read_heartbeats(hb_sub)
    patterns = [os.path.join(directory, "*.jsonl"),
                os.path.join(directory, FLIGHT_DIRNAME, "*.jsonl")]
    paths = sorted(p for pat in patterns for p in glob_mod.glob(pat))
    events, warnings = load_trace_files(paths)
    return heartbeats, events, warnings


def depth_timeline(events, limit: int = 12) -> list:
    """(ts, depth) points from streamed ``queue_depth`` gauge events —
    the transition-stamped timeline (ISSUE 10 satellite: submit/
    complete/fail stamp depth, so low poll rates don't alias it).
    Down-sampled evenly to ``limit`` points for rendering (the shared
    :func:`obs.report.gauge_timeline` resampler)."""
    from .report import gauge_timeline

    return gauge_timeline(events, "queue_depth", limit=limit)


def _worker_memory(hb: dict) -> dict | None:
    """The worker's memory column: the heartbeat's direct ``devmem``
    sample (works untraced), falling back to the traced registry's
    ``hbm_*`` gauges.  None when the worker's backend has no plane."""
    mem = hb.get("devmem")
    if isinstance(mem, dict) and "bytes_in_use" in mem:
        out = {"bytes_in_use": mem.get("bytes_in_use"),
               "peak_bytes_in_use": mem.get("peak_bytes_in_use"),
               "bytes_limit": mem.get("bytes_limit"),
               "headroom": mem.get("headroom")}
        if mem.get("step_peaks"):
            out["step_peaks"] = mem["step_peaks"]
        return out
    from .report import bracketed_values

    g = hb.get("gauges") or {}
    in_use, limit = g.get("hbm_bytes_in_use"), g.get("hbm_bytes_limit")
    if not isinstance(in_use, (int, float)):
        return None
    out = {"bytes_in_use": in_use, "peak_bytes_in_use": None,
           "bytes_limit": limit,
           "headroom": (limit - in_use
                        if isinstance(limit, (int, float)) and limit
                        else None)}
    peaks = bracketed_values(g, "step_hbm_peak[")
    if peaks:
        out["step_peaks"] = {label: {"bytes": v}
                             for label, v in peaks.items()}
    return out


def _worker_row(hb: dict, now: float) -> dict:
    c = hb.get("counters") or {}
    hists = hb.get("hists") or {}
    qw = None
    if "queue_wait_s" in hists:
        try:
            qw = Hist.from_dict(hists["queue_wait_s"]).summary()
        except (ValueError, TypeError, KeyError):
            qw = None
    cold = sum(v for k, v in c.items()
               if k.startswith("compile_ms[") and k.endswith(":cold]"))
    warm = sum(v for k, v in c.items()
               if k.startswith("compile_ms[") and k.endswith(":warm]"))
    lanes_total = c.get("serve_lanes_total", 0)
    return {
        "worker": hb.get("worker"), "pid": hb.get("pid"),
        "age_s": round(max(now - hb.get("ts", now), 0.0), 3),
        "stale": heartbeat_stale(hb, now),
        "memory": _worker_memory(hb),
        "last_claim_age_s": hb.get("last_claim_age_s"),
        "jobs_done": int(c.get("jobs_done", 0)),
        "jobs_failed": int(c.get("jobs_failed", 0)),
        "job_retries": int(c.get("job_retries", 0)),
        "job_transient_retries": int(c.get("job_transient_retries", 0)),
        "epochs_quarantined": int(c.get("epochs_quarantined", 0)),
        "fill_ratio": (round(c.get("serve_lanes_filled", 0)
                             / lanes_total, 4) if lanes_total else None),
        "queue_wait": qw,
        "compile_cold_ms": round(cold, 3),
        "compile_warm_ms": round(warm, 3),
        "warm_cache": (hb.get("digests") or {}).get("warm_cache"),
        # registered live feeds (ISSUE 15): the worker's per-feed
        # stream payload (tick count, lag, quarantines) when any
        "streams": hb.get("streams") or None,
    }


def fleet_rollup(heartbeats, events=(), depth=None,
                 now: float | None = None) -> dict:
    """The machine-readable fleet readout: per-worker rows, the merged
    aggregate, trace reassembly stats, the depth timeline, and the
    backpressure scalar.  ``depth`` overrides the heartbeat-reported
    queue depth with a live measurement when the caller has one (the
    ``fleet status`` CLI reads the queue dir directly)."""
    now = time.time() if now is None else now
    # stale workers (beat age > 3x their own interval) are excluded
    # from the drain rate — and therefore from backpressure — so a
    # dead worker's frozen deltas cannot dilute the fleet estimate
    merged = merge_heartbeats(heartbeats, now=now)
    eff_depth = depth if depth is not None else merged["depth"]
    traces = assemble_traces(events) if events else {}
    rollup = {
        "workers": [_worker_row(hb, now) for hb in
                    sorted(heartbeats,
                           key=lambda h: str(h.get("worker")))],
        "merged": merged,
        "depth": eff_depth,
        "drain_rate_per_s": merged["drain_rate_per_s"],
        "backpressure": backpressure(eff_depth,
                                     merged["drain_rate_per_s"]),
        "depth_timeline": depth_timeline(events),
        "traces": {
            "count": len(traces),
            "orphan_events": sum(len(t["orphans"])
                                 for t in traces.values()),
            "multi_process": sum(1 for t in traces.values()
                                 if len(t["pids"]) > 1),
        },
    }
    return rollup


def attach_slo_status(rollup: dict, heartbeats) -> None:
    """Attach fleet-scope SLO statuses to a rollup that carries a
    declared registry (``queue_extras``): histogram kinds evaluate the
    merged heartbeat window deltas — exactly the single-process burn
    math on the summed counts — and liveness kinds read beat ages."""
    specs = rollup.get("slos")
    if not specs:
        return
    from .slo import fleet_statuses

    rollup["slo_status"] = fleet_statuses(
        specs, (rollup.get("merged") or {}).get("slo"), heartbeats)


def _fmt_hist(s: dict | None) -> str:
    if not s or not s.get("count"):
        return "-"
    return (f"n={s['count']} p50={s['p50']:.4g} p95={s['p95']:.4g} "
            f"p99={s['p99']:.4g}")


def render_fleet(rollup: dict) -> str:
    """Human rendering of :func:`fleet_rollup` (the ``trace report
    --fleet`` / ``fleet status`` payload)."""
    lines = ["fleet (merged heartbeats + traces):"]
    alerts = rollup.get("alerts") or []
    firing = [a for a in alerts if a.get("state") == "firing"]
    if firing:
        # the banner an operator must not scroll past: every alert
        # currently in the firing state, burn context inline
        lines.append(
            "  *** ALERTS FIRING: " + ", ".join(
                f"{a.get('slo')} (burn fast/slow = "
                f"{a.get('burn_fast')}/{a.get('burn_slow')}"
                + (", acked" if a.get("ack") else "") + ")"
                for a in firing) + " ***")
    workers = rollup["workers"]
    if workers:
        for w in workers:
            qw = _fmt_hist(w["queue_wait"])
            claim = (f"{w['last_claim_age_s']:.1f}s"
                     if w["last_claim_age_s"] is not None else "-")
            fill = (f"{w['fill_ratio']}" if w["fill_ratio"] is not None
                    else "-")
            stale = " STALE" if w.get("stale") else ""
            lines.append(
                f"  worker {w['worker']} (pid {w['pid']}){stale}: beat "
                f"{w['age_s']:.1f}s ago, last claim {claim}, done = "
                f"{w['jobs_done']}, failed = {w['jobs_failed']}, "
                f"retries = {w['job_retries']}"
                f"+{w['job_transient_retries']}t, fill = {fill}")
            lines.append(
                f"    queue_wait_s: {qw}; compile cold/warm ms = "
                f"{w['compile_cold_ms']:.1f}/{w['compile_warm_ms']:.1f}"
                + (f"; warm_cache = {w['warm_cache']}"
                   if w["warm_cache"] else ""))
            mem = w.get("memory")
            if mem:
                def _gib(v):
                    return (f"{v / 2**30:.2f}"
                            if isinstance(v, (int, float)) else "-")

                peak = mem.get("peak_bytes_in_use")
                lines.append(
                    f"    hbm GiB: in_use = {_gib(mem['bytes_in_use'])}"
                    f", peak = {_gib(peak)}, limit = "
                    f"{_gib(mem['bytes_limit'])}, headroom = "
                    f"{_gib(mem.get('headroom'))}"
                    + (f" ({len(mem['step_peaks'])} signature peak(s))"
                       if mem.get("step_peaks") else ""))
            streams = w.get("streams")
            if streams:
                for s in streams.values():
                    if not isinstance(s, dict):
                        continue
                    lag = s.get("lag_s")
                    fin = " finalized" if s.get("finalized") else ""
                    lines.append(
                        f"    stream {s.get('feed', '?')}: ticks = "
                        f"{s.get('ticks', 0)}, consumed = "
                        f"{s.get('consumed', 0)}/"
                        f"{s.get('committed', 0)}{fin}, lag = "
                        f"{lag if lag is not None else '-'} s, "
                        f"quarantined = {s.get('quarantined', 0)}")
    else:
        lines.append("  (no heartbeats)")
    merged = rollup["merged"]
    if merged["hists"]:
        lines.append("  merged latency histograms:")
        for name, s in merged["hists"].items():
            lines.append(f"    {name}: {_fmt_hist(s)}")
    c = merged["counters"]
    if c:
        lines.append(
            "  totals: jobs_done = %d, jobs_failed = %d, job_retries "
            "= %d, transient = %d, quarantined = %d" % (
                c.get("jobs_done", 0), c.get("jobs_failed", 0),
                c.get("job_retries", 0),
                c.get("job_transient_retries", 0),
                c.get("epochs_quarantined", 0)))
        if c.get("stream_ticks"):
            lines.append(
                "  streams: ticks = %d, chunks quarantined = %d" % (
                    c.get("stream_ticks", 0),
                    c.get("chunks_quarantined", 0)))
    tl = rollup["depth_timeline"]
    if tl:
        lines.append("  queue_depth timeline: "
                     + " ".join(f"{int(v)}" for _, v in tl))
    sd = rollup.get("shard_depths")
    if sd and any(sd.values()):
        lines.append("  queued depth by shard: "
                     + " ".join(f"{k}={v}"
                                for k, v in sorted(sd.items()) if v))
    ld = rollup.get("lane_depths")
    if ld and any(ld.values()):
        lines.append("  queued depth by lane: "
                     + " ".join(f"{k}={v}"
                                for k, v in sorted(ld.items())))
    pool = rollup.get("pool")
    if pool:
        ps = pool.get("stats") or {}
        nw = len(pool.get("workers") or {})
        draining = sum(1 for w in (pool.get("workers") or {}).values()
                       if isinstance(w, dict) and w.get("draining"))
        lines.append(
            f"  pool controller (pid {pool.get('pid')}): workers = "
            f"{nw}" + (f" ({draining} draining)" if draining else "")
            + f" in [{pool.get('min_workers')}, "
            f"{pool.get('max_workers')}], scale_up = "
            f"{ps.get('scale_up', 0)}, scale_down = "
            f"{ps.get('scale_down', 0)}, stale_replaced = "
            f"{ps.get('stale_replaced', 0)}"
            + (f", last = {pool['last_decision']}"
               if pool.get("last_decision") else ""))
    fsck = rollup.get("fsck")
    if fsck:
        cls = fsck.get("classes") or {}
        detail = (" [" + " ".join(f"{k}={v}"
                                  for k, v in sorted(cls.items()))
                  + "]" if cls else "")
        lines.append(
            f"  fsck (last audit, "
            f"{'repair' if fsck.get('repair') else 'dry-run'}): "
            + ("clean" if fsck.get("clean") else "NOT CLEAN")
            + f", {fsck.get('findings', 0)} finding(s)"
            + f", {fsck.get('repaired', 0)} repaired" + detail)
    slo_rows = rollup.get("slo_status")
    if slo_rows:
        lines.append("  slo (error budgets over merged heartbeats):")
        for st in slo_rows:
            w = st["windows"]
            lines.append(
                f"    {st['slo']} [{st['metric']} <= "
                f"{st['threshold_s']:g}s @ {st['objective']:g}]: "
                f"burn fast = {w['fast']['burn']:g} "
                f"(n={w['fast']['n']}), slow = {w['slow']['burn']:g} "
                f"(n={w['slow']['n']}), budget remaining = "
                f"{st['budget_remaining']:g}"
                + (" BREACH" if st.get("breach") else ""))
    if alerts:
        lines.append("  alerts (durable newest-wins rows):")
        for a in alerts:
            lines.append(
                f"    {a.get('slo')}: {a.get('state')}"
                + (f" since {a.get('since_ts')}"
                   if a.get("state") in ("pending", "firing")
                   and a.get("since_ts") else "")
                + (" acked" if a.get("ack") else "")
                + (f" trace {a.get('trace_id')}"
                   if a.get("trace_id") else ""))
    tr = rollup["traces"]
    if tr["count"]:
        lines.append(
            f"  traces: {tr['count']} reassembled, "
            f"{tr['multi_process']} spanning >1 process, "
            f"{tr['orphan_events']} orphan event(s)")
    stale_n = rollup["merged"].get("stale_workers", 0)
    if stale_n:
        lines.append(f"  {stale_n} STALE worker(s) excluded from the "
                     "drain rate (beat age > 3x their interval)")
    lines.append(
        f"  depth = {rollup['depth'] if rollup['depth'] is not None else '-'}, "
        f"drain = {rollup['drain_rate_per_s']}/s, "
        f"backpressure = {rollup['backpressure']} "
        f"(depth / (depth + drain*{BACKPRESSURE_HORIZON_S:.0f}s))")
    return "\n".join(lines)


def fleet_report(directory: str, depth=None) -> tuple[str, list]:
    """(rendered rollup, warnings) for one fleet directory — the CLI
    entrypoint shared by ``trace report --fleet`` and ``fleet
    status``.  When the directory is a live queue dir, the rollup also
    carries its measured depth, per-shard/per-lane queued depths and
    the pool controller's decisions (:func:`queue_extras`)."""
    heartbeats, events, warnings = collect_fleet(directory)
    extras = queue_extras(directory)
    if depth is None:
        depth = extras.get("depth")
    rollup = fleet_rollup(heartbeats, events, depth=depth)
    rollup.update(extras)
    attach_slo_status(rollup, heartbeats)
    return render_fleet(rollup), warnings
