"""Epoch preflight: cheap host-side health checks before batching.

A batched survey step is an SPMD program: one pathological epoch does
not fail alone, it NaN-poisons its lane mid-fit and burns a device
step (and, under serve, a whole batch's retry round) discovering what
a microsecond-scale host check could have said up front.  This module
is that check — run inside the shared load chain (``serve.load_epoch``,
which the batched CLI engine and the serve worker both use) on the RAW
post-trim epoch, *before* ``refill`` can repair-by-interpolation what
should be rejected and before the epoch enters a batch — routing bad
epochs to a quarantine list with machine-readable reason codes instead
of letting them fit.

Reason codes (stable strings — they land in serve ``job.error`` fields
and quarantine logs, so downstream tooling can bucket them):

* ``nonfinite``        — more than ``max_nonfinite_frac`` of the dynspec
                         is NaN/inf: ``refill`` would fabricate the
                         majority of the epoch by interpolation.
* ``all_zero``         — the dynspec is identically zero (dead receiver/
                         zero-filled file): every downstream normalise
                         divides by zero.
* ``zero_band``        — more than ``max_zero_band_frac`` of frequency
                         channels are entirely zero (dropped subband):
                         legal per-channel, but this much dead band
                         biases the whole-epoch fits.
* ``axis_nonmonotonic``— freqs/times are not strictly monotonic (the
                         resample/FFT grids assume ordered axes).
* ``axis_shape``       — axis lengths disagree with the dynspec shape,
                         or fewer than 2 channels/subints survive.

The thresholds are deliberately loose: preflight exists to catch
*structurally* bad epochs deterministically, not to second-guess RFI
excision (``--clean`` owns that).  Counters: ``epochs_quarantined``
plus per-reason ``epochs_quarantined[<reason>]`` (rendered by ``trace
report``; docs/reliability.md documents the fault model).
"""

from __future__ import annotations

import numpy as np

from . import obs

# quarantine when more than this fraction of samples is NaN/inf
DEFAULT_MAX_NONFINITE_FRAC = 0.5
# quarantine when more than this fraction of channels is entirely zero
DEFAULT_MAX_ZERO_BAND_FRAC = 0.5


def preflight_epoch(epoch, max_nonfinite_frac: float =
                    DEFAULT_MAX_NONFINITE_FRAC,
                    max_zero_band_frac: float =
                    DEFAULT_MAX_ZERO_BAND_FRAC) -> list[str]:
    """Reason codes for one epoch ([] = healthy).  Host-side numpy
    only — never touches the device, costs microseconds per epoch."""
    reasons: list[str] = []
    dyn = np.asarray(epoch.dyn)
    freqs = np.asarray(epoch.freqs)
    times = np.asarray(epoch.times)
    if (dyn.ndim != 2 or freqs.ndim != 1 or times.ndim != 1
            or dyn.shape != (len(freqs), len(times))
            or len(freqs) < 2 or len(times) < 2):
        # shape pathologies make the remaining checks meaningless
        return ["axis_shape"]
    for ax in (freqs, times):
        d = np.diff(ax)
        if not (np.all(d > 0) or np.all(d < 0)):
            reasons.append("axis_nonmonotonic")
            break
    finite = np.isfinite(dyn)
    nonfinite_frac = 1.0 - finite.mean()
    if nonfinite_frac > max_nonfinite_frac:
        reasons.append("nonfinite")
    vals = np.where(finite, dyn, 0.0)
    if not np.any(vals):
        reasons.append("all_zero")
    else:
        zero_band_frac = float(np.mean(~np.any(vals != 0.0, axis=1)))
        if zero_band_frac > max_zero_band_frac:
            reasons.append("zero_band")
    return reasons


class PreflightError(ValueError):
    """An epoch rejected by preflight.  ``reasons`` carries the
    machine-readable codes; ``str()`` is ``"preflight: a,b"`` — the
    exact string serve writes into ``job.error`` fields, so queue
    tooling can bucket quarantines without parsing prose.  A
    ``ValueError``: deterministic for a given input, so
    ``faults.classify_error`` routes it down the poison path, never
    the budget-preserving transient one."""

    def __init__(self, reasons):
        self.reasons = list(reasons)
        super().__init__("preflight: " + ",".join(self.reasons))


def quarantine_check(epoch, name=None, log=None) -> None:
    """Raise :class:`PreflightError` when ``epoch`` fails preflight —
    the single gate ``serve.load_epoch`` runs on the RAW (post-trim,
    pre-refill) epoch, where dead bands and NaN gaps are still visible
    (``refill`` repairs them by interpolation, which is exactly the
    silent fabrication preflight exists to refuse at scale).  Emits an
    ``epoch_quarantined`` log event and the ``epochs_quarantined`` /
    ``epochs_quarantined[<reason>]`` counters at the raise site, so
    every caller of the shared load chain is counted once."""
    from .utils.log import get_logger, log_event

    reasons = preflight_epoch(epoch)
    if not reasons:
        return
    obs.inc("epochs_quarantined")
    for r in reasons:
        obs.inc(f"epochs_quarantined[{r}]")
    log_event(log or get_logger(), "epoch_quarantined",
              file=name if name is not None else "?",
              reasons=",".join(reasons))
    raise PreflightError(reasons)
